"""File-backed model registry.

Replaces the ClearML model repository the reference queries for model lookup,
upload, publication, and auto-deployment (reference __main__.py:123-154
`func_model_upload`; model_request_processor.py:874-923 monitored-model query).
Each model is a directory with metadata (`model.json`) + payload files, queryable
by project / name / tags / published, newest-first — which is exactly the
ordering the monitoring auto-deploy logic depends on.
"""

from __future__ import annotations

import shutil
import time
import uuid
from pathlib import Path
from typing import Any, Dict, List, Optional, Union

from ..utils.files import atomic_write_json, read_json, sha256_file


class ModelRecord:
    def __init__(self, registry: "ModelRegistry", model_id: str, meta: Dict[str, Any]):
        self._registry = registry
        self.id = model_id
        self._meta = meta

    @property
    def name(self) -> str:
        return self._meta.get("name") or ""

    @property
    def project(self) -> str:
        return self._meta.get("project") or ""

    @property
    def tags(self) -> List[str]:
        return list(self._meta.get("tags") or [])

    @property
    def framework(self) -> Optional[str]:
        return self._meta.get("framework")

    @property
    def published(self) -> bool:
        return bool(self._meta.get("published"))

    @property
    def created(self) -> float:
        return float(self._meta.get("created") or 0)

    @property
    def uri(self) -> Optional[str]:
        return self._meta.get("uri")

    @property
    def files_dir(self) -> Path:
        return self._registry.models_dir / self.id / "files"

    def get_local_copy(self) -> Optional[str]:
        """Local filesystem path to the model payload: the single stored file,
        or the files directory for multi-file models (SavedModel dirs etc.)."""
        d = self.files_dir
        if not d.is_dir():
            return None
        entries = sorted(d.iterdir())
        if len(entries) == 1:
            return str(entries[0])
        return str(d) if entries else None

    def publish(self) -> None:
        self._meta["published"] = True
        self._registry._write_meta(self.id, self._meta)

    def set_metadata(self, **kwargs) -> None:
        self._meta.update(kwargs)
        self._registry._write_meta(self.id, self._meta)

    def as_dict(self) -> Dict[str, Any]:
        return dict(self._meta, id=self.id)


class ModelRegistry:
    def __init__(self, root: Union[str, Path]):
        self.models_dir = Path(root) / "models"
        self.models_dir.mkdir(parents=True, exist_ok=True)

    def _write_meta(self, model_id: str, meta: Dict[str, Any]) -> None:
        atomic_write_json(self.models_dir / model_id / "model.json", meta)

    def register(
        self,
        name: str,
        project: Optional[str] = None,
        tags: Optional[List[str]] = None,
        framework: Optional[str] = None,
        path: Optional[Union[str, Path]] = None,
        uri: Optional[str] = None,
        publish: bool = False,
        metadata: Optional[Dict[str, Any]] = None,
    ) -> ModelRecord:
        """Create a model entry; `path` copies a local file/dir into the
        registry, `uri` records an external destination without copying
        (reference `model upload --url`)."""
        model_id = uuid.uuid4().hex
        model_dir = self.models_dir / model_id
        files_dir = model_dir / "files"
        files_dir.mkdir(parents=True)
        file_hash = None
        if path is not None:
            path = Path(path)
            if path.is_dir():
                shutil.copytree(str(path), str(files_dir / path.name))
            else:
                shutil.copyfile(str(path), str(files_dir / path.name))
                file_hash = sha256_file(files_dir / path.name)
        meta = {
            "id": model_id,
            "name": name,
            "project": project,
            "tags": sorted(set(tags or [])),
            "framework": framework,
            "published": bool(publish),
            "created": time.time(),
            "uri": uri,
            "hash": file_hash,
            "metadata": metadata or {},
        }
        self._write_meta(model_id, meta)
        return ModelRecord(self, model_id, meta)

    def get(self, model_id: str) -> Optional[ModelRecord]:
        meta = read_json(self.models_dir / model_id / "model.json")
        return ModelRecord(self, model_id, meta) if meta else None

    def query(
        self,
        project: Optional[str] = None,
        name: Optional[str] = None,
        tags: Optional[List[str]] = None,
        only_published: bool = False,
        max_results: Optional[int] = None,
    ) -> List[ModelRecord]:
        """Newest-first query — the ordering contract the auto-deploy monitor
        relies on (reference model_request_processor.py:884-893 uses
        `Model.query_models(..., max_results=max_versions)` newest-first)."""
        out: List[ModelRecord] = []
        for entry in self.models_dir.iterdir() if self.models_dir.is_dir() else []:
            meta = read_json(entry / "model.json")
            if not meta:
                continue
            if project is not None and meta.get("project") != project:
                continue
            if name is not None and name not in (meta.get("name") or ""):
                continue
            if tags and not set(tags).issubset(set(meta.get("tags") or [])):
                continue
            if only_published and not meta.get("published"):
                continue
            out.append(ModelRecord(self, meta["id"], meta))
        out.sort(key=lambda m: m.created, reverse=True)
        if max_results:
            out = out[: int(max_results)]
        return out
