"""Self-contained control-plane state store.

The reference keeps all control-plane state on a ClearML "Task" object that every
runtime container polls and reconciles against (SURVEY.md §0; reference
model_request_processor.py:610-760). This module provides the same semantics
without an external server: a **file-backed service document** with

- parameters (the "General/*" config keys),
- named config objects (endpoints / canary / model_monitoring / metric_logging /
  model_monitoring_eps),
- runtime properties (framework version etc.),
- artifacts (uploaded preprocess code, content-hashed),
- a monotonically increasing ``update_counter`` and heartbeat timestamps.

Writes are atomic (tmp + rename) and read-modify-write cycles take an
``fcntl`` file lock, so any number of router / engine / statistics processes can
poll one service document concurrently — the same eventual-consistency model as
the reference's Task polling, with the filesystem (or a network mount / object
store sync) as the transport.
"""

from __future__ import annotations

import fcntl
import os
import shutil
import time
import uuid
from contextlib import contextmanager
from pathlib import Path
from typing import Any, Dict, List, Optional, Union

from ..utils.files import atomic_write_json, read_json, sha256_file
from ..version import __version__

SERVICE_TAG = "serving-control-plane"


def default_state_root() -> Path:
    return Path(
        os.environ.get("TPUSERVE_STATE_ROOT")
        or os.environ.get("CLEARML_SERVING_STATE_ROOT")
        or (Path.home() / ".tpu-serving")
    )


class ServingService:
    """Handle on one service document (the control-plane 'Task' equivalent)."""

    def __init__(self, store: "StateStore", service_id: str):
        self._store = store
        self.id = service_id
        self._dir = store.services_dir / service_id
        self._doc_path = self._dir / "service.json"
        self._lock_path = self._dir / ".lock"
        self.artifacts_dir = self._dir / "artifacts"

    # -- lifecycle ---------------------------------------------------------

    @property
    def exists(self) -> bool:
        return self._doc_path.is_file()

    def _read(self) -> Dict[str, Any]:
        doc = read_json(self._doc_path)
        if doc is None:
            raise FileNotFoundError(
                "serving service {!r} not found under {}".format(self.id, self._dir)
            )
        return doc

    @contextmanager
    def _locked(self):
        self._dir.mkdir(parents=True, exist_ok=True)
        with open(self._lock_path, "a+") as lock_f:
            fcntl.flock(lock_f.fileno(), fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lock_f.fileno(), fcntl.LOCK_UN)

    def _mutate(self, fn) -> Dict[str, Any]:
        """Locked read-modify-write; bumps update_counter."""
        with self._locked():
            doc = self._read()
            fn(doc)
            doc["update_counter"] = int(doc.get("update_counter", 0)) + 1
            doc["last_update"] = time.time()
            atomic_write_json(self._doc_path, doc)
            return doc

    # -- reference-Task-equivalent surface ---------------------------------

    def get_snapshot(self) -> Dict[str, Any]:
        """One consistent read of the whole service document (atomic replace on
        the writer side guarantees a torn-free view)."""
        return self._read()

    def get_parameters(self) -> Dict[str, Any]:
        return dict(self._read().get("parameters") or {})

    def update_parameters(self, params: Dict[str, Any]) -> None:
        self._mutate(lambda d: d.setdefault("parameters", {}).update(params))

    def get_configuration_object(self, name: str) -> Optional[Any]:
        return (self._read().get("configuration") or {}).get(name)

    def set_configuration_objects(self, objects: Dict[str, Any]) -> None:
        self._mutate(lambda d: d.setdefault("configuration", {}).update(objects))

    def get_runtime_properties(self) -> Dict[str, Any]:
        return dict(self._read().get("runtime") or {})

    def set_runtime_properties(self, props: Dict[str, Any]) -> None:
        self._mutate(lambda d: d.setdefault("runtime", {}).update(props))

    def ping(self, instance_id: Optional[str] = None) -> None:
        """Heartbeat (reference: Task keep-alive ping each poll cycle)."""
        def _apply(doc):
            doc["last_ping"] = time.time()
            if instance_id:
                doc.setdefault("instances", {})[instance_id] = time.time()
        self._mutate(_apply)

    @property
    def name(self) -> str:
        return self._read().get("name") or ""

    @property
    def project(self) -> str:
        return self._read().get("project") or ""

    @property
    def update_counter(self) -> int:
        return int(self._read().get("update_counter", 0))

    # -- artifacts (preprocess code) ---------------------------------------

    def upload_artifact(self, name: str, local_path: Union[str, Path]) -> str:
        """Store a file (or package directory) under the service; returns the
        artifact name. Directories are zipped (reference uploads preprocess
        packages the same way)."""
        local_path = Path(local_path)
        dest_dir = self.artifacts_dir / name
        with self._locked():
            if dest_dir.exists():
                shutil.rmtree(dest_dir)
            dest_dir.mkdir(parents=True)
            if local_path.is_dir():
                archive = shutil.make_archive(
                    str(dest_dir / "package"), "zip", root_dir=str(local_path)
                )
                stored = Path(archive)
            else:
                stored = dest_dir / local_path.name
                shutil.copyfile(str(local_path), str(stored))
            meta = {
                "file": stored.name,
                "hash": sha256_file(stored),
                "uploaded": time.time(),
            }
            atomic_write_json(dest_dir / "artifact.json", meta)
            # Update the service doc inside the SAME lock acquisition so the
            # doc's artifact hash can never diverge from artifact.json when
            # two processes upload the same artifact name concurrently.
            doc = self._read()
            doc.setdefault("artifacts", {})[name] = meta
            doc["update_counter"] = int(doc.get("update_counter", 0)) + 1
            doc["last_update"] = time.time()
            atomic_write_json(self._doc_path, doc)
        return name

    def get_artifact(self, name: str) -> Optional[Path]:
        """Local path of a stored artifact file (hash in ``artifact_hash``)."""
        meta = read_json(self.artifacts_dir / name / "artifact.json")
        if not meta:
            return None
        return self.artifacts_dir / name / meta["file"]

    def artifact_hash(self, name: str) -> Optional[str]:
        meta = read_json(self.artifacts_dir / name / "artifact.json")
        return meta.get("hash") if meta else None

    def list_artifacts(self) -> List[str]:
        return sorted((self._read().get("artifacts") or {}).keys())


class StateStore:
    """Root of all local control-plane state: services + the model registry."""

    def __init__(self, root: Optional[Union[str, Path]] = None):
        self.root = Path(root) if root else default_state_root()
        self.services_dir = self.root / "services"
        self.services_dir.mkdir(parents=True, exist_ok=True)

    def create_service(
        self,
        name: str,
        project: str = "DevOps",
        tags: Optional[List[str]] = None,
    ) -> ServingService:
        service_id = uuid.uuid4().hex
        svc = ServingService(self, service_id)
        doc = {
            "id": service_id,
            "name": name,
            "project": project,
            "tags": sorted(set(list(tags or []) + [SERVICE_TAG])),
            "type": "service",
            "created": time.time(),
            "last_update": time.time(),
            "update_counter": 0,
            "parameters": {},
            "configuration": {},
            "runtime": {"version": __version__},
            "artifacts": {},
            "instances": {},
        }
        svc._dir.mkdir(parents=True, exist_ok=True)
        atomic_write_json(svc._doc_path, doc)
        return svc

    def get_service(self, service_id: str) -> ServingService:
        svc = ServingService(self, service_id)
        if not svc.exists:
            raise FileNotFoundError("serving service {!r} not found".format(service_id))
        return svc

    def find_service(self, name: Optional[str] = None) -> Optional[ServingService]:
        """Most recently updated service (optionally by name)."""
        candidates = []
        for entry in self.services_dir.iterdir() if self.services_dir.is_dir() else []:
            doc = read_json(entry / "service.json")
            if not doc:
                continue
            if name and doc.get("name") != name:
                continue
            candidates.append((doc.get("last_update", 0), doc["id"]))
        if not candidates:
            return None
        candidates.sort(reverse=True)
        return ServingService(self, candidates[0][1])

    def list_services(self) -> List[Dict[str, Any]]:
        out = []
        for entry in sorted(self.services_dir.iterdir()) if self.services_dir.is_dir() else []:
            doc = read_json(entry / "service.json")
            if doc:
                out.append(
                    {
                        "id": doc.get("id"),
                        "name": doc.get("name"),
                        "project": doc.get("project"),
                        "tags": doc.get("tags"),
                        "created": doc.get("created"),
                        "update_counter": doc.get("update_counter"),
                    }
                )
        return out
