"""Pluggable statistics transport.

The reference decouples request-time stats from the Prometheus exporter with a
Kafka topic (SURVEY.md §3.6). Kafka is not a hard dependency here — the broker
is a URL-selected transport with the same decoupled-queue shape:

- ``file:///path/to/dir``   — JSONL segment files on a shared filesystem; the
  consumer tails them. Zero-dependency default for single-host / shared-volume
  deployments.
- ``kafka://host:port``     — Kafka topic (requires kafka-python; gated).
- ``""`` (empty)            — stats dropped (best-effort contract, same as the
  reference when no broker is configured).

Producers are best-effort and must never raise into the serving hot path.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional

TOPIC = "tpuserve_inference_stats"


class FileBrokerProducer:
    """Append-only JSONL segments, one file per producer instance (no
    cross-process write contention); consumers tail the directory."""

    def __init__(self, directory: str):
        self._dir = Path(directory)
        self._dir.mkdir(parents=True, exist_ok=True)
        self._path = self._dir / "{}_{}.jsonl".format(TOPIC, uuid.uuid4().hex[:12])

    def send_batch(self, batch: List[Dict[str, Any]]) -> None:
        with open(self._path, "a") as f:
            for item in batch:
                f.write(json.dumps(item) + "\n")


class FileBrokerConsumer:
    """Tails every segment file in the directory, remembering per-file offsets."""

    def __init__(self, directory: str):
        self._dir = Path(directory)
        self._offsets: Dict[str, int] = {}

    def poll(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        if not self._dir.is_dir():
            return out
        for seg in sorted(self._dir.glob("{}_*.jsonl".format(TOPIC))):
            key = seg.name
            offset = self._offsets.get(key, 0)
            try:
                with open(seg, "r") as f:
                    f.seek(offset)
                    for line in f:
                        if not line.endswith("\n"):
                            break  # partial write; re-read next poll
                        try:
                            out.append(json.loads(line))
                        except json.JSONDecodeError:
                            pass
                        offset += len(line.encode("utf-8"))
                self._offsets[key] = offset
            except OSError:
                continue
        return out


class KafkaBrokerProducer:
    def __init__(self, bootstrap: str):
        from kafka import KafkaProducer  # gated dependency

        self._producer = KafkaProducer(
            bootstrap_servers=bootstrap,
            value_serializer=lambda v: json.dumps(v).encode("utf-8"),
        )

    def send_batch(self, batch: List[Dict[str, Any]]) -> None:
        for item in batch:
            self._producer.send(TOPIC, item)
        self._producer.flush(timeout=10)


class KafkaBrokerConsumer:
    def __init__(self, bootstrap: str):
        from kafka import KafkaConsumer

        self._consumer = KafkaConsumer(
            TOPIC,
            bootstrap_servers=bootstrap,
            value_deserializer=lambda b: json.loads(b.decode("utf-8")),
            auto_offset_reset="earliest",
        )

    def poll(self) -> List[Dict[str, Any]]:
        records = self._consumer.poll(timeout_ms=1000)
        return [rec.value for recs in records.values() for rec in recs]


def make_producer(url: str):
    if not url:
        return None
    if url.startswith("file://"):
        return FileBrokerProducer(url[len("file://"):])
    if url.startswith("kafka://"):
        return KafkaBrokerProducer(url[len("kafka://"):])
    # bare path == file broker
    return FileBrokerProducer(url)


def make_consumer(url: str):
    if not url:
        return None
    if url.startswith("file://"):
        return FileBrokerConsumer(url[len("file://"):])
    if url.startswith("kafka://"):
        return KafkaBrokerConsumer(url[len("kafka://"):])
    return FileBrokerConsumer(url)
