"""Statistics service entrypoint (reference clearml_serving/statistics/main.py).

Consumes the stats broker and exposes a Prometheus scrape endpoint on
``TPUSERVE_STATS_PORT`` (default 9999, same as the reference). Prometheus
scrapes this + Grafana dashboards sit on top (docker/ provisioning).
"""

from __future__ import annotations

import os

from prometheus_client import start_http_server

from .metrics import StatisticsController
from ..serving.model_request_processor import ModelRequestProcessor


def main() -> None:
    service_id = os.environ.get("TPUSERVE_SERVICE_ID") or None
    broker_url = os.environ.get("TPUSERVE_STATS_BROKER", "")
    port = int(os.environ.get("TPUSERVE_STATS_PORT", 9999))
    poll_freq_min = float(os.environ.get("TPUSERVE_POLL_FREQ", 1.0))

    processor = None
    try:
        processor = ModelRequestProcessor(service_id=service_id)
        if not broker_url:
            broker_url = processor._service.get_parameters().get("stats_broker") or ""
    except Exception as ex:
        print("statistics: no control-plane service ({}) — reserved metrics only".format(ex))

    if not broker_url:
        raise SystemExit(
            "statistics: no stats broker configured "
            "(TPUSERVE_STATS_BROKER or `tpu-serving config --stats-broker`)"
        )

    start_http_server(port)
    print("statistics: Prometheus scrape endpoint on :{}".format(port))
    controller = StatisticsController(
        broker_url, processor=processor, poll_frequency_sec=poll_freq_min * 60.0
    )
    controller.start()


if __name__ == "__main__":
    main()
