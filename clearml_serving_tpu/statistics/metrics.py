"""Statistics controller: broker → Prometheus collectors.

Capability parity with the reference's StatisticsController
(clearml_serving/statistics/metrics.py:188-373):

- consumes the stats topic, lazily creating one Prometheus collector per
  (endpoint, variable), named ``{endpoint}:{variable}`` sanitized;
- reserved variables: ``_latency`` → histogram with the reference's 5ms…5s
  buckets, ``_count`` → counter (weighted by the sampling-unbias factor);
- metric-spec types: scalar → bucketed Histogram, enum → EnumHistogram over
  the declared buckets (labeled-Counter fallback when no buckets declared),
  value → Gauge, counter → Counter;
- endpoints it doesn't know get auto-added with reserved-only logging and a
  throttled config re-sync;
- a sync daemon polls the control plane for metric-spec updates.

TPU addition (SURVEY.md §5.1/§5.5): per-chip HBM gauges sourced from
``jax.local_devices()[i].memory_stats()`` — the bytes-in-use / bytes-limit
pair is the serving fleet's north-star memory signal.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Any, Dict, Optional

from prometheus_client import Counter, Gauge, Histogram, REGISTRY

from .broker import make_consumer

_LATENCY_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.075, 0.1, 0.25, 0.5, 0.75,
    1.0, 2.5, 5.0, float("inf"),
)

_name_re = re.compile(r"[^a-zA-Z0-9_]")


def _sanitize(name: str) -> str:
    return _name_re.sub("_", name)


class EnumHistogram:
    """Reference-parity enum histogram (reference statistics/metrics.py:64-185).

    Exports a histogram-typed family with one NON-cumulative
    ``{name}_bucket{enum="<value>"}`` series per **declared** enum value (in
    declared order — the bucket set and ordering come from the metric spec,
    not from whichever values happen to arrive first) plus ``{name}_sum`` =
    total observations. Values outside the declared set are dropped, matching
    the reference's fixed-bucket contract. Enum specs below the two-bucket
    minimum fall back to a value-labeled Counter (dynamic value set) — see
    StatisticsController._collector.
    """

    def __init__(self, name: str, documentation: str, buckets, registry=REGISTRY):
        buckets = [str(b) for b in buckets]
        if len(buckets) < 2:
            raise ValueError("enum histogram needs at least two declared buckets")
        self._name = name
        self._documentation = documentation
        self._buckets = {b: 0.0 for b in buckets}  # insertion = declared order
        self._sum = 0.0
        self._lock = threading.Lock()
        if registry is not None:
            registry.register(self)

    def observe(self, value) -> None:
        v = str(value)
        with self._lock:
            if v not in self._buckets:
                return
            self._buckets[v] += 1.0
            self._sum += 1.0

    def collect(self):
        from prometheus_client.core import Metric

        metric = Metric(self._name, self._documentation, "histogram")
        with self._lock:
            for bucket, acc in self._buckets.items():
                metric.add_sample(
                    self._name + "_bucket", {"enum": bucket}, acc
                )
            metric.add_sample(self._name + "_sum", {}, self._sum)
        return [metric]

    def describe(self):
        return self.collect()


class PrefixCacheCollector:
    """Live LLM prefix-cache observability (llm/prefix_cache.py
    RadixPrefixCache): collect() reads each registered cache's counters —
    and, on the paged backend, the page pool's sharing/CoW counters — at
    scrape time, so the hit rate and HBM dedup of "millions of users share a
    system prompt" traffic are visible without the engine pushing samples
    anywhere.

    ONE collector per registry holds an entry per model (label ``model``):
    re-registering a model (endpoint hot-reload rebuilds its engine)
    REPLACES its entry, dropping the dead engine's cache reference — a
    per-engine collector would both leak the old cache's device KV and emit
    duplicate metric families, which makes Prometheus reject the scrape."""

    def __init__(self, prefix: str = "llm_prefix_cache"):
        self._prefix = _sanitize(prefix)
        self._entries: Dict[str, tuple] = {}  # model key -> (cache, pool)
        self._lock = threading.Lock()

    def set_entry(self, key: str, cache, pool=None) -> None:
        with self._lock:
            self._entries[str(key)] = (cache, pool)

    def remove_entry(self, key: str) -> None:
        with self._lock:
            self._entries.pop(str(key), None)

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        with self._lock:
            entries = dict(self._entries)
        p = self._prefix
        # hit counter carries the serving TIER (docs/kv_tiering.md): hbm =
        # the whole run was resident, host = it needed promotion from the
        # host-RAM tier; sum over tier = total hits
        hits = CounterMetricFamily(
            p + "_hits", "prefix-cache lookups that matched >= 1 block, by "
            "serving tier (hbm = resident, host = promoted from host RAM)",
            labels=["model", "tier"])
        cache_fams = [
            ("misses", CounterMetricFamily(
                p + "_misses", "prefix-cache lookups with no shared block",
                labels=["model"])),
            ("hit_tokens", CounterMetricFamily(
                p + "_hit_tokens", "prompt tokens served from cached KV "
                "(prefill compute skipped)", labels=["model"])),
            ("evictions", CounterMetricFamily(
                p + "_evictions", "radix-tree leaf evictions",
                labels=["model"])),
            ("nodes", GaugeMetricFamily(
                p + "_nodes", "cached block-granular tree nodes",
                labels=["model"])),
            ("cached_bytes", GaugeMetricFamily(
                p + "_bytes", "bytes of KV held (dense) or referenced "
                "(paged) by the cache", labels=["model"])),
            ("cached_pages", GaugeMetricFamily(
                p + "_pages", "KV pool pages referenced by the cache (paged "
                "backend)", labels=["model"])),
        ]
        shared = GaugeMetricFamily(
            "kv_pool_shared_pages",
            "pool pages with more than one reference (slot+cache or "
            "slot+slot zero-copy sharing)", labels=["model"],
        )
        free = GaugeMetricFamily(
            "kv_pool_free_pages", "unreferenced pool pages", labels=["model"]
        )
        cow = CounterMetricFamily(
            "kv_pool_cow_events",
            "copy-on-write page duplications (live slot extended into a "
            "shared page)", labels=["model"],
        )
        any_pool = False
        for key, (cache, pool) in entries.items():
            s = cache.stats()
            by_tier = s.get("hits_by_tier") or {"hbm": s.get("hits", 0)}
            for tier_name, count in by_tier.items():
                hits.add_metric([key, str(tier_name)], count)
            for stat_key, fam in cache_fams:
                fam.add_metric([key], s[stat_key])
            if pool is not None:
                any_pool = True
                shared.add_metric([key], pool.shared_pages)
                free.add_metric([key], pool.free_pages)
                cow.add_metric([key], pool.cow_events)
        yield hits
        for _, fam in cache_fams:
            yield fam
        if any_pool:
            yield shared
            yield free
            yield cow

    def describe(self):
        # empty describe => prometheus_client registers without probing
        # collect() (the engine may not be fully constructed yet)
        return []


class EngineLifecycleCollector:
    """Request-lifecycle observability (docs/robustness.md): shed / deadline
    / watchdog / step-failure counters plus queue-depth and active-slot
    gauges, read live from each registered provider at scrape time so
    shedding decisions are observable next to what triggered them.

    A provider is a zero-arg callable returning the engine's
    ``lifecycle_stats()`` dict (or the gRPC client's retry stats); unknown
    keys are ignored so providers can grow without a collector change. One
    collector per registry holds an entry per model key — re-registering a
    key REPLACES its provider (engine hot-reload must not leak the old
    engine or duplicate families)."""

    def __init__(self, prefix: str = "engine"):
        self._prefix = _sanitize(prefix)
        self._providers: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def set_entry(self, key: str, provider) -> None:
        with self._lock:
            self._providers[str(key)] = provider

    def remove_entry(self, key: str) -> None:
        with self._lock:
            self._providers.pop(str(key), None)

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        with self._lock:
            providers = dict(self._providers)
        p = self._prefix
        # per-class queue depth (docs/slo_scheduling.md): one series per
        # priority class plus class="all" for the total; providers that
        # report only a plain queue_depth int emit class="all"
        queue_depth = GaugeMetricFamily(
            p + "_queue_depth",
            "requests waiting in the engine's admission queue, by priority "
            "class (class=\"all\" = total)",
            labels=["model", "class"],
        )
        active_slots = GaugeMetricFamily(
            p + "_active_slots", "decode slots currently generating",
            labels=["model"],
        )
        ready = GaugeMetricFamily(
            p + "_ready", "1 while the engine accepts work (0 = stopped or "
            "watchdog recovery in progress)", labels=["model"],
        )
        sheds = CounterMetricFamily(
            p + "_sheds_total",
            "admissions shed at the front door, by reason and priority "
            "class (class=\"all\" = legacy per-reason totals)",
            labels=["model", "reason", "class"],
        )
        preemptions = CounterMetricFamily(
            p + "_preemptions_total",
            "batch-lane slots preempted for queued interactive work "
            "(docs/slo_scheduling.md)", labels=["model"],
        )
        brownout_stage = GaugeMetricFamily(
            p + "_brownout_stage",
            "staged-degradation level (0 = normal; 1 spec decode off; 2 + "
            "batch token cap; 3 + prefill budget shrunk and best-effort "
            "shed)", labels=["model"],
        )
        brownout_score = GaugeMetricFamily(
            p + "_brownout_score",
            "overload pressure score driving the brownout stage",
            labels=["model"],
        )
        deadlines = CounterMetricFamily(
            p + "_deadline_hits_total",
            "requests failed on an elapsed budget",
            labels=["model", "stage"],
        )
        trips = CounterMetricFamily(
            p + "_watchdog_trips_total",
            "stalled-loop detections (each failed the in-flight batch and "
            "recovered the loop)", labels=["model"],
        )
        failures = CounterMetricFamily(
            p + "_step_failures_total",
            "decode dispatch failures survived by the loop",
            labels=["model"],
        )
        grpc = CounterMetricFamily(
            "grpc_client_upstream_total",
            "engine-server gRPC attempts/retries/retry-budget exhaustions",
            labels=["model", "kind"],
        )
        # pipelined-decode observability (docs/pipelined_decode.md): stage
        # timing histograms + the live in-flight dispatch queue depth
        from prometheus_client.core import HistogramMetricFamily

        inflight = GaugeMetricFamily(
            p + "_pipeline_inflight",
            "decode chunks dispatched but not yet retired",
            labels=["model"],
        )
        pipe_depth = GaugeMetricFamily(
            p + "_pipeline_depth",
            "configured decode pipeline depth (1 = serial)",
            labels=["model"],
        )
        dispatch_ms = HistogramMetricFamily(
            p + "_step_dispatch_ms",
            "host time to enqueue one decode chunk (ms)",
            labels=["model"],
        )
        retire_ms = HistogramMetricFamily(
            p + "_step_retire_ms",
            "host time to sync + emit one retired chunk (ms)",
            labels=["model"],
        )
        # ragged token-budget scheduler (docs/ragged_attention.md): how full
        # each mixed launch ran against its token budget, and how many rows
        # of each phase rode the launches — occupancy and admission
        # interleave are dashboard lines, not log greps
        budget_util = HistogramMetricFamily(
            p + "_step_token_budget_utilization",
            "per ragged step: tokens dispatched / step token budget",
            labels=["model"],
        )
        step_rows = CounterMetricFamily(
            p + "_step_rows",
            "rows carried by ragged mixed launches, by phase "
            "(prefill = admission chunk rows, decode = one-token rows)",
            labels=["model", "phase"],
        )
        ragged_jobs = GaugeMetricFamily(
            p + "_ragged_prefill_jobs",
            "admissions currently mid-prefill in the ragged scheduler",
            labels=["model"],
        )
        ragged_budget = GaugeMetricFamily(
            p + "_step_token_budget",
            "effective ragged step token budget (brownout stage 3 shrinks "
            "it)", labels=["model"],
        )
        # paged KV pool capacity (docs/paged_kv_quant.md): bytes split by
        # kind (kv = data planes, scale = int8 dequant scale rows) plus an
        # info gauge carrying the pool dtype — the int8 capacity win is a
        # dashboard line, not a code comment
        kv_pool_bytes = GaugeMetricFamily(
            p + "_kv_pool_bytes",
            "device HBM held by the paged KV pools, by kind",
            labels=["model", "kind"],
        )
        kv_pool_dtype = GaugeMetricFamily(
            p + "_kv_pool_dtype",
            "info gauge (always 1): storage dtype of the paged KV pools",
            labels=["model", "dtype"],
        )
        # host-RAM KV tier (docs/kv_tiering.md): where the prefix cache's
        # pages live (hbm vs host) and how many moved each way — the
        # capacity-planning signal the tier exists for
        kv_tier_pages = GaugeMetricFamily(
            p + "_kv_tier_pages",
            "prefix-cache KV pages held, by tier (hbm = device pool, "
            "host = pinned host RAM)",
            labels=["model", "tier"],
        )
        kv_tier_bytes = GaugeMetricFamily(
            p + "_kv_tier_bytes",
            "prefix-cache KV bytes held, by tier",
            labels=["model", "tier"],
        )
        kv_demotions = CounterMetricFamily(
            p + "_kv_demotions",
            "demotion events: batched HBM->host spill rounds (eviction "
            "pressure spilled instead of dropping; pages moved are in "
            "lifecycle_stats kv_tier.demoted_pages_total)",
            labels=["model"],
        )
        kv_promotions = CounterMetricFamily(
            p + "_kv_promotions",
            "promotion events: demoted runs re-onlined to HBM (async DMA "
            "on a host-tier hit, or by reference at a store)",
            labels=["model"],
        )
        # compile-surface discipline (docs/static_analysis.md TPU6xx): XLA
        # compilations observed by the compile sentry, split at the warmup
        # fence — phase="serve" must stay 0 on a zero-recompile-certified
        # engine; anything else is a loop-thread stall hiding in the tail
        xla_compiles = CounterMetricFamily(
            p + "_xla_compiles_total",
            "XLA compilations observed by the compile sentry "
            "(TPUSERVE_COMPILE_SENTRY), by phase (warmup = before the "
            "llm/warmup.py fence, serve = after: each is a loop-thread "
            "compile stall)",
            labels=["model", "phase"],
        )
        xla_compile_ms = HistogramMetricFamily(
            p + "_xla_compile_ms",
            "per-compilation XLA compile time (ms) observed by the "
            "compile sentry",
            labels=["model"],
        )

        def _hist_buckets(snap):
            """Engine _MsHistogram snapshot -> prometheus cumulative
            (le, count) pairs + sum."""
            edges = [str(b) for b in snap.get("buckets", [])] + ["+Inf"]
            cum, out = 0, []
            for edge, count in zip(edges, snap.get("counts", [])):
                cum += count
                out.append((edge, cum))
            return out, float(snap.get("sum_ms", 0.0))

        any_grpc = False
        any_pipeline = False
        any_kv_pool = False
        any_kv_tier = False
        any_slo = False
        any_ragged = False
        any_compile = False
        for key, provider in providers.items():
            try:
                s = provider() or {}
            except Exception:
                continue
            kv_pool = s.get("kv_pool") or {}
            if kv_pool:
                any_kv_pool = True
                for kind in ("kv", "scale"):
                    if kind in kv_pool:
                        kv_pool_bytes.add_metric([key, kind], kv_pool[kind])
                if kv_pool.get("dtype"):
                    kv_pool_dtype.add_metric([key, str(kv_pool["dtype"])], 1)
            kv_tier = s.get("kv_tier") or {}
            if kv_tier:
                any_kv_tier = True
                for tier_name, v in (kv_tier.get("pages") or {}).items():
                    kv_tier_pages.add_metric([key, str(tier_name)], v)
                for tier_name, v in (kv_tier.get("bytes") or {}).items():
                    kv_tier_bytes.add_metric([key, str(tier_name)], v)
                if "demotions" in kv_tier:
                    kv_demotions.add_metric([key], kv_tier["demotions"])
                if "promotions" in kv_tier:
                    kv_promotions.add_metric([key], kv_tier["promotions"])
            compile_block = s.get("compile") or {}
            if compile_block:
                any_compile = True
                for phase in ("warmup", "serve"):
                    if phase in compile_block:
                        xla_compiles.add_metric(
                            [key, phase], compile_block[phase]
                        )
                snap = compile_block.get("compile_ms")
                if snap:
                    buckets, total = _hist_buckets(snap)
                    xla_compile_ms.add_metric([key], buckets, total)
            ragged = s.get("ragged") or {}
            if ragged:
                any_ragged = True
                snap = ragged.get("budget_utilization")
                if snap:
                    buckets, total = _hist_buckets(snap)
                    budget_util.add_metric([key], buckets, total)
                for phase, v in (ragged.get("step_rows") or {}).items():
                    step_rows.add_metric([key, str(phase)], v)
                if "prefill_jobs" in ragged:
                    ragged_jobs.add_metric([key], ragged["prefill_jobs"])
                if "effective_budget" in ragged:
                    ragged_budget.add_metric([key], ragged["effective_budget"])
            pipe = s.get("pipeline") or {}
            if pipe:
                any_pipeline = True
                if "inflight" in pipe:
                    inflight.add_metric([key], pipe["inflight"])
                if "depth" in pipe:
                    pipe_depth.add_metric([key], pipe["depth"])
                for fam, field in ((dispatch_ms, "dispatch_ms"),
                                   (retire_ms, "retire_ms")):
                    snap = pipe.get(field)
                    if snap:
                        buckets, total = _hist_buckets(snap)
                        fam.add_metric([key], buckets, total)
            qd_classes = s.get("queue_depths")
            if isinstance(qd_classes, dict):
                for cls_name, v in qd_classes.items():
                    queue_depth.add_metric([key, str(cls_name)], v)
            if "queue_depth" in s:
                queue_depth.add_metric([key, "all"], s["queue_depth"])
            if "active_slots" in s:
                active_slots.add_metric([key], s["active_slots"])
            if "ready" in s:
                ready.add_metric([key], s["ready"])
            by_class = s.get("sheds_by_class")
            if isinstance(by_class, dict):
                for reason, per in by_class.items():
                    for cls_name, v in (per or {}).items():
                        sheds.add_metric([key, str(reason), str(cls_name)], v)
            for reason, v in (s.get("sheds") or {}).items():
                sheds.add_metric([key, reason, "all"], v)
            if "preemptions" in s:
                any_slo = True
                preemptions.add_metric([key], s["preemptions"])
            brown = s.get("brownout")
            if isinstance(brown, dict):
                any_slo = True
                brownout_stage.add_metric([key], brown.get("stage", 0))
                brownout_score.add_metric([key], brown.get("score", 0.0))
            for stage, v in (s.get("deadlines") or {}).items():
                deadlines.add_metric([key, stage], v)
            if "watchdog_trips" in s:
                trips.add_metric([key], s["watchdog_trips"])
            if "step_failures" in s:
                failures.add_metric([key], s["step_failures"])
            for kind, v in (s.get("grpc") or {}).items():
                any_grpc = True
                grpc.add_metric([key, kind], v)
        yield queue_depth
        yield active_slots
        yield ready
        yield sheds
        yield deadlines
        yield trips
        yield failures
        if any_slo:
            yield preemptions
            yield brownout_stage
            yield brownout_score
        if any_pipeline:
            yield inflight
            yield pipe_depth
            yield dispatch_ms
            yield retire_ms
        if any_ragged:
            yield budget_util
            yield step_rows
            yield ragged_jobs
            yield ragged_budget
        if any_kv_pool:
            yield kv_pool_bytes
            yield kv_pool_dtype
        if any_kv_tier:
            yield kv_tier_pages
            yield kv_tier_bytes
            yield kv_demotions
            yield kv_promotions
        if any_compile:
            yield xla_compiles
            yield xla_compile_ms
        if any_grpc:
            yield grpc

    def describe(self):
        # empty describe => register without probing collect() (providers
        # may not be fully constructed yet)
        return []


# one collector per live registry (weak: test registries die with their
# tests; a reused id must not resurrect a collector bound to a dead one)
_prefix_collectors: "weakref.WeakKeyDictionary" = None  # lazy init
_lifecycle_collectors: "weakref.WeakKeyDictionary" = None  # lazy init


def register_engine_lifecycle(provider, registry=REGISTRY, key: str = "llm",
                              prefix: str = "engine"):
    """Expose live request-lifecycle metrics for ``key`` (model/endpoint
    name). ``provider`` is a zero-arg callable returning a
    ``lifecycle_stats()``-shaped dict. Idempotent per (registry, key):
    re-registering replaces the provider. Returns the shared collector."""
    global _lifecycle_collectors
    import weakref

    if _lifecycle_collectors is None:
        _lifecycle_collectors = weakref.WeakKeyDictionary()
    per_registry = _lifecycle_collectors.setdefault(registry, {})
    collector = per_registry.get(prefix)
    if collector is None:
        collector = EngineLifecycleCollector(prefix)
        registry.register(collector)
        per_registry[prefix] = collector
    collector.set_entry(key, provider)
    return collector


def register_prefix_cache(cache, pool=None, registry=REGISTRY,
                          key: str = "llm",
                          prefix: str = "llm_prefix_cache"):
    """Expose live prefix-cache metrics for ``key`` (the model/endpoint
    name). Idempotent per (registry, key): re-registering replaces the
    entry, so engine hot-reloads neither leak the old cache nor duplicate
    metric families. Returns the registry's shared collector."""
    global _prefix_collectors
    import weakref

    if _prefix_collectors is None:
        _prefix_collectors = weakref.WeakKeyDictionary()
    per_registry = _prefix_collectors.setdefault(registry, {})
    collector = per_registry.get(prefix)
    if collector is None:
        collector = PrefixCacheCollector(prefix)
        registry.register(collector)
        per_registry[prefix] = collector
    collector.set_entry(key, cache, pool)
    return collector


class StatisticsController:
    _sync_threshold_sec = 30.0

    def __init__(
        self,
        broker_url: str,
        processor=None,  # ModelRequestProcessor for metric-spec sync (optional)
        registry=REGISTRY,
        poll_frequency_sec: float = 60.0,
    ):
        self._consumer = make_consumer(broker_url)
        self._processor = processor
        self._registry = registry
        self._poll_frequency_sec = poll_frequency_sec
        self._collectors: Dict[str, Dict[str, Any]] = {}
        self._metric_specs: Dict[str, Dict[str, dict]] = {}
        self._last_sync = 0.0
        self._stop_event = threading.Event()
        self._device_gauges_ready = False

    # -- spec sync -----------------------------------------------------------

    def sync_specs(self) -> None:
        if self._processor is None:
            return
        try:
            self._processor.deserialize(skip_sync=True)
        except Exception:
            pass
        specs: Dict[str, Dict[str, dict]] = {}
        for name, spec in self._processor.list_endpoint_logging().items():
            specs[name] = {k: v.as_dict() for k, v in spec.metrics.items()}
        self._metric_specs = specs
        self._last_sync = time.time()
        # Drop cached "no spec" sentinels so variables whose spec arrived after
        # their first observation start exporting without a restart.
        for per_ep in self._collectors.values():
            for variable in [k for k, v in per_ep.items() if v is None]:
                del per_ep[variable]

    def _spec_for(self, url: str) -> Dict[str, dict]:
        if url in self._metric_specs:
            return self._metric_specs[url]
        for name, metrics in self._metric_specs.items():
            if name.endswith("/*") and url.startswith(name[:-1]):
                return metrics
        # unknown endpoint: reserved-only logging + throttled re-sync
        if time.time() - self._last_sync > self._sync_threshold_sec:
            self.sync_specs()
            if url in self._metric_specs:
                return self._metric_specs[url]
        return {}

    # -- collectors -----------------------------------------------------------

    def _collector(self, url: str, variable: str) -> Optional[Any]:
        per_ep = self._collectors.setdefault(url, {})
        if variable in per_ep:
            return per_ep[variable]
        full_name = _sanitize("{}:{}".format(url, variable))
        collector = None
        if variable == "_latency":
            collector = ("histogram", Histogram(
                full_name, "Request latency for {}".format(url),
                buckets=_LATENCY_BUCKETS, registry=self._registry,
            ))
        elif variable == "_count":
            collector = ("counter", Counter(
                full_name, "Estimated request count for {}".format(url),
                registry=self._registry,
            ))
        else:
            spec = self._spec_for(url).get(variable)
            if spec is None:
                per_ep[variable] = None
                return None
            mtype = spec.get("type", "value")
            if mtype == "scalar":
                buckets = sorted(float(b) for b in (spec.get("buckets") or []))
                if not buckets:
                    buckets = list(_LATENCY_BUCKETS)
                if buckets[-1] != float("inf"):
                    buckets.append(float("inf"))
                collector = ("histogram", Histogram(
                    full_name, "scalar {} for {}".format(variable, url),
                    buckets=buckets, registry=self._registry,
                ))
            elif mtype == "enum":
                declared = [str(b) for b in (spec.get("buckets") or [])]
                if len(declared) >= 2:
                    # declared bucket set -> reference-parity EnumHistogram
                    # (fixed buckets, declared ordering)
                    collector = ("enum_hist", EnumHistogram(
                        full_name, "enum {} for {}".format(variable, url),
                        declared, registry=self._registry,
                    ))
                else:
                    # spec-less enum: dynamic value set via labeled Counter
                    collector = ("enum", Counter(
                        full_name, "enum {} for {}".format(variable, url),
                        labelnames=("value",), registry=self._registry,
                    ))
            elif mtype == "counter":
                collector = ("counter", Counter(
                    full_name, "counter {} for {}".format(variable, url),
                    registry=self._registry,
                ))
            else:
                collector = ("gauge", Gauge(
                    full_name, "value {} for {}".format(variable, url),
                    registry=self._registry,
                ))
        per_ep[variable] = collector
        return collector

    def _observe(self, url: str, variable: str, value: Any, count_weight: int) -> None:
        entry = self._collector(url, variable)
        if entry is None:
            return
        kind, collector = entry
        values = value if isinstance(value, (list, tuple)) else [value]
        for v in values:
            try:
                if kind == "histogram":
                    collector.observe(float(v))
                elif kind == "enum_hist":
                    collector.observe(v)
                elif kind == "enum":
                    collector.labels(value=str(v)).inc()
                elif kind == "counter":
                    collector.inc(float(v))
                else:
                    collector.set(float(v))
            except (TypeError, ValueError):
                continue

    # -- consumption -----------------------------------------------------------

    def process_batch(self, batch) -> int:
        n = 0
        for stats in batch:
            url = stats.get("_url")
            if not url:
                continue
            count_weight = int(stats.get("_count", 1))
            for variable, value in stats.items():
                if variable == "_url":
                    continue
                if variable == "_count":
                    entry = self._collector(url, "_count")
                    if entry:
                        entry[1].inc(count_weight)
                    continue
                self._observe(url, variable, value, count_weight)
            n += 1
        return n

    def update_device_gauges(self) -> None:
        """Per-chip HBM gauges (no-op on backends without memory_stats)."""
        try:
            import jax

            devices = jax.local_devices()
        except Exception:
            return
        if not self._device_gauges_ready:
            self._hbm_used = Gauge(
                "tpu_hbm_bytes_in_use", "HBM bytes in use", labelnames=("device",),
                registry=self._registry,
            )
            self._hbm_limit = Gauge(
                "tpu_hbm_bytes_limit", "HBM bytes limit", labelnames=("device",),
                registry=self._registry,
            )
            self._device_gauges_ready = True
        for d in devices:
            try:
                stats = d.memory_stats() or {}
            except Exception:
                continue
            if "bytes_in_use" in stats:
                self._hbm_used.labels(device=str(d.id)).set(stats["bytes_in_use"])
            limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
            if limit:
                self._hbm_limit.labels(device=str(d.id)).set(limit)

    def start(self) -> None:
        """Blocking consume loop (run in the statistics container main)."""
        self.sync_specs()
        last_spec_sync = time.time()
        while not self._stop_event.is_set():
            batch = self._consumer.poll() if self._consumer else []
            if batch:
                self.process_batch(batch)
            self.update_device_gauges()
            if time.time() - last_spec_sync > self._poll_frequency_sec:
                self.sync_specs()
                last_spec_sync = time.time()
            if not batch:
                self._stop_event.wait(timeout=1.0)

    def stop(self) -> None:
        self._stop_event.set()
