"""Statistics controller: broker → Prometheus collectors.

Capability parity with the reference's StatisticsController
(clearml_serving/statistics/metrics.py:188-373):

- consumes the stats topic, lazily creating one Prometheus collector per
  (endpoint, variable), named ``{endpoint}:{variable}`` sanitized;
- reserved variables: ``_latency`` → histogram with the reference's 5ms…5s
  buckets, ``_count`` → counter (weighted by the sampling-unbias factor);
- metric-spec types: scalar → bucketed Histogram, enum → EnumHistogram over
  the declared buckets (labeled-Counter fallback when no buckets declared),
  value → Gauge, counter → Counter;
- endpoints it doesn't know get auto-added with reserved-only logging and a
  throttled config re-sync;
- a sync daemon polls the control plane for metric-spec updates.

TPU addition (SURVEY.md §5.1/§5.5): per-chip HBM gauges sourced from
``jax.local_devices()[i].memory_stats()`` — the bytes-in-use / bytes-limit
pair is the serving fleet's north-star memory signal.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Any, Dict, Optional

from prometheus_client import Counter, Gauge, Histogram, REGISTRY

from .broker import make_consumer

_LATENCY_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.075, 0.1, 0.25, 0.5, 0.75,
    1.0, 2.5, 5.0, float("inf"),
)

_name_re = re.compile(r"[^a-zA-Z0-9_]")


def _sanitize(name: str) -> str:
    return _name_re.sub("_", name)


class EnumHistogram:
    """Reference-parity enum histogram (reference statistics/metrics.py:64-185).

    Exports a histogram-typed family with one NON-cumulative
    ``{name}_bucket{enum="<value>"}`` series per **declared** enum value (in
    declared order — the bucket set and ordering come from the metric spec,
    not from whichever values happen to arrive first) plus ``{name}_sum`` =
    total observations. Values outside the declared set are dropped, matching
    the reference's fixed-bucket contract. Enum specs below the two-bucket
    minimum fall back to a value-labeled Counter (dynamic value set) — see
    StatisticsController._collector.
    """

    def __init__(self, name: str, documentation: str, buckets, registry=REGISTRY):
        buckets = [str(b) for b in buckets]
        if len(buckets) < 2:
            raise ValueError("enum histogram needs at least two declared buckets")
        self._name = name
        self._documentation = documentation
        self._buckets = {b: 0.0 for b in buckets}  # insertion = declared order
        self._sum = 0.0
        self._lock = threading.Lock()
        if registry is not None:
            registry.register(self)

    def observe(self, value) -> None:
        v = str(value)
        with self._lock:
            if v not in self._buckets:
                return
            self._buckets[v] += 1.0
            self._sum += 1.0

    def collect(self):
        from prometheus_client.core import Metric

        metric = Metric(self._name, self._documentation, "histogram")
        with self._lock:
            for bucket, acc in self._buckets.items():
                metric.add_sample(
                    self._name + "_bucket", {"enum": bucket}, acc
                )
            metric.add_sample(self._name + "_sum", {}, self._sum)
        return [metric]

    def describe(self):
        return self.collect()



class _KeyedCollector:
    """Shared bookkeeping for scrape-time collectors keyed by model (or
    model@replica): one entry per key, replace-on-reregister (endpoint
    hot-reload must not leak the old engine or duplicate families), and
    hot-reload pruning for per-replica key variants."""

    def __init__(self, prefix: str):
        self._prefix = _sanitize(prefix)
        self._entries: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def set_entry(self, key: str, value) -> None:
        with self._lock:
            self._entries[str(key)] = value

    def remove_entry(self, key: str) -> None:
        with self._lock:
            self._entries.pop(str(key), None)

    def prune_entries(self, key: str, keep) -> None:
        """Drop entries registered for ``key`` or its per-replica
        variants (``key@...``) that are not in ``keep``: an endpoint
        hot-reload that changes the replica count must not leave stale
        entries pinning dead engines' state or exporting frozen series
        (docs/replication.md)."""
        keep = set(keep)
        with self._lock:
            stale = [
                k for k in self._entries
                if (k == key or k.startswith(key + "@")) and k not in keep
            ]
            for k in stale:
                self._entries.pop(k, None)

    def _snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._entries)

    def describe(self):
        # empty describe => prometheus_client registers without probing
        # collect() (providers may not be fully constructed yet)
        return []


class PrefixCacheCollector(_KeyedCollector):
    """Live LLM prefix-cache observability (llm/prefix_cache.py
    RadixPrefixCache): collect() reads each registered cache's counters —
    and, on the paged backend, the page pool's sharing/CoW counters — at
    scrape time, so the hit rate and HBM dedup of "millions of users share a
    system prompt" traffic are visible without the engine pushing samples
    anywhere.

    ONE collector per registry holds an entry per model (label ``model``):
    re-registering a model (endpoint hot-reload rebuilds its engine)
    REPLACES its entry, dropping the dead engine's cache reference — a
    per-engine collector would both leak the old cache's device KV and emit
    duplicate metric families, which makes Prometheus reject the scrape.
    Replica-fleet entries (docs/replication.md) register per replica with
    ``model``/``replica`` overrides: their samples carry the same
    {model, replica} label split as the lifecycle families (never a
    mangled model label), while legacy entries keep the historical
    {model} shape."""

    def __init__(self, prefix: str = "llm_prefix_cache"):
        super().__init__(prefix)

    def set_entry(self, key: str, cache, pool=None, *, model=None,
                  replica=None) -> None:
        super().set_entry(key, (cache, pool, model, replica))

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        entries = self._snapshot()
        p = self._prefix

        def labels(key, model, replica, extra=None):
            out = {"model": str(model or key)}
            if replica is not None:
                out["replica"] = str(replica)
            if extra:
                out.update({k: str(v) for k, v in extra.items()})
            return out

        # hit counter carries the serving TIER (docs/kv_tiering.md): hbm =
        # the whole run was resident, host = it needed promotion from the
        # host-RAM tier; sum over tier = total hits
        hits = CounterMetricFamily(
            p + "_hits", "prefix-cache lookups that matched >= 1 block, by "
            "serving tier (hbm = resident, host = promoted from host RAM)")
        cache_fams = [
            ("misses", "_total", CounterMetricFamily(
                p + "_misses", "prefix-cache lookups with no shared block")),
            ("hit_tokens", "_total", CounterMetricFamily(
                p + "_hit_tokens", "prompt tokens served from cached KV "
                "(prefill compute skipped)")),
            ("evictions", "_total", CounterMetricFamily(
                p + "_evictions", "radix-tree leaf evictions")),
            ("nodes", "", GaugeMetricFamily(
                p + "_nodes", "cached block-granular tree nodes")),
            ("cached_bytes", "", GaugeMetricFamily(
                p + "_bytes", "bytes of KV held (dense) or referenced "
                "(paged) by the cache")),
            ("cached_pages", "", GaugeMetricFamily(
                p + "_pages", "KV pool pages referenced by the cache (paged "
                "backend)")),
        ]
        shared = GaugeMetricFamily(
            "kv_pool_shared_pages",
            "pool pages with more than one reference (slot+cache or "
            "slot+slot zero-copy sharing)",
        )
        free = GaugeMetricFamily(
            "kv_pool_free_pages", "unreferenced pool pages"
        )
        cow = CounterMetricFamily(
            "kv_pool_cow_events",
            "copy-on-write page duplications (live slot extended into a "
            "shared page)",
        )
        any_pool = False
        for key, (cache, pool, model, replica) in entries.items():
            if not hasattr(cache, "stats"):
                # routing-only prefix probes (process-backend proxies)
                # have no stats surface; a single bad entry must not
                # poison the whole registry scrape
                continue
            stats = cache.stats()
            by_tier = stats.get("hits_by_tier") or {
                "hbm": stats.get("hits", 0)
            }
            for tier_name, count in by_tier.items():
                hits.add_sample(
                    hits.name + "_total",
                    labels(key, model, replica, {"tier": tier_name}), count,
                )
            for stat_key, suffix, fam in cache_fams:
                fam.add_sample(
                    fam.name + suffix, labels(key, model, replica),
                    stats[stat_key],
                )
            if pool is not None:
                any_pool = True
                row = labels(key, model, replica)
                shared.add_sample(shared.name, row, pool.shared_pages)
                free.add_sample(free.name, row, pool.free_pages)
                cow.add_sample(cow.name + "_total", row, pool.cow_events)
        yield hits
        for _, _, fam in cache_fams:
            yield fam
        if any_pool:
            yield shared
            yield free
            yield cow


class EngineLifecycleCollector(_KeyedCollector):
    """Request-lifecycle observability (docs/robustness.md): shed / deadline
    / watchdog / step-failure counters plus queue-depth and active-slot
    gauges, read live from each registered provider at scrape time so
    shedding decisions are observable next to what triggered them.

    A provider is a zero-arg callable returning the engine's
    ``lifecycle_stats()`` dict (or the gRPC client's retry stats); unknown
    keys are ignored so providers can grow without a collector change. One
    collector per registry holds an entry per model key — re-registering a
    key REPLACES its provider (engine hot-reload must not leak the old
    engine or duplicate families)."""

    def __init__(self, prefix: str = "engine"):
        super().__init__(prefix)

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
            HistogramMetricFamily,
        )

        providers = self._snapshot()
        rows = []
        for key, provider in providers.items():
            try:
                s = provider() or {}
            except Exception:
                continue
            rows.append((key, s))

        # label shape is PER ROW (docs/replication.md): a provider that
        # reports a `replica` id gets the replica label on its samples —
        # two replicas of one model would otherwise emit duplicate series
        # and Prometheus rejects the scrape — while providers without one
        # keep the historical {model} label set. Deciding this per row
        # (raw samples, not add_metric) means a fleet endpoint registering
        # on a shared registry never changes a LEGACY endpoint's series
        # identity: dashboards on engine_ready{model="A"} keep matching
        # when endpoint B scales out, and nothing flaps when B is evicted.
        def _labels(key, s, extra=None):
            out = {"model": str(s.get("model") or key)}
            if "replica" in s:
                out["replica"] = str(s["replica"])
            if extra:
                out.update({k: str(v) for k, v in extra.items()})
            return out

        def gauge(fam, key, s, value, **extra):
            fam.add_sample(fam.name, _labels(key, s, extra), value)

        def counter(fam, key, s, value, **extra):
            # CounterMetricFamily strips a trailing _total from its name;
            # sample names re-append it (same as add_metric)
            fam.add_sample(fam.name + "_total", _labels(key, s, extra), value)

        def hist(fam, key, s, snap, **extra):
            labels = _labels(key, s, extra)
            buckets, total = _hist_buckets(snap)
            for edge, cum in buckets:
                fam.add_sample(
                    fam.name + "_bucket", dict(labels, le=edge), cum
                )
            if buckets:
                # +Inf is last and provides the count (add_metric parity)
                fam.add_sample(fam.name + "_count", labels, buckets[-1][1])
            fam.add_sample(fam.name + "_sum", labels, total)

        p = self._prefix
        # per-class queue depth (docs/slo_scheduling.md): one series per
        # priority class plus class="all" for the total; providers that
        # report only a plain queue_depth int emit class="all"
        queue_depth = GaugeMetricFamily(
            p + "_queue_depth",
            "requests waiting in the engine's admission queue, by priority "
            "class (class=\"all\" = total)",
        )
        active_slots = GaugeMetricFamily(
            p + "_active_slots", "decode slots currently generating",
        )
        ready = GaugeMetricFamily(
            p + "_ready", "1 while the engine accepts work (0 = stopped or "
            "watchdog recovery in progress)",
        )
        sheds = CounterMetricFamily(
            p + "_sheds_total",
            "admissions shed at the front door, by reason and priority "
            "class (class=\"all\" = legacy per-reason totals)",
        )
        preemptions = CounterMetricFamily(
            p + "_preemptions_total",
            "batch-lane slots preempted for queued interactive work "
            "(docs/slo_scheduling.md)",
        )
        brownout_stage = GaugeMetricFamily(
            p + "_brownout_stage",
            "staged-degradation level (0 = normal; 1 spec decode off; 2 + "
            "batch token cap; 3 + prefill budget shrunk and best-effort "
            "shed)",
        )
        brownout_score = GaugeMetricFamily(
            p + "_brownout_score",
            "overload pressure score driving the brownout stage",
        )
        deadlines = CounterMetricFamily(
            p + "_deadline_hits_total",
            "requests failed on an elapsed budget",
        )
        trips = CounterMetricFamily(
            p + "_watchdog_trips_total",
            "stalled-loop detections (each failed the in-flight batch and "
            "recovered the loop)",
        )
        failures = CounterMetricFamily(
            p + "_step_failures_total",
            "decode dispatch failures survived by the loop",
        )
        grpc = CounterMetricFamily(
            "grpc_client_upstream_total",
            "engine-server gRPC attempts/retries/retry-budget exhaustions",
        )
        # pipelined-decode observability (docs/pipelined_decode.md): stage
        # timing histograms + the live in-flight dispatch queue depth
        inflight = GaugeMetricFamily(
            p + "_pipeline_inflight",
            "decode chunks dispatched but not yet retired",
        )
        pipe_depth = GaugeMetricFamily(
            p + "_pipeline_depth",
            "configured decode pipeline depth (1 = serial)",
        )
        dispatch_ms = HistogramMetricFamily(
            p + "_step_dispatch_ms",
            "host time to enqueue one decode chunk (ms)",
        )
        retire_ms = HistogramMetricFamily(
            p + "_step_retire_ms",
            "host time to sync + emit one retired chunk (ms)",
        )
        # ragged token-budget scheduler (docs/ragged_attention.md): how full
        # each mixed launch ran against its token budget, and how many rows
        # of each phase rode the launches — occupancy and admission
        # interleave are dashboard lines, not log greps
        budget_util = HistogramMetricFamily(
            p + "_step_token_budget_utilization",
            "per ragged step: tokens dispatched / step token budget",
        )
        step_rows = CounterMetricFamily(
            p + "_step_rows",
            "rows carried by ragged mixed launches, by phase "
            "(prefill = admission chunk rows, decode = multi-step token "
            "windows, spec_verify = q=k+1 draft-chain verify rows)",
        )
        ragged_jobs = GaugeMetricFamily(
            p + "_ragged_prefill_jobs",
            "admissions currently mid-prefill in the ragged scheduler",
        )
        ragged_budget = GaugeMetricFamily(
            p + "_step_token_budget",
            "effective ragged step token budget (brownout stage 3 shrinks "
            "it)",
        )
        # multi-step decode rows + spec-as-row (docs/ragged_attention.md):
        # tokens advanced per mixed launch (the dispatch-bubble
        # amortization headline — 1/mean is dispatches-per-decode-token)
        # and the per-launch accepted-draft fraction over verify rows
        tokens_per_launch = HistogramMetricFamily(
            p + "_decode_tokens_per_launch",
            "decode tokens advanced per ragged mixed launch (multi-step "
            "windows + accepted spec tokens)",
        )
        spec_accept = HistogramMetricFamily(
            p + "_spec_acceptance_rate",
            "per ragged launch: mean accepted-draft fraction over its "
            "spec verify rows (accepted / spec_k)",
        )
        # tree-draft verify rows (docs/spec_decode_trees.md): committed
        # root-to-leaf depth per verify row (the acceptance-gap headline
        # vs the chain baseline at equal verify budget) and how often the
        # proposer's drafts came from real history matches rather than
        # the repeat-last fallback
        spec_tree_depth = HistogramMetricFamily(
            p + "_spec_tree_accept_depth",
            "per tree-verify row: accepted root-to-leaf path depth "
            "(tokens committed from the draft tree in one launch)",
        )
        spec_proposer_hits = CounterMetricFamily(
            p + "_spec_proposer_hits_total",
            "verify rows whose draft came from a real proposer history "
            "match (not the repeat-last fallback), by proposer backend",
        )
        # paged KV pool capacity (docs/paged_kv_quant.md): bytes split by
        # kind (kv = data planes, scale = int8 dequant scale rows) plus an
        # info gauge carrying the pool dtype — the int8 capacity win is a
        # dashboard line, not a code comment
        kv_pool_bytes = GaugeMetricFamily(
            p + "_kv_pool_bytes",
            "device HBM held by the paged KV pools, by kind",
        )
        kv_pool_dtype = GaugeMetricFamily(
            p + "_kv_pool_dtype",
            "info gauge (always 1): storage dtype of the paged KV pools",
        )
        # host-RAM KV tier (docs/kv_tiering.md): where the prefix cache's
        # pages live (hbm vs host) and how many moved each way — the
        # capacity-planning signal the tier exists for
        kv_tier_pages = GaugeMetricFamily(
            p + "_kv_tier_pages",
            "prefix-cache KV pages held, by tier (hbm = device pool, "
            "host = pinned host RAM)",
        )
        kv_tier_bytes = GaugeMetricFamily(
            p + "_kv_tier_bytes",
            "prefix-cache KV bytes held, by tier",
        )
        kv_demotions = CounterMetricFamily(
            p + "_kv_demotions",
            "demotion events: batched HBM->host spill rounds (eviction "
            "pressure spilled instead of dropping; pages moved are in "
            "lifecycle_stats kv_tier.demoted_pages_total)",
        )
        kv_promotions = CounterMetricFamily(
            p + "_kv_promotions",
            "promotion events: demoted runs re-onlined to HBM (async DMA "
            "on a host-tier hit, or by reference at a store)",
        )
        # disaggregated prefill/decode (docs/disaggregation.md): pages
        # moved through the KV transport (direction="out" = shipped at a
        # prefill commit, "in" = imported on the decode replica), the
        # per-operation wall time, and the decode-side ship hit rate —
        # >= 0.9 is the clean-path headline (a shipped request's admission
        # recomputes none of the shipped KV)
        kv_ship_pages = CounterMetricFamily(
            p + "_kv_ship_pages",
            "KV pages moved through the cross-replica transport, by "
            "direction (out = exported at a prefill-replica commit, in = "
            "imported on a decode replica)",
        )
        kv_ship_ms = HistogramMetricFamily(
            p + "_kv_ship_ms",
            "per-shipment transport operation wall time (ms), by "
            "direction (out = export+send at commit, in = receive+fenced "
            "import)",
        )
        kv_ship_hit_rate = GaugeMetricFamily(
            p + "_kv_ship_hit_rate",
            "decode-replica ship hit rate: shipped requests whose "
            "admission found the whole storable prefix resident / all "
            "judged shipped requests (clean-path bound: >= 0.9)",
        )
        kv_ship_overlap = GaugeMetricFamily(
            p + "_kv_ship_overlap_ratio",
            "draft-ahead shipping overlap: pages shipped as unsealed "
            "partial frames before the prefill commit / all pages "
            "shipped for committed prefixes (0 = every page waited for "
            "the seal; -> 1 = the seal carried only the held-back tail)",
        )
        # socket KV-wire backend (llm/kv_wire.py, docs/disaggregation.md):
        # bytes actually framed onto the wire and the send->ack round trip
        # — absent entirely on the in-heap shared-slab backend, so the
        # series' existence also answers "which transport is this fleet on"
        kv_ship_wire_bytes = CounterMetricFamily(
            p + "_kv_ship_wire_bytes",
            "KV shipment bytes crossing the socket transport, by "
            "direction (out = framed + sent, in = received + decoded); "
            "only exported by the socket wire backend",
        )
        kv_ship_rtt_ms = HistogramMetricFamily(
            p + "_kv_ship_rtt_ms",
            "socket KV-wire send round-trip time (ms): frame write to "
            "receiver ack, per shipment",
        )
        # compile-surface discipline (docs/static_analysis.md TPU6xx): XLA
        # compilations observed by the compile sentry, split at the warmup
        # fence — phase="serve" must stay 0 on a zero-recompile-certified
        # engine; anything else is a loop-thread stall hiding in the tail
        xla_compiles = CounterMetricFamily(
            p + "_xla_compiles_total",
            "XLA compilations observed by the compile sentry "
            "(TPUSERVE_COMPILE_SENTRY), by phase (warmup = before the "
            "llm/warmup.py fence, serve = after: each is a loop-thread "
            "compile stall)",
        )
        xla_compile_ms = HistogramMetricFamily(
            p + "_xla_compile_ms",
            "per-compilation XLA compile time (ms) observed by the "
            "compile sentry",
        )
        # ownership discipline (docs/static_analysis.md TPU7xx): the
        # runtime ledger's live holds and its leak findings — a nonzero
        # leak total on an armed engine is a lost release on some
        # exception path, named (resource + acquire site) in the ledger's
        # violation records
        ledger_outstanding = GaugeMetricFamily(
            p + "_ledger_outstanding",
            "resources currently held per the ownership ledger "
            "(TPUSERVE_LEDGER), by resource class (cache-scoped classes "
            "are legitimately nonzero at idle; request-scoped classes "
            "drain to zero)",
        )
        ledger_leaks = CounterMetricFamily(
            p + "_ledger_leaks_total",
            "lost releases found by the ownership ledger's request-exit "
            "and drain audits (each names the leaked resource and its "
            "acquire site in lifecycle_stats()[\"ledger\"])",
        )
        # sharding discipline (docs/static_analysis.md TPU8xx): the
        # runtime sharding sentry's boundary audits and the two violation
        # classes — either counter moving on an armed engine is a silent
        # device<->host round-trip or layout drift that becomes a
        # cross-host gather (or one shard's garbage) under multi-process
        shard_audits = CounterMetricFamily(
            p + "_shard_audits_total",
            "loop-boundary sharding audits run by the sharding sentry "
            "(TPUSERVE_SHARD_SENTRY)",
        )
        shard_violations = CounterMetricFamily(
            p + "_shard_violations_total",
            "sharding-discipline violations found by the sentry, by kind "
            "(implicit_transfer = silent host materialization, "
            "unplanned_reshard = live spec drifted off the declared "
            "builder layout); each names the array path in "
            "lifecycle_stats()[\"sharding\"]",
        )

        def _hist_buckets(snap):
            """Engine _MsHistogram snapshot -> prometheus cumulative
            (le, count) pairs + sum."""
            edges = [str(b) for b in snap.get("buckets", [])] + ["+Inf"]
            cum, out = 0, []
            for edge, count in zip(edges, snap.get("counts", [])):
                cum += count
                out.append((edge, cum))
            return out, float(snap.get("sum_ms", 0.0))

        any_grpc = False
        any_pipeline = False
        any_kv_pool = False
        any_kv_tier = False
        any_kv_ship = False
        any_kv_wire = False
        any_slo = False
        any_ragged = False
        any_compile = False
        any_ledger = False
        any_shard = False
        for key, s in rows:
            kv_pool = s.get("kv_pool") or {}
            if kv_pool:
                any_kv_pool = True
                for kind in ("kv", "scale"):
                    if kind in kv_pool:
                        gauge(kv_pool_bytes, key, s, kv_pool[kind], kind=kind)
                if kv_pool.get("dtype"):
                    gauge(kv_pool_dtype, key, s, 1, dtype=kv_pool["dtype"])
            kv_tier = s.get("kv_tier") or {}
            if kv_tier:
                any_kv_tier = True
                for tier_name, v in (kv_tier.get("pages") or {}).items():
                    gauge(kv_tier_pages, key, s, v, tier=tier_name)
                for tier_name, v in (kv_tier.get("bytes") or {}).items():
                    gauge(kv_tier_bytes, key, s, v, tier=tier_name)
                if "demotions" in kv_tier:
                    counter(kv_demotions, key, s, kv_tier["demotions"])
                if "promotions" in kv_tier:
                    counter(kv_promotions, key, s, kv_tier["promotions"])
            kv_ship = s.get("kv_ship") or {}
            if kv_ship:
                any_kv_ship = True
                counter(kv_ship_pages, key, s,
                        kv_ship.get("ship_pages", 0), direction="out")
                counter(kv_ship_pages, key, s,
                        kv_ship.get("receive_pages", 0), direction="in")
                snap = kv_ship.get("ship_ms")
                if snap:
                    hist(kv_ship_ms, key, s, snap, direction="out")
                snap = kv_ship.get("receive_ms")
                if snap:
                    hist(kv_ship_ms, key, s, snap, direction="in")
                if kv_ship.get("hit_rate") is not None:
                    gauge(kv_ship_hit_rate, key, s, kv_ship["hit_rate"])
                if kv_ship.get("overlap_ratio") is not None:
                    gauge(kv_ship_overlap, key, s,
                          kv_ship["overlap_ratio"])
                wire = (kv_ship.get("transport") or {}).get("wire") or {}
                if wire:
                    any_kv_wire = True
                    counter(kv_ship_wire_bytes, key, s,
                            wire.get("bytes_sent", 0), direction="out")
                    counter(kv_ship_wire_bytes, key, s,
                            wire.get("bytes_received", 0), direction="in")
                    snap = wire.get("rtt_ms")
                    if snap:
                        hist(kv_ship_rtt_ms, key, s, snap)
            ledger_block = s.get("ledger") or {}
            if ledger_block:
                any_ledger = True
                for resource, v in (
                    ledger_block.get("outstanding") or {}
                ).items():
                    gauge(ledger_outstanding, key, s, v, resource=resource)
                if "leaks" in ledger_block:
                    counter(ledger_leaks, key, s, ledger_block["leaks"])
            shard_block = s.get("sharding") or {}
            if shard_block:
                any_shard = True
                if "audits" in shard_block:
                    counter(shard_audits, key, s, shard_block["audits"])
                for kind in ("implicit_transfers", "unplanned_reshards"):
                    if kind in shard_block:
                        counter(
                            shard_violations, key, s, shard_block[kind],
                            kind=kind.rstrip("s"),
                        )
            compile_block = s.get("compile") or {}
            if compile_block:
                any_compile = True
                for phase in ("warmup", "serve"):
                    if phase in compile_block:
                        counter(
                            xla_compiles, key, s, compile_block[phase],
                            phase=phase,
                        )
                snap = compile_block.get("compile_ms")
                if snap:
                    hist(xla_compile_ms, key, s, snap)
            ragged = s.get("ragged") or {}
            if ragged:
                any_ragged = True
                snap = ragged.get("budget_utilization")
                if snap:
                    hist(budget_util, key, s, snap)
                for phase, v in (ragged.get("step_rows") or {}).items():
                    counter(step_rows, key, s, v, phase=phase)
                if "prefill_jobs" in ragged:
                    gauge(ragged_jobs, key, s, ragged["prefill_jobs"])
                if "effective_budget" in ragged:
                    gauge(ragged_budget, key, s, ragged["effective_budget"])
                snap = ragged.get("tokens_per_launch")
                if snap:
                    hist(tokens_per_launch, key, s, snap)
                snap = ragged.get("spec_acceptance")
                if snap:
                    hist(spec_accept, key, s, snap)
                snap = ragged.get("spec_tree_depth")
                if snap:
                    hist(spec_tree_depth, key, s, snap)
                prop = ragged.get("spec_proposer")
                if prop:
                    counter(spec_proposer_hits, key, s,
                            prop.get("hit", 0),
                            proposer=prop.get("name", "unknown"))
            pipe = s.get("pipeline") or {}
            if pipe:
                any_pipeline = True
                if "inflight" in pipe:
                    gauge(inflight, key, s, pipe["inflight"])
                if "depth" in pipe:
                    gauge(pipe_depth, key, s, pipe["depth"])
                for fam, field in ((dispatch_ms, "dispatch_ms"),
                                   (retire_ms, "retire_ms")):
                    snap = pipe.get(field)
                    if snap:
                        hist(fam, key, s, snap)
            qd_classes = s.get("queue_depths")
            if isinstance(qd_classes, dict):
                for cls_name, v in qd_classes.items():
                    gauge(queue_depth, key, s, v, **{"class": cls_name})
            if "queue_depth" in s:
                gauge(queue_depth, key, s, s["queue_depth"],
                      **{"class": "all"})
            if "active_slots" in s:
                gauge(active_slots, key, s, s["active_slots"])
            if "ready" in s:
                gauge(ready, key, s, s["ready"])
            by_class = s.get("sheds_by_class")
            if isinstance(by_class, dict):
                for reason, per in by_class.items():
                    for cls_name, v in (per or {}).items():
                        counter(sheds, key, s, v, reason=reason,
                                **{"class": cls_name})
            for reason, v in (s.get("sheds") or {}).items():
                counter(sheds, key, s, v, reason=reason, **{"class": "all"})
            if "preemptions" in s:
                any_slo = True
                counter(preemptions, key, s, s["preemptions"])
            brown = s.get("brownout")
            if isinstance(brown, dict):
                any_slo = True
                gauge(brownout_stage, key, s, brown.get("stage", 0))
                gauge(brownout_score, key, s, brown.get("score", 0.0))
            for stage, v in (s.get("deadlines") or {}).items():
                counter(deadlines, key, s, v, stage=stage)
            if "watchdog_trips" in s:
                counter(trips, key, s, s["watchdog_trips"])
            if "step_failures" in s:
                counter(failures, key, s, s["step_failures"])
            for kind, v in (s.get("grpc") or {}).items():
                any_grpc = True
                counter(grpc, key, s, v, kind=kind)
        yield queue_depth
        yield active_slots
        yield ready
        yield sheds
        yield deadlines
        yield trips
        yield failures
        if any_slo:
            yield preemptions
            yield brownout_stage
            yield brownout_score
        if any_pipeline:
            yield inflight
            yield pipe_depth
            yield dispatch_ms
            yield retire_ms
        if any_ragged:
            yield budget_util
            yield step_rows
            yield ragged_jobs
            yield ragged_budget
            yield tokens_per_launch
            yield spec_accept
            yield spec_tree_depth
            yield spec_proposer_hits
        if any_kv_pool:
            yield kv_pool_bytes
            yield kv_pool_dtype
        if any_kv_tier:
            yield kv_tier_pages
            yield kv_tier_bytes
            yield kv_demotions
            yield kv_promotions
        if any_kv_ship:
            yield kv_ship_pages
            yield kv_ship_ms
            yield kv_ship_hit_rate
            yield kv_ship_overlap
        if any_kv_wire:
            yield kv_ship_wire_bytes
            yield kv_ship_rtt_ms
        if any_compile:
            yield xla_compiles
            yield xla_compile_ms
        if any_ledger:
            yield ledger_outstanding
            yield ledger_leaks
        if any_shard:
            yield shard_audits
            yield shard_violations
        if any_grpc:
            yield grpc



class ReplicaRouterCollector(_KeyedCollector):
    """Replica-fleet routing observability (docs/replication.md): ring
    size, per-(replica, route) request counters and ejection/re-admission
    events, read live from each registered router provider at scrape time.
    A provider is a zero-arg callable returning ``ReplicaRouter.stats()``
    (optionally with a ``model`` key overriding the entry key as the model
    label). One collector per registry, one entry per model key —
    re-registering a key replaces its provider (endpoint hot-reload)."""

    def __init__(self, prefix: str = "router"):
        super().__init__(prefix)

    def collect(self):
        from prometheus_client.core import (
            CounterMetricFamily,
            GaugeMetricFamily,
        )

        providers = self._snapshot()
        p = self._prefix
        ring_size = GaugeMetricFamily(
            p + "_ring_size",
            "replicas currently serving traffic (ready + warm)",
            labels=["model"],
        )
        replicas = GaugeMetricFamily(
            p + "_replicas",
            "replicas configured in the engine group",
            labels=["model"],
        )
        requests = CounterMetricFamily(
            p + "_requests_total",
            "routing decisions, by replica, route and role (affine = HRW "
            "first choice, spill = load-aware second choice, rebalance = "
            "health/eject reroute; role = the replica's prefill/decode/"
            "hybrid specialization, docs/disaggregation.md); decisions "
            "can exceed served requests when a stale pin re-routes "
            "between admission and generation",
            labels=["model", "replica", "route", "role"],
        )
        ejections = CounterMetricFamily(
            p + "_ejections_total",
            "ring ejections (engine not ready, or fault-forced via the "
            "router.eject seam)", labels=["model", "replica", "role"],
        )
        readmissions = CounterMetricFamily(
            p + "_readmissions_total",
            "ring re-admissions after recovery (each re-warmed through "
            "the warmup gate first)",
            labels=["model", "replica", "role"],
        )
        role_members = GaugeMetricFamily(
            p + "_role_members",
            "ring members currently serving, by replica role "
            "(docs/disaggregation.md; hybrid-only fleets report every "
            "member as hybrid)", labels=["model", "role"],
        )
        fleet_stage = GaugeMetricFamily(
            p + "_fleet_brownout_stage",
            "fleet brownout stage: the minimum stage over ring members "
            "(what the least-pressured replica can still absorb)",
            labels=["model"],
        )
        fleet_sheds = CounterMetricFamily(
            p + "_fleet_sheds_total",
            "requests shed at the router door by the fleet-wide brownout, "
            "by priority class", labels=["model", "class"],
        )
        # info gauge (value always 1): which replica backend the fleet
        # runs on — "inprocess" (N engines on one heap) or "process"
        # (supervised worker subprocesses, serving/process_replica.py)
        replica_backend = GaugeMetricFamily(
            p + "_replica_backend",
            "replica backend info gauge: value 1 on the series whose "
            "backend label names the fleet's backend (inprocess | process)",
            labels=["model", "backend"],
        )
        for key, provider in providers.items():
            try:
                s = provider() or {}
            except Exception:
                continue
            model = str(s.get("model") or key)
            roles = s.get("roles") or {}

            def role_of(name):
                return str(roles.get(name, "hybrid"))

            if "ring_size" in s:
                ring_size.add_metric([model], s["ring_size"])
            if "replicas" in s:
                replicas.add_metric([model], s["replicas"])
            if s.get("replica_backend"):
                replica_backend.add_metric(
                    [model, str(s["replica_backend"])], 1
                )
            for name, routes in (s.get("requests") or {}).items():
                for route, v in (routes or {}).items():
                    requests.add_metric(
                        [model, str(name), str(route), role_of(name)], v
                    )
            for name, v in (s.get("ejections") or {}).items():
                ejections.add_metric([model, str(name), role_of(name)], v)
            for name, v in (s.get("readmissions") or {}).items():
                readmissions.add_metric([model, str(name), role_of(name)], v)
            ring = set(s.get("ring") or [])
            if ring or roles:
                by_role = {}
                for name in ring:
                    by_role[role_of(name)] = by_role.get(role_of(name), 0) + 1
                for role in ("prefill", "decode", "hybrid"):
                    if role in by_role or role in roles.values():
                        role_members.add_metric(
                            [model, role], by_role.get(role, 0)
                        )
            brown = s.get("fleet_brownout") or {}
            if "stage" in brown:
                fleet_stage.add_metric([model], brown["stage"])
            for cls, v in (s.get("fleet_sheds") or {}).items():
                fleet_sheds.add_metric([model, str(cls)], v)
        yield ring_size
        yield replicas
        yield requests
        yield ejections
        yield readmissions
        yield role_members
        yield fleet_stage
        yield fleet_sheds
        yield replica_backend



# one collector per live registry (weak: test registries die with their
# tests; a reused id must not resurrect a collector bound to a dead one)
_prefix_collectors: "weakref.WeakKeyDictionary" = None  # lazy init
_lifecycle_collectors: "weakref.WeakKeyDictionary" = None  # lazy init
_router_collectors: "weakref.WeakKeyDictionary" = None  # lazy init


def register_replica_router(provider, registry=REGISTRY, key: str = "llm",
                            prefix: str = "router"):
    """Expose live replica-router metrics for ``key`` (model/endpoint
    name). ``provider`` is a zero-arg callable returning a
    ``ReplicaRouter.stats()``-shaped dict. Idempotent per (registry, key):
    re-registering replaces the provider. Returns the shared collector."""
    global _router_collectors
    import weakref

    if _router_collectors is None:
        _router_collectors = weakref.WeakKeyDictionary()
    per_registry = _router_collectors.setdefault(registry, {})
    collector = per_registry.get(prefix)
    if collector is None:
        collector = ReplicaRouterCollector(prefix)
        registry.register(collector)
        per_registry[prefix] = collector
    collector.set_entry(key, provider)
    return collector


def register_engine_lifecycle(provider, registry=REGISTRY, key: str = "llm",
                              prefix: str = "engine"):
    """Expose live request-lifecycle metrics for ``key`` (model/endpoint
    name). ``provider`` is a zero-arg callable returning a
    ``lifecycle_stats()``-shaped dict. Idempotent per (registry, key):
    re-registering replaces the provider. Returns the shared collector."""
    global _lifecycle_collectors
    import weakref

    if _lifecycle_collectors is None:
        _lifecycle_collectors = weakref.WeakKeyDictionary()
    per_registry = _lifecycle_collectors.setdefault(registry, {})
    collector = per_registry.get(prefix)
    if collector is None:
        collector = EngineLifecycleCollector(prefix)
        registry.register(collector)
        per_registry[prefix] = collector
    collector.set_entry(key, provider)
    return collector


def register_prefix_cache(cache, pool=None, registry=REGISTRY,
                          key: str = "llm",
                          prefix: str = "llm_prefix_cache",
                          model: Optional[str] = None,
                          replica: Optional[str] = None):
    """Expose live prefix-cache metrics for ``key`` (the model/endpoint
    name). Idempotent per (registry, key): re-registering replaces the
    entry, so engine hot-reloads neither leak the old cache nor duplicate
    metric families. Replica-fleet callers register one entry per replica
    under a unique key with ``model``/``replica`` overrides — samples then
    carry the {model, replica} label split (docs/replication.md). Returns
    the registry's shared collector."""
    global _prefix_collectors
    import weakref

    if _prefix_collectors is None:
        _prefix_collectors = weakref.WeakKeyDictionary()
    per_registry = _prefix_collectors.setdefault(registry, {})
    collector = per_registry.get(prefix)
    if collector is None:
        collector = PrefixCacheCollector(prefix)
        registry.register(collector)
        per_registry[prefix] = collector
    collector.set_entry(key, cache, pool, model=model, replica=replica)
    return collector



def _registry_collector(store, registry, prefix):
    if store is None:
        return None
    try:
        return store.get(registry, {}).get(prefix)
    except TypeError:
        return None


def prune_prefix_caches(key, keep, registry=REGISTRY,
                        prefix: str = "llm_prefix_cache") -> None:
    """Drop stale per-replica prefix-cache entries for ``key`` (see
    collector.prune_entries). No-op when no collector exists yet."""
    collector = _registry_collector(_prefix_collectors, registry, prefix)
    if collector is not None:
        collector.prune_entries(key, keep)


def prune_engine_lifecycle(key, keep, registry=REGISTRY,
                           prefix: str = "engine") -> None:
    """Drop stale per-replica lifecycle providers for ``key``."""
    collector = _registry_collector(_lifecycle_collectors, registry, prefix)
    if collector is not None:
        collector.prune_entries(key, keep)


def prune_replica_router(key, keep, registry=REGISTRY,
                         prefix: str = "router") -> None:
    """Drop stale router providers for ``key`` (e.g. a fleet endpoint
    reloaded as a single engine)."""
    collector = _registry_collector(_router_collectors, registry, prefix)
    if collector is not None:
        collector.prune_entries(key, keep)


class StatisticsController:
    _sync_threshold_sec = 30.0

    def __init__(
        self,
        broker_url: str,
        processor=None,  # ModelRequestProcessor for metric-spec sync (optional)
        registry=REGISTRY,
        poll_frequency_sec: float = 60.0,
    ):
        self._consumer = make_consumer(broker_url)
        self._processor = processor
        self._registry = registry
        self._poll_frequency_sec = poll_frequency_sec
        self._collectors: Dict[str, Dict[str, Any]] = {}
        self._metric_specs: Dict[str, Dict[str, dict]] = {}
        self._last_sync = 0.0
        self._stop_event = threading.Event()
        self._device_gauges_ready = False

    # -- spec sync -----------------------------------------------------------

    def sync_specs(self) -> None:
        if self._processor is None:
            return
        try:
            self._processor.deserialize(skip_sync=True)
        except Exception:
            pass
        specs: Dict[str, Dict[str, dict]] = {}
        for name, spec in self._processor.list_endpoint_logging().items():
            specs[name] = {k: v.as_dict() for k, v in spec.metrics.items()}
        self._metric_specs = specs
        self._last_sync = time.time()
        # Drop cached "no spec" sentinels so variables whose spec arrived after
        # their first observation start exporting without a restart.
        for per_ep in self._collectors.values():
            for variable in [k for k, v in per_ep.items() if v is None]:
                del per_ep[variable]

    def _spec_for(self, url: str) -> Dict[str, dict]:
        if url in self._metric_specs:
            return self._metric_specs[url]
        for name, metrics in self._metric_specs.items():
            if name.endswith("/*") and url.startswith(name[:-1]):
                return metrics
        # unknown endpoint: reserved-only logging + throttled re-sync
        if time.time() - self._last_sync > self._sync_threshold_sec:
            self.sync_specs()
            if url in self._metric_specs:
                return self._metric_specs[url]
        return {}

    # -- collectors -----------------------------------------------------------

    def _collector(self, url: str, variable: str) -> Optional[Any]:
        per_ep = self._collectors.setdefault(url, {})
        if variable in per_ep:
            return per_ep[variable]
        full_name = _sanitize("{}:{}".format(url, variable))
        collector = None
        if variable == "_latency":
            collector = ("histogram", Histogram(
                full_name, "Request latency for {}".format(url),
                buckets=_LATENCY_BUCKETS, registry=self._registry,
            ))
        elif variable == "_count":
            collector = ("counter", Counter(
                full_name, "Estimated request count for {}".format(url),
                registry=self._registry,
            ))
        else:
            spec = self._spec_for(url).get(variable)
            if spec is None:
                per_ep[variable] = None
                return None
            mtype = spec.get("type", "value")
            if mtype == "scalar":
                buckets = sorted(float(b) for b in (spec.get("buckets") or []))
                if not buckets:
                    buckets = list(_LATENCY_BUCKETS)
                if buckets[-1] != float("inf"):
                    buckets.append(float("inf"))
                collector = ("histogram", Histogram(
                    full_name, "scalar {} for {}".format(variable, url),
                    buckets=buckets, registry=self._registry,
                ))
            elif mtype == "enum":
                declared = [str(b) for b in (spec.get("buckets") or [])]
                if len(declared) >= 2:
                    # declared bucket set -> reference-parity EnumHistogram
                    # (fixed buckets, declared ordering)
                    collector = ("enum_hist", EnumHistogram(
                        full_name, "enum {} for {}".format(variable, url),
                        declared, registry=self._registry,
                    ))
                else:
                    # spec-less enum: dynamic value set via labeled Counter
                    collector = ("enum", Counter(
                        full_name, "enum {} for {}".format(variable, url),
                        labelnames=("value",), registry=self._registry,
                    ))
            elif mtype == "counter":
                collector = ("counter", Counter(
                    full_name, "counter {} for {}".format(variable, url),
                    registry=self._registry,
                ))
            else:
                collector = ("gauge", Gauge(
                    full_name, "value {} for {}".format(variable, url),
                    registry=self._registry,
                ))
        per_ep[variable] = collector
        return collector

    def _observe(self, url: str, variable: str, value: Any, count_weight: int) -> None:
        entry = self._collector(url, variable)
        if entry is None:
            return
        kind, collector = entry
        values = value if isinstance(value, (list, tuple)) else [value]
        for v in values:
            try:
                if kind == "histogram":
                    collector.observe(float(v))
                elif kind == "enum_hist":
                    collector.observe(v)
                elif kind == "enum":
                    collector.labels(value=str(v)).inc()
                elif kind == "counter":
                    collector.inc(float(v))
                else:
                    collector.set(float(v))
            except (TypeError, ValueError):
                continue

    # -- consumption -----------------------------------------------------------

    def process_batch(self, batch) -> int:
        n = 0
        for stats in batch:
            url = stats.get("_url")
            if not url:
                continue
            count_weight = int(stats.get("_count", 1))
            for variable, value in stats.items():
                if variable == "_url":
                    continue
                if variable == "_count":
                    entry = self._collector(url, "_count")
                    if entry:
                        entry[1].inc(count_weight)
                    continue
                self._observe(url, variable, value, count_weight)
            n += 1
        return n

    def update_device_gauges(self) -> None:
        """Per-chip HBM gauges (no-op on backends without memory_stats)."""
        try:
            import jax

            devices = jax.local_devices()
        except Exception:
            return
        if not self._device_gauges_ready:
            self._hbm_used = Gauge(
                "tpu_hbm_bytes_in_use", "HBM bytes in use", labelnames=("device",),
                registry=self._registry,
            )
            self._hbm_limit = Gauge(
                "tpu_hbm_bytes_limit", "HBM bytes limit", labelnames=("device",),
                registry=self._registry,
            )
            self._device_gauges_ready = True
        for d in devices:
            try:
                stats = d.memory_stats() or {}
            except Exception:
                continue
            if "bytes_in_use" in stats:
                self._hbm_used.labels(device=str(d.id)).set(stats["bytes_in_use"])
            limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
            if limit:
                self._hbm_limit.labels(device=str(d.id)).set(limit)

    def start(self) -> None:
        """Blocking consume loop (run in the statistics container main)."""
        self.sync_specs()
        last_spec_sync = time.time()
        while not self._stop_event.is_set():
            batch = self._consumer.poll() if self._consumer else []
            if batch:
                self.process_batch(batch)
            self.update_device_gauges()
            if time.time() - last_spec_sync > self._poll_frequency_sec:
                self.sync_specs()
                last_spec_sync = time.time()
            if not batch:
                self._stop_event.wait(timeout=1.0)

    def stop(self) -> None:
        self._stop_event.set()
