"""Atomic file IO + hashing helpers shared by the state store and engines."""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from pathlib import Path
from typing import Any, Optional, Union


def atomic_write_text(path: Union[str, Path], text: str) -> None:
    """Write a file so readers never observe a partial write (tmp + rename)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=str(path.parent), prefix=".{}.".format(path.name))
    try:
        # mkstemp creates 0600; shared-state docs must be readable by the other
        # service processes (router/engine/statistics may run as different UIDs
        # against one mount).
        umask = os.umask(0)
        os.umask(umask)
        os.fchmod(fd, 0o666 & ~umask)
        with os.fdopen(fd, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, str(path))
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_write_json(path: Union[str, Path], obj: Any) -> None:
    # No `default=` fallback: a non-JSON-serializable value must fail at the
    # write site, not silently stringify and corrupt the round-trip.
    atomic_write_text(path, json.dumps(obj, indent=1, sort_keys=True))


def read_json(path: Union[str, Path], retries: int = 3) -> Optional[Any]:
    """Read JSON, tolerating a concurrent atomic replace (retry on decode
    error) and stray non-document paths (None, like a missing file)."""
    path = Path(path)
    for attempt in range(retries):
        try:
            with open(path, "r") as f:
                return json.load(f)
        except (FileNotFoundError, NotADirectoryError, IsADirectoryError):
            return None
        except json.JSONDecodeError:
            if attempt == retries - 1:
                raise
    return None


def sha256_file(path: Union[str, Path], chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            data = f.read(chunk)
            if not data:
                break
            h.update(data)
    return h.hexdigest()


def sha256_obj(obj: Any) -> str:
    """Stable content hash of a JSON-serializable object."""
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True, default=str).encode("utf-8")
    ).hexdigest()
