"""TPU backend identity helpers.

The serving image's TPU is tunneled through an **experimental PJRT platform
named "axon"** (registered by the image's sitecustomize); jax reports the
device's platform as "axon" while device_kind still says TPU. A directly
attached chip reports platform "tpu". Everything that needs to answer "is
this device the TPU?" — the bench driver, the tunnel-watcher battery, the
kernel microbenches — shares this one predicate so a future rename only has
one place to miss.
"""

from __future__ import annotations


def is_tpu_device(dev) -> bool:
    """True if this jax device is the TPU, under any of its names."""
    return dev.platform in ("tpu", "axon") or "TPU" in getattr(
        dev, "device_kind", ""
    )
