#!/usr/bin/env bash
# Role-selecting entrypoint with the reference's restart-on-failure loop
# (reference serving/entrypoint.sh CLEARML_SERVING_RESTART_ON_FAILURE).
set -uo pipefail

ROLE="${1:-inference}"
RESTART="${TPUSERVE_RESTART_ON_FAILURE:-1}"

if [ -n "${TPUSERVE_EXTRA_PYTHON_PACKAGES:-}" ]; then
    pip install --no-cache-dir ${TPUSERVE_EXTRA_PYTHON_PACKAGES}
fi

run_role() {
    case "$ROLE" in
        inference)  exec_cmd="tpu-serving-inference" ;;
        engine)     exec_cmd="tpu-serving-engine" ;;
        statistics) exec_cmd="tpu-serving-statistics" ;;
        *) echo "unknown role: $ROLE" >&2; exit 2 ;;
    esac
    $exec_cmd
}

while true; do
    run_role
    code=$?
    if [ "$RESTART" != "1" ]; then
        exit $code
    fi
    echo "service exited ($code); restarting in 5s..." >&2
    sleep 5
done
