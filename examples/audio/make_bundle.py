"""Build a CI-sized random-weight Whisper bundle for the audio walkthrough.

Real deployments convert an HF checkpoint instead (readme step 1:
``python -m clearml_serving_tpu.engines.importers.convert_hf_whisper``);
this stands in for that step the way the other suites' train_model.py
scripts stand in for real training, so the register -> deploy -> transcribe
flow runs end-to-end in CI without model downloads.
"""

import jax


def main(out_dir: str = "whisper-bundle") -> None:
    from clearml_serving_tpu import models
    from clearml_serving_tpu.engines.jax_engine import save_bundle

    cfg = dict(
        preset="whisper-test",
        # decoder prompt ids a converted checkpoint would carry (the values
        # are arbitrary for random weights; the STRUCTURE mirrors
        # <|startoftranscript|> <|task|> <|...|> <|notimestamps|>)
        transcribe_prompt_ids=[300, 301, 302, 349],
        translate_prompt_ids=[300, 303, 302, 349],
        eos_token_id=340,
        notimestamps_token_id=349,
        timestamp_begin=350,
        time_precision=0.02,
        sampling_rate=16000,
        chunk_length=1,
    )
    bundle = models.build_model("whisper", cfg)
    params = bundle.init(jax.random.PRNGKey(0))
    save_bundle(out_dir, "whisper", dict(bundle.config), params)
    print("saved whisper bundle to {}".format(out_dir))


if __name__ == "__main__":
    main()
