"""Build a bert-base token-classification jax bundle.

With a local HuggingFace checkpoint this copies real weights; without one it
falls back to random init (identical serving path; reference parity is the
conversion flow: reference examples/huggingface exports ONNX for Triton,
here HF state-dict -> jax pytree)."""

import sys

import jax

from clearml_serving_tpu import models
from clearml_serving_tpu.engines.jax_engine import save_bundle

CONFIG = {"preset": "bert-base", "num_labels": 9}


def convert_from_hf(hf_dir: str):
    """Map a HF BertForTokenClassification state dict into our param pytree."""
    import numpy as np
    import torch

    from transformers import AutoModelForTokenClassification

    hf = AutoModelForTokenClassification.from_pretrained(hf_dir, local_files_only=True)
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    bundle = models.build_model("bert", CONFIG)
    params = bundle.init(jax.random.PRNGKey(0))

    def t(name):
        return np.asarray(sd[name])

    params["word_embed"] = t("bert.embeddings.word_embeddings.weight")
    params["pos_embed"] = t("bert.embeddings.position_embeddings.weight")
    params["type_embed"] = t("bert.embeddings.token_type_embeddings.weight")
    params["embed_norm"] = {
        "scale": t("bert.embeddings.LayerNorm.weight"),
        "bias": t("bert.embeddings.LayerNorm.bias"),
    }
    for i, layer in enumerate(params["layers"]):
        pre = "bert.encoder.layer.{}.".format(i)
        wq = t(pre + "attention.self.query.weight").T
        wk = t(pre + "attention.self.key.weight").T
        wv = t(pre + "attention.self.value.weight").T
        layer["wqkv"] = np.concatenate([wq, wk, wv], axis=1)
        layer["bqkv"] = np.concatenate(
            [t(pre + "attention.self.query.bias"), t(pre + "attention.self.key.bias"),
             t(pre + "attention.self.value.bias")]
        )
        layer["wo"] = t(pre + "attention.output.dense.weight").T
        layer["bo"] = t(pre + "attention.output.dense.bias")
        layer["attn_norm"] = {
            "scale": t(pre + "attention.output.LayerNorm.weight"),
            "bias": t(pre + "attention.output.LayerNorm.bias"),
        }
        layer["w1"] = t(pre + "intermediate.dense.weight").T
        layer["b1"] = t(pre + "intermediate.dense.bias")
        layer["w2"] = t(pre + "output.dense.weight").T
        layer["b2"] = t(pre + "output.dense.bias")
        layer["ffn_norm"] = {
            "scale": t(pre + "output.LayerNorm.weight"),
            "bias": t(pre + "output.LayerNorm.bias"),
        }
    params["classifier"] = {"w": t("classifier.weight").T, "b": t("classifier.bias")}
    return params


def main():
    bundle = models.build_model("bert", CONFIG)
    if len(sys.argv) > 1:
        params = convert_from_hf(sys.argv[1])
        print("converted weights from", sys.argv[1])
    else:
        params = bundle.init(jax.random.PRNGKey(0))
        print("no checkpoint given: random init (serving-path demo)")
    save_bundle("bert-bundle", "bert", CONFIG, params)
    print("saved ./bert-bundle")


if __name__ == "__main__":
    main()
