"""BERT NER endpoint hooks: text -> token ids; logits -> labeled spans."""

from typing import Any

import numpy as np

SEQ_LEN = 128


class Preprocess(object):
    def __init__(self):
        self._tokenizer = None

    def _tok(self):
        if self._tokenizer is None:
            try:
                from transformers import AutoTokenizer

                self._tokenizer = AutoTokenizer.from_pretrained(
                    "bert-base-cased", local_files_only=True
                )
            except Exception:
                self._tokenizer = False  # whitespace fallback
        return self._tokenizer

    def preprocess(self, body: dict, state: dict, collect_custom_statistics_fn=None) -> Any:
        text = body.get("text", "")
        tok = self._tok()
        if tok:
            enc = tok(text, padding="max_length", truncation=True, max_length=SEQ_LEN)
            ids = enc["input_ids"]
            mask = enc["attention_mask"]
        else:
            words = text.split()[: SEQ_LEN - 1]
            ids = [hash(w) % 30000 for w in words] + [0] * (SEQ_LEN - len(words))
            mask = [1] * len(words) + [0] * (SEQ_LEN - len(words))
        state["mask"] = mask
        return {
            "input_ids": np.asarray([ids], np.int32),
            "attention_mask": np.asarray([mask], np.int32),
        }

    def postprocess(self, data: Any, state: dict, collect_custom_statistics_fn=None) -> dict:
        logits = np.asarray(data)[0]
        labels = logits.argmax(-1)
        n = sum(state.get("mask", []))
        return {"labels": labels[:n].tolist()}
