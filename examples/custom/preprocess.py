"""Fully-custom model: load() builds it, process() runs it."""

from typing import Any


class Preprocess(object):
    def load(self, local_file_name) -> Any:
        # build/load anything; keep a reference for process() (per-endpoint
        # instance — safe), and return it so the engine tracks lifetime
        self.model = lambda xs: [x * 2 for x in xs]
        return self.model

    def preprocess(self, body: dict, state: dict, collect_custom_statistics_fn=None) -> Any:
        return body.get("x", [])

    def process(self, data: Any, state: dict, collect_custom_statistics_fn=None) -> Any:
        if collect_custom_statistics_fn:
            collect_custom_statistics_fn({"x0": data[0] if data else 0})
        return self.model(data)

    def postprocess(self, data: Any, state: dict, collect_custom_statistics_fn=None) -> dict:
        return {"y": data}
