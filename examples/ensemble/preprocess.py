"""Ensemble endpoint pre/post-processing (reference examples/ensemble
preprocess.py contract: x0, x1 in, y out)."""

from typing import Any

import numpy as np


class Preprocess(object):
    def preprocess(self, body: dict, state: dict, collect_custom_statistics_fn=None) -> Any:
        return [[body.get("x0", 0), body.get("x1", 0)]]

    def postprocess(self, data: Any, state: dict, collect_custom_statistics_fn=None) -> dict:
        return dict(y=data.tolist() if isinstance(data, np.ndarray) else data)
