"""Train a sklearn VotingRegressor ensemble and save it for serving
(reference examples/ensemble/train_model.py parity, without the ClearML SDK)."""

import joblib
from sklearn.datasets import make_regression
from sklearn.ensemble import RandomForestRegressor, VotingRegressor
from sklearn.linear_model import LinearRegression


def main() -> None:
    X, y = make_regression(n_samples=500, n_features=2, random_state=0, noise=4.0)
    reg1 = RandomForestRegressor(n_estimators=10, random_state=1)
    reg2 = LinearRegression()
    ensemble = VotingRegressor([("rf", reg1), ("lr", reg2)])
    ensemble.fit(X, y)
    joblib.dump(ensemble, "ensemble-model.pkl", compress=True)
    print("saved ensemble-model.pkl")


if __name__ == "__main__":
    main()
