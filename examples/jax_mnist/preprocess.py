"""MNIST endpoint hooks: accept a nested-list image, return digit + probs."""

from typing import Any

import numpy as np


class Preprocess(object):
    def preprocess(self, body: dict, state: dict, collect_custom_statistics_fn=None) -> Any:
        image = np.asarray(body["image"], dtype=np.float32)
        if image.ndim == 2:           # single image -> batch of one
            image = image[None]
        return {"image": image}

    def postprocess(self, data: Any, state: dict, collect_custom_statistics_fn=None) -> dict:
        logits = np.asarray(data)
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        return {"digit": int(probs[0].argmax()), "probs": probs[0].tolist()}
