"""Build an MNIST-CNN jax bundle (random-init here; swap in real training or a
converted checkpoint for accuracy — the serving path is identical)."""

import jax

from clearml_serving_tpu import models
from clearml_serving_tpu.engines.jax_engine import save_bundle

CONFIG = {"in_hw": [28, 28], "in_ch": 1, "channels": [32, 64], "dense": 128, "out_dim": 10}


def main():
    bundle = models.build_model("cnn", CONFIG)
    params = bundle.init(jax.random.PRNGKey(0))
    save_bundle("mnist-bundle", "cnn", CONFIG, params)
    print("saved ./mnist-bundle")


if __name__ == "__main__":
    main()
