"""LightGBM iris endpoint pre/post-processing (reference examples/lightgbm
preprocess.py contract: x0..x3 in, argmax class out)."""

from typing import Any

import numpy as np


class Preprocess(object):
    def preprocess(self, body: dict, state: dict, collect_custom_statistics_fn=None) -> Any:
        return [
            [body.get("x0", 0), body.get("x1", 0), body.get("x2", 0), body.get("x3", 0)]
        ]

    def postprocess(self, data: Any, state: dict, collect_custom_statistics_fn=None) -> dict:
        # softmax class probabilities -> predicted class + probs
        probs = np.asarray(data)
        return dict(y=probs.tolist(), predicted=int(np.argmax(probs, axis=-1)[0]))
