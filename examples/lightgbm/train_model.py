"""Train a LightGBM iris classifier and save it for serving (reference
examples/lightgbm/train_model.py parity, without the ClearML SDK)."""

import lightgbm as lgb
from sklearn.datasets import load_iris
from sklearn.model_selection import train_test_split


def main() -> None:
    X, y = load_iris(return_X_y=True)
    X_train, X_test, y_train, y_test = train_test_split(X, y, test_size=0.1)
    dtrain = lgb.Dataset(X_train, label=y_train)
    params = {"objective": "multiclass", "metric": "softmax", "num_class": 3}
    model = lgb.train(params=params, train_set=dtrain)
    model.save_model("lgbm_model.txt")
    print("saved lgbm_model.txt")


if __name__ == "__main__":
    main()
