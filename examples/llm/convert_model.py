"""Convert a HuggingFace Llama checkpoint into a jax bundle.

    python examples/llm/convert_model.py /path/to/hf-llama-dir [out-bundle-dir]

The mapping is validated in tests/test_hf_convert.py by comparing logits
against transformers' LlamaForCausalLM on a tiny random-init config —
our decoder is numerically faithful to the HF implementation
(RoPE half-split convention, GQA head grouping, fp32 RMSNorm).
"""

from __future__ import annotations

import sys

import numpy as np


def convert_hf_llama(hf_model, dtype: str = "float32") -> tuple:
    """(config_dict, params) from a transformers llama-family CausalLM:
    LlamaForCausalLM, Qwen2ForCausalLM (QKV biases), MistralForCausalLM
    (sliding-window attention), Phi3ForCausalLM (fused qkv/gate_up
    projections split here; LongRoPE rides rope_scaling) — same skeleton,
    small config/tensor deltas. `dtype` sets both the stored weight dtype
    and the bundle's compute dtype (serving default: pass "bfloat16")."""
    hf_cfg = hf_model.config
    sd_keys = hf_model.state_dict().keys()
    # Qwen2 sets no attention_bias flag pre-4.37-config models; detect from
    # the checkpoint itself
    attn_bias = bool(getattr(hf_cfg, "attention_bias", False)) or (
        "model.layers.0.self_attn.q_proj.bias" in sd_keys
    )
    sliding = 0
    if getattr(hf_cfg, "use_sliding_window", True):  # Mistral has no flag
        sliding = int(getattr(hf_cfg, "sliding_window", 0) or 0)
    if sliding:
        # Qwen2 windows only layers >= max_window_layers; our bundle has one
        # global window, so a MIXED checkpoint would silently mis-window the
        # full-attention layers — refuse instead
        mwl = getattr(hf_cfg, "max_window_layers", None)
        n_layers_ = int(hf_cfg.num_hidden_layers)
        if mwl is not None:
            if int(mwl) >= n_layers_:
                sliding = 0  # no layer actually slides
            elif int(mwl) > 0:
                raise ValueError(
                    "mixed sliding/full attention (max_window_layers={} of {}"
                    " layers) is not supported; re-export with "
                    "use_sliding_window=False or convert a uniform-window "
                    "checkpoint".format(mwl, n_layers_)
                )
    rope_scaling = getattr(hf_cfg, "rope_scaling", None)
    model_type = str(getattr(hf_cfg, "model_type", "llama"))
    gemma = model_type in ("gemma", "gemma2")
    config = {
        "vocab_size": int(hf_cfg.vocab_size),
        "dim": int(hf_cfg.hidden_size),
        "n_layers": int(hf_cfg.num_hidden_layers),
        "n_heads": int(hf_cfg.num_attention_heads),
        "n_kv_heads": int(hf_cfg.num_key_value_heads),
        "ffn_dim": int(hf_cfg.intermediate_size),
        "rope_theta": float(getattr(hf_cfg, "rope_theta", 10000.0)),
        "norm_eps": float(hf_cfg.rms_norm_eps),
        "max_seq_len": int(getattr(hf_cfg, "max_position_embeddings", 4096)),
        "tie_embeddings": bool(getattr(hf_cfg, "tie_word_embeddings", False)),
        "dtype": dtype,
    }
    if attn_bias:
        config["attn_bias"] = True
    if rope_scaling:
        # validated by the model build (llama3/linear/longrope supported;
        # others raise)
        config["rope_scaling"] = dict(rope_scaling)
        rtype = rope_scaling.get("rope_type") or rope_scaling.get("type")
        if rtype == "longrope":
            # the attention scale needs the deployed AND original context
            # lengths; HF (Phi-3) keeps both OUTSIDE the rope_scaling dict
            config["rope_scaling"].setdefault(
                "max_position_embeddings",
                int(getattr(hf_cfg, "max_position_embeddings", 4096)),
            )
            orig = getattr(
                hf_cfg, "original_max_position_embeddings", None
            )
            if orig:
                config["rope_scaling"].setdefault(
                    "original_max_position_embeddings", int(orig)
                )
    if gemma:
        # Gemma family deltas: zero-init (1+w) norms, GeGLU, sqrt(dim) embed
        # scaling, head_dim decoupled from dim
        config["norm_offset"] = True
        # HF forces gelu_pytorch_tanh whenever hidden_activation is unset —
        # original Gemma-1.0 configs carry hidden_act="gelu" but transformers
        # ignores it (GemmaMLP warns and uses the tanh approximation), so
        # falling back to hidden_act here would silently diverge
        config["hidden_act"] = str(
            getattr(hf_cfg, "hidden_activation", None) or "gelu_pytorch_tanh"
        )
        config["embed_scale"] = float(config["dim"]) ** 0.5
        config["head_dim"] = int(
            getattr(hf_cfg, "head_dim", config["dim"] // config["n_heads"])
        )
    if model_type == "gemma2":
        # Gemma-2: logit softcaps, query_pre_attn_scalar score scale,
        # post-sublayer norms, interleaved local/global attention
        if getattr(hf_cfg, "attn_logit_softcapping", None):
            config["attn_logit_softcap"] = float(hf_cfg.attn_logit_softcapping)
        if getattr(hf_cfg, "final_logit_softcapping", None):
            config["final_logit_softcap"] = float(hf_cfg.final_logit_softcapping)
        if getattr(hf_cfg, "query_pre_attn_scalar", None):
            config["query_scale"] = float(hf_cfg.query_pre_attn_scalar) ** -0.5
        config["post_block_norms"] = True
        sliding = int(getattr(hf_cfg, "sliding_window", 0) or 0)
        layer_types = list(getattr(hf_cfg, "layer_types", None) or [])
        if not layer_types:
            # HF Gemma-2 default: even layers slide, odd layers are global
            layer_types = [
                "sliding_attention" if i % 2 == 0 else "full_attention"
                for i in range(config["n_layers"])
            ]
        is_global = [1.0 if t == "full_attention" else 0.0 for t in layer_types]
        if sliding and any(g == 0.0 for g in is_global):
            config["sliding_window"] = sliding
            if any(g == 1.0 for g in is_global):
                config["alt_window"] = True
                config["attn_global_layers"] = is_global
    elif sliding and sliding < config["max_seq_len"]:
        config["sliding_window"] = sliding
    sd = {k: v.detach().cpu().numpy() for k, v in hf_model.state_dict().items()}
    import jax.numpy as jnp

    np_dtype = jnp.dtype(dtype)

    def t(name):
        return np.asarray(sd[name]).astype(np_dtype)

    params = {
        "embed": t("model.embed_tokens.weight"),
        "final_norm": t("model.norm.weight"),
        "layers": [],
    }
    if not config["tie_embeddings"]:
        params["lm_head"] = t("lm_head.weight").T
    gemma2 = model_type == "gemma2"
    phi3 = model_type == "phi3"
    prf = getattr(hf_cfg, "partial_rotary_factor", None)
    if prf not in (None, 1, 1.0):
        # e.g. Phi-4-mini (model_type phi3, partial_rotary_factor 0.75):
        # our rope applies to the full head_dim, so converting would serve
        # silently wrong logits (or fail with a misleading factor-length
        # error under longrope) — refuse loudly
        raise ValueError(
            "partial_rotary_factor={} is not supported (RoPE applies to "
            "the full head_dim)".format(prf)
        )
    head_dim_ = int(
        getattr(hf_cfg, "head_dim", None)
        or config["dim"] // config["n_heads"]
    )
    if head_dim_ != config["dim"] // config["n_heads"]:
        # decoupled head_dim must reach the bundle, or build_model's
        # dim//n_heads fallback reshapes the split projections wrongly
        config["head_dim"] = head_dim_
    q_rows = config["n_heads"] * head_dim_
    kv_rows = config["n_kv_heads"] * head_dim_
    for i in range(config["n_layers"]):
        pre = "model.layers.{}.".format(i)
        if phi3:
            # Phi-3 fuses the attention projections into qkv_proj
            # ([q+2kv rows, dim]) and the GLU input into gate_up_proj
            # ([2*ffn, dim]); split them into the separate factors the
            # bundle stores
            qkv = t(pre + "self_attn.qkv_proj.weight")
            gate_up = t(pre + "mlp.gate_up_proj.weight")
            wq = qkv[:q_rows].T
            wk = qkv[q_rows : q_rows + kv_rows].T
            wv = qkv[q_rows + kv_rows :].T
            w_gate = gate_up[: config["ffn_dim"]].T
            w_up = gate_up[config["ffn_dim"] :].T
        else:
            wq = t(pre + "self_attn.q_proj.weight").T
            wk = t(pre + "self_attn.k_proj.weight").T
            wv = t(pre + "self_attn.v_proj.weight").T
            w_gate = t(pre + "mlp.gate_proj.weight").T
            w_up = t(pre + "mlp.up_proj.weight").T
        layer = {
            "attn_norm": t(pre + "input_layernorm.weight"),
            "wq": wq,
            "wk": wk,
            "wv": wv,
            "wo": t(pre + "self_attn.o_proj.weight").T,
            # Gemma-2 renames: its pre_feedforward_layernorm plays the
            # standard pre-FFN role; post_attention_layernorm becomes the
            # post-sublayer norm
            "ffn_norm": t(
                pre + ("pre_feedforward_layernorm.weight" if gemma2
                       else "post_attention_layernorm.weight")
            ),
            "w_gate": w_gate,
            "w_up": w_up,
            "w_down": t(pre + "mlp.down_proj.weight").T,
        }
        if gemma2:
            layer["post_attn_norm"] = t(pre + "post_attention_layernorm.weight")
            layer["post_ffn_norm"] = t(pre + "post_feedforward_layernorm.weight")
            if config.get("alt_window"):
                layer["attn_global"] = np.float32(
                    config["attn_global_layers"][i]
                )
        if attn_bias:
            layer["bq"] = t(pre + "self_attn.q_proj.bias")
            layer["bk"] = t(pre + "self_attn.k_proj.bias")
            layer["bv"] = t(pre + "self_attn.v_proj.bias")
        params["layers"].append(layer)
    return config, params


def main():
    from transformers import AutoModelForCausalLM

    from clearml_serving_tpu.engines.jax_engine import save_bundle

    src = sys.argv[1]
    out = sys.argv[2] if len(sys.argv) > 2 else "llama-bundle"
    dtype = sys.argv[3] if len(sys.argv) > 3 else "bfloat16"
    hf = AutoModelForCausalLM.from_pretrained(src, local_files_only=True)
    config, params = convert_hf_llama(hf, dtype=dtype)
    save_bundle(out, "llama", config, params)
    print("saved {} ({} layers, dim {})".format(out, config["n_layers"], config["dim"]))
    print("serve with: tpu-serving model upload --name llama --path {} ...".format(out))


if __name__ == "__main__":
    main()
