"""Load-test harness — the reference's ApacheBench recipe as a script
(reference examples/huggingface readme "Benchmarking": ab -l -n 8000 -c 128).

Reports req/s, p50/p99 latency, and for OpenAI streaming endpoints p50/p99
TTFT — the BASELINE.md per-endpoint metrics.

    python examples/loadtest/loadtest.py http://127.0.0.1:8080/serve/test_model \
        --payload '{"x0":1,"x1":2,"x2":3,"x3":4}' -n 1000 -c 32
"""

from __future__ import annotations

import argparse
import asyncio
import json
import time

import aiohttp
import numpy as np


async def worker(session, url, payload, results, ttfts, n_done, n_total, stream):
    while True:
        i = next(n_done)
        if i >= n_total:
            return
        t0 = time.perf_counter()
        try:
            async with session.post(url, json=payload) as resp:
                if stream:
                    first = True
                    async for _ in resp.content.iter_any():
                        if first:
                            ttfts.append(time.perf_counter() - t0)
                            first = False
                else:
                    await resp.read()
                results.append((time.perf_counter() - t0, resp.status))
        except Exception:
            results.append((time.perf_counter() - t0, -1))


async def run(args):
    payload = json.loads(args.payload)
    stream = bool(payload.get("stream"))
    results, ttfts = [], []
    counter = iter(range(10**9))
    timeout = aiohttp.ClientTimeout(total=args.timeout)
    async with aiohttp.ClientSession(timeout=timeout) as session:
        t0 = time.perf_counter()
        await asyncio.gather(
            *[
                worker(session, args.url, payload, results, ttfts, counter, args.n, stream)
                for _ in range(args.concurrency)
            ]
        )
        wall = time.perf_counter() - t0
    lat = np.array([r[0] for r in results if r[1] == 200])
    errors = sum(1 for r in results if r[1] != 200)
    out = {
        "requests": len(results),
        "errors": errors,
        "req_per_sec": round(len(lat) / wall, 2),
        "p50_ms": round(float(np.percentile(lat, 50)) * 1000, 2) if len(lat) else None,
        "p99_ms": round(float(np.percentile(lat, 99)) * 1000, 2) if len(lat) else None,
    }
    if ttfts:
        out["ttft_p50_ms"] = round(float(np.percentile(ttfts, 50)) * 1000, 2)
        out["ttft_p99_ms"] = round(float(np.percentile(ttfts, 99)) * 1000, 2)
    print(json.dumps(out))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("url")
    parser.add_argument("--payload", default="{}")
    parser.add_argument("-n", type=int, default=1000)
    parser.add_argument("-c", "--concurrency", type=int, default=32)
    parser.add_argument("--timeout", type=float, default=120.0)
    asyncio.run(run(parser.parse_args()))


if __name__ == "__main__":
    main()
