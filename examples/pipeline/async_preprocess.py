"""Async pipeline: fan out to stage_a and stage_b concurrently, merge."""

import asyncio


class Preprocess(object):
    async def process(self, data, state, collect_custom_statistics_fn=None):
        a, b = await asyncio.gather(
            self.send_request("stage_a", data=data),
            self.send_request("stage_b", data=data),
        )
        return {"a": a, "b": b}
