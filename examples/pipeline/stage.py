class Preprocess(object):
    def process(self, data, state, collect_custom_statistics_fn=None):
        return {"sum": sum(data.get("x", []))}
