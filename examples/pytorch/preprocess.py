"""MNIST CNN endpoint pre/post-processing (reference examples/pytorch
preprocess.py contract: base64/array image in, argmax digit out)."""

from typing import Any

import numpy as np


class Preprocess(object):
    def preprocess(self, body: dict, state: dict, collect_custom_statistics_fn=None) -> Any:
        # {"image": [[...28x28...]]} or a flat 784 list
        image = np.asarray(body.get("image", body), np.float32)
        if image.ndim == 1:
            image = image.reshape(28, 28)
        if image.ndim == 2:
            image = image[None]  # add channel
        if image.ndim == 3:
            image = image[None]  # add batch
        return {"input_0": image.tolist()}

    def postprocess(self, data: Any, state: dict, collect_custom_statistics_fn=None) -> dict:
        log_probs = np.asarray(data)
        return {"digit": int(np.argmax(log_probs, axis=-1)[0])}
