"""Train a small MNIST-style CNN and export it as TorchScript (model.pt).

Mirror of the reference pytorch example (reference
examples/pytorch/train_pytorch_mnist.py) without the torchvision dependency:
trains on synthetic digit-like data so the walkthrough runs anywhere, exports
TorchScript — the same format the reference's Triton/libtorch path consumes
(triton_helper.py:165-167). The serving side converts it to a JAX/XLA
executable for TPU (engines/importers/torchscript_import.py); no torch at
serving time.
"""

import torch
import torch.nn as nn


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(1, 8, 3, padding=1)
        self.conv2 = nn.Conv2d(8, 16, 3, stride=2, padding=1)
        self.fc1 = nn.Linear(16 * 7 * 7, 64)
        self.fc2 = nn.Linear(64, 10)

    def forward(self, x):
        x = torch.relu(self.conv1(x))
        x = torch.max_pool2d(torch.relu(self.conv2(x)), 2)
        x = torch.flatten(x, 1)
        x = torch.relu(self.fc1(x))
        return torch.log_softmax(self.fc2(x), dim=-1)


def main() -> None:
    torch.manual_seed(0)
    model = Net()
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    loss_fn = nn.NLLLoss()
    # synthetic "digits": class-dependent blob patterns
    for step in range(60):
        labels = torch.randint(0, 10, (64,))
        images = torch.randn(64, 1, 28, 28) * 0.1
        for i, lab in enumerate(labels):
            images[i, 0, lab.item() : lab.item() + 8, 8:20] += 1.0
        opt.zero_grad()
        loss = loss_fn(model(images), labels)
        loss.backward()
        opt.step()
        if step % 20 == 0:
            print("step {} loss {:.4f}".format(step, loss.item()))

    model.eval()
    scripted = torch.jit.script(model)
    scripted.save("pytorch-mnist.pt")
    print("saved TorchScript model to pytorch-mnist.pt")


if __name__ == "__main__":
    main()
