"""Iris endpoint pre/post-processing (same contract as the reference example)."""

from typing import Any


class Preprocess(object):
    def preprocess(self, body: dict, state: dict, collect_custom_statistics_fn=None) -> Any:
        # {"x0": .., "x1": .., "x2": .., "x3": ..} -> [[x0, x1, x2, x3]]
        return [[body.get("x0", 0), body.get("x1", 0), body.get("x2", 0), body.get("x3", 0)]]

    def postprocess(self, data: Any, state: dict, collect_custom_statistics_fn=None) -> dict:
        return {"y": data.tolist() if hasattr(data, "tolist") else data}
