"""Train a toy iris classifier and save it with joblib."""

import joblib
from sklearn.datasets import load_iris
from sklearn.linear_model import LogisticRegression


def main():
    x, y = load_iris(return_X_y=True)
    model = LogisticRegression(max_iter=200).fit(x, y)
    joblib.dump(model, "sklearn-model.pkl")
    print("saved sklearn-model.pkl (train acc {:.3f})".format(model.score(x, y)))


if __name__ == "__main__":
    main()
