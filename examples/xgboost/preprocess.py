"""XGBoost iris endpoint pre/post-processing (reference examples/xgboost
preprocess.py contract: x0..x3 in, y out).

Unlike the reference, the xgboost engine here builds the DMatrix itself
(engines/cpu_engines.py) — preprocess returns plain nested lists, so user
code needs no xgboost import."""

from typing import Any

import numpy as np


class Preprocess(object):
    def preprocess(self, body: dict, state: dict, collect_custom_statistics_fn=None) -> Any:
        return [
            [body.get("x0", 0), body.get("x1", 0), body.get("x2", 0), body.get("x3", 0)]
        ]

    def postprocess(self, data: Any, state: dict, collect_custom_statistics_fn=None) -> dict:
        return dict(y=data.tolist() if isinstance(data, np.ndarray) else data)
