"""Train an XGBoost iris model and save it for serving (reference
examples/xgboost/train_model.py parity, without the ClearML SDK dependency)."""

import xgboost as xgb
from sklearn.datasets import load_iris
from sklearn.model_selection import train_test_split


def main() -> None:
    X, y = load_iris(return_X_y=True)
    X_train, X_test, y_train, y_test = train_test_split(
        X, y, test_size=0.2, random_state=100
    )
    dtrain = xgb.DMatrix(X_train, label=y_train)
    dtest = xgb.DMatrix(X_test, label=y_test)
    params = {"objective": "reg:squarederror", "eval_metric": "rmse"}
    bst = xgb.train(
        params,
        dtrain,
        num_boost_round=100,
        evals=[(dtrain, "train"), (dtest, "test")],
        verbose_eval=0,
    )
    bst.save_model("xgb_model.json")
    print("saved xgb_model.json")


if __name__ == "__main__":
    main()
