#!/usr/bin/env bash
# Static checks, one entrypoint: ruff -> mypy -> tpuserve-analyze.
#
# The project-native analyzer is the HARD gate: dependency-free (stdlib ast
# only, no jax import), so it runs identically in every container and its
# findings always fail this script.
#
# ruff and mypy run with the permissive pyproject.toml baselines when
# installed; the serving container does not ship them, so their baselines
# have not been validated against this tree on every image. To keep tier-1
# hermetic (green here must not mean red on an image that happens to have
# them), their findings are ADVISORY by default — printed, not fatal. Set
# CHECK_STRICT=1 to make them fail the script once the baselines have been
# validated where the tools exist.
#
# Usage: scripts/check.sh [paths...]   (default: clearml_serving_tpu/)
set -o pipefail
cd "$(dirname "$0")/.."

paths=("$@")
if [ ${#paths[@]} -eq 0 ]; then
  paths=(clearml_serving_tpu/)
fi

rc=0
advisory_rc=0

if python -c "import ruff" >/dev/null 2>&1 || command -v ruff >/dev/null 2>&1; then
  echo "== ruff =="
  if command -v ruff >/dev/null 2>&1; then
    ruff check "${paths[@]}" || advisory_rc=1
  else
    python -m ruff check "${paths[@]}" || advisory_rc=1
  fi
else
  echo "== ruff == (not installed; skipped)"
fi

if python -c "import mypy" >/dev/null 2>&1; then
  echo "== mypy =="
  python -m mypy "${paths[@]}" || advisory_rc=1
else
  echo "== mypy == (not installed; skipped)"
fi

if [ "$advisory_rc" -ne 0 ]; then
  if [ -n "$CHECK_STRICT" ]; then
    rc=1
  else
    echo "(ruff/mypy findings above are advisory; CHECK_STRICT=1 makes them fatal)"
  fi
fi

# one pass runs every rule family, TPU1xx..TPU8xx — including the
# compile-surface rules (TPU601-604), the ownership-discipline rules
# (TPU701-704: acquire/release pairing over exception paths) and the
# sharding/mesh-discipline rules (TPU801-804: mesh-axis closed world,
# __shardings__ declarations, multihost-unsafe host access, silent
# replication fallbacks; docs/static_analysis.md). --timings keeps the
# per-family analyzer cost
# visible as the catalog grows (the gate must stay a pre-commit-scale
# tool, not a CI-only one). CI (.github/workflows/checks.yml) invokes
# this same script; use `--format github` there for inline diff
# annotations, and `--changed-only` for the PR fast lane.
echo "== tpuserve-analyze =="
python -m clearml_serving_tpu.analyze --timings "${paths[@]}" || rc=1

exit $rc
