#!/usr/bin/env python
"""Offline checkpoint quantizer: bf16 llama bundle -> packed int8/int4 tree.

Converts a native jax bundle directory (model_config.json + params.msgpack,
engines/jax_engine.py) into the packed quantized layouts of ops/quant.py:

    int8: per-output-channel symmetric  {"_q8", "_scale"}
    int4: group-quantized w4a16 (AWQ/GPTQ-style)  {"_q4", "_scale4"}

The output is a normal bundle: load it with the usual endpoint config and
the engine detects the packed tree (ops/quant.detect_weight_quant), so no
``engine.weight_quant`` override is needed — quantization cost is paid once
offline instead of at every endpoint load, and the full-precision weights
never have to fit in serving-host memory again. int4 decode matmuls then
route through the Pallas fused dequant-matmul (ops/fused_matmul.py,
docs/w4a16.md).

Usage:
    python scripts/quantize_ckpt.py SRC_BUNDLE DST_BUNDLE [--bits 4]
                                    [--group 128] [--dry-run]

``--group`` (int4 only) must keep the fused kernel's alignment gates in
mind: group % 64 == 0 shapes take the kernel on hardware; anything else
still serves via the XLA fallback.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _tree_bytes(tree) -> int:
    import jax

    return int(sum(
        leaf.nbytes for leaf in jax.tree.leaves(tree) if hasattr(leaf, "nbytes")
    ))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Quantize a bf16 llama bundle to packed int8/int4."
    )
    parser.add_argument("src", help="source bundle dir (model_config.json)")
    parser.add_argument("dst", help="output bundle dir (created)")
    parser.add_argument("--bits", type=int, default=4, choices=(4, 8))
    parser.add_argument(
        "--group", type=int, default=None,
        help="int4 scale-group size in input rows (default {}; group %% 64 "
             "== 0 keeps the fused TPU kernel eligible)".format(128),
    )
    parser.add_argument(
        "--dry-run", action="store_true",
        help="quantize in memory and print the byte savings without "
             "writing the output bundle",
    )
    args = parser.parse_args(argv)

    from clearml_serving_tpu.utils.files import read_json

    src = Path(args.src)
    meta = read_json(src / "model_config.json")
    if not meta:
        parser.error("not a native jax bundle (missing model_config.json): "
                     "{}".format(src))
    if meta.get("arch") != "llama":
        parser.error(
            "quantize_ckpt handles llama-family bundles (got arch={!r})"
            .format(meta.get("arch"))
        )

    from clearml_serving_tpu.engines.jax_engine import load_bundle, save_bundle
    from clearml_serving_tpu.ops import quant

    bundle, params = load_bundle(src)
    already = quant.detect_weight_quant(params)
    if already:
        parser.error(
            "bundle is already {}-quantized; quantize from the original "
            "full-precision checkpoint".format(already)
        )
    group = args.group if args.group is not None else quant.INT4_GROUP
    before = _tree_bytes(params)
    qparams = quant.quantize_llama_params(params, bits=args.bits, group=group)
    after = _tree_bytes(qparams)
    if not args.dry_run:
        save_bundle(Path(args.dst), meta["arch"],
                    dict(meta.get("config") or {}), qparams)
    print(
        "{verb} {src} -> {dst}: int{bits}{grp}, {before:.1f} MB -> "
        "{after:.1f} MB ({ratio:.2f}x)".format(
            verb="would quantize (dry run)" if args.dry_run else "quantized",
            src=src, dst=args.dst, bits=args.bits,
            grp=" (group {})".format(group) if args.bits == 4 else "",
            before=before / 2**20, after=after / 2**20,
            ratio=before / max(after, 1),
        )
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
