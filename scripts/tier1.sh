#!/usr/bin/env bash
# Canonical tier-1 test entrypoint — the EXACT command ROADMAP.md specifies
# ("Tier-1 verify"). Builders and CI invoke this instead of hand-copying the
# pipeline, so the pass-count extraction and flags can never drift.
set -o pipefail
cd "$(dirname "$0")/.."
# static checks gate the run: a lock-discipline or donation violation fails
# fast with the rule table instead of surfacing as a flaky test 10 minutes in
# (skip with TIER1_SKIP_CHECKS=1 when bisecting runtime-only failures)
if [ -z "$TIER1_SKIP_CHECKS" ]; then
  scripts/check.sh || exit 1
  # deterministic interleaving explorer smoke (docs/static_analysis.md):
  # small K, fixed seed, CPU — clean sweep of every scenario plus the
  # mutation self-test (each seeded defect must be caught)
  echo "== schedule-explorer smoke =="
  env JAX_PLATFORMS=cpu python -m clearml_serving_tpu.llm.schedule_explorer \
    --smoke || exit 1
fi
LOG="${TIER1_LOG:-/tmp/_t1.log}"
rm -f "$LOG"
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)
exit $rc
