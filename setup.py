from pathlib import Path

from setuptools import find_packages, setup

here = Path(__file__).parent
version = {}
exec((here / "clearml_serving_tpu" / "version.py").read_text(), version)

setup(
    name="clearml-serving-tpu",
    version=version["__version__"],
    description=(
        "TPU-native model serving: CLI + control plane + JAX/XLA/Pallas engine "
        "tier with clearml-serving capability parity"
    ),
    long_description=(here / "README.md").read_text(),
    long_description_content_type="text/markdown",
    packages=find_packages(include=["clearml_serving_tpu*"]),
    include_package_data=True,
    package_data={"clearml_serving_tpu.native": ["*.cpp", "Makefile"]},
    python_requires=">=3.10",
    install_requires=[
        "jax",
        "flax",
        "numpy",
        "aiohttp",
        "requests",
        "msgpack",
        "grpcio",
        "prometheus-client",
    ],
    extras_require={
        "cpu-engines": ["scikit-learn", "joblib", "xgboost", "lightgbm"],
        "kafka": ["kafka-python"],
        "tokenizers": ["transformers", "tokenizers"],
    },
    entry_points={
        "console_scripts": [
            "tpu-serving = clearml_serving_tpu.__main__:main",
            "tpu-serving-inference = clearml_serving_tpu.serving.main:main",
            "tpu-serving-engine = clearml_serving_tpu.engine_server.server:main",
            "tpu-serving-statistics = clearml_serving_tpu.statistics.main:main",
        ]
    },
)
