"""Test configuration: force JAX onto a virtual 8-device CPU platform so the
full stack (including multi-chip sharding) runs without TPU hardware.

Must set the env vars before jax is imported anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import pytest  # noqa: E402


@pytest.fixture()
def state_root(tmp_path):
    """Isolated control-plane state root per test."""
    root = tmp_path / "state"
    os.environ["TPUSERVE_STATE_ROOT"] = str(root)
    yield root
    os.environ.pop("TPUSERVE_STATE_ROOT", None)
