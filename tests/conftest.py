"""Test configuration: force JAX onto a virtual 8-device CPU platform so the
full stack (including multi-chip sharding) runs without TPU hardware.

Must set the env vars before jax is imported anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The serving image preloads jax via sitecustomize, so the env vars above can
# arrive after import. The config knobs below still apply as long as the
# backend itself has not been initialized yet.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    # jax >= 0.4.x with the explicit knob; older/other versions rely on the
    # XLA_FLAGS fallback set above
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass

assert jax.device_count() == 8, (
    "tests need 8 virtual CPU devices (got {}); the XLA_FLAGS "
    "--xla_force_host_platform_device_count=8 fallback did not take — jax "
    "was initialized before conftest ran".format(jax.device_count())
)

import pytest  # noqa: E402


def pytest_configure(config):
    # the repo has no pytest.ini/pyproject marker section; register the
    # tier-1 exclusion marker here so `-m 'not slow'` runs warning-free
    config.addinivalue_line(
        "markers", "slow: long-running test excluded from the tier-1 run"
    )
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection robustness test (CPU-fast, runs in tier-1; "
        "select with -m chaos)",
    )


@pytest.fixture()
def state_root(tmp_path):
    """Isolated control-plane state root per test."""
    root = tmp_path / "state"
    os.environ["TPUSERVE_STATE_ROOT"] = str(root)
    yield root
    os.environ.pop("TPUSERVE_STATE_ROOT", None)
