"""tpuserve-analyze: per-rule fixtures (positive / negative / ignore) and the
tree-wide zero-findings gate that makes the analyzer part of tier-1.

Each rule gets at least: a snippet that MUST flag, a closely-related snippet
that must NOT flag, and proof the inline `# tpuserve: ignore[CODE]` escape
hatch silences exactly that finding. The tree-wide test is the acceptance
criterion: `python -m clearml_serving_tpu.analyze clearml_serving_tpu/`
exits 0 on the committed tree, and reintroducing a violation (or deleting an
ignore annotation) flips it non-zero.
"""

import os
import subprocess
import sys
import textwrap

import pytest

from clearml_serving_tpu.analyze import RULES, analyze_paths, analyze_source
from clearml_serving_tpu.analyze import rules_errors, rules_locks
from clearml_serving_tpu.llm import faults

PKG_ROOT = os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)  # repo root
PKG_DIR = os.path.join(PKG_ROOT, "clearml_serving_tpu")

# path hints: some rules gate on where the file lives
LLM_PATH = "clearml_serving_tpu/llm/fixture.py"
ROUTER_PATH = "clearml_serving_tpu/serving/fixture.py"


def codes(source, path=LLM_PATH):
    return [f.code for f in analyze_source(textwrap.dedent(source), path)]


# -- TPU101/102/103/104: async-blocking ---------------------------------------


def test_tpu101_time_sleep_in_async_def():
    src = """
        import time
        async def handler():
            time.sleep(1)
    """
    assert codes(src) == ["TPU101"]


def test_tpu101_asyncio_sleep_is_fine():
    src = """
        import asyncio
        async def handler():
            await asyncio.sleep(1)
    """
    assert codes(src) == []


def test_tpu101_sync_def_sleep_is_fine():
    src = """
        import time
        def worker():
            time.sleep(1)
    """
    assert codes(src) == []


def test_tpu101_nested_sync_def_inside_async_is_fine():
    # a nested def handed to to_thread re-enters synchronous land
    src = """
        import asyncio, time
        async def handler():
            def blocking():
                time.sleep(1)
            await asyncio.to_thread(blocking)
    """
    assert codes(src) == []


def test_tpu101_ignore_comment():
    src = """
        import time
        async def handler():
            time.sleep(1)  # tpuserve: ignore[TPU101] event loop not running yet
    """
    assert codes(src) == []


def test_tpu102_open_in_async_def():
    src = """
        async def handler():
            with open("f") as fh:
                return fh.read()
    """
    assert codes(src) == ["TPU102"]


def test_tpu103_block_until_ready_and_device_get():
    src = """
        import jax
        async def handler(x):
            y = x.block_until_ready()
            return jax.device_get(y)
    """
    assert codes(src) == ["TPU103", "TPU103"]


def test_tpu104_unawaited_acquire():
    src = """
        async def handler(self):
            self._lock.acquire()
    """
    assert codes(src) == ["TPU104"]


def test_tpu104_awaited_acquire_is_fine():
    src = """
        async def handler(lock):
            await lock.acquire()
    """
    assert codes(src) == []


# -- TPU201/202/203: jit boundaries -------------------------------------------


def test_tpu201_closure_over_self():
    src = """
        import jax
        class Engine:
            def __init__(self):
                def _step(x):
                    return x * self.scale
                self._step_jit = jax.jit(_step)
    """
    assert codes(src) == ["TPU201"]


def test_tpu201_local_capture_is_fine():
    src = """
        import jax
        class Engine:
            def __init__(self):
                scale = self.scale
                def _step(x):
                    return x * scale
                self._step_jit = jax.jit(_step)
    """
    assert codes(src) == []


def test_tpu201_lambda_over_self():
    src = """
        import jax
        class Engine:
            def compile(self):
                return jax.jit(lambda x: self.fn(x))
    """
    assert codes(src) == ["TPU201"]


def test_tpu202_donated_buffer_reused():
    src = """
        import jax
        class Cache:
            def __init__(self):
                def _write(pool, x):
                    return pool
                self._write = jax.jit(_write, donate_argnums=(0,))
            def update(self, x):
                out = self._write(self.buf, x)
                return self.buf.sum()
    """
    assert codes(src) == ["TPU202"]


def test_tpu202_rebind_idiom_is_fine():
    src = """
        import jax
        class Cache:
            def __init__(self):
                def _write(pool, x):
                    return pool
                self._write = jax.jit(_write, donate_argnums=(0,))
            def update(self, x):
                self.buf = self._write(self.buf, x)
                return self.buf.sum()
    """
    assert codes(src) == []


def test_tpu203_unhashable_static_arg():
    src = """
        import jax
        class Engine:
            def __init__(self):
                def _f(x, cfg):
                    return x
                self._f = jax.jit(_f, static_argnums=(1,))
            def run(self, x):
                return self._f(x, [1, 2])
    """
    assert codes(src) == ["TPU203"]


def test_tpu203_tuple_static_arg_is_fine():
    src = """
        import jax
        class Engine:
            def __init__(self):
                def _f(x, cfg):
                    return x
                self._f = jax.jit(_f, static_argnums=(1,))
            def run(self, x):
                return self._f(x, (1, 2))
    """
    assert codes(src) == []


# -- TPU301: lock discipline --------------------------------------------------

_POOL_DECL = """
    import threading
    class Pool:
        __guarded_by__ = {"_mutex": ("_table",)}
        def __init__(self):
            self._mutex = threading.Lock()
            self._table = []
"""


def test_tpu301_mutation_outside_lock():
    src = _POOL_DECL + """
        def grow(self, page):
            self._table.append(page)
    """
    assert codes(src) == ["TPU301"]


def test_tpu301_mutation_under_lock_is_fine():
    src = _POOL_DECL + """
        def grow(self, page):
            with self._mutex:
                self._table.append(page)
    """
    assert codes(src) == []


def test_tpu301_subscript_and_augassign():
    src = _POOL_DECL + """
        def bump(self, i):
            self._table[i] += 1
    """
    assert codes(src) == ["TPU301"]


def test_tpu301_init_is_exempt():
    assert codes(_POOL_DECL) == []


def test_tpu301_def_line_ignore_covers_whole_helper():
    src = _POOL_DECL + """
        def _grow_locked(self, page):  # tpuserve: ignore[TPU301] lock held by caller
            self._table.append(page)
            self._table.pop()
    """
    assert codes(src) == []


def test_tpu301_nested_def_does_not_inherit_lock():
    # the nested callback may run after the with block exits
    src = _POOL_DECL + """
        def grow(self, page):
            with self._mutex:
                def later():
                    self._table.append(page)
                return later
    """
    assert codes(src) == ["TPU301"]


def test_tpu301_cross_module_registry_applies():
    # _refs lives in the PROJECT registry (kv_cache.PagePool), so poking it
    # from another module is flagged without any local declaration
    src = """
        def corrupt(pool, page):
            pool._refs[page] += 1
    """
    assert codes(src) == ["TPU301"]
    src_locked = """
        def fix(pool, page):
            with pool._lock:
                pool._refs[page] += 1
    """
    assert codes(src_locked) == []


# -- TPU401/402: error discipline ---------------------------------------------


def test_tpu401_bare_except_flagged_everywhere():
    src = """
        def f():
            try:
                g()
            except:
                pass
    """
    assert codes(src, path=LLM_PATH) == ["TPU401"]


def test_tpu401_swallow_on_router_path():
    src = """
        def f():
            try:
                g()
            except Exception:
                pass
    """
    assert codes(src, path=ROUTER_PATH) == ["TPU401"]
    # same snippet off the router path: not flagged (swallows there are
    # judged by humans; only the bare form is globally banned)
    assert codes(src, path=LLM_PATH) == []


def test_tpu401_handled_exception_is_fine():
    src = """
        def f():
            try:
                g()
            except Exception as ex:
                print(ex)
    """
    assert codes(src, path=ROUTER_PATH) == []


def test_tpu401_ignore_with_reason():
    src = """
        def f():
            try:
                g()
            except Exception:  # tpuserve: ignore[TPU401] best-effort metrics
                pass
    """
    assert codes(src, path=ROUTER_PATH) == []


def test_tpu402_raise_exception_on_router_path():
    src = """
        def f():
            raise Exception("boom")
    """
    assert codes(src, path=ROUTER_PATH) == ["TPU402"]
    assert codes(src, path=LLM_PATH) == []


def test_tpu402_structured_raise_is_fine():
    src = """
        from clearml_serving_tpu.errors import EngineOverloadedError
        def f():
            raise EngineOverloadedError("busy")
    """
    assert codes(src, path=ROUTER_PATH) == []


# -- TPU403: fault-point registry ---------------------------------------------


def test_tpu403_unknown_point():
    src = """
        from clearml_serving_tpu.llm import faults
        def f():
            faults.fire("engine.decoed")
    """
    assert codes(src, path="/nonexistent/llm/fixture.py") == ["TPU403"]


def test_tpu403_known_point_is_fine():
    src = """
        from clearml_serving_tpu.llm import faults
        def f():
            faults.fire("engine.decode")
    """
    assert codes(src, path="/nonexistent/llm/fixture.py") == []


def test_tpu403_reads_registry_from_real_faults_py():
    # a file inside the package resolves KNOWN_POINTS from llm/faults.py
    src = """
        from . import faults
        def f():
            faults.fire("engine.release")
            faults.fire("not.a.point")
    """
    found = codes(src, path=os.path.join(PKG_DIR, "llm", "fixture.py"))
    assert found == ["TPU403"]


def test_fallback_registry_matches_runtime_registry():
    assert rules_errors.FALLBACK_POINTS == faults.KNOWN_POINTS


def test_configure_rejects_unknown_point():
    with pytest.raises(ValueError, match="unknown fault point"):
        faults.configure([{"point": "engine.nope"}])
    faults.clear()


# -- TPU501-504: thread-affinity discipline -----------------------------------

_AFFINE_DECL = """
    import asyncio
    class Engine:
        __affine_to__ = {"loop": ("_chunks",)}
"""


def test_tpu501_worker_mutation_of_loop_affine_state():
    src = _AFFINE_DECL + """
        def _worker(self):
            self._chunks.append(1)
        async def step(self):
            await asyncio.to_thread(self._worker)
    """
    assert codes(src) == ["TPU501"]


def test_tpu501_loop_mutation_is_fine():
    src = _AFFINE_DECL + """
        async def step(self):
            self._chunks.append(1)
    """
    assert codes(src) == []


def test_tpu501_thread_target_is_a_worker_root():
    src = _AFFINE_DECL + """
        import threading
        def _daemon(self):
            self._chunks.clear()
        async def launch(self):
            threading.Thread(target=self._daemon).start()
    """
    assert codes(src) == ["TPU501"]


def test_tpu501_uncontexted_function_fails_open():
    # a function never reached from a thread root has no context: the pass
    # fails open (documented blind spot) instead of guessing
    src = _AFFINE_DECL + """
        def orphan(self):
            self._chunks.append(1)
    """
    assert codes(src) == []


def test_tpu501_def_line_ignore():
    src = _AFFINE_DECL + """
        def _worker(self):  # tpuserve: ignore[TPU501] protocol-serialized: loop awaits this call
            self._chunks.append(1)
        async def step(self):
            await asyncio.to_thread(self._worker)
    """
    assert codes(src) == []


def test_tpu501_context_propagates_through_calls():
    # the mutation sits two intra-module calls below the worker root
    src = _AFFINE_DECL + """
        def _inner(self):
            self._chunks.append(1)
        def _outer(self):
            self._inner()
        async def step(self):
            await asyncio.to_thread(self._outer)
    """
    assert codes(src) == ["TPU501"]


_HANDOFF_DECL = """
    import asyncio
    import jax.numpy as jnp
    class Engine:
"""


def test_tpu502_uncopied_host_buffer_in_worker():
    src = _HANDOFF_DECL + """
        def _dispatch(self):
            return jnp.asarray(self._next_token)
        async def step(self):
            await asyncio.to_thread(self._dispatch)
    """
    assert codes(src) == ["TPU502"]


def test_tpu502_copy_at_the_handoff_is_fine():
    src = _HANDOFF_DECL + """
        def _dispatch(self):
            return jnp.asarray(self._next_token.copy())
        async def step(self):
            await asyncio.to_thread(self._dispatch)
    """
    assert codes(src) == []


def test_tpu502_needs_cross_thread_structure():
    # a module with no worker roots has no handoff to race: local uploads
    # of attributes are the single-threaded norm elsewhere in the tree
    src = """
        import jax.numpy as jnp
        class Engine:
            async def step(self):
                return jnp.asarray(self._next_token)
    """
    assert codes(src) == []


def test_tpu502_locals_are_fine():
    src = _HANDOFF_DECL + """
        def _dispatch(self, prep):
            return jnp.asarray(prep["tokens"])
        async def step(self):
            await asyncio.to_thread(self._dispatch, {})
    """
    assert codes(src) == []


def test_tpu502_ignore_comment():
    src = _HANDOFF_DECL + """
        def _dispatch(self):
            return jnp.asarray(self._frozen_table)  # tpuserve: ignore[TPU502] written once at init
        async def step(self):
            await asyncio.to_thread(self._dispatch)
    """
    assert codes(src) == []


def test_tpu503_await_under_sync_lock():
    src = """
        import asyncio
        class Engine:
            async def step(self):
                with self._lock:
                    await asyncio.sleep(0)
    """
    assert codes(src) == ["TPU503"]


def test_tpu503_async_with_is_fine():
    src = """
        import asyncio
        class Engine:
            async def step(self):
                async with self._alock:
                    await asyncio.sleep(0)
    """
    assert codes(src) == []


def test_tpu503_nested_coroutine_does_not_inherit_lock():
    # a coroutine DEFINED under the with runs later, without the lock
    src = """
        import asyncio
        class Engine:
            def build(self):
                with self._lock:
                    async def later():
                        await asyncio.sleep(0)
                    return later
    """
    assert codes(src) == []


def test_tpu503_await_after_release_is_fine():
    src = """
        import asyncio
        class Engine:
            async def step(self):
                with self._lock:
                    self.n += 1
                await asyncio.sleep(0)
    """
    assert codes(src) == []


_HELPER_DECL = """
    import asyncio
    import threading
    class Pool:
        __guarded_by__ = {"_lock": ("_table",)}
        def _grow_locked(self, x):  # tpuserve: ignore[TPU301] lock held by caller
            self._table.append(x)
"""


def test_tpu504_helper_called_without_the_lock():
    src = _HELPER_DECL + """
        async def handler(self, x):
            self._grow_locked(x)
    """
    assert codes(src) == ["TPU504"]


def test_tpu504_helper_called_under_the_lock_is_fine():
    src = _HELPER_DECL + """
        async def handler(self, x):
            with self._lock:
                self._grow_locked(x)
    """
    assert codes(src) == []


def test_tpu504_helper_chain_inside_annotated_helper_is_fine():
    # a helper calling a sibling helper is itself a lock-held context
    src = _HELPER_DECL + """
        def _grow_two_locked(self, x):  # tpuserve: ignore[TPU301] lock held by caller
            self._grow_locked(x)
            self._grow_locked(x)
        async def handler(self, x):
            with self._lock:
                self._grow_two_locked(x)
    """
    assert codes(src) == []


def test_tpu504_ignore_with_reason():
    src = _HELPER_DECL + """
        async def handler(self, x):
            self._grow_locked(x)  # tpuserve: ignore[TPU504] single-threaded startup path
    """
    assert codes(src) == []


# -- registry / catalog consistency -------------------------------------------


def test_guarded_by_declarations_match_project_registry():
    from clearml_serving_tpu.llm.engine import _ClassedPendingQueue
    from clearml_serving_tpu.llm.kv_cache import (
        HostKVTier,
        PagedKVCache,
        PagePool,
    )
    from clearml_serving_tpu.llm.kv_transport import SharedSlabTransport
    from clearml_serving_tpu.llm.kv_wire import SocketSlabTransport
    from clearml_serving_tpu.llm.prefix_cache import RadixPrefixCache
    from clearml_serving_tpu.serving.process_replica import (
        ProcessEngineReplica,
        _SyncChannel,
    )
    from clearml_serving_tpu.serving.replica_router import ReplicaRouter

    for cls in (PagePool, PagedKVCache, RadixPrefixCache,
                _ClassedPendingQueue, HostKVTier, ReplicaRouter,
                SharedSlabTransport, SocketSlabTransport, _SyncChannel,
                ProcessEngineReplica):
        for lock, attrs in cls.__guarded_by__.items():
            for attr in attrs:
                entry = rules_locks.PROJECT_REGISTRY.get(attr)
                assert entry is not None and entry[0] == lock, (
                    "{}.{} declared guarded by {} but the analyzer's "
                    "PROJECT_REGISTRY disagrees".format(cls.__name__, attr, lock)
                )


def test_affine_declarations_match_affinity_registry():
    from clearml_serving_tpu.analyze import rules_threads
    from clearml_serving_tpu.llm.engine import LLMEngineCore
    from clearml_serving_tpu.serving.model_request_processor import (
        ModelRequestProcessor,
    )
    from clearml_serving_tpu.serving.process_replica import (
        ProcessEngineReplica,
    )
    from clearml_serving_tpu.serving.replica_router import ReplicaRouter

    for cls in (LLMEngineCore, ModelRequestProcessor, ReplicaRouter,
                ProcessEngineReplica):
        for thread, attrs in cls.__affine_to__.items():
            for attr in attrs:
                entry = rules_threads.AFFINITY_REGISTRY.get(attr)
                assert entry is not None and entry[0] == thread, (
                    "{}.{} declared {}-affine but the analyzer's "
                    "AFFINITY_REGISTRY disagrees".format(
                        cls.__name__, attr, thread
                    )
                )


def test_every_emitted_code_is_in_the_catalog():
    # fixture sources above exercise every rule; RULES must describe each
    # (TPU000 = unparseable file, emitted by the driver itself)
    for code in ("TPU000", "TPU101", "TPU102", "TPU103", "TPU104", "TPU201",
                 "TPU202", "TPU203", "TPU301", "TPU401", "TPU402", "TPU403",
                 "TPU501", "TPU502", "TPU503", "TPU504",
                 "TPU601", "TPU602", "TPU603", "TPU604"):
        assert code in RULES


def test_syntax_error_reports_tpu000():
    found = analyze_source("def broken(:\n    pass\n", "x.py")
    assert [f.code for f in found] == ["TPU000"]


def test_cross_module_pool_handle_rebind_needs_dispatch_lock():
    # PagedKVCache's k/v handles are in the project registry: a rebind from
    # another module (e.g. engine code) outside the dispatch lock is flagged
    src = """
        def rebind(cache, new_k):
            cache.k = new_k
    """
    assert codes(src) == ["TPU301"]
    src_locked = """
        def rebind(cache, new_k):
            with cache.dispatch_lock:
                cache.k = new_k
    """
    assert codes(src_locked) == []
    # the k/v entries are receiver-filtered: an unrelated class's `self.k`
    # is NOT dragged into the rule
    src_unrelated = """
        class Sampler:
            def set_k(self, k):
                self.k = k
    """
    assert codes(src_unrelated) == []


# -- the tier-1 gate ----------------------------------------------------------


def test_fused_matmul_module_is_covered_and_clean():
    """The w4a16 kernel module (ops/fused_matmul.py, PR 7) sits inside the
    analyzer's walk with zero findings — and the jit-boundary rules really
    apply to it: grafting a TPU201-style stale-trace closure into its
    source is flagged at the right file."""
    path = os.path.join(PKG_DIR, "ops", "fused_matmul.py")
    assert analyze_paths([path]) == []
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    bad = source + textwrap.dedent(
        """

        class _KernelHolder:
            def __init__(self):
                self.block_n = 512

                def go(v):
                    return v * self.block_n  # closure over self: stale trace

                self._go = jax.jit(go)
        """
    )
    found = [f.code for f in analyze_source(bad, path)]
    assert "TPU201" in found


def test_first_party_tree_has_zero_findings():
    """Acceptance: the committed tree is clean. Any new violation (or a
    deleted ignore annotation) fails this test with the rule and file:line."""
    findings = analyze_paths([PKG_DIR])
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


def test_deleting_an_ignore_annotation_fails_the_tree():
    """The committed annotations are load-bearing, not decorative: strip the
    lock-helper annotations from kv_cache.py and TPU301 findings appear."""
    path = os.path.join(PKG_DIR, "llm", "kv_cache.py")
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    stripped = source.replace("# tpuserve: ignore[TPU301] lock held by caller", "")
    assert stripped != source, "expected ignore annotations in kv_cache.py"
    found = [f.code for f in analyze_source(stripped, path)]
    assert "TPU301" in found


def test_mutation_dropped_buffer_copy_is_caught_statically():
    """Seeded defect (acceptance): stripping the PR-4-style snapshot copies
    from the engine's spec-path thread handoffs resurfaces as TPU502."""
    path = os.path.join(PKG_DIR, "llm", "engine.py")
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    mutated = source.replace(
        "jnp.asarray(self._tokbuf.copy())", "jnp.asarray(self._tokbuf)"
    )
    assert mutated != source, "expected spec-path snapshot copies in engine.py"
    found = [f.code for f in analyze_source(mutated, path)]
    assert "TPU502" in found
    # the committed tree (with the copies) is clean
    assert "TPU502" not in [f.code for f in analyze_source(source, path)]


def test_mutation_dropped_lock_is_caught_statically():
    """Seeded defect (acceptance): stripping the pool's lock acquisitions
    resurfaces as TPU301 — the static half of the dropped-lock net (the
    interleaving explorer's refcount_lock scenario is the dynamic half)."""
    path = os.path.join(PKG_DIR, "llm", "kv_cache.py")
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    mutated = source.replace("with self._lock:", "if True:")
    assert mutated != source
    found = [f.code for f in analyze_source(mutated, path)]
    assert "TPU301" in found


def test_mutation_offthread_affinity_annotation_is_load_bearing():
    """Deleting the serial-spec-path TPU501 annotation resurfaces the
    worker-thread mutation of loop-affine state it documents."""
    path = os.path.join(PKG_DIR, "llm", "engine.py")
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    marker = (
        "# tpuserve: ignore[TPU501] serial spec path: the loop is suspended "
        "awaiting this worker call and commits land at loop tops, so no "
        "loop-thread mutator runs concurrently"
    )
    mutated = source.replace(marker, "")
    assert mutated != source, "expected the _spec_commit_state annotation"
    found = [f.code for f in analyze_source(mutated, path)]
    assert "TPU501" in found


def test_cli_json_format(tmp_path):
    import json

    # clean file -> exit 0, EMPTY stdout (CI counts lines)
    good = tmp_path / "good.py"
    good.write_text("async def f():\n    return 1\n")
    proc = subprocess.run(
        [sys.executable, "-m", "clearml_serving_tpu.analyze",
         "--format", "json", str(good)],
        capture_output=True, text=True, cwd=PKG_ROOT,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert proc.stdout.strip() == ""
    # violations -> exit 1, one JSON object per line with the stable keys
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import time\nasync def f():\n    time.sleep(1)\n    time.sleep(2)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "clearml_serving_tpu.analyze",
         "--format", "json", str(bad)],
        capture_output=True, text=True, cwd=PKG_ROOT,
    )
    assert proc.returncode == 1
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 2
    for line in lines:
        obj = json.loads(line)
        assert obj["rule"] == "TPU101"
        assert obj["file"].endswith("bad.py")
        assert isinstance(obj["line"], int) and obj["line"] in (3, 4)
        assert "fix" in obj and "message" in obj and "col" in obj


def test_cli_exit_codes_and_output(tmp_path):
    # clean file -> 0
    good = tmp_path / "good.py"
    good.write_text("async def f():\n    return 1\n")
    proc = subprocess.run(
        [sys.executable, "-m", "clearml_serving_tpu.analyze", str(good)],
        capture_output=True, text=True, cwd=PKG_ROOT,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # seeded violation -> 1, with the rule code and file:line in the output
    bad = tmp_path / "bad.py"
    bad.write_text("import time\nasync def f():\n    time.sleep(1)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "clearml_serving_tpu.analyze", str(bad)],
        capture_output=True, text=True, cwd=PKG_ROOT,
    )
    assert proc.returncode == 1
    assert "TPU101" in proc.stdout
    assert "bad.py:3" in proc.stdout
