"""TPU6xx compile-surface discipline: per-rule fixtures, registry
consistency, source-mutation regressions, and the github CLI format
(docs/static_analysis.md; analyze/rules_compile.py).

Mirrors test_analyze.py's contract for the new rule family: every rule has
a positive, a negative, and an ignore-comment fixture; the project
registries (bucketizers, warmup coverage, ``__compile_keys__``) are pinned
to the definitions they mirror; and stripping the PR's bucketizer fixes
from kv_cache.py resurfaces TPU601 — the annotations and pads are
load-bearing, not decorative.
"""

import ast
import os
import subprocess
import sys
import textwrap

from clearml_serving_tpu.analyze import RULES, analyze_paths, analyze_source
from clearml_serving_tpu.analyze import rules_compile

PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_DIR = os.path.join(PKG_ROOT, "clearml_serving_tpu")

# in-package fixture path: TPU603 resolves the REAL llm/warmup.py registry
# relative to it; the out-of-tree path falls back to the analyzer's mirror
LLM_PATH = os.path.join(PKG_DIR, "llm", "fixture.py")
OUT_OF_TREE = "/nonexistent/fixture.py"


def codes(source, path=LLM_PATH):
    return [f.code for f in analyze_source(textwrap.dedent(source), path)]


# -- TPU601: unbucketed request-varying shape keys ----------------------------


def test_tpu601_raw_request_varying_upload():
    src = """
        import jax.numpy as jnp
        def f(self, request):
            ids = request.prompt_ids
            return jnp.asarray(ids, jnp.int32)
    """
    assert codes(src) == ["TPU601"]


def test_tpu601_parameter_name_is_a_taint_source():
    src = """
        import jax.numpy as jnp
        def demote(self, pages):
            return jnp.asarray(pages, jnp.int32)
    """
    assert codes(src) == ["TPU601"]


def test_tpu601_bucketizer_launders():
    src = """
        import jax.numpy as jnp
        from .shapes import pad_pages
        def demote(self, pages):
            return jnp.asarray(pad_pages(pages), jnp.int32)
    """
    assert codes(src) == []


def test_tpu601_taint_flows_through_host_buffers():
    # shape taint survives an intermediate np.zeros of a tainted shape ...
    bad = """
        import jax.numpy as jnp, numpy as np
        def f(self, ids):
            row = np.zeros((1, len(ids)), np.int32)
            return jnp.asarray(row)
    """
    assert codes(bad) == ["TPU601"]
    # ... and a bucketed shape cleans the SAME name
    good = """
        import jax.numpy as jnp, numpy as np
        def f(self, ids):
            bucket = self._bucket_for(len(ids))
            tokens = np.zeros((1, bucket), np.int32)
            return jnp.asarray(tokens)
    """
    assert codes(good) == []


def test_tpu601_floor_div_pad_idiom_is_clean():
    # the `-(-n // m) * m` page-multiple pad collapses the key space
    src = """
        import jax.numpy as jnp, numpy as np
        def f(self, ids):
            bucket = -(-len(ids) // 512) * 512
            tokens = np.zeros((1, bucket), np.int32)
            return jnp.asarray(tokens)
    """
    assert codes(src) == []


def test_tpu601_device_alloc_shaped_by_request():
    src = """
        import jax.numpy as jnp
        def f(self, ids):
            return jnp.zeros(len(ids))
    """
    assert codes(src) == ["TPU601"]


def test_tpu601_module_bucketizer_registration():
    src = """
        import jax.numpy as jnp
        __bucketizers__ = ("_my_pad",)
        def f(self, pages):
            return jnp.asarray(_my_pad(pages), jnp.int32)
    """
    assert codes(src) == []


def test_tpu601_ignore_comment():
    src = """
        import jax.numpy as jnp
        def f(self, pages):
            return jnp.asarray(pages, jnp.int32)  # tpuserve: ignore[TPU601] page-count-keyed, warmup-covered
    """
    assert codes(src) == []


def test_tpu601_plain_np_asarray_is_readback_not_upload():
    # np.asarray is the device->host readback idiom (TPU502's rationale);
    # only the jnp-family uploads mint device programs
    src = """
        import numpy as np
        def f(self, pages):
            return np.asarray(pages, np.int32)
    """
    assert codes(src) == []
    # the spelled-out host module is host too — only jax.numpy is device
    bare = """
        import numpy
        def f(self, pages):
            return numpy.asarray(pages)
    """
    assert codes(bare) == []
    spelled = """
        import jax
        def f(self, pages):
            return jax.numpy.asarray(pages)
    """
    assert codes(spelled) == ["TPU601"]


# -- TPU602: dtype/weak-type drift at jit boundaries --------------------------


def test_tpu602_float_literal_at_jit_call():
    src = """
        def f(self, x):
            return self._decode_chunk_jit(x, 0.5)
    """
    assert codes(src) == ["TPU602"]


def test_tpu602_typed_constant_is_fine():
    src = """
        import jax.numpy as jnp
        def f(self, x):
            return self._decode_chunk_jit(x, jnp.float32(0.5))
    """
    assert codes(src) == []


def test_tpu602_dtype_less_np_asarray():
    src = """
        import numpy as np
        def f(self, x):
            return self._decode_chunk_jit(np.asarray(x))
    """
    assert codes(src) == ["TPU602"]
    src_typed = """
        import numpy as np
        def f(self, x):
            return self._decode_chunk_jit(np.asarray(x, np.int32))
    """
    assert codes(src_typed) == []


def test_tpu602_ignore_comment():
    src = """
        def f(self, x):
            return self._decode_chunk_jit(x, 0.5)  # tpuserve: ignore[TPU602] reasoned
    """
    assert codes(src) == []


def test_tpu602_non_jit_calls_not_checked():
    src = """
        def f(self, x):
            return helper(x, 0.5)
    """
    assert codes(src) == []


# -- TPU603: __compile_keys__ closed world ------------------------------------


def test_tpu603_undeclared_jit_entry():
    src = """
        import jax
        class E:
            __compile_keys__ = {"serve": ()}
            __shardings__ = {"params": "llama_param_sharding"}
            def __init__(self):
                self._rogue_jit = jax.jit(lambda x: x)
    """
    assert codes(src, path=OUT_OF_TREE) == ["TPU603"]


def test_tpu603_serve_entry_missing_from_warmup_registry():
    src = """
        import jax
        class E:
            __compile_keys__ = {"serve": ("_never_warmed_jit",)}
            __shardings__ = {"params": "llama_param_sharding"}
            def __init__(self):
                self._never_warmed_jit = jax.jit(lambda x: x)
    """
    assert codes(src, path=OUT_OF_TREE) == ["TPU603"]
    # the same entry under a non-serve role is a deliberate classification
    lazy = src.replace('"serve"', '"lazy"')
    assert codes(lazy, path=OUT_OF_TREE) == []


def test_tpu603_covered_serve_entry_is_fine():
    src = """
        import jax
        class E:
            __compile_keys__ = {"serve": ("_decode_chunk_jit",)}
            __shardings__ = {"params": "llama_param_sharding"}
            def __init__(self):
                self._decode_chunk_jit = jax.jit(lambda x: x)
    """
    assert codes(src, path=OUT_OF_TREE) == []


def test_tpu603_jit_suffix_convention_counts_without_jit_call():
    # `self._sample_jit = sample_tokens` (a module-level jitted function
    # re-exported under the naming convention) is still a compile entry
    src = """
        class E:
            __compile_keys__ = {"serve": ()}
            __shardings__ = {"params": "llama_param_sharding"}
            def __init__(self):
                self._sneaky_jit = sample_tokens
    """
    assert codes(src, path=OUT_OF_TREE) == ["TPU603"]


def test_tpu603_reads_registry_from_real_warmup_py():
    # a file INSIDE the package resolves WARMUP_COVERED from llm/warmup.py
    # — an entry the real registry covers passes with no mirror involved
    src = """
        import jax
        class E:
            __compile_keys__ = {"serve": ("_gather_finish_jit",)}
            __shardings__ = {"params": "llama_param_sharding"}
            def __init__(self):
                self._gather_finish_jit = jax.jit(lambda x: x)
    """
    assert codes(src, path=LLM_PATH) == []


def test_tpu603_classes_without_declaration_are_not_checked():
    src = """
        import jax
        class Free:
            def __init__(self):
                self._whatever_jit = jax.jit(lambda x: x)
    """
    assert codes(src, path=OUT_OF_TREE) == []


# -- TPU604: request-varying static args --------------------------------------


def test_tpu604_tainted_static_argnum():
    src = """
        import jax
        g = jax.jit(fn, static_argnums=(1,))
        def f(self, request):
            n = len(request.prompt_ids)
            return g(0, n)
    """
    assert codes(src) == ["TPU604"]


def test_tpu604_bucketized_static_is_fine():
    src = """
        import jax
        g = jax.jit(fn, static_argnums=(1,))
        def f(self, request):
            n = self._bucket_for(len(request.prompt_ids))
            return g(0, n)
    """
    assert codes(src) == []


def test_tpu604_tainted_static_argname():
    src = """
        import jax
        g = jax.jit(fn, static_argnames=("n",))
        def f(self, request):
            return g(0, n=len(request.prompt_ids))
    """
    assert codes(src) == ["TPU604"]


def test_tpu604_ignore_comment():
    src = """
        import jax
        g = jax.jit(fn, static_argnums=(1,))
        def f(self, request):
            return g(0, len(request.prompt_ids))  # tpuserve: ignore[TPU604] reasoned
    """
    assert codes(src) == []


# -- registry consistency -----------------------------------------------------


def test_warmup_registry_mirror_matches_warmup_py():
    from clearml_serving_tpu.llm import warmup

    assert rules_compile.WARMUP_COVERED == warmup.WARMUP_COVERED, (
        "analyze/rules_compile.WARMUP_COVERED and llm/warmup.WARMUP_COVERED "
        "drifted — update both together"
    )


def test_compile_keys_serve_entries_are_warmup_covered():
    from clearml_serving_tpu.llm import warmup
    from clearml_serving_tpu.llm.engine import LLMEngineCore

    serve = set(LLMEngineCore.__compile_keys__["serve"])
    missing = serve - warmup.WARMUP_COVERED
    assert not missing, (
        "serve-path jit entries missing from the warmup shape registry: "
        "{}".format(sorted(missing))
    )


def test_compile_keys_declaration_matches_engine_source():
    """Closed world both ways: every jit attribute the engine source
    assigns is declared, and every declared name is actually assigned
    (a stale declaration would grandfather a removed entry's name)."""
    from clearml_serving_tpu.llm.engine import LLMEngineCore

    path = os.path.join(PKG_DIR, "llm", "engine.py")
    with open(path, "r", encoding="utf-8") as fh:
        tree = ast.parse(fh.read())
    cls = next(
        n for n in ast.walk(tree)
        if isinstance(n, ast.ClassDef) and n.name == "LLMEngineCore"
    )
    assigned = {attr for attr, _node in rules_compile._class_jit_attrs(cls)}
    declared = set()
    for names in LLMEngineCore.__compile_keys__.values():
        declared |= set(names)
    assert assigned == declared, (
        "engine.__compile_keys__ out of sync with the jit assignments: "
        "undeclared={} stale={}".format(
            sorted(assigned - declared), sorted(declared - assigned)
        )
    )


def test_bucketizer_registry_names_exist_in_tree():
    """Every project-level bucketizer name resolves to a real definition
    somewhere in the package — a typo'd registry entry would silently
    launder nothing."""
    defined = set()
    for dirpath, _dirs, files in os.walk(PKG_DIR):
        if "__pycache__" in dirpath:
            continue
        for name in files:
            if not name.endswith(".py"):
                continue
            with open(os.path.join(dirpath, name), "r",
                      encoding="utf-8") as fh:
                try:
                    tree = ast.parse(fh.read())
                except SyntaxError:
                    continue
            for node in ast.walk(tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    defined.add(node.name)
    missing = rules_compile.BUCKETIZERS - defined
    assert not missing, "bucketizers with no definition: {}".format(
        sorted(missing)
    )


def test_shapes_helpers_behave():
    from clearml_serving_tpu.llm.shapes import (
        pad_pages,
        pad_to_multiple,
        pow2_bucket,
    )

    assert [pow2_bucket(n) for n in (0, 1, 2, 3, 5, 8, 9)] == [
        1, 1, 2, 4, 8, 8, 16,
    ]
    assert pow2_bucket(3, lo=8) == 8
    assert pad_to_multiple(17, 16) == 32
    assert pad_to_multiple(16, 16) == 16
    assert pad_pages([4, 7, 9]) == [4, 7, 9, 0]
    assert pad_pages([5]) == [5]


def test_every_tpu6xx_code_is_in_the_catalog():
    for code in ("TPU601", "TPU602", "TPU603", "TPU604"):
        assert code in RULES


# -- satellite: the tier-path fixes are load-bearing --------------------------


def test_mutation_unbucketed_demote_is_caught_statically():
    """Stripping the demotion gather's pad_pages bucketizer resurfaces
    TPU601 — the regression test for this PR's tier-path fix."""
    path = os.path.join(PKG_DIR, "llm", "kv_cache.py")
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    stripped = source.replace(
        "idx = jnp.asarray(pad_pages(pages), jnp.int32)",
        "idx = jnp.asarray(pages, jnp.int32)",
    )
    assert stripped != source, "expected the demote pad_pages call"
    found = [f.code for f in analyze_source(stripped, path)]
    assert "TPU601" in found


def test_mutation_unbucketed_promote_is_caught_statically():
    path = os.path.join(PKG_DIR, "llm", "kv_cache.py")
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    stripped = source.replace(
        "page_ids = jnp.asarray(padded, jnp.int32)",
        "page_ids = jnp.asarray(pages, jnp.int32)",
    )
    assert stripped != source, "expected the promote padded upload"
    found = [f.code for f in analyze_source(stripped, path)]
    assert "TPU601" in found


def test_mutation_undeclared_engine_jit_entry_is_caught():
    """Grafting a new undeclared jit entry into the engine class is
    flagged: the compile surface is closed-world."""
    path = os.path.join(PKG_DIR, "llm", "engine.py")
    with open(path, "r", encoding="utf-8") as fh:
        source = fh.read()
    grafted = source.replace(
        "        self._insert_jit = jax.jit(_insert, donate_argnums=(0,))",
        "        self._insert_jit = jax.jit(_insert, donate_argnums=(0,))\n"
        "        self._grafted_jit = jax.jit(_insert)",
    )
    assert grafted != source
    found = [f.code for f in analyze_source(grafted, path)]
    assert "TPU603" in found


def test_tree_is_clean_for_tpu6xx():
    findings = [
        f for f in analyze_paths([PKG_DIR])
        if f.code.startswith("TPU6")
    ]
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


# -- CLI: --format github -----------------------------------------------------


def test_cli_github_format(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import jax.numpy as jnp
        def f(self, pages):
            return jnp.asarray(pages, jnp.int32)
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "clearml_serving_tpu.analyze",
         "--format", "github", str(bad)],
        capture_output=True, text=True, cwd=PKG_ROOT,
    )
    assert proc.returncode == 1
    lines = [l for l in proc.stdout.splitlines() if l]
    assert len(lines) == 1
    assert lines[0].startswith("::error file=")
    assert "title=TPU601" in lines[0]
    assert "line=4" in lines[0]

    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    proc = subprocess.run(
        [sys.executable, "-m", "clearml_serving_tpu.analyze",
         "--format", "github", str(clean)],
        capture_output=True, text=True, cwd=PKG_ROOT,
    )
    assert proc.returncode == 0
    assert proc.stdout.strip() == ""
