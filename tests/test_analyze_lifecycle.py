"""tpuserve-analyze TPU7xx (analyze/rules_lifecycle.py): per-rule fixtures
(positive / negative / ignore), the __acquires__/LIFECYCLE_REGISTRY
consistency gate, source-mutation gates proving the committed fixes are
load-bearing, and the CLI's family-select/--changed-only/--timings modes.

The tree-wide zero-findings acceptance gate lives in test_analyze.py (it
runs every family); here a family-selected pass pins that TPU7xx alone is
clean, so a future failure names the family immediately.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from clearml_serving_tpu.analyze import (
    RULES,
    analyze_paths,
    analyze_source,
    expand_select,
)
from clearml_serving_tpu.analyze import rules_lifecycle

PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_DIR = os.path.join(PKG_ROOT, "clearml_serving_tpu")
LLM_PATH = "clearml_serving_tpu/llm/fixture.py"


def codes(source, path=LLM_PATH, select=None):
    return [
        f.code
        for f in analyze_source(textwrap.dedent(source), path, select=select)
    ]


# -- TPU701: leaking exception paths -----------------------------------------


def test_tpu701_exception_path_leak():
    src = """
        def admit(pool, slot, tokens):
            pages = pool.allocate(slot, tokens)
            prepare_dispatch()
            pool.free(slot)
    """
    assert codes(src) == ["TPU701"]


def test_tpu701_normal_path_leak():
    src = """
        def admit(pool, slot, tokens):
            pages = pool.allocate(slot, tokens)
    """
    assert codes(src) == ["TPU701"]


def test_tpu701_catch_all_cleanup_is_fine():
    src = """
        def admit(pool, slot, tokens):
            pages = pool.allocate(slot, tokens)
            try:
                prepare_dispatch()
            except Exception:
                pool.free(slot)
                raise
            pool.free(slot)
    """
    assert codes(src) == []


def test_tpu701_typed_handler_still_leaks_other_exceptions():
    src = """
        def admit(pool, slot, tokens):
            pages = pool.allocate(slot, tokens)
            try:
                prepare_dispatch()
            except MemoryError:
                pool.free(slot)
                raise
            pool.free(slot)
    """
    assert codes(src) == ["TPU701"]


def test_tpu701_try_finally_is_fine():
    src = """
        def admit(pool, slot, tokens):
            pages = pool.allocate(slot, tokens)
            try:
                prepare_dispatch()
            finally:
                pool.free(slot)
    """
    assert codes(src) == []


def test_tpu701_none_check_early_return_is_fine():
    src = """
        def admit(cache, ids):
            hit = cache.lookup_pages(ids)
            if hit is None:
                return None
            use(hit)
    """
    # `use(hit)` is an ownership hand-off (fail-open); the None branch is
    # vacuous — neither path leaks
    assert codes(src) == []


def test_tpu701_ownership_transfers_discharge():
    # stash on an object / return / registered drop handler all transfer
    src = """
        def stash(cache, request, ids):
            hit = cache.lookup_pages(ids)
            request._prefix_hit = hit

        def forward(cache, ids):
            hit = cache.lookup_pages(ids)
            return hit

        def degrade(cache, ids):
            hit = cache.lookup_pages(ids)
            cache.uncount_hit(hit)
    """
    assert codes(src) == []


def test_tpu701_release_in_loop_over_collection_is_fine():
    src = """
        def sweep(pool, jobs, lengths0):
            extended = []
            for slot in jobs:
                pool.extend(slot, 4)
                extended.append(slot)
            try:
                dispatch()
            except Exception:
                for slot in extended:
                    pool.truncate(slot, 0)
                raise
            for slot in extended:
                pool.truncate(slot, 0)
    """
    assert codes(src) == []


def test_tpu701_pin_run_and_host_tier_pairs():
    src = """
        def preempt(cache, tier, ids, pages):
            handle = cache.pin_run(ids)
            commit()
            cache.unpin_run(handle)

        def demote(tier, pages):
            ids = tier.allocate(len(pages))
            copy_rows()
            tier.free(ids)
    """
    # commit()/copy_rows() can raise with the handle held
    assert codes(src) == ["TPU701", "TPU701"]


def test_tpu701_ignore_comment():
    src = """
        def transfer(pool, slot, tokens):
            pages = pool.allocate(slot, tokens)  # tpuserve: ignore[TPU701] pages ride the slot table
            publish()
    """
    assert codes(src) == []


def test_tpu701_static_false_protocols_are_ledger_only():
    # cross-function protocols (declared "static": False) never produce
    # TPU701: the runtime ownership ledger audits them instead
    src = """
        def store(cache, pool, pages):
            pool.ref_pages(pages)
            attach_nodes()
    """
    assert codes(src) == []


# -- TPU702: double release ---------------------------------------------------


def test_tpu702_double_free():
    src = """
        def teardown(pool, slot):
            pages = pool.allocate(slot, 8)
            pool.free(slot)
            pool.free(slot)
    """
    assert codes(src) == ["TPU702"]


def test_tpu702_single_release_per_path_is_fine():
    src = """
        def teardown(pool, slot, ok):
            pages = pool.allocate(slot, 8)
            if ok:
                pool.free(slot)
            else:
                pool.truncate(slot, 0)
    """
    assert codes(src) == []


def test_tpu702_loop_release_not_flagged():
    # the SAME release statement re-visited by a loop back edge is not a
    # double free (each iteration pairs with its own acquire)
    src = """
        def per_job(pool, jobs):
            for slot in jobs:
                pages = pool.allocate(slot, 8)
                emit()
                pool.free(slot)
    """
    assert "TPU702" not in codes(src)


def test_tpu702_ignore_comment():
    src = """
        def teardown(pool, slot):
            pages = pool.allocate(slot, 8)
            pool.free(slot)
            pool.free(slot)  # tpuserve: ignore[TPU702] idempotent by construction
    """
    assert codes(src) == []


# -- TPU703: publish before the fence ----------------------------------------


def test_tpu703_publish_before_fence():
    src = """
        def promote(pool, backend, node, n):
            fresh = pool.allocate_cache_pages(n)
            node.pages = list(fresh)
            backend.import_pages(hk, hv, fresh)
    """
    assert "TPU703" in codes(src)


def test_tpu703_fenced_publish_is_fine():
    src = """
        def promote(pool, backend, node, n):
            fresh = pool.allocate_cache_pages(n)
            try:
                backend.import_pages(hk, hv, fresh)
            except BaseException:
                pool.unref_pages(fresh)
                raise
            node.pages = list(fresh)
    """
    assert codes(src) == []


def test_tpu703_tracks_derived_names():
    # the publish uses a name DERIVED from the mint (the store_shipped
    # shape: pages = list(fresh[i:j]))
    src = """
        def promote(pool, backend, node, n):
            fresh = pool.allocate_cache_pages(n)
            pages = list(fresh)
            node.pages = pages
            backend.import_pages(hk, hv, fresh)
    """
    assert "TPU703" in codes(src)


def test_tpu703_ignore_comment():
    src = """
        def promote(pool, backend, node, n):
            fresh = pool.allocate_cache_pages(n)
            node.pages = list(fresh)  # tpuserve: ignore[TPU703] fixture
            backend.import_pages(hk, hv, fresh)
    """
    assert "TPU703" not in codes(src)


# -- TPU704: consume-once transport ------------------------------------------


def test_tpu704_reuse_after_attach():
    src = """
        def receive(transport, cache, key, ids, backend):
            shipment = transport.recv(key)
            if shipment is None:
                return 0
            cache.store_shipped(ids, 0, shipment, backend)
            return shipment.hk
    """
    assert codes(src) == ["TPU704"]


def test_tpu704_double_recv_same_key():
    src = """
        def receive(transport, key):
            shipment = transport.recv(key)
            again = transport.recv(key)
            return again
    """
    assert codes(src) == ["TPU704"]


def test_tpu704_clean_receive_is_fine():
    src = """
        def receive(transport, cache, key, ids, backend):
            shipment = transport.recv(key)
            if shipment is None:
                return 0
            cache.store_shipped(ids, 0, shipment, backend)
            return 1
    """
    assert codes(src) == []


def test_tpu704_retry_loop_is_fine():
    # the explorer's bounded-retry receiver: the rebinding recv in a loop
    # is one logical pop, not a double consume
    src = """
        def receive(transport, cache, key, ids, backend):
            got = None
            for _ in range(6):
                got = transport.recv(key)
                if got is not None:
                    break
            if got is not None:
                cache.store_shipped(ids, 0, got, backend)
    """
    assert codes(src) == []


def test_tpu704_receiver_filter():
    # an unrelated .recv() (sockets, queues) never matches
    src = """
        def pump(sock, cache, ids, backend):
            data = sock.recv(4096)
            cache.store_shipped(ids, 0, data, backend)
            return data
    """
    assert codes(src) == []


def test_tpu704_ignore_comment():
    src = """
        def receive(transport, cache, key, ids, backend):
            shipment = transport.recv(key)
            cache.store_shipped(ids, 0, shipment, backend)
            return shipment.hk  # tpuserve: ignore[TPU704] fixture
    """
    assert codes(src) == []


# -- declarations <-> registry consistency ------------------------------------


def test_acquires_declarations_match_lifecycle_registry():
    """Every __acquires__ class declaration must appear in the analyzer's
    LIFECYCLE_REGISTRY (resource + releases + static flag agree): the
    declaration next to the code and the cross-module registry can never
    drift apart."""
    from clearml_serving_tpu.llm.engine import LLMEngineCore
    from clearml_serving_tpu.llm.kv_cache import HostKVTier, PagePool
    from clearml_serving_tpu.llm.kv_transport import SharedSlabTransport
    from clearml_serving_tpu.llm.kv_wire import SocketSlabTransport
    from clearml_serving_tpu.llm.prefix_cache import RadixPrefixCache
    from clearml_serving_tpu.serving.process_replica import (
        ProcessEngineReplica,
    )

    for cls in (PagePool, HostKVTier, RadixPrefixCache, SharedSlabTransport,
                SocketSlabTransport, ProcessEngineReplica, LLMEngineCore):
        for method, decl in cls.__acquires__.items():
            entries = rules_lifecycle.LIFECYCLE_REGISTRY.get(method)
            assert entries, (
                "{}.{} declared in __acquires__ but missing from "
                "LIFECYCLE_REGISTRY".format(cls.__name__, method)
            )
            match = [
                e for e in entries if e["resource"] == decl["resource"]
            ]
            assert match, (
                "{}.{}: resource {!r} not in the registry's entries "
                "{}".format(cls.__name__, method, decl["resource"], entries)
            )
            entry = match[0]
            assert set(decl["releases"]) <= set(entry["releases"]), (
                "{}.{}: declared releases {} not all in registry "
                "{}".format(cls.__name__, method, decl["releases"],
                            entry["releases"])
            )
            assert bool(decl.get("static", True)) == bool(
                entry.get("static", True)
            ), "{}.{}: static flag disagrees".format(cls.__name__, method)


def test_registry_resources_are_ledger_resources():
    """Every registry resource the static pass names must be a resource
    the runtime ledger tracks — the two halves audit ONE protocol set."""
    from clearml_serving_tpu.llm import lifecycle_ledger

    for entries in rules_lifecycle.LIFECYCLE_REGISTRY.values():
        for entry in entries:
            assert entry["resource"] in lifecycle_ledger.RESOURCES, (
                "registry resource {!r} unknown to the ledger".format(
                    entry["resource"]
                )
            )


def test_file_declarations_parse_from_source():
    """__acquires__ declarations parse with stdlib ast (no import of the
    declaring module) — the analyzer must work on detached fixtures."""
    import ast

    path = os.path.join(PKG_DIR, "llm", "kv_cache.py")
    with open(path, encoding="utf-8") as fh:
        tree = ast.parse(fh.read())
    decls = rules_lifecycle.file_declarations(tree)
    assert "allocate" in decls and "pin_pages" in decls


def test_every_tpu7_code_is_in_the_catalog():
    for code in ("TPU701", "TPU702", "TPU703", "TPU704"):
        assert code in RULES
    assert len(RULES) == 28, sorted(RULES)


# -- tree gate (family-selected) ----------------------------------------------


def test_tree_is_clean_under_tpu7xx():
    findings = analyze_paths([PKG_DIR], select=["TPU7xx"])
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


# -- source-mutation gates: the committed fixes are load-bearing --------------


def _mutate(path, old, new):
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    mutated = source.replace(old, new)
    assert mutated != source, "mutation target not found in {}".format(path)
    return source, mutated


def test_mutation_store_shipped_unref_guard_is_load_bearing():
    """Stripping the unref-on-failure guard from store_shipped's mint
    resurfaces the exception-path leak as TPU701 (the fix this PR made:
    a raise out of the row gather used to leak the fresh pages)."""
    path = os.path.join(PKG_DIR, "llm", "prefix_cache.py")
    source, mutated = _mutate(
        path,
        "            except BaseException:\n"
        "                self._pool.unref_pages(fresh)\n"
        "                raise",
        "            except BaseException:\n"
        "                raise",
    )
    assert "TPU701" in [f.code for f in analyze_source(mutated, path)]
    assert "TPU701" not in [f.code for f in analyze_source(source, path)]


def test_mutation_spec_rollback_is_load_bearing():
    """Stripping the speculative over-allocation rollback from the paged
    spec dispatch resurfaces TPU701 (the fix this PR made: a dispatch
    failure stranded the slack pages on surviving slots)."""
    path = os.path.join(PKG_DIR, "llm", "engine.py")
    source, mutated = _mutate(
        path,
        "            for slot in extended:\n"
        "                pool.truncate(slot, int(lengths0[slot]))\n"
        "            raise",
        "            raise",
    )
    assert "TPU701" in [f.code for f in analyze_source(mutated, path)]
    assert "TPU701" not in [f.code for f in analyze_source(source, path)]


def test_mutation_fence_call_is_load_bearing():
    """Renaming store_shipped's import_pages fence call resurfaces TPU703:
    fresh page ids would publish before any upload was enqueued."""
    path = os.path.join(PKG_DIR, "llm", "prefix_cache.py")
    source, mutated = _mutate(
        path, "backend.import_pages(", "backend.import_pages_deferred("
    )
    assert "TPU703" in [f.code for f in analyze_source(mutated, path)]
    assert "TPU703" not in [f.code for f in analyze_source(source, path)]


def test_mutation_deleting_transfer_annotation_fails_the_tree():
    """The TPU701 ownership-transfer annotations are load-bearing, not
    decorative: stripping the lookup_pages pin-transfer annotation
    resurfaces the finding."""
    path = os.path.join(PKG_DIR, "llm", "prefix_cache.py")
    source, mutated = _mutate(
        path, "# tpuserve: ignore[TPU701] pin rides the returned hit", ""
    )
    assert "TPU701" in [f.code for f in analyze_source(mutated, path)]


# -- select expansion + CLI ---------------------------------------------------


def test_expand_select_families_and_codes():
    assert expand_select(["TPU7xx"]) == {
        "TPU701", "TPU702", "TPU703", "TPU704",
    }
    assert expand_select(["TPU3"]) == {"TPU301"}
    assert expand_select(["tpu301"]) == {"TPU301"}
    assert expand_select(["TPU301", "TPU7XX"]) == {
        "TPU301", "TPU701", "TPU702", "TPU703", "TPU704",
    }
    # unknown exact codes pass through (forward compatibility)
    assert "TPU999" in expand_select(["TPU999"])


def test_select_family_filters_findings():
    src = """
        import time
        def admit(pool, slot, tokens):
            pages = pool.allocate(slot, tokens)
            time.sleep(1)
    """
    # full run: TPU701 only (sleep is fine in a sync def)
    assert codes(src) == ["TPU701"]
    assert codes(src, select=["TPU7xx"]) == ["TPU701"]
    assert codes(src, select=["TPU1xx"]) == []


def _run_cli(args, cwd=None):
    # the analyzer package must be importable from ANY cwd (the
    # --changed-only test runs inside a scratch git repo)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = PKG_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "clearml_serving_tpu.analyze"] + args,
        capture_output=True, text=True, env=env,
        cwd=cwd or PKG_ROOT,
    )


def test_cli_select_family_and_timings():
    proc = _run_cli(
        ["--select", "TPU7xx", "--timings", "clearml_serving_tpu/analyze"]
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout
    assert "rules_lifecycle" in proc.stdout  # per-family timing table


def test_cli_changed_only(tmp_path):
    """--changed-only reports only findings on diff-touched lines, with
    json format and exit codes unchanged."""
    repo = tmp_path / "repo"
    repo.mkdir()
    subprocess.run(["git", "init", "-q"], cwd=repo, check=True)
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "-q", "--allow-empty", "-m", "seed"],
        cwd=repo, check=True,
    )
    clean = textwrap.dedent("""
        def admit(pool, slot, tokens):
            pages = pool.allocate(slot, tokens)
            pool.free(slot)
    """)
    target = repo / "mod.py"
    target.write_text(clean)
    subprocess.run(["git", "add", "mod.py"], cwd=repo, check=True)
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t",
         "commit", "-q", "-m", "clean"],
        cwd=repo, check=True,
    )
    # introduce a leak on a NEW line plus an untouched pre-existing one
    leaky = textwrap.dedent("""
        def admit(pool, slot, tokens):
            pages = pool.allocate(slot, tokens)
            prepare_dispatch()
            pool.free(slot)
    """)
    target.write_text(leaky)
    # full run flags the acquire line (line 3, unchanged text but the
    # finding anchors there); changed-only keeps it only if the diff
    # touched it — the diff touched line 4 (the inserted call), so the
    # acquire-line finding is filtered out
    proc = _run_cli(["--format", "json", str(target)], cwd=repo)
    assert proc.returncode == 1
    rows = [json.loads(line) for line in proc.stdout.splitlines()]
    assert any(r["rule"] == "TPU701" for r in rows)
    proc = _run_cli(
        ["--format", "json", "--changed-only", str(target)], cwd=repo
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert proc.stdout.strip() == ""
    # a change ON the acquire line itself survives the filter
    target.write_text(leaky.replace(
        "pages = pool.allocate(slot, tokens)",
        "pages = pool.allocate(slot, tokens)  # touched",
    ))
    proc = _run_cli(
        ["--format", "json", "--changed-only", str(target)], cwd=repo
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    rows = [json.loads(line) for line in proc.stdout.splitlines()]
    assert [r["rule"] for r in rows] == ["TPU701"]
    # ...and from a SUBDIRECTORY with a relative path: the pathspec must
    # resolve against the caller's cwd, not the repo root (a silent empty
    # diff would filter real findings and report the run clean)
    sub = repo / "sub"
    sub.mkdir()
    proc = _run_cli(
        ["--format", "json", "--changed-only", os.path.join("..", "mod.py")],
        cwd=sub,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    rows = [json.loads(line) for line in proc.stdout.splitlines()]
    assert [r["rule"] for r in rows] == ["TPU701"]
