"""tpuserve-analyze TPU8xx (analyze/rules_sharding.py): per-rule fixtures
(positive / negative / ignore), the registry round-trip gates pinning the
``__mesh_axes__`` / ``__sharding_builders__`` / ``__shardings__``
declarations to the code both ways, source-mutation gates proving the
committed annotations are load-bearing, and the CLI's ``--format sarif``
mode (the code-scanning upload artifact).

The tree-wide zero-findings acceptance gate lives in test_analyze.py (it
runs every family); here a family-selected pass pins that TPU8xx alone is
clean, so a future failure names the family immediately.
"""

import json
import os
import subprocess
import sys
import textwrap

from clearml_serving_tpu.analyze import (
    RULES,
    analyze_paths,
    analyze_source,
    expand_select,
)
from clearml_serving_tpu.analyze import rules_sharding

PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_DIR = os.path.join(PKG_ROOT, "clearml_serving_tpu")
# a detached fixture path: _find_up never reaches parallel/mesh.py from
# here, so the in-module fallback registries apply (the round-trip tests
# below pin those fallbacks to the real files)
DETACHED = os.path.join(os.sep, "nonexistent", "llm", "fixture.py")


def codes(source, path=DETACHED, select=None):
    return [
        f.code
        for f in analyze_source(textwrap.dedent(source), path, select=select)
    ]


# -- TPU801: mesh-axis closed world -------------------------------------------


def test_tpu801_unknown_axis_in_partition_spec():
    src = """
        from jax.sharding import PartitionSpec as P

        def spec():
            return P("dp", "tensor")
    """
    assert codes(src) == ["TPU801"]


def test_tpu801_declared_axes_are_fine():
    src = """
        from jax.sharding import PartitionSpec as P

        def spec():
            return P(("dp", "sp"), None, "tp")
    """
    assert codes(src) == []


def test_tpu801_collective_axis_literal():
    src = """
        from jax import lax

        def reduce(x):
            return lax.psum(x, "tq")
    """
    assert codes(src) == ["TPU801"]


def test_tpu801_axis_name_default():
    src = """
        def ring(q, k, v, axis_name="sq"):
            return q
    """
    assert codes(src) == ["TPU801"]


def test_tpu801_spec_forwarding_helper_is_checked():
    # the ns/col pattern from parallel/sharding.py: a local helper that
    # forwards *axes into P(...) is checked like a direct P(...) call
    src = """
        from jax.sharding import PartitionSpec as P

        def ns(mesh, *axes):
            return P(*axes)

        def spec(mesh):
            return ns(mesh, "dp", "tensor_parallel")
    """
    assert codes(src) == ["TPU801"]


def test_tpu801_non_axis_strings_elsewhere_are_fine():
    src = """
        def log(msg):
            print("prefill", msg, sep="|")
    """
    assert codes(src) == []


def test_tpu801_ignore_comment():
    src = """
        from jax import lax

        def reduce(x):
            return lax.psum(x, "model")  # tpuserve: ignore[TPU801] external-library axis vocabulary
    """
    assert codes(src) == []


# -- TPU802: sharding declarations for serve-path jit entries ----------------


def test_tpu802_serve_class_without_shardings():
    src = """
        class Engine:
            __compile_keys__ = {"serve": ("prefill", "decode")}
    """
    assert codes(src) == ["TPU802"]


def test_tpu802_serve_class_with_shardings_is_fine():
    src = """
        class Engine:
            __compile_keys__ = {"serve": ("prefill", "decode")}
            __shardings__ = {
                "params": "parallel.sharding.llama_param_sharding",
                "kv_cache": "parallel.sharding.llama_cache_sharding",
            }
    """
    assert codes(src) == []


def test_tpu802_unregistered_builder_name():
    src = """
        class Engine:
            __compile_keys__ = {"serve": ("prefill",)}
            __shardings__ = {
                "params": "parallel.sharding.mystery_sharding",
            }
    """
    assert codes(src) == ["TPU802"]


def test_tpu802_non_serve_class_needs_no_shardings():
    src = """
        class Offline:
            __compile_keys__ = {"warmup": ("compile_all",)}
    """
    assert codes(src) == []


def test_tpu802_registry_module_declares_undefined_builder():
    src = """
        __sharding_builders__ = ("real_builder", "ghost_builder")

        def real_builder(mesh):
            return None
    """
    assert codes(src) == ["TPU802"]


# -- TPU803: multihost-unsafe host access ------------------------------------


def test_tpu803_host_read_of_sharded_global():
    src = """
        import numpy as np

        def publish(mesh, params):
            sharded = shard_params(mesh, params)
            return np.asarray(sharded)
    """
    assert codes(src) == ["TPU803"]


def test_tpu803_tolist_and_int_sinks():
    src = """
        def peek(mesh, tokens, spec):
            g = device_put(tokens, spec)
            return g.tolist(), int(g)
    """
    assert codes(src) == ["TPU803", "TPU803"]


def test_tpu803_addressable_shards_readback_is_fine():
    src = """
        import numpy as np

        def local_view(mesh, params):
            sharded = shard_params(mesh, params)
            return np.asarray(sharded.addressable_shards[0].data)
    """
    assert codes(src) == []


def test_tpu803_local_device_put_is_fine():
    # device_put without a sharding argument is a local placement, not a
    # sharded-global taint source
    src = """
        import numpy as np

        def place(tokens):
            local = device_put(tokens)
            return np.asarray(local)
    """
    assert codes(src) == []


def test_tpu803_ignore_comment():
    src = """
        import numpy as np

        def replicated_read(mesh, params):
            state = broadcast_one_to_all(params)
            return np.asarray(state)  # tpuserve: ignore[TPU803] broadcast result is replicated
    """
    assert codes(src) == []


# -- TPU804: silent replication fallback --------------------------------------

_BUILDER_MODULE = """
    __sharding_builders__ = ("param_sharding",)

    def param_sharding(mesh, name, shape):
        if shape[-1] % mesh.shape["tp"] == 0:
            return ("tp",)
        {fallback}
"""


def test_tpu804_silent_replication_fallback():
    src = _BUILDER_MODULE.format(fallback="return None")
    assert codes(src) == ["TPU804"]


def test_tpu804_annotated_fallback_is_fine():
    src = _BUILDER_MODULE.format(
        fallback="return None  "
        "# tpuserve: ignore[TPU804] misaligned projections replicate"
    )
    assert codes(src) == []


def test_tpu804_only_applies_to_builder_registry_modules():
    # the same shape outside a __sharding_builders__ module is not a
    # sharding builder and must not flag
    src = """
        def pick(mesh, shape):
            if shape[-1] % 2 == 0:
                return ("tp",)
            return None
    """
    assert codes(src) == []


# -- registry round-trips: declarations match the code, both ways -------------


def test_mesh_axes_round_trip():
    """rules_sharding.MESH_AXES (the detached-fixture fallback), the
    parsed-from-source ``__mesh_axes__``, and the runtime mesh module all
    agree — registry drift fails here, not at trace time on hardware."""
    from clearml_serving_tpu.parallel import mesh

    assert frozenset(mesh.__mesh_axes__) == rules_sharding.MESH_AXES
    assert frozenset(mesh.AXES) == rules_sharding.MESH_AXES
    parsed = rules_sharding._mesh_axes(
        os.path.join(PKG_DIR, "llm", "engine.py")
    )
    assert parsed == rules_sharding.MESH_AXES


def test_sharding_builders_round_trip():
    """__sharding_builders__ <-> SHARDING_REGISTRY <-> actual function
    definitions in parallel/sharding.py, in both directions."""
    from clearml_serving_tpu.parallel import sharding

    declared = tuple(sharding.__sharding_builders__)
    assert declared == rules_sharding.SHARDING_REGISTRY
    parsed = rules_sharding._sharding_builders(
        os.path.join(PKG_DIR, "llm", "engine.py")
    )
    assert parsed == declared
    for name in declared:
        assert callable(getattr(sharding, name)), (
            "registry declares {!r} but parallel/sharding.py does not "
            "define it".format(name)
        )


def test_engine_shardings_resolve_to_registered_builders():
    """The engine's __shardings__ annotation names real registered
    builders (the runtime mirror of the TPU802 static check)."""
    from clearml_serving_tpu.llm.engine import LLMEngineCore
    from clearml_serving_tpu.parallel import sharding

    shardings = LLMEngineCore.__shardings__
    assert "params" in shardings and "kv_cache" in shardings
    for family, dotted in shardings.items():
        builder = dotted.rsplit(".", 1)[-1]
        assert builder in sharding.__sharding_builders__, (
            "__shardings__[{!r}] names unregistered builder {!r}".format(
                family, builder
            )
        )


def test_drift_fault_point_registered_everywhere():
    """The seeded-defect seam for the sharding sentry exists in both the
    runtime fault registry and the analyzer's TPU403 fallback mirror."""
    from clearml_serving_tpu.analyze import rules_errors
    from clearml_serving_tpu.llm import faults

    assert "engine.shard.drift" in faults.KNOWN_POINTS
    assert "engine.shard.drift" in rules_errors.FALLBACK_POINTS


def test_every_tpu8_code_is_in_the_catalog():
    for code in ("TPU801", "TPU802", "TPU803", "TPU804"):
        assert code in RULES


def test_expand_select_tpu8xx():
    assert expand_select(["TPU8xx"]) == {
        "TPU801", "TPU802", "TPU803", "TPU804",
    }


# -- tree gate (family-selected) ----------------------------------------------


def test_tree_is_clean_under_tpu8xx():
    findings = analyze_paths([PKG_DIR], select=["TPU8xx"])
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


# -- source-mutation gates: the committed annotations are load-bearing --------


def _mutate(path, old, new):
    with open(path, encoding="utf-8") as fh:
        source = fh.read()
    mutated = source.replace(old, new)
    assert mutated != source, "mutation target not found in {}".format(path)
    return source, mutated


def test_mutation_ring_attention_axis_default_is_checked():
    """Typo'ing ring_attention's axis_name default ("sp" -> "sq") fails
    TPU801 at lint time instead of at trace time on hardware."""
    path = os.path.join(PKG_DIR, "parallel", "ring_attention.py")
    source, mutated = _mutate(
        path, 'axis_name: str = "sp"', 'axis_name: str = "sq"'
    )
    assert "TPU801" in [f.code for f in analyze_source(mutated, path)]
    assert "TPU801" not in [f.code for f in analyze_source(source, path)]


def test_mutation_deleting_replication_annotation_fails_the_tree():
    """The head_tp replication-fallback annotation is load-bearing, not
    decorative: stripping it resurfaces TPU804."""
    path = os.path.join(PKG_DIR, "parallel", "sharding.py")
    source, mutated = _mutate(
        path,
        "# tpuserve: ignore[TPU804] a tp boundary inside a head",
        "# a tp boundary inside a head",
    )
    assert "TPU804" in [f.code for f in analyze_source(mutated, path)]
    assert "TPU804" not in [f.code for f in analyze_source(source, path)]


def test_mutation_deleting_broadcast_annotation_fails_the_tree():
    """multihost.py's recv() host reads are safe only because the
    broadcast result is replicated — stripping the annotation resurfaces
    TPU803."""
    path = os.path.join(PKG_DIR, "parallel", "multihost.py")
    source, mutated = _mutate(
        path,
        "# tpuserve: ignore[TPU803] header is replicated",
        "# header is replicated",
    )
    assert "TPU803" in [f.code for f in analyze_source(mutated, path)]
    assert "TPU803" not in [f.code for f in analyze_source(source, path)]


def test_mutation_deleting_engine_shardings_fails_the_tree():
    """Dropping the engine's __shardings__ registry resurfaces TPU802:
    the serve-path jit entries would have no declared operand layouts."""
    path = os.path.join(PKG_DIR, "llm", "engine.py")
    source, mutated = _mutate(path, "__shardings__", "__shardings_off__")
    assert "TPU802" in [f.code for f in analyze_source(mutated, path)]
    assert "TPU802" not in [f.code for f in analyze_source(source, path)]


# -- CLI: --select TPU8xx and --format sarif ----------------------------------


def _run_cli(args, cwd=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = PKG_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "clearml_serving_tpu.analyze"] + args,
        capture_output=True, text=True, env=env,
        cwd=cwd or PKG_ROOT,
    )


def test_cli_select_tpu8xx_clean_with_timings():
    proc = _run_cli(
        ["--select", "TPU8xx", "--timings", "clearml_serving_tpu/parallel"]
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout
    assert "rules_sharding" in proc.stdout  # per-family timing table


def test_cli_sarif_output(tmp_path):
    """--format sarif emits a valid SARIF 2.1.0 document: the full rule
    catalog in tool.driver.rules, one result per finding with a physical
    location, exit code 1 on findings / 0 clean."""
    dirty = tmp_path / "mod.py"
    dirty.write_text(textwrap.dedent("""
        from jax.sharding import PartitionSpec as P

        def spec():
            return P("tensor_parallel")
    """))
    proc = _run_cli(["--format", "sarif", str(dirty)], cwd=str(tmp_path))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "tpuserve-analyze"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert set(RULES) == rule_ids
    results = run["results"]
    assert any(r["ruleId"] == "TPU801" for r in results)
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("mod.py")
    assert loc["region"]["startLine"] >= 1

    clean = tmp_path / "ok.py"
    clean.write_text("x = 1\n")
    proc = _run_cli(["--format", "sarif", str(clean)], cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["runs"][0]["results"] == []
