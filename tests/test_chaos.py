"""Fault-injection chaos suite (llm/faults.py seam; docs/robustness.md).

Proves the request-lifecycle hardening contracts end to end on CPU:

- a poisoned decode step fails ONLY the affected request; concurrently
  active requests complete and the engine keeps serving without a restart;
- the watchdog detects a stuck decode loop, fails the stalled batch with a
  structured error, flips not-ready, and recovers;
- admission sheds (queue bound / KV-pool saturation) raise structured 429s;
- queue-wait / TTFT / total deadlines fail requests with structured 408s;
- the gRPC client retries transient upstream codes with backoff and maps
  exhaustion to 503/504 instead of raw tracebacks.

All tests are fast and deterministic (faults fire on exact match/points, no
sleeps racing compiles beyond an explicit warmup) — they run inside tier-1
(`scripts/tier1.sh`); select just this suite with `pytest -m chaos`.
"""

import asyncio
import time

import jax
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.errors import (
    DeadlineExceededError,
    EngineOverloadedError,
    EngineStepError,
    EngineStuckError,
    EngineUnavailableError,
    UpstreamTimeoutError,
    UpstreamUnavailableError,
)
from clearml_serving_tpu.llm import faults
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
from clearml_serving_tpu.llm.kv_sanitizer import KVSanitizerError

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def parts():
    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, params


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(autouse=True)
def armed_sanitizer(monkeypatch):
    """Every engine this suite builds runs with the KV sanitizer armed:
    recovery paths must not merely produce the right tokens — page
    accounting must balance after every step and at drain
    (docs/static_analysis.md, invariant list)."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")


@pytest.fixture(autouse=True)
def armed_compile_sentry(monkeypatch):
    """The compile sentry rides along non-strict (like the KV sanitizer):
    chaos engines exercise recovery paths with the compile hook live, so
    the seam itself is proven inert under faults. No fence is ever set
    here, so every compile counts as warmup and nothing can raise."""
    monkeypatch.setenv("TPUSERVE_COMPILE_SENTRY", "1")
    yield
    from clearml_serving_tpu.llm import compile_sentry

    if compile_sentry._sentry is not None:
        compile_sentry._sentry.reset(strict=False)


@pytest.fixture(autouse=True)
def armed_ledger(monkeypatch):
    """The ownership ledger rides along in count mode (docs/
    static_analysis.md TPU7xx): every chaos engine records acquire/release
    pairing through its recovery paths, proving the bookkeeping itself is
    inert under faults. Count mode, not strict — several tests here leak
    DELIBERATELY (that is what they test), and their own assertions own
    the failure; the strict end-to-end case lives in
    tests/test_lifecycle_ledger.py."""
    monkeypatch.setenv("TPUSERVE_LEDGER", "1")
    from clearml_serving_tpu.llm import lifecycle_ledger

    lifecycle_ledger.arm(strict=False).reset(strict=False)
    yield
    lifecycle_ledger.get().reset(strict=False)
    lifecycle_ledger.disarm()


@pytest.fixture(autouse=True)
def armed_shard_sentry(monkeypatch):
    """The sharding sentry rides along in count mode (docs/
    static_analysis.md TPU8xx): every chaos engine audits its live arrays
    against the declared builder specs through the recovery paths, proving
    failure handling never silently host-materializes or reshards the
    chained state. Count mode, not strict — fault recovery is allowed to
    fail requests, not to drift layouts; each test's teardown asserts the
    audit stayed clean."""
    monkeypatch.setenv("TPUSERVE_SHARD_SENTRY", "1")
    from clearml_serving_tpu.llm import sharding_sentry

    sharding_sentry.arm(strict=False).reset(strict=False)
    yield
    stats = sharding_sentry.get().stats()
    sharding_sentry.get().reset(strict=False)
    sharding_sentry.disarm()
    assert stats["implicit_transfers"] == 0, stats["events"][:5]
    assert stats["unplanned_reshards"] == 0, stats["events"][:5]


def _make_engine(bundle, params, **kwargs):
    kwargs.setdefault("max_batch", 4)
    kwargs.setdefault("max_seq_len", 128)
    kwargs.setdefault("prefill_buckets", [16, 32])
    kwargs.setdefault("eos_token_id", 257)
    return LLMEngineCore(bundle, params, **kwargs)


async def _collect(engine, req):
    out = []
    async for token in engine.generate(req):
        out.append(token)
    return out


# -- decode-step poison: failure isolation ------------------------------------


def test_poisoned_decode_fails_only_that_request(parts):
    """Acceptance: with fault injection poisoning one request's decode step,
    that request fails with a structured error while a concurrently active
    request completes and the engine serves new requests — no restart."""
    bundle, params = parts
    marker = 300  # token only the poisoned request's prompt contains

    async def run():
        engine = _make_engine(bundle, params, decode_steps=1)
        # warm up (compile the decode chunk) before arming the fault
        await _collect(engine, GenRequest(prompt_ids=[256, 1], max_new_tokens=2))

        a = GenRequest(prompt_ids=[256, 5, 6], max_new_tokens=12)
        a_task = asyncio.create_task(_collect(engine, a))
        # wait until A is decoding so the poison has a live co-resident
        while a.produced < 2:
            await asyncio.sleep(0.01)
        faults.configure([
            {"point": "engine.decode", "action": "raise",
             "match_token": marker, "times": 1, "message": "poisoned step"},
        ])
        b = GenRequest(prompt_ids=[256, marker, 7], max_new_tokens=12)
        with pytest.raises(EngineStepError):
            await _collect(engine, b)
        # the co-resident completes in full
        out_a = await a_task
        assert len(out_a) == 12 or 257 in out_a
        # and the engine keeps serving new work without a process restart
        out_c = await _collect(
            engine, GenRequest(prompt_ids=[256, 9], max_new_tokens=4)
        )
        assert len(out_c) >= 1
        return engine

    engine = asyncio.run(run())
    assert engine.counters["step_failures"] == 1
    assert engine.active_slots == 0


def test_batch_wide_decode_failure_recovers_engine(parts):
    """An unattributable dispatch exception fails the in-flight batch with
    structured errors but the loop survives: new requests are served by the
    same engine instance."""
    bundle, params = parts

    async def run():
        engine = _make_engine(bundle, params, decode_steps=1)
        await _collect(engine, GenRequest(prompt_ids=[256, 1], max_new_tokens=2))
        faults.configure([
            {"point": "engine.decode", "action": "raise", "times": 1,
             "message": "device exploded"},
        ])
        with pytest.raises(EngineStepError):
            await _collect(
                engine, GenRequest(prompt_ids=[256, 2], max_new_tokens=8)
            )
        out = await _collect(
            engine, GenRequest(prompt_ids=[256, 3], max_new_tokens=4)
        )
        assert len(out) >= 1
        return engine

    engine = asyncio.run(run())
    assert engine.counters["step_failures"] == 1


def test_paged_poison_recovery_conserves_pages(parts):
    """Paged-cache variant of poison isolation, audited: the sanitizer
    checks refcount conservation after every decode step INCLUDING the
    recovery epoch, and at drain every page is back on the free list."""
    bundle, params = parts
    marker = 310

    async def run():
        engine = _make_engine(
            bundle, params, decode_steps=1, cache_mode="paged", page_size=16
        )
        assert engine._sanitizer is not None, "TPUSERVE_SANITIZE did not arm"
        await _collect(engine, GenRequest(prompt_ids=[256, 1], max_new_tokens=2))
        a = GenRequest(prompt_ids=[256, 5, 6], max_new_tokens=10)
        a_task = asyncio.create_task(_collect(engine, a))
        while a.produced < 2:
            await asyncio.sleep(0.01)
        faults.configure([
            {"point": "engine.decode", "action": "raise",
             "match_token": marker, "times": 1, "message": "poisoned step"},
        ])
        b = GenRequest(prompt_ids=[256, marker, 7], max_new_tokens=10)
        with pytest.raises(EngineStepError):
            await _collect(engine, b)
        out_a = await a_task
        assert len(out_a) >= 1
        # wait for drain so the drain-audit (strictest check) also ran
        t0 = time.monotonic()
        while (
            engine._loop_task is not None
            and not engine._loop_task.done()
            and time.monotonic() - t0 < 10.0
        ):
            await asyncio.sleep(0.01)
        if engine._loop_task is not None and engine._loop_task.done():
            assert engine._loop_task.exception() is None
        return engine

    engine = asyncio.run(run())
    stats = engine._sanitizer.stats()
    assert stats["checks"] > 0 and stats["failures"] == 0
    pool = engine.paged_cache.pool
    # no prefix cache configured: at drain every usable page is free again
    assert pool.free_pages == pool.num_pages - 1


def test_paged_poison_recovery_conserves_pages_int8(parts):
    """int8 paged KV (docs/paged_kv_quant.md) under chaos: poison recovery
    with int8 pools + radix shared-prefix reuse + copy-on-write, audited by
    the armed sanitizer (scale rows share the page lifecycle, so a clean
    page balance proves the scale pools balanced too)."""
    bundle, _ = parts
    qbundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32",
                  "kv_quant": "int8"}
    )
    params = parts[1]
    marker = 311
    shared = [256] + [(i * 3 + 1) % 250 for i in range(31)]

    async def run():
        engine = _make_engine(
            qbundle, params, decode_steps=1, cache_mode="paged",
            page_size=16, prefill_buckets=[32, 64],
            prefix_cache=8, prefix_block=16,
            # eos disabled: the pin-induced CoW below needs request A still
            # decoding when the pin lands (a sampled 257 would race it)
            eos_token_id=None,
        )
        assert engine._sanitizer is not None, "TPUSERVE_SANITIZE did not arm"
        assert engine.paged_cache.pool_dtype == "int8"
        # cold admission stores the shared prefix; the next two map it by
        # reference and their first decode write CoWs the shared tail page
        await _collect(
            engine, GenRequest(prompt_ids=shared, max_new_tokens=2)
        )
        a = GenRequest(prompt_ids=shared + [5], max_new_tokens=10)
        a_task = asyncio.create_task(_collect(engine, a))
        while a.produced < 2:
            await asyncio.sleep(0.01)
        # force a copy-on-write under live int8 decode: pin A's tail page
        # (an ACCOUNTED transient ref, like an in-flight admission holds) so
        # the next mid-page extend must give the slot a private copy — data
        # plane AND scale rows (kv_cache.apply_pending_cow)
        pool = engine.paged_cache.pool
        a_slot = next(
            (s for s, r in enumerate(engine._slot_req) if r is a), None
        )
        assert a_slot is not None, "request A left its slot before the pin"
        pinned = [pool.slot_pages(a_slot)[-1]]
        pool.pin_pages(pinned)
        while pool.cow_events < 1 and a.produced < 8:
            await asyncio.sleep(0.01)
        pool.unpin_pages(pinned)
        faults.configure([
            {"point": "engine.decode", "action": "raise",
             "match_token": marker, "times": 1, "message": "poisoned step"},
        ])
        b = GenRequest(prompt_ids=shared + [marker], max_new_tokens=10)
        with pytest.raises(EngineStepError):
            await _collect(engine, b)
        out_a = await a_task
        assert len(out_a) >= 1
        t0 = time.monotonic()
        while (
            engine._loop_task is not None
            and not engine._loop_task.done()
            and time.monotonic() - t0 < 10.0
        ):
            await asyncio.sleep(0.01)
        if engine._loop_task is not None and engine._loop_task.done():
            assert engine._loop_task.exception() is None
        return engine

    engine = asyncio.run(run())
    stats = engine._sanitizer.stats()
    assert stats["checks"] > 0 and stats["failures"] == 0
    assert engine._prefix.hits >= 1          # shared-prefix reuse happened
    assert engine.paged_cache.pool.cow_events >= 1  # CoW exercised
    pool = engine.paged_cache.pool
    # at drain: only the radix cache may keep pages; every page it holds is
    # accounted (the sanitizer's drain audit proved conservation already)
    assert pool.free_pages == (
        pool.num_pages - 1 - engine._prefix.cached_pages
    )
    engine.stop()


def test_deliberate_leak_is_caught_with_named_pages(parts):
    """Acceptance: a seeded teardown bug (engine.release fault swallows the
    page free) must fail CLOSED — the sanitizer's drain audit raises
    KVSanitizerError naming the leaked pages, instead of the pool quietly
    shrinking forever."""
    bundle, params = parts

    async def run():
        engine = _make_engine(
            bundle, params, decode_steps=1, cache_mode="paged", page_size=16
        )
        assert engine._sanitizer is not None
        # clean warmup request: proves the audit passes when teardown works
        await _collect(engine, GenRequest(prompt_ids=[256, 1], max_new_tokens=2))
        faults.configure([
            {"point": "engine.release", "times": 1, "message": "lost free"},
        ])
        out = await _collect(
            engine, GenRequest(prompt_ids=[256, 2, 3], max_new_tokens=3)
        )
        assert out, "the request itself succeeds; the leak is in teardown"
        t0 = time.monotonic()
        while not engine._loop_task.done() and time.monotonic() - t0 < 10.0:
            await asyncio.sleep(0.01)
        assert engine._loop_task.done(), "loop should exit at drain"
        return engine, engine._loop_task.exception()

    engine, exc = asyncio.run(run())
    assert isinstance(exc, KVSanitizerError), exc
    assert exc.where == "drain"
    assert exc.pages, "diagnostic must name the leaked page ids"
    assert "leaked pages at drain" in str(exc)
    assert all(str(p) in str(exc) for p in exc.pages)
    assert engine._sanitizer.stats()["failures"] == 1


# -- watchdog: stuck loop detection + supervised recovery ---------------------


def test_watchdog_trips_on_stalled_decode_and_recovers(parts):
    """A wedged decode dispatch (worker-thread stall) trips the watchdog:
    the stalled request fails with EngineStuckError, the engine reports
    not-ready while recovering, then flips back to ready and serves new
    requests — all inside one process."""
    bundle, params = parts

    async def run():
        engine = _make_engine(
            bundle, params, decode_steps=1, watchdog_interval=0.3
        )
        await _collect(engine, GenRequest(prompt_ids=[256, 1], max_new_tokens=2))
        assert engine.is_ready
        # quiesce the pipelined loop before arming the one-shot stall: a
        # leftover in-flight chunk's retire would burn the firing while the
        # engine is idle (no active slots -> no watchdog trip)
        await engine.wait_drained()
        faults.configure([
            {"point": "engine.decode.stall", "action": "delay",
             "delay": 1.2, "times": 1},
        ])
        req = GenRequest(prompt_ids=[256, 4, 5], max_new_tokens=50)
        task = asyncio.create_task(_collect(engine, req))
        saw_not_ready = False
        t0 = time.monotonic()
        while time.monotonic() - t0 < 10.0:
            await asyncio.sleep(0.01)
            if not engine.is_ready:
                saw_not_ready = True
            if task.done():
                break
        with pytest.raises(EngineStuckError):
            await task
        assert saw_not_ready, "/ready never observed the recovery window"
        assert engine.counters["watchdog_trips"] >= 1
        # the stalled dispatch drains and the engine flips back to ready
        t0 = time.monotonic()
        while not engine.is_ready and time.monotonic() - t0 < 10.0:
            await asyncio.sleep(0.01)
        assert engine.is_ready
        out = await _collect(
            engine, GenRequest(prompt_ids=[256, 8], max_new_tokens=3)
        )
        assert len(out) >= 1
        return engine

    engine = asyncio.run(run())
    assert engine.health()["ready"]


# -- admission shedding -------------------------------------------------------


def test_queue_bound_sheds_with_retry_after(parts):
    bundle, params = parts

    async def run():
        engine = _make_engine(bundle, params, max_batch=1, max_pending=1)
        a = GenRequest(prompt_ids=[256, 1], max_new_tokens=10_000)
        agen = engine.generate(a)
        await agen.__anext__()  # A holds the single slot
        b = GenRequest(prompt_ids=[256, 2], max_new_tokens=2)
        b_task = asyncio.create_task(_collect(engine, b))
        while engine._pending.qsize() < 1:  # B parked in the queue
            await asyncio.sleep(0.005)
        c = GenRequest(prompt_ids=[256, 3], max_new_tokens=2)
        with pytest.raises(EngineOverloadedError) as ei:
            async for _ in engine.generate(c):
                pass
        assert ei.value.status == 429 and ei.value.retry_after is not None
        await agen.aclose()  # free the slot; B proceeds
        out_b = await b_task
        assert len(out_b) >= 1
        return engine

    engine = asyncio.run(run())
    assert engine.counters["sheds_queue"] == 1


def test_pool_saturation_sheds_paged_admission(parts):
    """With admission control on, a prompt the KV pool cannot hold right now
    is shed 429 at the front door instead of queueing forever."""
    bundle, params = parts
    engine = _make_engine(
        bundle, params, cache_mode="paged", page_size=16, max_batch=2,
        max_pending=8,
    )
    pool = engine.paged_cache.pool
    # occupy nearly the whole pool via a raw slot allocation
    free0 = pool.free_pages
    pool.allocate(0, (free0 - 1) * pool.page_size)
    big = GenRequest(prompt_ids=list(range(64)), max_new_tokens=2)
    with pytest.raises(EngineOverloadedError):
        engine.check_admission(big)
    assert engine.counters["sheds_pool"] == 1
    pool.free(0)
    engine.check_admission(big)  # headroom restored -> admissible again


def test_pool_shed_accounts_for_cached_prefix(parts):
    """The headroom check must charge only the NON-cached tail: a request
    whose prefix the radix cache already holds is admissible where a cold
    prompt of the same length is shed."""
    bundle, params = parts

    async def run():
        engine = _make_engine(
            bundle, params, cache_mode="paged", page_size=4, max_batch=2,
            max_pending=8, prefix_cache=64, prefix_block=16,
        )
        system = [(i * 5 + 1) % 256 for i in range(32)]
        await _collect(engine, GenRequest(
            prompt_ids=system + [9], max_new_tokens=2
        ))
        return engine, system

    engine, system = asyncio.run(run())
    pool = engine.paged_cache.pool
    assert engine._prefix.match_len(system + [7], 0) == 32
    # leave exactly 2 free pages (8 tokens of headroom)
    pool.allocate(0, (pool.free_pages - 2) * 4)
    warm = GenRequest(prompt_ids=system + [7], max_new_tokens=2)
    engine.check_admission(warm)  # 32/33 tokens cached -> 1 page suffices
    cold = GenRequest(prompt_ids=list(range(33)), max_new_tokens=2)
    with pytest.raises(EngineOverloadedError):
        engine.check_admission(cold)
    pool.free(0)


def test_injected_admission_shed(parts):
    bundle, params = parts
    engine = _make_engine(bundle, params)
    faults.configure([{"point": "engine.admit", "times": 1}])
    with pytest.raises(EngineOverloadedError):
        engine.check_admission(GenRequest(prompt_ids=[256], max_new_tokens=1))
    engine.check_admission(GenRequest(prompt_ids=[256], max_new_tokens=1))


def test_stopped_engine_is_unavailable(parts):
    bundle, params = parts

    async def run():
        engine = _make_engine(bundle, params)
        engine.stop()
        with pytest.raises(EngineUnavailableError):
            async for _ in engine.generate(
                GenRequest(prompt_ids=[256], max_new_tokens=1)
            ):
                pass
        return engine

    engine = asyncio.run(run())
    assert not engine.is_ready


# -- deadlines ----------------------------------------------------------------


def test_ttft_deadline_on_slow_prefill(parts):
    """Delayed prefill (injected) blows the request's TTFT budget: the
    request fails 408/ttft at the commit boundary, the engine stays up."""
    bundle, params = parts
    marker = 301

    async def run():
        engine = _make_engine(bundle, params)
        await _collect(engine, GenRequest(prompt_ids=[256, 1], max_new_tokens=2))
        faults.configure([
            {"point": "engine.prefill", "action": "delay", "delay": 0.4,
             "match_token": marker, "times": 1},
        ])
        req = GenRequest(
            prompt_ids=[256, marker], max_new_tokens=4, ttft_timeout=0.1
        )
        with pytest.raises(DeadlineExceededError) as ei:
            await _collect(engine, req)
        assert ei.value.stage == "ttft" and ei.value.status == 408
        out = await _collect(
            engine, GenRequest(prompt_ids=[256, 2], max_new_tokens=3)
        )
        assert len(out) >= 1
        return engine

    engine = asyncio.run(run())
    assert engine.counters["deadline_ttft"] == 1


def test_queue_wait_deadline_expires_parked_request(parts):
    bundle, params = parts

    async def run():
        engine = _make_engine(bundle, params, max_batch=1, decode_steps=1)
        a = GenRequest(prompt_ids=[256, 1], max_new_tokens=10_000)
        agen = engine.generate(a)
        await agen.__anext__()  # A pins the only slot
        b = GenRequest(
            prompt_ids=[256, 2], max_new_tokens=2, queue_timeout=0.1
        )
        with pytest.raises(DeadlineExceededError) as ei:
            await _collect(engine, b)
        assert ei.value.stage == "queue"
        await agen.aclose()
        return engine

    engine = asyncio.run(run())
    assert engine.counters["deadline_queue"] == 1


def test_total_deadline_cuts_generation_short(parts):
    bundle, params = parts

    async def run():
        engine = _make_engine(bundle, params, decode_steps=1)
        await _collect(engine, GenRequest(prompt_ids=[256, 1], max_new_tokens=2))
        req = GenRequest(
            prompt_ids=[256, 3], max_new_tokens=100_000, total_timeout=0.25
        )
        got = []
        with pytest.raises(DeadlineExceededError) as ei:
            async for tok in engine.generate(req):
                got.append(tok)
        assert ei.value.stage == "total"
        assert got, "some tokens should stream before the budget elapses"
        return engine

    engine = asyncio.run(run())
    assert engine.counters["deadline_total"] >= 1
    assert engine.active_slots == 0  # slot + pages reclaimed


# -- gRPC retry/backoff -------------------------------------------------------


class _FakeRpcError(Exception):
    def __init__(self, code):
        super().__init__("fake upstream error {}".format(code))
        self.grpc_code = code


def _grpc_client(monkeypatch):
    from clearml_serving_tpu.engines.grpc_client import JaxGrpcEngineRequest

    monkeypatch.setenv("TPUSERVE_GRPC_RETRY_BACKOFF", "0.001")
    monkeypatch.setenv("TPUSERVE_GRPC_RETRY_BACKOFF_MAX", "0.002")
    return object.__new__(JaxGrpcEngineRequest)


def test_grpc_transient_errors_retry_then_succeed(monkeypatch):
    from clearml_serving_tpu.engines import grpc_client as gc

    cli = _grpc_client(monkeypatch)
    calls = []

    async def flaky(payload, timeout=None):
        calls.append(1)
        if len(calls) < 3:
            raise _FakeRpcError("UNAVAILABLE")
        return b"ok"

    before = dict(gc.RETRY_STATS)
    out = asyncio.run(cli._call_with_retry(flaky, b"req", timeout=1.0))
    assert out == b"ok" and len(calls) == 3
    assert gc.RETRY_STATS["retries"] - before["retries"] == 2


def test_grpc_retry_budget_maps_to_structured_errors(monkeypatch):
    cli = _grpc_client(monkeypatch)

    async def always_unavailable(payload, timeout=None):
        raise _FakeRpcError("UNAVAILABLE")

    async def always_deadline(payload, timeout=None):
        raise _FakeRpcError("DEADLINE_EXCEEDED")

    with pytest.raises(UpstreamUnavailableError) as ei:
        asyncio.run(cli._call_with_retry(always_unavailable, b"r", timeout=1.0))
    assert ei.value.status == 503 and ei.value.retry_after is not None
    with pytest.raises(UpstreamTimeoutError) as ei:
        asyncio.run(cli._call_with_retry(always_deadline, b"r", timeout=1.0))
    assert ei.value.status == 504


def test_grpc_non_transient_errors_do_not_retry(monkeypatch):
    cli = _grpc_client(monkeypatch)
    calls = []

    async def internal(payload, timeout=None):
        calls.append(1)
        raise _FakeRpcError("INTERNAL")

    with pytest.raises(_FakeRpcError):
        asyncio.run(cli._call_with_retry(internal, b"r", timeout=1.0))
    assert len(calls) == 1


def test_grpc_injected_fault_exercises_retry_path(monkeypatch):
    """The faults seam covers the gRPC path too: injected UNAVAILABLE on the
    first two attempts, then the real call runs."""
    cli = _grpc_client(monkeypatch)
    faults.configure([
        {"point": "grpc.call", "grpc_code": "UNAVAILABLE", "times": 2},
    ])
    calls = []

    async def ok(payload, timeout=None):
        calls.append(1)
        return b"fine"

    out = asyncio.run(cli._call_with_retry(ok, b"r", timeout=1.0))
    assert out == b"fine" and len(calls) == 1


# -- pipelined decode under chaos (docs/pipelined_decode.md) ------------------


def test_watchdog_recovery_with_nonempty_inflight_queue(parts):
    """Depth-2 pipeline, paged backend, several live requests: a stall at
    the retire stage trips the watchdog WHILE a younger chunk is still in
    flight. Recovery must discard the whole in-flight queue under the epoch
    bump, execute the deferred (quarantined) frees, flip back to ready, and
    keep page accounting balanced (armed sanitizer) — then serve again."""
    bundle, params = parts

    async def run():
        engine = _make_engine(
            bundle, params, decode_steps=2, watchdog_interval=0.3,
            cache_mode="paged", page_size=4, pipeline_depth=2,
            eos_token_id=None,  # victims must still be decoding at the stall
        )
        assert engine.pipeline_depth == 2
        assert engine._sanitizer is not None
        reqs = [
            GenRequest(prompt_ids=[256, 1 + i], max_new_tokens=2)
            for i in range(3)
        ]
        await asyncio.gather(*(_collect(engine, r) for r in reqs))
        await engine.wait_drained()
        victims = [
            GenRequest(prompt_ids=[256, 40 + i], max_new_tokens=600)
            for i in range(3)
        ]
        tasks = [asyncio.create_task(_collect(engine, v)) for v in victims]
        # arm the stall only once every victim holds a slot — a victim
        # still mid-admission at the trip would be committed afterwards
        # and complete normally
        t0 = time.monotonic()
        while time.monotonic() - t0 < 10.0 and not all(
            v.produced >= 1 for v in victims
        ):
            await asyncio.sleep(0.01)
        assert all(v.produced >= 1 for v in victims)
        faults.configure([
            {"point": "engine.decode.stall", "action": "delay",
             "delay": 1.2, "times": 1},
        ])
        done, pending = await asyncio.wait(tasks, timeout=15.0)
        assert not pending
        errors = [t.exception() for t in tasks]
        assert all(isinstance(e, EngineStuckError) for e in errors), errors
        assert engine.counters["watchdog_trips"] >= 1
        # the pipeline was discarded wholesale
        t0 = time.monotonic()
        while not engine.is_ready and time.monotonic() - t0 < 10.0:
            await asyncio.sleep(0.01)
        assert engine.is_ready
        assert not engine._inflight and not engine._quarantine
        # still serves, and page accounting balances through drain
        out = await _collect(
            engine, GenRequest(prompt_ids=[256, 9], max_new_tokens=3)
        )
        assert len(out) >= 1
        await engine.wait_drained()
        assert engine.paged_cache.pool.free_pages == (
            engine.paged_cache.pool.num_pages - 1
        )
        return engine

    engine = asyncio.run(run())
    assert engine.health()["ready"]


def test_retire_fault_isolates_matched_request(parts):
    """An engine.decode.retire fault matched to one request fails ONLY that
    request (EngineStepError); the rest of the chunk still emits, the other
    requests complete, and the paged pool balances at drain."""
    bundle, params = parts
    marker = 301

    async def run():
        engine = _make_engine(
            bundle, params, decode_steps=2, cache_mode="paged", page_size=4,
            pipeline_depth=2,
            eos_token_id=None,  # exact token counts below
        )
        await _collect(engine, GenRequest(prompt_ids=[256, 1], max_new_tokens=2))
        await engine.wait_drained()
        faults.configure([
            {"point": "engine.decode.retire", "match_token": marker,
             "times": 1, "message": "retire blew up"},
        ])
        poisoned = GenRequest(prompt_ids=[256, marker], max_new_tokens=40)
        healthy = GenRequest(prompt_ids=[256, 7], max_new_tokens=6)
        p_task = asyncio.create_task(_collect(engine, poisoned))
        h_task = asyncio.create_task(_collect(engine, healthy))
        out_h = await asyncio.wait_for(h_task, timeout=30)
        with pytest.raises(EngineStepError):
            await asyncio.wait_for(p_task, timeout=30)
        assert len(out_h) == 6, "healthy request must emit every token"
        assert engine.counters["step_failures"] >= 1
        await engine.wait_drained()
        assert engine.paged_cache.pool.free_pages == (
            engine.paged_cache.pool.num_pages - 1
        )
        return engine

    engine = asyncio.run(run())
    assert engine.is_ready


def test_injected_class_shed(parts):
    """The engine.admit.class seam forces a class-policy shed: structured
    429 carrying the request's priority class, booked under reason
    'class'."""
    bundle, params = parts
    engine = _make_engine(bundle, params)
    faults.configure([{"point": "engine.admit.class", "times": 1}])
    with pytest.raises(EngineOverloadedError) as ei:
        engine.check_admission(
            GenRequest(prompt_ids=[256], max_new_tokens=1, priority="batch")
        )
    assert ei.value.shed_class == "batch"
    assert engine._class_sheds["class"]["batch"] == 1
    engine.check_admission(
        GenRequest(prompt_ids=[256], max_new_tokens=1, priority="batch")
    )
    engine.stop()


# -- preemptible batch lane under chaos (docs/slo_scheduling.md) --------------


def test_preempt_fault_mid_commit_aborts_without_leaking_pages(parts):
    """An engine.preempt fault fires mid-preemption — AFTER the victim's
    generated-so-far KV was committed into the radix cache, BEFORE the slot
    free/requeue. The preemption must abort cleanly: the victim keeps
    decoding, a later retry succeeds, and page accounting stays balanced
    under the armed sanitizer (the radix store alone is a normal
    admission-commit store)."""
    bundle, params = parts

    async def run():
        engine = _make_engine(
            bundle, params, max_batch=1, decode_steps=2, cache_mode="paged",
            page_size=16, prefix_cache=64, prefix_block=16,
            prefill_buckets=[32, 64], eos_token_id=None,
        )
        assert engine._sanitizer is not None, "TPUSERVE_SANITIZE did not arm"
        batch = GenRequest(
            prompt_ids=[256] + [(i * 3 + 1) % 250 for i in range(16)],
            max_new_tokens=30, priority="batch",
        )
        b_task = asyncio.create_task(_collect(engine, batch))
        while batch.produced < 4:
            await asyncio.sleep(0.005)
        # the FIRST preemption attempt dies mid-commit; the retry (next
        # chunk boundary) must succeed
        faults.configure([{"point": "engine.preempt", "times": 1}])
        hi = GenRequest(prompt_ids=[256, 9], max_new_tokens=2)
        out_hi = await asyncio.wait_for(_collect(engine, hi), timeout=60)
        assert len(out_hi) >= 1
        out_b = await asyncio.wait_for(b_task, timeout=60)
        assert len(out_b) == 30
        await engine.wait_drained()
        return engine

    engine = asyncio.run(run())
    assert engine.counters["preemptions"] >= 1, "retry never preempted"
    stats = engine._sanitizer.stats()
    assert stats["checks"] > 0 and stats["failures"] == 0
    pool = engine.paged_cache.pool
    assert pool.free_pages == (
        pool.num_pages - 1 - engine._prefix.cached_pages
    )
    engine.stop()


def test_seeded_interactive_stream_identical_across_batch_preemption(parts):
    """Acceptance (ISSUE 6): a SEEDED interactive stream must be
    byte-identical whether or not a batch neighbor was preempted — seeded
    sampling keys on (seed, tokens-generated) per slot, so scheduler
    decisions about neighbors must never leak into the stream."""
    bundle, params = parts
    seed_req = dict(
        prompt_ids=[256, 11, 12, 13], max_new_tokens=16, temperature=0.9,
        seed=1234,
    )

    def make_engine():
        return _make_engine(
            bundle, params, max_batch=2, decode_steps=2, cache_mode="paged",
            page_size=16, prefix_cache=64, prefix_block=16,
            prefill_buckets=[16, 32], eos_token_id=None,
        )

    async def alone():
        engine = make_engine()
        out = await _collect(engine, GenRequest(**seed_req))
        await engine.wait_drained()
        engine.stop()
        return out

    async def with_preempted_neighbors():
        engine = make_engine()
        victims = [
            GenRequest(
                prompt_ids=[256, 40 + i, 41], max_new_tokens=40,
                priority="batch",
            )
            for i in range(2)
        ]
        tasks = [asyncio.create_task(_collect(engine, v)) for v in victims]
        while not all(v.produced >= 2 for v in victims):
            await asyncio.sleep(0.005)
        # both slots busy with batch work: the seeded interactive request
        # forces a preemption
        out = await asyncio.wait_for(
            _collect(engine, GenRequest(**seed_req)), timeout=60
        )
        for t in tasks:
            await asyncio.wait_for(t, timeout=60)
        await engine.wait_drained()
        return engine, out

    expected = asyncio.run(alone())
    engine, got = asyncio.run(with_preempted_neighbors())
    assert engine.counters["preemptions"] >= 1, "no neighbor was preempted"
    assert got == expected, "seeded stream diverged across preemption"
    stats = engine._sanitizer.stats()
    assert stats["checks"] > 0 and stats["failures"] == 0
    engine.stop()


def test_stop_with_chunks_in_flight_reclaims_pages(parts):
    """stop() while the depth-2 pipeline holds undelivered chunks: every
    consumer unblocks with EngineUnavailableError and the loop's exit path
    reclaims all pages despite the dropped in-flight queue."""
    bundle, params = parts

    async def run():
        engine = _make_engine(
            bundle, params, decode_steps=2, cache_mode="paged", page_size=4,
            pipeline_depth=2,
            eos_token_id=None,  # long-runners must still be live at stop()
        )
        reqs = [
            GenRequest(prompt_ids=[256, 20 + i], max_new_tokens=10_000)
            for i in range(2)
        ]
        tasks = [asyncio.create_task(_collect(engine, r)) for r in reqs]
        # let decode reach a pipelined steady state
        t0 = time.monotonic()
        while time.monotonic() - t0 < 10.0 and not all(
            r.produced > 2 for r in reqs
        ):
            await asyncio.sleep(0.01)
        engine.stop()
        for t in tasks:
            with pytest.raises(EngineUnavailableError):
                await asyncio.wait_for(t, timeout=15)
        t0 = time.monotonic()
        while time.monotonic() - t0 < 10.0 and not engine._loop_task.done():
            await asyncio.sleep(0.01)
        assert engine._loop_task.done()
        pool = engine.paged_cache.pool
        assert pool.free_pages == pool.num_pages - 1
        assert not engine._quarantine
        return engine

    asyncio.run(run())


def test_dispatch_prepare_seam_fails_batch_structurally(parts):
    """The engine.dispatch.prepare yield-point seam (interleaving-explorer
    boundary, docs/static_analysis.md) is a live fault point: a raise-once
    spec there fails the in-flight batch with a structured error and the
    engine keeps serving — armed sanitizer balancing the books."""
    bundle, params = parts

    async def run():
        engine = _make_engine(bundle, params, decode_steps=1)
        await _collect(engine, GenRequest(prompt_ids=[256, 1], max_new_tokens=2))
        faults.configure([
            {"point": "engine.dispatch.prepare", "action": "raise",
             "times": 1, "message": "prep seam"},
        ])
        with pytest.raises(EngineStepError):
            await _collect(
                engine, GenRequest(prompt_ids=[256, 2], max_new_tokens=8)
            )
        out = await _collect(
            engine, GenRequest(prompt_ids=[256, 3], max_new_tokens=4)
        )
        assert len(out) >= 1
        return engine

    engine = asyncio.run(run())
    assert engine.counters["step_failures"] == 1


def test_drain_seam_fires_at_the_drained_boundary(parts):
    """engine.drain fires exactly once per drain, at the boundary the
    drained sanitizer audit runs on."""
    bundle, params = parts

    async def run():
        engine = _make_engine(bundle, params, decode_steps=1)
        spec = faults.FaultSpec(point="engine.drain", action="delay",
                                delay=0.0, times=-1)
        faults.configure([spec])
        await _collect(engine, GenRequest(prompt_ids=[256, 4], max_new_tokens=2))
        await engine.wait_drained()
        return spec.fired

    fired = asyncio.run(run())
    assert fired >= 1
