import json

import pytest

from clearml_serving_tpu.__main__ import cli
from clearml_serving_tpu.serving.model_request_processor import ModelRequestProcessor

ECHO_CODE = """
class Preprocess:
    def process(self, data, state, collect_fn):
        return {"echo": data}
"""


@pytest.fixture()
def svc_id(state_root, capsys):
    assert cli(["create", "--name", "cli-test"]) == 0
    out = capsys.readouterr().out
    return out.strip().rsplit("id=", 1)[-1]


def test_create_and_list(svc_id, capsys):
    assert cli(["list"]) == 0
    services = json.loads(capsys.readouterr().out)
    assert any(s["id"] == svc_id for s in services)


def test_model_upload_add_remove(svc_id, tmp_path, capsys):
    code = tmp_path / "pre.py"
    code.write_text(ECHO_CODE)
    payload = tmp_path / "model.bin"
    payload.write_bytes(b"x")

    assert cli(["--yes", "--id", svc_id, "model", "upload", "--name", "m1",
                "--project", "p", "--path", str(payload), "--publish"]) == 0
    model_id = capsys.readouterr().out.strip().split("id=")[1].split()[0]

    assert cli(["--yes", "--id", svc_id, "model", "add", "--engine", "custom",
                "--endpoint", "test_model", "--model-id", model_id,
                "--preprocess", str(code)]) == 0
    capsys.readouterr()

    # model query path (--name instead of --model-id)
    assert cli(["--yes", "--id", svc_id, "model", "add", "--engine", "custom",
                "--endpoint", "test_model2", "--name", "m1", "--project", "p",
                "--published", "--preprocess", str(code)]) == 0
    out = capsys.readouterr().out
    assert model_id in out

    assert cli(["--yes", "--id", svc_id, "model", "list"]) == 0
    listed = json.loads(capsys.readouterr().out)
    assert "test_model" in listed["endpoints"]
    assert listed["endpoints"]["test_model"]["model_id"] == model_id

    assert cli(["--yes", "--id", svc_id, "model", "remove",
                "--endpoint", "test_model"]) == 0
    capsys.readouterr()
    assert cli(["--yes", "--id", svc_id, "model", "list"]) == 0
    listed = json.loads(capsys.readouterr().out)
    assert "test_model" not in listed["endpoints"]

    with pytest.raises(SystemExit):
        cli(["--yes", "--id", svc_id, "model", "remove", "--endpoint", "ghost"])


def test_canary_and_auto_update(svc_id, tmp_path, capsys):
    code = tmp_path / "pre.py"
    code.write_text(ECHO_CODE)
    assert cli(["--yes", "--id", svc_id, "model", "auto-update", "--engine", "custom",
                "--endpoint", "auto_m", "--project", "prod", "--max-versions", "2",
                "--preprocess", str(code)]) == 0
    assert cli(["--yes", "--id", svc_id, "model", "canary", "--endpoint", "auto_m",
                "--weights", "0.1", "0.9",
                "--input-endpoint-prefix", "auto_m/"]) == 0
    capsys.readouterr()
    assert cli(["--yes", "--id", svc_id, "model", "list"]) == 0
    listed = json.loads(capsys.readouterr().out)
    assert "auto_m" in listed["model_monitoring"]
    assert "auto_m" in listed["canary"]


def test_config_and_metrics(svc_id, tmp_path, capsys):
    assert cli(["--yes", "--id", svc_id, "config",
                "--base-serve-url", "http://127.0.0.1:9090/serve",
                "--metric-log-freq", "0.5"]) == 0
    assert cli(["--yes", "--id", svc_id, "metrics", "add", "--endpoint", "test_model",
                "--log-freq", "1.0",
                "--variable-scalar", "x0=0/1/0.25", "x1=0,1,2,5",
                "--variable-enum", "label=cat,dog",
                "--variable-value", "rawval"]) == 0
    capsys.readouterr()
    assert cli(["--yes", "--id", svc_id, "metrics", "list"]) == 0
    listed = json.loads(capsys.readouterr().out)
    spec = listed["test_model"]
    assert spec["metrics"]["x0"]["buckets"] == [0.0, 0.25, 0.5, 0.75, 1.0]
    assert spec["metrics"]["x1"]["buckets"] == [0.0, 1.0, 2.0, 5.0]
    assert spec["metrics"]["label"]["type"] == "enum"
    assert spec["metrics"]["rawval"]["type"] == "value"

    # verify the config param round-trips into a processor
    mrp = ModelRequestProcessor(service_id=svc_id)
    mrp.deserialize(skip_sync=True)
    assert mrp._serving_base_url == "http://127.0.0.1:9090/serve"
    assert mrp._metric_log_freq == 0.5

    assert cli(["--yes", "--id", svc_id, "metrics", "remove", "--endpoint", "test_model",
                "--variable", "x1"]) == 0
    capsys.readouterr()
    assert cli(["--yes", "--id", svc_id, "metrics", "list"]) == 0
    listed = json.loads(capsys.readouterr().out)
    assert "x1" not in listed["test_model"]["metrics"]


def test_aux_config_kv(svc_id, tmp_path, capsys):
    code = tmp_path / "pre.py"
    code.write_text(ECHO_CODE)
    assert cli(["--yes", "--id", svc_id, "model", "add", "--engine", "custom",
                "--endpoint", "aux_ep", "--preprocess", str(code),
                "--aux-config", "batching.buckets=[1,2,4]", "mesh.tp=8"]) == 0
    capsys.readouterr()
    assert cli(["--yes", "--id", svc_id, "model", "list"]) == 0
    listed = json.loads(capsys.readouterr().out)
    aux = listed["endpoints"]["aux_ep"]["auxiliary_cfg"]
    assert aux == {"batching": {"buckets": [1, 2, 4]}, "mesh": {"tp": 8}}
