"""Runtime compile-sentry suite (llm/compile_sentry.py + llm/warmup.py;
docs/static_analysis.md TPU6xx).

Proves the dynamic half of the compile-surface discipline end to end:

- the sentry's hook counts real XLA compilations, attributes them to the
  thread context, splits them at the warmup fence, and raises in strict
  mode through the engine's loop-boundary check;
- the shared warmup registry (llm/warmup.py) drives a real engine to ZERO
  post-fence compiles over novel in-class traffic (the full paged sweep is
  `slow`; a reduced dense sweep runs in tier-1);
- the SEEDED SHAPE-DRIFT DEFECT — `engine.compile.bucket` makes the
  prefill bucket picker return raw request lengths — is proven caught:
  post-fence compiles appear, the strict check raises naming the function,
  and the attribution carries the prefill context (acceptance criterion).
"""

import asyncio
import threading

import jax
import jax.numpy as jnp
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.llm import compile_sentry, faults
from clearml_serving_tpu.llm.compile_sentry import (
    CompileSentry,
    CompileSentryError,
)
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore


@pytest.fixture(scope="module")
def parts():
    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, params


@pytest.fixture(autouse=True)
def clean_state():
    faults.clear()
    yield
    faults.clear()
    # the singleton is process-wide: never leave a fence (or strictness)
    # behind for unrelated suites — post-fence state would misattribute
    # THEIR legitimate first-use compiles as violations
    if compile_sentry._sentry is not None:
        compile_sentry._sentry.reset(strict=False)


async def _collect(engine, req):
    out = []
    async for token in engine.generate(req):
        out.append(token)
    return out


# -- sentry unit behavior (private instance, no singleton) --------------------


def test_sentry_counts_fence_and_strict_raise():
    sentry = CompileSentry(strict=True).install()
    try:
        assert sentry.stats()["mode"] == "log"
        jax.jit(lambda x: x * 2)(jnp.ones((3,)))  # fresh lambda: compiles
        assert sentry.counts["warmup"] >= 1
        assert sentry.counts["serve"] == 0
        sentry.check()  # pre-fence: nothing to raise
        sentry.fence()
        jax.jit(lambda x: x * 3)(jnp.ones((5,)))
        assert sentry.post_fence_compiles >= 1
        with pytest.raises(CompileSentryError) as exc:
            sentry.check(where="unit")
        assert "AFTER the warmup fence" in str(exc.value)
        assert "ShapedArray" in str(exc.value)
    finally:
        sentry.uninstall()
    # uninstalled: further compiles are invisible
    before = dict(sentry.counts)
    jax.jit(lambda x: x * 5)(jnp.ones((7,)))
    assert sentry.counts == before


def test_sentry_nonstrict_counts_without_raising():
    sentry = CompileSentry(strict=False).install()
    try:
        sentry.fence()
        jax.jit(lambda x: x * 7)(jnp.ones((2,)))
        assert sentry.post_fence_compiles >= 1
        sentry.check()  # counts, never raises
    finally:
        sentry.uninstall()


def test_sentry_thread_context_attribution_and_durations():
    sentry = CompileSentry(strict=False).install()
    try:
        def worker():
            with sentry.context(phase="decode", seq=41):
                jax.jit(lambda x: x * 11)(jnp.ones((9,)))

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        tagged = [
            e for e in sentry.stats()["events"]
            if e["context"].get("phase") == "decode"
        ]
        assert tagged and tagged[0]["context"]["seq"] == 41
        # the Finished-compilation lines attach per-compile durations,
        # which feed the ms histogram
        assert any(e["duration_ms"] is not None for e in sentry.stats()["events"])
        snap = sentry.hist_snapshot()
        assert sum(snap["counts"]) >= 1 and snap["sum_ms"] > 0
    finally:
        sentry.uninstall()


def test_sentry_lazy_context_is_counted_not_violated():
    # __compile_keys__ "lazy"-role entries (e.g. _score_prompt_jit) are
    # one-bounded-compile-per-variant BY DESIGN: post-fence they count
    # into serve (observable) but never trip strict
    sentry = CompileSentry(strict=True).install()
    try:
        sentry.fence()
        with sentry.context(phase="score", lazy=True):
            jax.jit(lambda x: x * 19)(jnp.ones((6,)))
        assert sentry.post_fence_compiles >= 1
        sentry.check()  # no violation recorded
        jax.jit(lambda x: x * 23)(jnp.ones((11,)))  # outside: violation
        with pytest.raises(CompileSentryError):
            sentry.check()
    finally:
        sentry.uninstall()


def test_sentry_reset_clears_fence_and_counts():
    sentry = CompileSentry(strict=True).install()
    try:
        sentry.fence()
        jax.jit(lambda x: x * 13)(jnp.ones((4,)))
        assert sentry.post_fence_compiles >= 1
        sentry.reset(strict=False)
        assert sentry.post_fence_compiles == 0
        assert not sentry.stats()["fenced"]
        sentry.check()  # no pending violation survives a reset
    finally:
        sentry.uninstall()


# -- warmup plan enumeration (no engine needed) -------------------------------


class _StubPool:
    page_size = 16

    def pages_needed(self, tokens):
        return -(-tokens // self.page_size)


class _StubPaged:
    pool = _StubPool()


class _StubPrefix:
    block = 16


class _StubEngine:
    _vocab = 300
    _buckets = [32, 64]
    max_seq_len = 128
    max_batch = 2
    decode_steps = 1
    _prefix = _StubPrefix()
    paged_cache = _StubPaged()
    _speculation = None
    _spec_k = 4
    _ragged = False


def test_warmup_plan_covers_the_key_space():
    from clearml_serving_tpu.llm.warmup import warmup_plan

    plan = warmup_plan(_StubEngine())
    lens = {len(p["prompt_ids"]) for p in plan}
    # every prompt admissible
    assert all(0 < n < _StubEngine.max_seq_len for n in lens)
    # the implicit max_seq_len fallback bucket is part of the surface
    assert any(n > 64 for n in lens)
    # single-page resume tails sweep every final-segment length at a
    # hit bucket (prefix 48 + tails 1..16 -> 49..64)
    assert set(range(49, 65)) <= lens
    # multi-page tails reach the larger buckets (2b: e.g. a 2-page tail
    # riding a shortened prefix)
    assert len(plan) > 40
    # the cheap startup subset stays cheap
    small = warmup_plan(_StubEngine(), full=False)
    assert 0 < len(small) <= 8


def test_warmup_plan_without_prefix_cache():
    class _NoPrefix(_StubEngine):
        _prefix = None
        paged_cache = None

    from clearml_serving_tpu.llm.warmup import warmup_plan

    plan = warmup_plan(_NoPrefix())
    assert plan, "cold per-bucket pass must survive prefix-less configs"
    assert all(
        0 < len(p["prompt_ids"]) < _NoPrefix.max_seq_len for p in plan
    )


# -- engine integration: warmed serve + the seeded defect ---------------------


def test_engine_warmup_fence_and_seeded_shape_drift(parts, monkeypatch):
    """Tier-1 acceptance path on a cheap dense engine: after the reduced
    warmup + fence, in-class traffic compiles NOTHING; then the seeded
    shape-drift defect (engine.compile.bucket skips the bucketizer) makes
    a novel length mint a fresh XLA program — the sentry counts it with
    prefill attribution and the strict check kills the request through
    the loop boundary."""
    monkeypatch.setenv("TPUSERVE_COMPILE_SENTRY", "strict")
    sentry = compile_sentry.get()
    sentry.reset(strict=True)
    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64,
        prefill_buckets=[16, 32], eos_token_id=None, decode_steps=1,
    )
    assert engine._compile_sentry is sentry

    async def run():
        # reduced warmup: one pass per bucket (incl. the fallback). A
        # partial sweep must NOT self-certify (only full=True fences);
        # this test fences explicitly to exercise the machinery on a
        # cheap engine whose traffic stays inside the reduced surface.
        stats = await engine.warmup(full=False)
        assert stats["fenced"] is False
        sentry.fence()
        block = engine.lifecycle_stats()["compile"]
        assert block["fenced"] and block["warmup"] > 0
        assert block["serve"] == 0
        assert engine.health()["compile"]["warmup"] == block["warmup"]

        # in-class traffic (warmed buckets, varied content): zero compiles
        for ids in ([7, 8, 9], [5] * 14, [9] * 29, [3] * 50):
            await _collect(engine, GenRequest(
                prompt_ids=list(ids), max_new_tokens=2
            ))
        await engine.wait_drained()
        assert sentry.post_fence_compiles == 0

        # seeded defect: skip the bucketizer for one admission
        faults.configure([
            {"point": "engine.compile.bucket", "action": "raise",
             "times": 1, "message": "shape drift"},
        ])
        with pytest.raises(CompileSentryError):
            await _collect(engine, GenRequest(
                prompt_ids=[4] * 23, max_new_tokens=4
            ))
        assert sentry.post_fence_compiles > 0
        prefill_tagged = [
            e for e in sentry.stats()["events"]
            if e["phase"] == "serve"
            and e["context"].get("phase") == "prefill"
        ]
        assert prefill_tagged, "drift compile must carry prefill attribution"
        return engine.lifecycle_stats()["compile"]

    try:
        block = asyncio.run(run())
        assert block["violations"] >= 1
        assert block["serve"] >= 1
    finally:
        engine.stop()
        sentry.reset(strict=False)


def test_warmup_covers_ragged_multistep_and_spec_rows(parts, monkeypatch):
    """Multi-step / spec-as-row compile surface (docs/ragged_attention.md):
    a ragged paged engine with speculation warms every (decode window,
    spec-row) launch variant through warmup.warm_ragged_variants — novel
    OVERLAPPING traffic (q=4 windows beside admission chunk rows, spec
    verify rows in pure-decode phases) then compiles NOTHING under the
    strict fence."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    monkeypatch.setenv("TPUSERVE_COMPILE_SENTRY", "strict")
    sentry = compile_sentry.get()
    sentry.reset(strict=True)
    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=128,
        prefill_buckets=[32, 64], eos_token_id=None, decode_steps=4,
        ragged_decode_steps=4, cache_mode="paged", page_size=16,
        scheduler="ragged", step_token_budget=32,
        speculation="ngram", spec_k=2, spec_ngram=2, pipeline_depth=1,
    )

    async def run():
        stats = await engine.warmup(full=True)
        assert stats["fenced"]
        # overlapped: a live decode stream rides q>1 windows while the
        # long prompt admits as chunk rows of the same launches
        a = GenRequest(
            prompt_ids=[5, 9, 2, 17, 5, 9, 2], max_new_tokens=24
        )
        a_task = asyncio.get_running_loop().create_task(_collect(engine, a))
        while a.produced < 2:
            await asyncio.sleep(0.005)
        await _collect(engine, GenRequest(
            prompt_ids=[(i * 7 + 3) % 250 + 1 for i in range(40)],
            max_new_tokens=6,
        ))
        await a_task
        await engine.wait_drained()
        ragged = engine.lifecycle_stats()["ragged"]
        assert ragged["step_rows"]["spec_verify"] >= 1
        assert ragged["tokens_per_launch"]["count"] >= 1
        assert sentry.post_fence_compiles == 0, sentry.stats()["events"][-5:]

    try:
        asyncio.run(run())
    finally:
        engine.stop()
        sentry.reset(strict=False)


def test_warmup_registry_covers_all_dispatch_paths_paged(parts, monkeypatch):
    """Full coverage certification: a paged+prefix-cache engine, the FULL
    warmup sweep, then novel random-length traffic with shared prefixes
    under the STRICT fence — zero post-fence compiles, proving
    WARMUP_COVERED means covered."""
    import random

    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    monkeypatch.setenv("TPUSERVE_COMPILE_SENTRY", "strict")
    sentry = compile_sentry.get()
    sentry.reset(strict=True)
    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=128,
        prefill_buckets=[32, 64], eos_token_id=None, decode_steps=1,
        cache_mode="paged", page_size=16, chunked_prefill_size=16,
        prefix_cache=64, prefix_block=16, num_pages=49,
        prefix_cache_pages=16, pipeline_depth=1,
    )

    async def run():
        stats = await engine.warmup(full=True)
        assert stats["fenced"]
        rng = random.Random(9)
        shared = [(5 * i + 3) % 250 + 1 for i in range(48)]
        for i in range(14):
            n = rng.randrange(1, 120)
            ids = [rng.randrange(1, 251) for _ in range(n)]
            if i % 3 == 0:
                ids = (shared + ids[:10])[:120]
            await _collect(engine, GenRequest(
                prompt_ids=ids, max_new_tokens=3
            ))
        await engine.wait_drained()
        assert sentry.post_fence_compiles == 0, sentry.stats()["events"][-5:]

    try:
        asyncio.run(run())
    finally:
        engine.stop()
        sentry.reset(strict=False)
