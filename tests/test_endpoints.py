import pytest

from clearml_serving_tpu.serving.endpoints import (
    CanaryEP,
    EndpointMetricLogging,
    MetricType,
    ModelEndpoint,
    ModelMonitoring,
)


def test_model_endpoint_roundtrip():
    ep = ModelEndpoint(
        engine_type="sklearn",
        serving_url="test_model_sklearn",
        model_id="abc",
        input_size=[1, 4],
        input_type="float32",
        input_name="features",
        output_size=[1],
        output_type="float32",
    )
    d = ep.as_dict()
    ep2 = ModelEndpoint.from_dict(d)
    assert ep2 == ep
    # scalar wrapping
    assert ep.input_type == ["float32"]
    assert ep.input_size == [[1, 4]]


def test_model_endpoint_bad_engine():
    with pytest.raises(ValueError):
        ModelEndpoint(engine_type="nope", serving_url="x")


def test_model_endpoint_bad_dtype():
    with pytest.raises(ValueError):
        ModelEndpoint(engine_type="custom", serving_url="x", input_type=["notatype"])


def test_model_endpoint_requires_url():
    with pytest.raises(ValueError):
        ModelEndpoint(engine_type="custom", serving_url="")


def test_multi_io_spec():
    ep = ModelEndpoint(
        engine_type="jax",
        serving_url="multi",
        input_size=[[3], [5, 5]],
        input_type=["float32", "int32"],
        input_name=["a", "b"],
    )
    assert ep.input_size == [[3], [5, 5]]
    assert len(ep.input_type) == 2


def test_canary_validation():
    with pytest.raises(ValueError):
        CanaryEP(endpoint="x", weights=[1], load_endpoints=["a"], load_endpoint_prefix="p")
    with pytest.raises(ValueError):
        CanaryEP(endpoint="x", weights=[1])
    c = CanaryEP(endpoint="x", weights=[0.9, 0.1], load_endpoints=["a/1", "a/2"])
    assert CanaryEP.from_dict(c.as_dict()) == c


def test_monitoring():
    m = ModelMonitoring(
        base_serving_url="auto_model",
        engine_type="jax",
        monitor_project="proj",
        max_versions=3,
    )
    assert ModelMonitoring.from_dict(m.as_dict()) == m


def test_metric_logging():
    ml = EndpointMetricLogging(
        endpoint="ep",
        log_frequency=0.5,
        metrics={
            "x0": {"type": "scalar", "buckets": [0, 1, 2]},
            "label": MetricType(type="enum", buckets=["cat", "dog"]),
            "out": {"type": "value"},
        },
    )
    d = ml.as_dict()
    ml2 = EndpointMetricLogging.from_dict(d)
    assert ml2.metrics["x0"].type == "scalar"
    assert ml2.metrics["label"].buckets == ["cat", "dog"]
    with pytest.raises(ValueError):
        MetricType(type="scalar", buckets=None)
    with pytest.raises(ValueError):
        EndpointMetricLogging(endpoint="ep", log_frequency=2.0)
