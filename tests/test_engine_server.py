import asyncio

import jax
import numpy as np
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.engine_server import protocol
from clearml_serving_tpu.engine_server.batcher import DynamicBatcher
from clearml_serving_tpu.engine_server.repo import EngineModelRepo
from clearml_serving_tpu.engine_server.server import make_server
from clearml_serving_tpu.engines import get_engine_cls
from clearml_serving_tpu.engines.jax_engine import save_bundle
from clearml_serving_tpu.serving.endpoints import ModelEndpoint
from clearml_serving_tpu.serving.model_request_processor import ModelRequestProcessor


def test_protocol_roundtrip():
    inputs = {
        "a": np.arange(6, dtype=np.float32).reshape(2, 3),
        "b": np.array([[1, 2]], dtype=np.int64),
    }
    data = protocol.encode_infer_request("m", inputs, version="2", output_names=["y"])
    req = protocol.decode_infer_request(data)
    assert req["model"] == "m" and req["version"] == "2" and req["outputs"] == ["y"]
    np.testing.assert_array_equal(req["inputs"]["a"], inputs["a"])
    assert req["inputs"]["b"].dtype == np.int64

    resp = protocol.decode_infer_response(
        protocol.encode_infer_response({"y": np.ones((2, 1), np.float32)})
    )
    assert resp["y"].shape == (2, 1)


def test_dynamic_batcher_batches_concurrent_requests():
    calls = []

    def run_batch(concat):
        calls.append(int(concat[0].shape[0]))
        return [concat[0] * 2]

    async def run():
        batcher = DynamicBatcher(run_batch, preferred_batch_size=4, max_queue_delay_us=50_000)
        outs = await asyncio.gather(
            *[batcher.infer([np.full((1, 2), i, np.float32)]) for i in range(4)]
        )
        return outs, batcher

    outs, batcher = asyncio.run(run())
    assert [o[0].tolist() for o in outs] == [[[2 * i, 2 * i]] for i in range(4)]
    # the four concurrent single-row requests must coalesce (not 4x batch=1)
    assert batcher.batches_executed < 4
    assert batcher.requests_served == 4


def test_dynamic_batcher_padding_observability():
    """The bucket-padding path's waste is counted: padded_rows_sum and the
    on_padding hook report bucket - real rows per executed batch."""
    buckets = [4, 8]

    def run_batch(concat):
        return [concat[0]]

    seen = []

    async def run():
        batcher = DynamicBatcher(
            run_batch, preferred_batch_size=4, max_queue_delay_us=1000,
            bucket_for=lambda rows: next((b for b in buckets if rows <= b), rows),
        )
        batcher.on_padding = lambda real, pad: seen.append((real, pad))
        await asyncio.gather(
            *[batcher.infer([np.zeros((1, 2), np.float32)]) for _ in range(3)]
        )
        return batcher

    batcher = asyncio.run(run())
    assert batcher.batch_size_sum == 3
    # 3 real rows pad to the 4-bucket (possibly split across batches; total
    # waste is bucket-sum minus real rows either way)
    assert batcher.padded_rows_sum == sum(p for _, p in seen)
    assert sum(r for r, _ in seen) == 3
    assert batcher.padded_rows_sum >= 1


def test_engine_metrics_padding_counter():
    """EngineMetrics wires the padding hook into the per-model
    engine_batch_rows_total{kind} counter next to the queue-delay series."""
    from prometheus_client import CollectorRegistry

    from clearml_serving_tpu.engine_server.server import EngineMetrics

    registry = CollectorRegistry()
    metrics = EngineMetrics(registry=registry)

    class _B:
        on_queue_delay = None
        on_padding = None

    b = _B()
    metrics.wire_batcher("m", b)
    b.on_padding(3, 5)
    assert registry.get_sample_value(
        "engine_batch_rows_total", {"model": "m", "kind": "real"}
    ) == 3
    assert registry.get_sample_value(
        "engine_batch_rows_total", {"model": "m", "kind": "padded"}
    ) == 5


def test_dynamic_batcher_error_propagates():
    def run_batch(concat):
        raise RuntimeError("boom")

    async def run():
        batcher = DynamicBatcher(run_batch, preferred_batch_size=2, max_queue_delay_us=100)
        with pytest.raises(RuntimeError):
            await batcher.infer([np.zeros((1, 2), np.float32)])

    asyncio.run(run())


@pytest.fixture()
def grpc_setup(state_root, tmp_path):
    """Control plane + jax_grpc endpoint + in-process engine server."""
    mrp = ModelRequestProcessor(state_root=str(state_root), force_create=True, name="es")
    bundle = models.build_model("mlp", {"in_dim": 4, "hidden": [8], "out_dim": 3})
    params = bundle.init(jax.random.PRNGKey(0))
    bdir = tmp_path / "bundle"
    save_bundle(bdir, "mlp", {"in_dim": 4, "hidden": [8], "out_dim": 3}, params)
    rec = mrp.registry.register("mlp", path=bdir, framework="jax")
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="jax_grpc",
            serving_url="grpc_mlp",
            model_id=rec.id,
            input_name="features",
            input_type="float32",
            input_size=[4],
            output_type="float32",
            output_name="logits",
        )
    )
    mrp.serialize()
    return mrp, bundle, params


def test_engine_server_end_to_end(grpc_setup, state_root):
    mrp, bundle, params = grpc_setup

    async def run():
        repo = EngineModelRepo(
            ModelRequestProcessor(service_id=mrp.get_id(), state_root=str(state_root))
        )
        assert repo.sync() == 1
        server, port = make_server(repo, 0)
        await server.start()
        try:
            # point the router config at the in-process server
            mrp.configure(external_engine_grpc_address="127.0.0.1:{}".format(port))
            client_mrp = ModelRequestProcessor(service_id=mrp.get_id(), state_root=str(state_root))
            client_mrp.deserialize(skip_sync=True)
            out = await client_mrp.process_request(
                "grpc_mlp", None, {"features": [[1, 2, 3, 4], [4, 3, 2, 1]]}
            )
            # unknown model -> 422-class EndpointModelError
            from clearml_serving_tpu.engines.base import EndpointModelError

            proc = client_mrp._engine_processor_lookup["grpc_mlp"]
            import dataclasses

            bad_ep = dataclasses.replace(proc.endpoint, serving_url="ghost")
            bad = get_engine_cls("jax_grpc")(bad_ep, service=client_mrp._service,
                                             registry=client_mrp.registry)
            try:
                await bad.process({"features": [[1, 2, 3, 4]]}, {}, None)
                raised = False
            except EndpointModelError:
                raised = True
            return out, raised
        finally:
            await server.stop(None)

    out, raised = asyncio.run(run())
    expected = bundle.apply(params, np.array([[1, 2, 3, 4], [4, 3, 2, 1]], np.float32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=1e-5)
    assert raised


def test_hot_swap_on_model_change(grpc_setup, state_root, tmp_path):
    mrp, bundle, params = grpc_setup
    repo = EngineModelRepo(
        ModelRequestProcessor(service_id=mrp.get_id(), state_root=str(state_root))
    )
    assert repo.sync() == 1
    assert repo.sync() == 0  # unchanged -> no reload

    # register a new model and repoint the endpoint at it
    params2 = bundle.init(jax.random.PRNGKey(7))
    bdir2 = tmp_path / "bundle2"
    save_bundle(bdir2, "mlp", {"in_dim": 4, "hidden": [8], "out_dim": 3}, params2)
    rec2 = mrp.registry.register("mlp-v2", path=bdir2, framework="jax")
    ep = mrp.list_endpoints()["grpc_mlp"]
    ep.model_id = rec2.id
    mrp.add_endpoint(ep)
    mrp.serialize()

    assert repo.sync() == 1  # hot swap
    x = np.ones((1, 4), np.float32)
    out = repo.get("grpc_mlp").run_batch([x])[0]
    np.testing.assert_allclose(out, np.asarray(bundle.apply(params2, x)), rtol=1e-5)

    # removing the endpoint drops the model
    mrp.remove_endpoint("grpc_mlp")
    mrp.serialize()
    repo.sync()
    assert repo.get("grpc_mlp") is None


def test_engine_metrics_histograms(grpc_setup, state_root):
    """The gRPC path must export latency/queue-delay histograms and
    outcome-labelled counters, not gauges only (VERDICT r1 weak #5)."""
    from prometheus_client import CollectorRegistry

    from clearml_serving_tpu.engine_server.server import EngineMetrics

    mrp, bundle, params = grpc_setup
    registry = CollectorRegistry()
    metrics = EngineMetrics(registry=registry)

    async def run():
        repo = EngineModelRepo(
            ModelRequestProcessor(service_id=mrp.get_id(), state_root=str(state_root))
        )
        repo.sync()
        server, port = make_server(repo, 0, metrics)
        await server.start()
        try:
            mrp.configure(external_engine_grpc_address="127.0.0.1:{}".format(port))
            client_mrp = ModelRequestProcessor(
                service_id=mrp.get_id(), state_root=str(state_root)
            )
            client_mrp.deserialize(skip_sync=True)
            for _ in range(3):
                await client_mrp.process_request(
                    "grpc_mlp", None, {"features": [[1, 2, 3, 4]]}
                )
        finally:
            await server.stop(None)

    asyncio.run(run())

    ok = registry.get_sample_value(
        "engine_infer_requests_total", {"model": "grpc_mlp", "outcome": "ok"}
    )
    assert ok == 3.0
    lat_count = registry.get_sample_value(
        "engine_infer_latency_seconds_count", {"model": "grpc_mlp"}
    )
    assert lat_count == 3.0
    qd_count = registry.get_sample_value(
        "engine_queue_delay_seconds_count", {"model": "grpc_mlp"}
    )
    assert qd_count == 3.0
