import asyncio

import jax
import numpy as np
import pytest

from clearml_serving_tpu.engines import get_engine_cls, load_engine_modules
from clearml_serving_tpu.engines.base import EndpointModelError
from clearml_serving_tpu.engines.jax_engine import bucket_for, save_bundle
from clearml_serving_tpu.serving.endpoints import ModelEndpoint
from clearml_serving_tpu.state import ModelRegistry, StateStore
from clearml_serving_tpu import models

CUSTOM_CODE = """
class Preprocess:
    def __init__(self):
        self.loaded = False
    def load(self, path):
        self.loaded = True
        return lambda x: [v * 2 for v in x]
    def preprocess(self, body, state, collect_fn):
        state["n"] = len(body["x"])
        return body["x"]
    def process(self, data, state, collect_fn):
        return self._model_fn(data) if hasattr(self, "_model_fn") else [v * 2 for v in data]
    def postprocess(self, data, state, collect_fn):
        return {"y": data, "n": state["n"]}
"""

ASYNC_CODE = """
import asyncio
class Preprocess:
    async def preprocess(self, body, state, collect_fn):
        await asyncio.sleep(0)
        return body["x"]
    async def process(self, data, state, collect_fn):
        return [v + 1 for v in data]
    def postprocess(self, data, state, collect_fn):
        return {"y": data}
"""


@pytest.fixture()
def service(state_root, tmp_path):
    store = StateStore(state_root)
    svc = store.create_service("svc")
    return svc, ModelRegistry(state_root), tmp_path


def _upload_code(svc, tmp_path, code, name="py_code_ep"):
    f = tmp_path / (name + ".py")
    f.write_text(code)
    svc.upload_artifact(name, f)
    return name


def test_custom_engine(service):
    svc, reg, tmp_path = service
    art = _upload_code(svc, tmp_path, CUSTOM_CODE)
    ep = ModelEndpoint(engine_type="custom", serving_url="c1", preprocess_artifact=art)
    proc = get_engine_cls("custom")(ep, service=svc, registry=reg, cache_dir=str(tmp_path / "cache"))
    state = {}
    data = proc.preprocess({"x": [1, 2, 3]}, state, None)
    out = proc.process(data, state, None)
    res = proc.postprocess(out, state, None)
    assert res == {"y": [2, 4, 6], "n": 3}


def test_custom_engine_requires_process(service):
    svc, reg, tmp_path = service
    ep = ModelEndpoint(engine_type="custom", serving_url="c2")
    proc = get_engine_cls("custom")(ep, service=svc, registry=reg, cache_dir=str(tmp_path / "cache"))
    with pytest.raises(EndpointModelError):
        proc.process([1], {}, None)


def test_hot_reload_on_artifact_change(service):
    svc, reg, tmp_path = service
    art = _upload_code(svc, tmp_path, CUSTOM_CODE)
    ep = ModelEndpoint(engine_type="custom", serving_url="c3", preprocess_artifact=art)
    proc = get_engine_cls("custom")(ep, service=svc, registry=reg, cache_dir=str(tmp_path / "cache"))
    assert proc.process([1], {}, None) == [2]
    # operator uploads new code under the same artifact name
    _upload_code(svc, tmp_path, CUSTOM_CODE.replace("v * 2", "v * 10"))
    proc._load_user_code()
    assert proc.process([1], {}, None) == [10]


def test_custom_async_engine(service):
    svc, reg, tmp_path = service
    art = _upload_code(svc, tmp_path, ASYNC_CODE, "py_code_async")
    ep = ModelEndpoint(engine_type="custom_async", serving_url="a1", preprocess_artifact=art)
    cls = get_engine_cls("custom_async")
    assert cls.is_process_async
    proc = cls(ep, service=svc, registry=reg, cache_dir=str(tmp_path / "cache"))

    async def run():
        state = {}
        data = await proc.preprocess({"x": [1, 2]}, state, None)
        out = await proc.process(data, state, None)
        return await proc.postprocess(out, state, None)

    assert asyncio.run(run()) == {"y": [2, 3]}


def test_sklearn_engine(service):
    svc, reg, tmp_path = service
    sklearn = pytest.importorskip("sklearn")
    import joblib
    from sklearn.linear_model import LogisticRegression

    X = np.array([[0.0], [1.0], [2.0], [3.0]])
    y = np.array([0, 0, 1, 1])
    model = LogisticRegression().fit(X, y)
    mf = tmp_path / "model.pkl"
    joblib.dump(model, mf)
    rec = reg.register("clf", path=mf, framework="sklearn")
    ep = ModelEndpoint(engine_type="sklearn", serving_url="s1", model_id=rec.id)
    proc = get_engine_cls("sklearn")(ep, service=svc, registry=reg, cache_dir=str(tmp_path / "cache"))
    out = proc.process(np.array([[0.0], [3.0]]), {}, None)
    assert out.tolist() == [0, 1]


def test_jax_engine_bundle(service):
    svc, reg, tmp_path = service
    bundle = models.build_model("mlp", {"in_dim": 4, "hidden": [8], "out_dim": 3})
    params = bundle.init(jax.random.PRNGKey(0))
    bdir = tmp_path / "bundle"
    save_bundle(bdir, "mlp", {"in_dim": 4, "hidden": [8], "out_dim": 3}, params)
    rec = reg.register("mlp-iris", path=bdir, framework="jax")
    ep = ModelEndpoint(
        engine_type="jax", serving_url="j1", model_id=rec.id,
        input_name="features", input_type="float32",
    )
    proc = get_engine_cls("jax")(ep, service=svc, registry=reg, cache_dir=str(tmp_path / "cache"))
    out = proc.process({"features": [[1, 2, 3, 4], [4, 3, 2, 1], [0, 0, 0, 0]]}, {}, None)
    # batch of 3 padded to bucket 4 internally, but only 3 rows returned
    assert np.asarray(out[0] if isinstance(out, list) else out).shape == (3, 3)
    res = proc.postprocess(out, {}, None)
    assert isinstance(res, list) and len(res) == 3

    # reference output must match direct apply
    direct = bundle.apply(params, np.array([[1, 2, 3, 4], [4, 3, 2, 1], [0, 0, 0, 0]], np.float32))
    np.testing.assert_allclose(np.asarray(res), np.asarray(direct), rtol=1e-5)


def test_bucketing():
    assert bucket_for(1, [1, 2, 4]) == 1
    assert bucket_for(3, [1, 2, 4]) == 4
    assert bucket_for(9, [1, 2, 4]) == 9  # beyond largest bucket: exact


def test_load_modules_noop():
    load_engine_modules()  # gated imports must never raise
