"""Example-suite walkthroughs as CI integration tests (the reference's
examples are its de-facto acceptance tests — SURVEY.md §2.13/§4).

Each test follows its readme end-to-end: train -> register -> endpoint ->
process_request with the suite's own Preprocess code. xgboost/lightgbm skip
when the library is not in the image (their engines gate the same way)."""

import asyncio
import importlib.util
import os
from pathlib import Path

import numpy as np
import pytest

from clearml_serving_tpu.serving.endpoints import ModelEndpoint
from clearml_serving_tpu.serving.model_request_processor import ModelRequestProcessor

EXAMPLES = Path(__file__).resolve().parent.parent / "examples"


def _run_example(state_root, tmp_path, suite, engine, train_artifact, body,
                 framework=None):
    """Execute examples/<suite>/train_model.py in tmp_path, register its
    artifact, serve it with the suite's preprocess.py, POST `body`."""
    spec = importlib.util.spec_from_file_location(
        "train_{}".format(suite), EXAMPLES / suite / "train_model.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        mod.main()
    finally:
        os.chdir(cwd)
    artifact = tmp_path / train_artifact
    assert artifact.exists()

    mrp = ModelRequestProcessor(
        state_root=str(state_root), force_create=True, name="ex-{}".format(suite)
    )
    rec = mrp.registry.register(
        "train {} model".format(suite), path=artifact, framework=framework or engine
    )
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type=engine,
            serving_url="test_model_{}".format(suite),
            model_id=rec.id,
        ),
        preprocess_code=str(EXAMPLES / suite / "preprocess.py"),
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    return asyncio.run(
        mrp.process_request("test_model_{}".format(suite), None, body)
    )


def test_ensemble_example(state_root, tmp_path):
    out = _run_example(
        state_root, tmp_path, "ensemble", "sklearn", "ensemble-model.pkl",
        {"x0": 1.2, "x1": -0.5}, framework="sklearn",
    )
    assert "y" in out and len(out["y"]) == 1
    assert np.isfinite(out["y"][0])


def test_xgboost_example(state_root, tmp_path):
    pytest.importorskip("xgboost")
    out = _run_example(
        state_root, tmp_path, "xgboost", "xgboost", "xgb_model.json",
        {"x0": 1, "x1": 2, "x2": 3, "x3": 4},
    )
    assert "y" in out


def test_lightgbm_example(state_root, tmp_path):
    pytest.importorskip("lightgbm")
    out = _run_example(
        state_root, tmp_path, "lightgbm", "lightgbm", "lgbm_model.txt",
        {"x0": 1, "x1": 2, "x2": 3, "x3": 4},
    )
    assert "y" in out and out["predicted"] in (0, 1, 2)


def test_sklearn_example(state_root, tmp_path):
    out = _run_example(
        state_root, tmp_path, "sklearn", "sklearn", "sklearn-model.pkl",
        {"x0": 5.1, "x1": 3.5, "x2": 1.4, "x3": 0.2},
    )
    assert "y" in out


def test_audio_example(state_root, tmp_path):
    """examples/audio walkthrough: build bundle -> register -> transcribe
    (multipart route shape is covered by tests/test_whisper.py; this runs
    the example's own bundle through the full register->serve flow)."""
    import base64
    import io
    import wave

    spec = importlib.util.spec_from_file_location(
        "make_bundle_audio", EXAMPLES / "audio" / "make_bundle.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    bdir = tmp_path / "whisper-bundle"
    mod.main(str(bdir))
    assert bdir.exists()

    mrp = ModelRequestProcessor(
        state_root=str(state_root), force_create=True, name="ex-audio"
    )
    rec = mrp.registry.register("whisper example", path=bdir, framework="jax")
    mrp.add_endpoint(
        ModelEndpoint(engine_type="llm", serving_url="speech", model_id=rec.id)
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)

    t = np.linspace(0, 0.5, 8000, endpoint=False)
    sig = (0.3 * np.sin(2 * np.pi * 220 * t)).astype(np.float32)
    buf = io.BytesIO()
    with wave.open(buf, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(16000)
        w.writeframes((sig * 32767).astype(np.int16).tobytes())

    out = asyncio.run(
        mrp.process_request(
            "speech",
            None,
            {"file": base64.b64encode(buf.getvalue()).decode(),
             "response_format": "verbose_json"},
            serve_type="v1/audio/transcriptions",
        )
    )
    assert isinstance(out["text"], str)
    assert out["segments"], "timestamp-capable bundle must yield segments"
