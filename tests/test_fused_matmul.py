"""w4a16 fused dequant-matmul tests (ops/fused_matmul.py, docs/w4a16.md):
interpret-mode kernel parity against the XLA ``dequantize_int4`` reference
across group sizes / K paddings / stacked trees, fallback routing for
ineligible shapes, int4 TP sharding guards, the offline checkpoint
quantizer, and end-to-end engine byte-identity under the armed sanitizer."""

import asyncio
import json
import os
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
from clearml_serving_tpu.ops.fused_matmul import (
    MAX_FUSED_ROWS,
    fused_int4_matmul,
    int4_kernel_unsupported_reason,
    int4_matmul_xla,
)
from clearml_serving_tpu.ops.quant import (
    detect_weight_quant,
    quantize_int4,
    quantize_llama_params,
)

REPO = Path(__file__).resolve().parent.parent


def _rand_wx(m, k, n, seed=0, scale=True):
    """Activation + weight at production-like magnitudes (dense init is
    normal * fan_in**-0.5), so the <=1e-5 absolute parity bound is measured
    on realistically scaled outputs."""
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(k, n)).astype(np.float32)
    if scale:
        w *= k ** -0.5
    x = rng.normal(size=(m, k)).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(w)


# -- kernel parity (interpret mode runs the Pallas path on any backend) ------

PARITY_GRID = [
    # (m, k, n, group): single group, exact multiples, coarse/fine groups,
    # K below the group size (per-channel fallback grouping), non-128 N,
    # and the 3-D activation case
    (1, 128, 128, 128),
    (2, 256, 256, 128),
    (3, 256, 384, 64),
    (8, 512, 1024, 128),
    (4, 96, 128, 128),     # K % group != 0 -> one per-channel group
    (5, 64, 130, 64),      # N not lane-aligned (interpret-only shape)
    (16, 384, 512, 192),
]


@pytest.mark.parametrize("m,k,n,group", PARITY_GRID)
def test_kernel_interpret_parity(m, k, n, group):
    x, w = _rand_wx(m, k, n, seed=m + k + n)
    q, s = quantize_int4(w, group=group)
    assert int4_kernel_unsupported_reason(x, q, s, interpret=True) is None
    ref = int4_matmul_xla(x, q, s, jnp.float32)
    out = fused_int4_matmul(x, q, s, dtype=jnp.float32, interpret=True)
    assert out.shape == ref.shape and out.dtype == ref.dtype
    assert float(jnp.max(jnp.abs(out - ref))) <= 1e-5


def test_kernel_interpret_parity_3d_activations():
    """[B, S, K] activations (speculative-verify shape) flatten to rows and
    reshape back."""
    x, w = _rand_wx(6, 256, 256, seed=7)
    x3 = x.reshape(2, 3, 256)
    q, s = quantize_int4(w, group=128)
    ref = int4_matmul_xla(x3, q, s, jnp.float32)
    out = fused_int4_matmul(x3, q, s, dtype=jnp.float32, interpret=True)
    assert out.shape == ref.shape
    assert float(jnp.max(jnp.abs(out - ref))) <= 1e-5


def test_kernel_interpret_parity_bf16():
    x, w = _rand_wx(4, 256, 256, seed=11)
    x = x.astype(jnp.bfloat16)
    q, s = quantize_int4(w, group=128)
    ref = int4_matmul_xla(x, q, s, jnp.bfloat16)
    out = fused_int4_matmul(x, q, s, dtype=jnp.bfloat16, interpret=True)
    assert out.dtype == jnp.bfloat16
    # bf16 epsilon-scale agreement (both paths accumulate in f32; the
    # operand rounding differs)
    assert float(jnp.max(jnp.abs(
        out.astype(jnp.float32) - ref.astype(jnp.float32)
    ))) <= 0.05


def test_kernel_parity_stacked_tree_slices():
    """Scanned trees hit the kernel one layer at a time ([L, K//2, N]
    sliced inside lax.scan): each slice must match the reference dequant of
    the stacked quantization."""
    rng = np.random.default_rng(3)
    L, k, n = 3, 256, 256
    w = jnp.asarray(rng.normal(size=(L, k, n)).astype(np.float32) * k ** -0.5)
    q, s = quantize_int4(w, group=128)
    x = jnp.asarray(rng.normal(size=(2, k)).astype(np.float32))
    from clearml_serving_tpu.ops.quant import dequantize_int4

    dense = dequantize_int4(q, s, jnp.float32)          # [L, K, N]
    for layer in range(L):
        out = fused_int4_matmul(
            x, q[layer], s[layer], dtype=jnp.float32, interpret=True
        )
        ref = x @ dense[layer]
        assert float(jnp.max(jnp.abs(out - ref))) <= 1e-5


# -- routing matrix ----------------------------------------------------------

def test_unsupported_reason_matrix():
    x, w = _rand_wx(2, 256, 256)
    q, s = quantize_int4(w, group=128)
    ok = lambda *a, **kw: int4_kernel_unsupported_reason(*a, **kw)

    assert ok(x, q, s, interpret=True) is None
    assert ok(x, q, s) is None  # hardware-aligned: 2 groups of 128, N=256

    # stacked (3-D) weights route per layer, never whole
    q3, s3 = quantize_int4(jnp.stack([w, w]), group=128)
    assert "2-D" in ok(x, q3, s3, interpret=True)

    # odd group size: nibble pairs straddle the group boundary
    q_odd, s_odd = quantize_int4(
        jnp.asarray(np.random.default_rng(0).normal(size=(6, 128)).astype(np.float32)),
        group=3,
    )
    x6 = jnp.ones((2, 6), jnp.float32)
    assert "odd group" in ok(x6, q_odd, s_odd, interpret=True)

    # prefill-shaped M falls back to the XLA path
    big = jnp.ones((MAX_FUSED_ROWS + 1, 256), jnp.float32)
    assert "rows exceed" in ok(big, q, s, interpret=True)

    # hardware-only gates: lane/sublane misalignment (fine in interpret)
    xs, ws = _rand_wx(2, 256, 130)
    qs, ss = quantize_int4(ws, group=128)
    assert ok(xs, qs, ss, interpret=True) is None
    assert "lane-tileable" in ok(xs, qs, ss)
    xg, wg = _rand_wx(2, 96, 128)   # single 96-row group -> 48 packed rows
    qg, sg = quantize_int4(wg, group=96)
    assert ok(xg, qg, sg, interpret=True) is None
    assert "sublane" in ok(xg, qg, sg)

    # int-typed activations are rejected outright
    assert "floating" in ok(x.astype(jnp.int32), q, s, interpret=True)


def test_fallback_shapes_match_reference_exactly():
    """Ineligible shapes must return the byte-identical historical XLA
    expression — routing through the wrapper is a no-op for them."""
    x, w = _rand_wx(2, 6, 10)
    q, s = quantize_int4(w, group=3)  # odd group -> fallback even in interpret
    out = fused_int4_matmul(x, q, s, dtype=jnp.float32, interpret=True)
    ref = int4_matmul_xla(x, q, s, jnp.float32)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    big = jnp.ones((MAX_FUSED_ROWS + 8, 6), jnp.float32)
    out = fused_int4_matmul(big, q, s, dtype=jnp.float32, interpret=True)
    ref = int4_matmul_xla(big, q, s, jnp.float32)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


# -- model-level routing -----------------------------------------------------

CFG = {"preset": "llama-tiny", "dtype": "float32"}


def test_scanned_vs_unscanned_int4_logits_match():
    """The _mm routing serves both tree layouts: a scanned [L, ...] int4
    tree and the per-layer list layout produce matching logits (the fused
    wrapper sees identical per-layer 2-D slices either way)."""
    bundle_scan = models.build_model("llama", dict(CFG, scan_layers=True))
    bundle_list = models.build_model("llama", CFG)
    params = bundle_list.init(jax.random.PRNGKey(0))
    q_list = quantize_llama_params(params, bits=4)
    q_scan = bundle_scan.prepare_params(q_list)
    tokens = jnp.asarray([[5, 9, 2, 17]], jnp.int32)
    a = bundle_scan.apply(q_scan, tokens)
    b = bundle_list.apply(q_list, tokens)
    np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5
    )


def test_int4_fused_flag_streams_byte_identical():
    """cfg int4_fused=False (the bench A/B arm) and the default routing
    produce byte-identical greedy streams off-TPU: the wrapper's fallback
    IS the historical expression."""
    bundle = models.build_model("llama", CFG)
    bundle_off = models.build_model("llama", dict(CFG, int4_fused=False))
    params = bundle.init(jax.random.PRNGKey(0))
    qparams = quantize_llama_params(params, bits=4)

    def gen(b):
        engine = LLMEngineCore(
            b, qparams, max_batch=2, max_seq_len=96,
            prefill_buckets=[16, 32], eos_token_id=None, decode_steps=2,
        )

        async def run():
            req = GenRequest(prompt_ids=[256, 5, 6, 7], max_new_tokens=8)
            out = [t async for t in engine.generate(req)]
            await engine.wait_drained()
            return out

        out = asyncio.run(run())
        engine.stop()
        return out

    assert gen(bundle) == gen(bundle_off)


def test_paged_int4_engine_byte_identical_to_dense_under_sanitizer(monkeypatch):
    """End-to-end: the paged int4 engine streams byte-identically to the
    dense int4 engine under the armed KV sanitizer — weight quantization is
    orthogonal to the KV backend, and the fused-route gate must not perturb
    either path."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    bundle = models.build_model("llama", CFG)
    params = bundle.init(jax.random.PRNGKey(0))

    def gen(cache_mode):
        engine = LLMEngineCore(
            bundle, params, max_batch=2, max_seq_len=96,
            prefill_buckets=[16, 32], eos_token_id=None, decode_steps=2,
            weight_quant="int4", cache_mode=cache_mode,
        )

        async def run():
            req = GenRequest(prompt_ids=[5, 9, 2, 17, 33], max_new_tokens=8)
            out = [t async for t in engine.generate(req)]
            await engine.wait_drained()
            return out

        out = asyncio.run(run())
        if cache_mode == "paged":
            pool = engine.paged_cache.pool
            assert pool.free_pages == pool.num_pages - 1  # no leaked pages
        engine.stop()
        return out

    dense = gen("dense")
    paged = gen("paged")
    assert dense == paged and len(dense) == 8


def test_engine_weight_quant_alias_and_conflict():
    bundle = models.build_model("llama", CFG)
    params = bundle.init(jax.random.PRNGKey(0))
    kw = dict(max_batch=1, max_seq_len=64, prefill_buckets=[16],
              eos_token_id=None)
    with pytest.raises(ValueError, match="conflicts"):
        LLMEngineCore(bundle, params, weight_quant="int4", quantize="int8",
                      **kw)
    with pytest.raises(ValueError, match="weight_quant"):
        LLMEngineCore(bundle, params, weight_quant="int3", **kw)
    # an already-packed tree + a redundant matching knob is a no-op; a
    # MISMATCHED knob is a clear error, not an AttributeError deep in
    # quantize_int4 (the offline bundle keeps its format either way)
    packed = quantize_llama_params(params, bits=4)
    redundant = LLMEngineCore(bundle, packed, weight_quant="int4", **kw)
    assert redundant.weight_quant == "int4"
    redundant.stop()
    with pytest.raises(ValueError, match="already int4-quantized"):
        LLMEngineCore(bundle, packed, weight_quant="int8", **kw)
    engine = LLMEngineCore(bundle, params, weight_quant="int4", **kw)
    assert engine.weight_quant == "int4"
    stats = engine.lifecycle_stats()["weights"]
    assert stats["quant"] == "int4"
    # packed tree is smaller than the f32 source
    assert 0 < stats["bytes"] < sum(
        leaf.nbytes for leaf in jax.tree.leaves(params)
    )
    engine.stop()


# -- TP sharding guard -------------------------------------------------------

def test_sharding_rejects_tp_that_splits_int4_groups():
    """parallel/sharding.py: a TP degree whose shard boundary lands inside
    a quantization group must raise naming the knob, not silently shard
    _q4 against replicated (wrong) scale rows."""
    from clearml_serving_tpu.parallel import (
        llama_quantized_param_sharding, make_mesh,
    )

    mesh = make_mesh({"tp": 4, "dp": 2})
    # w_down: ffn_dim=384 input rows -> 3 groups of 128; tp=4 splits them
    cfg = dict(CFG, dim=128, ffn_dim=384, n_heads=4, n_kv_heads=2,
               vocab_size=256)
    bundle = models.build_model("llama", cfg)
    params = bundle.init(jax.random.PRNGKey(0))
    qparams = quantize_llama_params(params, bits=4)
    with pytest.raises(ValueError) as err:
        llama_quantized_param_sharding(
            mesh, qparams, n_kv_heads=2, n_heads=4
        )
    msg = str(err.value)
    assert "quantization groups" in msg and "mesh.tp" in msg

    # aligned degrees still shard: ffn 512 -> 4 groups, tp=2 divides all
    cfg_ok = dict(cfg, ffn_dim=512)
    bundle_ok = models.build_model("llama", cfg_ok)
    q_ok = quantize_llama_params(
        bundle_ok.init(jax.random.PRNGKey(0)), bits=4
    )
    mesh2 = make_mesh({"tp": 2, "dp": 4})
    specs = llama_quantized_param_sharding(
        mesh2, q_ok, n_kv_heads=2, n_heads=4
    )
    leaf = specs["layers"][0]["w_down"]
    assert set(leaf) == {"_q4", "_scale4"}
    down_spec = list(leaf["_scale4"].spec)
    down_spec += [None] * (2 - len(down_spec))
    assert down_spec[-2] == "tp"  # group axis sharded WITH the weight rows

    # the single-group (K < group) fallback replicates the scale row
    # instead of raising — one per-channel row serves every shard exactly
    tiny = models.build_model("llama", CFG)  # dim 64 -> 1 group everywhere
    tq = quantize_llama_params(tiny.init(jax.random.PRNGKey(0)), bits=4)
    specs = llama_quantized_param_sharding(
        make_mesh({"tp": 4, "dp": 2}), tq, n_kv_heads=2, n_heads=4
    )
    scale_spec = specs["layers"][0]["w_gate"]["_scale4"].spec
    padded = list(scale_spec) + [None] * (2 - len(scale_spec))
    assert padded[-2] is None  # input (group) axis replicated


# -- offline checkpoint quantizer --------------------------------------------

def test_quantize_ckpt_roundtrip(tmp_path):
    """scripts/quantize_ckpt.py converts a bf16 bundle offline; loading the
    output serves byte-identically to quantize-at-load (quantize_int4 is
    deterministic), the engine detects the packed tree, and re-quantizing
    is refused."""
    from clearml_serving_tpu.engines.jax_engine import load_bundle, save_bundle

    bundle = models.build_model("llama", CFG)
    params = bundle.init(jax.random.PRNGKey(0))
    src, dst = tmp_path / "src", tmp_path / "dst"
    save_bundle(src, "llama", CFG, params)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "quantize_ckpt.py"),
         str(src), str(dst), "--bits", "4"],
        capture_output=True, text=True, env=env, cwd=str(REPO),
    )
    assert out.returncode == 0, out.stderr[-1000:]

    qbundle, qparams = load_bundle(dst)
    assert detect_weight_quant(qparams) == "int4"

    def gen(b, p, **kw):
        engine = LLMEngineCore(
            b, p, max_batch=2, max_seq_len=96, prefill_buckets=[16, 32],
            eos_token_id=None, decode_steps=2, **kw,
        )

        async def run():
            req = GenRequest(prompt_ids=[256, 5, 6, 7], max_new_tokens=6)
            res = [t async for t in engine.generate(req)]
            await engine.wait_drained()
            return res

        res = asyncio.run(run())
        offline_quant = engine.weight_quant
        engine.stop()
        return res, offline_quant

    offline, wq = gen(qbundle, qparams)
    assert wq == "int4"  # detected from the packed tree, no knob needed
    online, _ = gen(bundle, params, weight_quant="int4")
    assert offline == online

    # double quantization refused with a clear message
    out2 = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "quantize_ckpt.py"),
         str(dst), str(tmp_path / "dst2")],
        capture_output=True, text=True, env=env, cwd=str(REPO),
    )
    assert out2.returncode != 0 and "already" in out2.stderr


# -- committed CPU smoke artifact --------------------------------------------

def test_int4_ab_artifact_schema():
    """benchmarks/INT4_AB_cpu.json (committed by ``bench.py --int4-ab``)
    carries the acceptance headline: int4 quantized-leaf bytes ~0.5x int8 /
    ~0.25x bf16-equivalent, byte-identical fused-vs-XLA streams, and
    interpret-mode kernel parity <= 1e-5."""
    path = REPO / "benchmarks" / "INT4_AB_cpu.json"
    row = json.loads(path.read_text())
    assert row["metric"] == "llm_int4_weight_ab_cpusmoke"
    assert row["identical_streams_fused_vs_xla"] is True
    assert 0.4 <= row["int4_vs_int8_quant_bytes"] <= 0.6
    assert 0.2 <= row["int4_vs_bf16_quant_bytes"] <= 0.3
    assert row["pallas_interpret_maxdiff"] <= 1e-5
    for arm in ("int4_fused", "int4_xla", "int8"):
        assert row["step_ms"][arm] > 0
        assert row["tok_s"][arm] > 0
