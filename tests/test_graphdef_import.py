"""Native GraphDef importer: frozen TF graphs -> jitted JAX executables.

No tensorflow in the image, so fixtures are built with a minimal protobuf
ENCODER (wire format is public spec) — the same bytes TF would serialize
for a frozen inference graph — and numerics verify against numpy.
"""

import struct

import jax
import numpy as np
import pytest

from clearml_serving_tpu.engines.importers.graphdef_import import (
    load_graphdef_bundle,
    parse_graphdef,
)

# -- minimal protobuf writer ---------------------------------------------------


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _len_field(num: int, payload: bytes) -> bytes:
    return _varint(num << 3 | 2) + _varint(len(payload)) + payload


def _varint_field(num: int, value: int) -> bytes:
    if value < 0:
        value += 1 << 64
    return _varint(num << 3) + _varint(value)


def _f32_field(num: int, value: float) -> bytes:
    return _varint(num << 3 | 5) + struct.pack("<f", value)


def _shape(dims) -> bytes:
    return b"".join(_len_field(2, _varint_field(1, d)) for d in dims)


def _tensor(arr: np.ndarray) -> bytes:
    dtype = {"float32": 1, "int32": 3, "int64": 9}[arr.dtype.name]
    return (
        _varint_field(1, dtype)
        + _len_field(2, _shape(arr.shape))
        + _len_field(4, arr.tobytes())
    )


def _attr(key: str, value: bytes) -> bytes:
    return _len_field(5, _len_field(1, key.encode()) + _len_field(2, value))


def attr_tensor(key, arr):
    return _attr(key, _len_field(8, _tensor(np.ascontiguousarray(arr))))


def attr_type(key, enum):
    return _attr(key, _varint_field(6, enum))


def attr_shape(key, dims):
    return _attr(key, _len_field(7, _shape(dims)))


def attr_s(key, s):
    return _attr(key, _len_field(2, s.encode()))


def attr_i(key, v):
    return _attr(key, _varint_field(3, v))


def attr_f(key, v):
    return _attr(key, _f32_field(4, v))


def attr_ilist(key, vals):
    lst = b"".join(_varint_field(3, v) for v in vals)
    return _attr(key, _len_field(1, lst))


def node(name, op, inputs=(), *attrs):
    body = _len_field(1, name.encode()) + _len_field(2, op.encode())
    for ref in inputs:
        body += _len_field(3, ref.encode())
    return body + b"".join(attrs)


def graphdef(*nodes) -> bytes:
    return b"".join(_len_field(1, n) for n in nodes)


def const(name, arr):
    return node(name, "Const", (), attr_tensor("value", arr))


# -- fixtures -----------------------------------------------------------------


def _mlp_graph(rng):
    w1 = rng.randn(4, 32).astype(np.float32)
    b1 = rng.randn(32).astype(np.float32)
    w2 = rng.randn(32, 3).astype(np.float32)
    b2 = rng.randn(3).astype(np.float32)
    gd = graphdef(
        node("x", "Placeholder", (), attr_type("dtype", 1), attr_shape("shape", [-1, 4])),
        const("w1", w1),
        const("b1", b1),
        const("w2", w2),
        const("b2", b2),
        node("mm1", "MatMul", ("x", "w1")),
        node("h1", "BiasAdd", ("mm1", "b1")),
        node("relu", "Relu", ("h1",)),
        node("mm2", "MatMul", ("relu", "w2")),
        node("logits", "BiasAdd", ("mm2", "b2")),
        node("probs", "Softmax", ("logits",)),
    )
    weights = (w1, b1, w2, b2)
    return gd, weights


def _mlp_ref(x, w1, b1, w2, b2):
    h = np.maximum(x @ w1 + b1, 0)
    logits = h @ w2 + b2
    e = np.exp(logits - logits.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def test_mlp_graph_matches_numpy(tmp_path):
    rng = np.random.RandomState(0)
    gd, (w1, b1, w2, b2) = _mlp_graph(rng)
    f = tmp_path / "model.graphdef"
    f.write_bytes(gd)
    bundle, params = load_graphdef_bundle(f)
    assert bundle.input_names == ["x"]
    assert bundle.output_names == ["probs"]
    assert bundle.config["input_shapes"]["x"] == [-1, 4]
    x = rng.randn(5, 4).astype(np.float32)
    out = jax.jit(bundle.apply)(params, x)
    np.testing.assert_allclose(
        np.asarray(out), _mlp_ref(x, w1, b1, w2, b2), rtol=1e-5, atol=1e-5
    )
    # the big weights became device params; small consts stayed host-side
    assert set(params) == {"w1", "w2"}


def test_cnn_graph_matches_reference(tmp_path):
    """Conv2D(SAME) -> BiasAdd -> Relu -> MaxPool -> Mean -> MatMul."""
    rng = np.random.RandomState(1)
    w = rng.randn(3, 3, 2, 4).astype(np.float32)   # HWIO
    b = rng.randn(4).astype(np.float32)
    wd = rng.randn(4, 3).astype(np.float32)
    gd = graphdef(
        node("img", "Placeholder", (), attr_type("dtype", 1),
             attr_shape("shape", [-1, 8, 8, 2])),
        const("w", w),
        const("b", b),
        const("wd", wd),
        const("axes", np.asarray([1, 2], np.int32)),
        node("conv", "Conv2D", ("img", "w"), attr_s("padding", "SAME"),
             attr_ilist("strides", [1, 1, 1, 1]), attr_s("data_format", "NHWC")),
        node("biased", "BiasAdd", ("conv", "b")),
        node("act", "Relu", ("biased",)),
        node("pool", "MaxPool", ("act",), attr_s("padding", "VALID"),
             attr_ilist("ksize", [1, 2, 2, 1]), attr_ilist("strides", [1, 2, 2, 1])),
        node("gap", "Mean", ("pool", "axes"), attr_i("keep_dims", 0)),
        node("out", "MatMul", ("gap", "wd")),
    )
    f = tmp_path / "model.pb"
    f.write_bytes(gd)
    bundle, params = load_graphdef_bundle(f)
    x = rng.randn(2, 8, 8, 2).astype(np.float32)
    out = np.asarray(jax.jit(bundle.apply)(params, x))

    # numpy reference
    from jax import lax
    import jax.numpy as jnp

    xp = jnp.pad(jnp.asarray(x), [(0, 0), (1, 1), (1, 1), (0, 0)])
    ref_conv = np.zeros((2, 8, 8, 4), np.float32)
    for i in range(8):
        for j in range(8):
            patch = np.asarray(xp)[:, i : i + 3, j : j + 3, :]
            ref_conv[:, i, j, :] = np.einsum("bhwc,hwco->bo", patch, w)
    act = np.maximum(ref_conv + b, 0)
    pool = act.reshape(2, 4, 2, 4, 2, 4).max(axis=(2, 4))
    gap = pool.mean(axis=(1, 2))
    ref = gap @ wd
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_savedmodel_wrapper_and_multi_output(tmp_path):
    """TF1 SavedModel wrapper parses, FusedBatchNorm's :0 output resolves."""
    rng = np.random.RandomState(2)
    scale = rng.rand(4).astype(np.float32) + 0.5
    offset = rng.randn(4).astype(np.float32)
    mean = rng.randn(4).astype(np.float32)
    var = rng.rand(4).astype(np.float32) + 0.5
    gd = graphdef(
        node("x", "Placeholder", (), attr_type("dtype", 1),
             attr_shape("shape", [-1, 2, 2, 4])),
        const("scale", scale),
        const("offset", offset),
        const("mean", mean),
        const("var", var),
        node("bn", "FusedBatchNormV3", ("x", "scale", "offset", "mean", "var"),
             attr_f("epsilon", 1e-3)),
        node("y", "Relu", ("bn:0",)),
    )
    # wrap: SavedModel{ meta_graphs{ graph_def{...} } }
    saved = _len_field(2, _len_field(2, gd))
    f = tmp_path / "saved_model.pb"
    f.write_bytes(saved)
    nodes = parse_graphdef(f.read_bytes())
    assert [n["name"] for n in nodes][0] == "x"
    bundle, params = load_graphdef_bundle(f)
    x = rng.randn(3, 2, 2, 4).astype(np.float32)
    out = np.asarray(jax.jit(bundle.apply)(params, x))
    ref = np.maximum((x - mean) / np.sqrt(var + 1e-3) * scale + offset, 0)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_unsupported_op_reports_recipe(tmp_path):
    gd = graphdef(
        node("x", "Placeholder", (), attr_type("dtype", 1)),
        node("w", "WeirdCustomOp", ("x",)),
    )
    f = tmp_path / "model.graphdef"
    f.write_bytes(gd)
    bundle, params = load_graphdef_bundle(f)
    with pytest.raises(ValueError, match="tf2onnx"):
        bundle.apply(params, np.zeros((1, 2), np.float32))


def test_served_through_jax_engine(tmp_path, state_root):
    """A .graphdef model registers and serves like any other import format."""
    import asyncio

    from clearml_serving_tpu.serving.endpoints import ModelEndpoint
    from clearml_serving_tpu.serving.model_request_processor import (
        ModelRequestProcessor,
    )

    rng = np.random.RandomState(3)
    gd, weights = _mlp_graph(rng)
    f = tmp_path / "model.graphdef"
    f.write_bytes(gd)

    mrp = ModelRequestProcessor(state_root=str(state_root), force_create=True, name="gd")
    rec = mrp.registry.register("tf mlp", path=f, framework="tensorflow")
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="jax", serving_url="tf_mlp", model_id=rec.id,
            input_size=[[4]], input_type=["float32"], input_name=["x"],
            output_size=[[3]], output_type=["float32"], output_name=["probs"],
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    x = rng.randn(2, 4).astype(np.float32)
    out = asyncio.run(mrp.process_request("tf_mlp", None, {"x": x.tolist()}))
    got = np.asarray(out["probs"] if isinstance(out, dict) else out)
    np.testing.assert_allclose(got, _mlp_ref(x, *weights), rtol=1e-4, atol=1e-4)


def test_real_savedmodel_leads_with_schema_version(tmp_path):
    """Real TF exporters always serialize saved_model_schema_version=1 first;
    the importer must not misparse that varint as a GraphDef node."""
    rng = np.random.RandomState(4)
    gd, weights = _mlp_graph(rng)
    saved = _varint_field(1, 1) + _len_field(2, _len_field(2, gd))
    f = tmp_path / "saved_model.pb"
    f.write_bytes(saved)
    bundle, params = load_graphdef_bundle(f)
    x = rng.randn(2, 4).astype(np.float32)
    out = np.asarray(jax.jit(bundle.apply)(params, x))
    np.testing.assert_allclose(out, _mlp_ref(x, *weights), rtol=1e-5, atol=1e-5)


def test_dead_nodes_do_not_break_import(tmp_path):
    """Frozen graphs keep Saver/init leftovers: dead unsupported ops and
    non-numeric consts outside the output's ancestry must not fail the
    load, nor leak into the auto-detected outputs."""
    rng = np.random.RandomState(5)
    gd, weights = _mlp_graph(rng)
    extras = graphdef(
        # dead unsupported op chain (never feeds "probs")
        node("save/Const", "Const", (), attr_tensor("value", np.asarray([7], np.int32))),
        node("save/SaveV2", "SaveV2", ("save/Const",)),
        # dead string const: unsupported dtype enum 7 must not parse eagerly
        node("labels", "Const", (),
             _attr("value", _len_field(8, _varint_field(1, 7) + _len_field(8, b"cat")))),
    )
    f = tmp_path / "model.graphdef"
    f.write_bytes(gd + extras)
    bundle, params = load_graphdef_bundle(f)
    assert bundle.output_names == ["probs"]  # leftovers not outputs
    x = rng.randn(2, 4).astype(np.float32)
    out = np.asarray(jax.jit(bundle.apply)(params, x))
    np.testing.assert_allclose(out, _mlp_ref(x, *weights), rtol=1e-5, atol=1e-5)


def test_input_arity_validated(tmp_path):
    rng = np.random.RandomState(6)
    gd, _ = _mlp_graph(rng)
    f = tmp_path / "model.graphdef"
    f.write_bytes(gd)
    bundle, params = load_graphdef_bundle(f)
    with pytest.raises(ValueError, match="expects 1 inputs"):
        bundle.apply(params)
