"""Guided (grammar-constrained) decoding: regex/JSON-schema compiler, token
DFA tables, engine enforcement under sampling, OpenAI response_format route.

Reference surface: vLLM's guided decoding reaches the reference through
request bodies forwarded by clearml_serving/serving/preprocess_service.py;
here the constraint compiles to on-device tables (llm/guided.py)."""

import asyncio
import json

import jax
import numpy as np
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
from clearml_serving_tpu.llm.guided import (
    ByteDFA,
    GuidedSpec,
    RegexError,
    TokenDFA,
    compile_guided,
    json_schema_to_regex,
    json_value_regex,
    token_byte_table,
)
from clearml_serving_tpu.llm.tokenizer import ByteTokenizer


# ------------------------------------------------------------ compiler

@pytest.mark.parametrize(
    "pattern,accept,reject",
    [
        ("(yes|no|maybe)", ["yes", "no", "maybe"], ["ye", "nope", ""]),
        (r"-?(0|[1-9][0-9]*)", ["0", "-7", "142"], ["01", "-", "+1"]),
        (r"[a-c]{2,3}x", ["abx", "cabx"], ["ax", "abcax", "abX"]),
        (r"a+b*c?", ["a", "aab", "abc", "ac"], ["", "b", "cc"]),
        (r"\d\d:\d\d", ["09:30"], ["9:30", "09-30"]),
        (r"[^0-9]+", ["abc", "x!"], ["a1", "7"]),
    ],
)
def test_regex_dfa(pattern, accept, reject):
    dfa = ByteDFA.from_regex(pattern)
    for s in accept:
        assert dfa.matches(s.encode()), (pattern, s)
    for s in reject:
        assert not dfa.matches(s.encode()), (pattern, s)


def test_regex_errors():
    for bad in ["(a", "a)", "[a", "*a", "a{2"]:
        with pytest.raises(RegexError):
            ByteDFA.from_regex(bad)


def test_regex_anchors_and_complement_escapes():
    """ADVICE r3: a leading '^' / trailing '$' are no-ops under implicit
    whole-string anchoring (vLLM users write r'\\d+$'); everything else
    outside the subset must fail pre-flight instead of mis-compiling into
    literal characters."""
    dfa = ByteDFA.from_regex(r"^\d+$")
    assert dfa.matches(b"42")
    assert not dfa.matches(b"42$")  # '$' is NOT forced into the output
    assert not dfa.matches(b"^42")
    dfa = ByteDFA.from_regex(r"\D+")
    assert dfa.matches(b"ab!")
    assert not dfa.matches(b"a1")
    assert ByteDFA.from_regex(r"[\S]+").matches(b"x.y")
    assert not ByteDFA.from_regex(r"\W").matches(b"a")
    for bad in [r"\bword\b", r"a\Z", r"\Aa", r"(a)\1", "a$b", "a^b",
                r"[\b]", r"\p{L}"]:
        with pytest.raises(RegexError):
            ByteDFA.from_regex(bad)


def test_json_schema_absent_required_means_all_optional():
    """ADVICE r3: JSON Schema semantics — absent `required` requires
    nothing (was: everything)."""
    schema = {
        "type": "object",
        "properties": {"x": {"type": "integer"}, "y": {"type": "integer"}},
    }
    dfa = ByteDFA.from_regex(json_schema_to_regex(schema))
    for ok in [{}, {"x": 1}, {"y": 2}, {"x": 1, "y": 2}]:
        assert dfa.matches(json.dumps(ok, separators=(",", ":")).encode()), ok


def test_json_schema_many_optional_properties_stays_polynomial():
    """r4 code review: the all-optional encoding must not be exponential —
    a ~28-property schema used to build a multi-GB regex in pre-flight."""
    n = 24
    schema = {
        "type": "object",
        "properties": {"p{}".format(i): {"type": "integer"} for i in range(n)},
    }
    pattern = json_schema_to_regex(schema)
    assert len(pattern) < 200_000
    dfa = ByteDFA.from_regex(pattern, max_states=16384)
    for ok in [{}, {"p0": 1}, {"p3": 1, "p17": 2}, {"p23": 9}]:
        assert dfa.matches(json.dumps(ok, separators=(",", ":")).encode()), ok
    assert not dfa.matches(b'{"p1":1"p2":2}')   # missing comma
    assert not dfa.matches(b'{"p2":2,"p1":1}')  # out of declaration order


def test_json_schema_regex_roundtrip():
    schema = {
        "type": "object",
        "properties": {
            "name": {"type": "string"},
            "age": {"type": "integer"},
            "tags": {"type": "array", "items": {"type": "string"}, "maxItems": 3},
            "kind": {"enum": ["cat", "dog"]},
        },
        "required": ["name", "age", "kind"],
    }
    dfa = ByteDFA.from_regex(json_schema_to_regex(schema))
    ok = {"name": "bo", "age": 3, "tags": ["a", "b"], "kind": "cat"}
    assert dfa.matches(json.dumps(ok, separators=(",", ":")).encode())
    no_tags = {"name": "bo", "age": 3, "kind": "dog"}
    assert dfa.matches(json.dumps(no_tags, separators=(",", ":")).encode())
    assert not dfa.matches(b'{"name":3,"age":3,"kind":"cat"}')   # wrong type
    assert not dfa.matches(b'{"age":3,"kind":"cat"}')            # missing req
    assert not dfa.matches(b'{"name":"bo","age":3,"kind":"fox"}')  # bad enum


def test_json_value_regex_bounded_depth():
    dfa = ByteDFA.from_regex(json_value_regex(2))
    for v in ['{"a": 1}', "[1,2]", '"x"', "true", '{"a": [1,2]}']:
        assert dfa.matches(v.encode()), v
    assert not dfa.matches(b'{"a":}')
    # depth 3 nesting exceeds a depth-2 value regex
    assert not dfa.matches(b'{"a": {"b": [1]}}')
    assert ByteDFA.from_regex(json_value_regex(3)).matches(b'{"a": {"b": [1]}}')


def test_json_schema_optional_property_commas():
    """Optional properties must keep comma separators valid for EVERY subset
    (regression: optionals used to concatenate without commas)."""
    schema = {
        "type": "object",
        "properties": {
            "a": {"type": "integer"},   # optional, before first required
            "b": {"type": "integer"},   # required
            "c": {"type": "integer"},   # optional, after
            "d": {"type": "integer"},   # optional, after
        },
        "required": ["b"],
    }
    dfa = ByteDFA.from_regex(json_schema_to_regex(schema))
    for ok in [
        {"b": 2},
        {"a": 1, "b": 2},
        {"b": 2, "c": 3},
        {"a": 1, "b": 2, "c": 3, "d": 4},
        {"b": 2, "d": 4},
    ]:
        assert dfa.matches(json.dumps(ok, separators=(",", ":")).encode()), ok
    assert not dfa.matches(b'{"a":1"b":2}')     # missing comma
    assert not dfa.matches(b'{"a":1,"b":2,}')   # trailing comma
    assert not dfa.matches(b'{"a":1}')          # missing required

    all_optional = {
        "type": "object",
        "properties": {
            "x": {"type": "integer"},
            "y": {"type": "integer"},
            "z": {"type": "integer"},
        },
        "required": [],
    }
    dfa = ByteDFA.from_regex(json_schema_to_regex(all_optional))
    for ok in [{}, {"x": 1}, {"y": 2}, {"x": 1, "z": 3}, {"x": 1, "y": 2, "z": 3}]:
        assert dfa.matches(json.dumps(ok, separators=(",", ":")).encode()), ok
    assert not dfa.matches(b'{"x":1"y":2}')
    assert not dfa.matches(b'{,}')


class _StubHF:
    """Mimics the transformers surface token_byte_table touches."""

    def __init__(self, pieces, special_ids):
        self._pieces = pieces
        self.all_special_ids = special_ids

    def convert_ids_to_tokens(self, ids):
        return [self._pieces[i] for i in ids]


class _StubTokenizer:
    def __init__(self, pieces, special_ids):
        self._tok = _StubHF(pieces, special_ids)
        self.bos_token_id = 0
        self.eos_token_id = 1
        self.pad_token_id = None


def test_token_byte_table_sentencepiece_convention():
    # '▁world' must contribute b' world' (HF decode([id]) strips the space —
    # the regression this mapping exists to avoid) and '<0x0A>' is a raw byte
    tok = _StubTokenizer(["<s>", "</s>", "▁world", "<0x0A>", "ab"], [0, 1])
    table = token_byte_table(tok, 5)
    assert table[0] is None and table[1] is None
    assert table[2] == b" world"
    assert table[3] == b"\n"
    assert table[4] == b"ab"


def test_spm_grammar_admits_word_start_piece():
    """ADVICE r3: on SentencePiece tokenizers the natural word-start piece
    ('▁north' -> b' north') must satisfy a grammar anchored at string start
    (decode strips the sequence-leading space), so compile_guided adds an
    optional leading-space branch — for SPM only."""
    pieces = ["<s>", "</s>", "▁north", "north", "n", "orth", "▁"]
    tok = _StubTokenizer(pieces, [0, 1])
    g = compile_guided(
        GuidedSpec(kind="regex", payload="north"), tok, len(pieces), eos_id=1
    )
    def allowed(gram, tid):
        return bool(gram.mask_bits[0, tid // 8] >> (tid % 8) & 1)
    assert allowed(g, 2)   # '▁north' (" north") admitted at start
    assert allowed(g, 3)   # plain 'north' still admitted
    assert not allowed(g, 5)  # 'orth' still not a valid start

    # the space branch is added at the AST level, so a user's no-op
    # anchors survive SPM wrapping (r4 code review)
    g_anchored = compile_guided(
        GuidedSpec(kind="regex", payload=r"^north$"), tok, len(pieces),
        eos_id=1,
    )
    assert allowed(g_anchored, 2)

    # byte-level BPE decode PRESERVES a leading space: no branch added
    bpe = _StubTokenizer(["<s>", "</s>", "Ġnorth", "north"], [0, 1])
    g2 = compile_guided(
        GuidedSpec(kind="regex", payload="north"), bpe, 4, eos_id=1
    )
    assert not allowed(g2, 2)  # ' north' would corrupt byte-level output
    assert allowed(g2, 3)


def test_token_byte_table_byte_level_convention():
    # GPT-2 alphabet: 'Ġ' (U+0120) is the space byte; 'Ċ' (U+010A) newline
    tok = _StubTokenizer(["<s>", "</s>", "Ġworld", "Ċ", "ab"], [0, 1])
    table = token_byte_table(tok, 5)
    assert table[2] == b" world"
    assert table[3] == b"\n"
    assert table[4] == b"ab"


def test_json_object_regex_requires_object():
    from clearml_serving_tpu.llm.guided import json_object_regex

    dfa = ByteDFA.from_regex(json_object_regex(2))
    assert dfa.matches(b'{"a": 1}')
    assert dfa.matches(b"{}")
    # bare values are NOT acceptable for OpenAI json_object mode
    for v in [b"true", b"3", b'"x"', b"[1,2]"]:
        assert not dfa.matches(v), v


def test_token_dfa_walk_and_eos():
    tok = ByteTokenizer(512)
    g = compile_guided(GuidedSpec("regex", "cat|dog"), tok, 512, tok.eos_token_id)
    # mask bit check: from start only 'c' and 'd' lead anywhere
    start_row = np.unpackbits(g.mask_bits[g.start], bitorder="little")[:512]
    allowed = set(np.nonzero(start_row)[0].tolist())
    assert allowed == {ord("c"), ord("d")}
    # byte walk 'c' 'a' 't' then eos allowed, not before
    s = g.start
    for b in b"cat":
        s = int(g.byte_trans[s, b])
        assert s >= 0
    row = np.unpackbits(g.mask_bits[s], bitorder="little")[:512]
    assert row[tok.eos_token_id] == 1
    assert np.unpackbits(g.mask_bits[g.start], bitorder="little")[tok.eos_token_id] == 0


def test_token_dfa_prunes_dead_ends():
    # 'a' followed by a byte no token can produce (0x00 is a real token for
    # ByteTokenizer, so use a grammar whose tail requires an over-long token)
    tok = ByteTokenizer(512)
    tokens = token_byte_table(tok, 512)
    dfa = ByteDFA.from_regex("ab")
    tdfa = TokenDFA.build(dfa, tokens, tok.eos_token_id)
    # every token admitted from every state leads to a token-live state
    live = (tdfa.table != -1).any(axis=1)
    tgt = tdfa.table[tdfa.table != -1]
    assert live[tgt].all()


# ------------------------------------------------------------ engine

@pytest.fixture(scope="module")
def guided_engine():
    tok = ByteTokenizer(512)
    bundle = models.build_model("llama", {"preset": "llama-tiny", "dtype": "float32"})
    params = bundle.init(jax.random.PRNGKey(0))
    engine = LLMEngineCore(
        bundle, params, max_batch=4, max_seq_len=128, prefill_buckets=[16, 32],
        eos_token_id=tok.eos_token_id, tokenizer=tok,
    )
    return engine, tok


def _gen(engine, req):
    async def run():
        out = []
        async for t in engine.generate(req):
            out.append(t)
        return out

    return asyncio.run(run())


def _text(tok, toks):
    return tok.decode(t for t in toks if t != tok.eos_token_id)


def test_engine_regex_constrains_sampling(guided_engine):
    engine, tok = guided_engine
    # high temperature: without the grammar, a random tiny model emits
    # arbitrary bytes; with it, output MUST be one of the alternatives
    for seed_prompt in ("Q:", "R:", "S:"):
        toks = _gen(engine, GenRequest(
            prompt_ids=tok.encode(seed_prompt), max_new_tokens=24,
            temperature=0.9, guided=GuidedSpec("regex", "(yes|no|maybe)"),
        ))
        assert _text(tok, toks) in ("yes", "no", "maybe")
    assert all(e["refs"] == 0 for e in engine._grammars.values())


def test_engine_json_schema_output_parses(guided_engine):
    engine, tok = guided_engine
    schema = json.dumps({
        "type": "object",
        "properties": {"n": {"type": "integer"}, "ok": {"type": "boolean"}},
        "required": ["n", "ok"],
    })
    toks = _gen(engine, GenRequest(
        prompt_ids=tok.encode("x:"), max_new_tokens=200, temperature=0.8,
        seed=5,  # deterministic completion before the token cap
        guided=GuidedSpec("json_schema", schema),
    ))
    assert toks[-1] == tok.eos_token_id, "expected EOS completion"
    obj = json.loads(_text(tok, toks))
    assert isinstance(obj["n"], int) and isinstance(obj["ok"], bool)


def test_engine_mixed_grammars_in_one_batch(guided_engine):
    engine, tok = guided_engine

    async def both():
        r1 = GenRequest(prompt_ids=tok.encode("a:"), max_new_tokens=16,
                        temperature=0.9,
                        guided=GuidedSpec("regex", "(red|green|blue)"))
        r2 = GenRequest(prompt_ids=tok.encode("b:"), max_new_tokens=16,
                        temperature=0.9, guided=GuidedSpec("regex", "[0-9]{3}"))
        r3 = GenRequest(prompt_ids=tok.encode("c:"), max_new_tokens=4,
                        temperature=0.9)  # unguided alongside

        async def col(r):
            out = []
            async for t in engine.generate(r):
                out.append(t)
            return out

        return await asyncio.gather(col(r1), col(r2), col(r3))

    o1, o2, _o3 = asyncio.run(both())
    assert _text(tok, o1) in ("red", "green", "blue")
    t2 = _text(tok, o2)
    assert len(t2) == 3 and t2.isdigit()


def test_engine_greedy_guided(guided_engine):
    """Greedy decoding under a grammar is deterministic and constrained."""
    engine, tok = guided_engine
    req = lambda: GenRequest(  # noqa: E731
        prompt_ids=tok.encode("t:"), max_new_tokens=16, temperature=0.0,
        guided=GuidedSpec("regex", "(alpha|beta|gamma)"),
    )
    a = _gen(engine, req())
    b = _gen(engine, req())
    assert a == b
    assert _text(tok, a) in ("alpha", "beta", "gamma")


def test_validate_rejects_bad_grammars(guided_engine):
    engine, tok = guided_engine
    with pytest.raises(ValueError):
        engine.validate(GenRequest(
            prompt_ids=[256], guided=GuidedSpec("regex", "(unclosed")
        ))
    with pytest.raises(ValueError):
        engine.validate(GenRequest(
            prompt_ids=[256], guided=GuidedSpec("json_schema", "{not json")
        ))
    with pytest.raises(ValueError):
        engine.validate(GenRequest(
            prompt_ids=[256], guided=GuidedSpec("nope", "x")
        ))


def test_guided_choice_maps_to_regex():
    from clearml_serving_tpu.llm.openai_api import LLMEngineRequest

    spec = LLMEngineRequest._guided_spec(
        {"guided_choice": ["yes", "no", "not.sure"]}
    )
    assert spec.kind == "regex"
    dfa = ByteDFA.from_regex(spec.payload)
    assert dfa.matches(b"yes") and dfa.matches(b"not.sure")
    assert not dfa.matches(b"notXsure")  # the dot is escaped, not wildcard
    # empty list is falsy -> unconstrained; non-list is a 4xx
    assert LLMEngineRequest._guided_spec({"guided_choice": []}) is None
    with pytest.raises(ValueError):
        LLMEngineRequest._guided_spec({"guided_choice": "bad"})


def test_engine_without_tokenizer_rejects_guided():
    bundle = models.build_model("llama", {"preset": "llama-tiny", "dtype": "float32"})
    params = bundle.init(jax.random.PRNGKey(0))
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64, prefill_buckets=[16],
        eos_token_id=257,
    )
    with pytest.raises(ValueError):
        engine.validate(GenRequest(
            prompt_ids=[256], guided=GuidedSpec("regex", "ab")
        ))


def test_json_schema_required_only_object_enforced():
    """r4 code review: {"type":"object","required":[...]} without
    `properties` must still enforce the required members, not widen to
    any-object."""
    schema = {"type": "object", "required": ["id"]}
    dfa = ByteDFA.from_regex(json_schema_to_regex(schema))
    assert dfa.matches(b'{"id":7}')
    assert dfa.matches(b'{"id":"x"}')
    assert not dfa.matches(b"{}")
    assert not dfa.matches(b'{"x":1}')
    # truly unconstrained object stays any-object
    dfa = ByteDFA.from_regex(json_schema_to_regex({"type": "object"}))
    assert dfa.matches(b"{}")
    assert dfa.matches(b'{"x": 1}')


def test_guided_slot_in_speculating_batch():
    """Per-slot spec gating: a grammar-constrained request sharing the
    engine with a greedy (speculating) request must still produce
    grammar-valid output — the guided mask + DFA advance run on the verify
    dispatch's position-0 path — and the greedy slot stays exact."""
    tok = ByteTokenizer(512)
    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    common = dict(max_batch=2, max_seq_len=128, prefill_buckets=[16, 32],
                  eos_token_id=tok.eos_token_id, tokenizer=tok,
                  decode_steps=2)
    greedy_p = [256, 1, 2, 1, 2, 1, 2]

    plain_engine = LLMEngineCore(bundle, params, **common)
    want_greedy = _gen(plain_engine, GenRequest(
        prompt_ids=greedy_p, max_new_tokens=12))

    engine = LLMEngineCore(
        bundle, params, speculation="ngram", spec_k=3, **common
    )
    dispatches = [0]
    orig = engine._spec_chunk_jit

    def counting(*a, **k):
        dispatches[0] += 1
        return orig(*a, **k)

    engine._spec_chunk_jit = counting

    async def run():
        greedy = GenRequest(prompt_ids=greedy_p, max_new_tokens=12)
        guided = GenRequest(
            prompt_ids=tok.encode("Q:"), max_new_tokens=24, temperature=0.9,
            guided=GuidedSpec("regex", "(yes|no|maybe)"),
        )

        async def col(r):
            out = []
            async for t in engine.generate(r):
                out.append(t)
            return out

        return await asyncio.gather(col(greedy), col(guided))

    out_greedy, out_guided = asyncio.run(run())
    assert out_greedy == want_greedy
    assert _text(tok, out_guided) in ("yes", "no", "maybe")
    assert dispatches[0] > 0, "guided slot knocked the batch off spec path"
    assert all(e["refs"] == 0 for e in engine._grammars.values())
