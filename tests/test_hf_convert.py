"""Architecture fidelity: our llama forward must match transformers'
LlamaForCausalLM logits on the same (random) weights."""

import os
import sys

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "examples", "llm")
)


@pytest.fixture(scope="module")
def tiny_hf_llama():
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        max_position_embeddings=128,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config)
    model.eval()
    return model


def test_converted_llama_matches_hf_logits(tiny_hf_llama):
    from convert_model import convert_hf_llama

    import jax.numpy as jnp

    from clearml_serving_tpu import models

    config, params = convert_hf_llama(tiny_hf_llama)
    config["dtype"] = "float32"
    bundle = models.build_model("llama", config)
    params = {
        k: (jnp.asarray(v) if not isinstance(v, list)
            else [{kk: jnp.asarray(vv) for kk, vv in layer.items()} for layer in v])
        for k, v in params.items()
    }

    tokens = np.array([[1, 5, 9, 77, 3, 42, 8, 11]], np.int32)
    with torch.no_grad():
        hf_logits = tiny_hf_llama(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    ours = np.asarray(bundle.apply(params, jnp.asarray(tokens)))

    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)


def test_rope_scaling_llama3_matches_hf():
    """Llama-3.1-style rope_scaling must match HF's scaled implementation."""
    from convert_model import convert_hf_llama

    import jax.numpy as jnp

    from transformers import LlamaConfig, LlamaForCausalLM

    from clearml_serving_tpu import models

    config = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, tie_word_embeddings=False,
        rope_scaling={
            "rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
            "high_freq_factor": 4.0, "original_max_position_embeddings": 64,
        },
    )
    torch.manual_seed(1)
    hf = LlamaForCausalLM(config)
    hf.eval()
    cfg, params = convert_hf_llama(hf)
    cfg["dtype"] = "float32"
    assert cfg["rope_scaling"]["rope_type"] == "llama3"
    bundle = models.build_model("llama", cfg)
    params = {
        k: (jnp.asarray(v) if not isinstance(v, list)
            else [{kk: jnp.asarray(vv) for kk, vv in layer.items()} for layer in v])
        for k, v in params.items()
    }
    tokens = np.array([[1, 5, 9, 77, 3, 42, 8, 11, 64, 100]], np.int32)
    with torch.no_grad():
        hf_logits = hf(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    ours = np.asarray(bundle.apply(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(ours, hf_logits, rtol=3e-4, atol=3e-4)


def test_converted_bundle_scan_layers_roundtrip(tiny_hf_llama, tmp_path):
    """A converted (list-layers) bundle saved with scan_layers=True must load
    into the stacked layout via prepare_params and still match HF."""
    from convert_model import convert_hf_llama

    import jax.numpy as jnp

    from clearml_serving_tpu.engines.jax_engine import load_bundle, save_bundle

    config, params = convert_hf_llama(tiny_hf_llama)
    config["dtype"] = "float32"
    config["scan_layers"] = True
    save_bundle(tmp_path / "b", "llama", config, params)
    bundle, loaded = load_bundle(tmp_path / "b")
    assert isinstance(loaded["layers"], dict)  # stacked for lax.scan
    tokens = np.array([[1, 5, 9, 77]], np.int32)
    with torch.no_grad():
        hf_logits = tiny_hf_llama(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    ours = np.asarray(bundle.apply(loaded, jnp.asarray(tokens)))
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)


def _convert_and_compare(hf_model, seq_len=24, atol=2e-4):
    from convert_model import convert_hf_llama

    import jax.numpy as jnp

    from clearml_serving_tpu import models

    config, params = convert_hf_llama(hf_model)
    bundle = models.build_model("llama", config)
    tokens = np.random.RandomState(0).randint(
        0, config["vocab_size"], (2, seq_len), dtype=np.int64
    )
    ours = bundle.apply(params, jnp.asarray(tokens, jnp.int32))
    with torch.no_grad():
        theirs = hf_model(torch.from_numpy(tokens)).logits
    np.testing.assert_allclose(
        np.asarray(ours), theirs.numpy(), rtol=2e-4, atol=atol
    )
    return config


def test_converted_qwen2_matches_hf_logits():
    """Qwen2 = llama skeleton + QKV biases; converter must detect and map
    the biases from the checkpoint."""
    from transformers import Qwen2Config, Qwen2ForCausalLM

    config = Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rms_norm_eps=1e-5, rope_theta=10000.0, max_position_embeddings=128,
        tie_word_embeddings=False, use_sliding_window=False,
    )
    torch.manual_seed(1)
    hf = Qwen2ForCausalLM(config)
    hf.eval()
    # make the biases matter: random, not the init zeros
    with torch.no_grad():
        for layer in hf.model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj):
                proj.bias.normal_(0, 0.5)
    cfg = _convert_and_compare(hf)
    assert cfg.get("attn_bias") is True


def test_converted_mistral_matches_hf_logits():
    """Mistral = llama skeleton + sliding-window attention; the window must
    actually bite (seq_len > window) for this to prove anything."""
    from transformers import MistralConfig, MistralForCausalLM

    config = MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rms_norm_eps=1e-5, rope_theta=10000.0, max_position_embeddings=128,
        tie_word_embeddings=False, sliding_window=8,
        attn_implementation="eager",
    )
    torch.manual_seed(2)
    hf = MistralForCausalLM(config)
    hf.eval()
    cfg = _convert_and_compare(hf, seq_len=24)
    assert cfg.get("sliding_window") == 8


def test_sliding_window_decode_matches_forward():
    """Cached decode + chunked/verify paths honor the window: greedy decode
    over a long sequence matches the full forward's argmax step by step."""
    import jax
    import jax.numpy as jnp

    from clearml_serving_tpu import models

    cfg = {"preset": "llama-tiny", "dtype": "float32", "sliding_window": 6}
    bundle = models.build_model("llama", cfg)
    params = bundle.init(jax.random.PRNGKey(0))
    prompt = np.random.RandomState(1).randint(1, 400, (1, 12)).tolist()[0]

    # reference: full causal forward with window, argmax next token each step
    seq = list(prompt)
    for _ in range(6):
        logits = bundle.apply(params, jnp.asarray([seq], jnp.int32))
        seq.append(int(np.argmax(np.asarray(logits)[0, -1])))
    expected = seq[len(prompt):]

    # cached path: prefill + decode
    cache = bundle.init_cache(1, 64)
    last, cache = bundle.prefill(
        params, jnp.asarray([prompt], jnp.int32),
        jnp.asarray([len(prompt)], jnp.int32), cache,
    )
    got = [int(np.argmax(np.asarray(last)[0]))]
    for _ in range(5):
        logits, cache = bundle.decode(
            params, jnp.asarray([got[-1]], jnp.int32), cache
        )
        got.append(int(np.argmax(np.asarray(logits)[0])))
    assert got == expected


def test_converted_gemma_matches_hf_logits():
    """Gemma-1 = llama skeleton + (1+w) norms, GeGLU, sqrt(dim) embed
    scaling, explicit head_dim, tied embeddings."""
    from transformers import GemmaConfig, GemmaForCausalLM

    config = GemmaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=32,  # decoupled: 4 * 32 = 128 != hidden_size
        rms_norm_eps=1e-6, rope_theta=10000.0, max_position_embeddings=128,
        attn_implementation="eager",
    )
    torch.manual_seed(3)
    hf = GemmaForCausalLM(config)
    hf.eval()
    cfg = _convert_and_compare(hf, atol=5e-4)
    assert cfg.get("norm_offset") is True
    assert cfg.get("head_dim") == 32
    assert cfg.get("tie_embeddings") is True


def test_converted_gemma2_matches_hf_logits():
    """Gemma-2 adds logit softcaps, query_pre_attn_scalar scaling,
    post-sublayer norms, and interleaved local/global attention — the
    sliding window must bite (seq_len > window) to prove the interleave."""
    from transformers import Gemma2Config, Gemma2ForCausalLM

    config = Gemma2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, rms_norm_eps=1e-6, rope_theta=10000.0,
        max_position_embeddings=128, sliding_window=8,
        query_pre_attn_scalar=64, attn_logit_softcapping=50.0,
        final_logit_softcapping=30.0, attn_implementation="eager",
    )
    torch.manual_seed(4)
    hf = Gemma2ForCausalLM(config)
    hf.eval()
    cfg = _convert_and_compare(hf, seq_len=24, atol=5e-4)
    assert cfg.get("alt_window") is True
    assert cfg.get("post_block_norms") is True
    assert cfg.get("attn_logit_softcap") == 50.0


def test_gemma2_decode_matches_forward():
    """The cached serving path honors the per-layer local/global interleave:
    greedy prefill+decode equals the full forward's argmax chain."""
    from transformers import Gemma2Config, Gemma2ForCausalLM

    import jax.numpy as jnp

    from convert_model import convert_hf_llama

    from clearml_serving_tpu import models

    config = Gemma2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, rms_norm_eps=1e-6, max_position_embeddings=128,
        sliding_window=6, query_pre_attn_scalar=64,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        attn_implementation="eager",
    )
    torch.manual_seed(5)
    hf = Gemma2ForCausalLM(config)
    hf.eval()
    cfg, params = convert_hf_llama(hf)
    bundle = models.build_model("llama", cfg)
    params = bundle.prepare_params(params)

    prompt = np.random.RandomState(2).randint(1, 120, (1, 12)).tolist()[0]
    seq = list(prompt)
    for _ in range(6):
        logits = bundle.apply(params, jnp.asarray([seq], jnp.int32))
        seq.append(int(np.argmax(np.asarray(logits)[0, -1])))
    expected = seq[len(prompt):]

    cache = bundle.init_cache(1, 64)
    last, cache = bundle.prefill(
        params, jnp.asarray([prompt], jnp.int32),
        jnp.asarray([len(prompt)], jnp.int32), cache,
    )
    got = [int(np.argmax(np.asarray(last)[0]))]
    for _ in range(5):
        logits, cache = bundle.decode(
            params, jnp.asarray([got[-1]], jnp.int32), cache
        )
        got.append(int(np.argmax(np.asarray(logits)[0])))
    assert got == expected


def test_gemma2_scan_layers_matches_unscanned():
    """The alt-window interleave survives scan stacking (attn_global rides
    the scanned layer pytree)."""
    from transformers import Gemma2Config, Gemma2ForCausalLM

    import jax.numpy as jnp

    from convert_model import convert_hf_llama

    from clearml_serving_tpu import models

    config = Gemma2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, rms_norm_eps=1e-6, max_position_embeddings=128,
        sliding_window=6, query_pre_attn_scalar=64,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        attn_implementation="eager",
    )
    torch.manual_seed(6)
    hf = Gemma2ForCausalLM(config)
    hf.eval()
    cfg, params = convert_hf_llama(hf)
    tokens = np.random.RandomState(3).randint(0, 120, (1, 16), dtype=np.int64)

    import jax

    plain = models.build_model("llama", cfg)
    a = plain.apply(params, jnp.asarray(tokens, jnp.int32))

    scan_bundle = models.build_model("llama", dict(cfg, scan_layers=True))
    scan_params = scan_bundle.prepare_params(
        {k: (list(v) if k == "layers" else v) for k, v in params.items()}
    )
    b = scan_bundle.apply(scan_params, jnp.asarray(tokens, jnp.int32))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_rope_scaling_linear_matches_hf():
    """Position-interpolation (linear) rope_scaling: full-logits fidelity
    against transformers with the same random weights."""
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, tie_word_embeddings=False,
        rope_scaling={"rope_type": "linear", "factor": 4.0},
    )
    torch.manual_seed(2)
    hf = LlamaForCausalLM(config)
    hf.eval()
    cfg = _convert_and_compare(hf, atol=3e-4)
    assert cfg["rope_scaling"]["rope_type"] == "linear"


def test_rope_longrope_matches_hf_tables():
    """Phi-3 LongRoPE: our per-position cos/sin (short factors inside the
    original window, long factors beyond, attention scale applied) must
    match transformers' longrope rope-init in both regions."""
    import jax.numpy as jnp

    from transformers import Phi3Config
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

    from clearml_serving_tpu.models.llama import _rope

    head_dim = 16
    orig, deployed = 64, 256
    short = [1.0 + 0.05 * i for i in range(head_dim // 2)]
    long = [2.0 + 0.1 * i for i in range(head_dim // 2)]
    cfg = Phi3Config(
        hidden_size=64, num_attention_heads=4, num_hidden_layers=1,
        max_position_embeddings=deployed, rope_theta=10000.0,
        original_max_position_embeddings=orig,
        rope_scaling={"type": "longrope", "short_factor": short,
                      "long_factor": long},
    )
    scaling = {
        "rope_type": "longrope", "short_factor": short, "long_factor": long,
        "original_max_position_embeddings": orig,
        "max_position_embeddings": deployed,
    }
    positions = jnp.asarray([[2, 10, 63, 64, 100, 200]], jnp.int32)
    cos, sin = _rope(positions, head_dim, 10000.0, scaling)
    cos, sin = np.asarray(cos)[0], np.asarray(sin)[0]

    fn = ROPE_INIT_FUNCTIONS["longrope"]
    # HF picks the factor set by the FORWARD length; our table is
    # per-position — compare the short region against a short-run init and
    # the long region against a long-run init
    inv_short, att_short = fn(cfg, device=None, seq_len=orig)
    inv_long, att_long = fn(cfg, device=None, seq_len=deployed)
    assert att_short == pytest.approx(att_long)  # one global scale
    for row, p in enumerate([2, 10, 63, 64, 100, 200]):
        inv = inv_short if p < orig else inv_long
        angles = p * inv.numpy()
        np.testing.assert_allclose(
            cos[row], np.cos(angles) * float(att_short), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            sin[row], np.sin(angles) * float(att_short), rtol=1e-5, atol=1e-5
        )


def test_rope_longrope_validation():
    from clearml_serving_tpu import models

    with pytest.raises(ValueError):
        models.build_model("llama", {
            "preset": "llama-tiny", "dtype": "float32",
            "rope_scaling": {"rope_type": "longrope",
                             "short_factor": [1.0],  # wrong length
                             "long_factor": [1.0],
                             "original_max_position_embeddings": 64},
        })
    with pytest.raises(ValueError):
        models.build_model("llama", {
            "preset": "llama-tiny", "dtype": "float32",
            "rope_scaling": {"rope_type": "dynamic", "factor": 2.0},
        })


def test_rope_longrope_defaults_deployed_length_from_max_seq_len():
    """When rope_scaling omits max_position_embeddings (HF keeps it outside
    the dict), the build must default it from the model's max_seq_len so the
    attention scale applies — NOT silently degrade to 1.0 (r5 review)."""
    import jax
    import jax.numpy as jnp

    from clearml_serving_tpu import models

    short = [1.0] * 8
    long = [2.0] * 8
    base_cfg = {
        "preset": "llama-tiny", "dtype": "float32", "max_seq_len": 512,
    }
    implicit = models.build_model("llama", dict(base_cfg, rope_scaling={
        "rope_type": "longrope", "short_factor": short, "long_factor": long,
        "original_max_position_embeddings": 64}))
    explicit = models.build_model("llama", dict(base_cfg, rope_scaling={
        "rope_type": "longrope", "short_factor": short, "long_factor": long,
        "original_max_position_embeddings": 64,
        "max_position_embeddings": 512}))
    p = jax.random.PRNGKey(0)
    params = implicit.init(p)
    toks = np.array([[1, 2, 3]], np.int32)
    a = np.asarray(implicit.apply(params, jnp.asarray(toks)))
    b = np.asarray(explicit.apply(params, jnp.asarray(toks)))
    np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)
    # and the scale genuinely differs from the unscaled (orig-only) model
    unscaled = models.build_model("llama", dict(base_cfg, rope_scaling={
        "rope_type": "longrope", "short_factor": short, "long_factor": long,
        "original_max_position_embeddings": 64,
        "max_position_embeddings": 64}))
    c = np.asarray(unscaled.apply(params, jnp.asarray(toks)))
    assert not np.allclose(a, c, rtol=1e-4, atol=1e-4)


def test_rope_longrope_decoupled_head_dim_validation():
    """Factor-length validation must use the RESOLVED head_dim (decoupled
    via cfg['head_dim']), not dim // n_heads (r5 review)."""
    from clearml_serving_tpu import models

    # llama-tiny dim=64 n_heads=4 -> dim//n_heads = 16, but head_dim=8:
    # 4 factors must validate; 8 must be rejected
    cfg = {"preset": "llama-tiny", "dtype": "float32", "head_dim": 8,
           "max_seq_len": 128}
    models.build_model("llama", dict(cfg, rope_scaling={
        "rope_type": "longrope", "short_factor": [1.0] * 4,
        "long_factor": [2.0] * 4,
        "original_max_position_embeddings": 64}))
    import pytest as _pytest

    with _pytest.raises(ValueError):
        models.build_model("llama", dict(cfg, rope_scaling={
            "rope_type": "longrope", "short_factor": [1.0] * 8,
            "long_factor": [2.0] * 8,
            "original_max_position_embeddings": 64}))


def test_converted_phi3_matches_hf_logits():
    """Phi-3 = llama skeleton + fused qkv/gate_up projections (split in the
    converter): full-logits fidelity against transformers."""
    from transformers import Phi3Config, Phi3ForCausalLM

    config = Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rms_norm_eps=1e-5, rope_theta=10000.0, max_position_embeddings=128,
        tie_word_embeddings=False, sliding_window=None, pad_token_id=0,
    )
    torch.manual_seed(4)
    hf = Phi3ForCausalLM(config)
    hf.eval()
    _convert_and_compare(hf)


def test_converted_phi3_longrope_matches_hf_inside_window():
    """Phi-3 with LongRoPE: inside the original window the short factors
    apply uniformly, so full logits must match HF exactly. (Past the window
    HF re-encodes the WHOLE sequence with long factors while the serving
    convention — vLLM's — is per-position selection, KV-cache-compatible
    by construction; pinned at the table level in
    test_rope_longrope_matches_hf_tables.)"""
    from transformers import Phi3Config, Phi3ForCausalLM

    hd2 = (64 // 4) // 2
    config = Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rms_norm_eps=1e-5, rope_theta=10000.0,
        max_position_embeddings=256,
        original_max_position_embeddings=64,
        tie_word_embeddings=False, sliding_window=None, pad_token_id=0,
        rope_scaling={"type": "longrope",
                      "short_factor": [1.0 + 0.1 * i for i in range(hd2)],
                      "long_factor": [2.0 + 0.2 * i for i in range(hd2)]},
    )
    torch.manual_seed(5)
    hf = Phi3ForCausalLM(config)
    hf.eval()
    cfg = _convert_and_compare(hf, seq_len=24)  # 24 < 64: short region
    assert (cfg["rope_scaling"].get("rope_type")
            or cfg["rope_scaling"].get("type")) == "longrope"
    assert cfg["rope_scaling"]["max_position_embeddings"] == 256


def test_partial_rotary_factor_is_rejected():
    """Phi-4-mini-style partial rotary (model_type phi3,
    partial_rotary_factor<1) must refuse to convert instead of serving
    silently wrong logits (r5 review)."""
    from convert_model import convert_hf_llama

    from transformers import Phi3Config, Phi3ForCausalLM

    config = Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=1, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, tie_word_embeddings=False,
        sliding_window=None, pad_token_id=0,
    )
    config.partial_rotary_factor = 0.75
    torch.manual_seed(6)
    hf = Phi3ForCausalLM(config)
    with pytest.raises(ValueError, match="partial_rotary_factor"):
        convert_hf_llama(hf)


def test_rope_scaling_yarn_matches_hf():
    """YaRN rope_scaling: full-logits fidelity against transformers
    (NTK-by-parts bands + attention temperature on cos/sin)."""
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, tie_word_embeddings=False,
        rope_scaling={"rope_type": "yarn", "factor": 4.0,
                      "original_max_position_embeddings": 64},
    )
    torch.manual_seed(3)
    hf = LlamaForCausalLM(config)
    hf.eval()
    cfg = _convert_and_compare(hf, atol=3e-4)
    assert (cfg["rope_scaling"].get("rope_type")
            or cfg["rope_scaling"].get("type")) == "yarn"


def test_rope_yarn_tables_match_hf_init():
    """YaRN inverse frequencies and attention scaling pinned against HF's
    rope-init directly (incl. non-default betas)."""
    from transformers import LlamaConfig
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

    from clearml_serving_tpu.models.llama import (
        _rope_freqs,
        _yarn_attention_factor,
    )

    scaling = {"rope_type": "yarn", "factor": 8.0,
               "original_max_position_embeddings": 128,
               "beta_fast": 16.0, "beta_slow": 2.0}
    cfg = LlamaConfig(
        hidden_size=128, num_attention_heads=4,
        max_position_embeddings=1024, rope_theta=10000.0,
        rope_scaling=dict(scaling),
    )
    inv, att = ROPE_INIT_FUNCTIONS["yarn"](cfg, device=None)
    ours = np.asarray(_rope_freqs(32, 10000.0, scaling))
    np.testing.assert_allclose(ours, inv.numpy(), rtol=1e-5, atol=1e-7)
    assert _yarn_attention_factor(scaling) == pytest.approx(float(att))
