"""Architecture fidelity: our llama forward must match transformers'
LlamaForCausalLM logits on the same (random) weights."""

import os
import sys

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

sys.path.insert(
    0, os.path.join(os.path.dirname(__file__), "..", "examples", "llm")
)


@pytest.fixture(scope="module")
def tiny_hf_llama():
    from transformers import LlamaConfig, LlamaForCausalLM

    config = LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        max_position_embeddings=128,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(config)
    model.eval()
    return model


def test_converted_llama_matches_hf_logits(tiny_hf_llama):
    from convert_model import convert_hf_llama

    import jax.numpy as jnp

    from clearml_serving_tpu import models

    config, params = convert_hf_llama(tiny_hf_llama)
    config["dtype"] = "float32"
    bundle = models.build_model("llama", config)
    params = {
        k: (jnp.asarray(v) if not isinstance(v, list)
            else [{kk: jnp.asarray(vv) for kk, vv in layer.items()} for layer in v])
        for k, v in params.items()
    }

    tokens = np.array([[1, 5, 9, 77, 3, 42, 8, 11]], np.int32)
    with torch.no_grad():
        hf_logits = tiny_hf_llama(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    ours = np.asarray(bundle.apply(params, jnp.asarray(tokens)))

    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)


def test_rope_scaling_llama3_matches_hf():
    """Llama-3.1-style rope_scaling must match HF's scaled implementation."""
    from convert_model import convert_hf_llama

    import jax.numpy as jnp

    from transformers import LlamaConfig, LlamaForCausalLM

    from clearml_serving_tpu import models

    config = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256, tie_word_embeddings=False,
        rope_scaling={
            "rope_type": "llama3", "factor": 8.0, "low_freq_factor": 1.0,
            "high_freq_factor": 4.0, "original_max_position_embeddings": 64,
        },
    )
    torch.manual_seed(1)
    hf = LlamaForCausalLM(config)
    hf.eval()
    cfg, params = convert_hf_llama(hf)
    cfg["dtype"] = "float32"
    assert cfg["rope_scaling"]["rope_type"] == "llama3"
    bundle = models.build_model("llama", cfg)
    params = {
        k: (jnp.asarray(v) if not isinstance(v, list)
            else [{kk: jnp.asarray(vv) for kk, vv in layer.items()} for layer in v])
        for k, v in params.items()
    }
    tokens = np.array([[1, 5, 9, 77, 3, 42, 8, 11, 64, 100]], np.int32)
    with torch.no_grad():
        hf_logits = hf(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    ours = np.asarray(bundle.apply(params, jnp.asarray(tokens)))
    np.testing.assert_allclose(ours, hf_logits, rtol=3e-4, atol=3e-4)


def test_converted_bundle_scan_layers_roundtrip(tiny_hf_llama, tmp_path):
    """A converted (list-layers) bundle saved with scan_layers=True must load
    into the stacked layout via prepare_params and still match HF."""
    from convert_model import convert_hf_llama

    import jax.numpy as jnp

    from clearml_serving_tpu.engines.jax_engine import load_bundle, save_bundle

    config, params = convert_hf_llama(tiny_hf_llama)
    config["dtype"] = "float32"
    config["scan_layers"] = True
    save_bundle(tmp_path / "b", "llama", config, params)
    bundle, loaded = load_bundle(tmp_path / "b")
    assert isinstance(loaded["layers"], dict)  # stacked for lax.scan
    tokens = np.array([[1, 5, 9, 77]], np.int32)
    with torch.no_grad():
        hf_logits = tiny_hf_llama(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    ours = np.asarray(bundle.apply(loaded, jnp.asarray(tokens)))
    np.testing.assert_allclose(ours, hf_logits, rtol=2e-4, atol=2e-4)
