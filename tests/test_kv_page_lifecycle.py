"""KV page lifecycle: PagePool refcount / copy-on-write / truncate / free
interactions, and the PagedKVCache device side of CoW (llm/kv_cache.py).

These are the invariants the radix prefix cache (llm/prefix_cache.py) leans
on: a page is recycled exactly when its LAST reference (slot or cache)
drops, a slot never writes into a page someone else still references, and
rollback (truncate) never strands or double-frees shared pages.
"""

import numpy as np
import pytest

from clearml_serving_tpu.llm.kv_cache import PagedKVCache, PagePool


def _pool(num_pages=16, page_size=4, max_slots=4):
    return PagePool(num_pages=num_pages, page_size=page_size, max_slots=max_slots)


# -- refcount basics ----------------------------------------------------------


def test_allocate_free_roundtrip():
    pool = _pool()
    pages = pool.allocate(0, 10)  # 3 pages
    assert len(pages) == 3
    assert all(pool.page_refcount(p) == 1 for p in pages)
    assert pool.free_pages == 15 - 3
    pool.free(0)
    assert pool.free_pages == 15
    assert all(pool.page_refcount(p) == 0 for p in pages)


def test_truncate_returns_only_unshared_surplus():
    pool = _pool()
    pool.allocate(0, 16)  # 4 pages
    pages = pool.slot_pages(0)
    pool.ref_pages(pages[3:])  # cache holds the last page
    pool.truncate(0, 5)        # keep 2 pages, surplus = pages[2:]
    assert pool.slot_pages(0) == pages[:2]
    assert pool.page_refcount(pages[2]) == 0   # unshared -> freed
    assert pool.page_refcount(pages[3]) == 1   # cache ref keeps it
    assert pool.slot_length(0) == 5
    # the shared surplus page is NOT in the free list
    assert pool.free_pages == 15 - 2 - 1


def test_truncate_past_length_raises():
    pool = _pool()
    pool.allocate(0, 4)
    with pytest.raises(ValueError):
        pool.truncate(0, 5)


def test_extend_after_truncate_reuses_tail_page():
    pool = _pool()
    pool.allocate(0, 8)
    pool.truncate(0, 5)
    new = pool.extend(0, 1)  # token 5 fits the kept tail page
    assert new == []
    new = pool.extend(0, 3)  # tokens 6,7,8 -> one new page
    assert len(new) == 1


def test_ref_unref_errors():
    pool = _pool()
    with pytest.raises(RuntimeError):
        pool.ref_pages([3])  # never allocated
    pages = pool.allocate(0, 4)
    pool.ref_pages(pages)
    assert pool.unref_pages(pages) == 0  # slot still holds them
    pool.free(0)
    assert pool.page_refcount(pages[0]) == 0


# -- sharing / map_shared -----------------------------------------------------


def test_map_shared_zero_copy_mapping():
    pool = _pool()
    pool.allocate(0, 8)
    shared = pool.slot_pages(0)
    pool.ref_pages(shared)   # cache stores them
    pool.free(0)             # original slot finishes
    assert all(pool.page_refcount(p) == 1 for p in shared)
    pool.map_shared(1, shared, 8)
    assert pool.slot_pages(1) == shared
    assert all(pool.page_refcount(p) == 2 for p in shared)
    assert pool.slot_length(1) == 8
    # both release: pages recycle exactly once
    pool.free(1)
    assert pool.unref_pages(shared) == len(shared)
    assert pool.free_pages == 15


def test_map_shared_requires_alignment_and_empty_slot():
    pool = _pool()
    pool.allocate(0, 8)
    shared = pool.slot_pages(0)
    with pytest.raises(ValueError):
        pool.map_shared(1, shared, 7)  # not page-aligned
    pool.allocate(1, 2)
    with pytest.raises(RuntimeError):
        pool.map_shared(1, shared, 8)  # slot not empty


# -- copy-on-write ------------------------------------------------------------


def test_extend_into_shared_tail_page_cows():
    pool = _pool()
    pool.allocate(0, 6)  # 2 pages; tail page half full
    pages = pool.slot_pages(0)
    pool.ref_pages([pages[1]])  # someone else references the tail page
    new = pool.extend(0, 1)     # write position 6 is INSIDE the shared page
    assert pool.cow_events == 1
    swapped = pool.slot_pages(0)
    assert swapped[0] == pages[0]
    assert swapped[1] != pages[1]          # private replacement
    assert pool.page_refcount(pages[1]) == 1   # only the external ref left
    assert pool.page_refcount(swapped[1]) == 1
    assert pool.drain_pending_cow() == [(pages[1], swapped[1])]
    assert new == []  # token 6 fit the (replacement) tail page


def test_extend_page_aligned_never_cows():
    pool = _pool()
    pool.allocate(0, 8)  # exactly 2 full pages
    pages = pool.slot_pages(0)
    pool.ref_pages(pages)  # everything shared
    new = pool.extend(0, 1)  # next write starts a FRESH page
    assert pool.cow_events == 0
    assert len(new) == 1


def test_cow_exhaustion_raises_memory_error():
    pool = PagePool(num_pages=3, page_size=4, max_slots=2)  # 2 usable
    pool.allocate(0, 6)  # both pages
    pool.ref_pages([pool.slot_pages(0)[1]])
    with pytest.raises(MemoryError):
        pool.extend(0, 1)  # CoW needs a free page; none left


def test_paged_kv_cache_cow_copies_device_page():
    """The device side: after a CoW swap, apply_pending_cow duplicates the
    page contents so the slot's history is intact in its private copy."""
    cache = PagedKVCache(
        n_layers=1, n_kv_heads=1, head_dim=2,
        num_pages=8, page_size=4, max_slots=2, dtype="float32",
    )
    pool = cache.pool
    # write a 6-token prompt (2 pages, tail half full)
    k = np.arange(6 * 2, dtype=np.float32).reshape(1, 6, 1, 2)
    cache.write_prompt(0, k, k * 10.0, 6)
    pages = pool.slot_pages(0)
    pool.ref_pages([pages[1]])            # share the tail page
    pool.extend(0, 1)
    assert pool.cow_events == 1
    copied = cache.apply_pending_cow()
    assert copied == 1
    new_tail = pool.slot_pages(0)[1]
    np.testing.assert_array_equal(
        np.asarray(cache.k[0, 0, new_tail]), np.asarray(cache.k[0, 0, pages[1]])
    )
    np.testing.assert_array_equal(
        np.asarray(cache.v[0, 0, new_tail]), np.asarray(cache.v[0, 0, pages[1]])
    )


def test_write_prompt_shared_scatters_only_tail():
    """write_prompt_shared maps the prefix by reference and scatters only
    the tail KV; the shared pages' contents are untouched."""
    cache = PagedKVCache(
        n_layers=1, n_kv_heads=1, head_dim=2,
        num_pages=8, page_size=4, max_slots=2, dtype="float32",
    )
    pool = cache.pool
    k = np.arange(8 * 2, dtype=np.float32).reshape(1, 8, 1, 2)
    cache.write_prompt(0, k, k, 8)
    shared = pool.slot_pages(0)
    pool.ref_pages(shared)  # "cache" keeps them
    before = np.asarray(cache.k[0, 0, shared[0]]).copy()
    tail = 100.0 + np.arange(3 * 2, dtype=np.float32).reshape(1, 3, 1, 2)
    cache.write_prompt_shared(1, shared, 8, tail, tail, 11)
    assert pool.slot_pages(1)[:2] == shared
    assert len(pool.slot_pages(1)) == 3
    np.testing.assert_array_equal(np.asarray(cache.k[0, 0, shared[0]]), before)
    own = pool.slot_pages(1)[2]
    np.testing.assert_array_equal(
        np.asarray(cache.k[0, 0, own, :3]), tail[0, :, 0]
    )
    # misaligned prefix refused (would put live writes inside shared pages)
    cache2 = PagedKVCache(
        n_layers=1, n_kv_heads=1, head_dim=2,
        num_pages=8, page_size=4, max_slots=2, dtype="float32",
    )
    with pytest.raises(ValueError):
        cache2.write_prompt_shared(0, [1], 3, tail, tail, 6)
