"""KV page lifecycle: PagePool refcount / copy-on-write / truncate / free
interactions, and the PagedKVCache device side of CoW (llm/kv_cache.py).

These are the invariants the radix prefix cache (llm/prefix_cache.py) leans
on: a page is recycled exactly when its LAST reference (slot or cache)
drops, a slot never writes into a page someone else still references, and
rollback (truncate) never strands or double-frees shared pages.
"""

import numpy as np
import pytest

from clearml_serving_tpu.llm.kv_cache import PagedKVCache, PagePool
from clearml_serving_tpu.llm.kv_sanitizer import (
    KVSanitizer,
    KVSanitizerError,
    enabled as sanitizer_enabled,
)
from clearml_serving_tpu.llm.prefix_cache import RadixPrefixCache


@pytest.fixture(autouse=True)
def armed_sanitizer(monkeypatch):
    """Paged-engine construction in this suite (and any engine built through
    it) runs with the runtime sanitizer armed."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    assert sanitizer_enabled()


def _pool(num_pages=16, page_size=4, max_slots=4):
    return PagePool(num_pages=num_pages, page_size=page_size, max_slots=max_slots)


# -- refcount basics ----------------------------------------------------------


def test_allocate_free_roundtrip():
    pool = _pool()
    pages = pool.allocate(0, 10)  # 3 pages
    assert len(pages) == 3
    assert all(pool.page_refcount(p) == 1 for p in pages)
    assert pool.free_pages == 15 - 3
    pool.free(0)
    assert pool.free_pages == 15
    assert all(pool.page_refcount(p) == 0 for p in pages)


def test_truncate_returns_only_unshared_surplus():
    pool = _pool()
    pool.allocate(0, 16)  # 4 pages
    pages = pool.slot_pages(0)
    pool.ref_pages(pages[3:])  # cache holds the last page
    pool.truncate(0, 5)        # keep 2 pages, surplus = pages[2:]
    assert pool.slot_pages(0) == pages[:2]
    assert pool.page_refcount(pages[2]) == 0   # unshared -> freed
    assert pool.page_refcount(pages[3]) == 1   # cache ref keeps it
    assert pool.slot_length(0) == 5
    # the shared surplus page is NOT in the free list
    assert pool.free_pages == 15 - 2 - 1


def test_truncate_past_length_raises():
    pool = _pool()
    pool.allocate(0, 4)
    with pytest.raises(ValueError):
        pool.truncate(0, 5)


def test_extend_after_truncate_reuses_tail_page():
    pool = _pool()
    pool.allocate(0, 8)
    pool.truncate(0, 5)
    new = pool.extend(0, 1)  # token 5 fits the kept tail page
    assert new == []
    new = pool.extend(0, 3)  # tokens 6,7,8 -> one new page
    assert len(new) == 1


def test_ref_unref_errors():
    pool = _pool()
    with pytest.raises(RuntimeError):
        pool.ref_pages([3])  # never allocated
    pages = pool.allocate(0, 4)
    pool.ref_pages(pages)
    assert pool.unref_pages(pages) == 0  # slot still holds them
    pool.free(0)
    assert pool.page_refcount(pages[0]) == 0


# -- sharing / map_shared -----------------------------------------------------


def test_map_shared_zero_copy_mapping():
    pool = _pool()
    pool.allocate(0, 8)
    shared = pool.slot_pages(0)
    pool.ref_pages(shared)   # cache stores them
    pool.free(0)             # original slot finishes
    assert all(pool.page_refcount(p) == 1 for p in shared)
    pool.map_shared(1, shared, 8)
    assert pool.slot_pages(1) == shared
    assert all(pool.page_refcount(p) == 2 for p in shared)
    assert pool.slot_length(1) == 8
    # both release: pages recycle exactly once
    pool.free(1)
    assert pool.unref_pages(shared) == len(shared)
    assert pool.free_pages == 15


def test_map_shared_requires_alignment_and_empty_slot():
    pool = _pool()
    pool.allocate(0, 8)
    shared = pool.slot_pages(0)
    with pytest.raises(ValueError):
        pool.map_shared(1, shared, 7)  # not page-aligned
    pool.allocate(1, 2)
    with pytest.raises(RuntimeError):
        pool.map_shared(1, shared, 8)  # slot not empty


# -- copy-on-write ------------------------------------------------------------


def test_extend_into_shared_tail_page_cows():
    pool = _pool()
    pool.allocate(0, 6)  # 2 pages; tail page half full
    pages = pool.slot_pages(0)
    pool.ref_pages([pages[1]])  # someone else references the tail page
    new = pool.extend(0, 1)     # write position 6 is INSIDE the shared page
    assert pool.cow_events == 1
    swapped = pool.slot_pages(0)
    assert swapped[0] == pages[0]
    assert swapped[1] != pages[1]          # private replacement
    assert pool.page_refcount(pages[1]) == 1   # only the external ref left
    assert pool.page_refcount(swapped[1]) == 1
    assert pool.drain_pending_cow() == [(pages[1], swapped[1])]
    assert new == []  # token 6 fit the (replacement) tail page


def test_extend_page_aligned_never_cows():
    pool = _pool()
    pool.allocate(0, 8)  # exactly 2 full pages
    pages = pool.slot_pages(0)
    pool.ref_pages(pages)  # everything shared
    new = pool.extend(0, 1)  # next write starts a FRESH page
    assert pool.cow_events == 0
    assert len(new) == 1


def test_cow_exhaustion_raises_memory_error():
    pool = PagePool(num_pages=3, page_size=4, max_slots=2)  # 2 usable
    pool.allocate(0, 6)  # both pages
    pool.ref_pages([pool.slot_pages(0)[1]])
    with pytest.raises(MemoryError):
        pool.extend(0, 1)  # CoW needs a free page; none left


def test_paged_kv_cache_cow_copies_device_page():
    """The device side: after a CoW swap, apply_pending_cow duplicates the
    page contents so the slot's history is intact in its private copy."""
    cache = PagedKVCache(
        n_layers=1, n_kv_heads=1, head_dim=2,
        num_pages=8, page_size=4, max_slots=2, dtype="float32",
    )
    pool = cache.pool
    # write a 6-token prompt (2 pages, tail half full)
    k = np.arange(6 * 2, dtype=np.float32).reshape(1, 6, 1, 2)
    cache.write_prompt(0, k, k * 10.0, 6)
    pages = pool.slot_pages(0)
    pool.ref_pages([pages[1]])            # share the tail page
    pool.extend(0, 1)
    assert pool.cow_events == 1
    copied = cache.apply_pending_cow()
    assert copied == 1
    new_tail = pool.slot_pages(0)[1]
    np.testing.assert_array_equal(
        np.asarray(cache.k[0, 0, new_tail]), np.asarray(cache.k[0, 0, pages[1]])
    )
    np.testing.assert_array_equal(
        np.asarray(cache.v[0, 0, new_tail]), np.asarray(cache.v[0, 0, pages[1]])
    )


def test_write_prompt_shared_scatters_only_tail():
    """write_prompt_shared maps the prefix by reference and scatters only
    the tail KV; the shared pages' contents are untouched."""
    cache = PagedKVCache(
        n_layers=1, n_kv_heads=1, head_dim=2,
        num_pages=8, page_size=4, max_slots=2, dtype="float32",
    )
    pool = cache.pool
    k = np.arange(8 * 2, dtype=np.float32).reshape(1, 8, 1, 2)
    cache.write_prompt(0, k, k, 8)
    shared = pool.slot_pages(0)
    pool.ref_pages(shared)  # "cache" keeps them
    before = np.asarray(cache.k[0, 0, shared[0]]).copy()
    tail = 100.0 + np.arange(3 * 2, dtype=np.float32).reshape(1, 3, 1, 2)
    cache.write_prompt_shared(1, shared, 8, tail, tail, 11)
    assert pool.slot_pages(1)[:2] == shared
    assert len(pool.slot_pages(1)) == 3
    np.testing.assert_array_equal(np.asarray(cache.k[0, 0, shared[0]]), before)
    own = pool.slot_pages(1)[2]
    np.testing.assert_array_equal(
        np.asarray(cache.k[0, 0, own, :3]), tail[0, :, 0]
    )
    # misaligned prefix refused (would put live writes inside shared pages)
    cache2 = PagedKVCache(
        n_layers=1, n_kv_heads=1, head_dim=2,
        num_pages=8, page_size=4, max_slots=2, dtype="float32",
    )
    with pytest.raises(ValueError):
        cache2.write_prompt_shared(0, [1], 3, tail, tail, 6)


# -- int8 pools: a page and its scale rows share one lifecycle ----------------


def _int8_cache(**kw):
    kw.setdefault("n_layers", 1)
    kw.setdefault("n_kv_heads", 1)
    kw.setdefault("head_dim", 2)
    kw.setdefault("num_pages", 8)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_slots", 2)
    return PagedKVCache(dtype="float32", kv_quant="int8", **kw)


def test_int8_cow_copies_scale_rows_with_the_page():
    """Copy-on-write on int8 pools must duplicate the page's scale rows in
    the same batch as its data — a private copy dequantizing with the old
    shared page's scales would corrupt every token in it."""
    cache = _int8_cache()
    pool = cache.pool
    k = np.clip(np.arange(6 * 2, dtype=np.float32), 0, 126).reshape(1, 6, 1, 2)
    k_q = k.astype(np.int8)
    k_s = (0.25 + np.arange(6, dtype=np.float32)).reshape(1, 6, 1)
    cache.write_prompt(0, k_q, k_q, 6, k_s, k_s * 2.0)
    pages = pool.slot_pages(0)
    pool.ref_pages([pages[1]])            # share the tail page
    pool.extend(0, 1)
    assert pool.cow_events == 1
    assert cache.apply_pending_cow() == 1
    new_tail = pool.slot_pages(0)[1]
    np.testing.assert_array_equal(
        np.asarray(cache.k[0, 0, new_tail]), np.asarray(cache.k[0, 0, pages[1]])
    )
    np.testing.assert_array_equal(
        np.asarray(cache.k_scale[0, 0, new_tail]),
        np.asarray(cache.k_scale[0, 0, pages[1]]),
    )
    np.testing.assert_array_equal(
        np.asarray(cache.v_scale[0, 0, new_tail]),
        np.asarray(cache.v_scale[0, 0, pages[1]]),
    )


def test_int8_write_prompt_shared_scatters_tail_scales():
    """Shared-prefix admission on int8 pools: prefix scale rows ride the
    shared page ids untouched; only the tail's scales scatter."""
    cache = _int8_cache()
    pool = cache.pool
    k = np.arange(8 * 2, dtype=np.float32).reshape(1, 8, 1, 2).astype(np.int8)
    s = (1.0 + np.arange(8, dtype=np.float32)).reshape(1, 8, 1)
    cache.write_prompt(0, k, k, 8, s, s)
    shared = pool.slot_pages(0)
    pool.ref_pages(shared)
    before = np.asarray(cache.k_scale[0, 0, shared[0]]).copy()
    tail = np.full((1, 3, 1, 2), 7, np.int8)
    tail_s = np.full((1, 3, 1), 0.5, np.float32)
    # int8 pools refuse a shared-tail scatter without its scales
    with pytest.raises(ValueError):
        cache.write_prompt_shared(1, shared, 8, tail, tail, 11)
    cache.write_prompt_shared(1, shared, 8, tail, tail, 11, tail_s, tail_s)
    np.testing.assert_array_equal(
        np.asarray(cache.k_scale[0, 0, shared[0]]), before
    )
    own = pool.slot_pages(1)[2]
    np.testing.assert_array_equal(
        np.asarray(cache.k_scale[0, 0, own, :3]), tail_s[0, :, 0]
    )


def test_int8_sanitizer_checks_scale_shape_and_names_scale_rows():
    """Invariant 6: a scale pool whose page axis drifted from the allocator
    fails the audit; drain leaks name the stranded scale rows."""
    cache = _int8_cache()
    pool = cache.pool
    san = KVSanitizer(pool, paged_cache=cache)
    san.check("step")  # consistent: passes
    # leak: a slot abandons pages -> drain audit names pages AND scale rows
    pool.allocate(0, 8)
    with pytest.raises(KVSanitizerError) as err:
        san.check("drain", drained=True)
    assert "scale rows" in str(err.value)
    pool.free(0)
    # shape drift: scale pool no longer addresses the allocator's pages
    import jax.numpy as jnp

    cache.k_scale = jnp.zeros((1, 1, 4, 4), jnp.float32)
    with pytest.raises(KVSanitizerError) as err:
        san.check("step")
    assert "lifecycle" in str(err.value)


# -- transient pins (prefix-cache lookup accounting) --------------------------


def test_pin_unpin_roundtrip_and_accounting():
    pool = _pool()
    pages = pool.allocate(0, 8)
    pool.pin_pages(pages)  # in-flight admission holds them
    assert all(pool.page_refcount(p) == 2 for p in pages)
    pool.free(0)  # slot exits first
    assert all(pool.page_refcount(p) == 1 for p in pages)  # pin keeps them
    assert pool.unpin_pages(pages) == len(pages)
    assert pool.free_pages == 15


def test_unpin_without_pin_raises():
    pool = _pool()
    pages = pool.allocate(0, 4)
    with pytest.raises(RuntimeError):
        pool.unpin_pages(pages)
    pool.free(0)


# -- runtime KV sanitizer (llm/kv_sanitizer.py) -------------------------------


def test_sanitizer_clean_pool_passes_all_checks():
    pool = _pool()
    san = KVSanitizer(pool)
    pool.allocate(0, 10)
    pool.allocate(1, 5)
    san.check("step")
    pool.free(0)
    pool.free(1)
    san.check("drain", drained=True)
    assert san.stats() == {"checks": 2, "failures": 0}


def test_sanitizer_names_unaccounted_reference():
    pool = _pool()
    san = KVSanitizer(pool)
    pages = pool.allocate(0, 4)
    with pool._lock:
        pool._refs[pages[0]] += 1  # simulate a lost unref (leak)
    with pytest.raises(KVSanitizerError) as ei:
        san.check("step")
    assert ei.value.pages == [pages[0]]
    assert "refcount conservation" in str(ei.value)
    assert "page {}".format(pages[0]) in str(ei.value)


def test_sanitizer_catches_free_list_corruption():
    pool = _pool()
    san = KVSanitizer(pool)
    pages = pool.allocate(0, 4)
    with pool._lock:
        pool._free.append(pages[0])  # referenced page back on the free list
    with pytest.raises(KVSanitizerError) as ei:
        san.check("step")
    assert "free list" in str(ei.value)


def test_sanitizer_catches_slot_table_shape_drift():
    pool = _pool()
    san = KVSanitizer(pool)
    pool.allocate(0, 5)  # 2 pages
    with pool._lock:
        pool._slot_len[0] = 9  # claims 3 pages' worth of tokens
    with pytest.raises(KVSanitizerError) as ei:
        san.check("step")
    assert "slot 0" in str(ei.value)


def test_sanitizer_drain_flags_abandoned_slot_pages():
    pool = _pool()
    san = KVSanitizer(pool)
    pages = pool.allocate(0, 8)
    san.check("step")  # mid-run: a populated slot is normal
    with pytest.raises(KVSanitizerError) as ei:
        san.check("drain", drained=True)
    assert ei.value.where == "drain"
    assert sorted(ei.value.pages) == sorted(pages)
    assert "leaked pages at drain" in str(ei.value)


def test_sanitizer_accounts_radix_cache_and_pins():
    """Full holder set: slot + radix-cache nodes + a lookup pin, all
    attributed; then each holder exits and the drain audit passes."""
    pool = _pool(num_pages=32, page_size=4)
    cache = RadixPrefixCache(
        max_nodes=16, block=4, pool=pool, page_bytes=64,
    )
    san = KVSanitizer(pool, cache)
    ids = list(range(1, 14))  # 13 tokens -> 12-token (3-block) prefix
    pool.allocate(0, len(ids))
    cache.store_pages(ids, 0, pool.slot_pages(0))
    san.check("step")
    hit = cache.lookup_pages(ids, 0)
    assert hit is not None and len(hit["pages"]) == 3
    san.check("step")           # pin attributed
    cache.release(hit)          # admission mapped (or failed): pin drops
    san.check("step")
    pool.free(0)                # slot exits; cache still holds the prefix
    san.check("drain", drained=True)
    assert san.stats()["failures"] == 0
