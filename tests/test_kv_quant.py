"""int8 KV cache tests (cfg kv_quant="int8"): storage layout, numeric
closeness to the bf16 cache, and engine-path composition (chunked prefill,
speculation, prefix cache)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

CFG = {"preset": "llama-tiny", "dtype": "float32"}
QCFG = dict(CFG, kv_quant="int8")


@pytest.fixture(scope="module")
def parts():
    bundle = models.build_model("llama", CFG)
    qbundle = models.build_model("llama", QCFG)
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, qbundle, params


def test_cache_layout(parts):
    _, qbundle, _ = parts
    cache = qbundle.init_cache(2, 32)
    assert cache["k"].dtype == jnp.int8
    assert cache["v"].dtype == jnp.int8
    assert cache["k_scale"].shape == cache["k"].shape[:-1]
    assert cache["k_scale"].dtype == jnp.float32


def test_quantized_decode_close_to_fp(parts):
    """Prefill + decode over the int8 cache tracks the bf16-cache logits
    (relative L2 error bounded; int8 per-vector quantization is ~0.4% RMS)."""
    bundle, qbundle, params = parts
    ids = [5, 9, 2, 17, 33, 8, 1, 40]
    tokens = jnp.asarray([ids], jnp.int32)
    lens = jnp.asarray([len(ids)], jnp.int32)

    ref_logits, ref_cache = bundle.prefill(params, tokens, lens, bundle.init_cache(1, 32))
    q_logits, q_cache = qbundle.prefill(params, tokens, lens, qbundle.init_cache(1, 32))
    # prefill logits identical: prefill attends over live (unquantized) K/V
    np.testing.assert_allclose(
        np.asarray(ref_logits), np.asarray(q_logits), rtol=1e-5, atol=1e-5
    )

    nxt = jnp.argmax(ref_logits, axis=-1).astype(jnp.int32)
    for _ in range(4):
        ref_logits, ref_cache = bundle.decode(params, nxt, ref_cache)
        q_logits, q_cache = qbundle.decode(params, nxt, q_cache)
        a, b = np.asarray(ref_logits), np.asarray(q_logits)
        rel = np.linalg.norm(a - b) / (np.linalg.norm(a) + 1e-9)
        assert rel < 0.05, rel
        nxt = jnp.argmax(ref_logits, axis=-1).astype(jnp.int32)


def test_chunked_prefill_matches_full_prefill_quantized(parts):
    """Chunked prefill tracks full prefill under kv_quant. The paths are not
    bit-identical: full prefill attends over LIVE (unquantized) K/V while
    chunked prefill reads back what it quantized, so outputs differ by
    bounded quantization noise."""
    _, qbundle, params = parts
    ids = [(i * 7 + 3) % 256 for i in range(24)]

    def rel(a, b):
        a, b = np.asarray(a), np.asarray(b)
        return np.linalg.norm(a - b) / (np.linalg.norm(a) + 1e-9)

    full_logits, full_cache = qbundle.prefill(
        params, jnp.asarray([ids], jnp.int32),
        jnp.asarray([len(ids)], jnp.int32), qbundle.init_cache(1, 48),
    )
    cache = qbundle.init_cache(1, 48)
    c = 8
    for s in range(0, len(ids), c):
        seg = ids[s : s + c]
        seg_tokens = np.zeros((1, c), np.int32)
        seg_tokens[0, : len(seg)] = seg
        chunk_logits, cache = qbundle.prefill_chunk(
            params, jnp.asarray(seg_tokens), jnp.asarray([s], jnp.int32),
            jnp.asarray([len(seg) - 1], jnp.int32), cache,
        )
    assert rel(full_logits, chunk_logits) < 0.05
    nxt = jnp.argmax(full_logits, axis=-1).astype(jnp.int32)
    l1, _ = qbundle.decode(params, nxt, full_cache)
    l2, _ = qbundle.decode(params, nxt, cache)
    assert rel(l1, l2) < 0.05


def _engine(bundle, params, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("prefill_buckets", [16, 32])
    kw.setdefault("eos_token_id", None)
    kw.setdefault("decode_steps", 2)
    return LLMEngineCore(bundle, params, **kw)


def _gen(engine, prompt, n=8, **req_kw):
    async def run():
        req = GenRequest(prompt_ids=list(prompt), max_new_tokens=n, **req_kw)
        out = [t async for t in engine.generate(req)]
        # wait out in-flight pipelined chunks so page accounting is final
        # before the paged assertions below
        await engine.wait_drained()
        return out

    return asyncio.run(run())


def test_engine_generates_deterministically(parts):
    _, qbundle, params = parts
    prompt = [5, 9, 2, 17, 33]
    e1 = _engine(qbundle, params)
    a = _gen(e1, prompt)
    e1.stop()
    e2 = _engine(qbundle, params)
    b = _gen(e2, prompt)
    e2.stop()
    assert a == b and len(a) == 8


def test_speculation_exact_under_kv_quant(parts):
    """Greedy n-gram speculation must stay token-identical to the plain
    chunk when both run over the int8 cache (verify shares the cache math)."""
    _, qbundle, params = parts
    prompt = [5, 9, 2, 17, 5, 9, 2]
    plain = _engine(qbundle, params)
    want = _gen(plain, prompt)
    plain.stop()
    spec = _engine(qbundle, params, speculation="ngram", spec_k=2, spec_ngram=2)
    got = _gen(spec, prompt)
    spec.stop()
    assert got == want


def test_prefix_cache_composes_with_kv_quant(parts):
    _, qbundle, params = parts
    prompt = [(i * 5 + 1) % 256 for i in range(40)]
    plain = _engine(qbundle, params, max_seq_len=160, prefill_buckets=[32, 64])
    want = _gen(plain, prompt, n=6)
    plain.stop()
    cached = _engine(
        qbundle, params, max_seq_len=160, prefill_buckets=[32, 64],
        prefix_cache=4, prefix_block=16,
    )
    first = _gen(cached, prompt, n=6)
    second = _gen(cached, prompt, n=6)
    assert cached._prefix.hits >= 1
    cached.stop()
    assert first == want
    assert second == want


def test_paged_engine_accepts_kv_quant(parts):
    """The paged backend serves kv_quant=int8 (int8 page pools + per-page
    scale rows, docs/paged_kv_quant.md): greedy streams match the dense
    int8 engine byte for byte — both quantize identically via _kv_store
    and dequantize in f32 before attending."""
    _, qbundle, params = parts
    prompt = [5, 9, 2, 17, 33]
    paged = _engine(qbundle, params, cache_mode="paged")
    assert paged.paged_cache.pool_dtype == "int8"
    assert paged.paged_cache.has_scales
    a = _gen(paged, prompt)
    pool = paged.paged_cache.pool
    assert pool.free_pages == pool.num_pages - 1  # drained: no leaked pages
    paged.stop()
    dense = _engine(qbundle, params, cache_mode="dense")
    b = _gen(dense, prompt)
    dense.stop()
    assert a == b and len(a) == 8


def test_paged_speculation_exact_under_kv_quant(parts):
    """Greedy n-gram speculation over int8 paged pools stays token-identical
    to the plain int8 paged chunk (verify_paged quantizes/dequantizes with
    the same scale pools the decode kernel reads)."""
    _, qbundle, params = parts
    prompt = [5, 9, 2, 17, 5, 9, 2]
    plain = _engine(qbundle, params, cache_mode="paged")
    want = _gen(plain, prompt)
    plain.stop()
    spec = _engine(
        qbundle, params, cache_mode="paged",
        speculation="ngram", spec_k=2, spec_ngram=2,
    )
    got = _gen(spec, prompt)
    spec.stop()
    assert got == want


def test_paged_prefix_cache_composes_with_kv_quant(parts, monkeypatch):
    """Radix shared-prefix reuse over int8 pools: shared pages carry their
    scale rows by page id, so warm admissions must replay the cold stream
    exactly — audited by the armed KV sanitizer (scale-row lifecycle)."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    _, qbundle, params = parts
    prompt = [(i * 5 + 1) % 256 for i in range(40)]
    plain = _engine(
        qbundle, params, cache_mode="paged", max_seq_len=160,
        prefill_buckets=[32, 64],
    )
    want = _gen(plain, prompt, n=6)
    plain.stop()
    cached = _engine(
        qbundle, params, cache_mode="paged", max_seq_len=160,
        prefill_buckets=[32, 64], prefix_cache=4, prefix_block=16,
    )
    first = _gen(cached, prompt, n=6)
    second = _gen(cached, prompt, n=6)
    assert cached._prefix.hits >= 1
    cached.stop()
    assert first == want
    assert second == want
