"""Host-RAM KV tiering tests (docs/kv_tiering.md): the HostKVTier
allocator, demote/promote byte round-trips at the pool level, run-level LRU
demotion vs pinned runs, the sanitizer's two-tier invariants, engine
stream byte-identity across a demote→promote cycle (both schedulers, both
pipeline depths, greedy + seeded, int8 KV, armed sanitizer), the chaos
fallback paths for the ``engine.kv.demote``/``engine.kv.promote`` seams,
and the committed ``--kv-tier-ab`` CPU artifact's schema + headline."""

import asyncio
import json
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.llm import faults
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
from clearml_serving_tpu.llm.kv_cache import HostKVTier, PagedKVCache
from clearml_serving_tpu.llm.kv_sanitizer import KVSanitizer, KVSanitizerError
from clearml_serving_tpu.llm.prefix_cache import RadixPrefixCache

REPO = Path(__file__).resolve().parent.parent

QCFG = {"preset": "llama-tiny", "dtype": "float32", "kv_quant": "int8"}


@pytest.fixture(autouse=True)
def _armed_sanitizer(monkeypatch):
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def parts():
    bundle = models.build_model("llama", QCFG)
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, params


# -- HostKVTier allocator ------------------------------------------------------


def test_host_tier_allocator_roundtrip():
    tier = HostKVTier(4, 8, 2, 2, 16, dtype=np.int8, quantized=True)
    assert tier.free_pages == 4 and tier.used_pages == 0
    ids = tier.allocate(3)
    assert len(set(ids)) == 3 and tier.used_pages == 3
    with pytest.raises(MemoryError):
        tier.allocate(2)
    tier.free(ids[:2])
    assert tier.free_pages == 3
    with pytest.raises(RuntimeError):
        tier.free([ids[0]])  # double free
    snap = tier.snapshot()
    assert len(snap["free"]) + len(snap["used"]) == snap["num_pages"]
    assert tier.hk_scale is not None and tier.quantized
    # page_bytes covers K+V slabs and both scale rows
    assert tier.page_bytes == 2 * tier.hk[0].nbytes + 2 * tier.hk_scale[0].nbytes


# -- pool-level demote/promote byte round-trip --------------------------------


def _tiered_parts(num_pages=9, host_pages=6, page_size=4, head_dim=8):
    pc = PagedKVCache(
        2, 2, head_dim, num_pages=num_pages, page_size=page_size,
        max_slots=2, kv_quant="int8",
    )
    pc.enable_host_tier(host_pages)
    cache = RadixPrefixCache(
        block=page_size, pool=pc.pool, page_bytes=64, backend=pc,
    )
    return pc, cache


def _fill_slot(pc, slot, tokens, seed=0):
    L, H, D = pc.k.shape[0], pc.k.shape[1], pc.k.shape[4]
    rng = np.random.default_rng(seed)
    k = rng.integers(-100, 100, (L, tokens, H, D)).astype(np.int8)
    v = rng.integers(-100, 100, (L, tokens, H, D)).astype(np.int8)
    ks = rng.random((L, tokens, H)).astype(np.float32)
    vs = rng.random((L, tokens, H)).astype(np.float32)
    pc.pool.allocate(slot, tokens)
    pc._scatter_pages(
        pc.pool.slot_pages(slot), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(ks), jnp.asarray(vs),
    )


def test_demote_promote_pages_byte_identical():
    pc, cache = _tiered_parts()
    ids = list(range(9))
    _fill_slot(pc, 0, 9)
    cache.store_pages(ids, 0, pc.pool.slot_pages(0))
    run_pages = pc.pool.slot_pages(0)[:2]
    before = {
        "k": np.asarray(pc.k[:, :, run_pages]).copy(),
        "v": np.asarray(pc.v[:, :, run_pages]).copy(),
        "ks": np.asarray(pc.k_scale[:, :, run_pages]).copy(),
        "vs": np.asarray(pc.v_scale[:, :, run_pages]).copy(),
    }
    pc.pool.free(0)
    sanitizer = KVSanitizer(pc.pool, prefix_cache=cache, paged_cache=pc)
    moved = cache.spill(0)
    assert moved == 2
    sanitizer.check("post-demote", drained=True)
    hit = cache.lookup_pages(ids)
    assert hit is not None and hit["tier"] == "host"
    sanitizer.check("post-promote")
    after_pages = hit["pages"]
    assert np.array_equal(before["k"], np.asarray(pc.k[:, :, after_pages]))
    assert np.array_equal(before["v"], np.asarray(pc.v[:, :, after_pages]))
    # the scale rows demoted and promoted WITH their pages
    assert np.array_equal(
        before["ks"], np.asarray(pc.k_scale[:, :, after_pages])
    )
    assert np.array_equal(
        before["vs"], np.asarray(pc.v_scale[:, :, after_pages])
    )
    pc.reap_promotions(force=True)
    stats = pc.tier_stats()
    assert stats["demoted_pages_total"] == 2
    assert stats["promoted_pages_total"] == 2
    assert stats["promotions_reaped"] == 1
    cache.release(hit)
    sanitizer.check("end", drained=True)


def test_bf16_pools_tier_without_scales():
    """Unquantized pools tier too: bf16 slabs, no scale buffers."""
    pc = PagedKVCache(2, 2, 8, num_pages=9, page_size=4, max_slots=2,
                      dtype="bfloat16")
    pc.enable_host_tier(6)
    cache = RadixPrefixCache(block=4, pool=pc.pool, page_bytes=64,
                             backend=pc)
    L, S, H, D = 2, 9, 2, 8
    k = jnp.arange(L * S * H * D, dtype=jnp.float32).reshape(
        L, S, H, D
    ).astype(jnp.bfloat16)
    pc.pool.allocate(0, 9)
    pc._scatter_pages(pc.pool.slot_pages(0), k, k + 1)
    ids = list(range(9))
    cache.store_pages(ids, 0, pc.pool.slot_pages(0))
    before = np.asarray(
        pc.k[:, :, pc.pool.slot_pages(0)[:2]].astype(jnp.float32)
    ).copy()
    pc.pool.free(0)
    assert cache.spill(0) == 2
    hit = cache.lookup_pages(ids)
    assert hit["tier"] == "host"
    after = np.asarray(pc.k[:, :, hit["pages"]].astype(jnp.float32))
    assert np.array_equal(before, after)
    assert pc.host_tier.hk_scale is None and not pc.host_tier.quantized
    cache.release(hit)
    pc.reap_promotions(force=True)
    KVSanitizer(pc.pool, prefix_cache=cache, paged_cache=pc).check(
        "bf16", drained=True
    )


def test_second_lookup_after_promotion_is_hbm():
    pc, cache = _tiered_parts()
    ids = list(range(9))
    _fill_slot(pc, 0, 9)
    cache.store_pages(ids, 0, pc.pool.slot_pages(0))
    pc.pool.free(0)
    cache.spill(0)
    first = cache.lookup_pages(ids)
    cache.release(first)
    second = cache.lookup_pages(ids)
    assert second["tier"] == "hbm"  # promoted in place: resident again
    cache.release(second)
    assert cache.stats()["hits_by_tier"] == {"hbm": 1, "host": 1}


def test_match_len_counts_resident_run_only():
    pc, cache = _tiered_parts()
    ids = list(range(9))
    _fill_slot(pc, 0, 9)
    cache.store_pages(ids, 0, pc.pool.slot_pages(0))
    pc.pool.free(0)
    assert cache.match_len(ids) == 8
    cache.spill(0)
    # demoted pages will need fresh device allocations at promotion: the
    # admission headroom check must not subtract them
    assert cache.match_len(ids) == 0


# -- LRU / budgets / pins ------------------------------------------------------


def test_device_budget_demotes_lru_run_whole(monkeypatch):
    """Storing a new run over the device budget demotes the OLD run top to
    bottom (run-level LRU) — the new run stays fully resident."""
    pc = PagedKVCache(2, 2, 8, num_pages=17, page_size=4, max_slots=2,
                      kv_quant="int8")
    pc.enable_host_tier(8)
    cache = RadixPrefixCache(
        block=4, pool=pc.pool, page_bytes=64, backend=pc, max_pages=2,
    )
    a, b = list(range(9)), list(range(100, 109))
    _fill_slot(pc, 0, 9, seed=1)
    cache.store_pages(a, 0, pc.pool.slot_pages(0))
    _fill_slot(pc, 1, 9, seed=2)
    cache.store_pages(b, 0, pc.pool.slot_pages(1))
    s = cache.stats()
    assert s["cached_pages"] == 2 and s["host_pages"] == 2
    assert s["demotions"] == 1  # one batched round moved the whole run
    # run B resident (hbm hit), run A demoted (host hit)
    hit_b = cache.lookup_pages(b)
    assert hit_b["tier"] == "hbm"
    cache.release(hit_b)
    hit_a = cache.lookup_pages(a)
    assert hit_a["tier"] == "host"
    cache.release(hit_a)
    pc.pool.free(0)
    pc.pool.free(1)
    KVSanitizer(pc.pool, prefix_cache=cache, paged_cache=pc).check(
        "lru", drained=True
    )


def test_host_budget_drops_lru_but_skips_pinned():
    """Host-tier LRU drops for real under the host budget; pinned runs are
    immune to BOTH motions (never demoted, never host-dropped)."""
    pc = PagedKVCache(2, 2, 8, num_pages=33, page_size=4, max_slots=4,
                      kv_quant="int8")
    pc.enable_host_tier(16)
    cache = RadixPrefixCache(
        block=4, pool=pc.pool, page_bytes=64, backend=pc,
        host_max_pages=2,
    )
    runs = [list(range(i * 100, i * 100 + 9)) for i in range(3)]
    for slot, ids in enumerate(runs):
        _fill_slot(pc, slot, 9, seed=slot)
        cache.store_pages(ids, 0, pc.pool.slot_pages(slot))
        pc.pool.free(slot)
    pin = cache.pin_run(runs[0])
    assert pin is not None and pin["host_nodes"] == 0
    # 4 unpinned pages demote into a 2-page host budget: the older host
    # run (run 1) LRU-drops for real; the pinned run 0 stays RESIDENT
    cache.spill(0)
    s = cache.stats()
    assert s["host_pages"] == 2 and s["cached_pages"] == 2
    hit0 = cache.lookup_pages(runs[0])
    assert hit0 is not None and hit0["tier"] == "hbm"   # pinned: resident
    cache.release(hit0)
    assert cache.lookup_pages(runs[1]) is None  # LRU victim dropped for real
    hit2 = cache.lookup_pages(runs[2])
    assert hit2 is not None and hit2["tier"] == "host"
    cache.release(hit2)
    # a pin taken on a DEMOTED run reports the promotion plan
    pin2 = cache.pin_run(runs[2])
    assert pin2 is not None and pin2["host_nodes"] == 0  # just promoted
    cache.unpin_run(pin2)
    cache.unpin_run(pin)
    KVSanitizer(pc.pool, prefix_cache=cache, paged_cache=pc).check(
        "host-lru", drained=True
    )


def test_pinned_runs_are_never_demoted():
    pc, cache = _tiered_parts(num_pages=17, host_pages=8)
    ids = list(range(9))
    _fill_slot(pc, 0, 9)
    cache.store_pages(ids, 0, pc.pool.slot_pages(0))
    pc.pool.free(0)
    pin = cache.pin_run(ids)
    assert cache.spill(0) == 0  # whole run pinned: nothing to demote
    assert cache.stats()["cached_pages"] == 2
    cache.unpin_run(pin)
    assert cache.spill(0) == 2


def test_store_reonlines_demoted_path_by_reference():
    """A store whose walk crosses demoted nodes re-points them at the
    admitting slot's own pages (zero copies) before attaching below."""
    pc, cache = _tiered_parts(num_pages=17, host_pages=8)
    ids = list(range(13))  # 12 cacheable tokens = 3 blocks
    _fill_slot(pc, 0, 13)
    cache.store_pages(ids[:9], 0, pc.pool.slot_pages(0))  # 2 blocks
    cache.spill(0)
    assert cache.stats()["host_pages"] == 2
    cache.store_pages(ids, 0, pc.pool.slot_pages(0))      # extends to 3
    s = cache.stats()
    assert s["host_pages"] == 0 and s["cached_pages"] == 3
    assert s["promotions"] == 1  # one run re-onlined by reference
    hit = cache.lookup_pages(ids)
    assert hit["tier"] == "hbm" and hit["len"] == 12
    cache.release(hit)
    pc.pool.free(0)
    KVSanitizer(pc.pool, prefix_cache=cache, paged_cache=pc).check(
        "reonline", drained=True
    )


def test_promotion_pool_pressure_falls_back_to_resident_prefix():
    """No free device pages for the promotion: the demoted suffix drops
    and the hit shortens (recompute), leak-free."""
    pc, cache = _tiered_parts(num_pages=9, host_pages=8)
    ids = list(range(9))
    _fill_slot(pc, 0, 9)
    cache.store_pages(ids, 0, pc.pool.slot_pages(0))
    cache.spill(0)
    pc.pool.free(0)
    # grab every free page so allocate_cache_pages must fail
    hog = pc.pool.allocate(1, 8 * pc.pool.page_size)
    assert hog is not None
    hit = cache.lookup_pages(ids)
    assert hit is None  # whole run was demoted; nothing resident remains
    assert cache.stats()["host_pages"] == 0  # dropped, not leaked
    pc.pool.free(1)
    KVSanitizer(pc.pool, prefix_cache=cache, paged_cache=pc).check(
        "fallback", drained=True
    )


def test_promotion_failure_never_drops_pinned_suffix():
    """A pin_run holder was PROMISED its (demoted) history survives: a
    different request's failed promotion must not drop the pinned suffix —
    the hit shortens, the pinned run stays for the pin holder's resume."""
    pc, cache = _tiered_parts(num_pages=9, host_pages=8)
    ids = list(range(9))
    _fill_slot(pc, 0, 9)
    cache.store_pages(ids, 0, pc.pool.slot_pages(0))
    cache.spill(0)
    pc.pool.free(0)
    pin = cache.pin_run(ids)
    assert pin is not None and pin["host_nodes"] == 2
    # exhaust the pool so promotion's allocate_cache_pages must fail
    pc.pool.allocate(1, 8 * pc.pool.page_size)
    hit = cache.lookup_pages(ids)
    assert hit is None  # fully demoted run: hit degrades to a miss
    # ...but the pinned host run SURVIVED for the pin holder
    assert cache.stats()["host_pages"] == 2
    pc.pool.free(1)
    resumed = cache.lookup_pages(ids)
    assert resumed is not None and resumed["tier"] == "host"
    cache.release(resumed)
    cache.unpin_run(pin)
    KVSanitizer(pc.pool, prefix_cache=cache, paged_cache=pc).check(
        "pinned-survives", drained=True
    )


def test_host_tier_knob_validation(parts):
    """Inert host-tier configs fail at construction (= endpoint load),
    naming the knob — a budget that silently does nothing reads as
    'tiering on' to the operator."""
    bundle, params = parts
    with pytest.raises(ValueError, match="prefix_cache_host_pages"):
        _engine(bundle, params, prefix_cache_host_bytes=1 << 20)
    with pytest.raises(ValueError, match="cache_mode='paged'"):
        _engine(bundle, params, host_pages=16, cache_mode="dense")
    with pytest.raises(ValueError, match="prefix_cache"):
        _engine(bundle, params, host_pages=16, prefix_cache=None)


# -- sanitizer two-tier violations --------------------------------------------


def test_sanitizer_catches_dual_payload_node():
    pc, cache = _tiered_parts()
    ids = list(range(9))
    _fill_slot(pc, 0, 9)
    cache.store_pages(ids, 0, pc.pool.slot_pages(0))
    cache.spill(0)
    node = next(iter(cache._leaf_nodes))
    node.pages = [1]  # corrupt: both tiers at once
    with pytest.raises(KVSanitizerError, match="exactly one tier"):
        KVSanitizer(pc.pool, prefix_cache=cache, paged_cache=pc).check("dual")


def test_sanitizer_catches_orphaned_host_page():
    pc, cache = _tiered_parts()
    ids = list(range(9))
    _fill_slot(pc, 0, 9)
    cache.store_pages(ids, 0, pc.pool.slot_pages(0))
    cache.spill(0)
    pc.host_tier.allocate(1)  # allocated but referenced by no node
    with pytest.raises(KVSanitizerError, match="ownership"):
        KVSanitizer(pc.pool, prefix_cache=cache, paged_cache=pc).check("orphan")


def test_sanitizer_catches_host_free_list_corruption():
    pc, cache = _tiered_parts()
    pc.host_tier._free.append(pc.host_tier._free[-1])  # duplicate id
    with pytest.raises(KVSanitizerError, match="duplicates"):
        KVSanitizer(pc.pool, prefix_cache=cache, paged_cache=pc).check("dupe")


def test_sanitizer_catches_lost_host_free():
    """A dropped node that forgot to free its host ids leaves the id
    allocated-but-unreferenced — the drain audit names it."""
    pc, cache = _tiered_parts()
    ids = list(range(9))
    _fill_slot(pc, 0, 9)
    cache.store_pages(ids, 0, pc.pool.slot_pages(0))
    cache.spill(0)
    pc.pool.free(0)
    # simulate the bug: node dropped without HostKVTier.free
    node = next(iter(cache._leaf_nodes))
    node.host_pages = None
    with pytest.raises(KVSanitizerError):
        KVSanitizer(pc.pool, prefix_cache=cache, paged_cache=pc).check(
            "lost-free", drained=True
        )


# -- engine byte-identity ------------------------------------------------------


def _engine(bundle, params, host_pages=None, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("prefill_buckets", [16, 32, 64])
    kw.setdefault("eos_token_id", None)
    kw.setdefault("decode_steps", 2)
    kw.setdefault("cache_mode", "paged")
    kw.setdefault("page_size", 16)
    kw.setdefault("prefix_cache", 64)
    kw.setdefault("prefix_block", 16)
    if host_pages:
        kw["prefix_cache_host_pages"] = host_pages
    return LLMEngineCore(bundle, params, **kw)


def _gen(engine, prompt, n=8, **req_kw):
    async def run():
        req = GenRequest(prompt_ids=list(prompt), max_new_tokens=n, **req_kw)
        out = [t async for t in engine.generate(req)]
        await engine.wait_drained()
        return out

    return asyncio.run(run())


PROMPT = [(7 * i + 3) % 100 + 1 for i in range(40)]  # 2 cached blocks


@pytest.mark.parametrize("scheduler", ["two_dispatch", "ragged"])
@pytest.mark.parametrize("depth", [1, 2])
def test_demoted_warm_hit_streams_byte_identical(parts, scheduler, depth):
    """ACCEPTANCE: a demoted-then-promoted prefix run produces streams
    byte-identical to an always-resident warm hit — greedy, int8 KV, both
    schedulers, pipeline depth 1 and 2, armed sanitizer."""
    bundle, params = parts
    control = _engine(bundle, params, scheduler=scheduler,
                      pipeline_depth=depth)
    _gen(control, PROMPT)
    resident = _gen(control, PROMPT)
    assert control._prefix.stats()["hits_by_tier"]["hbm"] >= 1
    control.stop()

    tiered = _engine(bundle, params, host_pages=16, scheduler=scheduler,
                     pipeline_depth=depth)
    _gen(tiered, PROMPT)
    assert tiered._prefix.spill(0) == 2
    promoted = _gen(tiered, PROMPT)
    assert promoted == resident
    stats = tiered.lifecycle_stats()["kv_tier"]
    assert stats["hits_by_tier"]["host"] >= 1
    assert stats["demoted_pages_total"] == 2
    assert stats["promoted_pages_total"] == 2
    tiered.stop()


def test_demoted_warm_hit_seeded_sampling_replays(parts):
    bundle, params = parts
    engine = _engine(bundle, params, host_pages=16)
    a = _gen(engine, PROMPT, temperature=0.8, seed=1234)
    engine._prefix.spill(0)
    b = _gen(engine, PROMPT, temperature=0.8, seed=1234)
    assert a == b
    assert engine.lifecycle_stats()["kv_tier"]["hits_by_tier"]["host"] >= 1
    engine.stop()


# -- chaos: fault seams --------------------------------------------------------


def test_chaos_promote_fault_falls_back_to_recompute(parts):
    """Injected engine.kv.promote mid-admission: the hit degrades to a
    recompute, the stream is unchanged, and nothing leaks (armed
    sanitizer + explicit drained audit)."""
    bundle, params = parts
    engine = _engine(bundle, params, host_pages=16)
    cold = _gen(engine, PROMPT)
    engine._prefix.spill(0)
    faults.configure([
        {"point": "engine.kv.promote", "action": "raise", "times": 1},
    ])
    try:
        warm = _gen(engine, PROMPT)
    finally:
        faults.clear()
    assert warm == cold
    s = engine._prefix.stats()
    assert s["host_pages"] == 0      # demoted suffix dropped, ids freed
    assert s["hits_by_tier"]["host"] == 0
    assert engine._sanitizer is not None
    assert engine._sanitizer.failures == 0
    engine.stop()


def test_chaos_demote_fault_drops_for_real(parts):
    """Injected engine.kv.demote: eviction drops instead of demoting —
    the next visit is a cold recompute but accounting stays clean."""
    bundle, params = parts
    engine = _engine(bundle, params, host_pages=16,
                     prefix_cache_pages=2)
    cold = _gen(engine, PROMPT)
    other = [(11 * i + 5) % 100 + 1 for i in range(40)]
    faults.configure([
        {"point": "engine.kv.demote", "action": "raise", "times": -1},
    ])
    try:
        _gen(engine, other)  # stores over budget: eviction must drop
    finally:
        faults.clear()
    s = engine._prefix.stats()
    assert s["host_pages"] == 0 and s["demotions"] == 0
    assert s["evictions"] >= 1
    warm = _gen(engine, PROMPT)
    assert warm == cold
    assert engine._sanitizer is not None and engine._sanitizer.failures == 0
    engine.stop()


# -- committed --kv-tier-ab artifact ------------------------------------------


def _artifact():
    return json.loads(
        (REPO / "benchmarks" / "KV_TIER_AB_cpu.json").read_text()
    )


def test_kv_tier_artifact_schema():
    row = _artifact()
    assert row["metric"].startswith("llm_kv_tier_ab")
    for arm in ("tiered", "untiered"):
        assert {"ttft_ms", "warm_hits", "decode_tok_s",
                "sanitizer_checks", "sanitizer_violations"} <= set(row[arm])
        assert {"cold", "hbm", "host", "warm_cold"} <= set(
            row[arm]["ttft_ms"]
        )
    assert row["working_set_pages"] > row["device_cache_pages"], (
        "the trace must overflow the device prefix-cache budget"
    )
    assert {"value", "unit", "identical_streams", "host_pages"} <= set(row)


def test_kv_tier_artifact_headline():
    """ACCEPTANCE: streams byte-identical, zero sanitizer violations, and
    host-tier warm TTFT well under cold-prefill TTFT on a working set
    larger than the device pool budget."""
    row = _artifact()
    assert row["identical_streams"] is True
    tiered, untiered = row["tiered"], row["untiered"]
    assert tiered["sanitizer_violations"] == 0
    assert untiered["sanitizer_violations"] == 0
    assert tiered["sanitizer_checks"] > 0
    # every warm revisit of the overflowed working set was a host hit in
    # the tiered arm and a cold recompute in the untiered arm
    assert tiered["warm_hits"]["host"] >= row["n_prefixes"] - 1
    assert untiered["warm_hits"]["cold"] == row["n_prefixes"]
    assert tiered["demotions"] > 0 and tiered["promotions"] > 0
    host = tiered["ttft_ms"]["host"]
    cold = tiered["ttft_ms"]["cold"]
    assert host is not None and cold is not None
    assert host < 0.7 * cold, (
        "host-tier warm TTFT must sit well under cold prefill "
        "(host={} cold={})".format(host, cold)
    )
    assert tiered["promo_overlap_ratio"] is not None
    assert 0.0 <= tiered["promo_overlap_ratio"] <= 1.0
