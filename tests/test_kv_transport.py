"""Disaggregated prefill/decode tests (docs/disaggregation.md): the
SharedSlabTransport mailbox, pool-level export/import byte round-trips,
``store_shipped`` attach semantics, the host-tier auto-sizer, role-aware
routing, and the group end-to-end contracts — two-replica disaggregated
streams exactly equal monolithic single-replica streams (greedy + seeded,
int8 paged KV, armed sanitizer), ship/receive chaos fallbacks, and the
kill-prefill-replica-mid-ship drain."""

import asyncio

import jax
import numpy as np
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.errors import HostTierAutoSizeError
from clearml_serving_tpu.llm import faults
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
from clearml_serving_tpu.llm.kv_cache import (
    PagedKVCache,
    available_host_memory_bytes,
)
from clearml_serving_tpu.llm.kv_transport import (
    KVShipment,
    SharedSlabTransport,
    shipment_key,
)
from clearml_serving_tpu.llm.prefix_cache import RadixPrefixCache
from clearml_serving_tpu.llm.replica import ReplicaGroup
from clearml_serving_tpu.serving.replica_router import ReplicaRouter

QCFG = {"preset": "llama-tiny", "dtype": "float32", "kv_quant": "int8"}


@pytest.fixture(autouse=True)
def _armed_sanitizer(monkeypatch):
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def parts():
    bundle = models.build_model("llama", QCFG)
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, params


# -- shipment keys ------------------------------------------------------------


def test_shipment_key_block_aligned_and_lora_namespaced():
    ids = list(range(1, 20))
    k1 = shipment_key(ids, 8)
    # the final token never ships: any prompt sharing the storable prefix
    # derives the same key
    assert shipment_key(ids[:17], 8) == k1          # depth 16 both
    assert shipment_key(ids + [99], 8) == k1        # still depth 16
    assert shipment_key(ids + list(range(90, 96)), 8) != k1  # depth 24
    assert shipment_key(list(range(2, 21)), 8) != k1  # different tokens
    assert shipment_key(ids, 8, lora=1) != k1       # per-adapter namespace
    # block size reaches the key THROUGH the alignment depth (replicas in
    # one group share a block config, so sender and receiver agree)
    assert shipment_key(ids, 5) != k1               # depth 15, not 16


def _shipment(pages=2, page_size=4, value=7, quantized=False, **kw):
    shape = (pages, 1, 1, page_size, 2)
    hk = np.full(shape, value, np.int8)
    kwargs = dict(
        key=kw.pop("key", b"k" * 16), src="r0",
        prefix_len=pages * page_size, page_size=page_size, lora=0,
        hk=hk, hv=hk.copy(),
    )
    if quantized:
        kwargs["hk_scale"] = np.ones(shape[:-1], np.float32)
        kwargs["hv_scale"] = np.ones(shape[:-1], np.float32)
    kwargs.update(kw)
    return KVShipment(**kwargs)


# -- SharedSlabTransport mailbox ----------------------------------------------


def test_transport_send_recv_is_consume_once():
    t = SharedSlabTransport(capacity_pages=8)
    ep = t.register("decode")
    assert ep.recv(b"k" * 16) is None
    assert t.send("decode", _shipment()) is True
    got = ep.recv(b"k" * 16)
    assert got is not None and got.pages == 2
    assert ep.recv(b"k" * 16) is None       # consumed
    assert t.received == 1 and t.sent == 1 and t.dropped == 0


def test_transport_capacity_drops_oldest_first():
    t = SharedSlabTransport(capacity_pages=4)
    t.register("decode")
    assert t.send("decode", _shipment(key=b"a" * 16))
    assert t.send("decode", _shipment(key=b"b" * 16))
    # a third 2-page shipment exceeds the 4-page slab: the OLDEST ages out
    assert t.send("decode", _shipment(key=b"c" * 16))
    assert t.recv("decode", b"a" * 16) is None
    assert t.recv("decode", b"b" * 16) is not None
    assert t.recv("decode", b"c" * 16) is not None
    assert t.dropped == 1 and t.dropped_pages == 2


def test_transport_oversized_shipment_is_dropped_not_queued():
    t = SharedSlabTransport(capacity_pages=4)
    t.register("decode")
    assert t.send("decode", _shipment(pages=8, key=b"z" * 16)) is False
    assert t.dropped == 1
    assert t.recv("decode", b"z" * 16) is None


def test_transport_reship_replaces_stale_payload():
    t = SharedSlabTransport(capacity_pages=8)
    t.register("decode")
    t.send("decode", _shipment(value=1))
    t.send("decode", _shipment(value=2))
    got = t.recv("decode", b"k" * 16)
    assert int(got.hk[0, 0, 0, 0, 0]) == 2
    assert t.stats()["queued"]["decode"] == {"shipments": 0, "pages": 0}


def test_transport_rejects_nonpositive_capacity():
    with pytest.raises(ValueError):
        SharedSlabTransport(capacity_pages=0)


# -- pool-level export/import round trip --------------------------------------


def _paged(num_pages=9, page_size=4, kv_quant="int8"):
    return PagedKVCache(
        2, 2, 8, num_pages=num_pages, page_size=page_size, max_slots=2,
        kv_quant=kv_quant,
    )


def _fill_slot(pc, slot, tokens, seed=0):
    """Write deterministic prompt KV (+ scales on int8 pools) into a slot."""
    rng = np.random.default_rng(seed)
    shape = (2, tokens, 2, 8)   # [L, S, Hkv, D]
    if pc.kv_quant:
        k = rng.integers(-100, 100, shape).astype(np.int8)
        v = rng.integers(-100, 100, shape).astype(np.int8)
        ks = rng.random(shape[:-1], np.float32)
        vs = rng.random(shape[:-1], np.float32)
        pc.write_prompt(slot, k, v, tokens, ks, vs)
    else:
        k = rng.random(shape, np.float32)
        v = rng.random(shape, np.float32)
        pc.write_prompt(slot, k, v, tokens)


@pytest.mark.parametrize("kv_quant", ["int8", ""])
def test_export_import_roundtrip_bytes(kv_quant):
    src = _paged(kv_quant=kv_quant)
    dst = _paged(kv_quant=kv_quant)
    _fill_slot(src, 0, 8, seed=3)
    pages = src.pool.slot_pages(0)
    slabs = src.export_pages(pages)
    assert slabs["hk"].shape[0] == len(pages) == 2
    fresh = dst.pool.allocate_cache_pages(len(pages))
    dst.import_pages(
        slabs["hk"], slabs["hv"], fresh,
        slabs.get("hk_scale"), slabs.get("hv_scale"),
    )
    assert dst.reap_promotions(force=True) == 1
    out = dst.export_pages(fresh)
    for name in slabs:
        np.testing.assert_array_equal(slabs[name], out[name])
    dst.pool.unref_pages(fresh)
    src.pool.free(0)


def test_import_pages_validates_scales_and_row_count():
    dst = _paged(kv_quant="int8")
    rows = np.zeros((2, 2, 2, 4, 8), np.int8)
    with pytest.raises(ValueError):
        dst.import_pages(rows, rows, [1, 2])        # int8 pool, no scales
    with pytest.raises(ValueError):
        dst.import_pages(rows, rows, [1, 2, 3],
                         np.zeros((2, 2, 2, 4), np.float32),
                         np.zeros((2, 2, 2, 4), np.float32))  # 2 rows != 3


# -- store_shipped (radix attach) ---------------------------------------------


def _export_shipment(pc, slot, ids, block):
    p = ((len(ids) - 1) // block) * block
    pages = pc.pool.slot_pages(slot)[: p // pc.pool.page_size]
    slabs = pc.export_pages(pages)
    return KVShipment(
        key=shipment_key(ids, block, 0), src="r0", prefix_len=p,
        page_size=pc.pool.page_size, lora=0,
        hk=slabs["hk"], hv=slabs["hv"],
        hk_scale=slabs.get("hk_scale"), hv_scale=slabs.get("hv_scale"),
    )


def test_store_shipped_attaches_only_missing_blocks():
    from clearml_serving_tpu.llm.kv_sanitizer import KVSanitizer

    block = 4
    src = _paged()
    ids = list(range(10, 23))    # 13 tokens -> 12 storable = 3 blocks
    _fill_slot(src, 0, 13, seed=5)
    shipment = _export_shipment(src, 0, ids, block)
    assert shipment.pages == 3

    dst = _paged(num_pages=17)
    cache = RadixPrefixCache(block=block, pool=dst.pool, page_bytes=64)
    # pre-store the FIRST block by reference from a live slot: the import
    # must then attach only the two missing blocks
    _fill_slot(dst, 0, 5, seed=6)
    cache.store_pages(ids[:5], 0, dst.pool.slot_pages(0))
    assert cache.match_len(ids) == block
    imported = cache.store_shipped(ids, 0, shipment, dst)
    assert imported == 2
    assert dst.reap_promotions(force=True) == 1
    assert cache.match_len(ids) == 12
    # re-import of the same shipment: nothing missing, nothing allocated
    assert cache.store_shipped(ids, 0, shipment, dst) == 0
    # a hit over the shipped run pins/maps like any radix hit
    hit = cache.lookup_pages(ids)
    assert hit is not None and hit["len"] == 12
    cache.release(hit)
    dst.pool.free(0)
    KVSanitizer(dst.pool, prefix_cache=cache).check("shipped", drained=True)


def test_store_shipped_validates_geometry():
    src = _paged()
    ids = list(range(9))
    _fill_slot(src, 0, 9, seed=1)
    shipment = _export_shipment(src, 0, ids, 4)
    dst_wrong_page = _paged(page_size=8)
    cache = RadixPrefixCache(
        block=8, pool=dst_wrong_page.pool, page_bytes=64
    )
    with pytest.raises(ValueError):
        cache.store_shipped(ids, 0, shipment, dst_wrong_page)
    # scale mismatch: strip the scales off an int8 shipment
    shipment.hk_scale = None
    shipment.hv_scale = None
    dst = _paged()
    cache2 = RadixPrefixCache(block=4, pool=dst.pool, page_bytes=64)
    with pytest.raises(ValueError):
        cache2.store_shipped(ids, 0, shipment, dst)


def test_store_shipped_pool_pressure_is_leak_free():
    from clearml_serving_tpu.llm.kv_sanitizer import KVSanitizer

    src = _paged()
    ids = list(range(13))
    _fill_slot(src, 0, 13, seed=2)
    shipment = _export_shipment(src, 0, ids, 4)
    dst = _paged(num_pages=3)    # 2 usable pages < the 3-page shipment
    cache = RadixPrefixCache(block=4, pool=dst.pool, page_bytes=64)
    with pytest.raises(MemoryError):
        cache.store_shipped(ids, 0, shipment, dst)
    assert cache.match_len(ids) == 0
    KVSanitizer(dst.pool, prefix_cache=cache).check("pressure", drained=True)


# -- host-tier auto-sizing (aux prefix_cache_host_mb: "auto") ------------------


def test_meminfo_probe_parses_and_names_failures(tmp_path):
    good = tmp_path / "meminfo"
    good.write_text("MemTotal: 100 kB\nMemAvailable:     2048 kB\n")
    assert available_host_memory_bytes(str(good)) == 2048 * 1024
    with pytest.raises(HostTierAutoSizeError, match="auto"):
        available_host_memory_bytes(str(tmp_path / "missing"))
    no_field = tmp_path / "nofield"
    no_field.write_text("MemTotal: 100 kB\n")
    with pytest.raises(HostTierAutoSizeError, match="MemAvailable"):
        available_host_memory_bytes(str(no_field))


def _auto_engine(bundle, params, monkeypatch, avail_bytes, **overrides):
    from clearml_serving_tpu.llm import kv_cache

    monkeypatch.setattr(
        kv_cache, "available_host_memory_bytes", lambda *a: avail_bytes
    )
    cfg = dict(
        max_batch=2, max_seq_len=64, prefill_buckets=[16, 32],
        eos_token_id=None, decode_steps=1, cache_mode="paged",
        page_size=16, prefix_cache=64, prefix_block=16,
        prefix_cache_host_bytes="auto",
    )
    cfg.update(overrides)
    return LLMEngineCore(bundle, params, **cfg)


def test_auto_host_tier_sizes_clamped_from_meminfo(parts, monkeypatch):
    from clearml_serving_tpu.llm.engine import (
        _AUTO_HOST_TIER_MIN_BYTES,
    )

    bundle, params = parts
    engine = _auto_engine(bundle, params, monkeypatch, 512 << 20)
    tier = engine.paged_cache.host_tier
    assert tier is not None
    page_bytes = (
        sum(engine.paged_cache.pool_bytes().values())
        // engine.paged_cache.pool.num_pages
    )
    assert tier.num_pages == max(1, (256 << 20) // page_bytes)
    engine.stop()
    # a tiny host still gets the clamp floor's worth of pages
    engine2 = _auto_engine(bundle, params, monkeypatch, 8 << 20)
    assert engine2.paged_cache.host_tier.num_pages == max(
        1, _AUTO_HOST_TIER_MIN_BYTES // page_bytes
    )
    engine2.stop()


def test_auto_host_tier_divides_by_cohosted_worker_count(parts, monkeypatch):
    """The half-of-MemAvailable heuristic is PER HOST: process-backend
    workers co-hosted on one machine (TPUSERVE_COHOSTED_PROCS,
    serving/process_replica.py) must split the budget, or an N-worker
    fleet over-commits host RAM N times over."""
    from clearml_serving_tpu.llm.kv_cache import cohosted_worker_processes

    bundle, params = parts
    monkeypatch.delenv("TPUSERVE_COHOSTED_PROCS", raising=False)
    assert cohosted_worker_processes() == 1
    solo = _auto_engine(bundle, params, monkeypatch, 4 << 30)
    solo_pages = solo.paged_cache.host_tier.num_pages
    solo.stop()

    monkeypatch.setenv("TPUSERVE_COHOSTED_PROCS", "2")
    assert cohosted_worker_processes() == 2
    duo = _auto_engine(bundle, params, monkeypatch, 4 << 30)
    assert duo.paged_cache.host_tier.num_pages == solo_pages // 2
    duo.stop()

    # garbage / sub-1 values degrade to the solo divisor, never crash
    monkeypatch.setenv("TPUSERVE_COHOSTED_PROCS", "banana")
    assert cohosted_worker_processes() == 1
    monkeypatch.setenv("TPUSERVE_COHOSTED_PROCS", "0")
    assert cohosted_worker_processes() == 1


def test_auto_host_tier_probe_failure_fails_construction(parts, monkeypatch):
    from clearml_serving_tpu.llm import kv_cache

    bundle, params = parts

    def boom(*a):
        raise HostTierAutoSizeError("no /proc/meminfo on this platform")

    monkeypatch.setattr(kv_cache, "available_host_memory_bytes", boom)
    cfg = dict(
        max_batch=2, max_seq_len=64, prefill_buckets=[16, 32],
        cache_mode="paged", page_size=16, prefix_cache=64,
        prefix_block=16, prefix_cache_host_bytes="auto",
    )
    with pytest.raises(HostTierAutoSizeError, match="platform"):
        LLMEngineCore(bundle, params, **cfg)


def test_auto_host_tier_knob_conflicts_are_named(parts):
    bundle, params = parts
    cfg = dict(
        max_batch=2, max_seq_len=64, prefill_buckets=[16, 32],
        cache_mode="paged", page_size=16, prefix_cache=64, prefix_block=16,
    )
    with pytest.raises(ValueError, match="prefix_cache_host_pages"):
        LLMEngineCore(
            bundle, params, prefix_cache_host_bytes="auto",
            prefix_cache_host_pages=8, **cfg
        )
    with pytest.raises(ValueError, match="auto"):
        LLMEngineCore(
            bundle, params, prefix_cache_host_bytes="always", **cfg
        )
    # auto on a dense engine fails like an explicit page count would
    with pytest.raises(ValueError, match="paged"):
        LLMEngineCore(
            bundle, params, max_batch=2, max_seq_len=64,
            prefill_buckets=[16, 32], cache_mode="dense",
            prefix_cache=64, prefix_block=16,
            prefix_cache_host_bytes="auto",
        )


# -- role-aware routing (stub level) ------------------------------------------


class StubReplica:
    def __init__(self, index, ready=True, warmed=True, depth=0, stage=0):
        self.index = index
        self.name = "r{}".format(index)
        self.engine_ready = ready
        self.warmed = warmed
        self.queue_depth = depth
        self.brownout_stage = stage
        self.warming = False

    def invalidate_warm(self):
        self.warmed = False

    def begin_warm(self):
        self.warmed = True


def _role_router(roles, stubs=None, **kw):
    stubs = stubs or [StubReplica(i) for i in range(len(roles))]
    return ReplicaRouter(
        stubs,
        roles={s.name: r for s, r in zip(stubs, roles)},
        **kw
    ), stubs


def _req(ids, priority="interactive"):
    return GenRequest(prompt_ids=list(ids), priority=priority)


def test_streams_route_to_decode_capable_members_only():
    router, stubs = _role_router(["prefill", "decode", "hybrid"])
    for seed in range(8):
        ids = [(seed * 31 + i) % 97 + 1 for i in range(40)]
        replica, route = router.pick(_req(ids))
        assert router.role_of(replica.name) in ("decode", "hybrid")


def test_empty_decode_class_degrades_to_any_ring_member():
    router, stubs = _role_router(["prefill", "decode"])
    stubs[1].engine_ready = False   # the only decode member leaves
    router.sweep()
    # hybrid degradation: the prefill-role member takes the stream
    # rather than shedding it (route label = HRW order within the
    # degraded candidate set)
    replica, route = router.pick(_req(list(range(40))))
    assert replica.name == "r0"
    assert route in ("affine", "rebalance")


def test_pick_prefill_prefers_dedicated_and_skips_brownout():
    router, stubs = _role_router(["prefill", "decode", "hybrid"])
    pre = router.pick_prefill(_req(list(range(40))), exclude="r1")
    assert pre is not None and pre.name == "r0"     # dedicated wins
    stubs[0].brownout_stage = 2                     # browned out: skip
    pre = router.pick_prefill(_req(list(range(40))), exclude="r1")
    assert pre is not None and pre.name == "r2"     # hybrid fallback
    stubs[2].engine_ready = False
    router.sweep()
    assert router.pick_prefill(_req(list(range(40))), exclude="r1") is None


def test_router_stats_carry_roles():
    router, _ = _role_router(["prefill", "decode"])
    stats = router.stats()
    assert stats["roles"] == {"r0": "prefill", "r1": "decode"}


def test_router_rejects_bad_roles():
    stubs = [StubReplica(0), StubReplica(1)]
    with pytest.raises(ValueError, match="role"):
        ReplicaRouter(stubs, roles={"r0": "decoder", "r1": "decode"})
    with pytest.raises(ValueError, match="unknown replica"):
        ReplicaRouter(stubs, roles={"rX": "decode"})


# -- group end-to-end (real engines, int8 paged KV) ---------------------------


def _make_group(bundle, params, n=2, roles=None, kv_backend="shared",
                **overrides):
    cfg = dict(
        max_batch=2, max_seq_len=128, prefill_buckets=[16, 32, 64],
        eos_token_id=None, decode_steps=1, cache_mode="paged",
        page_size=16, prefix_cache=64, prefix_block=16, num_pages=65,
        pipeline_depth=1,
    )
    cfg.update(overrides)
    engines = [
        LLMEngineCore(bundle, params, replica="r{}".format(i), **cfg)
        for i in range(n)
    ]
    return ReplicaGroup(engines, roles=roles, kv_transport_backend=kv_backend)


# both KV transport backends run the SAME chaos contracts (the socket
# variants are tier-2: they re-build full engine fleets, so they ride the
# `slow` lane alongside the process-backend suite)
BACKENDS = [
    "shared",
    pytest.param("socket", marks=pytest.mark.slow),
]


def _conv(seed, n=44):
    return [(seed * 29 + i * 7) % 200 + 1 for i in range(n)]


async def _collect(group, ids, n=5, **kw):
    request = GenRequest(prompt_ids=list(ids), max_new_tokens=n, **kw)
    out = []
    async for token in group.generate(request):
        out.append(int(token))
    return out, request


def _drained_clean(group):
    async def check():
        await group.wait_drained()

    asyncio.run(check())
    for replica in group.replicas:
        sanitizer = replica.engine._sanitizer
        assert sanitizer is not None
        assert sanitizer.stats()["failures"] == 0


def test_group_roles_validation():
    # length mismatch and bad values fail at construction (endpoint load)
    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    engines = [
        LLMEngineCore(
            bundle, params, max_batch=1, max_seq_len=32,
            prefill_buckets=[16], cache_mode="paged", page_size=16,
            prefix_cache=16, prefix_block=16,
        )
        for _ in range(2)
    ]
    with pytest.raises(ValueError, match="replica_roles"):
        ReplicaGroup(list(engines), roles=["prefill"])
    with pytest.raises(ValueError, match="prefill/decode/hybrid"):
        ReplicaGroup(list(engines), roles=["prefill", "decoder"])
    with pytest.raises(ValueError, match="decode-capable"):
        ReplicaGroup(list(engines), roles=["prefill", "prefill"])
    # dense engines cannot disaggregate (no pages to ship)
    dense = [
        LLMEngineCore(
            bundle, params, max_batch=1, max_seq_len=32,
            prefill_buckets=[16], cache_mode="dense",
        )
        for _ in range(2)
    ]
    with pytest.raises(ValueError, match="paged"):
        ReplicaGroup(dense, roles=["prefill", "decode"])
    for e in engines + dense:
        e.stop()


def test_group_rejects_unknown_kv_transport_backend(parts):
    bundle, params = parts
    engines = [
        LLMEngineCore(
            bundle, params, replica="r{}".format(i), max_batch=1,
            max_seq_len=32, prefill_buckets=[16], cache_mode="paged",
            page_size=16, prefix_cache=16, prefix_block=16,
        )
        for i in range(2)
    ]
    with pytest.raises(ValueError, match="kv_transport_backend"):
        ReplicaGroup(
            engines, roles=["prefill", "decode"],
            kv_transport_backend="carrier-pigeon",
        )
    for e in engines:
        e.stop()


def test_disagg_streams_equal_monolithic_greedy_and_seeded(parts):
    """The ISSUE-14 byte-identity contract: a two-replica disaggregated
    fleet's streams exactly equal a monolithic single replica's (greedy
    + seeded, int8 paged KV, armed sanitizer), and the decode replica's
    admissions HIT the shipped prefix (recompute none of the shipped
    KV)."""
    bundle, params = parts

    async def scenario():
        prompts = [_conv(1), _conv(2, n=60), _conv(3, n=33)]
        mono = _make_group(bundle, params, n=1)
        expected = []
        for i, ids in enumerate(prompts):
            expected.append((await _collect(mono, ids))[0])
        seeded_exp = (await _collect(mono, prompts[0], seed=77,
                                     temperature=0.8))[0]
        await mono.wait_drained()
        mono.stop()

        disagg = _make_group(
            bundle, params, n=2, roles=["prefill", "decode"]
        )
        got = []
        for ids in prompts:
            got.append((await _collect(disagg, ids))[0])
        seeded_got = (await _collect(disagg, prompts[0], seed=77,
                                     temperature=0.8))[0]
        assert got == expected
        assert seeded_got == seeded_exp
        decode = disagg.replicas[1].engine
        prefill = disagg.replicas[0].engine
        ship = decode._kv_ship_snapshot()
        assert ship["role"] == "decode"
        assert ship["receives"] >= 3 and ship["hits"] >= 3
        assert ship["recomputes"] == 0 and ship["hit_rate"] == 1.0
        sent = prefill._kv_ship_snapshot()
        assert sent["ships"] >= 3 and sent["ship_pages"] > 0
        assert disagg._disagg_snapshot()["ship_leg_failures"] == 0
        # the decode replica never ran a cold prefill for shipped work:
        # its prefix-cache hits cover every shipped admission
        await disagg.wait_drained()
        return disagg

    group = asyncio.run(scenario())
    _drained_clean(group)
    group.stop()


def test_warm_turns_skip_the_ship_leg(parts):
    bundle, params = parts

    async def scenario():
        group = _make_group(
            bundle, params, n=2, roles=["prefill", "decode"]
        )
        ids = _conv(9)
        await _collect(group, ids)
        legs0 = group.ship_legs
        await _collect(group, ids)      # same conversation: decode is warm
        assert group.ship_warm_skips >= 1
        assert group.ship_legs == legs0
        await group.wait_drained()
        return group

    group = asyncio.run(scenario())
    _drained_clean(group)
    group.stop()


@pytest.mark.parametrize("kv_backend", BACKENDS)
def test_ship_fault_falls_back_to_decode_recompute(parts, kv_backend):
    """Chaos: an injected ``engine.kv.ship`` fault at the prefill commit
    drops the shipment leak-free; the stream completes byte-identically
    via decode-side recompute and the drop is counted. Runs identically
    over the in-process slab and the socket wire."""
    bundle, params = parts

    async def scenario():
        ids = _conv(11)
        mono = _make_group(bundle, params, n=1)
        expected = (await _collect(mono, ids))[0]
        await mono.wait_drained()
        mono.stop()

        group = _make_group(
            bundle, params, n=2, roles=["prefill", "decode"],
            kv_backend=kv_backend,
        )
        faults.configure([
            {"point": "engine.kv.ship", "action": "raise"},
        ])
        try:
            got, _ = await _collect(group, ids)
        finally:
            faults.clear()
        assert got == expected
        prefill = group.replicas[0].engine._kv_ship_snapshot()
        decode = group.replicas[1].engine._kv_ship_snapshot()
        assert prefill["ship_drops"] >= 1 and prefill["ships"] == 0
        assert decode["receives"] == 0
        assert decode["recomputes"] >= 1 and decode["hits"] == 0
        await group.wait_drained()
        return group

    group = asyncio.run(scenario())
    _drained_clean(group)
    group.stop()


@pytest.mark.parametrize("kv_backend", BACKENDS)
def test_receive_fault_reroutes_to_hybrid(parts, kv_backend):
    """Chaos: an injected ``engine.kv.receive`` fault on the decode
    replica re-routes the stream to a hybrid-capable sibling (recompute
    there), leak-free and byte-identical. Runs identically over the
    in-process slab and the socket wire."""
    bundle, params = parts

    async def scenario():
        ids = _conv(13)
        mono = _make_group(bundle, params, n=1)
        expected = (await _collect(mono, ids))[0]
        await mono.wait_drained()
        mono.stop()

        group = _make_group(
            bundle, params, n=3, roles=["prefill", "decode", "hybrid"],
            kv_backend=kv_backend,
        )
        # route the stream at a DECODE-role member so the receive runs
        # there (a hybrid pick would already be the fallback)
        decode_name = next(
            r.name for r in group.replicas
            if group.router.role_of(r.name) == "decode"
        )
        faults.configure([
            {"point": "engine.kv.receive", "action": "raise", "times": 1},
        ])
        try:
            request = GenRequest(prompt_ids=list(ids), max_new_tokens=5)
            request._replica_name = decode_name
            got = []
            async for token in group.generate(request):
                got.append(int(token))
        finally:
            faults.clear()
        assert got == expected
        assert group.receive_reroutes == 1
        # the stream ran on the hybrid member, not the faulted decode one
        assert group.router.role_of(request._replica_name) == "hybrid"
        decode = group._replica_by_name(decode_name).engine
        assert decode._kv_ship_snapshot()["receive_failures"] == 1
        await group.wait_drained()
        return group

    group = asyncio.run(scenario())
    _drained_clean(group)
    group.stop()


@pytest.mark.parametrize("kv_backend", BACKENDS)
def test_kill_prefill_replica_mid_ship_resumes_on_remaining(parts, kv_backend):
    """Chaos: the prefill replica dies mid-ship-leg — the stream still
    completes on the decode replica (hybrid degradation: it prefills for
    itself), zero page leaks; once the prefill replica is gone entirely,
    later requests skip the leg (pick_prefill returns None). Runs
    identically over the in-process slab and the socket wire; the
    process-backend variant (real SIGKILL of the worker) lives in
    tests/test_process_replica.py."""
    bundle, params = parts

    async def scenario():
        ids = _conv(17)
        mono = _make_group(bundle, params, n=1)
        expected = (await _collect(mono, ids))[0]
        await mono.wait_drained()
        mono.stop()

        group = _make_group(
            bundle, params, n=2, roles=["prefill", "decode"],
            kv_backend=kv_backend,
        )
        # leg 1: the prefill replica fails MID-ADMISSION (raise inside
        # its prefill worker); the leg is best-effort so the stream
        # completes via decode-side recompute
        faults.configure([
            {"point": "engine.prefill", "action": "raise", "times": 1},
        ])
        try:
            got, _ = await _collect(group, ids)
        finally:
            faults.clear()
        assert got == expected
        assert group.ship_leg_failures == 1
        # now KILL the prefill replica outright: later disaggregated
        # requests degrade to hybrid (no leg at all), streams unaffected
        group.replicas[0].engine.stop()
        group.router.sweep()
        legs0 = group.ship_legs
        got2, _ = await _collect(group, _conv(18))
        assert len(got2) == 5
        assert group.ship_legs == legs0     # no prefill-capable member
        await group.replicas[1].engine.wait_drained()
        return group

    group = asyncio.run(scenario())
    for replica in group.replicas[1:]:
        sanitizer = replica.engine._sanitizer
        assert sanitizer is not None and sanitizer.stats()["failures"] == 0
    group.stop()


# -- draft-ahead KV shipping (docs/spec_decode_trees.md) ----------------------


def test_draft_ahead_overlaps_ragged_prefill_over_socket(parts):
    """The draft-ahead certificate's clean path, over the REAL wire: a
    ragged prefill replica ships storable pages at chunk boundaries
    (unsealed partial frames overlapping the prefill tail) and seals at
    commit; the decode replica's admission hits the shipped prefix, the
    stream is byte-identical to a monolithic replica's, and the overlap
    gauge is live (> 0)."""
    bundle, params = parts
    ragged = dict(scheduler="ragged", step_token_budget=16)

    async def scenario():
        ids = _conv(21, n=60)       # spans several 16-token ragged chunks
        mono = _make_group(bundle, params, n=1, **ragged)
        expected = (await _collect(mono, ids))[0]
        await mono.wait_drained()
        mono.stop()

        group = _make_group(
            bundle, params, n=2, roles=["prefill", "decode"],
            kv_backend="socket", **ragged,
        )
        got = (await _collect(group, ids))[0]
        assert got == expected
        prefill = group.replicas[0].engine._kv_ship_snapshot()
        decode = group.replicas[1].engine._kv_ship_snapshot()
        # the prefix head rode unsealed frames ahead of the commit seal
        assert prefill["draft_ships"] >= 1
        assert prefill["draft_pages"] >= 1
        assert prefill["draft_aborts"] == 0
        assert prefill["overlap_ratio"] > 0
        assert prefill["ships"] >= 1
        assert prefill["ship_pages"] > prefill["draft_pages"]  # seal pages
        # transport saw the assembly seal exactly once per ship
        transport = decode["transport"]
        assert transport["partial_frames"] >= 1
        assert transport["assembled"] == prefill["ships"]
        assert transport["assembly_drops"] == 0
        # decode replica recomputed none of the shipped prefix
        assert decode["receives"] >= 1 and decode["hits"] >= 1
        assert decode["recomputes"] == 0 and decode["hit_rate"] == 1.0
        await group.wait_drained()
        return group

    group = asyncio.run(scenario())
    _drained_clean(group)
    group.stop()


@pytest.mark.chaos
def test_partial_ship_fault_drops_to_recompute(parts):
    """Chaos: an injected ``kv.ship.partial`` fault mid-draft-ahead
    aborts the job's whole partial stream AND the commit seal — the
    receiver's unsealed assembly is never consumable, the decode replica
    recomputes, the stream stays byte-identical, and nothing leaks on
    either side."""
    bundle, params = parts
    ragged = dict(scheduler="ragged", step_token_budget=16)

    async def scenario():
        ids = _conv(23, n=60)
        mono = _make_group(bundle, params, n=1, **ragged)
        expected = (await _collect(mono, ids))[0]
        await mono.wait_drained()
        mono.stop()

        group = _make_group(
            bundle, params, n=2, roles=["prefill", "decode"], **ragged,
        )
        faults.configure([
            {"point": "kv.ship.partial", "action": "raise", "times": 1},
        ])
        try:
            got, _ = await _collect(group, ids)
        finally:
            faults.clear()
        assert got == expected
        prefill = group.replicas[0].engine._kv_ship_snapshot()
        decode = group.replicas[1].engine._kv_ship_snapshot()
        assert prefill["draft_aborts"] >= 1
        assert prefill["ships"] == 0            # the seal was skipped
        assert prefill["ship_drops"] >= 1
        assert decode["receives"] == 0
        assert decode["recomputes"] >= 1 and decode["hits"] == 0
        await group.wait_drained()
        return group

    group = asyncio.run(scenario())
    _drained_clean(group)
    group.stop()
