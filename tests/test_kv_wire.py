"""Wire-format KV transport tests (llm/kv_wire.py,
docs/disaggregation.md "process backends"): to_wire/from_wire byte
round-trips for bf16 and int8+scale-row slabs, every header/geometry/
dtype/key inconsistency raising the named WireFormatError with a
leak-free drop, truncated-frame receives mapping to drop-to-recompute,
and the socket endpoint keeping SharedSlabTransport's bounded-mailbox
semantics (overflow drops oldest, re-ship replaces, consume-once)."""

import socket
import struct
import time

import numpy as np
import pytest

from clearml_serving_tpu.llm import faults, lifecycle_ledger
from clearml_serving_tpu.llm.kv_transport import KVShipment
from clearml_serving_tpu.llm.kv_wire import (
    MAGIC,
    SocketSlabFabric,
    WireFormatError,
    shipment_from_wire,
    shipment_to_wire,
)

try:
    import ml_dtypes

    BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover - present in the jax image
    BF16 = None


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    lifecycle_ledger.get().reset(strict=False)
    yield
    faults.clear()
    lifecycle_ledger.get().reset(strict=False)
    lifecycle_ledger.disarm()


def _shipment(pages=2, page_size=4, dtype=np.int8, quantized=False, **kw):
    shape = (pages, 3, 2, page_size, 8)
    rng = np.random.default_rng(7)
    hk = rng.integers(-100, 100, size=shape).astype(dtype)
    hv = rng.integers(-100, 100, size=shape).astype(dtype)
    kwargs = dict(
        key=kw.pop("key", b"k" * 16), src="r0",
        prefix_len=pages * page_size, page_size=page_size, lora=0,
        hk=hk, hv=hv,
    )
    if quantized:
        kwargs["hk_scale"] = rng.random(shape[:-1]).astype(np.float32)
        kwargs["hv_scale"] = rng.random(shape[:-1]).astype(np.float32)
    kwargs.update(kw)
    return KVShipment(**kwargs)


def _assert_roundtrip(shipment):
    frame = shipment.to_wire()
    got = KVShipment.from_wire(frame)
    assert got.key == shipment.key
    assert got.src == shipment.src
    assert got.prefix_len == shipment.prefix_len
    assert got.page_size == shipment.page_size
    assert got.lora == shipment.lora
    # byte-identity, not just value-equality: the slabs re-attach verbatim
    assert got.hk.dtype == shipment.hk.dtype
    assert got.hk.tobytes() == shipment.hk.tobytes()
    assert got.hv.tobytes() == shipment.hv.tobytes()
    if shipment.quantized:
        assert got.quantized
        assert got.hk_scale.tobytes() == shipment.hk_scale.tobytes()
        assert got.hv_scale.tobytes() == shipment.hv_scale.tobytes()
    else:
        assert not got.quantized
    return got


# -- codec round-trips --------------------------------------------------------


@pytest.mark.skipif(BF16 is None, reason="ml_dtypes not installed")
def test_roundtrip_bf16():
    shape = (2, 3, 2, 4, 8)
    rng = np.random.default_rng(3)
    ship = _shipment(
        hk=rng.standard_normal(shape).astype(BF16),
        hv=rng.standard_normal(shape).astype(BF16),
    )
    got = _assert_roundtrip(ship)
    assert got.hk.dtype == BF16


def test_roundtrip_int8_with_scale_rows():
    got = _assert_roundtrip(_shipment(quantized=True))
    assert got.hk_scale.dtype == np.float32
    assert got.hk_scale.shape == got.hk.shape[:4]


def test_roundtrip_survives_non_contiguous_slabs():
    ship = _shipment(pages=4)
    view = KVShipment(
        key=ship.key, src=ship.src, prefix_len=2 * ship.page_size,
        page_size=ship.page_size, lora=0,
        hk=ship.hk[::2], hv=ship.hv[::2],
    )
    got = _assert_roundtrip(view)
    assert got.pages == 2


def test_unsupported_dtype_rejected_at_encode():
    with pytest.raises(WireFormatError, match="dtype"):
        shipment_to_wire(_shipment(dtype=np.float64))


# -- header/geometry validation ----------------------------------------------


def _tamper(frame, **hdr_changes):
    """Re-frame with selected header fields overwritten (body verbatim)."""
    import json

    version, flags, hdr_len = struct.unpack("<BBH", frame[4:8])
    header = json.loads(frame[8:8 + hdr_len].decode("utf-8"))
    header.update(hdr_changes)
    hdr = json.dumps(header, separators=(",", ":")).encode("utf-8")
    return (MAGIC + struct.pack("<BBH", version, flags, len(hdr)) + hdr
            + bytes(frame[8 + hdr_len:]))


def test_truncated_frame_rejected():
    frame = shipment_to_wire(_shipment())
    with pytest.raises(WireFormatError, match="truncated"):
        shipment_from_wire(frame[: len(frame) - 10])
    with pytest.raises(WireFormatError, match="truncated"):
        shipment_from_wire(frame[:6])


def test_bad_magic_and_version_rejected():
    frame = shipment_to_wire(_shipment())
    with pytest.raises(WireFormatError, match="magic"):
        shipment_from_wire(b"NOPE" + bytes(frame[4:]))
    with pytest.raises(WireFormatError, match="version"):
        shipment_from_wire(MAGIC + b"\x63" + bytes(frame[5:]))


def test_trailing_garbage_rejected():
    frame = shipment_to_wire(_shipment())
    with pytest.raises(WireFormatError, match="trailing"):
        shipment_from_wire(frame + b"\x00\x01")


def test_geometry_lies_rejected():
    frame = shipment_to_wire(_shipment(page_size=4))
    # header page_size disagreeing with the slab page dim
    with pytest.raises(WireFormatError, match="page_size"):
        shipment_from_wire(_tamper(frame, page_size=8))
    # prefix_len outside the shipped pages
    with pytest.raises(WireFormatError, match="prefix_len"):
        shipment_from_wire(_tamper(frame, prefix_len=999))
    with pytest.raises(WireFormatError, match="prefix_len"):
        shipment_from_wire(_tamper(frame, prefix_len=0))


def test_dtype_lies_rejected():
    ship = _shipment()
    frame = shipment_to_wire(ship)
    import json

    version, flags, hdr_len = struct.unpack("<BBH", frame[4:8])
    header = json.loads(frame[8:8 + hdr_len].decode("utf-8"))
    # unsupported dtype name in a section descriptor
    header["sections"][0]["dtype"] = "float64"
    with pytest.raises(WireFormatError, match="dtype"):
        shipment_from_wire(_tamper(frame, sections=header["sections"]))
    # hk/hv dtype mismatch (both individually supported)
    mixed = KVShipment(
        key=b"k" * 16, src="r0", prefix_len=8, page_size=4, lora=0,
        hk=ship.hk.astype(np.float16), hv=ship.hv,
    )
    with pytest.raises(WireFormatError, match="dtype mismatch"):
        shipment_from_wire(shipment_to_wire(mixed))


def test_key_lies_rejected():
    frame = shipment_to_wire(_shipment())
    with pytest.raises(WireFormatError, match="key"):
        shipment_from_wire(_tamper(frame, key="abcd"))  # 2 bytes, not 16
    with pytest.raises(WireFormatError, match="header"):
        shipment_from_wire(_tamper(frame, key="zz" * 16))  # not hex


def test_scale_row_lies_rejected():
    ship = _shipment(quantized=True)
    bad = KVShipment(
        key=ship.key, src=ship.src, prefix_len=ship.prefix_len,
        page_size=ship.page_size, lora=0, hk=ship.hk, hv=ship.hv,
        hk_scale=ship.hk_scale[:1], hv_scale=ship.hv_scale,
    )
    with pytest.raises(WireFormatError, match="hk_scale"):
        shipment_from_wire(shipment_to_wire(bad))
    f16 = KVShipment(
        key=ship.key, src=ship.src, prefix_len=ship.prefix_len,
        page_size=ship.page_size, lora=0, hk=ship.hk, hv=ship.hv,
        hk_scale=ship.hk_scale.astype(np.float16), hv_scale=ship.hv_scale,
    )
    with pytest.raises(WireFormatError, match="float32"):
        shipment_from_wire(shipment_to_wire(f16))


# -- socket endpoint semantics ------------------------------------------------


def _fabric_pair(**kw):
    fabric = SocketSlabFabric(**kw)
    return fabric, fabric.register("r0"), fabric.register("r1")


def test_socket_send_recv_is_consume_once():
    fabric, r0, r1 = _fabric_pair(capacity_pages=8)
    try:
        assert r1.recv(b"k" * 16) is None
        assert r0.send("r1", _shipment()) is True
        got = r1.recv(b"k" * 16)
        assert got is not None and got.pages == 2
        assert got.hk.tobytes() == _shipment().hk.tobytes()
        assert r1.recv(b"k" * 16) is None          # consumed
        wire = r0.stats()["wire"]
        assert wire["frames_sent"] == 1 and wire["bytes_sent"] > 0
        assert wire["rtt_ms"]["count"] == 1
        rwire = r1.stats()["wire"]
        assert rwire["frames_received"] == 1 and rwire["bytes_received"] > 0
    finally:
        fabric.close()


def test_socket_mailbox_overflow_drops_oldest():
    fabric, r0, r1 = _fabric_pair(capacity_pages=4)
    try:
        assert r0.send("r1", _shipment(key=b"a" * 16))
        assert r0.send("r1", _shipment(key=b"b" * 16))
        assert r0.send("r1", _shipment(key=b"c" * 16))
        assert r1.recv(b"a" * 16) is None          # oldest aged out
        assert r1.recv(b"b" * 16) is not None
        assert r1.recv(b"c" * 16) is not None
    finally:
        fabric.close()


def test_socket_send_failure_paths_drop_to_recompute():
    fabric, r0, r1 = _fabric_pair(capacity_pages=8)
    try:
        # unknown peer: counted drop, no raise
        assert r0.send("rX", _shipment()) is False
        assert r0.stats()["wire"]["send_failures"] == 1
        # injected transport.wire.send fault: counted drop
        faults.configure([
            {"point": "transport.wire.send", "action": "raise", "times": 1},
        ])
        assert r0.send("r1", _shipment()) is False
        assert r0.stats()["wire"]["send_failures"] == 2
        # next send succeeds (fault exhausted, connection re-established)
        assert r0.send("r1", _shipment()) is True
    finally:
        fabric.close()


def test_socket_recv_fault_nacks_sender_and_drops_leak_free():
    lifecycle_ledger.arm(strict=False)
    fabric, r0, r1 = _fabric_pair(capacity_pages=8)
    try:
        faults.configure([
            {"point": "transport.wire.recv", "action": "raise", "times": 1},
        ])
        # the receiver drops the decoded frame before any attach and
        # nacks; the sender maps the nack to a counted drop
        assert r0.send("r1", _shipment()) is False
        assert r1.stats()["wire"]["recv_failures"] == 1
        assert r1.recv(b"k" * 16) is None
        assert r0.stats()["wire"]["send_failures"] == 1
        # leak-free: no transport.shipment units outstanding anywhere
        outstanding = lifecycle_ledger.get().outstanding()
        assert outstanding.get("transport.shipment", 0) == 0
        # and the wire recovers on the next send
        assert r0.send("r1", _shipment()) is True
        assert r1.recv(b"k" * 16) is not None
    finally:
        fabric.close()
    assert lifecycle_ledger.get().outstanding().get("transport.wire.conn", 0) == 0


def test_truncated_frame_on_the_wire_drops_to_recompute():
    """A sender that dies mid-frame (short body vs its length prefix)
    must not wedge or corrupt the receiver: the read times out, the
    partial frame is dropped, and nothing lands in the mailbox."""
    fabric, r0, r1 = _fabric_pair(capacity_pages=8)
    try:
        addr = r1.bind[len("unix:"):]
        r1.recv_deadline_s = 0.2
        raw = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        raw.connect(addr)
        frame = shipment_to_wire(_shipment())
        # claim the full frame but ship half, then hang up
        raw.sendall(struct.pack("<I", len(frame)) + frame[: len(frame) // 2])
        raw.close()
        deadline = time.monotonic() + 5.0
        while (r1.stats()["wire"]["recv_failures"] == 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert r1.stats()["wire"]["recv_failures"] == 1
        assert r1.recv(b"k" * 16) is None
        # the endpoint still works for well-formed frames afterwards
        assert r0.send("r1", _shipment()) is True
        assert r1.recv(b"k" * 16) is not None
    finally:
        fabric.close()


# -- draft-ahead partial frames (docs/spec_decode_trees.md) -------------------


def _split_frames(pages=4, page_size=4, split=2, key=b"k" * 16):
    """(whole, head, tail): the same prefix as one legacy shipment and as
    an unsealed head frame + sealing tail frame."""
    whole = _shipment(pages=pages, page_size=page_size, key=key)
    head = KVShipment(
        key=key, src="r0", prefix_len=split * page_size,
        page_size=page_size, lora=0,
        hk=whole.hk[:split], hv=whole.hv[:split],
        page_offset=0, final=False,
    )
    tail = KVShipment(
        key=key, src="r0", prefix_len=pages * page_size,
        page_size=page_size, lora=0,
        hk=whole.hk[split:], hv=whole.hv[split:],
        page_offset=split, final=True,
    )
    return whole, head, tail


def test_partial_wire_roundtrip_preserves_framing():
    """page_offset/final survive the wire; a legacy whole-prefix frame
    OMITS the keys entirely (byte-compatible with PR 19 receivers)."""
    import json

    whole, head, tail = _split_frames()
    got = shipment_from_wire(shipment_to_wire(head))
    assert got.page_offset == 0 and got.final is False
    assert got.hk.tobytes() == head.hk.tobytes()
    got = shipment_from_wire(shipment_to_wire(tail))
    assert got.page_offset == 2 and got.final is True
    frame = shipment_to_wire(whole)
    _, _, hdr_len = struct.unpack("<BBH", frame[4:8])
    header = json.loads(frame[8:8 + hdr_len].decode("utf-8"))
    assert "page_offset" not in header and "final" not in header
    got = shipment_from_wire(frame)
    assert got.page_offset == 0 and got.final is True


def test_partial_frame_geometry_validated():
    _, head, tail = _split_frames()
    # unsealed frames must cover whole pages exactly
    with pytest.raises(WireFormatError, match="partial frame"):
        shipment_from_wire(_tamper(shipment_to_wire(head), prefix_len=7))
    # a negative page offset is a header lie
    with pytest.raises(WireFormatError, match="page_offset"):
        shipment_from_wire(_tamper(shipment_to_wire(head), page_offset=-1))
    # the sealing frame's prefix tail must land inside ITS pages
    with pytest.raises(WireFormatError, match="prefix_len"):
        shipment_from_wire(
            _tamper(shipment_to_wire(tail), prefix_len=2 * 4)
        )


def test_partial_frames_reassemble_and_seal_over_socket():
    """The draft-ahead happy path over the real wire: head frame queues
    UNSEALED (recv misses — an unsealed assembly is never consumable),
    the sealing tail frame fuses the assembly into the mailbox, and the
    received shipment is byte-identical to the single-frame legacy
    equivalent."""
    whole, head, tail = _split_frames()
    fabric, r0, r1 = _fabric_pair(capacity_pages=8)
    try:
        assert r0.send("r1", head) is True
        assert r1.recv(whole.key) is None          # unsealed: invisible
        assert r0.send("r1", tail) is True
        got = r1.recv(whole.key)
        assert got is not None and got.final and got.page_offset == 0
        assert got.pages == whole.pages
        assert got.prefix_len == whole.prefix_len
        assert got.hk.tobytes() == whole.hk.tobytes()
        assert got.hv.tobytes() == whole.hv.tobytes()
        stats = r1.stats()
        assert stats["partial_frames"] == 1
        assert stats["assembled"] == 1
        assert stats["assembly_drops"] == 0
    finally:
        fabric.close()


def test_partial_duplicate_and_gap_frames_drop_whole_assembly():
    """Ordering violations reject the ENTIRE assembly, not just the bad
    frame: a duplicated middle frame, a gapped seal, and a seal with no
    assembly all leave nothing consumable (drop-to-recompute)."""
    pages, page_size = 4, 4
    whole = _shipment(pages=pages, page_size=page_size)
    frame = lambda lo, hi, final: KVShipment(
        key=whole.key, src="r0",
        prefix_len=(pages if final else hi) * page_size,
        page_size=page_size, lora=0,
        hk=whole.hk[lo:hi], hv=whole.hv[lo:hi],
        page_offset=lo, final=final,
    )
    fabric, r0, r1 = _fabric_pair(capacity_pages=8)
    try:
        # duplicate middle frame: offset 1 twice
        assert r0.send("r1", frame(0, 1, False)) is True
        assert r0.send("r1", frame(1, 2, False)) is True
        assert r0.send("r1", frame(1, 2, False)) is False   # dup -> drop all
        assert r0.send("r1", frame(2, 4, True)) is False    # assembly gone
        assert r1.recv(whole.key) is None
        assert r1.stats()["assembly_drops"] == 2
        # gap: head then a seal that skips a page
        assert r0.send("r1", frame(0, 1, False)) is True
        assert r0.send("r1", frame(2, 4, True)) is False
        assert r1.recv(whole.key) is None
        # seal with no assembly at all
        assert r0.send("r1", frame(2, 4, True)) is False
        assert r1.recv(whole.key) is None
        assert r1.stats()["assembled"] == 0
        # the endpoint still works for a fresh, in-order stream
        assert r0.send("r1", frame(0, 2, False)) is True
        assert r0.send("r1", frame(2, 4, True)) is True
        got = r1.recv(whole.key)
        assert got is not None and got.hk.tobytes() == whole.hk.tobytes()
    finally:
        fabric.close()


def test_legacy_reship_supersedes_unsealed_assembly():
    """A whole-prefix re-ship of the same key (e.g. the sender restarted
    and took the single-frame path) replaces the dangling assembly — the
    received payload is the legacy shipment, not a half-fused hybrid."""
    whole, head, _ = _split_frames()
    fabric, r0, r1 = _fabric_pair(capacity_pages=8)
    try:
        assert r0.send("r1", head) is True
        assert r0.send("r1", whole) is True
        got = r1.recv(whole.key)
        assert got is not None and got.pages == whole.pages
        assert got.hk.tobytes() == whole.hk.tobytes()
        # the stale head can no longer seal into anything
        _, _, tail = _split_frames()
        assert r0.send("r1", tail) is False
        assert r1.recv(whole.key) is None
    finally:
        fabric.close()
