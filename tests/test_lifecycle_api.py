"""HTTP surface of the request-lifecycle hardening (docs/robustness.md):

- shed paths answer 429 with ``Retry-After`` on BOTH the OpenAI streaming
  and non-streaming routes (pre-headers for streams);
- expired deadlines answer 408 with the structured code on both routes;
- /ready reflects engine health (not-ready during watchdog recovery,
  draining) while /health stays liveness-only;
- SIGTERM drain: new requests shed 503 while in-flight ones finish, then
  engines stop cleanly.
"""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from clearml_serving_tpu.llm import faults
from clearml_serving_tpu.serving.endpoints import ModelEndpoint
from clearml_serving_tpu.serving.main import build_app, drain_app
from clearml_serving_tpu.serving.model_request_processor import (
    ModelRequestProcessor,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(scope="module")
def llm_served(tmp_path_factory):
    import os

    root = tmp_path_factory.mktemp("state")
    os.environ["TPUSERVE_STATE_ROOT"] = str(root)
    mrp = ModelRequestProcessor(
        state_root=str(root), force_create=True, name="llm-lifecycle"
    )
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="tiny_llm",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 2,
                    "max_seq_len": 128,
                    "prefill_buckets": [32],
                    "watchdog_interval": 0,  # not under test here
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    return mrp


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    yield
    faults.clear()


def _run(mrp, fn):
    async def runner():
        app = build_app(mrp)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            return await fn(client, app)
        finally:
            await client.close()

    return asyncio.run(runner())


def _chat_body(**extra):
    return {
        "model": "tiny_llm",
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 4,
        **extra,
    }


def test_shed_returns_429_with_retry_after(llm_served):
    async def fn(client, app):
        # warm path first (also instantiates the engine)
        r = await client.post(
            "/serve/openai/v1/chat/completions", json=_chat_body()
        )
        assert r.status == 200, await r.text()

        # non-streaming: injected admission shed -> 429 + Retry-After
        faults.configure([{"point": "engine.admit", "times": 1}])
        r = await client.post(
            "/serve/openai/v1/chat/completions", json=_chat_body()
        )
        assert r.status == 429, await r.text()
        assert "Retry-After" in r.headers
        body = await r.json()
        assert body["code"] == "overloaded"

        # streaming: the shed precedes the 200/SSE headers entirely
        faults.configure([{"point": "engine.admit", "times": 1}])
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(stream=True),
        )
        assert r.status == 429, await r.text()
        assert "Retry-After" in r.headers
        assert (await r.json())["code"] == "overloaded"

        # and the engine still serves once the overload clears
        r = await client.post(
            "/serve/openai/v1/chat/completions", json=_chat_body()
        )
        assert r.status == 200
        return True

    assert _run(llm_served, fn)


def test_deadline_returns_408_on_both_routes(llm_served):
    async def fn(client, app):
        # a zero total budget is already expired at submission: 408 before
        # any device work, on the non-streaming AND the streaming route
        r = await client.post(
            "/serve/openai/v1/chat/completions", json=_chat_body(timeout=0)
        )
        assert r.status == 408, await r.text()
        assert (await r.json())["code"] == "deadline_exceeded"

        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(timeout=0, stream=True),
        )
        assert r.status == 408, await r.text()
        assert (await r.json())["code"] == "deadline_exceeded"

        # completions route (non-chat) maps identically
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_llm", "prompt": "hi", "max_tokens": 4,
                  "timeout": 0},
        )
        assert r.status == 408, await r.text()
        return True

    assert _run(llm_served, fn)


def test_streaming_deadline_mid_stream_emits_sse_error(llm_served):
    """A budget that expires AFTER headers (mid-generation) cannot change
    the status line; the structured error arrives as an SSE error event."""
    async def fn(client, app):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(stream=True, max_tokens=100_000, timeout=0.3),
        )
        assert r.status == 200
        text = await r.text()
        assert "DeadlineExceededError" in text or "data: [DONE]" in text
        return True

    assert _run(llm_served, fn)


def test_ready_reflects_engine_health(llm_served):
    async def fn(client, app):
        # instantiate the engine, then flip its recovery flag
        r = await client.post(
            "/serve/openai/v1/chat/completions", json=_chat_body()
        )
        assert r.status == 200
        engine = llm_served._engine_processor_lookup["tiny_llm"].engine

        r = await client.get("/ready")
        assert r.status == 200
        body = await r.json()
        assert body["status"] == "ready"
        assert body["engines"]["tiny_llm"]["ready"]

        engine._recovering = True  # what a watchdog trip sets
        try:
            r = await client.get("/ready")
            assert r.status == 503
            body = await r.json()
            assert body["status"] == "not_ready"
            assert "tiny_llm" in body["not_ready"]
            assert "Retry-After" in r.headers
            # /health stays liveness-only: still 200 while recovering
            r = await client.get("/health")
            assert r.status == 200
        finally:
            engine._recovering = False

        r = await client.get("/ready")
        assert r.status == 200
        return True

    assert _run(llm_served, fn)


# -- graceful drain (cheap custom endpoint; no LLM engine needed) -------------


ECHO_CODE = """
from clearml_serving_tpu.serving.main import StreamingOutput

class Preprocess:
    def process(self, data, state, collect_fn):
        delay = float((data or {}).get("sleep", 0) or 0)
        if not delay:
            return {"echo": data}
        # slow in-flight work modeled as a stream (async; the custom
        # engine's plain process hook is synchronous)
        async def gen():
            import asyncio
            await asyncio.sleep(delay)
            yield "data: done\\n\\n"
        return StreamingOutput(gen())
"""


class _DummyEngine:
    def __init__(self):
        self.stopped = False

    def health(self):
        return {"ready": not self.stopped}

    def stop(self):
        self.stopped = True


class _DummyProc:
    def __init__(self):
        self.engine = _DummyEngine()


@pytest.fixture()
def echo_served(state_root, tmp_path):
    mrp = ModelRequestProcessor(
        state_root=str(state_root), force_create=True, name="drain"
    )
    f = tmp_path / "echo.py"
    f.write_text(ECHO_CODE)
    mrp.add_endpoint(
        ModelEndpoint(engine_type="custom", serving_url="echo"),
        preprocess_code=str(f),
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    return mrp


def test_graceful_drain_sheds_new_lets_inflight_finish(echo_served):
    dummy = _DummyProc()

    async def fn(client, app):
        echo_served._engine_processor_lookup["dummy_llm"] = dummy
        r = await client.post("/serve/echo", json={"x": 1})
        assert r.status == 200

        # start a slow in-flight request, then begin the drain
        inflight = asyncio.create_task(
            client.post("/serve/echo", json={"sleep": 0.4})
        )
        await asyncio.sleep(0.1)  # request is in flight
        drain = asyncio.create_task(
            drain_app(app, echo_served, timeout=5.0)
        )
        await asyncio.sleep(0.05)

        # new requests shed immediately with 503 + Retry-After
        r = await client.post("/serve/echo", json={"x": 2})
        assert r.status == 503
        assert (await r.json())["code"] == "draining"
        assert "Retry-After" in r.headers
        # /ready flips too
        r = await client.get("/ready")
        assert r.status == 503
        assert (await r.json())["status"] == "draining"

        # the in-flight request still completes normally
        r = await inflight
        assert r.status == 200
        assert "done" in await r.text()

        await drain
        # engines were stopped only after the drain completed
        assert dummy.engine.stopped
        return True

    assert _run(echo_served, fn)
