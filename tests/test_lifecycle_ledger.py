"""Runtime ownership ledger (llm/lifecycle_ledger.py): unit pairing
semantics, the engine integration (strict-armed clean runs stay leak-free;
lifecycle_stats()/health() carry the ledger block), and the chaos seam —
``engine.ledger.leak`` suppresses one real release firing and the strict
ledger must fail the drain audit naming the lost resource and its acquire
site. Node pins are invisible to page-refcount accounting, so this leak
class is provable by the ledger ALONE (the KV sanitizer stays green
through it)."""

import asyncio
import os
import time

import jax
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.llm import faults, lifecycle_ledger
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
from clearml_serving_tpu.llm.kv_cache import HostKVTier, PagePool
from clearml_serving_tpu.llm.lifecycle_ledger import (
    LedgerError,
    OwnershipLedger,
)
from clearml_serving_tpu.llm.prefix_cache import RadixPrefixCache


@pytest.fixture(scope="module")
def parts():
    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, params


@pytest.fixture(autouse=True)
def clean_state():
    faults.clear()
    lifecycle_ledger.get().reset(strict=False)
    yield
    faults.clear()
    lifecycle_ledger.get().reset(strict=False)
    lifecycle_ledger.disarm()


async def _collect(engine, req):
    out = []
    async for token in engine.generate(req):
        out.append(token)
    return out


# -- unit: pairing semantics --------------------------------------------------


def test_acquire_release_balances():
    ledger = OwnershipLedger(strict=True)
    ledger.acquire("pages.slot", key=0, n=3, domain="pool")
    assert ledger.outstanding()["pages.slot"] == 3
    ledger.release("pages.slot", key=0, n=3, domain="pool")
    assert ledger.outstanding()["pages.slot"] == 0
    ledger.check("drain", drained=True)  # no raise
    assert ledger.stats()["leaks"] == 0


def test_release_all_of_key():
    ledger = OwnershipLedger(strict=True)
    ledger.acquire("pages.slot", key=1, n=2, domain="pool")
    ledger.acquire("pages.slot", key=1, n=4, domain="pool")
    ledger.release("pages.slot", key=1, domain="pool", all_of_key=True)
    assert ledger.outstanding()["pages.slot"] == 0
    # a second all-of-key release of an empty slot is a legitimate
    # defensive free, never a violation
    ledger.release("pages.slot", key=1, domain="pool", all_of_key=True)
    assert ledger.stats()["double_releases"] == 0


def test_double_release_is_a_violation():
    ledger = OwnershipLedger(strict=True)
    ledger.acquire("pages.pin", key=(1, 2), n=2, domain="pool")
    ledger.release("pages.pin", key=(1, 2), n=2, domain="pool")
    ledger.release("pages.pin", key=(1, 2), n=2, domain="pool")
    assert ledger.stats()["double_releases"] == 1
    with pytest.raises(LedgerError, match="double free"):
        ledger.check("step")


def test_drain_audit_names_resource_and_site():
    ledger = OwnershipLedger(strict=True)
    ledger.acquire("prefix.resume_pin", key=7, domain="cache")
    with pytest.raises(LedgerError) as info:
        ledger.check("drain", drained=True)
    assert info.value.resource == "prefix.resume_pin"
    assert info.value.site  # file:line of the acquiring caller
    assert "still outstanding at the drained boundary" in str(info.value)


def test_drain_audit_respects_domains():
    """Co-hosted engines audit only their own primitives: a foreign
    domain's outstanding entry never fails this engine's drain."""
    ledger = OwnershipLedger(strict=True)
    ledger.acquire("pages.slot", key=0, n=1, domain="other-engine-pool")
    ledger.check("drain", drained=True, domains=["my-pool"])  # no raise
    with pytest.raises(LedgerError):
        ledger.check("drain", drained=True,
                     domains=["other-engine-pool", "my-pool"])


def test_cache_scoped_resources_exempt_from_drain_zero():
    ledger = OwnershipLedger(strict=True)
    ledger.acquire("pages.ref", n=4, domain="pool")
    ledger.acquire("host.pages", n=2, domain="tier")
    ledger.acquire("transport.shipment", key=b"k", domain="transport")
    ledger.check("drain", drained=True)  # cache-lifetime holds are legal
    assert ledger.outstanding()["pages.ref"] == 4


def test_request_audit_owner_attribution():
    ledger = OwnershipLedger(strict=True)
    with ledger.owner("req:a"):
        ledger.acquire("prefix.hit", key=1, domain="cache")
    with ledger.owner("req:b"):
        ledger.acquire("prefix.hit", key=2, domain="cache")
    ledger.release("prefix.hit", key=2, domain="cache")
    ledger.audit_request("req:b", "emit-finish")  # b released: clean
    with pytest.raises(LedgerError, match="req:a"):
        ledger.audit_request("req:a", "emit-finish")


def test_shared_key_release_discharges_the_releasers_slab():
    """Two requests sharing one resource key (the same grammar, the same
    pinned page run): a release attributed to request A must discharge
    A's slab, not whichever was newest — or the survivor's request-exit
    audit reports a phantom leak on healthy code."""
    ledger = OwnershipLedger(strict=True)
    with ledger.owner("req:a"):
        ledger.acquire("guided.ref", key="g", domain="eng")
    with ledger.owner("req:b"):
        ledger.acquire("guided.ref", key="g", domain="eng")
    # A finishes first; without owner preference this would pop B's slab
    ledger.release("guided.ref", key="g", domain="eng", owner="req:a")
    ledger.audit_request("req:a", "emit-finish")  # clean
    ledger.release("guided.ref", key="g", domain="eng", owner="req:b")
    ledger.audit_request("req:b", "emit-finish")  # clean
    assert ledger.outstanding()["guided.ref"] == 0
    # the thread-local owner context works as the implicit preference too
    with ledger.owner("req:c"):
        ledger.acquire("pages.pin", key=(1, 2), n=2, domain="pool")
    with ledger.owner("req:d"):
        ledger.acquire("pages.pin", key=(1, 2), n=2, domain="pool")
    with ledger.owner("req:c"):
        ledger.release("pages.pin", key=(1, 2), n=2, domain="pool")
    ledger.audit_request("req:c", "emit-finish")  # clean
    with pytest.raises(LedgerError, match="req:d"):
        ledger.audit_request("req:d", "emit-finish")


def test_leak_counted_once_across_repeated_audits():
    """A leaked entry survives in the books, but the leaks counter counts
    lost frees, not the drains that observed them — and the violations
    list must not grow per drained boundary on a long-lived server."""
    ledger = OwnershipLedger(strict=False)
    with ledger.owner("req:x"):
        ledger.acquire("prefix.resume_pin", key=1, domain="cache")
    for _ in range(5):
        ledger.check("drain", drained=True)
    assert ledger.stats()["leaks"] == 1
    assert ledger.stats()["violations"] == 1
    # the request-exit audit does not re-count what the drain reported
    ledger.audit_request("req:x", "fail")
    assert ledger.stats()["leaks"] == 1


def test_count_mode_records_without_raising():
    ledger = OwnershipLedger(strict=False)
    ledger.acquire("pages.pin", key=(3,), domain="pool")
    ledger.audit_request("req:x", "fail")  # no owner match: clean
    with ledger.owner("req:y"):
        ledger.acquire("pages.pin", key=(4,), domain="pool")
    ledger.audit_request("req:y", "fail")
    ledger.check("drain", drained=True)
    stats = ledger.stats()
    assert stats["leaks"] >= 2 and stats["violations"] >= 2


def test_unknown_resource_rejected():
    ledger = OwnershipLedger()
    with pytest.raises(ValueError, match="unknown ledger resource"):
        ledger.acquire("nope", key=1)
    with pytest.raises(ValueError, match="unknown ledger resource"):
        ledger.release("nope", key=1)


def test_env_arming(monkeypatch):
    monkeypatch.delenv(lifecycle_ledger.ENV, raising=False)
    assert not lifecycle_ledger.enabled()
    monkeypatch.setenv(lifecycle_ledger.ENV, "1")
    assert lifecycle_ledger.enabled() and not lifecycle_ledger.strict_enabled()
    monkeypatch.setenv(lifecycle_ledger.ENV, "strict")
    assert lifecycle_ledger.enabled() and lifecycle_ledger.strict_enabled()


def test_module_helpers_noop_when_disarmed():
    lifecycle_ledger.disarm()
    before = lifecycle_ledger.get().stats()["acquires"]
    lifecycle_ledger.acquire("pages.slot", key=0, n=5, domain="p")
    lifecycle_ledger.release("pages.slot", key=0, n=5, domain="p")
    assert lifecycle_ledger.get().stats()["acquires"] == before


# -- primitives record through the module seam --------------------------------


def test_pool_and_cache_record_when_armed():
    ledger = lifecycle_ledger.arm(strict=True)
    pool = PagePool(9, 4, 2)
    cache = RadixPrefixCache(block=4, pool=pool, page_bytes=8)
    ids = list(range(9))   # 9 tokens -> 8 storable (2 blocks = 2 pages)
    pool.allocate(0, 9)
    assert ledger.outstanding()["pages.slot"] == 3
    cache.store_pages(ids, 0, pool.slot_pages(0))
    assert ledger.outstanding()["pages.ref"] == 2
    hit = cache.lookup_pages(ids)
    assert ledger.outstanding()["prefix.hit"] == 1
    assert ledger.outstanding()["pages.pin"] == 2
    cache.release(hit)
    pool.free(0)
    assert ledger.outstanding()["prefix.hit"] == 0
    assert ledger.outstanding()["pages.pin"] == 0
    assert ledger.outstanding()["pages.slot"] == 0
    ledger.check("drain", drained=True, domains=[pool, cache])


def test_host_tier_records_when_armed():
    import numpy as np

    ledger = lifecycle_ledger.arm(strict=True)
    tier = HostKVTier(4, 4, 1, 1, 2, dtype=np.int8, quantized=False)
    ids = tier.allocate(3)
    assert ledger.outstanding()["host.pages"] == 3
    tier.free(ids)
    assert ledger.outstanding()["host.pages"] == 0


def test_resources_cover_ledger_only_registry_entries():
    """Every "static": False protocol the analyzer defers to the ledger is
    a resource the ledger actually tracks (the fail-open contract)."""
    from clearml_serving_tpu.analyze.rules_lifecycle import (
        LIFECYCLE_REGISTRY,
    )

    deferred = {
        e["resource"]
        for entries in LIFECYCLE_REGISTRY.values()
        for e in entries
        if not e.get("static", True)
    }
    assert deferred <= set(lifecycle_ledger.RESOURCES)
    for resource in deferred:
        assert resource in lifecycle_ledger.RESOURCES


# -- engine integration -------------------------------------------------------


def _make_engine(bundle, params, **kwargs):
    kwargs.setdefault("max_batch", 2)
    kwargs.setdefault("max_seq_len", 128)
    kwargs.setdefault("prefill_buckets", [16, 32])
    kwargs.setdefault("eos_token_id", 257)
    return LLMEngineCore(bundle, params, **kwargs)


def test_engine_clean_run_is_leak_free_strict(parts, monkeypatch):
    """A strict-armed paged engine serves and drains with zero leaks, and
    lifecycle_stats()/health() expose the ledger block."""
    bundle, params = parts
    monkeypatch.setenv("TPUSERVE_LEDGER", "strict")
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")

    async def run():
        engine = _make_engine(
            bundle, params, cache_mode="paged", page_size=16,
            prefix_cache=64, prefix_block=16,
        )
        assert engine._ledger is not None, "TPUSERVE_LEDGER did not arm"
        engine._ledger.reset(strict=True)
        for seed in (1, 2, 1):
            out = await _collect(
                engine,
                GenRequest(prompt_ids=[256, seed] + list(range(2, 18)),
                           max_new_tokens=4),
            )
            assert out
        await engine.wait_drained()
        return engine

    engine = asyncio.run(run())
    block = engine.lifecycle_stats()["ledger"]
    assert block["strict"] is True
    assert block["leaks"] == 0 and block["double_releases"] == 0
    assert block["acquires"] > 0
    for resource in ("pages.slot", "pages.pin", "prefix.hit",
                     "prefix.resume_pin", "slot.quarantine", "guided.ref"):
        assert block["outstanding"][resource] == 0, (resource, block)
    assert engine.health()["ledger"]["leaks"] == 0
    engine.stop()


def test_engine_without_env_has_no_ledger(parts, monkeypatch):
    bundle, params = parts
    monkeypatch.delenv("TPUSERVE_LEDGER", raising=False)
    engine = _make_engine(bundle, params)
    assert engine._ledger is None
    assert engine.lifecycle_stats()["ledger"] is None
    engine.stop()


@pytest.mark.chaos
def test_ledger_leak_seam_caught_at_drain_strict(parts, monkeypatch):
    """Acceptance (end to end): the ``engine.ledger.leak`` seam suppresses
    ONE resume-pin release on the preemption resume path — a lost free on
    radix NODES, invisible to page accounting (the KV sanitizer stays
    green) — and the strict ledger fails the drain audit naming
    ``prefix.resume_pin`` and the pin_run acquire site in engine.py."""
    bundle, params = parts
    monkeypatch.setenv("TPUSERVE_LEDGER", "strict")
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")

    async def run():
        engine = _make_engine(
            bundle, params, max_batch=1, decode_steps=2, cache_mode="paged",
            page_size=16, prefix_cache=64, prefix_block=16,
            prefill_buckets=[32, 64], eos_token_id=None,
        )
        assert engine._ledger is not None
        engine._ledger.reset(strict=True)
        batch = GenRequest(
            prompt_ids=[256] + [(i * 3 + 1) % 250 for i in range(16)],
            max_new_tokens=24, priority="batch",
        )
        b_task = asyncio.create_task(_collect(engine, batch))
        while batch.produced < 4:
            await asyncio.sleep(0.005)
        # the preemption pins the victim's stored history; the seam then
        # eats the unpin when the resume leg's admission releases it
        faults.configure([
            {"point": "engine.ledger.leak", "times": 1,
             "message": "lost unpin"},
        ])
        out_hi = await asyncio.wait_for(
            _collect(engine, GenRequest(prompt_ids=[256, 9],
                                        max_new_tokens=2)),
            timeout=60,
        )
        assert len(out_hi) >= 1
        out_b = await asyncio.wait_for(b_task, timeout=60)
        assert len(out_b) == 24
        t0 = time.monotonic()
        while not engine._loop_task.done() and time.monotonic() - t0 < 15.0:
            await asyncio.sleep(0.01)
        assert engine._loop_task.done(), "loop should fail at the drain audit"
        return engine, engine._loop_task.exception()

    engine, exc = asyncio.run(run())
    assert engine.counters["preemptions"] >= 1, "no preemption: seam unhit"
    assert isinstance(exc, LedgerError), exc
    assert exc.resource == "prefix.resume_pin"
    assert "engine.py" in exc.site, exc.site  # the pin_run acquire site
    # the page books balanced throughout: only the LEDGER sees this class
    assert engine._sanitizer is not None
    assert engine._sanitizer.stats()["failures"] == 0
    engine.stop()


def test_ragged_job_failure_arm_reclaim_is_load_bearing(parts, monkeypatch):
    """Runtime mutation gate for this PR's _start_ragged_job fix (its
    static TPU701 finding is annotation-covered, so the LEDGER carries the
    regression): with the failure arm's slot reclaim disabled (the pre-fix
    behavior), a raise AFTER the prefix hit's map_shared strands the
    mapped pages on a slot no job owns, and the strict ledger's drain
    audit must fail naming pages.slot — sanitizer OFF on purpose: the
    ledger alone suffices, and names the resource, not just page ids.
    (The fixed path's cleanliness is covered by
    test_engine_clean_run_is_leak_free_strict and the ragged chaos
    suite.)"""
    bundle, params = parts
    monkeypatch.setenv("TPUSERVE_LEDGER", "strict")

    def build():
        monkeypatch.setenv("TPUSERVE_SANITIZE", "0")
        engine = _make_engine(
            bundle, params, cache_mode="paged", page_size=16,
            prefix_cache=64, prefix_block=16, scheduler="ragged",
            eos_token_id=None,
        )
        assert engine._ledger is not None
        engine._ledger.reset(strict=True)
        return engine

    async def run(engine, break_reclaim):
        shared = [256] + list(range(1, 32))
        # request A stores the shared prefix at commit
        out = await _collect(
            engine, GenRequest(prompt_ids=shared + [40], max_new_tokens=2)
        )
        assert out
        await engine.wait_drained()
        if break_reclaim:
            # the pre-fix behavior: the failure arm loses the mapped pages
            monkeypatch.setattr(
                engine, "_free_ragged_slot", lambda slot: None
            )
        # request B hits the prefix; release() dies once AFTER map_shared
        real_release = engine._prefix.release
        state = {"armed": True}

        def exploding_release(hit):
            # the pin drops normally; the failure lands AFTER it — the
            # modeled defect is strictly "the try body raised after
            # map_shared", leaving only the slot's mapped pages at risk
            result = real_release(hit)
            if state["armed"]:
                state["armed"] = False
                raise RuntimeError("post-map_shared failure")
            return result

        monkeypatch.setattr(engine._prefix, "release", exploding_release)
        with pytest.raises(RuntimeError, match="post-map_shared failure"):
            await _collect(
                engine,
                GenRequest(prompt_ids=shared + [41], max_new_tokens=2),
            )
        monkeypatch.setattr(engine._prefix, "release", real_release)
        # the loop reaches its drained boundary (B was the only request):
        # the drain audit runs there and decides the loop task's fate
        t0 = time.monotonic()
        while not engine._loop_task.done() and time.monotonic() - t0 < 15.0:
            await asyncio.sleep(0.01)
        assert engine._loop_task.done()
        return engine._loop_task

    engine = build()
    task = asyncio.run(run(engine, break_reclaim=True))
    exc = task.exception()
    assert isinstance(exc, LedgerError), exc
    assert exc.resource == "pages.slot"
    engine.stop()


# the ledger_pairing scenario's seeded defects (drop_release_on_raise,
# double_free) are proven caught by tests/test_schedule_explorer.py's
# parametrized mutation self-test — the --self-test acceptance for this
# PR's defect classes lives there with the other eight.


def test_explorer_scenario_restores_ledger_mode():
    """The ledger_pairing scenario arms the process-wide ledger strict for
    its own run; a co-armed count-mode harness must get count mode BACK
    (a leaked strict=True would turn later checks into raises)."""
    from clearml_serving_tpu.llm.schedule_explorer import explore

    lifecycle_ledger.arm(strict=False)
    explore("ledger_pairing", schedules=2, seed=0)
    assert lifecycle_ledger.armed()
    assert lifecycle_ledger.get().strict is False
