import asyncio
import json

import jax
import numpy as np
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
from clearml_serving_tpu.llm.sampling import make_sampling_params, sample_tokens
from clearml_serving_tpu.llm.tokenizer import ByteTokenizer, load_tokenizer


@pytest.fixture(scope="module")
def tiny_engine_parts():
    bundle = models.build_model("llama", {"preset": "llama-tiny", "dtype": "float32"})
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, params


def _make_engine(bundle, params, **kwargs):
    kwargs.setdefault("max_batch", 4)
    kwargs.setdefault("max_seq_len", 128)
    kwargs.setdefault("prefill_buckets", [16, 32])
    kwargs.setdefault("eos_token_id", 257)
    return LLMEngineCore(bundle, params, **kwargs)


async def _collect(engine, req):
    out = []
    async for token in engine.generate(req):
        out.append(token)
    return out


def test_greedy_generation_deterministic(tiny_engine_parts):
    bundle, params = tiny_engine_parts

    async def run():
        engine = _make_engine(bundle, params)
        prompt = [256, 10, 20, 30]
        r1 = await _collect(engine, GenRequest(prompt_ids=prompt, max_new_tokens=8))
        r2 = await _collect(engine, GenRequest(prompt_ids=prompt, max_new_tokens=8))
        return r1, r2

    r1, r2 = asyncio.run(run())
    assert len(r1) == 8 or (257 in r1)
    assert r1 == r2


def test_continuous_batching_matches_sequential(tiny_engine_parts):
    bundle, params = tiny_engine_parts
    prompts = [[256, 1, 2, 3], [256, 9, 8, 7, 6, 5], [256, 42]]

    async def sequential():
        engine = _make_engine(bundle, params)
        return [
            await _collect(engine, GenRequest(prompt_ids=p, max_new_tokens=6))
            for p in prompts
        ]

    async def concurrent():
        engine = _make_engine(bundle, params)
        return await asyncio.gather(
            *[
                _collect(engine, GenRequest(prompt_ids=p, max_new_tokens=6))
                for p in prompts
            ]
        )

    seq = asyncio.run(sequential())
    conc = asyncio.run(concurrent())
    assert seq == conc


def test_more_requests_than_slots(tiny_engine_parts):
    bundle, params = tiny_engine_parts

    async def run():
        engine = _make_engine(bundle, params, max_batch=2)
        results = await asyncio.gather(
            *[
                _collect(engine, GenRequest(prompt_ids=[256, i], max_new_tokens=4))
                for i in range(5)
            ]
        )
        return results

    results = asyncio.run(run())
    assert len(results) == 5
    assert all(len(r) >= 1 for r in results)


def test_prompt_too_long(tiny_engine_parts):
    bundle, params = tiny_engine_parts

    async def run():
        engine = _make_engine(bundle, params)
        req = GenRequest(prompt_ids=list(range(200)), max_new_tokens=4)
        async for _ in engine.generate(req):
            pass

    with pytest.raises(ValueError):
        asyncio.run(run())


def test_sampling_greedy_vs_random():
    logits = np.full((2, 16), -10.0, np.float32)
    logits[0, 3] = 10.0
    logits[1, 7] = 10.0
    out = sample_tokens(
        np.asarray(logits), make_sampling_params(2, temperature=0.0), jax.random.PRNGKey(0)
    )
    assert np.asarray(out).tolist() == [3, 7]
    # temperature sampling with a dominant peak still picks it
    out = sample_tokens(
        np.asarray(logits), make_sampling_params(2, temperature=0.5, top_p=0.9),
        jax.random.PRNGKey(1),
    )
    assert np.asarray(out).tolist() == [3, 7]


def test_top_k_masks_tail():
    logits = np.zeros((1, 8), np.float32)
    logits[0] = [5, 4, 3, -50, -50, -50, -50, -50]
    params = make_sampling_params(1, temperature=1.0, top_k=2)
    outs = {
        int(np.asarray(sample_tokens(np.asarray(logits), params, jax.random.PRNGKey(i)))[0])
        for i in range(20)
    }
    assert outs.issubset({0, 1})


def test_int8_quantized_engine_generates(tiny_engine_parts):
    """quantize="int8": weights live as int8; generation still works and the
    greedy output stays consistent run-to-run."""
    bundle, params = tiny_engine_parts

    async def run():
        engine = _make_engine(bundle, params, quantize="int8")
        prompt = [256, 5, 6, 7]
        r1 = await _collect(engine, GenRequest(prompt_ids=prompt, max_new_tokens=6))
        r2 = await _collect(engine, GenRequest(prompt_ids=prompt, max_new_tokens=6))
        return r1, r2, engine

    r1, r2, engine = asyncio.run(run())
    assert r1 == r2 and len(r1) >= 1
    # params at rest are int8 trees
    import jax

    leaves = jax.tree.leaves(engine.params)
    assert any(l.dtype == np.int8 for l in leaves if hasattr(l, "dtype"))


def test_int4_quantized_engine_generates(tiny_engine_parts):
    """quantize="int4": weights live as packed 4-bit; generation works and
    stays deterministic."""
    bundle, params = tiny_engine_parts

    async def run():
        engine = _make_engine(bundle, params, quantize="int4")
        prompt = [256, 5, 6, 7]
        r1 = await _collect(engine, GenRequest(prompt_ids=prompt, max_new_tokens=6))
        r2 = await _collect(engine, GenRequest(prompt_ids=prompt, max_new_tokens=6))
        return r1, r2, engine

    r1, r2, engine = asyncio.run(run())
    assert r1 == r2 and len(r1) >= 1
    import jax

    leaves = jax.tree.leaves(engine.params)
    assert any(l.dtype == np.uint8 for l in leaves if hasattr(l, "dtype"))


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer(512)
    ids = tok.encode("hello world")
    assert ids[0] == tok.bos_token_id
    assert tok.decode(ids) == "hello world"
    text = tok.apply_chat_template([{"role": "user", "content": "hi"}])
    assert "<|assistant|>" in text
    assert load_tokenizer(None, 512).vocab_size == 512

def test_cancel_frees_slot_early(tiny_engine_parts):
    """A consumer that stops reading (client disconnect) must free the decode
    slot instead of decoding to max_new_tokens for nobody (ADVICE r1)."""
    bundle, params = tiny_engine_parts

    async def run():
        engine = _make_engine(bundle, params, max_batch=1)
        req = GenRequest(prompt_ids=[256, 1, 2], max_new_tokens=10_000)
        gen = engine.generate(req)
        await gen.__anext__()  # one token, then walk away
        await gen.aclose()     # delivers GeneratorExit -> request.cancelled
        assert req.cancelled
        # the single slot must come free again: a second request can run
        out = await _collect(
            engine, GenRequest(prompt_ids=[256, 5], max_new_tokens=3)
        )
        assert engine.active_slots == 0
        return out

    out = asyncio.run(run())
    assert len(out) >= 1


def test_decode_continues_during_slow_admission(tiny_engine_parts):
    """Prefill/decode overlap: while one request's (artificially slow) prefill
    runs, an already-active request keeps receiving tokens (VERDICT r1 #6)."""
    import time as _time

    bundle, params = tiny_engine_parts

    async def run():
        engine = _make_engine(bundle, params, max_batch=2, decode_steps=1)
        orig = engine._prefill_device

        slow_started = asyncio.Event()

        def slow_prefill(request):
            if len(request.prompt_ids) == 5:  # only request B is slowed
                slow_started.set()
                _time.sleep(0.5)
            return orig(request)

        engine._prefill_device = slow_prefill

        a_tokens_during_b_prefill = 0
        a_warm = asyncio.Event()  # A's decode chunk compiled + flowing
        b_first_token = asyncio.Event()

        async def consume_a():
            nonlocal a_tokens_during_b_prefill
            req = GenRequest(prompt_ids=[256, 1], max_new_tokens=10_000)
            produced = 0
            async for _ in engine.generate(req):
                produced += 1
                if produced >= 3:
                    a_warm.set()
                if slow_started.is_set() and not b_first_token.is_set():
                    a_tokens_during_b_prefill += 1
                if b_first_token.is_set():
                    req.cancel()

        async def consume_b():
            req = GenRequest(prompt_ids=[256, 9, 8, 7, 6], max_new_tokens=2)
            async for _ in engine.generate(req):
                b_first_token.set()

        task_a = asyncio.create_task(consume_a())
        # wait until A's decode executable is compiled and emitting — a fixed
        # sleep races the first jit compile and flakes
        await asyncio.wait_for(a_warm.wait(), timeout=120)
        await consume_b()
        await asyncio.wait_for(task_a, timeout=120)
        return a_tokens_during_b_prefill

    overlapped = asyncio.run(run())
    # with serialized admission this is 0 — decode stalls for the full 0.5s
    assert overlapped >= 1, "decode stalled during admission"


def test_int8_engine_with_mesh(tiny_engine_parts):
    """Quantized engine under a tp mesh: params TP-shard (not replicate) and
    generation still works."""
    from clearml_serving_tpu.parallel import make_mesh

    bundle, params = tiny_engine_parts

    async def run():
        # tp bounded by llama-tiny's 2 kv heads (dense cache shards kv heads)
        mesh = make_mesh({"dp": 4, "tp": 2})
        engine = _make_engine(bundle, params, quantize="int8", mesh=mesh, max_batch=4)
        wq = engine.params["layers"][0]["wq"]
        assert wq["_q8"].addressable_shards[0].data.size == wq["_q8"].size // 2
        return await _collect(
            engine, GenRequest(prompt_ids=[256, 1, 2], max_new_tokens=4)
        )

    out = asyncio.run(run())
    assert len(out) >= 1


def test_chunked_prefill_matches_plain(tiny_engine_parts):
    """Chunked prefill (C-token segments over the cache) must generate the
    same greedy tokens as one-shot prefill, including ragged final chunks."""
    bundle, params = tiny_engine_parts
    prompts = [
        [256, 5, 9, 13, 2, 7, 40, 41, 42],          # 9 tokens, C=4 -> 4+4+1
        [256] + list(range(1, 17)),                  # 17 tokens -> 4x4+1
        [256, 3],                                    # shorter than C: plain path
    ]

    async def run(engine):
        outs = []
        for p in prompts:
            outs.append(
                await _collect(engine, GenRequest(prompt_ids=p, max_new_tokens=5))
            )
        return outs

    plain = asyncio.run(run(_make_engine(bundle, params)))
    chunked_engine = _make_engine(bundle, params, chunked_prefill_size=4)
    assert chunked_engine._chunked == 4
    chunked = asyncio.run(run(chunked_engine))
    assert chunked == plain

    # C that does NOT divide the buckets (16/32): a clamped final-chunk
    # write would silently corrupt earlier prompt K/V (review r2 finding)
    odd_engine = _make_engine(bundle, params, chunked_prefill_size=6)
    odd = asyncio.run(run(odd_engine))
    assert odd == plain
    # the chunked mini cache rounded up to a multiple of C
    assert any(b % 6 == 0 for b in odd_engine._prefill_templates)


def test_prefill_gate_semantics():
    """Decode-first pacing: open when decode is idle, bounded permits while
    active, starvation-bound timeout when decode stops depositing."""
    import threading
    import time as _time

    from clearml_serving_tpu.llm.engine import _PrefillGate

    gate = _PrefillGate(segments_per_chunk=2, stall_timeout=0.2)

    # inactive: acquire never blocks and never consumes permits
    t0 = _time.perf_counter()
    for _ in range(10):
        gate.acquire()
    assert _time.perf_counter() - t0 < 0.05

    # active: the initial budget is segments_per_chunk; the third acquire
    # blocks until a deposit arrives
    gate.set_active(True)
    gate.acquire()
    gate.acquire()
    released = threading.Event()

    def depositor():
        _time.sleep(0.05)
        gate.deposit()
        released.set()

    threading.Thread(target=depositor, daemon=True).start()
    t0 = _time.perf_counter()
    gate.acquire()  # must wait for the deposit, not the 0.2s stall timeout
    waited = _time.perf_counter() - t0
    assert released.is_set() and 0.03 < waited < 0.19

    # starvation bound: no deposits -> proceeds after ~stall_timeout
    gate.deposit()
    gate.acquire()
    gate.acquire()
    t0 = _time.perf_counter()
    gate.acquire()
    assert 0.15 < _time.perf_counter() - t0 < 1.0

    # deactivating releases any waiter immediately
    gate.deposit()
    gate.acquire()
    gate.acquire()
    t0 = _time.perf_counter()
    threading.Thread(target=lambda: (_time.sleep(0.03), gate.set_active(False)),
                     daemon=True).start()
    gate.acquire()
    assert _time.perf_counter() - t0 < 0.15


def test_prefill_segments_interleave_with_decode(tiny_engine_parts):
    """While a request is decoding, a long prompt's chunked-prefill segment
    train must not enqueue more than segments_per_chunk dispatches between
    decode chunks (decode latency stays bounded during admission)."""
    bundle, params = tiny_engine_parts
    from clearml_serving_tpu.llm.engine import _PrefillGate

    engine = _make_engine(
        bundle, params, chunked_prefill_size=4, decode_steps=1,
        prefill_buckets=[16, 32, 64], eos_token_id=None,
    )
    # deterministic pacing: a long stall timeout means every segment truly
    # waits for its decode-chunk permit instead of timing out past the gate
    engine._prefill_gate = _PrefillGate(segments_per_chunk=1, stall_timeout=10.0)

    events = []
    lock = __import__("threading").Lock()

    def record(tag, fn):
        def wrapped(*a, **k):
            with lock:
                events.append(tag)
            return fn(*a, **k)
        return wrapped

    engine._decode_chunk_jit = record("D", engine._decode_chunk_jit)
    engine._prefill_chunk_jit = record("P", engine._prefill_chunk_jit)
    engine._prefill_chunk_first_jit = record("P", engine._prefill_chunk_first_jit)

    async def warmup():
        # compile the chunked-segment + decode executables up front: a cold
        # multi-second jit inside the measured phase would let A finish
        # before B's second segment even starts
        await _collect(
            engine,
            GenRequest(prompt_ids=[256] + list(range(1, 33)), max_new_tokens=2),
        )

    asyncio.run(warmup())
    events.clear()

    async def run():
        # request A decodes 100 one-token chunks; wait for its FIRST token so
        # it is committed and decoding (gate active) before B's admission —
        # pacing only applies against active decode, so starting B during
        # A's own admission would legitimately run an open gate
        agen = engine.generate(
            GenRequest(prompt_ids=[256, 1, 2], max_new_tokens=100)
        )
        out_a = [await agen.__anext__()]
        # request B: 33-token prompt -> 9 chunked segments of C=4
        b = asyncio.create_task(_collect(
            engine,
            GenRequest(prompt_ids=[256] + list(range(1, 33)), max_new_tokens=2),
        ))
        async for token in agen:
            out_a.append(token)
        return out_a, await b

    out_a, out_b = asyncio.run(run())
    assert len(out_a) >= 1 and len(out_b) >= 1
    seq = "".join(events)
    assert "P" in seq and "D" in seq
    # the pacing contract only applies while decode is ACTIVE — trailing
    # segments after A finishes run through an open gate by design — so
    # bound prefill runs inside the window that still has decode chunks
    window = seq[: seq.rindex("D") + 1]
    gated_ps = window.count("P")
    assert gated_ps >= 3, "admission did not overlap decode: {}".format(seq)
    max_p_run = max((len(run_) for run_ in window.split("D")), default=0)
    assert max_p_run <= 2, "prefill burst {} in {}".format(max_p_run, seq)


def test_speculative_decoding_matches_plain(tiny_engine_parts):
    """n-gram speculation is greedy-EXACT: every accepted draft equals the
    argmax the plain path would have produced, so outputs are identical
    token-for-token — on repetitive prompts (drafts hit) and non-repetitive
    ones (drafts miss, bonus token still correct)."""
    bundle, params = tiny_engine_parts
    prompts = [
        [256] + [10, 20, 30, 10, 20, 30, 10, 20],   # repetitive: drafts hit
        [256] + list(range(40, 52)),                # no repeats: drafts miss
        [256, 99],                                  # tiny prompt
    ]

    async def run(engine):
        outs = []
        for p in prompts:
            outs.append(await _collect(
                engine, GenRequest(prompt_ids=p, max_new_tokens=24)
            ))
        return outs

    plain = asyncio.run(run(_make_engine(bundle, params, decode_steps=3)))
    spec_engine = _make_engine(
        bundle, params, decode_steps=3, speculation="ngram",
        spec_k=3, spec_ngram=2,
    )
    dispatches = [0]
    orig = spec_engine._spec_chunk_jit

    def counting(*a, **k):
        dispatches[0] += 1
        return orig(*a, **k)

    spec_engine._spec_chunk_jit = counting
    spec = asyncio.run(run(spec_engine))
    assert spec == plain
    assert dispatches[0] > 0, "speculative path never dispatched"
    # every spec dispatch yields >= decode_steps tokens (1+ per round), so
    # it can never need more dispatches than the plain scan would
    total_tokens = sum(len(o) for o in spec)
    assert total_tokens >= dispatches[0] * 3 or any(
        len(o) < 24 for o in spec
    )


def test_speculative_concurrent_and_sampled_fallback(tiny_engine_parts):
    """Concurrent greedy requests share speculative dispatches; a sampled
    (temperature>0) request rides the SAME dispatch on the position-0
    sampled path (per-slot gating) without perturbing the greedy slots."""
    bundle, params = tiny_engine_parts
    engine = _make_engine(
        bundle, params, decode_steps=2, speculation="ngram", spec_k=3,
    )

    async def run():
        a = _collect(engine, GenRequest(
            prompt_ids=[256, 1, 2, 1, 2], max_new_tokens=10))
        b = _collect(engine, GenRequest(
            prompt_ids=[256, 7, 8, 7, 8], max_new_tokens=10))
        c = _collect(engine, GenRequest(
            prompt_ids=[256, 3], max_new_tokens=6, temperature=0.9))
        return await asyncio.gather(a, b, c)

    out_a, out_b, out_c = asyncio.run(run())
    assert len(out_a) >= 1 and len(out_b) >= 1 and len(out_c) >= 1
    # greedy outputs must match a fresh plain engine exactly
    plain = _make_engine(bundle, params, decode_steps=2)

    async def run_plain():
        a = await _collect(plain, GenRequest(
            prompt_ids=[256, 1, 2, 1, 2], max_new_tokens=10))
        b = await _collect(plain, GenRequest(
            prompt_ids=[256, 7, 8, 7, 8], max_new_tokens=10))
        return a, b

    pa, pb = asyncio.run(run_plain())
    assert out_a == pa and out_b == pb


def test_speculative_mixed_batch_per_slot_gating(tiny_engine_parts):
    """Per-slot gating (VERDICT r3 #5): a mixed batch — greedy, seeded
    sampled, and extras-carrying (logit_bias) requests — keeps speculation
    ACTIVE, and every request's output is token-identical to a plain
    engine's: the verify dispatch reproduces the plain chunk's sampling
    semantics for non-greedy slots."""
    bundle, params = tiny_engine_parts
    reqs = [
        dict(prompt_ids=[256, 1, 2, 1, 2, 1, 2], max_new_tokens=12),  # greedy
        dict(prompt_ids=[256, 5], max_new_tokens=12,
             temperature=0.9, seed=1234),                      # seeded sample
        dict(prompt_ids=[256, 9], max_new_tokens=12, temperature=0.7,
             seed=99, logit_bias={"3": 4.0}),                  # extras slot
    ]

    async def run(engine):
        return await asyncio.gather(*[
            _collect(engine, GenRequest(**r)) for r in reqs
        ])

    plain = asyncio.run(run(_make_engine(bundle, params, decode_steps=2)))
    spec_engine = _make_engine(
        bundle, params, decode_steps=2, speculation="ngram", spec_k=3,
    )
    dispatches = [0]
    orig = spec_engine._spec_chunk_jit

    def counting(*a, **k):
        dispatches[0] += 1
        return orig(*a, **k)

    spec_engine._spec_chunk_jit = counting
    spec = asyncio.run(run(spec_engine))
    assert spec == plain
    assert dispatches[0] > 0, "mixed batch fell off the speculative path"


def test_speculative_mixed_batch_logprobs(tiny_engine_parts):
    """A logprob-tracking sampled request in a speculating batch gets its
    per-token logprob entries from the verify dispatch's position-0 path —
    same values the plain chunk reports."""
    bundle, params = tiny_engine_parts

    async def run(engine):
        greedy = GenRequest(
            prompt_ids=[256, 1, 2, 1, 2, 1], max_new_tokens=10)
        lp_req = GenRequest(
            prompt_ids=[256, 4], max_new_tokens=8,
            temperature=0.8, seed=7, logprobs=2)
        outs = await asyncio.gather(
            _collect(engine, greedy), _collect(engine, lp_req))
        return outs, lp_req.logprob_entries

    plain_out, plain_lp = asyncio.run(
        run(_make_engine(bundle, params, decode_steps=2)))
    spec_out, spec_lp = asyncio.run(run(_make_engine(
        bundle, params, decode_steps=2, speculation="ngram", spec_k=3)))
    assert spec_out == plain_out
    assert len(spec_lp) == len(plain_lp) > 0
    for a, b in zip(spec_lp, plain_lp):
        assert a["id"] == b["id"] and a["top_ids"] == b["top_ids"]
        assert a["logprob"] == pytest.approx(b["logprob"], abs=1e-4)


def test_speculative_moe_greedy_exact():
    """MoE verification must route dropless like decode, or speculation's
    argmax diverges from plain greedy with batch occupancy."""
    bundle = models.build_model(
        "llama",
        {"preset": "llama-tiny", "dtype": "float32",
         "n_experts": 4, "moe_top_k": 2, "moe_capacity_factor": 1.0},
    )
    params = bundle.init(jax.random.PRNGKey(0))
    prompts = [[256, 1, 2, 1, 2, 1], [256, 8, 9, 8, 9]]

    async def run(engine):
        return await asyncio.gather(*[
            _collect(engine, GenRequest(prompt_ids=p, max_new_tokens=12))
            for p in prompts
        ])

    plain = asyncio.run(run(_make_engine(bundle, params, decode_steps=2)))
    spec = asyncio.run(run(_make_engine(
        bundle, params, decode_steps=2, speculation="ngram", spec_k=3,
    )))
    assert spec == plain


def test_speculative_sample_chain_preserves_distribution():
    """Rejection-based speculative sampling with a point-mass draft must
    leave the emitted-token law EXACTLY the target distribution: empirical
    first-token frequencies over many keys match P0, both when the draft is
    likely (often accepted) and when it is unlikely (mostly resampled)."""
    import jax
    import jax.numpy as jnp

    from clearml_serving_tpu.llm.sampling import (
        make_sampling_params,
        speculative_sample_chain,
    )

    v = 8
    p0 = np.array([0.4, 0.3, 0.1, 0.1, 0.05, 0.03, 0.01, 0.01])
    p1 = np.array([0.05, 0.05, 0.5, 0.2, 0.1, 0.05, 0.03, 0.02])
    logits = jnp.log(jnp.asarray(
        np.stack([p0, p1, p0]), jnp.float32
    ))[None]                                           # [1, 3, V] (k=2)
    params = make_sampling_params(1, temperature=1.0)

    def run_many(draft0, n=20000):
        drafts = jnp.asarray([[draft0, 2]], jnp.int32)
        toks, accs = jax.jit(jax.vmap(
            lambda key: speculative_sample_chain(logits, drafts, params, key)
        ))(jax.random.split(jax.random.PRNGKey(0), n))
        return np.asarray(toks)[:, 0], np.asarray(accs)[:, 0]

    for draft0 in (0, 6):  # likely draft (p=0.4) and unlikely draft (p=0.01)
        toks, accs = run_many(draft0)
        first = toks[:, 0]
        emp = np.bincount(first, minlength=v) / len(first)
        tv = 0.5 * np.abs(emp - p0).sum()
        assert tv < 0.02, (draft0, emp, p0)
        # second token, conditioned on the first draft being accepted,
        # must follow P1 (the chain continues autoregressively)
        cont = toks[accs >= 1]
        if len(cont) > 2000:
            emp1 = np.bincount(cont[:, 1], minlength=v) / len(cont)
            tv1 = 0.5 * np.abs(emp1 - p1).sum()
            assert tv1 < 0.03, (draft0, emp1, p1)
    # accept rate tracks the draft probability
    _, acc_hi = run_many(0)
    _, acc_lo = run_many(6)
    assert (acc_hi >= 1).mean() == pytest.approx(0.4, abs=0.03)
    assert (acc_lo >= 1).mean() == pytest.approx(0.01, abs=0.01)


def test_speculative_sample_chain_respects_top_k():
    """The chain samples from the SAME warped law as sample_tokens: with
    top_k=2 every emitted token is in the per-position top-2."""
    import jax
    import jax.numpy as jnp

    from clearml_serving_tpu.llm.sampling import (
        make_sampling_params,
        speculative_sample_chain,
    )

    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(2, 4, 16), jnp.float32)  # [B=2, k+1=4, V]
    top2 = np.argsort(np.asarray(logits), axis=-1)[..., -2:]
    params = make_sampling_params(2, temperature=0.8, top_k=2)
    drafts = jnp.asarray(rng.randint(0, 16, size=(2, 3)), jnp.int32)
    for trial in range(50):
        toks, accs = speculative_sample_chain(
            logits, drafts, params, jax.random.PRNGKey(trial)
        )
        toks, accs = np.asarray(toks), np.asarray(accs)
        for b in range(2):
            # every EMITTED token (accepted prefix + fallback) is top-2
            for i in range(int(accs[b]) + 1):
                assert toks[b, i] in top2[b, i], (trial, b, i)


def test_sampled_speculation_in_engine(tiny_engine_parts):
    """temperature>0 slots speculate via the rejection chain: the spec path
    dispatches for a mixed greedy+sampled batch, the greedy co-resident
    stays exact, and a sampled request submitted ALONE (deterministic rng
    stream — concurrent admissions race on the shared stream by design) is
    repeatable across engines with the same seed."""
    bundle, params = tiny_engine_parts
    hot_req = dict(prompt_ids=[256, 5, 6, 5, 6], max_new_tokens=10,
                   temperature=0.9)

    def build(**kw):
        return _make_engine(
            bundle, params, decode_steps=2, speculation="ngram", spec_k=3,
            rng_seed=42, **kw,
        )

    async def run_mixed(engine):
        greedy = GenRequest(prompt_ids=[256, 1, 2, 1, 2, 1], max_new_tokens=10)
        hot = GenRequest(**hot_req)
        return await asyncio.gather(
            _collect(engine, greedy), _collect(engine, hot))

    e1 = build()
    dispatches = [0]
    orig = e1._spec_chunk_jit

    def counting(*a, **k):
        dispatches[0] += 1
        return orig(*a, **k)

    e1._spec_chunk_jit = counting
    g1, s1 = asyncio.run(run_mixed(e1))
    assert dispatches[0] > 0 and len(s1) >= 1
    # greedy slot remains exact vs plain engine
    plain = _make_engine(bundle, params, decode_steps=2, rng_seed=42)

    async def run_greedy():
        return await _collect(plain, GenRequest(
            prompt_ids=[256, 1, 2, 1, 2, 1], max_new_tokens=10))

    assert g1 == asyncio.run(run_greedy())

    # sampled request alone: the rejection chain IS the only decode path
    # (sspec-only batch) and the rng stream is deterministic
    async def run_alone(engine):
        return await _collect(engine, GenRequest(**hot_req))

    e2 = build()
    chain_dispatches = [0]
    orig2 = e2._spec_chunk_jit

    def counting2(*a, **k):
        chain_dispatches[0] += 1
        return orig2(*a, **k)

    e2._spec_chunk_jit = counting2
    a1 = asyncio.run(run_alone(e2))
    assert chain_dispatches[0] > 0, "sampled-only batch skipped the chain"
    a2 = asyncio.run(run_alone(build()))
    assert a1 == a2 and len(a1) >= 1
    # spec_sampling=False: the sampled-only batch takes the PLAIN chunk
    # (no spec-eligible slot at all) and still completes
    off = build(spec_sampling=False)
    a3 = asyncio.run(run_alone(off))
    assert len(a3) >= 1


# -- cancellation during admission (request-lifecycle hardening) --------------


def test_cancel_while_parked_in_pending(tiny_engine_parts):
    """Client disconnect while the request sits in _pending: the consumer
    unblocks promptly, the queued request never takes a slot, and the engine
    keeps serving (slot pipeline untouched)."""
    bundle, params = tiny_engine_parts

    async def run():
        engine = _make_engine(bundle, params, max_batch=1, decode_steps=1)
        a = GenRequest(prompt_ids=[256, 1], max_new_tokens=10_000)
        agen = engine.generate(a)
        await agen.__anext__()  # A pins the single slot
        b = GenRequest(prompt_ids=[256, 2], max_new_tokens=4)
        b_task = asyncio.create_task(_collect(engine, b))
        while engine._pending.qsize() < 1:
            await asyncio.sleep(0.005)
        b.cancel()  # disconnect while parked
        out_b = await asyncio.wait_for(b_task, timeout=30)
        assert out_b == []
        await agen.aclose()
        out_c = await _collect(
            engine, GenRequest(prompt_ids=[256, 3], max_new_tokens=3)
        )
        return out_c, engine

    out_c, engine = asyncio.run(run())
    assert len(out_c) >= 1
    assert engine.active_slots == 0


def test_cancel_during_prefill_releases_guided_refs(tiny_engine_parts):
    """Client disconnect while the request's prefill is in flight must
    return the grammar ref _ensure_grammar took in the admission worker —
    a leaked ref would block the guided-table compaction forever."""
    from clearml_serving_tpu.llm import faults
    from clearml_serving_tpu.llm.guided import GuidedSpec
    from clearml_serving_tpu.llm.tokenizer import ByteTokenizer

    bundle, params = tiny_engine_parts
    tok = ByteTokenizer(512)
    marker = 301

    async def run():
        engine = _make_engine(
            bundle, params, eos_token_id=tok.eos_token_id, tokenizer=tok
        )
        faults.configure([
            {"point": "engine.prefill", "action": "delay", "delay": 0.3,
             "match_token": marker, "times": 1},
        ])
        b = GenRequest(
            prompt_ids=[256, marker], max_new_tokens=8,
            guided=GuidedSpec("regex", "(yes|no)"),
        )
        b_task = asyncio.create_task(_collect(engine, b))
        await asyncio.sleep(0.1)  # prefill (delayed) is in flight
        b.cancel()  # disconnect mid-admission
        out_b = await asyncio.wait_for(b_task, timeout=30)
        assert out_b == []
        # the compiled grammar's ref came back (slot never committed)
        assert all(e["refs"] == 0 for e in engine._grammars.values())
        # and guided decoding still works for the next client
        out = await _collect(engine, GenRequest(
            prompt_ids=[256, 2], max_new_tokens=8,
            guided=GuidedSpec("regex", "(yes|no)"),
        ))
        return out, engine

    try:
        out, engine = asyncio.run(run())
    finally:
        from clearml_serving_tpu.llm import faults as _f

        _f.clear()
    assert len(out) >= 1
    assert all(e["refs"] == 0 for e in engine._grammars.values())


def test_cancel_during_prefill_releases_prefix_pin(tiny_engine_parts):
    """Paged prefix cache: a lookup pins shared pages until the loop-thread
    commit. A client disconnect while that prefill is in flight must drop
    the pin — otherwise the pages leak out of the pool forever."""
    from clearml_serving_tpu.llm import faults

    bundle, params = tiny_engine_parts
    system = [(i * 5 + 1) % 256 for i in range(32)]
    marker = 302

    async def run():
        engine = _make_engine(
            bundle, params, cache_mode="paged", page_size=4,
            prefix_cache=64, prefix_block=16,
        )
        pool = engine.paged_cache.pool
        # request 1 stores the 32-token prefix by page reference
        await _collect(engine, GenRequest(
            prompt_ids=system + [9, 8], max_new_tokens=3
        ))
        # pipelined loop: the slot's deferred page free lands at the retire
        # of the last in-flight chunk — sample the baseline at quiescence
        await engine.wait_drained()
        free0, shared0 = pool.free_pages, pool.shared_pages
        faults.configure([
            {"point": "engine.prefill", "action": "delay", "delay": 0.3,
             "match_token": marker, "times": 1},
        ])
        b = GenRequest(
            prompt_ids=system + [marker, 7], max_new_tokens=3
        )
        b_task = asyncio.create_task(_collect(engine, b))
        await asyncio.sleep(0.1)  # lookup will pin the shared pages
        b.cancel()
        out_b = await asyncio.wait_for(b_task, timeout=30)
        assert out_b == []
        await engine.wait_drained()
        # pin released, no page leaked: pool refcounts back to baseline
        assert pool.free_pages == free0
        assert pool.shared_pages == shared0
        # the prefix is still hittable by the next client
        out = await _collect(engine, GenRequest(
            prompt_ids=system + [5, 4], max_new_tokens=3
        ))
        assert engine._prefix.hits >= 1
        return out

    try:
        out = asyncio.run(run())
    finally:
        faults.clear()
    assert len(out) >= 1
