import asyncio
import json
import numpy as np

import pytest
from aiohttp.test_utils import TestClient, TestServer

from clearml_serving_tpu.serving.endpoints import ModelEndpoint
from clearml_serving_tpu.serving.main import build_app
from clearml_serving_tpu.serving.model_request_processor import ModelRequestProcessor


@pytest.fixture(scope="module")
def llm_served(tmp_path_factory):
    import os

    root = tmp_path_factory.mktemp("state")
    os.environ["TPUSERVE_STATE_ROOT"] = str(root)
    mrp = ModelRequestProcessor(state_root=str(root), force_create=True, name="llm")
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="tiny_llm",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 2,
                    "max_seq_len": 128,
                    "prefill_buckets": [32],
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    return mrp


def _run(mrp, fn):
    async def runner():
        client = TestClient(TestServer(build_app(mrp)))
        await client.start_server()
        try:
            return await fn(client)
        finally:
            await client.close()

    return asyncio.run(runner())


def test_chat_completion(llm_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json={
                "model": "tiny_llm",
                "messages": [{"role": "user", "content": "hello"}],
                "max_tokens": 6,
            },
        )
        assert r.status == 200, await r.text()
        return await r.json()

    out = _run(llm_served, fn)
    assert out["object"] == "chat.completion"
    assert out["choices"][0]["message"]["role"] == "assistant"
    assert out["usage"]["completion_tokens"] >= 1
    assert out["usage"]["prompt_tokens"] > 0


def test_chat_response_format_json(llm_served):
    """OpenAI response_format json_object: the constrained output must parse
    as JSON even at high temperature (vLLM guided-decoding parity)."""

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json={
                "model": "tiny_llm",
                "messages": [{"role": "user", "content": "data"}],
                "max_tokens": 300,
                "temperature": 0.9,
                "seed": 11,  # deterministic: guarantees EOS before the cap
                "response_format": {"type": "json_object"},
            },
        )
        assert r.status == 200, await r.text()
        out = await r.json()
        if out["choices"][0]["finish_reason"] == "stop":
            # completed match: MUST parse (and be an object, not a scalar)
            obj = json.loads(out["choices"][0]["message"]["content"])
            assert isinstance(obj, dict)
        else:
            # truncation at max_tokens is the one case the grammar cannot
            # protect against (same contract as vLLM guided decoding)
            assert out["choices"][0]["finish_reason"] == "length"

        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json={
                "model": "tiny_llm",
                "messages": [{"role": "user", "content": "pick"}],
                "max_tokens": 16,
                "temperature": 0.9,
                "guided_regex": "(north|south|east|west)",
            },
        )
        assert r.status == 200, await r.text()
        out = await r.json()
        assert out["choices"][0]["message"]["content"] in (
            "north", "south", "east", "west"
        )

        # invalid grammar -> 4xx before any streaming
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json={
                "model": "tiny_llm",
                "messages": [{"role": "user", "content": "x"}],
                "guided_regex": "(unclosed",
            },
        )
        assert r.status in (400, 422), await r.text()

    _run(llm_served, fn)


def test_chat_completion_streaming(llm_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json={
                "model": "tiny_llm",
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 5,
                "stream": True,
            },
        )
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/event-stream")
        return await r.text()

    text = _run(llm_served, fn)
    lines = [l for l in text.split("\n\n") if l.startswith("data: ")]
    assert lines[-1] == "data: [DONE]"
    first = json.loads(lines[0][len("data: "):])
    assert first["object"] == "chat.completion.chunk"
    assert first["choices"][0]["delta"].get("role") == "assistant"


def test_completions_and_tokenize(llm_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_llm", "prompt": "abc", "max_tokens": 4},
        )
        assert r.status == 200
        comp = await r.json()

        r = await client.post(
            "/serve/openai/v1/tokenize", json={"model": "tiny_llm", "prompt": "abc"}
        )
        tok = await r.json()
        r = await client.post(
            "/serve/openai/v1/detokenize",
            json={"model": "tiny_llm", "tokens": tok["tokens"]},
        )
        detok = await r.json()

        r = await client.post(
            "/serve/openai/v1/models", json={"model": "tiny_llm"}
        )
        mods = await r.json()

        # model-independent route: plain GET with no body must work
        # (reference show_version), as must the body-carrying POST form
        r = await client.get("/serve/openai/version")
        ver = await r.json()
        r = await client.post("/serve/openai/version", json={"model": "tiny_llm"})
        ver_post = await r.json()
        assert ver_post == ver
        return comp, tok, detok, mods, ver

    comp, tok, detok, mods, ver = _run(llm_served, fn)
    assert comp["object"] == "text_completion"
    assert tok["count"] == 4  # bos + 3 bytes
    assert detok["prompt"] == "abc"
    assert mods["data"][0]["id"] == "tiny_llm"
    from clearml_serving_tpu.version import __version__

    assert ver == {"version": __version__}


def test_unsupported_capability(llm_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/embeddings",
            json={"model": "tiny_llm", "input": "x"},
        )
        assert r.status == 422
        body = await r.json()
        assert "does not support" in body["detail"]

    _run(llm_served, fn)


def test_plain_serve_route(llm_served):
    """POST /serve/tiny_llm behaves as a non-streaming chat completion."""

    async def fn(client):
        r = await client.post(
            "/serve/tiny_llm",
            json={"messages": [{"role": "user", "content": "yo"}], "max_tokens": 3},
        )
        assert r.status == 200
        return await r.json()

    out = _run(llm_served, fn)
    assert out["object"] == "chat.completion"


def test_streaming_emits_stats_packet(llm_served):
    """Streaming requests must record TTFT/token stats at stream completion
    (VERDICT r1 #7: streaming chat is THE LLM workload)."""
    llm_served._metric_log_freq = 1.0  # sample every request
    try:
        async def fn(client):
            r = await client.post(
                "/serve/openai/v1/chat/completions",
                json={
                    "model": "tiny_llm",
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 4,
                    "stream": True,
                },
            )
            assert r.status == 200
            return await r.text()

        _run(llm_served, fn)
        packets = llm_served._stats_queue.get_all(timeout=0.01)
        mine = [p for p in packets if p.get("_url") == "tiny_llm"]
        assert mine, "no stats packet for the streaming request"
        last = mine[-1]
        assert last.get("gen_tokens", 0) >= 1
        assert "ttft" in last and last["ttft"] >= 0
        assert last["_latency"] >= last["ttft"]
    finally:
        llm_served._metric_log_freq = 0.0


def test_streaming_flushes_trailing_replacement_char(llm_served):
    """A final delta ending in U+FFFD must still be flushed (ADVICE r1)."""
    import types

    from clearml_serving_tpu.llm.engine import GenRequest

    processor = llm_served._get_processor("tiny_llm")

    async def run():
        # token 0xE2 alone is an invalid utf-8 tail -> decodes to '�'
        req = GenRequest(prompt_ids=[256, 1, 2], max_new_tokens=3)
        deltas = []

        async def fake_generate(request):
            for t in [72, 105, 0xE2]:  # "H", "i", then a dangling utf-8 byte
                yield t

        orig = processor.engine.generate
        processor.engine.generate = fake_generate
        try:
            async for piece in processor._stream_deltas(req):
                deltas.append(piece["delta"])
        finally:
            processor.engine.generate = orig
        return "".join(deltas)

    text = asyncio.run(run())
    assert text == "Hi�"


def test_chat_template_no_double_bos(llm_served):
    """encode_chat must not re-add BOS to chat-template output (ADVICE r1)."""
    processor = llm_served._get_processor("tiny_llm")
    tok = processor.tokenizer
    prompt = tok.apply_chat_template([{"role": "user", "content": "x"}])
    ids = tok.encode_chat(prompt)
    assert ids[0] == tok.bos_token_id
    assert ids[1] != tok.bos_token_id


@pytest.fixture(scope="module")
def encoder_served(tmp_path_factory):
    """BERT-tiny encoder endpoint (task=embed) next to the decoder endpoint."""
    import os

    root = tmp_path_factory.mktemp("enc_state")
    os.environ["TPUSERVE_STATE_ROOT"] = str(root)
    mrp = ModelRequestProcessor(state_root=str(root), force_create=True, name="enc")
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="tiny_bert",
            auxiliary_cfg={
                "engine": {
                    "arch": "bert",
                    "preset": "bert-tiny",
                    "config": {"dtype": "float32", "num_labels": 3},
                    "task": "embed",
                    "labels": ["neg", "neu", "pos"],
                    "seq_buckets": [16, 32],
                    "batch_buckets": [1, 2, 4],
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    return mrp


def test_embeddings_route(encoder_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/embeddings",
            json={"model": "tiny_bert", "input": ["hello world", "hello world", "bye"]},
        )
        assert r.status == 200, await r.text()
        return await r.json()

    out = _run(encoder_served, fn)
    assert out["object"] == "list"
    assert len(out["data"]) == 3
    v0, v1, v2 = (np.array(d["embedding"]) for d in out["data"])
    # identical inputs -> identical embeddings; L2-normalized
    np.testing.assert_allclose(v0, v1, rtol=1e-5)
    np.testing.assert_allclose(np.linalg.norm(v0), 1.0, rtol=1e-5)
    assert not np.allclose(v0, v2)
    assert out["usage"]["prompt_tokens"] > 0


def test_embeddings_base64(encoder_served):
    """OpenAI SDK default format: base64-packed float32."""
    import base64

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/embeddings",
            json={"model": "tiny_bert", "input": "hi", "encoding_format": "base64"},
        )
        assert r.status == 200, await r.text()
        return await r.json()

    out = _run(encoder_served, fn)
    raw = base64.b64decode(out["data"][0]["embedding"])
    vec = np.frombuffer(raw, np.float32)
    assert vec.shape[0] == 64  # bert-tiny dim
    np.testing.assert_allclose(np.linalg.norm(vec), 1.0, rtol=1e-5)


def test_score_and_rerank_routes(encoder_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/score",
            json={"model": "tiny_bert", "text_1": "aaaa", "text_2": ["aaaa", "zzzz zz z"]},
        )
        assert r.status == 200, await r.text()
        score_out = await r.json()
        rr = await client.post(
            "/serve/openai/v1/rerank",
            json={
                "model": "tiny_bert",
                "query": "aaaa",
                "documents": ["zzzz zz z", "aaaa", "bbbb"],
                "top_n": 2,
            },
        )
        assert rr.status == 200, await rr.text()
        return score_out, await rr.json()

    score_out, rerank_out = _run(encoder_served, fn)
    scores = [d["score"] for d in score_out["data"]]
    assert len(scores) == 2
    # identical pair scores the cosine max
    assert scores[0] > scores[1]
    assert scores[0] == pytest.approx(1.0, rel=1e-4)
    results = rerank_out["results"]
    assert len(results) == 2
    # the identical document must rank first
    assert results[0]["document"]["text"] == "aaaa"
    assert results[0]["relevance_score"] >= results[1]["relevance_score"]


def test_classify_route(encoder_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/classify",
            json={"model": "tiny_bert", "input": ["hello", "world"]},
        )
        assert r.status == 200, await r.text()
        return await r.json()

    out = _run(encoder_served, fn)
    assert len(out["data"]) == 2
    for d in out["data"]:
        assert d["num_classes"] == 3
        assert d["label"] in ("neg", "neu", "pos")
        assert sum(d["probs"]) == pytest.approx(1.0, rel=1e-5)


def test_generation_route_gated_on_encoder(encoder_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json={"model": "tiny_bert", "messages": [{"role": "user", "content": "x"}]},
        )
        return r.status, await r.text()

    status, text = _run(encoder_served, fn)
    assert status == 422
    assert "does not support" in text


def test_encoder_long_input_and_many_inputs(encoder_served):
    """Inputs longer than the largest configured seq bucket (but within
    max_seq_len) and input counts beyond the largest batch bucket must both
    serve, not crash (review r2 findings 1-2)."""
    processor = encoder_served._get_processor("tiny_bert")
    enc = processor.encoder
    # fixture buckets: seq [16, 32] (+128 terminal), batch [1, 2, 4]
    long_ids = list(range(1, 60))  # > 32, < 128
    vecs = enc.embed([long_ids])
    assert vecs.shape == (1, 64)
    # 6 inputs straddling two chunks with different seq buckets
    mixed = [[1, 2, 3]] * 4 + [long_ids, [7] * 20]
    states = enc.token_states(mixed)
    assert [s.shape[0] for s in states] == [3, 3, 3, 3, 59, 20]
    assert enc.embed(mixed).shape == (6, 64)


def test_cross_encoder_pair_assembly():
    """num_labels==1 bundles joint-encode [CLS] a [SEP] b [SEP] (bare
    segments), keeping the final SEP under truncation (review r2 finding 3)."""
    import jax as _jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.encoder import EncoderCore

    bundle = models.build_model(
        "bert",
        {"preset": "bert-tiny", "dtype": "float32", "num_labels": 1, "max_seq_len": 16},
    )
    params = bundle.init(_jax.random.PRNGKey(0))
    enc = EncoderCore(bundle, params, cls_token_id=101, sep_token_id=102)
    assert enc.is_cross_encoder
    joined = enc._join_pair([5, 6], [7, 8])
    assert joined == [101, 5, 6, 102, 7, 8, 102]
    truncated = enc._join_pair(list(range(1, 10)), list(range(10, 20)))
    assert len(truncated) == 16
    assert truncated[0] == 101 and truncated[-1] == 102
    scores = enc.score_pairs([([5, 6], [7, 8]), ([5, 6], [9, 9])])
    assert len(scores) == 2 and all(0.0 <= s <= 1.0 for s in scores)


def test_unknown_task_rejected(tmp_path):
    import os

    os.environ["TPUSERVE_STATE_ROOT"] = str(tmp_path)
    mrp = ModelRequestProcessor(state_root=str(tmp_path), force_create=True, name="badtask")
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="bad_task_ep",
            auxiliary_cfg={
                "engine": {"arch": "bert", "preset": "bert-tiny", "task": "nonsense"}
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/embeddings", json={"model": "bad_task_ep", "input": "x"}
        )
        return r.status, await r.text()

    status, text = _run(mrp, fn)
    assert status == 422
    assert "unknown engine task" in text


def test_embeddings_dimensions(encoder_served):
    """OpenAI `dimensions` (matryoshka truncation): leading dims kept,
    re-normalized; out-of-range values 422."""

    async def fn(client):
        full = await client.post(
            "/serve/openai/v1/embeddings",
            json={"model": "tiny_bert", "input": "hi"},
        )
        cut = await client.post(
            "/serve/openai/v1/embeddings",
            json={"model": "tiny_bert", "input": "hi", "dimensions": 16},
        )
        bad = await client.post(
            "/serve/openai/v1/embeddings",
            json={"model": "tiny_bert", "input": "hi", "dimensions": 9999},
        )
        assert full.status == 200 and cut.status == 200
        return await full.json(), await cut.json(), bad.status

    full, cut, bad_status = _run(encoder_served, fn)
    v_full = np.array(full["data"][0]["embedding"])
    v_cut = np.array(cut["data"][0]["embedding"])
    assert v_cut.shape[0] == 16
    np.testing.assert_allclose(np.linalg.norm(v_cut), 1.0, rtol=1e-5)
    # the truncated vector is the renormalized prefix of the full one
    expect = v_full[:16] / np.linalg.norm(v_full[:16])
    np.testing.assert_allclose(v_cut, expect, rtol=1e-5)
    assert bad_status == 422


def test_prefix_cache_aux_config_plumbing(tmp_path, state_root):
    """aux engine.{prefix_cache,prefix_block,prefix_cache_pages} builds a
    radix cache on the paged backend, repeated chats hit it, and the live
    Prometheus collector is registered — all through the public API layer."""
    mrp = ModelRequestProcessor(
        state_root=str(state_root), force_create=True, name="llmpfx"
    )
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="tiny_llm_pfx",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 2,
                    "max_seq_len": 128,
                    "prefill_buckets": [32, 64],
                    "cache": "paged",
                    "page_size": 4,
                    "prefix_cache": 64,
                    "prefix_block": 16,
                    "prefix_cache_pages": 32,
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)

    body = {
        "model": "tiny_llm_pfx",
        "messages": [{"role": "user", "content": "repeat after me please"}],
        "max_tokens": 5,
        "temperature": 0,
    }

    async def fn(client):
        a = await client.post("/serve/openai/v1/chat/completions", json=body)
        assert a.status == 200, await a.text()
        b = await client.post("/serve/openai/v1/chat/completions", json=body)
        assert b.status == 200, await b.text()
        return await a.json(), await b.json()

    out_a, out_b = _run(mrp, fn)
    assert (
        out_a["choices"][0]["message"]["content"]
        == out_b["choices"][0]["message"]["content"]
    )
    processor = mrp._get_processor("tiny_llm_pfx")
    prefix = processor.engine._prefix
    assert prefix is not None
    assert prefix.block == 16  # 16 is already a page multiple
    assert prefix.max_pages == 32
    assert prefix.hits >= 1
    assert getattr(processor, "_prefix_collector", None) is not None
    # the collector scrapes the live cache under the model's label; the
    # hit counter carries a serving-tier label (docs/kv_tiering.md) —
    # summing over tiers recovers the total
    hits_by_tier = {}
    for m in processor._prefix_collector.collect():
        if m.name == "llm_prefix_cache_hits":
            for s in m.samples:
                if s.labels["model"] == "tiny_llm_pfx":
                    hits_by_tier[s.labels["tier"]] = s.value
    assert sum(hits_by_tier.values()) == prefix.hits
    assert hits_by_tier.get("hbm") == prefix.hits  # untiered: all resident
