import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from clearml_serving_tpu.serving.endpoints import ModelEndpoint
from clearml_serving_tpu.serving.main import build_app
from clearml_serving_tpu.serving.model_request_processor import ModelRequestProcessor


@pytest.fixture(scope="module")
def llm_served(tmp_path_factory):
    import os

    root = tmp_path_factory.mktemp("state")
    os.environ["TPUSERVE_STATE_ROOT"] = str(root)
    mrp = ModelRequestProcessor(state_root=str(root), force_create=True, name="llm")
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="tiny_llm",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 2,
                    "max_seq_len": 128,
                    "prefill_buckets": [32],
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    return mrp


def _run(mrp, fn):
    async def runner():
        client = TestClient(TestServer(build_app(mrp)))
        await client.start_server()
        try:
            return await fn(client)
        finally:
            await client.close()

    return asyncio.run(runner())


def test_chat_completion(llm_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json={
                "model": "tiny_llm",
                "messages": [{"role": "user", "content": "hello"}],
                "max_tokens": 6,
            },
        )
        assert r.status == 200, await r.text()
        return await r.json()

    out = _run(llm_served, fn)
    assert out["object"] == "chat.completion"
    assert out["choices"][0]["message"]["role"] == "assistant"
    assert out["usage"]["completion_tokens"] >= 1
    assert out["usage"]["prompt_tokens"] > 0


def test_chat_completion_streaming(llm_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json={
                "model": "tiny_llm",
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 5,
                "stream": True,
            },
        )
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/event-stream")
        return await r.text()

    text = _run(llm_served, fn)
    lines = [l for l in text.split("\n\n") if l.startswith("data: ")]
    assert lines[-1] == "data: [DONE]"
    first = json.loads(lines[0][len("data: "):])
    assert first["object"] == "chat.completion.chunk"
    assert first["choices"][0]["delta"].get("role") == "assistant"


def test_completions_and_tokenize(llm_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_llm", "prompt": "abc", "max_tokens": 4},
        )
        assert r.status == 200
        comp = await r.json()

        r = await client.post(
            "/serve/openai/v1/tokenize", json={"model": "tiny_llm", "prompt": "abc"}
        )
        tok = await r.json()
        r = await client.post(
            "/serve/openai/v1/detokenize",
            json={"model": "tiny_llm", "tokens": tok["tokens"]},
        )
        detok = await r.json()

        r = await client.post(
            "/serve/openai/v1/models", json={"model": "tiny_llm"}
        )
        mods = await r.json()
        return comp, tok, detok, mods

    comp, tok, detok, mods = _run(llm_served, fn)
    assert comp["object"] == "text_completion"
    assert tok["count"] == 4  # bos + 3 bytes
    assert detok["prompt"] == "abc"
    assert mods["data"][0]["id"] == "tiny_llm"


def test_unsupported_capability(llm_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/embeddings",
            json={"model": "tiny_llm", "input": "x"},
        )
        assert r.status == 422
        body = await r.json()
        assert "does not support" in body["detail"]

    _run(llm_served, fn)


def test_plain_serve_route(llm_served):
    """POST /serve/tiny_llm behaves as a non-streaming chat completion."""

    async def fn(client):
        r = await client.post(
            "/serve/tiny_llm",
            json={"messages": [{"role": "user", "content": "yo"}], "max_tokens": 3},
        )
        assert r.status == 200
        return await r.json()

    out = _run(llm_served, fn)
    assert out["object"] == "chat.completion"


def test_streaming_emits_stats_packet(llm_served):
    """Streaming requests must record TTFT/token stats at stream completion
    (VERDICT r1 #7: streaming chat is THE LLM workload)."""
    llm_served._metric_log_freq = 1.0  # sample every request
    try:
        async def fn(client):
            r = await client.post(
                "/serve/openai/v1/chat/completions",
                json={
                    "model": "tiny_llm",
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 4,
                    "stream": True,
                },
            )
            assert r.status == 200
            return await r.text()

        _run(llm_served, fn)
        packets = llm_served._stats_queue.get_all(timeout=0.01)
        mine = [p for p in packets if p.get("_url") == "tiny_llm"]
        assert mine, "no stats packet for the streaming request"
        last = mine[-1]
        assert last.get("gen_tokens", 0) >= 1
        assert "ttft" in last and last["ttft"] >= 0
        assert last["_latency"] >= last["ttft"]
    finally:
        llm_served._metric_log_freq = 0.0


def test_streaming_flushes_trailing_replacement_char(llm_served):
    """A final delta ending in U+FFFD must still be flushed (ADVICE r1)."""
    import types

    from clearml_serving_tpu.llm.engine import GenRequest

    processor = llm_served._get_processor("tiny_llm")

    async def run():
        # token 0xE2 alone is an invalid utf-8 tail -> decodes to '�'
        req = GenRequest(prompt_ids=[256, 1, 2], max_new_tokens=3)
        deltas = []

        async def fake_generate(request):
            for t in [72, 105, 0xE2]:  # "H", "i", then a dangling utf-8 byte
                yield t

        orig = processor.engine.generate
        processor.engine.generate = fake_generate
        try:
            async for piece in processor._stream_deltas(req):
                deltas.append(piece["delta"])
        finally:
            processor.engine.generate = orig
        return "".join(deltas)

    text = asyncio.run(run())
    assert text == "Hi�"


def test_chat_template_no_double_bos(llm_served):
    """encode_chat must not re-add BOS to chat-template output (ADVICE r1)."""
    processor = llm_served._get_processor("tiny_llm")
    tok = processor.tokenizer
    prompt = tok.apply_chat_template([{"role": "user", "content": "x"}])
    ids = tok.encode_chat(prompt)
    assert ids[0] == tok.bos_token_id
    assert ids[1] != tok.bos_token_id
