"""Tier-1 guard on the SLO loadtest artifact (benchmarks/LOADTEST_cpu.json).

The artifact is the committed evidence for the ISSUE 6 headline claim (at
>= 2x saturation: bounded interactive p99 TTFT, smooth batch goodput
degradation, sanitizer-clean preemptions). This test pins its SCHEMA — the
battery's phase 6 and `bench.py --loadtest --smoke` both regenerate it, and
a drifting shape would silently break the ROOFLINE.md methodology and any
dashboards reading it. It does NOT re-run the loadtest (tier-1 stays fast);
the committed numbers themselves are asserted only for internal
consistency, not re-measured.
"""

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from benchmarks import replica_loadtest  # noqa: E402
from benchmarks.slo_loadtest import (  # noqa: E402
    CLASS_KEYS,
    CLASSES,
    HEADLINE_KEYS,
    LOAD_KEYS,
    SCHEMA_KEYS,
    TRACES,
)


def _artifact():
    return json.loads((REPO / "benchmarks" / "LOADTEST_cpu.json").read_text())


def _replica_artifact():
    return json.loads(
        (REPO / "benchmarks" / "LOADTEST_replicas_cpu.json").read_text()
    )


def test_artifact_schema():
    row = _artifact()
    assert SCHEMA_KEYS <= set(row), "missing top-level keys"
    assert row["metric"].startswith("llm_slo_loadtest")
    assert set(row["mix"]) == {t["name"] for t in TRACES}
    assert {"p50", "p99", "samples"} <= set(row["unloaded_ttft_ms"])
    assert len(row["loads"]) >= 3, "sweep needs 0.5x/1x/2x points"
    for load in row["loads"]:
        assert LOAD_KEYS <= set(load)
        assert set(load["classes"]) == set(CLASSES)
        for cls in CLASSES:
            assert CLASS_KEYS <= set(load["classes"][cls]), cls
    assert HEADLINE_KEYS <= set(row["headline"])


def test_artifact_internal_consistency():
    row = _artifact()
    loads = sorted(row["loads"], key=lambda l: l["x_saturation"])
    assert loads[-1]["x_saturation"] >= 2.0, "no >=2x overload point"
    head = row["headline"]
    # the committed artifact must carry a PASSING headline: bounded
    # interactive tail, no batch cliff, sanitizer-clean preemptions
    assert head["ttft_within_bound"] is True
    assert head["batch_no_cliff"] is True
    assert head["preemptions_total"] >= 10
    assert head["sanitizer_violations"] == 0
    assert head["sanitizer_checks"] > 0
    # zero-recompile certification (docs/static_analysis.md TPU6xx): the
    # run completed under the STRICT compile sentry with every XLA compile
    # landing before llm/warmup.py's fence — no number in this artifact
    # hides a mid-run compile stall
    assert head["post_warmup_compiles"] == 0
    assert head["compile_sentry_mode"] in ("log", "monitoring")
    # leak-free certification (docs/static_analysis.md TPU7xx): the run
    # completed under the STRICT ownership ledger with zero lost releases
    # across every preemption/shed/deadline path the sweep exercised
    assert head["leaks"] == 0
    assert head["ledger_mode"] == "strict"
    # sharding certification (docs/static_analysis.md TPU8xx): the run
    # completed under the STRICT sharding sentry with zero implicit
    # device<->host transfers and zero unplanned reshards across every
    # loop-boundary audit — no number in this artifact was produced by a
    # silently host-materialized or drifted array
    assert head["implicit_transfers"] == 0
    assert head["unplanned_reshards"] == 0
    assert head["shard_sentry_mode"] == "strict"
    assert row["warmup"]["fenced"] is True
    # headline fields restate the curves they were derived from
    at_2x = loads[-1]["classes"]["interactive"]
    assert head["interactive_p99_ttft_at_2x_ms"] == at_2x["ttft_p99_ms"]
    assert head["batch_goodput_curve_tok_s"] == [
        l["classes"]["batch"]["goodput_tok_s"] for l in row["loads"]
    ]
    # per-class accounting adds up
    for load in row["loads"]:
        for cls in CLASSES:
            c = load["classes"][cls]
            assert c["completed"] + c["shed"] + c["errors"] <= c["requests"]


# -- replica-fleet loadtest artifact (docs/replication.md, ISSUE 12) ----------


def test_replica_artifact_schema():
    row = _replica_artifact()
    assert replica_loadtest.SCHEMA_KEYS <= set(row), "missing top-level keys"
    assert row["metric"].startswith("llm_replica_loadtest")
    assert row["replicas"] >= 2
    assert len(row["arms"]) == 3
    for arm in row["arms"]:
        assert replica_loadtest.ARM_KEYS <= set(arm), arm.keys()
    assert row["arms"][0]["replicas"] == 1
    assert row["arms"][1]["replicas"] == row["replicas"]
    assert [a["routing"] for a in row["arms"]] == [
        "single", "affine", "random"
    ]
    assert replica_loadtest.CHAOS_KEYS <= set(row["chaos"])
    assert replica_loadtest.HEADLINE_KEYS <= set(row["headline"])


def test_replica_artifact_headline_passes():
    """The committed artifact must carry a PASSING ISSUE-12 headline:
    affine-hit rate >= 0.9 on the repeated-conversation slice, aggregate
    goodput >= 1.6x the single-replica arm, zero post-warmup compiles
    under the strict sentry, zero sanitizer violations, and the
    kill-one-replica chaos case with zero user-visible 503s."""
    row = _replica_artifact()
    head = row["headline"]
    assert head["affine_ok"] is True
    assert head["affine_hit_rate"] >= 0.9
    assert head["speedup_ok"] is True
    assert head["speedup"] >= 1.6
    assert head["post_warmup_compiles"] == 0
    assert head["compile_sentry_mode"] in ("log", "monitoring")
    assert head["sanitizer_checks"] > 0
    assert head["sanitizer_violations"] == 0
    assert head["chaos_unavailable_errors"] == 0
    assert head["chaos_ok"] is True


def test_replica_artifact_internal_consistency():
    row = _replica_artifact()
    a1, a2, a3 = row["arms"]
    head = row["headline"]
    # headline fields restate the arms they were derived from
    assert head["goodput_tok_s_single"] == a1["goodput_tok_s"]
    assert head["goodput_tok_s_fleet"] == a2["goodput_tok_s"]
    assert head["affine_hit_rate"] == a2["affine_hit_rate"]
    assert abs(
        head["speedup"] - a2["goodput_tok_s"] / a1["goodput_tok_s"]
    ) < 0.01
    # every arm replayed the same trace
    assert a1["requests"] == a2["requests"] == a3["requests"]
    assert head["affine_hit_rate_random"] == a3["affine_hit_rate"]
    assert head["goodput_tok_s_random"] == a3["goodput_tok_s"]
    for arm in row["arms"]:
        assert arm["completed"] + arm["shed"] + arm["errors"] == arm["requests"]
        assert arm["sanitizer_violations"] == 0
        assert arm["post_warmup_compiles"] == 0
    # the route counters cover the fleet arm's routed requests, and the
    # single arm can only ever route to its one replica
    assert set(a2["routes"]) == {
        "r{}".format(i) for i in range(row["replicas"])
    }
    assert set(a1["routes"]) == {"r0"}
    # the chaos case drove a real ejection + re-warm + readmission
    chaos = row["chaos"]
    assert chaos["completed"] == chaos["requests"]
    assert chaos["unavailable_errors"] == 0 and chaos["other_errors"] == 0
    assert chaos["failovers"] >= 1
    assert chaos["ejections"] >= 1 and chaos["readmissions"] >= 1
    assert chaos["ring_recovered"] is True
    assert chaos["untouched_streams_identical"] is True
    assert chaos["failover_stream_identical"] is True


# -- disaggregated prefill/decode artifact (benchmarks/DISAGG_AB_cpu.json,
# docs/disaggregation.md; regenerated by
# `bench.py --loadtest --replicas 2 --disaggregated --smoke`) ---------------

from benchmarks import disagg_loadtest  # noqa: E402


def _disagg_artifact():
    return json.loads(
        (REPO / "benchmarks" / "DISAGG_AB_cpu.json").read_text()
    )


def test_disagg_artifact_schema():
    row = _disagg_artifact()
    assert disagg_loadtest.SCHEMA_KEYS <= set(row), "missing top-level keys"
    assert row["metric"].startswith("llm_disagg_loadtest")
    assert row["replicas"] >= 2
    assert len(row["arms"]) == 3
    for arm in row["arms"]:
        assert disagg_loadtest.ARM_KEYS <= set(arm), arm.keys()
    assert [a["name"] for a in row["arms"]] == ["mono", "hybrid", "disagg"]
    assert row["arms"][0]["replicas"] == 1
    assert row["arms"][2]["replicas"] == row["replicas"]
    assert "decode" in row["arms"][2]["roles"]
    assert "prefill" in row["arms"][2]["roles"]
    assert disagg_loadtest.HEADLINE_KEYS <= set(row["headline"])


def test_disagg_artifact_headline_passes():
    """The committed artifact must carry a PASSING ISSUE-14 headline:
    ship hit rate >= 0.9 on the clean path (the decode replica's
    admissions recompute none of the shipped KV), byte-identical streams
    across all three arms, zero sanitizer violations, and zero
    post-warmup compiles under the strict sentry."""
    row = _disagg_artifact()
    head = row["headline"]
    assert head["ship_ok"] is True
    assert head["ship_hit_rate"] >= head["ship_hit_bound"] == 0.9
    assert head["streams_identical"] is True
    assert head["post_warmup_compiles"] == 0
    assert head["compile_sentry_mode"] in ("log", "monitoring")
    assert head["sanitizer_checks"] > 0
    assert head["sanitizer_violations"] == 0


def test_disagg_artifact_internal_consistency():
    row = _disagg_artifact()
    a1, a2, a3 = row["arms"]
    head = row["headline"]
    # every arm replayed the same trace, and nothing was lost
    assert a1["requests"] == a2["requests"] == a3["requests"]
    for arm in row["arms"]:
        assert arm["completed"] + arm["shed"] + arm["errors"] == arm["requests"]
        assert arm["completed"] == arm["requests"], "clean path must complete"
        assert arm["sanitizer_violations"] == 0
        assert arm["post_warmup_compiles"] == 0
    # only the disagg arm carries transport traffic; its clean path took
    # no drops, no receive failures, no re-routes
    assert a1["kv_ship"] is None and a1["disaggregation"] is None
    assert a3["kv_ship"] is not None and a3["disaggregation"] is not None
    ship = a3["kv_ship"]
    dis = a3["disaggregation"]
    assert head["ship_hit_rate"] == ship["hit_rate"]
    assert ship["hits"] > 0 and ship["receives"] > 0
    assert ship["ships"] == ship["receives"], "clean path: every shipment lands"
    # the import attaches only MISSING blocks (earlier turns' blocks are
    # already resident on the decode replica), so pages imported can be
    # fewer than pages shipped — never more
    assert 0 < ship["receive_pages"] <= ship["ship_pages"]
    assert dis["ship_leg_failures"] == 0
    assert dis["receive_reroutes"] == 0
    assert dis["transport"]["dropped"] == 0
    # every judged shipped request either hit or recomputed; the clean
    # path's ship legs all produced a judged outcome
    assert ship["hits"] + ship["recomputes"] == dis["ship_legs"]
    # byte-identity columns restate the arms
    assert a2["streams_identical_to_mono"] is True
    assert a3["streams_identical_to_mono"] is True
    assert head["goodput_tok_s_mono"] == a1["goodput_tok_s"]
    assert head["goodput_tok_s_hybrid"] == a2["goodput_tok_s"]
    assert head["goodput_tok_s_disagg"] == a3["goodput_tok_s"]


# -- process-backend fleet artifact (benchmarks/PROCESS_FLEET_cpu.json,
# docs/replication.md "process backends"; regenerated by
# `python benchmarks/process_fleet_loadtest.py --smoke`) ---------------------

from benchmarks import process_fleet_loadtest  # noqa: E402


def _process_artifact():
    return json.loads(
        (REPO / "benchmarks" / "PROCESS_FLEET_cpu.json").read_text()
    )


def test_process_artifact_schema():
    row = _process_artifact()
    assert process_fleet_loadtest.SCHEMA_KEYS <= set(row), (
        "missing top-level keys"
    )
    assert row["metric"].startswith("llm_process_fleet_loadtest")
    assert row["replicas"] == 2
    assert len(row["arms"]) == 2
    for arm in row["arms"]:
        assert process_fleet_loadtest.ARM_KEYS <= set(arm), arm.keys()
    assert [a["name"] for a in row["arms"]] == ["mono", "proc_disagg"]
    assert [a["backend"] for a in row["arms"]] == ["inprocess", "process"]
    assert row["arms"][1]["roles"] == ["prefill", "decode"]
    assert row["trace"]["seeded_requests"] >= 1
    assert process_fleet_loadtest.HEADLINE_KEYS <= set(row["headline"])


def test_process_artifact_headline_passes():
    """The committed artifact must carry a PASSING ISSUE-19 headline:
    ship hit rate >= 0.9 across a REAL socket hop between two worker
    processes, streams byte-identical to the mono in-process arm (greedy
    AND seeded), zero sanitizer violations, zero ownership-ledger leaks,
    zero post-warmup compiles under the strict sentry, and zero implicit
    transfers — the worker-side certificates read over the health RPC."""
    row = _process_artifact()
    head = row["headline"]
    assert head["ship_ok"] is True
    assert head["ship_hit_rate"] >= head["ship_hit_bound"] == 0.9
    assert head["streams_identical"] is True
    assert head["seeded_identical"] is True
    assert head["post_warmup_compiles"] == 0
    assert head["compile_sentry_mode"] == "strict"
    assert head["sanitizer_checks"] > 0
    assert head["sanitizer_violations"] == 0
    assert head["ledger_leaks"] == 0
    assert head["implicit_transfers"] == 0
    # the clean-path run restarted nothing: every ship leg crossed a live
    # socket, and real bytes moved
    assert head["worker_restarts"] == 0
    assert head["wire_bytes_total"] > 0
    assert head["wire_frames_total"] > 0


def test_process_artifact_internal_consistency():
    row = _process_artifact()
    a1, a2 = row["arms"]
    head = row["headline"]
    # both arms replayed the same trace, and nothing was lost
    assert a1["requests"] == a2["requests"]
    for arm in row["arms"]:
        assert arm["completed"] + arm["shed"] + arm["errors"] == arm["requests"]
        assert arm["completed"] == arm["requests"], "clean path must complete"
        assert arm["sanitizer_violations"] == 0
        assert arm["ledger_leaks"] == 0
        assert arm["implicit_transfers"] == 0
        assert arm["post_warmup_compiles"] == 0
    # only the process arm carries socket traffic; the mono baseline has
    # no transport at all
    assert a1["wire"] is None
    assert a2["wire"] is not None
    assert head["wire_bytes_total"] == a2["wire"]["bytes_total"]
    assert head["wire_frames_total"] == a2["wire"]["frames_total"]
    ship = a2["kv_ship"]
    assert ship is not None
    assert head["ship_hit_rate"] == ship["hit_rate"]
    assert ship["hits"] > 0 and ship["receives"] > 0
    assert ship["ships"] == ship["receives"], "every SENT shipment lands"
    assert ship["receive_failures"] == 0
    assert head["ship_legs"] == ship["ships"]
    assert head["ship_drops"] == ship["ship_drops"]
    # drop-to-recompute restated: a send-side drop is counted, never
    # raised, and the leg still completes — so every completed leg
    # (landed or dropped) is judged exactly once at decode admission
    assert (
        ship["hits"] + ship["recomputes"]
        == ship["ships"] + ship["ship_drops"]
    )
    # byte-identity columns restate the arms
    assert a1["streams_identical_to_mono"] is None
    assert a2["streams_identical_to_mono"] is True
    assert a2["seeded_identical_to_mono"] is True
    assert head["goodput_tok_s_mono"] == a1["goodput_tok_s"]
    assert head["goodput_tok_s_proc"] == a2["goodput_tok_s"]
    assert head["worker_restarts"] == a2["restarts"]
