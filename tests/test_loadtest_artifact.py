"""Tier-1 guard on the SLO loadtest artifact (benchmarks/LOADTEST_cpu.json).

The artifact is the committed evidence for the ISSUE 6 headline claim (at
>= 2x saturation: bounded interactive p99 TTFT, smooth batch goodput
degradation, sanitizer-clean preemptions). This test pins its SCHEMA — the
battery's phase 6 and `bench.py --loadtest --smoke` both regenerate it, and
a drifting shape would silently break the ROOFLINE.md methodology and any
dashboards reading it. It does NOT re-run the loadtest (tier-1 stays fast);
the committed numbers themselves are asserted only for internal
consistency, not re-measured.
"""

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from benchmarks.slo_loadtest import (  # noqa: E402
    CLASS_KEYS,
    CLASSES,
    HEADLINE_KEYS,
    LOAD_KEYS,
    SCHEMA_KEYS,
    TRACES,
)


def _artifact():
    return json.loads((REPO / "benchmarks" / "LOADTEST_cpu.json").read_text())


def test_artifact_schema():
    row = _artifact()
    assert SCHEMA_KEYS <= set(row), "missing top-level keys"
    assert row["metric"].startswith("llm_slo_loadtest")
    assert set(row["mix"]) == {t["name"] for t in TRACES}
    assert {"p50", "p99", "samples"} <= set(row["unloaded_ttft_ms"])
    assert len(row["loads"]) >= 3, "sweep needs 0.5x/1x/2x points"
    for load in row["loads"]:
        assert LOAD_KEYS <= set(load)
        assert set(load["classes"]) == set(CLASSES)
        for cls in CLASSES:
            assert CLASS_KEYS <= set(load["classes"][cls]), cls
    assert HEADLINE_KEYS <= set(row["headline"])


def test_artifact_internal_consistency():
    row = _artifact()
    loads = sorted(row["loads"], key=lambda l: l["x_saturation"])
    assert loads[-1]["x_saturation"] >= 2.0, "no >=2x overload point"
    head = row["headline"]
    # the committed artifact must carry a PASSING headline: bounded
    # interactive tail, no batch cliff, sanitizer-clean preemptions
    assert head["ttft_within_bound"] is True
    assert head["batch_no_cliff"] is True
    assert head["preemptions_total"] >= 10
    assert head["sanitizer_violations"] == 0
    assert head["sanitizer_checks"] > 0
    # zero-recompile certification (docs/static_analysis.md TPU6xx): the
    # run completed under the STRICT compile sentry with every XLA compile
    # landing before llm/warmup.py's fence — no number in this artifact
    # hides a mid-run compile stall
    assert head["post_warmup_compiles"] == 0
    assert head["compile_sentry_mode"] in ("log", "monitoring")
    assert row["warmup"]["fenced"] is True
    # headline fields restate the curves they were derived from
    at_2x = loads[-1]["classes"]["interactive"]
    assert head["interactive_p99_ttft_at_2x_ms"] == at_2x["ttft_p99_ms"]
    assert head["batch_goodput_curve_tok_s"] == [
        l["classes"]["batch"]["goodput_tok_s"] for l in row["loads"]
    ]
    # per-class accounting adds up
    for load in row["loads"]:
        for cls in CLASSES:
            c = load["classes"][cls]
            assert c["completed"] + c["shed"] + c["errors"] <= c["requests"]
