"""Multi-LoRA serving tests (models/lora.py + llama lora_idx threading +
engine adapter routing).

Ground truth for the batched gather path is the classic offline dense merge
(W + A @ B): per-slot stacked-LoRA outputs must match a model whose weights
were merged with the same adapter.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.models import lora as lora_lib

TINY = {
    "preset": "llama-tiny",
    "dtype": "float32",
    "lora_rank": 4,
    "max_loras": 2,
}


def _rand_adapter(cfg, n_layers, rng, targets=("wq", "wk", "wv", "wo"), rank=4):
    """Random adapter tree {target: {"a": [L, in, r], "b": [L, r, out]}}."""
    out = {}
    for t in targets:
        d_in, d_out = lora_lib.target_dims(cfg, t)
        k1, k2, rng = jax.random.split(rng, 3)
        out[t] = {
            "a": 0.1 * np.asarray(jax.random.normal(k1, (n_layers, d_in, rank))),
            "b": 0.1 * np.asarray(jax.random.normal(k2, (n_layers, rank, d_out))),
        }
    return out


@pytest.fixture(scope="module")
def lora_parts():
    bundle = models.build_model("llama", TINY)
    params = bundle.init(jax.random.PRNGKey(0))
    adapter = _rand_adapter(bundle.config, bundle.n_layers, jax.random.PRNGKey(7))
    params = lora_lib.install_adapter(params, 1, adapter)
    return bundle, params, adapter


def test_base_index_matches_no_lora(lora_parts):
    """lora_idx == 0 must equal a model built without LoRA entirely."""
    bundle, params, _ = lora_parts
    plain_bundle = models.build_model(
        "llama", {k: v for k, v in TINY.items() if not k.startswith(("lora", "max_"))}
    )
    plain_params = plain_bundle.init(jax.random.PRNGKey(0))
    tokens = jnp.asarray([[5, 9, 2, 17, 33, 1, 4, 8]], jnp.int32)
    base = plain_bundle.apply(plain_params, tokens)
    via_zero = bundle.apply(params, tokens, lora_idx=jnp.zeros((1,), jnp.int32))
    np.testing.assert_allclose(
        np.asarray(base), np.asarray(via_zero), rtol=1e-5, atol=1e-5
    )


def test_adapter_matches_dense_merge(lora_parts):
    """Batched stacked-LoRA == offline dense merge of the same adapter."""
    bundle, params, adapter = lora_parts
    plain_bundle = models.build_model(
        "llama", {k: v for k, v in TINY.items() if not k.startswith(("lora", "max_"))}
    )
    merged = lora_lib.merge_adapter_into_weights(
        plain_bundle.init(jax.random.PRNGKey(0)), adapter
    )
    tokens = jnp.asarray([[5, 9, 2, 17, 33, 1, 4, 8]], jnp.int32)
    want = plain_bundle.apply(merged, tokens)
    got = bundle.apply(params, tokens, lora_idx=jnp.ones((1,), jnp.int32))
    np.testing.assert_allclose(np.asarray(want), np.asarray(got), rtol=2e-4, atol=2e-4)


def test_mixed_batch_slots_independent(lora_parts):
    """A batch mixing base + adapter rows must equal per-row single runs."""
    bundle, params, _ = lora_parts
    tokens = jnp.asarray(
        [[5, 9, 2, 17, 33, 1, 4, 8], [5, 9, 2, 17, 33, 1, 4, 8]], jnp.int32
    )
    mixed = bundle.apply(params, tokens, lora_idx=jnp.asarray([0, 1], jnp.int32))
    solo0 = bundle.apply(params, tokens[:1], lora_idx=jnp.asarray([0], jnp.int32))
    solo1 = bundle.apply(params, tokens[1:], lora_idx=jnp.asarray([1], jnp.int32))
    np.testing.assert_allclose(
        np.asarray(mixed[0]), np.asarray(solo0[0]), rtol=1e-5, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(mixed[1]), np.asarray(solo1[0]), rtol=1e-5, atol=1e-5
    )
    # and the two rows genuinely differ (the adapter does something)
    assert not np.allclose(np.asarray(mixed[0]), np.asarray(mixed[1]), atol=1e-3)


def test_prefill_decode_with_adapter_matches_apply(lora_parts):
    """The cached serving path (prefill + decode) under an adapter agrees
    with the uncached causal forward's argmax chain."""
    bundle, params, _ = lora_parts
    ids = [5, 9, 2, 17, 33]
    lora1 = jnp.ones((1,), jnp.int32)
    tokens = jnp.asarray([ids], jnp.int32)
    cache = bundle.init_cache(1, 32)
    logits, cache = bundle.prefill(
        params, tokens, jnp.asarray([len(ids)], jnp.int32), cache, lora1
    )
    ref_logits = bundle.apply(params, tokens, lora_idx=lora1)
    np.testing.assert_allclose(
        np.asarray(logits[0]), np.asarray(ref_logits[0, -1]), rtol=1e-4, atol=1e-4
    )
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits2, cache = bundle.decode(params, nxt, cache, lora1)
    full = jnp.asarray([ids + [int(nxt[0])]], jnp.int32)
    ref2 = bundle.apply(params, full, lora_idx=lora1)
    np.testing.assert_allclose(
        np.asarray(logits2[0]), np.asarray(ref2[0, -1]), rtol=1e-4, atol=1e-4
    )


def test_lower_rank_adapter_pads(lora_parts):
    bundle, _, _ = lora_parts
    params = bundle.init(jax.random.PRNGKey(0))
    adapter = _rand_adapter(
        bundle.config, bundle.n_layers, jax.random.PRNGKey(3), rank=2
    )
    params2 = lora_lib.install_adapter(params, 2, adapter)
    tokens = jnp.asarray([[5, 9, 2, 17]], jnp.int32)
    base = bundle.apply(params2, tokens, lora_idx=jnp.zeros((1,), jnp.int32))
    with_a = bundle.apply(params2, tokens, lora_idx=jnp.full((1,), 2, jnp.int32))
    assert not np.allclose(np.asarray(base), np.asarray(with_a), atol=1e-4)


def test_install_adapter_bounds(lora_parts):
    bundle, params, adapter = lora_parts
    with pytest.raises(ValueError):
        lora_lib.install_adapter(params, 0, adapter)  # 0 is the base
    with pytest.raises(ValueError):
        lora_lib.install_adapter(params, 3, adapter)  # max_loras=2
    big = _rand_adapter(bundle.config, bundle.n_layers, jax.random.PRNGKey(1), rank=8)
    with pytest.raises(ValueError):
        lora_lib.install_adapter(params, 1, big)  # rank 8 > built rank 4


def test_quantize_keeps_lora_full_precision(lora_parts):
    from clearml_serving_tpu.ops.quant import quantize_llama_params

    bundle, params, _ = lora_parts
    q = quantize_llama_params(params)
    layers = q["layers"]
    sample = layers if isinstance(layers, dict) else layers[0]
    assert isinstance(sample["wq"], dict) and "_q8" in sample["wq"]
    assert not isinstance(sample["lora_a_wq"], dict)  # untouched array
    tokens = jnp.asarray([[5, 9, 2, 17]], jnp.int32)
    out = bundle.apply(q, tokens, lora_idx=jnp.ones((1,), jnp.int32))
    assert np.isfinite(np.asarray(out)).all()


def test_scan_layers_lora_matches_unscanned():
    cfg = dict(TINY)
    bundle = models.build_model("llama", cfg)
    params = bundle.init(jax.random.PRNGKey(0))
    adapter = _rand_adapter(bundle.config, bundle.n_layers, jax.random.PRNGKey(7))
    params = lora_lib.install_adapter(params, 1, adapter)

    scan_bundle = models.build_model("llama", dict(cfg, scan_layers=True))
    scan_params = scan_bundle.prepare_params(
        {k: (list(v) if k == "layers" else v) for k, v in params.items()}
    )
    tokens = jnp.asarray([[5, 9, 2, 17, 33, 1]], jnp.int32)
    one = jnp.ones((1,), jnp.int32)
    a = bundle.apply(params, tokens, lora_idx=one)
    b = scan_bundle.apply(scan_params, tokens, lora_idx=one)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)


def test_peft_adapter_roundtrip(tmp_path):
    """A PEFT-format checkpoint (adapter_model.bin + adapter_config.json)
    loads with the alpha/r scaling folded into B."""
    import json

    import torch

    bundle = models.build_model("llama", TINY)
    cfg = bundle.config
    n_layers = bundle.n_layers
    rank, alpha = 4, 8.0
    rng = np.random.RandomState(0)
    sd = {}
    d_in, d_out = lora_lib.target_dims(cfg, "wq")
    for li in range(n_layers):
        prefix = "base_model.model.model.layers.{}.self_attn.q_proj".format(li)
        sd[prefix + ".lora_A.weight"] = torch.tensor(
            rng.randn(rank, d_in).astype(np.float32)
        )
        sd[prefix + ".lora_B.weight"] = torch.tensor(
            rng.randn(d_out, rank).astype(np.float32)
        )
    torch.save(sd, tmp_path / "adapter_model.bin")
    (tmp_path / "adapter_config.json").write_text(
        json.dumps({"r": rank, "lora_alpha": alpha, "target_modules": ["q_proj"]})
    )
    tree = lora_lib.load_adapter(tmp_path, n_layers)
    assert set(tree) == {"wq"}
    assert tree["wq"]["a"].shape == (n_layers, d_in, rank)
    assert tree["wq"]["b"].shape == (n_layers, rank, d_out)
    # scaling folded: b == (alpha/r) * B^T
    want = (alpha / rank) * np.asarray(
        sd["base_model.model.model.layers.0.self_attn.q_proj.lora_B.weight"]
    ).T
    np.testing.assert_allclose(tree["wq"]["b"][0], want, rtol=1e-6)


def test_native_adapter_save_load(tmp_path):
    bundle = models.build_model("llama", TINY)
    adapter = _rand_adapter(bundle.config, bundle.n_layers, jax.random.PRNGKey(5))
    lora_lib.save_adapter(tmp_path / "ad", adapter)
    back = lora_lib.load_adapter(tmp_path / "ad", bundle.n_layers)
    for t in adapter:
        np.testing.assert_allclose(back[t]["a"], adapter[t]["a"], rtol=1e-6)
        np.testing.assert_allclose(back[t]["b"], adapter[t]["b"], rtol=1e-6)


# -- engine-level -------------------------------------------------------------


def _engine(bundle, params, **kw):
    from clearml_serving_tpu.llm.engine import LLMEngineCore

    kw.setdefault("max_batch", 4)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("prefill_buckets", [16])
    kw.setdefault("eos_token_id", None)
    kw.setdefault("decode_steps", 2)
    return LLMEngineCore(bundle, params, **kw)


def test_engine_routes_adapters():
    """Two concurrent requests on different adapters produce the same tokens
    as each adapter run alone; unknown adapter names are rejected."""
    from clearml_serving_tpu.llm.engine import GenRequest

    bundle = models.build_model("llama", TINY)
    params = bundle.init(jax.random.PRNGKey(0))
    ad1 = _rand_adapter(bundle.config, bundle.n_layers, jax.random.PRNGKey(7))
    ad2 = _rand_adapter(bundle.config, bundle.n_layers, jax.random.PRNGKey(8))
    adapters = {"fin-tune": ad1, "med-tune": ad2}
    prompt = [5, 9, 2, 17, 33, 1]

    async def run_pair():
        engine = _engine(bundle, params, lora_adapters=adapters)
        reqs = [
            GenRequest(prompt_ids=list(prompt), max_new_tokens=6, adapter=a)
            for a in (None, "fin-tune", "med-tune")
        ]

        async def collect(r):
            return [t async for t in engine.generate(r)]

        outs = await asyncio.gather(*[collect(r) for r in reqs])
        engine.stop()
        return outs

    async def run_solo(adapter):
        engine = _engine(bundle, params, lora_adapters=adapters)
        req = GenRequest(prompt_ids=list(prompt), max_new_tokens=6, adapter=adapter)
        out = [t async for t in engine.generate(req)]
        engine.stop()
        return out

    base, fin, med = asyncio.run(run_pair())
    assert fin != base or med != base  # adapters change greedy output
    assert asyncio.run(run_solo("fin-tune")) == fin
    assert asyncio.run(run_solo("med-tune")) == med

    async def run_unknown():
        engine = _engine(bundle, params, lora_adapters=adapters)
        req = GenRequest(prompt_ids=list(prompt), max_new_tokens=2, adapter="nope")
        try:
            with pytest.raises(ValueError):
                async for _ in engine.generate(req):
                    pass
        finally:
            engine.stop()

    asyncio.run(run_unknown())


def test_router_serves_adapter_by_model_field(tmp_path):
    """Full stack: aux engine.lora.modules -> endpoint load -> OpenAI chat
    with `model` naming the adapter; /v1/models lists it with a parent."""
    import os

    from aiohttp.test_utils import TestClient, TestServer

    from clearml_serving_tpu.serving.endpoints import ModelEndpoint
    from clearml_serving_tpu.serving.main import build_app
    from clearml_serving_tpu.serving.model_request_processor import (
        ModelRequestProcessor,
    )

    bundle = models.build_model("llama", TINY)
    adapter = _rand_adapter(bundle.config, bundle.n_layers, jax.random.PRNGKey(9))
    lora_lib.save_adapter(tmp_path / "tuned", adapter)

    root = tmp_path / "state"
    os.environ["TPUSERVE_STATE_ROOT"] = str(root)
    try:
        mrp = ModelRequestProcessor(
            state_root=str(root), force_create=True, name="lora-llm"
        )
        mrp.add_endpoint(
            ModelEndpoint(
                engine_type="llm",
                serving_url="lora_llm",
                auxiliary_cfg={
                    "engine": {
                        "preset": "llama-tiny",
                        "config": {
                            "dtype": "float32",
                            "lora_rank": 4,
                            "max_loras": 2,
                        },
                        "max_batch": 2,
                        "max_seq_len": 64,
                        "prefill_buckets": [16],
                        "lora": {"modules": {"tuned": str(tmp_path / "tuned")}},
                    }
                },
            )
        )
        mrp.serialize()
        mrp.deserialize(skip_sync=True)

        async def drive():
            client = TestClient(TestServer(build_app(mrp)))
            await client.start_server()
            try:
                body = {
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 4,
                    # suppress EOS (ByteTokenizer id 257): the tiny random
                    # model can greedily emit it first on BOTH routes, and
                    # two empty contents would vacuously equal each other
                    "logit_bias": {"257": -100},
                    # compare chosen-token logprobs, not decoded text: the
                    # adapter perturbs every logit, so the floats must
                    # differ even if the argmax tokens happen to coincide
                    "logprobs": True,
                }
                r_base = await client.post(
                    "/serve/openai/v1/chat/completions",
                    json=dict(body, model="lora_llm"),
                )
                assert r_base.status == 200, await r_base.text()
                r_tuned = await client.post(
                    "/serve/openai/v1/chat/completions",
                    json=dict(body, model="tuned"),
                )
                assert r_tuned.status == 200, await r_tuned.text()
                r_models = await client.post(
                    "/serve/openai/v1/models", json={"model": "lora_llm"}
                )
                listing = await r_models.json()
                return (
                    await r_base.json(),
                    await r_tuned.json(),
                    listing,
                )
            finally:
                await client.close()

        base, tuned, listing = asyncio.run(drive())
        ids = {m["id"]: m for m in listing["data"]}
        assert "tuned" in ids and ids["tuned"].get("parent") == "lora_llm"
        # the adapter changes the greedy decode: tokens or (at minimum)
        # their logprobs must differ — identical floats under different
        # effective weights would mean the adapter never routed
        def trace(out):
            return [
                (e["token"], round(e["logprob"], 6))
                for e in out["choices"][0]["logprobs"]["content"]
            ]

        assert trace(base) != trace(tuned)
    finally:
        os.environ.pop("TPUSERVE_STATE_ROOT", None)


def test_engine_lora_with_speculation():
    """Adapter routing composes with n-gram speculative decoding (verify
    threads lora_idx): greedy output equals the plain-decode engine's."""
    from clearml_serving_tpu.llm.engine import GenRequest

    bundle = models.build_model("llama", TINY)
    params = bundle.init(jax.random.PRNGKey(0))
    ad = {"tune": _rand_adapter(bundle.config, bundle.n_layers, jax.random.PRNGKey(7))}
    prompt = [5, 9, 2, 17, 5, 9, 2]

    async def run(**kw):
        engine = _engine(bundle, params, lora_adapters=ad, **kw)
        req = GenRequest(prompt_ids=list(prompt), max_new_tokens=8, adapter="tune")
        out = [t async for t in engine.generate(req)]
        engine.stop()
        return out

    plain = asyncio.run(run())
    spec = asyncio.run(run(speculation="ngram", spec_k=2, spec_ngram=2))
    assert spec == plain


def test_score_prompt_uses_adapter():
    """echo+logprobs prompt scoring must run through the SAME LoRA the
    generation uses — base-model prompt logprobs next to adapter generated
    logprobs would be silently wrong (r5 review)."""
    bundle = models.build_model("llama", TINY)
    params = bundle.init(jax.random.PRNGKey(0))
    ad = _rand_adapter(bundle.config, bundle.n_layers, jax.random.PRNGKey(7))
    engine = _engine(bundle, params, lora_adapters={"tune": ad})
    prompt = [5, 9, 2, 17, 33, 1]
    base = engine.score_prompt(prompt)
    tuned = engine.score_prompt(prompt, adapter="tune")
    assert len(base) == len(tuned) == len(prompt) - 1
    assert any(
        abs(a["logprob"] - b["logprob"]) > 1e-6 for a, b in zip(base, tuned)
    ), "adapter did not change prompt scoring"
    engine.stop()
