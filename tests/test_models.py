import jax
import jax.numpy as jnp
import numpy as np
import pytest

from clearml_serving_tpu import models


def test_mlp_shapes():
    b = models.build_model("mlp", {"in_dim": 4, "hidden": [8], "out_dim": 3})
    params = b.init(jax.random.PRNGKey(0))
    out = b.apply(params, jnp.ones((5, 4)))
    assert out.shape == (5, 3)
    assert np.isfinite(np.asarray(out)).all()


def test_cnn_shapes():
    b = models.build_model("cnn", {"in_hw": (28, 28), "channels": [4, 8], "dense": 16})
    params = b.init(jax.random.PRNGKey(0))
    out = b.apply(params, jnp.ones((2, 28, 28)))  # channel dim auto-added
    assert out.shape == (2, 10)


def test_bert_shapes_and_masking():
    b = models.build_model("bert", {"preset": "bert-tiny", "num_labels": 5, "dtype": "float32"})
    params = b.init(jax.random.PRNGKey(0))
    ids = jnp.ones((2, 16), jnp.int32)
    mask = jnp.array([[1] * 16, [1] * 4 + [0] * 12], jnp.int32)
    out = b.apply(params, ids, mask)
    assert out.shape == (2, 16, 5)
    # masked positions must not influence unmasked token outputs:
    ids2 = ids.at[1, 8].set(7)  # change a masked-out token
    out2 = b.apply(params, ids2, mask)
    np.testing.assert_allclose(out[1, :4], out2[1, :4], rtol=2e-4, atol=2e-4)


def test_unknown_arch():
    with pytest.raises(ValueError):
        models.build_model("nope", {})


class TestLlama:
    @pytest.fixture(scope="class")
    def setup(self):
        b = models.build_model("llama", {"preset": "llama-tiny", "dtype": "float32"})
        params = b.init(jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, 512)
        return b, params, tokens

    def test_causal_forward(self, setup):
        b, params, tokens = setup
        logits = b.apply(params, tokens)
        assert logits.shape == (2, 12, 512)
        # causality: changing a later token must not affect earlier logits
        tokens2 = tokens.at[:, 9].set(3)
        logits2 = b.apply(params, tokens2)
        np.testing.assert_allclose(logits[:, :9], logits2[:, :9], rtol=1e-4, atol=1e-4)
        assert not np.allclose(logits[:, 9:], logits2[:, 9:])

    def test_prefill_matches_forward(self, setup):
        b, params, tokens = setup
        full = b.apply(params, tokens)
        cache = b.init_cache(batch=2, max_len=32)
        seq_lens = jnp.array([12, 12], jnp.int32)
        last, cache = b.prefill(params, tokens, seq_lens, cache)
        np.testing.assert_allclose(last, full[:, -1], rtol=1e-3, atol=1e-3)

    def test_ragged_prefill(self, setup):
        b, params, tokens = setup
        # sequence 1 is only 5 tokens (right-padded): last logits must equal
        # a dense forward over just those 5 tokens.
        cache = b.init_cache(batch=2, max_len=32)
        seq_lens = jnp.array([12, 5], jnp.int32)
        last, cache = b.prefill(params, tokens, seq_lens, cache)
        short = b.apply(params, tokens[1:2, :5])
        np.testing.assert_allclose(last[1], short[0, -1], rtol=1e-3, atol=1e-3)

    def test_scan_layers_matches_unrolled(self, setup):
        """scan_layers=True (stacked params + lax.scan) must be numerically
        identical to the unrolled python-loop build, across apply, prefill,
        decode, and decode_paged."""
        b_unroll, params_u, tokens = setup
        b_scan = models.build_model(
            "llama", {"preset": "llama-tiny", "dtype": "float32", "scan_layers": True}
        )
        # stack the unrolled params so both builds share weights
        params_s = dict(params_u)
        params_s["layers"] = jax.tree.map(
            lambda *xs: jnp.stack(xs), *params_u["layers"]
        )
        np.testing.assert_allclose(
            b_scan.apply(params_s, tokens), b_unroll.apply(params_u, tokens),
            rtol=1e-4, atol=1e-4,
        )
        seq_lens = jnp.array([12, 7], jnp.int32)
        cache_u = b_unroll.init_cache(2, 32)
        cache_s = b_scan.init_cache(2, 32)
        last_u, cache_u = b_unroll.prefill(params_u, tokens, seq_lens, cache_u)
        last_s, cache_s = b_scan.prefill(params_s, tokens, seq_lens, cache_s)
        np.testing.assert_allclose(last_s, last_u, rtol=1e-4, atol=1e-4)
        step = jnp.array([3, 4], jnp.int32)
        logits_u, _ = b_unroll.decode(params_u, step, cache_u)
        logits_s, _ = b_scan.decode(params_s, step, cache_s)
        np.testing.assert_allclose(logits_s, logits_u, rtol=1e-4, atol=1e-4)

    def test_decode_matches_forward(self, setup):
        b, params, tokens = setup
        full = b.apply(params, tokens)
        cache = b.init_cache(batch=2, max_len=32)
        seq_lens = jnp.array([8, 8], jnp.int32)
        last, cache = b.prefill(params, tokens[:, :8], seq_lens, cache)
        np.testing.assert_allclose(last, full[:, 7], rtol=1e-3, atol=1e-3)
        # feed the true next tokens; decode logits must match the dense forward
        for t in range(8, 12):
            logits, cache = b.decode(params, tokens[:, t], cache)
            np.testing.assert_allclose(logits, full[:, t], rtol=1e-3, atol=1e-3)
        assert np.asarray(cache["length"]).tolist() == [12, 12]


def test_moe_ffn_matches_naive_routing():
    """GShard one-hot dispatch must equal naive per-token top-k routing when
    capacity is ample (no drops), and the full MoE forward must be finite."""
    import jax

    from clearml_serving_tpu import models

    cfg = {
        "preset": "llama-tiny", "dtype": "float32",
        "n_experts": 4, "moe_top_k": 2, "moe_capacity_factor": 4.0,
    }
    bundle = models.build_model("llama", cfg)
    params = bundle.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 512)
    out = np.asarray(bundle.apply(params, tokens))
    assert out.shape == (2, 8, 512)
    assert np.all(np.isfinite(out))

    layer = params["layers"][0]
    x = np.asarray(jax.random.normal(jax.random.PRNGKey(2), (1, 6, 64)), np.float32)
    got = np.asarray(bundle.ffn(layer, x)).reshape(-1, 64)

    flat = x.reshape(-1, 64)
    router = flat @ np.asarray(layer["w_router"])
    probs = np.exp(router - router.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    expected = np.zeros_like(flat)
    for t in range(flat.shape[0]):
        top = np.argsort(probs[t])[::-1][:2]
        weights = probs[t][top] / probs[t][top].sum()
        for w_i, e in zip(weights, top):
            h = flat[t] @ np.asarray(layer["w_gate_e"])[e]
            h = h / (1.0 + np.exp(-h)) * (flat[t] @ np.asarray(layer["w_up_e"])[e])
            expected[t] += w_i * (h @ np.asarray(layer["w_down_e"])[e])
    np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-4)


def test_moe_generation_through_engine():
    """A MoE llama serves through the continuous-batching engine."""
    import asyncio

    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

    bundle = models.build_model(
        "llama",
        {"preset": "llama-tiny", "dtype": "float32", "n_experts": 4},
    )
    params = bundle.init(jax.random.PRNGKey(0))
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64,
        prefill_buckets=[16], eos_token_id=257,
    )

    async def run():
        out = []
        async for t in engine.generate(
            GenRequest(prompt_ids=[256, 1, 2, 3], max_new_tokens=4)
        ):
            out.append(t)
        return out

    out = asyncio.run(run())
    assert len(out) >= 1


def test_moe_int8_quantization_covers_expert_stacks():
    """quantize_llama_params must quantize the expert stacks (the bulk of a
    MoE model), and the quantized model must still generate."""
    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.ops.quant import quantize_llama_params

    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32", "n_experts": 4}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    qparams = quantize_llama_params(params)
    layer = qparams["layers"][0]
    for key in ("w_gate_e", "w_up_e", "w_down_e"):
        assert "_q8" in layer[key], key
        assert layer[key]["_q8"].dtype == np.int8 or str(layer[key]["_q8"].dtype) == "int8"
    assert "_q8" not in layer["w_router"] if isinstance(layer["w_router"], dict) else True
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, 512)
    out_q = np.asarray(bundle.apply(qparams, tokens))
    out_f = np.asarray(bundle.apply(params, tokens))
    assert np.all(np.isfinite(out_q))
    # int8 is approximate but must track the full-precision logits closely
    assert np.mean(np.abs(out_q - out_f)) < 0.5


def test_verify_matches_sequential_decode():
    """Speculative verification: one verify() pass over S positions must
    produce exactly the logits of S sequential decode() steps, leave
    `length` untouched, and support partial-acceptance rollback (decoding
    after length advance by fewer than S positions matches a sequential
    cache)."""
    import numpy as np

    for scan in (False, True):
        bundle = models.build_model(
            "llama", {"preset": "llama-tiny", "dtype": "float32", "scan_layers": scan}
        )
        params = bundle.init(jax.random.PRNGKey(0))
        cache = bundle.init_cache(2, 64)
        prompt = jnp.asarray([[256, 5, 9, 0], [256, 7, 0, 0]], jnp.int32)
        _, cache = bundle.prefill(
            params, prompt, jnp.asarray([3, 2], jnp.int32), cache
        )
        tokens = jnp.asarray([[11, 3, 4, 5], [13, 6, 7, 8]], jnp.int32)
        vlogits, vcache = bundle.verify(params, tokens, cache)
        assert np.array_equal(
            np.asarray(vcache["length"]), np.asarray(cache["length"])
        )
        c, ref = cache, []
        for i in range(4):
            lg, c = bundle.decode(params, tokens[:, i], c)
            ref.append(np.asarray(lg))
        np.testing.assert_allclose(
            np.asarray(vlogits), np.stack(ref, axis=1), rtol=2e-4, atol=2e-4
        )
        # accept 1 draft (2 new tokens in cache) then decode: must equal a
        # cache built by sequential decodes of the same two tokens
        vc = dict(vcache)
        vc["length"] = cache["length"] + 2
        nxt = jnp.asarray([3, 6], jnp.int32)
        lg_spec, _ = bundle.decode(params, nxt, vc)
        c2 = cache
        _, c2 = bundle.decode(params, tokens[:, 0], c2)
        _, c2 = bundle.decode(params, tokens[:, 1], c2)
        lg_ref, _ = bundle.decode(params, nxt, c2)
        np.testing.assert_allclose(
            np.asarray(lg_spec), np.asarray(lg_ref), rtol=2e-4, atol=2e-4
        )
