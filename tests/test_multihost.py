"""Multi-host broadcast dispatch: 2-process CPU jax.distributed proof
(VERDICT r1 #6 done-criterion).

Two real processes form a jax.distributed job (1 CPU device each, global
device set of 2). Host 0 drives HostZeroDispatcher; host 1 sits in
follower_loop. The dispatched computation is jitted over the GLOBAL mesh with
the weight sharded across the two processes, so the matmul's reduction runs a
genuine cross-host psum — if the follower failed to enter the same
executable, the test would deadlock (and time out), not just mismatch.

The worker script forces the CPU platform via jax.config (never via a
JAX_PLATFORMS env var, which hangs this image's sitecustomize at interpreter
startup — see .claude/skills/verify/SKILL.md).
"""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

REPO = str(Path(__file__).resolve().parent.parent)

WORKER = r"""
import sys

sys.path.insert(0, {repo!r})
import jax

jax.config.update("jax_platforms", "cpu")
try:
    # jax >= 0.4.x with the explicit knob; absent it the stripped-env
    # default is already ONE cpu device (the parent removed conftest's
    # XLA_FLAGS), which is exactly what each worker wants
    jax.config.update("jax_num_cpu_devices", 1)
except AttributeError:
    pass
jax.config.update("jax_cpu_collectives_implementation", "gloo")

coordinator, pid = sys.argv[1], int(sys.argv[2])
jax.distributed.initialize(coordinator, num_processes=2, process_id=pid)
assert jax.process_count() == 2
assert len(jax.devices()) == 2

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from clearml_serving_tpu.parallel import multihost

mesh = Mesh(np.array(jax.devices()), ("tp",))
rng = np.random.RandomState(0)
w_full = rng.rand(4, 6).astype(np.float32)

# shard W's reduction dim across the two processes: each provides its half
w_sharding = NamedSharding(mesh, P("tp", None))
local_rows = w_full[pid * 2 : (pid + 1) * 2]
w_global = jax.make_array_from_process_local_data(w_sharding, local_rows)

rep = NamedSharding(mesh, P())


@jax.jit
def matmul(w, x):
    # reduction over the sharded axis => cross-host psum inserted by GSPMD
    return jax.numpy.einsum("io,i->o", w, x)


def run_step(inputs):
    x = jax.make_array_from_process_local_data(rep, np.asarray(inputs, np.float32))
    out = matmul(w_global, x)
    return np.asarray(jax.device_get(out))


if pid == 0:
    dispatcher = multihost.HostZeroDispatcher()
    for i in range(3):
        x = np.arange(4, dtype=np.float32) + i
        got = dispatcher.run("step", run_step, x)
        expected = w_full.T @ x
        np.testing.assert_allclose(got, expected, rtol=1e-5)
    dispatcher.stop()
    print("HOST0-OK")
else:
    executed = []

    def resolve(key):
        assert key == "step"
        return lambda inputs: executed.append(run_step(inputs))

    multihost.follower_loop(resolve)
    assert len(executed) == 3, executed
    print("FOLLOWER-OK ran={{}}".format(len(executed)))
"""


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_broadcast_dispatch(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER.format(repo=REPO))
    coordinator = "127.0.0.1:{}".format(_free_port())
    # strip JAX_PLATFORMS (inheriting it hangs the child's sitecustomize) and
    # conftest's XLA_FLAGS (its 8 virtual host devices would skew the global
    # device set; the worker pins jax_num_cpu_devices itself)
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coordinator, str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multi-host dispatch deadlocked:\n{}".format(outs))
    assert procs[0].returncode == 0, outs[0]
    assert procs[1].returncode == 0, outs[1]
    assert "HOST0-OK" in outs[0]
    assert "FOLLOWER-OK ran=3" in outs[1]


ENGINE_WORKER = r"""
import asyncio
import sys

sys.path.insert(0, {repo!r})
import jax

jax.config.update("jax_platforms", "cpu")
try:
    # jax >= 0.4.x with the explicit knob; absent it the stripped-env
    # default is already ONE cpu device (the parent removed conftest's
    # XLA_FLAGS), which is exactly what each worker wants
    jax.config.update("jax_num_cpu_devices", 1)
except AttributeError:
    pass
jax.config.update("jax_cpu_collectives_implementation", "gloo")

coordinator, pid, state_root, service_id = (
    sys.argv[1], int(sys.argv[2]), sys.argv[3], sys.argv[4]
)
jax.distributed.initialize(coordinator, num_processes=2, process_id=pid)

import numpy as np

from clearml_serving_tpu.engine_server.repo import EngineModelRepo
from clearml_serving_tpu.serving.model_request_processor import ModelRequestProcessor

if pid == 0:
    from clearml_serving_tpu.parallel.multihost import HostZeroDispatcher

    dispatcher = HostZeroDispatcher()
    processor = ModelRequestProcessor(service_id=service_id, state_root=state_root)
    repo = EngineModelRepo(processor, dispatcher=dispatcher)
    assert repo.sync() == 1

    async def drive():
        model = repo.get("grpc_mlp")
        out = await model.batcher.infer([np.ones((2, 4), np.float32)])
        return out

    out = asyncio.run(drive())
    assert out[0].shape == (2, 3), out[0].shape
    dispatcher.stop()
    print("HOST0-ENGINE-OK")
else:
    import os

    os.environ["TPUSERVE_STATE_ROOT"] = state_root
    os.environ["TPUSERVE_SERVICE_ID"] = service_id
    from clearml_serving_tpu.engine_server.server import serve_follower

    serve_follower(service_id)
    print("FOLLOWER-ENGINE-OK")
"""


def test_engine_server_follower_replay(tmp_path):
    """serve_follower end-to-end: a follower process syncs the same repo
    from the shared control plane and replays host-0's batcher dispatches
    until STOP (the r1 refusal at server.py:176-183 is gone)."""
    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.engines.jax_engine import save_bundle
    from clearml_serving_tpu.serving.endpoints import ModelEndpoint
    from clearml_serving_tpu.serving.model_request_processor import (
        ModelRequestProcessor,
    )

    state_root = tmp_path / "state"
    mrp = ModelRequestProcessor(state_root=str(state_root), force_create=True, name="mh")
    bundle = models.build_model("mlp", {"in_dim": 4, "hidden": [8], "out_dim": 3})
    params = bundle.init(jax.random.PRNGKey(0))
    bdir = tmp_path / "bundle"
    save_bundle(bdir, "mlp", {"in_dim": 4, "hidden": [8], "out_dim": 3}, params)
    rec = mrp.registry.register("mlp", path=bdir, framework="jax")
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="jax_grpc",
            serving_url="grpc_mlp",
            model_id=rec.id,
            input_name="features",
            input_type="float32",
            input_size=[4],
            output_type="float32",
            output_name="logits",
        )
    )
    mrp.serialize()

    script = tmp_path / "engine_worker.py"
    script.write_text(ENGINE_WORKER.format(repo=REPO))
    coordinator = "127.0.0.1:{}".format(_free_port())
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coordinator, str(pid), str(state_root),
             mrp.get_id()],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("engine follower replay deadlocked:\n{}".format(outs))
    assert procs[0].returncode == 0, outs[0]
    assert procs[1].returncode == 0, outs[1]
    assert "HOST0-ENGINE-OK" in outs[0]
    assert "FOLLOWER-ENGINE-OK" in outs[1]


# -- broadcast op-code closed world (docs/static_analysis.md TPU8xx era) ------


def test_broadcast_op_registry_is_closed():
    """recv() validates every header op against the declared _OP_NAMES
    registry: an op this build cannot name (version skew between host 0
    and a follower) raises UnknownBroadcastOp instead of silently
    desyncing the follower loop."""
    from clearml_serving_tpu.parallel import multihost

    declared = {
        multihost.OP_NOOP: "noop",
        multihost.OP_RUN: "run",
        multihost.OP_STOP: "stop",
    }
    assert multihost._OP_NAMES == declared
    for op in declared:
        assert multihost._check_op(op) == op
    with pytest.raises(multihost.UnknownBroadcastOp) as exc:
        multihost._check_op(3)
    assert "version skew" in str(exc.value)


# -- 2-process sharding-sentry smoke (docs/static_analysis.md TPU8xx) ---------

SENTRY_WORKER = r"""
import os
import sys

sys.path.insert(0, {repo!r})
os.environ["TPUSERVE_SHARD_SENTRY"] = "1"  # count mode (never JAX_PLATFORMS)
import jax

jax.config.update("jax_platforms", "cpu")
try:
    # jax >= 0.4.x with the explicit knob; absent it the stripped-env
    # default is already ONE cpu device (the parent removed conftest's
    # XLA_FLAGS), which is exactly what each worker wants
    jax.config.update("jax_num_cpu_devices", 1)
except AttributeError:
    pass
jax.config.update("jax_cpu_collectives_implementation", "gloo")

coordinator, pid = sys.argv[1], int(sys.argv[2])
jax.distributed.initialize(coordinator, num_processes=2, process_id=pid)
assert jax.process_count() == 2

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from clearml_serving_tpu.llm import sharding_sentry

sentry = sharding_sentry.arm(strict=False)
mesh = Mesh(np.array(jax.devices()), ("tp",))
w_sharding = NamedSharding(mesh, P("tp", None))
local_rows = np.full((2, 4), pid + 1, np.float32)
w = jax.make_array_from_process_local_data(w_sharding, local_rows)
rep = NamedSharding(mesh, P())
x = jax.make_array_from_process_local_data(rep, np.ones(4, np.float32))


@jax.jit
def step(w, x):
    # reduction over the sharded axis => cross-host psum; w flows through
    # unchanged so its P('tp', None) layout must survive every rebind
    return w * 1.0, jax.numpy.einsum("io,i->o", w, x)


for i in range(3):
    w, out = step(w, x)
    sentry.audit(
        [("mh.w", w, None), ("mh.out", out, None)],
        where="step%d" % i,
    )
    # per-host readback through addressable_shards: the TPU803-safe form
    # (np.asarray on the GLOBAL w would cross-host gather)
    local_view = np.asarray(w.addressable_shards[0].data)
    assert local_view.shape == (2, 4)

stats = sentry.stats()
assert stats["audits"] == 3, stats
assert stats["arrays_checked"] == 6, stats
print("SENTRY-OK transfers={{}} reshards={{}}".format(
    stats["implicit_transfers"], stats["unplanned_reshards"]
))
"""


def test_two_process_sharding_sentry_smoke(tmp_path):
    """The sentry audits genuinely process-spanning arrays: each worker
    arms count mode, runs 3 jitted steps over a weight sharded across the
    two processes, audits the rebound outputs against the first-step
    baseline, and reads its local shard back through addressable_shards —
    zero implicit transfers, zero reshards, on both hosts."""
    script = tmp_path / "sentry_worker.py"
    script.write_text(SENTRY_WORKER.format(repo=REPO))
    coordinator = "127.0.0.1:{}".format(_free_port())
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("JAX_PLATFORMS", "XLA_FLAGS")
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coordinator, str(pid)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("sharding-sentry smoke deadlocked:\n{}".format(outs))
    for pid in (0, 1):
        assert procs[pid].returncode == 0, outs[pid]
        assert "SENTRY-OK transfers=0 reshards=0" in outs[pid], outs[pid]
