import threading

import pytest

from clearml_serving_tpu.native import NativeHistogram, NativeQueue, load_native

pytestmark = pytest.mark.skipif(
    load_native() is None, reason="native library unavailable (no toolchain)"
)


def test_queue_roundtrip():
    q = NativeQueue(capacity=16, cell_bytes=64)
    assert q.pop() is None
    assert q.push(b"hello")
    assert q.push(b"world")
    assert len(q) == 2
    assert q.pop() == b"hello"
    assert q.pop() == b"world"
    assert q.pop() is None


def test_queue_oversize_and_full():
    q = NativeQueue(capacity=4, cell_bytes=8)
    assert not q.push(b"x" * 9)  # oversized
    for i in range(4):
        assert q.push(bytes([i]))
    assert not q.push(b"full")   # ring full -> rejected
    assert q.rejected >= 1
    assert q.pop_all() == [bytes([i]) for i in range(4)]


def test_queue_concurrent_producers():
    q = NativeQueue(capacity=8192, cell_bytes=32)
    n_threads, per_thread = 4, 2000
    received = []

    def producer(tid):
        for i in range(per_thread):
            while not q.push("{}:{}".format(tid, i).encode()):
                pass

    consumer_done = threading.Event()

    def consumer():
        while len(received) < n_threads * per_thread:
            item = q.pop()
            if item is not None:
                received.append(item)
        consumer_done.set()

    threads = [threading.Thread(target=producer, args=(t,)) for t in range(n_threads)]
    ct = threading.Thread(target=consumer)
    ct.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ct.join(timeout=30)
    assert consumer_done.is_set()
    assert len(received) == n_threads * per_thread
    # per-producer FIFO order is preserved
    for tid in range(n_threads):
        seq = [int(r.split(b":")[1]) for r in received if r.startswith(str(tid).encode())]
        assert seq == sorted(seq)


def test_histogram():
    h = NativeHistogram()
    h.observe_seconds(0.003)
    h.observe_seconds(0.05)
    h.observe_seconds(10.0)  # beyond last bound -> +inf bucket
    snap = h.snapshot()
    assert snap["total"] == 3
    assert sum(snap["counts"]) == 3
    assert snap["counts"][-1] == 1
    assert snap["total_us"] >= int(10.0e6)


def test_stats_queue_uses_native(state_root, monkeypatch):
    from clearml_serving_tpu.serving.model_request_processor import FastSimpleQueue

    monkeypatch.setenv("TPUSERVE_NATIVE_QUEUE", "1")
    q = FastSimpleQueue()
    assert q._native is not None
    q.put({"_url": "e", "_latency": 0.1})
    q.put({"not-json": object()})  # non-serializable -> deque fallback
    out = q.get_all(timeout=0.05)
    assert {"_url": "e", "_latency": 0.1} in out
    assert len(out) == 2
