"""ONNX / TorchScript import fidelity: converted JAX functions must match
torch outputs on the same weights (the reference gets this breadth from
Triton's onnxruntime/libtorch backends; we convert instead)."""

import asyncio

import jax
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402

from clearml_serving_tpu.engines.importers.onnx_import import load_onnx_bundle
from clearml_serving_tpu.engines.importers.torchscript_import import (
    export_torch_to_onnx_bytes,
    load_torchscript_bundle,
)


def _export(module, args, path, dynamic_batch=True):
    data = export_torch_to_onnx_bytes(
        module, [list(a.shape) for a in args]
    )
    path.write_bytes(data)
    return path


def _check_fidelity(module, args, tmp_path, rtol=1e-4, atol=1e-5):
    module.eval()
    f = tmp_path / "m.onnx"
    f.write_bytes(export_torch_to_onnx_bytes(module, [list(a.shape) for a in args]))
    bundle, params = load_onnx_bundle(f)
    with torch.no_grad():
        expected = module(*args)
    got = jax.jit(bundle.apply)(params, *[a.numpy() for a in args])
    np.testing.assert_allclose(
        np.asarray(got), expected.numpy(), rtol=rtol, atol=atol
    )
    return bundle


def test_mlp_onnx_fidelity(tmp_path):
    torch.manual_seed(0)
    m = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 16), nn.Tanh(), nn.Linear(16, 3))
    x = torch.randn(5, 8)
    _check_fidelity(m, (x,), tmp_path)


def test_cnn_onnx_fidelity(tmp_path):
    torch.manual_seed(1)

    class CNN(nn.Module):
        def __init__(self):
            super().__init__()
            self.c1 = nn.Conv2d(1, 8, 3, padding=1)
            self.c2 = nn.Conv2d(8, 16, 3, stride=2)
            self.fc = nn.Linear(16 * 6 * 6, 10)

        def forward(self, x):
            x = torch.relu(self.c1(x))
            x = torch.max_pool2d(torch.relu(self.c2(x)), 2, ceil_mode=False)
            x = torch.flatten(x, 1)
            return torch.log_softmax(self.fc(x), dim=-1)

    x = torch.randn(2, 1, 28, 28)
    _check_fidelity(CNN(), (x,), tmp_path)


def test_cnn_onnx_dynamic_batch(tmp_path):
    """The exported graph must serve batch sizes other than the example's."""
    torch.manual_seed(2)
    m = nn.Sequential(nn.Conv2d(1, 4, 3), nn.ReLU(), nn.Flatten(), nn.Linear(4 * 26 * 26, 5))
    m.eval()
    f = tmp_path / "m.onnx"
    f.write_bytes(export_torch_to_onnx_bytes(m, [[1, 1, 28, 28]]))
    bundle, params = load_onnx_bundle(f)
    for batch in (1, 3, 7):
        x = torch.randn(batch, 1, 28, 28)
        with torch.no_grad():
            expected = m(x)
        got = jax.jit(bundle.apply)(params, x.numpy())
        np.testing.assert_allclose(np.asarray(got), expected.numpy(), rtol=1e-4, atol=1e-5)


def test_hf_bert_onnx_fidelity(tmp_path):
    """A real transformers BERT encoder (random weights) through the
    converter: exercises LayerNorm-decomposition, Erf-GELU, Softmax,
    attention-mask Where chains, Gather embeddings, Slice/Concat shape
    metaprograms — the BASELINE bert acceptance config's op diet."""
    transformers = pytest.importorskip("transformers")

    cfg = transformers.BertConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64, max_position_embeddings=64,
    )
    torch.manual_seed(3)

    class Wrapped(nn.Module):
        def __init__(self):
            super().__init__()
            self.bert = transformers.BertModel(cfg)

        def forward(self, input_ids, attention_mask):
            return self.bert(
                input_ids=input_ids, attention_mask=attention_mask
            ).last_hidden_state

    m = Wrapped()
    m.eval()
    ids = torch.randint(0, 128, (2, 12))
    mask = torch.ones(2, 12, dtype=torch.int64)
    f = tmp_path / "bert.onnx"
    data = export_torch_to_onnx_bytes(
        m, [[2, 12], [2, 12]], example_dtypes=["int64", "int64"]
    )
    f.write_bytes(data)
    bundle, params = load_onnx_bundle(f)
    with torch.no_grad():
        expected = m(ids, mask)
    got = jax.jit(bundle.apply)(params, ids.numpy(), mask.numpy())
    np.testing.assert_allclose(
        np.asarray(got), expected.numpy(), rtol=1e-3, atol=1e-4
    )


def test_torchscript_bundle(tmp_path):
    torch.manual_seed(4)
    m = nn.Sequential(nn.Linear(6, 12), nn.ReLU(), nn.Linear(12, 4))
    m.eval()
    scripted = torch.jit.script(m)
    pt = tmp_path / "model.pt"
    scripted.save(str(pt))
    bundle, params = load_torchscript_bundle(pt, [[1, 6]])
    x = torch.randn(3, 6)
    with torch.no_grad():
        expected = m(x)
    got = jax.jit(bundle.apply)(params, x.numpy())
    np.testing.assert_allclose(np.asarray(got), expected.numpy(), rtol=1e-4, atol=1e-5)
    assert bundle.config["arch"] == "torchscript"


def test_onnx_served_through_router(tmp_path, state_root):
    """A stock .onnx file registered as a model serves through the jax
    engine end-to-end (VERDICT r1 #3 done-criterion)."""
    from clearml_serving_tpu.serving.endpoints import ModelEndpoint
    from clearml_serving_tpu.serving.model_request_processor import (
        ModelRequestProcessor,
    )

    torch.manual_seed(5)
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    m.eval()
    f = tmp_path / "model.onnx"
    f.write_bytes(export_torch_to_onnx_bytes(m, [[1, 4]]))

    mrp = ModelRequestProcessor(state_root=str(state_root), force_create=True, name="onnx")
    rec = mrp.registry.register("onnx_mlp", path=f, framework="onnx")
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="jax",
            serving_url="onnx_ep",
            model_id=rec.id,
            input_name="x",
            input_type="float32",
            input_size=[4],
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    x = np.random.rand(2, 4).astype(np.float32)
    out = asyncio.run(mrp.process_request("onnx_ep", None, {"x": x.tolist()}))
    with torch.no_grad():
        expected = m(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-4, atol=1e-5)


def test_unsupported_op_fails_loudly(tmp_path):
    """Unknown ops must raise by name at conversion, not at runtime."""

    class Weird(nn.Module):
        def forward(self, x):
            return torch.det(x)  # Det: not in the supported set

    m = Weird()
    m.eval()
    f = tmp_path / "weird.onnx"
    f.write_bytes(export_torch_to_onnx_bytes(m, [[1, 3, 3]]))
    with pytest.raises(ValueError, match="unsupported op"):
        load_onnx_bundle(f)


def test_pytorch_example_end_to_end(tmp_path, state_root, monkeypatch):
    """The examples/pytorch walkthrough: train -> TorchScript -> register ->
    serve with the example's Preprocess (reference examples/pytorch parity)."""
    import importlib.util
    import sys

    spec = importlib.util.spec_from_file_location(
        "pt_train", "examples/pytorch/train_model.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    torch.manual_seed(0)
    model = mod.Net()
    model.eval()
    pt = tmp_path / "pytorch-mnist.pt"
    torch.jit.script(model).save(str(pt))

    from clearml_serving_tpu.serving.endpoints import ModelEndpoint
    from clearml_serving_tpu.serving.model_request_processor import (
        ModelRequestProcessor,
    )

    mrp = ModelRequestProcessor(state_root=str(state_root), force_create=True, name="pt")
    rec = mrp.registry.register("train pytorch model", path=pt, framework="pytorch")
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="jax",
            serving_url="test_model_pytorch",
            model_id=rec.id,
            input_name="input_0",
            input_type="float32",
            input_size=[1, 28, 28],
        ),
        preprocess_code="examples/pytorch/preprocess.py",
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    image = np.zeros((28, 28), np.float32)
    image[3:11, 8:20] = 1.0
    out = asyncio.run(
        mrp.process_request("test_model_pytorch", None, {"image": image.tolist()})
    )
    assert set(out) == {"digit"} and 0 <= out["digit"] <= 9
    # fidelity vs torch on the same input
    with torch.no_grad():
        expected = int(
            model(torch.from_numpy(image)[None, None]).argmax(dim=-1)[0]
        )
    assert out["digit"] == expected


def test_maxpool_ceil_mode(tmp_path):
    """ceil_mode=1 graphs must match torch exactly (review r2 finding)."""
    torch.manual_seed(6)

    class M(nn.Module):
        def forward(self, x):
            return torch.max_pool2d(x, 2, ceil_mode=True)

    m = M()
    m.eval()
    x = torch.randn(1, 3, 27, 27)  # odd dims: ceil 14 vs floor 13
    _check_fidelity(m, (x,), tmp_path)


def test_fp16_int32_data_bit_reinterpretation():
    """FLOAT16 typed storage holds uint16 bit patterns in int32_data; a
    numeric cast would turn fp16 1.0 (0x3C00=15360) into 15360.0."""
    from clearml_serving_tpu.engines.importers.onnx_proto import tensor_to_numpy

    t = {"dims": [2], "data_type": 10, "int32_data": [15360, 16384]}  # 1.0, 2.0
    arr = tensor_to_numpy(t)
    assert arr.dtype == np.float16
    np.testing.assert_array_equal(arr.astype(np.float32), [1.0, 2.0])
