"""OpenAI request-parameter parity at the route level: stop strings,
n choices, logprobs, penalties/seed passthrough (llm/openai_api.py)."""

import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from clearml_serving_tpu.serving.endpoints import ModelEndpoint
from clearml_serving_tpu.serving.main import build_app
from clearml_serving_tpu.serving.model_request_processor import ModelRequestProcessor


@pytest.fixture(scope="module")
def llm_served(tmp_path_factory):
    import os

    root = tmp_path_factory.mktemp("state")
    os.environ["TPUSERVE_STATE_ROOT"] = str(root)
    mrp = ModelRequestProcessor(state_root=str(root), force_create=True, name="llmp")
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="tiny_llm",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 4,
                    "max_seq_len": 128,
                    "prefill_buckets": [32],
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    return mrp


def _run(mrp, fn):
    async def runner():
        client = TestClient(TestServer(build_app(mrp)))
        await client.start_server()
        try:
            return await fn(client)
        finally:
            await client.close()

    return asyncio.run(runner())


def _chat_body(**kw):
    body = {
        "model": "tiny_llm",
        "messages": [{"role": "user", "content": "hello"}],
        "max_tokens": 8,
    }
    body.update(kw)
    return body


def test_n_choices(llm_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(n=3, temperature=1.0, seed=5),
        )
        assert r.status == 200, await r.text()
        return await r.json()

    out = _run(llm_served, fn)
    assert len(out["choices"]) == 3
    assert [c["index"] for c in out["choices"]] == [0, 1, 2]
    # seeded choices offset per index -> not all identical (vocab 512,
    # temperature 1: three identical 8-token outputs would be astronomical)
    texts = [c["message"]["content"] for c in out["choices"]]
    assert len(set(texts)) > 1
    assert out["usage"]["completion_tokens"] == sum(
        1 for c in texts for _ in c
    ) or out["usage"]["completion_tokens"] > 0


def test_chat_logprobs(llm_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(logprobs=True, top_logprobs=3, max_tokens=4),
        )
        assert r.status == 200, await r.text()
        return await r.json()

    out = _run(llm_served, fn)
    lp = out["choices"][0]["logprobs"]
    assert lp is not None and "content" in lp
    assert len(lp["content"]) >= 1
    entry = lp["content"][0]
    assert set(entry) == {"token", "logprob", "bytes", "top_logprobs"}
    assert len(entry["top_logprobs"]) == 3
    assert entry["logprob"] <= 0.0
    # top alternatives are sorted descending
    tops = [t["logprob"] for t in entry["top_logprobs"]]
    assert tops == sorted(tops, reverse=True)


def test_completions_logprobs_and_offsets(llm_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={
                "model": "tiny_llm",
                "prompt": "abc",
                "max_tokens": 4,
                "logprobs": 2,
            },
        )
        assert r.status == 200, await r.text()
        return await r.json()

    out = _run(llm_served, fn)
    lp = out["choices"][0]["logprobs"]
    assert lp is not None
    assert len(lp["tokens"]) == len(lp["token_logprobs"]) == len(lp["text_offset"])
    assert all(len(d) <= 2 for d in lp["top_logprobs"])
    # text offsets are cumulative over the decoded tokens
    assert lp["text_offset"][0] == 0
    for i in range(1, len(lp["tokens"])):
        assert lp["text_offset"][i] == lp["text_offset"][i - 1] + len(
            lp["tokens"][i - 1]
        )


# logit_bias {42:+200, 43:+100} with presence_penalty 150 forces the exact
# byte sequence 42,43,42,42,... ("*+***" under the byte tokenizer): after
# the first '*' its logit drops to 50 so '+' (100) wins, then both are
# penalized (50 vs -50) and '*' repeats. Deterministic text to stop on.
_FORCED = {"logit_bias": {"42": 200.0, "43": 100.0}, "presence_penalty": 150.0}


def test_stop_string_truncates(llm_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(max_tokens=8, **_FORCED),
        )
        base = (await r.json())["choices"][0]["message"]["content"]
        r2 = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(max_tokens=8, stop="**", **_FORCED),
        )
        assert r2.status == 200, await r2.text()
        return base, await r2.json()

    base, out = _run(llm_served, fn)
    assert base.startswith("*+**")
    text = out["choices"][0]["message"]["content"]
    assert text == "*+"  # truncated before the first "**" occurrence
    assert out["choices"][0]["finish_reason"] == "stop"


def test_stop_string_streaming(llm_served):
    async def fn(client):
        r2 = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(max_tokens=8, stop=["**"], stream=True, **_FORCED),
        )
        assert r2.status == 200
        return (await r2.read()).decode()

    raw = _run(llm_served, fn)
    import json as _json

    pieces = []
    finish = None
    for line in raw.splitlines():
        if not line.startswith("data: ") or line == "data: [DONE]":
            continue
        chunk = _json.loads(line[6:])
        for ch in chunk.get("choices", []):
            delta = ch.get("delta", {})
            if "content" in delta:
                pieces.append(delta["content"])
            if ch.get("finish_reason"):
                finish = ch["finish_reason"]
    assert "".join(pieces) == "*+"
    assert finish == "stop"


def test_streaming_emits_logprobs(llm_served):
    """OpenAI streaming parity: SSE chunks carry logprobs.content entries
    covering every emitted token."""

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(
                max_tokens=4, stream=True, logprobs=True, top_logprobs=2
            ),
        )
        assert r.status == 200
        return (await r.read()).decode()

    raw = _run(llm_served, fn)
    import json as _json

    entries = []
    for line in raw.splitlines():
        if not line.startswith("data: ") or line == "data: [DONE]":
            continue
        chunk = _json.loads(line[6:])
        for ch in chunk.get("choices", []):
            lp = ch.get("logprobs")
            if lp:
                entries.extend(lp["content"])
    assert len(entries) >= 1
    for e in entries:
        assert e["logprob"] <= 0.0
        assert len(e["top_logprobs"]) == 2


def test_stop_with_logprobs_is_consistent(llm_served):
    """Stop truncation trims logprob entries and usage to the returned text."""

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(
                max_tokens=8, stop="**", logprobs=True, top_logprobs=1,
                **_FORCED,
            ),
        )
        assert r.status == 200, await r.text()
        return await r.json()

    out = _run(llm_served, fn)
    choice = out["choices"][0]
    assert choice["message"]["content"] == "*+"
    toks = [e["token"] for e in choice["logprobs"]["content"]]
    assert toks == ["*", "+"]  # no phantom stop-sequence tokens
    assert out["usage"]["completion_tokens"] == 2


def test_streaming_accepts_multi_choice(llm_served):
    """Plain chat n>1 streaming is supported (r5); tools still require a
    single choice (covered below)."""

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(n=2, stream=True),
        )
        return r.status

    assert _run(llm_served, fn) == 200


def test_penalties_and_seed_passthrough(llm_served):
    """Seeded sampled requests reproduce through the HTTP surface."""

    async def fn(client):
        body = _chat_body(temperature=1.0, seed=42, max_tokens=6)
        r1 = await client.post("/serve/openai/v1/chat/completions", json=body)
        r2 = await client.post("/serve/openai/v1/chat/completions", json=body)
        return (await r1.json()), (await r2.json())

    a, b = _run(llm_served, fn)
    assert (
        a["choices"][0]["message"]["content"]
        == b["choices"][0]["message"]["content"]
    )


def test_bad_logit_bias_is_422(llm_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(logit_bias={"999999": 5}),
        )
        return r.status

    assert _run(llm_served, fn) == 422

def test_response_role_and_usage_stream_options(llm_served):
    """vLLM chat knobs: response_role renames the assistant role;
    stream_options.include_usage adds usage:null chunks + a final
    choices-less usage chunk (OpenAI stream_options semantics)."""
    import json as _json

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(response_role="bot"),
        )
        non_stream = await r.json()
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(
                stream=True,
                response_role="bot",
                stream_options={"include_usage": True},
            ),
        )
        return non_stream, await r.text()

    non_stream, text = _run(llm_served, fn)
    assert non_stream["choices"][0]["message"]["role"] == "bot"
    lines = [l for l in text.split("\n\n") if l.startswith("data: ")]
    assert lines[-1] == "data: [DONE]"
    chunks = [_json.loads(l[len("data: "):]) for l in lines[:-1]]
    assert chunks[0]["choices"][0]["delta"]["role"] == "bot"
    # every non-final chunk: usage null; final chunk: no choices, real usage
    for c in chunks[:-1]:
        assert c["usage"] is None
    final = chunks[-1]
    assert final["choices"] == []
    assert final["usage"]["completion_tokens"] >= 1
    assert final["usage"]["total_tokens"] == (
        final["usage"]["prompt_tokens"] + final["usage"]["completion_tokens"]
    )


def test_return_tokens_as_token_ids(llm_served):
    """vLLM return_tokens_as_token_ids: logprob token strings become
    "token_id:<id>" in chat and completions shapes."""

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(logprobs=True, top_logprobs=2,
                            return_tokens_as_token_ids=True, max_tokens=4),
        )
        chat = await r.json()
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_llm", "prompt": "ab", "max_tokens": 4,
                  "logprobs": 2, "return_tokens_as_token_ids": True},
        )
        return chat, await r.json()

    chat, comp = _run(llm_served, fn)
    for item in chat["choices"][0]["logprobs"]["content"]:
        assert item["token"].startswith("token_id:")
        int(item["token"].split(":", 1)[1])
        for top in item["top_logprobs"]:
            assert top["token"].startswith("token_id:")
    lp = comp["choices"][0]["logprobs"]
    assert all(t.startswith("token_id:") for t in lp["tokens"])
    assert all(
        k.startswith("token_id:") for d in lp["top_logprobs"] for k in d
    )
    # offsets still track emitted TEXT, not the token_id strings
    assert lp["text_offset"][0] == 0


def test_best_of_returns_top_ranked(llm_served):
    """vLLM `best_of`: 4 candidates generated server-side, top 2 by
    cumulative logprob returned; usage bills ALL candidates; no logprobs
    leak into the reply when the user didn't ask for them."""

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_llm", "prompt": "hi", "max_tokens": 6,
                  "temperature": 1.0, "seed": 3, "n": 2, "best_of": 4},
        )
        assert r.status == 200, await r.text()
        return await r.json()

    out = _run(llm_served, fn)
    assert len(out["choices"]) == 2
    assert [c["index"] for c in out["choices"]] == [0, 1]
    assert all(c["logprobs"] is None for c in out["choices"])
    # all 4 candidates billed (4 x 6 tokens): strictly more than the 2
    # returned choices' worth, so a selected-only billing regression fails
    assert out["usage"]["completion_tokens"] >= 18


def test_best_of_ranking_is_by_cumulative_logprob(llm_served):
    """best_of=3, n=1 with user logprobs on: the returned choice's summed
    token logprobs must be >= every discarded candidate's (verified by
    re-running the same seeds as plain n=3).

    EOS is suppressed via logit_bias so every candidate runs to max_tokens:
    the server ranks by vLLM cumulative_logprob, which INCLUDES the
    finishing token's entry, while the response's token_logprobs exclude a
    terminating EOS — a candidate that stops early would make the two
    metrics diverge (its visible partial sum overstates its cumulative),
    and whether one stops early shifts with the backend's sampling stream."""

    async def fn(client):
        body = {"model": "tiny_llm", "prompt": "go", "max_tokens": 6,
                "temperature": 1.0, "seed": 11, "logprobs": 0,
                "logit_bias": {"257": -100}}  # ByteTokenizer EOS
        best = await client.post(
            "/serve/openai/v1/completions", json=dict(body, n=1, best_of=3))
        assert best.status == 200, await best.text()
        all3 = await client.post(
            "/serve/openai/v1/completions", json=dict(body, n=3))
        assert all3.status == 200, await all3.text()
        return await best.json(), await all3.json()

    best, all3 = _run(llm_served, fn)
    (chosen,) = best["choices"]
    chosen_lp = sum(chosen["logprobs"]["token_logprobs"])
    # seeds offset identically (seed+i per choice), so plain n=3 reproduces
    # the candidate pool; the winner must dominate it
    pool = [
        sum(c["logprobs"]["token_logprobs"]) for c in all3["choices"]
    ]
    assert chosen_lp == pytest.approx(max(pool), abs=1e-3)


def test_best_of_validation(llm_served):
    async def fn(client):
        r1 = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_llm", "prompt": "x", "max_tokens": 4,
                  "n": 3, "best_of": 2},
        )
        r2 = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_llm", "prompt": "x", "max_tokens": 4,
                  "stream": True, "best_of": 2},
        )
        return r1.status, r2.status

    s1, s2 = _run(llm_served, fn)
    assert s1 == 422  # best_of < n
    assert s2 == 422  # best_of with streaming


def test_best_of_with_logprobs_false(llm_served):
    """`logprobs: false` (not just absent) must still rank candidates — the
    parser treats false as logprobs-off, so internal collection has to key
    off the parsed request, not the raw body (r5 code review)."""

    async def fn(client):
        body = {"model": "tiny_llm", "prompt": "go", "max_tokens": 6,
                "temperature": 1.0, "seed": 11, "logprobs": False}
        best = await client.post(
            "/serve/openai/v1/completions", json=dict(body, n=1, best_of=3))
        assert best.status == 200, await best.text()
        ref = await client.post(
            "/serve/openai/v1/completions",
            json=dict(body, n=1, best_of=3, logprobs=0))
        assert ref.status == 200, await ref.text()
        return await best.json(), await ref.json()

    best, ref = _run(llm_served, fn)
    (choice,) = best["choices"]
    assert choice["logprobs"] is None  # user asked for none
    # same seeds -> same candidate pool: the winner must match the
    # logprobs-on run's winner, proving ranking actually happened
    assert choice["text"] == ref["choices"][0]["text"]


def test_echo_prepends_prompt_with_logprobs(llm_served):
    """OpenAI completions `echo`: the prompt text leads the output, and with
    `logprobs` the block starts with prompt-token entries (first one null)
    followed by the generated entries, offsets continuous."""

    async def fn(client):
        base = {"model": "tiny_llm", "prompt": "abc", "max_tokens": 4,
                "logprobs": 1}
        plain = await client.post("/serve/openai/v1/completions", json=base)
        echoed = await client.post(
            "/serve/openai/v1/completions", json=dict(base, echo=True))
        assert plain.status == 200 and echoed.status == 200
        return await plain.json(), await echoed.json()

    plain, echoed = _run(llm_served, fn)
    p_choice, e_choice = plain["choices"][0], echoed["choices"][0]
    assert e_choice["text"].endswith(p_choice["text"])
    assert "abc" in e_choice["text"][: len(e_choice["text"]) - len(p_choice["text"])]
    lp = e_choice["logprobs"]
    n_prompt = len(lp["tokens"]) - len(p_choice["logprobs"]["tokens"])
    assert n_prompt >= 2  # BOS + "abc" bytes
    assert lp["token_logprobs"][0] is None and lp["top_logprobs"][0] is None
    assert all(isinstance(v, float) for v in lp["token_logprobs"][1:])
    # offsets strictly increase across the prompt/generated boundary
    assert lp["text_offset"] == sorted(lp["text_offset"])
    # generated entries identical to the non-echo run's
    assert lp["token_logprobs"][n_prompt:] == pytest.approx(
        p_choice["logprobs"]["token_logprobs"], abs=1e-4
    )


def test_echo_streaming_prompt_first_chunk(llm_served):
    """Streaming echo: the first SSE chunk carries the prompt text."""

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_llm", "prompt": "xyz", "max_tokens": 3,
                  "stream": True, "echo": True},
        )
        assert r.status == 200
        return (await r.read()).decode()

    raw = _run(llm_served, fn)
    import json as _json

    texts = []
    for line in raw.splitlines():
        if not line.startswith("data: ") or line == "data: [DONE]":
            continue
        for ch in _json.loads(line[6:]).get("choices", []):
            if ch.get("text"):
                texts.append(ch["text"])
    assert texts and "xyz" in texts[0]
    assert len(texts) >= 2  # prompt chunk + generated deltas


def test_echo_max_tokens_zero_scores_prompt(llm_served):
    """The canonical OpenAI scoring call — echo + logprobs + max_tokens 0 —
    returns the scored prompt, generates nothing, and bills nothing (a
    falsy-zero must not fall through to the default budget)."""

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_llm", "prompt": "abc", "max_tokens": 0,
                  "echo": True, "logprobs": 1},
        )
        assert r.status == 200, await r.text()
        return await r.json()

    out = _run(llm_served, fn)
    (choice,) = out["choices"]
    assert choice["text"] == "abc" or choice["text"].endswith("abc")
    assert choice["finish_reason"] == "length"
    lp = choice["logprobs"]
    assert len(lp["tokens"]) >= 2
    assert lp["token_logprobs"][0] is None
    assert all(isinstance(v, float) for v in lp["token_logprobs"][1:])
    assert out["usage"]["completion_tokens"] == 0
    assert out["usage"]["total_tokens"] == out["usage"]["prompt_tokens"]


def test_streaming_completions_multi_choice(llm_served):
    """OpenAI n>1 streaming: chunks interleave with per-chunk `index`, each
    choice finishes independently, and accumulating by index reproduces the
    non-streaming choices (same seeds: seed+i per choice)."""
    import json as _json

    async def fn(client):
        body = {"model": "tiny_llm", "prompt": "go", "max_tokens": 6,
                "temperature": 1.0, "seed": 21, "n": 3,
                "stream_options": {"include_usage": True}}
        r = await client.post(
            "/serve/openai/v1/completions", json=dict(body, stream=True))
        assert r.status == 200
        raw = (await r.read()).decode()
        r2 = await client.post("/serve/openai/v1/completions", json=body)
        assert r2.status == 200, await r2.text()
        return raw, await r2.json()

    raw, ref = _run(llm_served, fn)
    texts = {0: "", 1: "", 2: ""}
    finishes = {}
    usage = None
    for line in raw.splitlines():
        if not line.startswith("data: ") or line == "data: [DONE]":
            continue
        chunk = _json.loads(line[6:])
        if chunk.get("usage"):
            usage = chunk["usage"]
        for ch in chunk.get("choices", []):
            texts[ch["index"]] += ch.get("text") or ""
            if ch.get("finish_reason"):
                finishes[ch["index"]] = ch["finish_reason"]
    assert set(finishes) == {0, 1, 2}
    ref_texts = {c["index"]: c["text"] for c in ref["choices"]}
    assert texts == ref_texts
    assert usage is not None and usage["completion_tokens"] == 18


def test_streaming_best_of_must_equal_n(llm_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_llm", "prompt": "x", "max_tokens": 4,
                  "stream": True, "n": 2, "best_of": 4},
        )
        return r.status

    assert _run(llm_served, fn) == 422


def test_streaming_chat_multi_choice(llm_served):
    """Chat n>1 streaming (no tools): role chunk per choice, interleaved
    content deltas by index, independent finishes; accumulation matches the
    non-streaming choices under the same seeds."""
    import json as _json

    async def fn(client):
        body = _chat_body(n=3, temperature=1.0, seed=9, max_tokens=5)
        r = await client.post(
            "/serve/openai/v1/chat/completions", json=dict(body, stream=True))
        assert r.status == 200, await r.text()
        raw = (await r.read()).decode()
        r2 = await client.post("/serve/openai/v1/chat/completions", json=body)
        assert r2.status == 200, await r2.text()
        return raw, await r2.json()

    raw, ref = _run(llm_served, fn)
    texts = {0: "", 1: "", 2: ""}
    roles, finishes = set(), {}
    for line in raw.splitlines():
        if not line.startswith("data: ") or line == "data: [DONE]":
            continue
        for ch in _json.loads(line[6:]).get("choices", []):
            delta = ch.get("delta", {})
            if delta.get("role"):
                roles.add(ch["index"])
            texts[ch["index"]] += delta.get("content") or ""
            if ch.get("finish_reason"):
                finishes[ch["index"]] = ch["finish_reason"]
    assert roles == {0, 1, 2} and set(finishes) == {0, 1, 2}
    assert texts == {
        c["index"]: c["message"]["content"] for c in ref["choices"]
    }


def test_streaming_chat_multi_choice_with_tools_rejected(llm_served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(n=2, stream=True, tools=[{
                "type": "function",
                "function": {"name": "f", "parameters": {"type": "object"}},
            }]),
        )
        return r.status

    assert _run(llm_served, fn) == 422


def test_prompt_logprobs_extension(llm_served):
    """vLLM `prompt_logprobs`: per-prompt-position dicts of token_id ->
    {logprob, rank, decoded_token}, first position None, the actual token
    always present with its exact vocab rank — on completions and chat."""

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_llm", "prompt": "abc", "max_tokens": 2,
                  "prompt_logprobs": 2},
        )
        assert r.status == 200, await r.text()
        rc = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(max_tokens=2, prompt_logprobs=1),
        )
        assert rc.status == 200, await rc.text()
        bad = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_llm", "prompt": "x", "max_tokens": 2,
                  "prompt_logprobs": 10_000},
        )
        return await r.json(), await rc.json(), bad.status

    out, chat, bad_status = _run(llm_served, fn)
    # completions: per-choice; chat: TOP-LEVEL field (vLLM response shapes)
    for payload in (out["choices"][0]["prompt_logprobs"],
                    chat["prompt_logprobs"]):
        assert payload[0] is None and len(payload) >= 2
        for pos in payload[1:]:
            assert isinstance(pos, dict) and pos
            for info in pos.values():
                assert set(info) == {"logprob", "rank", "decoded_token"}
                assert info["rank"] >= 1
            # top-1 entry has rank 1 and the best logprob in the dict
            best = min(info["rank"] for info in pos.values())
            assert best == 1
    assert bad_status == 422  # over the engine top-k ceiling


def test_prompt_logprobs_streaming_rejected_and_zero_gen_supported(llm_served):
    """r5 review: prompt_logprobs + stream must 422 up front (vLLM
    semantics), and the max_tokens=0 scoring call returns them."""

    async def fn(client):
        r1 = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_llm", "prompt": "x", "max_tokens": 2,
                  "stream": True, "prompt_logprobs": 1},
        )
        r2 = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(stream=True, prompt_logprobs=1),
        )
        r3 = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_llm", "prompt": "abc", "max_tokens": 0,
                  "prompt_logprobs": 1},
        )
        assert r3.status == 200, await r3.text()
        return r1.status, r2.status, await r3.json()

    s1, s2, zero = _run(llm_served, fn)
    assert s1 == 422 and s2 == 422
    plp = zero["choices"][0]["prompt_logprobs"]
    assert plp[0] is None and len(plp) >= 2
    assert zero["usage"]["completion_tokens"] == 0


def test_prompt_logprobs_zero_gen_stream_still_rejected(llm_served):
    """The stream rejection must hold even with max_tokens=0 (the zero
    short-circuit cannot bypass it — r5 review)."""

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_llm", "prompt": "x", "max_tokens": 0,
                  "stream": True, "prompt_logprobs": 1},
        )
        return r.status

    assert _run(llm_served, fn) == 422


def test_suffix_rejected(llm_served):
    """vLLM semantics: `suffix` (fill-in-middle) is rejected explicitly —
    silently ignoring it would return a continuation the client believes
    is an infill."""

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_llm", "prompt": "def f(", "max_tokens": 4,
                  "suffix": "return x"},
        )
        return r.status

    assert _run(llm_served, fn) == 422


def test_priority_class_route_level(llm_served):
    """SLO classes (docs/slo_scheduling.md): body `priority` reaches the
    engine (unknown values 422 before streaming), and the endpoint-level
    aux engine.default_priority fills it in when absent."""

    async def fn(client):
        bad = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(priority="vip"),
        )
        ok = await client.post(
            "/serve/openai/v1/chat/completions",
            json=_chat_body(priority="batch", max_tokens=2),
        )
        return bad.status, ok.status

    bad_status, ok_status = _run(llm_served, fn)
    assert bad_status == 422
    assert ok_status == 200

    # endpoint default plumbs through the request builder; an explicit
    # body priority wins over it
    proc = llm_served._engine_processor_lookup["tiny_llm"]
    assert proc._default_priority == "interactive"
    proc._default_priority = "batch"
    try:
        req = proc._gen_request_from_body({"max_tokens": 2}, [1, 2, 3])
        assert req.priority == "batch"
        req = proc._gen_request_from_body(
            {"max_tokens": 2, "priority": "best_effort"}, [1, 2, 3]
        )
        assert req.priority == "best_effort"
    finally:
        proc._default_priority = "interactive"


def test_default_priority_typo_fails_at_endpoint_load(tmp_path):
    """aux engine.default_priority is validated when the endpoint LOADS: a
    typo'd value must fail fast there, not 422 every request that omits an
    explicit body priority."""
    mrp = ModelRequestProcessor(
        state_root=str(tmp_path), force_create=True, name="badprio"
    )
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="bad_prio",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 1,
                    "max_seq_len": 64,
                    "prefill_buckets": [16],
                    "default_priority": "Interactive",  # typo'd case
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "bad_prio", "prompt": [1, 2], "max_tokens": 2},
        )
        return r.status, await r.text()

    status, text = _run(mrp, fn)
    # the router surfaces the load failure with the CONFIG error (naming
    # the knob), and the endpoint never registers — not a per-request 422
    # that would misdirect debugging at the request body
    assert status == 422 and "default_priority" in text, (status, text)
    assert "bad_prio" not in mrp._engine_processor_lookup


def test_warmup_knob_typo_fails_at_endpoint_load(tmp_path):
    """aux engine.warmup (llm/warmup.py, docs/static_analysis.md TPU6xx)
    is validated when the endpoint LOADS, like default_priority: a typo'd
    mode fails fast naming the knob — an inert warmup knob would read as
    "warmed" while every cold shape still compiled under live traffic."""
    mrp = ModelRequestProcessor(
        state_root=str(tmp_path), force_create=True, name="badwarm"
    )
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="bad_warm",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 1,
                    "max_seq_len": 64,
                    "prefill_buckets": [16],
                    "warmup": "ful",  # typo'd mode
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "bad_warm", "prompt": [1, 2], "max_tokens": 2},
        )
        return r.status, await r.text()

    status, text = _run(mrp, fn)
    assert status == 422 and "warmup" in text, (status, text)
    assert "bad_warm" not in mrp._engine_processor_lookup


def test_warmup_knob_startup_serves_warm(tmp_path):
    """aux engine.warmup="startup": the first request awaits the shared
    warmup task (engine.warmup(full=False)) and then serves normally."""
    mrp = ModelRequestProcessor(
        state_root=str(tmp_path), force_create=True, name="warm"
    )
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="warm_ep",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 1,
                    "max_seq_len": 64,
                    "prefill_buckets": [16],
                    "warmup": "startup",
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "warm_ep", "prompt": [1, 2], "max_tokens": 2},
        )
        return r.status, await r.json()

    status, body = _run(mrp, fn)
    assert status == 200, body
    proc = mrp._engine_processor_lookup["warm_ep"]
    assert proc._warmup_needed is False  # ran (or disabled after running)
    assert proc._warmup_task is not None


def test_weight_quant_typo_fails_at_endpoint_load(tmp_path):
    """aux engine.weight_quant (docs/w4a16.md) is validated when the
    endpoint LOADS, like default_priority: a typo'd value fails fast with
    the knob's name and the endpoint never registers — the engine would
    otherwise reject it only after the (possibly long) bundle load, with a
    message that doesn't say which aux key to fix."""
    mrp = ModelRequestProcessor(
        state_root=str(tmp_path), force_create=True, name="badwq"
    )
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="bad_wq",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 1,
                    "max_seq_len": 64,
                    "prefill_buckets": [16],
                    "weight_quant": "int-4",  # typo'd
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "bad_wq", "prompt": [1, 2], "max_tokens": 2},
        )
        return r.status, await r.text()

    status, text = _run(mrp, fn)
    assert status == 422 and "weight_quant" in text, (status, text)
    assert "bad_wq" not in mrp._engine_processor_lookup


def test_weight_quant_conflicting_alias_fails_at_endpoint_load(tmp_path):
    """A config spelling the knob BOTH ways with different values must not
    silently pick one — same fail-fast contract as the engine kwargs."""
    mrp = ModelRequestProcessor(
        state_root=str(tmp_path), force_create=True, name="dupwq"
    )
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="dup_wq",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 1,
                    "max_seq_len": 64,
                    "prefill_buckets": [16],
                    "weight_quant": "int4",
                    "quantize": "int8",  # conflicting legacy alias
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "dup_wq", "prompt": [1, 2], "max_tokens": 2},
        )
        return r.status, await r.text()

    status, text = _run(mrp, fn)
    assert status == 422 and "conflicts" in text, (status, text)
    assert "dup_wq" not in mrp._engine_processor_lookup


def test_weight_quant_int4_endpoint_serves(tmp_path):
    """A weightless-preset endpoint with engine.weight_quant=int4 loads,
    serves greedily, and reports the packed weight tree through the
    engine's lifecycle stats (the quantize alias spells the same knob)."""
    mrp = ModelRequestProcessor(
        state_root=str(tmp_path), force_create=True, name="wq4"
    )
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="tiny_w4",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 2,
                    "max_seq_len": 64,
                    "prefill_buckets": [16],
                    "weight_quant": "int4",
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "tiny_w4", "prompt": [1, 2, 3], "max_tokens": 4},
        )
        return r.status, await r.json()

    status, body = _run(mrp, fn)
    assert status == 200 and body["choices"][0]["text"] is not None
    engine = mrp._engine_processor_lookup["tiny_w4"].engine
    assert engine.weight_quant == "int4"
    stats = engine.lifecycle_stats()["weights"]
    assert stats["quant"] == "int4" and stats["bytes"] > 0


# -- replica fleet (docs/replication.md) --------------------------------------


def test_replicas_knob_typo_fails_at_endpoint_load(tmp_path):
    """aux engine.replicas is validated when the endpoint LOADS, like
    default_priority: a non-integer value fails fast naming the knob and
    the endpoint never registers."""
    mrp = ModelRequestProcessor(
        state_root=str(tmp_path), force_create=True, name="badrep"
    )
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="bad_rep",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 1,
                    "max_seq_len": 64,
                    "prefill_buckets": [16],
                    "replicas": "two",  # not an integer
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "bad_rep", "prompt": [1, 2], "max_tokens": 2},
        )
        return r.status, await r.text()

    status, text = _run(mrp, fn)
    assert status == 422 and "replicas" in text, (status, text)
    assert "bad_rep" not in mrp._engine_processor_lookup


def test_replica_fleet_endpoint_serves_and_aggregates_ready(tmp_path):
    """aux engine.replicas=2 builds a replica group behind the endpoint:
    requests serve through the prefix-affine router, /ready aggregates
    per-replica state (ready iff >= 1 ring member) with the fleet block,
    and stopping one replica keeps the endpoint ready while its sibling
    serves."""
    mrp = ModelRequestProcessor(
        state_root=str(tmp_path), force_create=True, name="fleet"
    )
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="fleet_llm",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 2,
                    "max_seq_len": 128,
                    "prefill_buckets": [32],
                    "cache": "paged",
                    "page_size": 16,
                    "prefix_cache": 64,
                    "prefix_block": 16,
                    "replicas": 2,
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)

    async def fn(client):
        prompt = [(3 + i * 7) % 90 + 1 for i in range(40)]
        for _ in range(2):
            r = await client.post(
                "/serve/openai/v1/completions",
                json={"model": "fleet_llm", "prompt": prompt,
                      "max_tokens": 2},
            )
            assert r.status == 200, await r.text()
        group = mrp._engine_processor_lookup["fleet_llm"].engine
        assert len(group.replicas) == 2
        # the repeated prompt stuck to one replica (prefix affinity)
        routes = group.router.stats()["requests"]
        assert sum(
            per["affine"] for per in routes.values()
        ) == 2
        assert max(per["affine"] for per in routes.values()) == 2

        r = await client.get("/ready")
        assert r.status == 200
        body = await r.json()
        fleet = body["fleet"]["fleet_llm"]
        assert fleet["replicas"] == 2 and fleet["ring_size"] == 2
        assert set(fleet["per_replica"]) == {"r0", "r1"}

        # one replica down: endpoint stays ready (ring >= 1), the fleet
        # block shows the ejected member
        group.replicas[1].engine.stop()
        r = await client.get("/ready")
        assert r.status == 200
        body = await r.json()
        fleet = body["fleet"]["fleet_llm"]
        assert fleet["ring_size"] == 1
        assert fleet["per_replica"]["r1"]["ring_state"] == "ejected"
        # /health carries the same fleet block
        r = await client.get("/health")
        assert r.status == 200
        assert (await r.json())["fleet"]["fleet_llm"]["ring_size"] == 1

        # the sibling still serves the conversation (rebalance route)
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "fleet_llm", "prompt": prompt, "max_tokens": 2},
        )
        assert r.status == 200, await r.text()

        # all replicas down: the endpoint flips not-ready
        group.replicas[0].engine.stop()
        r = await client.get("/ready")
        assert r.status == 503
        body = await r.json()
        assert "fleet_llm" in body["not_ready"]
        return True

    assert _run(mrp, fn)


def test_canary_weights_across_replica_groups(tmp_path):
    """The control plane composes with fleets: a CanaryEP weights traffic
    ACROSS endpoints, each of which may itself be a replica group —
    weighted routing across groups, prefix-affine routing within one
    (docs/replication.md)."""
    from clearml_serving_tpu.serving.endpoints import CanaryEP

    mrp = ModelRequestProcessor(
        state_root=str(tmp_path), force_create=True, name="canaryfleet"
    )
    for url, replicas in (("fleet_a", 2), ("solo_b", 1)):
        mrp.add_endpoint(
            ModelEndpoint(
                engine_type="llm",
                serving_url=url,
                auxiliary_cfg={
                    "engine": {
                        "preset": "llama-tiny",
                        "config": {"dtype": "float32"},
                        "max_batch": 2,
                        "max_seq_len": 128,
                        "prefill_buckets": [32],
                        "replicas": replicas,
                    }
                },
            )
        )
    mrp.add_canary_endpoint(
        CanaryEP(
            endpoint="cn_ep",
            load_endpoints=["fleet_a", "solo_b"],
            weights=[0.5, 0.5],
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)

    async def fn(client):
        prompt = [(9 + i * 5) % 90 + 1 for i in range(40)]
        for _ in range(12):
            r = await client.post(
                "/serve/openai/v1/completions",
                json={"model": "cn_ep", "prompt": prompt, "max_tokens": 2},
            )
            assert r.status == 200, await r.text()
        served = set(mrp._engine_processor_lookup)
        # both canary targets took traffic; the fleet target is a group
        assert {"fleet_a", "solo_b"} <= served
        group = mrp._engine_processor_lookup["fleet_a"].engine
        assert len(group.replicas) == 2
        routed = sum(
            sum(per.values())
            for per in group.router.stats()["requests"].values()
        )
        assert routed >= 1  # the canary sent the group a share
        return True

    assert _run(mrp, fn)


def test_replica_roles_knob_typo_fails_at_endpoint_load(tmp_path):
    """aux engine.replica_roles is validated when the endpoint LOADS
    (docs/disaggregation.md): a bad role value fails fast naming the
    knob and the endpoint never registers."""
    mrp = ModelRequestProcessor(
        state_root=str(tmp_path), force_create=True, name="badroles"
    )
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="bad_roles",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 1,
                    "max_seq_len": 64,
                    "prefill_buckets": [16],
                    "cache": "paged",
                    "page_size": 16,
                    "prefix_cache": 32,
                    "prefix_block": 16,
                    "replicas": 2,
                    "replica_roles": ["prefill", "decoder"],  # typo
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "bad_roles", "prompt": [1, 2], "max_tokens": 2},
        )
        return r.status, await r.text()

    status, text = _run(mrp, fn)
    assert status == 422 and "replica_roles" in text, (status, text)
    assert "bad_roles" not in mrp._engine_processor_lookup


def test_replica_roles_without_fleet_fails_at_endpoint_load(tmp_path):
    """engine.replica_roles on a single-replica endpoint is a config
    contradiction: fail at load naming both knobs."""
    mrp = ModelRequestProcessor(
        state_root=str(tmp_path), force_create=True, name="soloroles"
    )
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="solo_roles",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 1,
                    "max_seq_len": 64,
                    "prefill_buckets": [16],
                    "replica_roles": "prefill,decode",
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)

    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "solo_roles", "prompt": [1, 2], "max_tokens": 2},
        )
        return r.status, await r.text()

    status, text = _run(mrp, fn)
    assert status == 422 and "replica_roles" in text, (status, text)


def test_disaggregated_endpoint_serves_and_ships(tmp_path):
    """aux engine.replicas=2 + engine.replica_roles=prefill,decode builds
    a disaggregated fleet behind the endpoint: requests serve through the
    role-aware router, the prefill replica ships every admission's prefix
    KV to the decode replica, and /health carries the disaggregation
    block (docs/disaggregation.md)."""
    mrp = ModelRequestProcessor(
        state_root=str(tmp_path), force_create=True, name="disagg"
    )
    mrp.add_endpoint(
        ModelEndpoint(
            engine_type="llm",
            serving_url="disagg_llm",
            auxiliary_cfg={
                "engine": {
                    "preset": "llama-tiny",
                    "config": {"dtype": "float32"},
                    "max_batch": 2,
                    "max_seq_len": 128,
                    "prefill_buckets": [32, 64],
                    "cache": "paged",
                    "page_size": 16,
                    "prefix_cache": 64,
                    "prefix_block": 16,
                    "replicas": 2,
                    "replica_roles": "prefill,decode",
                    "kv_transport_pages": 32,
                }
            },
        )
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)

    async def fn(client):
        prompt = [(3 + i * 7) % 90 + 1 for i in range(40)]
        r = await client.post(
            "/serve/openai/v1/completions",
            json={"model": "disagg_llm", "prompt": prompt, "max_tokens": 2},
        )
        assert r.status == 200, await r.text()
        group = mrp._engine_processor_lookup["disagg_llm"].engine
        assert group.router.role_of("r0") == "prefill"
        assert group.router.role_of("r1") == "decode"
        assert group.transport is not None
        assert group.transport.capacity_pages == 32
        dis = group._disagg_snapshot()
        assert dis["ship_legs"] == 1 and dis["ship_leg_failures"] == 0
        decode = group.replicas[1].engine._kv_ship_snapshot()
        assert decode["receives"] == 1 and decode["hits"] == 1
        health = group.health()
        assert health["disaggregation"]["roles"] == {
            "r0": "prefill", "r1": "decode"
        }
        return True

    assert _run(mrp, fn) is True
