import jax
import jax.numpy as jnp
import numpy as np
import pytest

from clearml_serving_tpu.llm.kv_cache import PagePool, PagedKVCache
from clearml_serving_tpu.ops.paged_attention import paged_attention, paged_attention_xla
from clearml_serving_tpu.ops.quant import (
    dequant_llama_params,
    dequantize,
    dequantize_int4,
    int8_matmul,
    quantize_int4,
    quantize_int8,
    quantize_llama_params,
)


def _dense_reference(q, k, v, lengths):
    """q: [B,Hkv,G,D]; k/v: [B,T,Hkv,D] dense with per-seq lengths."""
    d = q.shape[-1]
    t_idx = jnp.arange(k.shape[1])[None]
    valid = t_idx < lengths[:, None]
    scores = jnp.einsum("bkgd,btkd->bkgt", q, k) * (d ** -0.5)
    scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bkgt,btkd->bkgd", probs, v)


def _random_paged_setup(rng, b=3, hkv=2, g=4, d=64, page_size=8, pages_per_seq=4):
    keys = jax.random.split(rng, 5)
    num_pages = b * pages_per_seq + 1
    q = jax.random.normal(keys[0], (b, hkv, g, d), jnp.float32)
    k_pool = jax.random.normal(keys[1], (hkv, num_pages, page_size, d), jnp.float32)
    v_pool = jax.random.normal(keys[2], (hkv, num_pages, page_size, d), jnp.float32)
    # distinct page ids per sequence (page 0 reserved as the null page)
    ids = np.arange(1, b * pages_per_seq + 1, dtype=np.int32)
    np.random.default_rng(0).shuffle(ids)
    page_table = jnp.asarray(ids.reshape(b, pages_per_seq))
    lengths = jnp.asarray([page_size * pages_per_seq, 13, 1], jnp.int32)
    return q, k_pool, v_pool, page_table, lengths


def test_paged_attention_xla_matches_dense():
    q, k_pool, v_pool, page_table, lengths = _random_paged_setup(jax.random.PRNGKey(0))
    out = paged_attention_xla(q, k_pool, v_pool, page_table, lengths)
    # dense equivalent: gather pages manually ([Hkv,B,PP,P,D] -> [B,T,Hkv,D])
    b, hkv, g, d = q.shape
    k = k_pool[:, page_table].reshape(hkv, b, -1, d).transpose(1, 2, 0, 3)
    v = v_pool[:, page_table].reshape(hkv, b, -1, d).transpose(1, 2, 0, 3)
    ref = _dense_reference(q, k, v, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_paged_attention_pallas_interpret_matches_xla():
    q, k_pool, v_pool, page_table, lengths = _random_paged_setup(jax.random.PRNGKey(1))
    ref = paged_attention_xla(q, k_pool, v_pool, page_table, lengths)
    out = paged_attention(q, k_pool, v_pool, page_table, lengths, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_paged_attention_single_token_sequence():
    q, k_pool, v_pool, page_table, lengths = _random_paged_setup(jax.random.PRNGKey(2))
    lengths = jnp.asarray([1, 1, 1], jnp.int32)
    ref = paged_attention_xla(q, k_pool, v_pool, page_table, lengths)
    out = paged_attention(q, k_pool, v_pool, page_table, lengths, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def _quantize_pool(pool):
    """Per-(token, head) symmetric int8 like models/llama._kv_store."""
    x = np.asarray(pool, np.float32)
    absmax = np.abs(x).max(-1)
    scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(x / scale[..., None]), -127, 127).astype(np.int8)
    return jnp.asarray(q), jnp.asarray(scale)


def test_paged_attention_int8_xla_matches_dequantized_dense():
    """The int8 XLA reference == running the bf16 reference over the
    eagerly dequantized pools (the scale operands ARE the dequant)."""
    q, k_pool, v_pool, page_table, lengths = _random_paged_setup(jax.random.PRNGKey(3))
    k8, ks = _quantize_pool(k_pool)
    v8, vs = _quantize_pool(v_pool)
    out = paged_attention_xla(q, k8, v8, page_table, lengths, ks, vs)
    kd = k8.astype(jnp.float32) * ks[..., None]
    vd = v8.astype(jnp.float32) * vs[..., None]
    ref = paged_attention_xla(q, kd, vd, page_table, lengths)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_paged_attention_int8_pallas_interpret_matches_xla():
    """Tentpole parity gate (tier-1): the Pallas int8 kernel — in-kernel
    dequant fused into the flash update — must match the XLA int8 gather
    reference to (better than) bf16 epsilon in interpret mode, including
    ragged lengths and an empty row."""
    q, k_pool, v_pool, page_table, lengths = _random_paged_setup(jax.random.PRNGKey(4))
    k8, ks = _quantize_pool(k_pool)
    v8, vs = _quantize_pool(v_pool)
    lengths = jnp.asarray([int(lengths[0]), 13, 0], jnp.int32)
    ref = paged_attention_xla(q, k8, v8, page_table, lengths, ks, vs)
    for pb in (1, 2, 32):
        out = paged_attention(
            q, k8, v8, page_table, lengths, k_scale=ks, v_scale=vs,
            pages_per_block=pb, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


def test_paged_attention_int8_partial_last_block_scales():
    """pages_per_seq NOT a multiple of pages_per_block, with live tokens in
    the final partial block: the kernel's fixed-width scale-window slices
    must not clamp into earlier rows (the gathered scales pad up to a
    block-token multiple). Regression for the r5 review finding."""
    q, k_pool, v_pool, page_table, lengths = _random_paged_setup(
        jax.random.PRNGKey(6), pages_per_seq=6, page_size=8
    )
    k8, ks = _quantize_pool(k_pool)
    v8, vs = _quantize_pool(v_pool)
    # lengths reach into the 6-page (48-token) capacity's final block when
    # pb=4 (block = 32 tokens): tokens 33..47 live in the partial block
    lengths = jnp.asarray([47, 35, 48], jnp.int32)
    ref = paged_attention_xla(q, k8, v8, page_table, lengths, ks, vs)
    out = paged_attention(
        q, k8, v8, page_table, lengths, k_scale=ks, v_scale=vs,
        pages_per_block=4, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_paged_attention_int8_requires_scales():
    q, k_pool, v_pool, page_table, lengths = _random_paged_setup(jax.random.PRNGKey(5))
    k8, _ = _quantize_pool(k_pool)
    v8, _ = _quantize_pool(v_pool)
    with pytest.raises(ValueError):
        paged_attention(q, k8, v8, page_table, lengths, interpret=True)


class TestPagePool:
    def test_alloc_free_cycle(self):
        pool = PagePool(num_pages=10, page_size=4, max_slots=3)
        assert pool.free_pages == 9  # page 0 reserved as the null page
        pages = pool.allocate(0, 10)  # 3 pages
        assert len(pages) == 3 and pool.free_pages == 6
        assert 0 not in pages
        pool.allocate(1, 4)
        assert pool.free_pages == 5
        pool.free(0)
        assert pool.free_pages == 8
        assert pool.slot_length(0) == 0

    def test_extend_allocates_on_boundary(self):
        pool = PagePool(num_pages=5, page_size=4, max_slots=1)
        pool.allocate(0, 4)
        assert pool.free_pages == 3  # 5 pages - null page - 1 allocated
        assert len(pool.extend(0, 1)) == 1    # crosses into page 2
        assert pool.slot_length(0) == 5
        assert pool.extend(0, 1) == []        # still inside page 2
        assert pool.slot_length(0) == 6
        assert len(pool.extend(0, 7)) == 2    # 6 -> 13 tokens spans two new pages

    def test_page_table_overflow_raises(self):
        pool = PagePool(num_pages=8, page_size=4, max_slots=1)
        pool.allocate(0, 12)  # 3 pages
        with pytest.raises(ValueError):
            pool.page_table(pages_per_seq=2)

    def test_exhaustion(self):
        pool = PagePool(num_pages=3, page_size=4, max_slots=2)
        pool.allocate(0, 8)  # 2 of the 2 allocatable pages (page 0 reserved)
        assert not pool.can_allocate(1)
        with pytest.raises(MemoryError):
            pool.allocate(1, 4)

    def test_page_table_shape(self):
        pool = PagePool(num_pages=8, page_size=4, max_slots=2)
        pool.allocate(1, 6)
        table = pool.page_table(pages_per_seq=4)
        assert table.shape == (2, 4)
        assert (table[0] == 0).all()
        assert table[1, :2].tolist() == pool._slot_pages[1]


def test_paged_kv_cache_roundtrip():
    cache = PagedKVCache(
        n_layers=2, n_kv_heads=2, head_dim=8, num_pages=8, page_size=4, max_slots=2,
        dtype="float32",
    )
    length = 6
    # stacked [L, S, Hkv, D]
    k_stack = jnp.stack(
        [jnp.arange(length * 2 * 8, dtype=jnp.float32).reshape(length, 2, 8) + li
         for li in range(2)]
    )
    v_stack = k_stack + 100
    cache.write_prompt(0, k_stack, v_stack, length)
    assert cache.pool.slot_length(0) == 6

    # append one token: [L, Hkv, D]
    k_new = jnp.stack([jnp.full((2, 8), 7.0 + li) for li in range(2)])
    v_new = k_new + 2
    cache.append_token(0, k_new, v_new)
    assert cache.pool.slot_length(0) == 7

    # reconstruct the sequence from pages and compare (layer 0)
    table = cache.pool.page_table(cache.max_pages_per_seq(16))
    k_l0, _ = cache.layer(0)                      # [Hkv, N, P, D]
    gathered = np.asarray(k_l0[:, table[0]])      # [Hkv, PP, P, D]
    gathered = gathered.transpose(1, 2, 0, 3).reshape(-1, 2, 8)[:7]
    np.testing.assert_allclose(gathered[:6], np.asarray(k_stack[0]))
    np.testing.assert_allclose(gathered[6], np.asarray(k_new[0]))


def test_paged_kv_cache_int8_roundtrip():
    """int8 pools: prompt scatter + per-token append store int8 values with
    their scale rows; dequantizing page-by-page recovers the source K/V to
    int8 precision (|err| <= scale/2 per element)."""
    cache = PagedKVCache(
        n_layers=2, n_kv_heads=2, head_dim=8, num_pages=8, page_size=4,
        max_slots=2, dtype="float32", kv_quant="int8",
    )
    assert cache.has_scales and cache.pool_dtype == "int8"
    assert cache.k_scale.shape == (2, 2, 8, 4)
    rng = np.random.default_rng(7)
    length = 6
    k_src = rng.normal(size=(2, length, 2, 8)).astype(np.float32)
    v_src = k_src + 0.5

    def store(x):  # [L, S, Hkv, D] -> (int8, scale [L, S, Hkv])
        absmax = np.abs(x).max(-1)
        scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
        q = np.clip(np.round(x / scale[..., None]), -127, 127).astype(np.int8)
        return jnp.asarray(q), jnp.asarray(scale)

    k_q, k_s = store(k_src)
    v_q, v_s = store(v_src)
    # scale operands are mandatory on int8 pools
    with pytest.raises(ValueError):
        cache.write_prompt(0, k_q, v_q, length)
    cache.write_prompt(0, k_q, v_q, length, k_s, v_s)

    k_tok = rng.normal(size=(2, 2, 8)).astype(np.float32)
    kt_q, kt_s = store(k_tok[:, None])  # [L,1,Hkv,D] -> squeeze below
    cache.append_token(
        0, kt_q[:, 0], kt_q[:, 0], kt_s[:, 0], kt_s[:, 0]
    )
    assert cache.pool.slot_length(0) == 7

    table = cache.pool.page_table(cache.max_pages_per_seq(16))
    k_l0 = np.asarray(cache.k[0][:, table[0]])          # [Hkv, PP, P, D] int8
    s_l0 = np.asarray(cache.k_scale[0][:, table[0]])    # [Hkv, PP, P]
    deq = (k_l0.astype(np.float32) * s_l0[..., None])
    deq = deq.transpose(1, 2, 0, 3).reshape(-1, 2, 8)[:7]
    np.testing.assert_allclose(deq[:6], k_src[0], atol=np.abs(k_src).max() / 127)
    np.testing.assert_allclose(
        deq[6], k_tok[0], atol=np.abs(k_tok).max() / 127
    )


def test_quantize_roundtrip():
    w = jax.random.normal(jax.random.PRNGKey(0), (64, 32), jnp.float32)
    q, scale = quantize_int8(w, axis=0)
    assert q.dtype == jnp.int8 and scale.shape == (1, 32)
    w2 = dequantize(q, scale, jnp.float32)
    # int8 symmetric quantization: error bounded by scale/2 per element
    assert float(jnp.max(jnp.abs(w2 - w) / scale)) <= 0.51


def test_int8_matmul_close():
    rng = jax.random.PRNGKey(1)
    x = jax.random.normal(rng, (4, 64), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(2), (64, 32), jnp.float32)
    q, scale = quantize_int8(w, axis=0)
    exact = x @ w
    approx = int8_matmul(x, q, scale)
    rel = float(jnp.linalg.norm(approx - exact) / jnp.linalg.norm(exact))
    assert rel < 0.02


def test_int4_roundtrip_grouped():
    # K=256 with group 128 -> 2 scale groups; error bounded by scale/2
    w = jax.random.normal(jax.random.PRNGKey(0), (256, 32), jnp.float32)
    packed, scale = quantize_int4(w)
    assert packed.dtype == jnp.uint8 and packed.shape == (128, 32)
    assert scale.shape == (2, 32)
    w2 = dequantize_int4(packed, scale, jnp.float32)
    per_elem_scale = jnp.repeat(scale, 128, axis=0)
    assert float(jnp.max(jnp.abs(w2 - w) / per_elem_scale)) <= 0.51


def test_int4_roundtrip_single_group_fallback():
    # K=64 < group -> one per-channel group, still packs two rows per byte
    w = jax.random.normal(jax.random.PRNGKey(1), (64, 16), jnp.float32)
    packed, scale = quantize_int4(w)
    assert packed.shape == (32, 16) and scale.shape == (1, 16)
    w2 = dequantize_int4(packed, scale, jnp.float32)
    assert float(jnp.max(jnp.abs(w2 - w) / scale)) <= 0.51


def test_int4_stacked_layers():
    # scan_layers-stacked [L, K, N] quantizes per layer independently
    w = jax.random.normal(jax.random.PRNGKey(2), (3, 256, 16), jnp.float32)
    packed, scale = quantize_int4(w)
    assert packed.shape == (3, 128, 16) and scale.shape == (3, 2, 16)
    w2 = dequantize_int4(packed, scale, jnp.float32)
    p0, s0 = quantize_int4(w[1])
    np.testing.assert_allclose(
        np.asarray(w2[1]), np.asarray(dequantize_int4(p0, s0, jnp.float32))
    )


def test_int4_matmul_close():
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 256), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(2), (256, 32), jnp.float32)
    packed, scale = quantize_int4(w)
    exact = x @ w
    approx = x @ dequantize_int4(packed, scale, jnp.float32)
    rel = float(jnp.linalg.norm(approx - exact) / jnp.linalg.norm(exact))
    # int4 noise floor on gaussian weights: step=absmax/7, absmax~=3sigma
    # over a 128-row group -> per-element rel noise ~ 3/(7*sqrt(12)) ~ 0.12.
    # Real checkpoints do better (outlier structure); random ones can't.
    assert rel < 0.15, rel


def test_int4_llama_forward_close():
    from clearml_serving_tpu import models

    bundle = models.build_model("llama", {"preset": "llama-tiny", "dtype": "float32"})
    params = bundle.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 512)
    ref = bundle.apply(params, tokens)
    qparams = quantize_llama_params(params, bits=4)
    # the tree really is 4-bit: projections hold packed uint8 at half rows
    wq = qparams["layers"][0]["wq"]
    assert wq["_q4"].dtype == jnp.uint8
    assert wq["_q4"].shape[-2] == params["layers"][0]["wq"].shape[-2] // 2
    out = bundle.apply(dequant_llama_params(qparams, jnp.float32), tokens)
    denom = float(jnp.std(ref))
    drift = float(jnp.max(jnp.abs(out - ref))) / denom
    # int4's ~12% per-matmul noise compounds through 2 layers + lm_head on
    # random weights; the exactness of the MECHANICS is pinned by the
    # roundtrip and accessor tests above, this guards against gross breakage
    assert drift < 2.5, drift


def test_int4_model_accessor_inline_dequant():
    """The model's _w accessor must serve an int4 tree directly (no eager
    dequant) — apply on the quantized tree equals apply on the dequantized
    tree."""
    from clearml_serving_tpu import models

    bundle = models.build_model("llama", {"preset": "llama-tiny", "dtype": "float32"})
    params = bundle.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 512)
    qparams = quantize_llama_params(params, bits=4)
    direct = bundle.apply(qparams, tokens)
    via_dequant = bundle.apply(dequant_llama_params(qparams, jnp.float32), tokens)
    np.testing.assert_allclose(
        np.asarray(direct), np.asarray(via_dequant), rtol=2e-4, atol=2e-4
    )


def test_quantized_llama_forward_close():
    from clearml_serving_tpu import models

    bundle = models.build_model("llama", {"preset": "llama-tiny", "dtype": "float32"})
    params = bundle.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, 512)
    ref = bundle.apply(params, tokens)
    qparams = quantize_llama_params(params)
    out = bundle.apply(dequant_llama_params(qparams, jnp.float32), tokens)
    # logits drift stays small relative to the logit scale
    denom = float(jnp.std(ref))
    drift = float(jnp.max(jnp.abs(out - ref))) / denom
    assert drift < 0.25, drift


def test_paged_attention_block_sizes_and_bf16():
    """The r2 multi-page kernel must be exact for any pages_per_block split
    (incl. non-dividing tails) and for bf16 pools."""
    q, k_pool, v_pool, page_table, lengths = _random_paged_setup(jax.random.PRNGKey(3))
    ref = paged_attention_xla(q, k_pool, v_pool, page_table, lengths)
    for pb in (1, 2, 3, 4, 8):
        out = paged_attention(
            q, k_pool, v_pool, page_table, lengths,
            pages_per_block=pb, interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg="pages_per_block={}".format(pb),
        )
    qb = q.astype(jnp.bfloat16)
    kb = k_pool.astype(jnp.bfloat16)
    vb = v_pool.astype(jnp.bfloat16)
    refb = paged_attention_xla(qb, kb, vb, page_table, lengths)
    outb = paged_attention(qb, kb, vb, page_table, lengths, interpret=True)
    np.testing.assert_allclose(
        np.asarray(outb, np.float32), np.asarray(refb, np.float32),
        rtol=2e-2, atol=2e-2,
    )
