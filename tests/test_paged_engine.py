"""Paged-KV serving path: model-level and engine-level equivalence with the
dense cache path (same greedy tokens / logits)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
from clearml_serving_tpu.llm.kv_cache import PagedKVCache


@pytest.fixture(scope="module")
def tiny():
    bundle = models.build_model("llama", {"preset": "llama-tiny", "dtype": "float32"})
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, params


def test_decode_paged_matches_dense(tiny):
    bundle, params = tiny
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, 512)
    seq_lens = jnp.array([9, 5], jnp.int32)

    # dense reference
    dense_cache = bundle.init_cache(2, 32)
    last_dense, dense_cache = bundle.prefill(params, tokens, seq_lens, dense_cache)

    # paged: write prompts into pools, then decode step by step
    cache = PagedKVCache(
        bundle.n_layers, bundle.n_kv_heads, bundle.head_dim,
        num_pages=32, page_size=4, max_slots=2, dtype="float32",
    )
    mini = bundle.init_cache(1, 16)
    for slot, n in ((0, 9), (1, 5)):
        last, filled = bundle.prefill(
            params, tokens[slot:slot + 1, :16][:, : mini["k"].shape[2]],
            jnp.asarray([n], jnp.int32), mini,
        )
        cache.write_prompt(slot, filled["k"][:, 0, :n], filled["v"][:, 0, :n], n)

    next_tokens = jnp.argmax(last_dense, axis=-1).astype(jnp.int32)
    pool = cache.pool
    for step in range(4):
        lengths0 = pool.lengths().copy()
        wp = np.zeros(2, np.int32)
        wo = np.zeros(2, np.int32)
        for slot in (0, 1):
            start = pool.slot_length(slot)
            pool.extend(slot, 1)
            ((wp[slot], wo[slot]),) = pool.token_coords(slot, start, 1)
        logits_paged, cache.k, cache.v = bundle.decode_paged(
            params, next_tokens, cache.k, cache.v,
            jnp.asarray(pool.page_table(8)), jnp.asarray(lengths0),
            jnp.asarray(wp), jnp.asarray(wo),
        )
        logits_dense, dense_cache = bundle.decode(params, next_tokens, dense_cache)
        np.testing.assert_allclose(
            np.asarray(logits_paged), np.asarray(logits_dense), rtol=2e-3, atol=2e-3
        )
        next_tokens = jnp.argmax(logits_dense, axis=-1).astype(jnp.int32)


def _collect(engine, req):
    async def run():
        out = []
        async for token in engine.generate(req):
            out.append(token)
        return out

    return asyncio.run(run())


def test_scan_layers_paged_engine_matches(tiny):
    """scan_layers + paged cache produce the same greedy tokens as the plain
    unrolled dense engine; and with int8 on BOTH engines (same quantized
    weights, list vs stacked) outputs still agree — the exact configuration
    the 8B bench runs (BENCH_QUANTIZE=int8 BENCH_SCAN_LAYERS=1)."""
    bundle_u, params_u = tiny
    bundle_s = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32", "scan_layers": True}
    )
    params_s = dict(params_u)
    params_s["layers"] = jax.tree.map(
        lambda *xs: jnp.stack(xs), *params_u["layers"]
    )
    common = dict(max_batch=2, max_seq_len=64, prefill_buckets=[16],
                  eos_token_id=257, decode_steps=3)
    p = [256, 11, 12, 13]

    dense = LLMEngineCore(bundle_u, params_u, cache_mode="dense", **common)
    paged_scan = LLMEngineCore(
        bundle_s, params_s, cache_mode="paged", page_size=4, **common
    )
    assert _collect(dense, GenRequest(prompt_ids=p, max_new_tokens=6)) == _collect(
        paged_scan, GenRequest(prompt_ids=p, max_new_tokens=6)
    )

    dense_q = LLMEngineCore(
        bundle_u, params_u, cache_mode="dense", quantize="int8", **common
    )
    paged_scan_q = LLMEngineCore(
        bundle_s, params_s, cache_mode="paged", page_size=4, quantize="int8", **common
    )
    assert _collect(dense_q, GenRequest(prompt_ids=p, max_new_tokens=6)) == _collect(
        paged_scan_q, GenRequest(prompt_ids=p, max_new_tokens=6)
    )


def test_paged_engine_matches_dense_engine(tiny):
    bundle, params = tiny
    prompts = [[256, 1, 2, 3], [256, 9, 8, 7, 6, 5], [256, 42]]
    common = dict(max_batch=2, max_seq_len=64, prefill_buckets=[16],
                  eos_token_id=257, decode_steps=3)

    dense = LLMEngineCore(bundle, params, cache_mode="dense", **common)
    paged = LLMEngineCore(bundle, params, cache_mode="paged", page_size=4, **common)

    for p in prompts:
        r_dense = _collect(dense, GenRequest(prompt_ids=p, max_new_tokens=7))
        r_paged = _collect(paged, GenRequest(prompt_ids=p, max_new_tokens=7))
        assert r_dense == r_paged, (p, r_dense, r_paged)

    # pages recycle: after all requests finished, the pool is fully free again
    assert paged.paged_cache.pool.free_pages == paged.paged_cache.pool.num_pages - 1


def test_paged_engine_concurrent(tiny):
    bundle, params = tiny

    async def run():
        engine = LLMEngineCore(
            bundle, params, cache_mode="paged", page_size=4,
            max_batch=2, max_seq_len=64, prefill_buckets=[16],
            eos_token_id=257, decode_steps=3,
        )
        results = await asyncio.gather(
            *[
                _collect_async(engine, GenRequest(prompt_ids=[256, i], max_new_tokens=5))
                for i in range(4)  # more requests than slots
            ]
        )
        return results, engine

    async def _collect_async(engine, req):
        out = []
        async for token in engine.generate(req):
            out.append(token)
        return out

    results, engine = asyncio.run(run())
    assert len(results) == 4 and all(len(r) >= 1 for r in results)
    assert engine.paged_cache.pool.free_pages == engine.paged_cache.pool.num_pages - 1


def test_paged_speculative_matches_plain_paged(tiny):
    """Speculation over the paged cache (verify_paged + over-allocate /
    truncate) is greedy-EXACT: outputs are token-identical to the plain
    paged engine — drafts hitting (repetitive prompt) and missing alike —
    and every over-allocated page rolls back to the pool."""
    bundle, params = tiny
    prompts = [
        [256] + [10, 20, 30, 10, 20, 30, 10, 20],   # repetitive: drafts hit
        [256] + list(range(40, 52)),                # no repeats: drafts miss
        [256, 99],                                  # tiny prompt
    ]
    common = dict(max_batch=2, max_seq_len=64, prefill_buckets=[16, 32],
                  eos_token_id=257, decode_steps=3)

    plain = LLMEngineCore(bundle, params, cache_mode="paged", page_size=4,
                          **common)
    spec = LLMEngineCore(
        bundle, params, cache_mode="paged", page_size=4,
        speculation="ngram", spec_k=3, spec_ngram=2, **common,
    )
    dispatches = [0]
    orig = spec._spec_paged_jit

    def counting(*a, **k):
        dispatches[0] += 1
        return orig(*a, **k)

    spec._spec_paged_jit = counting
    for p in prompts:
        r_plain = _collect(plain, GenRequest(prompt_ids=p, max_new_tokens=24))
        r_spec = _collect(spec, GenRequest(prompt_ids=p, max_new_tokens=24))
        assert r_plain == r_spec, (p, r_plain, r_spec)
    assert dispatches[0] > 0, "paged speculative path never dispatched"
    # truncate + finish-free bookkeeping: no page leaked
    assert spec.paged_cache.pool.free_pages == spec.paged_cache.pool.num_pages - 1


def test_paged_speculative_mixed_batch(tiny):
    """Concurrent greedy + seeded-sampled requests on the paged spec engine:
    per-slot gating keeps speculation active and both outputs match the
    plain paged engine token-for-token."""
    bundle, params = tiny
    reqs = [
        dict(prompt_ids=[256, 1, 2, 1, 2, 1, 2], max_new_tokens=10),
        dict(prompt_ids=[256, 5], max_new_tokens=10, temperature=0.9, seed=42),
    ]
    common = dict(max_batch=2, max_seq_len=64, prefill_buckets=[16],
                  eos_token_id=257, decode_steps=2)

    async def run(engine):
        return await asyncio.gather(*[
            _gather_one(engine, GenRequest(**r)) for r in reqs
        ])

    async def _gather_one(engine, req):
        out = []
        async for t in engine.generate(req):
            out.append(t)
        return out

    plain = asyncio.run(run(LLMEngineCore(
        bundle, params, cache_mode="paged", page_size=4, **common)))
    spec_engine = LLMEngineCore(
        bundle, params, cache_mode="paged", page_size=4,
        speculation="ngram", spec_k=3, **common,
    )
    spec = asyncio.run(run(spec_engine))
    assert spec == plain
    assert spec_engine.paged_cache.pool.free_pages == (
        spec_engine.paged_cache.pool.num_pages - 1
    )


def test_paged_speculative_pool_slack_fallback(tiny):
    """When the pool cannot hold the speculative over-allocation, the
    dispatch declines (returns None) and the iteration falls back to the
    plain paged chunk — requests still complete with exact greedy output."""
    bundle, params = tiny
    common = dict(max_batch=1, max_seq_len=64, prefill_buckets=[16],
                  eos_token_id=257, decode_steps=3)
    p = [256, 1, 2, 1, 2, 1]

    plain = LLMEngineCore(bundle, params, cache_mode="paged", page_size=4,
                          **common)
    want = _collect(plain, GenRequest(prompt_ids=p, max_new_tokens=8))

    # pool: 5 usable pages = 20 tokens — enough for the 6-token prompt plus
    # every plain chunk (max length 6+3*3=15 => 4 pages), but NOT for the
    # spec slack (6 + decode_steps*(k+1)=18 => 24 tokens => 6 pages)
    spec = LLMEngineCore(
        bundle, params, cache_mode="paged", page_size=4,
        speculation="ngram", spec_k=5,
        num_pages=6,
        **common,
    )
    declines = [0]
    orig = spec._dispatch_spec_paged_chunk

    def counting(*a, **k):
        res = orig(*a, **k)
        if res is None:
            declines[0] += 1
        return res

    spec._dispatch_spec_paged_chunk = counting
    got = _collect(spec, GenRequest(prompt_ids=p, max_new_tokens=8))
    assert got == want
    assert declines[0] > 0, "undersized pool never triggered the fallback"


def test_paged_pool_exhaustion_fails_only_that_request(tiny):
    """An undersized pool (oversubscription) must fail only the sequence that
    hits capacity, not the whole engine."""
    bundle, params = tiny

    async def run():
        engine = LLMEngineCore(
            bundle, params, cache_mode="paged", page_size=4,
            max_batch=2, max_seq_len=64, prefill_buckets=[16],
            eos_token_id=None, decode_steps=3,
            num_pages=2 + 16 // 4 + 1,  # room for ~1 bucket prompt + a little
        )
        ok = err = 0
        for want in (6, 40):
            try:
                out = []
                async for t in engine.generate(
                    GenRequest(prompt_ids=[256, 1, 2], max_new_tokens=want)
                ):
                    out.append(t)
                ok += 1
            except MemoryError:
                err += 1
        # engine still serves after the failure
        out = []
        async for t in engine.generate(GenRequest(prompt_ids=[256, 9], max_new_tokens=4)):
            out.append(t)
        return ok, err, len(out)

    ok, err, n = asyncio.run(run())
    assert err >= 1, "long generation should exhaust the tiny pool"
    assert n >= 1, "engine must keep serving after a capacity failure"


def test_paged_sampled_speculation(tiny):
    """Rejection-sampled speculation over the paged cache: a temperature>0
    request alone drives the spec dispatch, completes the full budget, and
    the over-allocated pages roll back (pool fully free afterwards)."""
    bundle, params = tiny
    engine = LLMEngineCore(
        bundle, params, cache_mode="paged", page_size=4,
        speculation="ngram", spec_k=3,
        max_batch=2, max_seq_len=64, prefill_buckets=[16],
        eos_token_id=None, decode_steps=2,
    )
    dispatches = [0]
    orig = engine._spec_paged_jit

    def counting(*a, **k):
        dispatches[0] += 1
        return orig(*a, **k)

    engine._spec_paged_jit = counting
    out = _collect(engine, GenRequest(
        prompt_ids=[256, 5, 6, 5, 6], max_new_tokens=12, temperature=0.9))
    assert len(out) == 12
    assert dispatches[0] > 0, "sampled-only paged batch skipped the chain"
    assert engine.paged_cache.pool.free_pages == (
        engine.paged_cache.pool.num_pages - 1
    )
