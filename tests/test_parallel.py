import jax
import jax.numpy as jnp
import numpy as np
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.parallel import (
    llama_cache_sharding,
    llama_param_sharding,
    make_mesh,
    mesh_from_aux_cfg,
    shard_params,
)
from clearml_serving_tpu.parallel.ring_attention import ring_attention


def dense_attention(q, k, v, causal):
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (q.shape[-1] ** -0.5)
    if causal:
        s = q.shape[1]
        mask = jnp.where(jnp.tril(jnp.ones((s, s), dtype=bool)), 0.0, -jnp.inf)
        scores = scores + mask[None, None]
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def test_make_mesh():
    mesh = make_mesh({"tp": 8})
    assert mesh.shape["tp"] == 8 and mesh.shape["dp"] == 1
    mesh = make_mesh({"dp": 2, "tp": -1})
    assert mesh.shape["tp"] == 4
    with pytest.raises(ValueError):
        make_mesh({"tp": 3})
    with pytest.raises(ValueError):
        make_mesh({"tp": -1, "dp": -1})


def test_mesh_from_aux_cfg():
    mesh = mesh_from_aux_cfg({"mesh": {"dp": 4, "tp": 2}})
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
    assert mesh_from_aux_cfg(None).shape["tp"] == 8


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    mesh = make_mesh({"sp": 8})
    rng = jax.random.PRNGKey(0)
    b, s, h, d = 2, 64, 4, 16
    q, k, v = (
        jax.random.normal(key, (b, s, h, d), jnp.float32)
        for key in jax.random.split(rng, 3)
    )
    out = ring_attention(q, k, v, mesh, axis_name="sp", causal=causal)
    ref = dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_llama_tp_sharded_forward_matches_single():
    """TP-sharded llama forward over a dp×tp mesh must equal the unsharded
    forward (GSPMD inserts the collectives; result must be invariant)."""
    mesh = make_mesh({"dp": 2, "tp": 4})
    bundle = models.build_model("llama", {"preset": "llama-tiny", "dtype": "float32"})
    params = bundle.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 512)

    expected = bundle.apply(params, tokens)

    shardings = llama_param_sharding(mesh, params)
    sharded_params = shard_params(mesh, params, shardings)
    from jax.sharding import NamedSharding, PartitionSpec as P

    tok_sharded = jax.device_put(tokens, NamedSharding(mesh, P("dp")))
    out = jax.jit(bundle.apply)(sharded_params, tok_sharded)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-3, atol=2e-3)


def test_distributed_single_process_noop():
    from clearml_serving_tpu.parallel import (
        global_mesh,
        initialize_distributed,
        is_primary_host,
    )

    assert initialize_distributed() == 0  # no coordinator configured -> no-op
    assert is_primary_host()
    mesh = global_mesh()
    assert mesh.shape["tp"] == 8


def test_llama_cache_sharding_spec():
    mesh = make_mesh({"dp": 2, "tp": 4})
    spec = llama_cache_sharding(mesh)
    assert set(spec) == {"k", "v", "length"}


def test_quantized_llama_tp_sharding():
    """The int8 tree must TP-shard like the bf16 weights: per-chip shard
    bytes ~ 1/tp of the whole tree (r1 VERDICT weak #2), and the sharded
    quantized forward must equal the replicated quantized forward."""
    from clearml_serving_tpu.ops.quant import quantize_llama_params
    from clearml_serving_tpu.parallel import llama_quantized_param_sharding

    mesh = make_mesh({"dp": 1, "tp": 8})
    bundle = models.build_model("llama", {"preset": "llama-tiny", "dtype": "float32"})
    params = bundle.init(jax.random.PRNGKey(0))
    qparams = quantize_llama_params(params)
    shardings = llama_quantized_param_sharding(mesh, qparams)
    sharded = shard_params(mesh, qparams, shardings)

    # every projection's int8 payload is split over tp, scales follow the
    # output axis
    wq = sharded["layers"][0]["wq"]
    assert wq["_q8"].sharding.spec == (None, "tp")
    total = wq["_q8"].size
    local = wq["_q8"].addressable_shards[0].data.size
    assert local == total // 8
    scale = wq["_scale"]
    assert scale.addressable_shards[0].data.shape[-1] == scale.shape[-1] // 8
    # row-parallel wo: q8 input dim sharded, scale replicated
    wo = sharded["layers"][0]["wo"]
    assert wo["_q8"].sharding.spec == ("tp", None)
    assert wo["_q8"].addressable_shards[0].data.size == wo["_q8"].size // 8

    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 512)
    expected = bundle.apply(qparams, tokens)
    out = jax.jit(bundle.apply)(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-3, atol=2e-3)


def test_int4_llama_tp_sharding():
    """The int4 tree TP-shards like the bf16 weights: packed payload splits
    over tp; a scale whose group count can't split (llama-tiny's K<group
    single-group fallback) replicates its input axis instead of failing."""
    from clearml_serving_tpu.ops.quant import quantize_llama_params
    from clearml_serving_tpu.parallel import llama_quantized_param_sharding

    mesh = make_mesh({"dp": 1, "tp": 8})
    bundle = models.build_model("llama", {"preset": "llama-tiny", "dtype": "float32"})
    params = bundle.init(jax.random.PRNGKey(0))
    qparams = quantize_llama_params(params, bits=4)
    shardings = llama_quantized_param_sharding(mesh, qparams)
    sharded = shard_params(mesh, qparams, shardings)

    wq = sharded["layers"][0]["wq"]
    assert wq["_q4"].sharding.spec == (None, "tp")
    assert wq["_q4"].addressable_shards[0].data.size == wq["_q4"].size // 8
    # column-parallel scale shards its output axis with the weight
    assert (
        wq["_scale4"].addressable_shards[0].data.shape[-1]
        == wq["_scale4"].shape[-1] // 8
    )
    # row-parallel wo: packed input dim sharded; the single-group scale's
    # input axis cannot split 8 ways and must replicate
    wo = sharded["layers"][0]["wo"]
    assert wo["_q4"].sharding.spec == ("tp", None)
    assert wo["_q4"].addressable_shards[0].data.size == wo["_q4"].size // 8
    assert wo["_scale4"].addressable_shards[0].data.shape[-2] == 1

    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 512)
    expected = bundle.apply(qparams, tokens)
    out = jax.jit(bundle.apply)(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-3, atol=2e-3)


def test_prefill_ring_matches_prefill():
    """sp-sharded ring prefill must produce the same last-token logits and
    KV cache as the plain prefill (ring attention leaves serving shelf-ware
    status — r1 VERDICT weak #6)."""
    mesh = make_mesh({"dp": 1, "tp": 2, "sp": 4})
    bundle = models.build_model("llama", {"preset": "llama-tiny", "dtype": "float32"})
    params = bundle.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 32), 0, 512)
    seq_lens = jnp.asarray([29], jnp.int32)  # ragged tail inside the ring
    template = bundle.init_cache(1, 32)

    last_ref, cache_ref = jax.jit(bundle.prefill)(params, tokens, seq_lens, template)
    last_ring, cache_ring = jax.jit(
        lambda p, t, s, c: bundle.prefill_ring(p, t, s, c, mesh)
    )(params, tokens, seq_lens, template)

    np.testing.assert_allclose(
        np.asarray(last_ring), np.asarray(last_ref), rtol=2e-4, atol=2e-4
    )
    # caches must agree on the live region (padding region is masked later)
    np.testing.assert_allclose(
        np.asarray(cache_ring["k"][:, :, :29]),
        np.asarray(cache_ref["k"][:, :, :29]),
        rtol=2e-4, atol=2e-4,
    )
    np.testing.assert_array_equal(
        np.asarray(cache_ring["length"]), np.asarray(cache_ref["length"])
    )


def test_engine_long_prompt_ring_prefill_generates_identically():
    """An engine with an sp mesh must route long prompts through ring
    prefill and generate the same greedy tokens as a mesh-less engine."""
    import asyncio

    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

    bundle = models.build_model("llama", {"preset": "llama-tiny", "dtype": "float32"})
    params = bundle.init(jax.random.PRNGKey(0))
    prompt = [256] + [int(x) for x in
                      np.random.RandomState(0).randint(1, 400, 40)]

    def make(mesh, **kw):
        return LLMEngineCore(
            bundle, params, max_batch=2, max_seq_len=128,
            prefill_buckets=[16, 32], eos_token_id=257, mesh=mesh, **kw,
        )

    async def collect(engine):
        out = []
        async for t in engine.generate(GenRequest(prompt_ids=prompt, max_new_tokens=6)):
            out.append(t)
        return out

    plain = asyncio.run(collect(make(None)))

    mesh = make_mesh({"dp": 1, "tp": 2, "sp": 4})
    engine = make(mesh, long_prefill_threshold=32, long_bucket_step=8)
    assert engine._sp == 4
    ringed = asyncio.run(collect(engine))
    # the 41-token prompt exceeds threshold 32 -> ring path; same greedy text
    assert ringed == plain
    assert 48 in engine._prefill_templates  # padded to the sp-divisible step


def test_prefill_pipeline_matches_prefill():
    """Microbatch pipeline prefill (true PP schedule, not just weight
    sharding — r2 VERDICT weak #3) must equal plain prefill exactly:
    logits, written KV region, and lengths, for ragged batches and for
    chunk counts that don't divide the prompt."""
    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32", "scan_layers": True}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (3, 24), 0, 512)
    seq_lens = jnp.asarray([24, 17, 3], jnp.int32)
    template = bundle.init_cache(3, 48)
    last_ref, cache_ref = jax.jit(bundle.prefill)(params, tokens, seq_lens, template)
    for stages, chunk in ((2, 4), (2, 8), (1, 24)):
        last_pp, cache_pp = jax.jit(
            lambda p, t, s, c, st=stages, ch=chunk: bundle.prefill_pipeline(
                p, t, s, c, stages=st, chunk=ch
            )
        )(params, tokens, seq_lens, template)
        np.testing.assert_allclose(
            np.asarray(last_pp), np.asarray(last_ref), rtol=2e-4, atol=2e-4
        )
        for row, n in enumerate((24, 17, 3)):
            np.testing.assert_allclose(
                np.asarray(cache_pp["k"][:, row, :n]),
                np.asarray(cache_ref["k"][:, row, :n]),
                rtol=2e-4, atol=2e-4,
            )
        np.testing.assert_array_equal(
            np.asarray(cache_pp["length"]), np.asarray(cache_ref["length"])
        )


def test_prefill_pipeline_sharded_matches_unsharded():
    """Under a pp mesh the pipeline prefill must still be exact: stage slabs
    shard over pp, activations hop stages via the shifted stage axis."""
    from clearml_serving_tpu.parallel import llama_param_sharding

    mesh = make_mesh({"dp": 1, "tp": 2, "pp": 4})
    bundle = models.build_model(
        "llama",
        {"preset": "llama-tiny", "dtype": "float32", "scan_layers": True,
         "n_layers": 4},
    )
    params = bundle.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 512)
    seq_lens = jnp.asarray([16, 11], jnp.int32)
    template = bundle.init_cache(2, 32)
    last_ref, _ = jax.jit(bundle.prefill)(params, tokens, seq_lens, template)

    sharded = shard_params(mesh, params, llama_param_sharding(mesh, params))
    with mesh:
        last_pp, cache_pp = jax.jit(
            lambda p, t, s, c: bundle.prefill_pipeline(
                p, t, s, c, stages=4, chunk=4
            )
        )(sharded, tokens, seq_lens, template)
    np.testing.assert_allclose(
        np.asarray(last_pp), np.asarray(last_ref), rtol=2e-4, atol=2e-4
    )


def test_engine_long_prompt_pipeline_prefill_generates_identically():
    """An engine with a pp mesh routes long prompts through the pipeline
    prefill and generates the same greedy tokens as a mesh-less engine."""
    import asyncio

    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32", "scan_layers": True}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    prompt = [256] + [int(x) for x in
                      np.random.RandomState(1).randint(1, 400, 40)]

    def make(mesh, **kw):
        return LLMEngineCore(
            bundle, params, max_batch=2, max_seq_len=128,
            prefill_buckets=[16, 32], eos_token_id=257, mesh=mesh, **kw,
        )

    async def collect(engine):
        out = []
        async for t in engine.generate(GenRequest(prompt_ids=prompt, max_new_tokens=6)):
            out.append(t)
        return out

    plain = asyncio.run(collect(make(None)))
    mesh = make_mesh({"dp": 2, "tp": 2, "pp": 2})
    engine = make(mesh, long_prefill_threshold=32, pipeline_chunk=16)
    assert engine._prefill_pipeline_jit is not None
    piped = asyncio.run(collect(engine))
    assert piped == plain
    # 41 tokens > threshold 32 -> pipeline bucket = ceil(41/16)*16 = 48
    assert 48 in engine._prefill_templates


def test_ring_cap_non_divisible_max_seq_len():
    """With max_seq_len not divisible by sp, prompts between the sp-divisible
    cap and max_seq_len must fall back to plain prefill, not crash the cache
    insert (review r2 finding)."""
    import asyncio

    from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

    bundle = models.build_model("llama", {"preset": "llama-tiny", "dtype": "float32"})
    params = bundle.init(jax.random.PRNGKey(0))
    mesh = make_mesh({"dp": 1, "tp": 2, "sp": 4})
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=126,  # 126 % 4 != 0
        prefill_buckets=[16, 32, 126], eos_token_id=257, mesh=mesh,
        long_prefill_threshold=32, long_bucket_step=8,
    )
    assert engine._long_cap == 124

    async def run(n):
        req = GenRequest(prompt_ids=[256] + list(range(1, n)), max_new_tokens=2)
        return [t async for t in engine.generate(req)]

    # 125-token prompt: > cap 124 -> plain prefill path; must serve
    assert len(asyncio.run(run(125))) >= 1
    # 60-token prompt: ring path, bucket 64 <= 124
    assert len(asyncio.run(run(60))) >= 1
    assert 64 in engine._prefill_templates


def test_moe_ep_sharded_forward_matches_single():
    """MoE expert weights shard over the ep axis (tp for the per-expert ffn);
    the sharded forward must equal the unsharded one — EP first-class over
    the mesh (SURVEY §2.9 parallelism checklist)."""
    mesh = make_mesh({"dp": 1, "tp": 2, "ep": 4})
    bundle = models.build_model(
        "llama",
        {"preset": "llama-tiny", "dtype": "float32",
         "n_experts": 4, "moe_top_k": 2, "moe_capacity_factor": 4.0},
    )
    params = bundle.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 512)
    expected = bundle.apply(params, tokens)

    shardings = llama_param_sharding(mesh, params)
    sharded = shard_params(mesh, params, shardings)
    wge = sharded["layers"][0]["w_gate_e"]
    assert wge.sharding.spec == ("ep", None, "tp")
    assert wge.addressable_shards[0].data.shape[0] == 1  # 4 experts / ep=4
    out = jax.jit(bundle.apply)(sharded, tokens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-3, atol=2e-3
    )


def test_pp_layer_sharded_scan_forward_matches_single():
    """pp shards the stacked layer dim (scan_layers): per-chip weights ~ L/pp
    and the forward still matches the unsharded model (XLA gathers one
    layer's weights per scan step)."""
    mesh = make_mesh({"dp": 1, "tp": 2, "pp": 4})
    bundle = models.build_model(
        "llama",
        {"preset": "llama-tiny", "dtype": "float32", "n_layers": 4,
         "scan_layers": True},
    )
    params = bundle.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, 512)
    expected = bundle.apply(params, tokens)

    shardings = llama_param_sharding(mesh, params)
    sharded = shard_params(mesh, params, shardings)
    wq = sharded["layers"]["wq"]
    assert wq.sharding.spec[0] == "pp"
    assert wq.addressable_shards[0].data.shape[0] == 1  # 4 layers / pp=4
    out = jax.jit(bundle.apply)(sharded, tokens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-3, atol=2e-3
    )
