"""Pipelined decode (docs/pipelined_decode.md).

Depth-2 double-buffered chunk dispatch with device-resident token chaining
must be BEHAVIOR-INVISIBLE next to the serial loop: byte-identical greedy
(and seeded) token streams, correct slot reuse through the quarantine
barrier, and clean page accounting at drain. These tests pin that contract
plus the observability surface (in-flight gauge, stage histograms)."""

import asyncio

import numpy as np
import pytest

import jax

from clearml_serving_tpu import models
from clearml_serving_tpu.llm.engine import (
    GenRequest,
    LLMEngineCore,
    _InFlightChunk,
)


@pytest.fixture(scope="module")
def parts():
    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, params


@pytest.fixture(scope="module")
def qparts(parts):
    """Same weights behind an int8-KV build (kv_quant applies to the cache,
    not the params, so the trees are interchangeable)."""
    bundle, params = parts
    qbundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32",
                  "kv_quant": "int8"}
    )
    return qbundle, params


def _make(bundle, params, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 128)
    kw.setdefault("prefill_buckets", [16, 32])
    kw.setdefault("eos_token_id", 257)
    kw.setdefault("decode_steps", 4)
    return LLMEngineCore(bundle, params, **kw)


def _run_group(engine, prompts, **req_kw):
    """Submit all prompts concurrently, return per-prompt token streams
    (ordered by prompt index), then wait for full drain so page accounting
    is final."""

    async def go():
        async def one(ids):
            req = GenRequest(prompt_ids=list(ids), **req_kw)
            return [t async for t in engine.generate(req)]

        outs = await asyncio.gather(*(one(p) for p in prompts))
        await engine.wait_drained()
        return outs

    return asyncio.run(go())


_PROMPTS = [
    [256] + [(7 * i + 3 * j) % 250 + 1 for j in range(11)] for i in range(5)
]


def test_pipeline_depth_env_knob(monkeypatch, parts):
    bundle, params = parts
    monkeypatch.setenv("TPUSERVE_PIPELINE_DEPTH", "1")
    assert _make(bundle, params).pipeline_depth == 1
    monkeypatch.delenv("TPUSERVE_PIPELINE_DEPTH")
    assert _make(bundle, params).pipeline_depth == 2  # default
    # explicit kwarg beats the env
    monkeypatch.setenv("TPUSERVE_PIPELINE_DEPTH", "3")
    assert _make(bundle, params, pipeline_depth=1).pipeline_depth == 1


@pytest.mark.parametrize("cache_mode", ["dense", "paged"])
def test_greedy_ab_identical_across_depths(parts, cache_mode, monkeypatch):
    """Greedy, fixed prompts, more requests than slots (so finished slots
    must be re-admitted through the quarantine barrier): the token streams
    at depth 1 (serial escape hatch) and depth 2 must be byte-identical —
    the overshoot chunks' extra tokens are dropped, never emitted."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    bundle, params = parts
    outs = {}
    for depth in (1, 2):
        engine = _make(
            bundle, params, cache_mode=cache_mode, pipeline_depth=depth
        )
        outs[depth] = _run_group(
            engine, _PROMPTS, max_new_tokens=23, temperature=0.0
        )
        if cache_mode == "paged":
            pool = engine.paged_cache.pool
            # drained: every page back in the pool (no prefix cache here)
            assert pool.free_pages == pool.num_pages - 1
        engine.stop()
    assert outs[1] == outs[2]
    assert all(len(s) >= 1 for s in outs[2])


def test_greedy_ab_identical_across_depths_int8_paged(qparts, monkeypatch):
    """docs/paged_kv_quant.md acceptance: with kv_quant=int8 on the PAGED
    backend (int8 page pools + in-kernel dequant), greedy streams must stay
    byte-identical between TPUSERVE_PIPELINE_DEPTH 1 and 2 — the scale
    pools chain through the pipelined dispatches exactly like the data
    pools, audited by the armed KV sanitizer."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    qbundle, params = qparts
    outs = {}
    for depth in (1, 2):
        engine = _make(
            qbundle, params, cache_mode="paged", pipeline_depth=depth
        )
        assert engine.paged_cache.pool_dtype == "int8"
        outs[depth] = _run_group(
            engine, _PROMPTS, max_new_tokens=23, temperature=0.0
        )
        pool = engine.paged_cache.pool
        assert pool.free_pages == pool.num_pages - 1
        engine.stop()
    assert outs[1] == outs[2]
    assert all(len(s) >= 1 for s in outs[2])


def test_seeded_sampling_ab_identical_across_depths(parts):
    """Seeded sampling keys off fold_in(seed, tokens_generated): the
    pipelined dispatch feeds counters that account for chunks still in
    flight, so seeded streams must replay identically at any depth."""
    bundle, params = parts
    outs = {}
    for depth in (1, 2):
        engine = _make(bundle, params, pipeline_depth=depth)
        outs[depth] = _run_group(
            engine,
            _PROMPTS[:3],
            max_new_tokens=17,
            temperature=0.9,
            top_k=40,
            seed=1234,
        )
        engine.stop()
    assert outs[1] == outs[2]


def test_quarantine_defers_free_until_barrier(parts):
    """A slot freed while a younger chunk still decodes it must stay
    unavailable (and, on the paged backend, keep its pages) until that
    chunk retires."""
    bundle, params = parts
    engine = _make(bundle, params, cache_mode="paged", max_batch=2)
    pool = engine.paged_cache.pool
    req = GenRequest(prompt_ids=[256, 1, 2], max_new_tokens=4)
    engine._slot_req[0] = req
    pool.allocate(0, 8)
    held = pool.free_pages
    # a younger dispatched-but-unretired chunk still references slot 0
    entry = _InFlightChunk(
        seq=7, epoch=0, active_mask=np.array([True, False]), chunk=None
    )
    engine._inflight.append(entry)
    engine._slot_req[0] = None
    engine._free_slot_pages(0)
    assert engine._quarantine == {0: 7}
    assert pool.free_pages == held  # pages NOT freed yet
    # an older retire must not release it...
    engine._release_quarantine(6)
    assert 0 in engine._quarantine
    # ...the barrier retire does
    engine._inflight.clear()
    engine._release_quarantine(7)
    assert engine._quarantine == {}
    assert pool.free_pages == pool.num_pages - 1


def test_dispatchable_mask_skips_covered_slots(parts):
    """A request whose remaining max_new_tokens budget is already covered
    by in-flight chunks is certain to finish at an earlier retire —
    dispatching more compute for it is pure waste."""
    bundle, params = parts
    engine = _make(bundle, params, decode_steps=4)
    a = GenRequest(prompt_ids=[256, 1], max_new_tokens=6)
    b = GenRequest(prompt_ids=[256, 2], max_new_tokens=100)
    a.produced, b.produced = 3, 3
    engine._slot_req[0], engine._slot_req[1] = a, b
    active = np.array([True, True])
    # nothing in flight: both dispatchable
    assert engine._dispatchable_mask(active).tolist() == [True, True]
    # one in-flight chunk covering both slots: slot 0 has 6-3=3 tokens left
    # <= 4 pending steps -> certain to finish; slot 1 keeps going
    engine._inflight.append(
        _InFlightChunk(
            seq=1, epoch=0, active_mask=np.array([True, True]), chunk=None
        )
    )
    assert engine._dispatchable_mask(active).tolist() == [False, True]


def test_pipeline_observability(parts):
    """health() / lifecycle_stats() expose depth, live in-flight count and
    the dispatch/retire stage histograms the metrics collector exports."""
    bundle, params = parts
    engine = _make(bundle, params, pipeline_depth=2)
    _run_group(engine, _PROMPTS[:2], max_new_tokens=9, temperature=0.0)
    health = engine.health()
    assert health["pipeline"]["depth"] == 2
    assert health["pipeline"]["inflight"] == 0  # drained
    stats = engine.lifecycle_stats()["pipeline"]
    assert stats["dispatch_ms"]["count"] > 0
    assert stats["retire_ms"]["count"] > 0
    assert stats["dispatch_ms"]["count"] == sum(stats["dispatch_ms"]["counts"])
    assert stats["retire_ms"]["sum_ms"] >= 0.0
    engine.stop()
