"""Radix prefix caching tests (llm/prefix_cache.py + engine hit paths).

Correctness bar: an engine WITH the prefix cache must emit exactly the greedy
tokens of an engine WITHOUT it, for both the first (miss+store) and second
(hit) admission of a shared prompt, and for prompts sharing only a prefix —
on BOTH cache backends. On the paged backend a hit must additionally share
pages PHYSICALLY (same page ids in both slots' tables, no KV copies).
"""

import asyncio

import jax
import numpy as np
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
from clearml_serving_tpu.llm.kv_cache import PagePool
from clearml_serving_tpu.llm.prefix_cache import RadixPrefixCache

CFG = {"preset": "llama-tiny", "dtype": "float32"}


@pytest.fixture(scope="module")
def parts():
    bundle = models.build_model("llama", CFG)
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, params


def _engine(bundle, params, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 160)
    kw.setdefault("prefill_buckets", [32, 64, 128])
    kw.setdefault("eos_token_id", None)
    kw.setdefault("decode_steps", 2)
    return LLMEngineCore(bundle, params, **kw)


def _gen(engine, prompt, n=6):
    async def run():
        req = GenRequest(prompt_ids=list(prompt), max_new_tokens=n)
        out = [t async for t in engine.generate(req)]
        return out

    return asyncio.run(run())


# -- unit (dense payloads) ----------------------------------------------------


def test_block_alignment_and_partial_hits():
    cache = RadixPrefixCache(max_nodes=16, block=4)
    ids = list(range(11))  # prefix cap = floor(10/4)*4 = 8
    assert cache.longest_prefix_len(len(ids)) == 8
    k = np.zeros((2, 1, 16, 2, 4), np.float32)
    cache.store(ids, 0, {"k": k, "v": k})
    hit = cache.lookup(ids, 0)
    assert hit is not None and hit["len"] == 8
    assert hit["bufs"]["k"].shape[2] == 8
    # a prompt sharing only the first 4 tokens hits PARTIALLY at block
    # granularity (the old exact-match LRU missed here)
    part = cache.lookup(ids[:4] + [99, 98, 97, 96, 95], 0)
    assert part is not None and part["len"] == 4
    assert part["bufs"]["k"].shape[2] == 4
    # a LONGER prompt sharing the 8-prefix hits the full stored run
    assert cache.lookup(ids[:8] + [55, 44, 33], 0)["len"] == 8
    # nothing shared at all -> miss
    assert cache.lookup([7, 7, 7, 7, 7], 0) is None


def test_store_extends_existing_path():
    cache = RadixPrefixCache(max_nodes=16, block=2)
    k8 = np.zeros((1, 1, 8, 1, 2), np.float32)
    cache.store([1, 2, 3], 0, {"k": k8, "v": k8})         # one block [1,2]
    assert len(cache) == 1
    cache.store([1, 2, 5, 6, 7], 0, {"k": k8, "v": k8})   # adds [5,6] below
    assert len(cache) == 2
    hit = cache.lookup([1, 2, 5, 6, 9], 0)
    assert hit["len"] == 4


def test_uncount_hit_reclassifies_as_miss():
    """A hit the engine cannot use (no prefill bucket fits) must not inflate
    the hit rate or the tokens-saved counter."""
    cache = RadixPrefixCache(max_nodes=16, block=2)
    k = np.zeros((1, 1, 8, 1, 2), np.float32)
    cache.store([1, 2, 3], 0, {"k": k, "v": k})
    hit = cache.lookup([1, 2, 9], 0)
    assert cache.hits == 1 and cache.hit_tokens == 2
    cache.uncount_hit(hit)
    assert cache.hits == 0 and cache.misses == 1 and cache.hit_tokens == 0


def test_lora_namespaces_are_separate():
    cache = RadixPrefixCache(max_nodes=16, block=2)
    ids = [1, 2, 3, 4, 5]
    k = np.zeros((1, 1, 8, 1, 2), np.float32)
    cache.store(ids, 0, {"k": k, "v": k})
    assert cache.lookup(ids, 0) is not None
    assert cache.lookup(ids, 1) is None  # adapter 1 never stored


def test_lru_leaf_eviction():
    cache = RadixPrefixCache(max_nodes=2, block=2)
    k = np.zeros((1, 1, 8, 1, 2), np.float32)
    cache.store([1, 2, 3], 0, {"k": k, "v": k})
    cache.store([4, 5, 6], 0, {"k": k, "v": k})
    assert cache.lookup([1, 2, 3], 0) is not None  # touch -> MRU
    cache.store([7, 8, 9], 0, {"k": k, "v": k})    # evicts the [4,5] leaf
    assert cache.lookup([4, 5, 6], 0) is None
    assert cache.lookup([1, 2, 3], 0) is not None
    assert cache.lookup([7, 8, 9], 0) is not None
    assert cache.evictions == 1


def test_eviction_is_leaf_first():
    """A deep path evicts from the leaf upward — an interior block with a
    surviving child is never dropped."""
    cache = RadixPrefixCache(max_nodes=3, block=2)
    k = np.zeros((1, 1, 16, 1, 2), np.float32)
    cache.store([1, 2, 3, 4, 5, 6, 7], 0, {"k": k, "v": k})  # 3 chained nodes
    cache.store([9, 9, 9], 0, {"k": k, "v": k})              # over budget
    # the chain's LEAF [5,6] went, its ancestors survived
    assert cache.lookup([1, 2, 3, 4, 0, 0, 0], 0)["len"] == 4
    assert cache.lookup([9, 9, 0], 0) is not None


def test_byte_budget_eviction():
    k = np.zeros((1, 1, 8, 1, 2), np.float32)  # 64 B per 2-token block slice
    per_block = k[:, :, :2].nbytes * 2  # k + v
    cache = RadixPrefixCache(max_nodes=64, block=2, max_bytes=2 * per_block)
    cache.store([1, 2, 3], 0, {"k": k, "v": k})
    cache.store([4, 5, 6], 0, {"k": k, "v": k})
    cache.store([7, 8, 9], 0, {"k": k, "v": k})
    assert cache.total_bytes <= 2 * per_block
    assert len(cache) == 2


# -- unit (paged payloads) ----------------------------------------------------


def _paged_cache(block=4, page_size=2, **kw):
    pool = PagePool(num_pages=32, page_size=page_size, max_slots=4)
    cache = RadixPrefixCache(
        block=block, pool=pool, page_bytes=64, **kw
    )
    return cache, pool


def test_store_pages_takes_refs_and_lookup_pins():
    cache, pool = _paged_cache()
    ids = [1, 2, 3, 4, 5, 6]  # store cap = 4 tokens = 2 pages
    pool.allocate(0, 6)
    pages = pool.slot_pages(0)
    cache.store_pages(ids, 0, pages)
    assert cache.cached_pages == 2
    assert pool.page_refcount(pages[0]) == 2  # slot + cache
    # slot finishes: cache ref keeps the prefix pages alive
    pool.free(0)
    assert pool.page_refcount(pages[0]) == 1
    assert pool.page_refcount(pages[2]) == 0  # unshared tail page freed
    hit = cache.lookup_pages([1, 2, 3, 4, 9, 9], 0)
    assert hit["len"] == 4 and hit["pages"] == pages[:2]
    assert pool.page_refcount(pages[0]) == 2  # pinned for the admission
    cache.release(hit)
    assert pool.page_refcount(pages[0]) == 1


def test_paged_eviction_never_frees_live_slot_pages():
    """Evicting a cached block whose pages a live slot still maps only drops
    the cache's reference — the pages stay allocated until the slot frees."""
    cache, pool = _paged_cache(max_nodes=1)
    pool.allocate(0, 6)
    pages0 = pool.slot_pages(0)
    cache.store_pages([1, 2, 3, 4, 5, 6], 0, pages0)
    # second prompt evicts the first (max_nodes=1) while slot 0 is LIVE
    pool.allocate(1, 6)
    cache.store_pages([7, 8, 9, 10, 11, 12], 0, pool.slot_pages(1))
    assert cache.evictions == 1
    # slot 0's pages were NOT recycled (refcount dropped to the slot's own)
    for p in pages0:
        assert pool.page_refcount(p) == 1
    free_before = pool.free_pages
    pool.free(0)
    assert pool.free_pages == free_before + len(pages0)


def test_pin_run_protects_stored_run_from_eviction():
    """The preemptible batch lane's contract (docs/slo_scheduling.md): a
    pinned run (a preempted request's stored history) survives LRU eviction
    under budget pressure; unpinning re-enables eviction."""
    cache, pool = _paged_cache(max_nodes=1)
    pool.allocate(0, 6)
    pages0 = pool.slot_pages(0)
    cache.store_pages([1, 2, 3, 4, 5, 6], 0, pages0)
    pin = cache.pin_run([1, 2, 3, 4, 5, 6], 0)
    assert pin is not None and pin["len"] == 4
    # over max_nodes with the only other leaf pinned: the NEW store's own
    # nodes are the eviction candidates, the pinned run survives
    pool.allocate(1, 6)
    cache.store_pages([7, 8, 9, 10, 11, 12], 0, pool.slot_pages(1))
    # (5-token query: the final token always computes live, so a 4-token
    # query can match at most 0)
    assert cache.match_len([1, 2, 3, 4, 9]) == 4, "pinned run was evicted"
    # a resume-style lookup still hits and pins pages as usual
    hit = cache.lookup_pages([1, 2, 3, 4, 9, 9], 0)
    assert hit is not None and hit["len"] == 4
    cache.release(hit)
    # unpin: deferred eviction brings the tree back under budget, and the
    # previously pinned run is evictable again
    cache.unpin_run(pin)
    assert len(cache) <= 1
    pool.allocate(2, 6)
    cache.store_pages([13, 14, 15, 16, 17, 18], 0, pool.slot_pages(2))
    assert cache.match_len([1, 2, 3, 4, 9]) == 0, (
        "unpinned run must be evictable"
    )


def test_pin_run_miss_returns_none_and_unpin_tolerates_it():
    cache, pool = _paged_cache()
    assert cache.pin_run([1, 2, 3], 0) is None  # nothing stored
    cache.unpin_run(None)  # no-op by contract


# -- engine (dense backend) ---------------------------------------------------


def test_hit_emits_identical_tokens(parts):
    bundle, params = parts
    prompt = [(i * 7 + 3) % 256 for i in range(40)]  # > one 16-token block

    plain = _engine(bundle, params)
    want = _gen(plain, prompt)
    plain.stop()

    cached = _engine(bundle, params, prefix_cache=8, prefix_block=16)
    first = _gen(cached, prompt)   # miss + store
    second = _gen(cached, prompt)  # hit
    assert cached._prefix.hits == 1
    assert cached._prefix.misses == 1
    cached.stop()
    assert first == want
    assert second == want


def test_shared_system_prefix_divergent_tails(parts):
    bundle, params = parts
    system = [(i * 5 + 1) % 256 for i in range(32)]
    tail_a = [9, 8, 7, 6, 5]
    tail_b = [100, 101, 102]

    plain = _engine(bundle, params)
    want_a = _gen(plain, system + tail_a)
    want_b = _gen(plain, system + tail_b)
    plain.stop()

    cached = _engine(bundle, params, prefix_cache=8, prefix_block=16)
    got_a = _gen(cached, system + tail_a)  # stores the 32-token prefix
    got_b = _gen(cached, system + tail_b)  # hits it, prefills only the tail
    assert cached._prefix.hits >= 1
    cached.stop()
    assert got_a == want_a
    assert got_b == want_b


def test_prefix_composes_with_chunked_prefill(parts):
    bundle, params = parts
    prompt = [(i * 11 + 2) % 256 for i in range(50)]

    plain = _engine(bundle, params)
    want = _gen(plain, prompt)
    plain.stop()

    cached = _engine(
        bundle, params, prefix_cache=8, prefix_block=16, chunked_prefill_size=16
    )
    first = _gen(cached, prompt)
    second = _gen(cached, prompt)
    cached.stop()
    assert first == want
    assert second == want


def test_prefix_composes_with_lora(parts):
    """Adapter-specific prefixes: the same prompt under two adapters must not
    cross-contaminate cached KV."""
    from clearml_serving_tpu.models import lora as lora_lib

    bundle = models.build_model(
        "llama", dict(CFG, lora_rank=4, max_loras=2)
    )
    params = bundle.init(jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(3)
    ad = {}
    for t in ("wq", "wv"):
        d_in, d_out = lora_lib.target_dims(bundle.config, t)
        k1, k2, rng = jax.random.split(rng, 3)
        ad[t] = {
            "a": 0.2 * np.asarray(
                jax.random.normal(k1, (bundle.n_layers, d_in, 4))
            ),
            "b": 0.2 * np.asarray(
                jax.random.normal(k2, (bundle.n_layers, 4, d_out))
            ),
        }
    adapters = {"tuned": ad}
    prompt = [(i * 3 + 5) % 256 for i in range(36)]

    def gen(engine, adapter):
        async def run():
            req = GenRequest(
                prompt_ids=list(prompt), max_new_tokens=6, adapter=adapter
            )
            return [t async for t in engine.generate(req)]

        return asyncio.run(run())

    plain = _engine(bundle, params, lora_adapters=adapters)
    want_base = gen(plain, None)
    want_tuned = gen(plain, "tuned")
    plain.stop()

    cached = _engine(
        bundle, params, lora_adapters=adapters, prefix_cache=8, prefix_block=16
    )
    assert gen(cached, None) == want_base     # miss+store (base key)
    assert gen(cached, "tuned") == want_tuned  # MISS: adapter key differs
    assert gen(cached, "tuned") == want_tuned  # hit on the adapter's entry
    assert gen(cached, None) == want_base      # hit on the base entry
    cached.stop()


# -- engine (paged backend: zero-copy page sharing) ---------------------------


def test_paged_hit_emits_identical_tokens(parts):
    bundle, params = parts
    prompt = [(i * 7 + 3) % 256 for i in range(40)]

    plain = _engine(bundle, params, cache_mode="paged", page_size=4)
    want = _gen(plain, prompt)
    plain.stop()

    cached = _engine(
        bundle, params, cache_mode="paged", page_size=4,
        prefix_cache=64, prefix_block=16,
    )
    first = _gen(cached, prompt)   # miss + zero-copy store
    second = _gen(cached, prompt)  # hit: shared pages map by reference
    assert cached._prefix.hits == 1
    assert cached._prefix.misses == 1
    assert cached._prefix.hit_tokens == 32
    cached.stop()
    assert first == want
    assert second == want


def test_paged_hit_physically_shares_pages(parts):
    """Two concurrent admissions sharing a prefix must point their page
    tables at the SAME pool pages for the shared run (zero KV copies), and
    finishing/eviction must never free a page the other still references."""
    bundle, params = parts
    system = [(i * 5 + 1) % 256 for i in range(32)]

    engine = _engine(
        bundle, params, cache_mode="paged", page_size=4,
        prefix_cache=64, prefix_block=16,
    )
    pool = engine.paged_cache.pool
    # admission 1 stores the 32-token prefix by reference to its own pages
    _gen(engine, system + [9, 8, 7])
    # cache kept the prefix pages alive after the request finished
    stats = engine._prefix.stats()
    assert stats["cached_pages"] >= 32 // 4

    captured = {}
    orig = engine.paged_cache.write_prompt_shared

    def spy(slot, shared_pages, prefix_len, k_tail, v_tail, length):
        captured["pages"] = list(shared_pages)
        captured["prefix_len"] = prefix_len
        captured["slot"] = slot
        return orig(slot, shared_pages, prefix_len, k_tail, v_tail, length)

    engine.paged_cache.write_prompt_shared = spy
    _gen(engine, system + [100, 101, 102])  # hit -> maps shared pages
    assert captured, "paged hit never took the zero-copy mapping path"
    assert captured["prefix_len"] == 32
    # the mapped pages ARE the cached pages (by id — no copies were made)
    hit = engine._prefix.lookup_pages(system + [1, 2, 3], 0)
    assert hit["pages"] == captured["pages"]
    engine._prefix.release(hit)
    # pool accounting intact: every page the cache references is allocated
    for p in captured["pages"]:
        assert pool.page_refcount(p) >= 1
    engine.stop()


def test_paged_prefix_pool_fully_recycles_after_eviction(parts):
    """Dropping every cached node returns the pool to fully-free — no page
    leaks from the ref/unref protocol."""
    bundle, params = parts
    engine = _engine(
        bundle, params, cache_mode="paged", page_size=4,
        prefix_cache=64, prefix_block=16,
    )
    pool = engine.paged_cache.pool
    _gen(engine, [(i * 7 + 3) % 256 for i in range(40)])
    _gen(engine, [(i * 11 + 5) % 256 for i in range(36)])
    assert pool.free_pages < pool.num_pages - 1  # cache holds pages
    # force-evict everything
    engine._prefix.max_nodes = 0
    with engine._prefix._lock:
        engine._prefix._evict_over_budget()
    assert pool.free_pages == pool.num_pages - 1
    engine.stop()


def test_paged_prefix_composes_with_speculation(parts):
    """Prefix sharing + n-gram speculation on the paged engine: exact greedy
    equivalence and no page leaks (spec over-allocation truncates correctly
    around shared pages)."""
    bundle, params = parts
    prompt = [256 % 256] + [10, 20, 30, 10, 20, 30, 10, 20] * 3

    plain = _engine(bundle, params, cache_mode="paged", page_size=4)
    want = _gen(plain, prompt, n=12)
    plain.stop()

    engine = _engine(
        bundle, params, cache_mode="paged", page_size=4,
        prefix_cache=64, prefix_block=16,
        speculation="ngram", spec_k=3, spec_ngram=2,
    )
    assert _gen(engine, prompt, n=12) == want
    assert _gen(engine, prompt, n=12) == want
    assert engine._prefix.hits >= 1
    engine.stop()
