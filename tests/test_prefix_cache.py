"""Automatic prefix caching tests (llm/prefix_cache.py + engine hit path).

Correctness bar: an engine WITH the prefix cache must emit exactly the greedy
tokens of an engine WITHOUT it, for both the first (miss+store) and second
(hit) admission of a shared prompt, and for prompts sharing only a prefix.
"""

import asyncio

import jax
import numpy as np
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
from clearml_serving_tpu.llm.prefix_cache import PrefixKVCache

CFG = {"preset": "llama-tiny", "dtype": "float32"}


@pytest.fixture(scope="module")
def parts():
    bundle = models.build_model("llama", CFG)
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, params


def _engine(bundle, params, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 160)
    kw.setdefault("prefill_buckets", [32, 64, 128])
    kw.setdefault("eos_token_id", None)
    kw.setdefault("decode_steps", 2)
    return LLMEngineCore(bundle, params, **kw)


def _gen(engine, prompt, n=6):
    async def run():
        req = GenRequest(prompt_ids=list(prompt), max_new_tokens=n)
        out = [t async for t in engine.generate(req)]
        return out

    return asyncio.run(run())


# -- unit ---------------------------------------------------------------------


def test_block_alignment_and_lookup():
    cache = PrefixKVCache(max_entries=4, block=4)
    ids = list(range(11))  # prefix cap = floor(10/4)*4 = 8
    assert cache.longest_prefix_len(len(ids)) == 8
    k = np.zeros((2, 1, 16, 2, 4), np.float32)
    cache.store(ids, 0, {"k": k, "v": k})
    hit = cache.lookup(ids, 0)
    assert hit is not None and hit["len"] == 8
    assert hit["k"].shape[2] == 8
    # a prompt sharing only the first 4 tokens still hits at p=4? No entry
    # at 4 was stored (only the longest, 8), so this is a miss.
    assert cache.lookup(ids[:4] + [99, 98, 97, 96, 95], 0) is None
    # but a LONGER prompt sharing the 8-prefix hits
    assert cache.lookup(ids[:8] + [55, 44, 33], 0)["len"] == 8


def test_lora_keys_are_separate():
    cache = PrefixKVCache(max_entries=4, block=2)
    ids = [1, 2, 3, 4, 5]
    k = np.zeros((1, 1, 8, 1, 2), np.float32)
    cache.store(ids, 0, {"k": k, "v": k})
    assert cache.lookup(ids, 0) is not None
    assert cache.lookup(ids, 1) is None  # adapter 1 never stored


def test_lru_eviction():
    cache = PrefixKVCache(max_entries=2, block=2)
    k = np.zeros((1, 1, 8, 1, 2), np.float32)
    cache.store([1, 2, 3], 0, {"k": k, "v": k})
    cache.store([4, 5, 6], 0, {"k": k, "v": k})
    assert cache.lookup([1, 2, 3], 0) is not None  # touch -> MRU
    cache.store([7, 8, 9], 0, {"k": k, "v": k})                # evicts [4,5,6]
    assert cache.lookup([4, 5, 6], 0) is None
    assert cache.lookup([1, 2, 3], 0) is not None
    assert cache.lookup([7, 8, 9], 0) is not None


# -- engine -------------------------------------------------------------------


def test_hit_emits_identical_tokens(parts):
    bundle, params = parts
    prompt = [(i * 7 + 3) % 256 for i in range(40)]  # > one 16-token block

    plain = _engine(bundle, params)
    want = _gen(plain, prompt)
    plain.stop()

    cached = _engine(bundle, params, prefix_cache=8, prefix_block=16)
    first = _gen(cached, prompt)   # miss + store
    second = _gen(cached, prompt)  # hit
    assert cached._prefix.hits == 1
    assert cached._prefix.misses == 1
    cached.stop()
    assert first == want
    assert second == want


def test_shared_system_prefix_divergent_tails(parts):
    bundle, params = parts
    system = [(i * 5 + 1) % 256 for i in range(32)]
    tail_a = [9, 8, 7, 6, 5]
    tail_b = [100, 101, 102]

    plain = _engine(bundle, params)
    want_a = _gen(plain, system + tail_a)
    want_b = _gen(plain, system + tail_b)
    plain.stop()

    cached = _engine(bundle, params, prefix_cache=8, prefix_block=16)
    got_a = _gen(cached, system + tail_a)  # stores the 32-token prefix
    got_b = _gen(cached, system + tail_b)  # hits it, prefills only the tail
    assert cached._prefix.hits >= 1
    cached.stop()
    assert got_a == want_a
    assert got_b == want_b


def test_prefix_composes_with_chunked_prefill(parts):
    bundle, params = parts
    prompt = [(i * 11 + 2) % 256 for i in range(50)]

    plain = _engine(bundle, params)
    want = _gen(plain, prompt)
    plain.stop()

    cached = _engine(
        bundle, params, prefix_cache=4, prefix_block=16, chunked_prefill_size=16
    )
    first = _gen(cached, prompt)
    second = _gen(cached, prompt)
    cached.stop()
    assert first == want
    assert second == want


def test_prefix_composes_with_lora(parts):
    """Adapter-specific prefixes: the same prompt under two adapters must not
    cross-contaminate cached KV."""
    from clearml_serving_tpu.models import lora as lora_lib

    bundle = models.build_model(
        "llama", dict(CFG, lora_rank=4, max_loras=2)
    )
    params = bundle.init(jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(3)
    ad = {}
    for t in ("wq", "wv"):
        d_in, d_out = lora_lib.target_dims(bundle.config, t)
        k1, k2, rng = jax.random.split(rng, 3)
        ad[t] = {
            "a": 0.2 * np.asarray(
                jax.random.normal(k1, (bundle.n_layers, d_in, 4))
            ),
            "b": 0.2 * np.asarray(
                jax.random.normal(k2, (bundle.n_layers, 4, d_out))
            ),
        }
    adapters = {"tuned": ad}
    prompt = [(i * 3 + 5) % 256 for i in range(36)]

    def gen(engine, adapter):
        async def run():
            req = GenRequest(
                prompt_ids=list(prompt), max_new_tokens=6, adapter=adapter
            )
            return [t async for t in engine.generate(req)]

        return asyncio.run(run())

    plain = _engine(bundle, params, lora_adapters=adapters)
    want_base = gen(plain, None)
    want_tuned = gen(plain, "tuned")
    plain.stop()

    cached = _engine(
        bundle, params, lora_adapters=adapters, prefix_cache=8, prefix_block=16
    )
    assert gen(cached, None) == want_base     # miss+store (base key)
    assert gen(cached, "tuned") == want_tuned  # MISS: adapter key differs
    assert gen(cached, "tuned") == want_tuned  # hit on the adapter's entry
    assert gen(cached, None) == want_base      # hit on the base entry
    cached.stop()
