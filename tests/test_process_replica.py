"""Process-backend replica tests (serving/process_replica.py,
docs/replication.md "process backends").

Fast lane (tier-1): the EngineReplica surface pin (the router and group
drive both replica kinds through one duck-typed contract), the control
frame codec, request/error wire round-trips (remaining-budget deadline
convention, error-by-name reconstruction), and the guided-decoding named
rejection.

Slow lane (full suite): real 2-worker fleets — boot, stream, disagg
ship-over-socket, supervised restart after a REAL SIGKILL of the worker
(the process-backend variant of the PR 14 kill-prefill chaos case), and
teardown hygiene."""

import asyncio
import inspect
import os
import signal
import socket
import time

import pytest

from clearml_serving_tpu.errors import (
    DeadlineExceededError,
    EngineOverloadedError,
    EngineUnavailableError,
)
from clearml_serving_tpu.llm import faults
from clearml_serving_tpu.llm.replica import EngineReplica
from clearml_serving_tpu.serving.process_replica import (
    ProcessEngineReplica,
    _err_from_dict,
    _err_to_dict,
    _recv_frame_sock,
    _req_from_wire,
    _req_to_wire,
    _send_frame_sock,
    build_process_fleet,
)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


# -- the shared replica surface ----------------------------------------------


def test_process_replica_pins_the_engine_replica_surface():
    """ProcessEngineReplica deliberately does NOT subclass EngineReplica
    (its worker bootstrap must not import the engine stack before device
    config) — this pin is what keeps the duck-typed contract honest: every
    public attribute the router/group consume exists on both."""
    for name, member in vars(EngineReplica).items():
        if name.startswith("_"):
            continue
        other = inspect.getattr_static(ProcessEngineReplica, name, None)
        assert other is not None, (
            "ProcessEngineReplica is missing EngineReplica surface "
            "member {!r}".format(name)
        )
        if isinstance(member, property):
            assert isinstance(other, property), (
                "{!r} is a property on EngineReplica but not on "
                "ProcessEngineReplica".format(name)
            )
        if inspect.iscoroutinefunction(member):
            assert inspect.iscoroutinefunction(other), (
                "{!r} is async on EngineReplica but not on "
                "ProcessEngineReplica".format(name)
            )


# -- frame codec --------------------------------------------------------------


def test_frame_codec_roundtrip_and_truncation():
    a, b = socket.socketpair()
    try:
        payload = {"id": 3, "op": "ping", "nested": {"x": [1, 2, 3]}}
        _send_frame_sock(a, payload)
        assert _recv_frame_sock(b) == payload
        # truncated frame: length prefix promises more than arrives
        a.sendall(b"\xff\x00\x00\x00{")
        a.close()
        assert _recv_frame_sock(b) is None
    finally:
        b.close()


# -- request wire --------------------------------------------------------------


def test_request_wire_roundtrip_carries_remaining_budgets():
    from clearml_serving_tpu.llm.engine import GenRequest

    request = GenRequest(
        prompt_ids=[1, 2, 3], max_new_tokens=7, temperature=0.5, top_k=11,
        seed=42, logprobs=2, logit_bias={5: -1.5}, stop_token_ids=[9],
        min_tokens=2, priority=1, total_timeout=30.0,
    )
    # a resolved monotonic deadline must cross as REMAINING time, not as
    # the other process's clock reading
    request._deadline = time.monotonic() + 10.0
    request._ship_to = "r1"
    request._shipped = True
    wire = _req_to_wire(request)
    assert 9.0 < wire["total_timeout"] <= 10.0
    assert wire["logit_bias"] == {"5": -1.5}
    rebuilt = _req_from_wire(wire)
    assert rebuilt.prompt_ids == [1, 2, 3]
    assert rebuilt.max_new_tokens == 7
    assert rebuilt.logit_bias == {5: -1.5}
    assert rebuilt.stop_token_ids == [9]
    assert rebuilt.seed == 42
    assert rebuilt._ship_to == "r1"
    # the post-ship marker drives the decode worker's hit/recompute
    # accounting (engine._count_ship_outcome) — it must survive the wire
    assert rebuilt._shipped is True


def test_guided_requests_rejected_with_named_error():
    from clearml_serving_tpu.llm.engine import GenRequest

    request = GenRequest(prompt_ids=[1], max_new_tokens=1)
    request.guided = {"choice": ["a", "b"]}
    with pytest.raises(ValueError, match="guided"):
        _req_to_wire(request)


# -- error wire ----------------------------------------------------------------


def test_error_wire_reconstructs_by_name_with_fields():
    err = _err_from_dict(_err_to_dict(
        EngineOverloadedError("queue full", retry_after=2.5, shed_class="bulk")
    ))
    assert isinstance(err, EngineOverloadedError)
    assert err.retry_after == 2.5 and err.shed_class == "bulk"

    err = _err_from_dict(_err_to_dict(DeadlineExceededError(
        "too slow", stage="ttft"
    )))
    assert isinstance(err, DeadlineExceededError) and err.stage == "ttft"

    assert isinstance(
        _err_from_dict(_err_to_dict(EngineUnavailableError("gone"))),
        EngineUnavailableError,
    )
    # builtins the degradation paths catch by type survive as builtins
    assert isinstance(_err_from_dict({"name": "MemoryError", "message": "x"}),
                      MemoryError)
    # unknown names degrade to RuntimeError, keeping the message
    err = _err_from_dict({"name": "WeirdVendorError", "message": "boom"})
    assert type(err) is RuntimeError and "boom" in str(err)


# -- real fleets (slow lane) ---------------------------------------------------


MODEL = {"arch": "llama", "config": {"preset": "llama-tiny"}, "seed": 0}
ENGINE = {
    "max_batch": 2, "max_seq_len": 64, "cache_mode": "paged",
    "page_size": 16, "num_pages": 64, "prefix_cache": True,
    "prefix_block": 16,
}


def _fleet(**kw):
    kwargs = dict(warmup_mode="off", cpu_devices=2, startup_timeout=180.0)
    kwargs.update(kw)
    return build_process_fleet(MODEL, dict(ENGINE), kw.pop("n", 2) or 2,
                               **kwargs)


async def _collect(group, ids, n=6, **kw):
    from clearml_serving_tpu.llm.engine import GenRequest

    request = GenRequest(prompt_ids=list(ids), max_new_tokens=n, **kw)
    out = []
    async for token in group.generate(request):
        out.append(int(token))
    return out


@pytest.mark.slow
def test_process_fleet_streams_match_inprocess_mono():
    """The 2-process fleet's greedy streams must be byte-identical to a
    monolithic in-process engine built from the same spec — the process
    boundary is a pure transport, never a numerics change."""
    import jax

    from clearml_serving_tpu import models
    from clearml_serving_tpu.llm.engine import LLMEngineCore

    prompts = [list(range(2, 22)), [7, 8, 9, 10]]

    async def mono_arm():
        bundle = models.build_model("llama", {"preset": "llama-tiny"})
        params = bundle.init(jax.random.PRNGKey(0))
        engine = LLMEngineCore(bundle, params, **ENGINE)
        out = [await _collect(engine, ids) for ids in prompts]
        await engine.wait_drained()
        engine.stop()
        return out

    expected = asyncio.run(mono_arm())
    group = _fleet()
    try:
        got = [asyncio.run(_collect(group, ids)) for ids in prompts]
        assert got == expected
        health = group.health()
        blocks = health["replicas"]
        assert set(blocks) == {"r0", "r1"}
        for block in blocks.values():
            proc = block["process"]
            assert proc["backend"] == "process" and proc["alive"]
            assert proc["pid"] > 0 and proc["pid"] != os.getpid()
    finally:
        group.stop()


@pytest.mark.slow
def test_process_fleet_disagg_ships_kv_over_sockets():
    group = _fleet(roles=["prefill", "decode"])
    try:
        toks = asyncio.run(_collect(group, list(range(2, 34))))
        assert len(toks) == 6
        assert group.ship_legs >= 1 and group.ship_leg_failures == 0
    finally:
        group.stop()


@pytest.mark.slow
def test_process_fleet_kill_worker_restarts_with_rewarm():
    """The process-backend variant of the PR 14 kill-prefill chaos case:
    the ``replica.proc.crash`` seam SIGKILLs the r0 worker FOR REAL;
    in-flight work fails over to the sibling, and the bounded
    restart-with-rewarm brings a fresh worker (new pid) back into the
    ring."""
    group = _fleet(heartbeat_interval=0.2, max_restarts=1)
    try:
        baseline = asyncio.run(_collect(group, [3, 4, 5, 6]))
        assert len(baseline) == 6
        replica = group.replicas[0]
        pid0 = replica.engine.pid
        assert pid0 and replica.engine.is_ready
        faults.configure([
            {"point": "replica.proc.crash", "action": "raise",
             "match_token": 0, "times": 1},
        ])
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if replica.restarts >= 1 and replica.engine.is_ready:
                break
            time.sleep(0.1)
        faults.clear()
        assert replica.restarts == 1, "worker was not restarted"
        assert replica.engine.pid != pid0, "restart must be a NEW process"
        # the reborn worker serves: route a stream pinned at it
        from clearml_serving_tpu.llm.engine import GenRequest

        async def pinned():
            request = GenRequest(prompt_ids=[11, 12, 13], max_new_tokens=4)
            request._replica_name = "r0"
            out = []
            async for token in group.generate(request):
                out.append(int(token))
            return out

        assert len(asyncio.run(pinned())) == 4
        # budget is bounded: a second kill (budget 1, already spent)
        # ejects the slot for good
        os.kill(replica.engine.pid, signal.SIGKILL)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if not replica.engine.is_ready:
                break
            time.sleep(0.1)
        assert not replica.engine.is_ready
        # the fleet still serves on the surviving replica
        assert len(asyncio.run(_collect(group, [21, 22, 23]))) == 6
    finally:
        group.stop()


@pytest.mark.slow
def test_process_fleet_stop_reaps_every_worker():
    group = _fleet()
    pids = [r.engine.pid for r in group.replicas]
    assert all(pids)
    group.stop()
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        alive = []
        for pid in pids:
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                continue
            alive.append(pid)
        if not alive:
            break
        time.sleep(0.2)
    assert not alive, "worker pids survived group.stop(): {}".format(alive)
