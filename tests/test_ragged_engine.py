"""Ragged scheduler engine tests (docs/ragged_attention.md): byte-identity
of the token-budget single-launch scheduler against the legacy two-dispatch
path (greedy + seeded, dense + paged, int8 KV, pipeline depths), prefix
cache / speculation composition, chaos behavior mid-ragged-dispatch, and
the committed ``bench.py --ragged-ab`` CPU smoke artifact."""

import asyncio
import json
import pathlib

import jax
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.errors import EngineOverloadedError
from clearml_serving_tpu.llm import faults
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore

REPO = pathlib.Path(__file__).resolve().parents[1]

CFG = {"preset": "llama-tiny", "dtype": "float32"}
QCFG = dict(CFG, kv_quant="int8")

LONG = [(i * 7 + 3) % 250 + 1 for i in range(40)]
SHORT = [5, 9, 2, 17, 33]


@pytest.fixture(scope="module")
def parts():
    bundle = models.build_model("llama", CFG)
    qbundle = models.build_model("llama", QCFG)
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, qbundle, params


def _engine(bundle, params, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("prefill_buckets", [16, 64])
    kw.setdefault("eos_token_id", None)
    kw.setdefault("decode_steps", 2)
    return LLMEngineCore(bundle, params, **kw)


def _staggered(engine, prompts, n=8, seeds=None):
    """Submit prompts 50 ms apart so later admissions overlap live decode
    streams — the mixed prefill+decode batch the ragged scheduler exists
    for. Seeded entries sample at temperature (deterministic per seed)."""

    async def one(i, ids):
        if i:
            await asyncio.sleep(0.05 * i)
        seed = seeds[i] if seeds else None
        req = GenRequest(
            prompt_ids=list(ids), max_new_tokens=n,
            temperature=0.7 if seed is not None else 0.0, seed=seed,
        )
        return [t async for t in engine.generate(req)]

    async def run():
        outs = await asyncio.gather(*(one(i, p) for i, p in enumerate(prompts)))
        await engine.wait_drained()
        return outs

    return asyncio.run(run())


def _ab(bundle, params, prompts, *, seeds=None, n=8, legacy_kw=None,
        ragged_kw=None, **common):
    """(legacy streams, ragged streams) for the same staggered workload.
    The legacy arm chunks EVERY prompt (chunk below the shortest prompt):
    under kv_quant, full prefill attends live precision while chunked
    prefill reads back what it quantized — different caches by design —
    and the ragged scheduler is a chunked path by construction."""
    legacy = _engine(bundle, params, chunked_prefill_size=4,
                     **{**common, **(legacy_kw or {})})
    a = _staggered(legacy, prompts, n=n, seeds=seeds)
    legacy.stop()
    ragged = _engine(bundle, params, scheduler="ragged",
                     step_token_budget=12, **{**common, **(ragged_kw or {})})
    b = _staggered(ragged, prompts, n=n, seeds=seeds)
    stats = ragged.lifecycle_stats()
    ragged.stop()
    return a, b, stats


def test_ragged_ab_dense_greedy_and_seeded(parts, monkeypatch):
    """One mixed batch carries a GREEDY decode stream (row 0, seed None)
    and a SEEDED temperature>0 admission (row 1) — both must replay the
    two-dispatch arm exactly, serial pipeline."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    bundle, _, params = parts
    a, b, stats = _ab(bundle, params, [SHORT, LONG], seeds=[None, 22],
                      cache_mode="dense", legacy_kw={"pipeline_depth": 1},
                      ragged_kw={"pipeline_depth": 1})
    assert a == b
    assert stats["ragged"]["steps"] >= 2           # chunked admission ran
    assert stats["ragged"]["step_rows"]["prefill"] >= 2
    assert stats["ragged"]["step_rows"]["decode"] >= 1  # mixed launches


def test_ragged_ab_paged_greedy_seeded_depth2(parts, monkeypatch):
    """Paged backend at pipeline depth 2: ragged phases drain the
    in-flight queue and reset the device chains; greedy + seeded streams
    still replay the two-dispatch arm exactly (depth 1 is covered by the
    dense cell above and the int8 cells below)."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    bundle, _, params = parts
    a, b, _ = _ab(
        bundle, params, [SHORT, LONG], seeds=[None, 22],
        cache_mode="paged",
        legacy_kw={"pipeline_depth": 2},
        ragged_kw={"pipeline_depth": 2},
    )
    assert a == b


def test_ragged_ab_int8_kv(parts, monkeypatch):
    """int8 KV through the ragged path: chunk K/V quantize via the same
    _kv_store math and the ragged kernel/reference dequantizes like the
    decode path — streams match the (fully chunked) two-dispatch arm on
    BOTH backends."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    _, qbundle, params = parts
    for cache_mode in ("dense", "paged"):
        a, b, _ = _ab(qbundle, params, [SHORT, LONG], cache_mode=cache_mode)
        assert a == b, cache_mode


def test_ragged_prefix_cache_tail_chunks(parts, monkeypatch):
    """Paged radix hits under the ragged scheduler: the shared run maps
    into the slot's table by reference at job start and only the TAIL
    rides the launches as chunk rows — warm streams replay the cold ones
    exactly, under the armed KV sanitizer, leak-free."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    bundle, _, params = parts
    plain = _engine(bundle, params, cache_mode="paged",
                    chunked_prefill_size=4, max_seq_len=160)
    want = _staggered(plain, [LONG], n=6)
    plain.stop()
    cached = _engine(bundle, params, cache_mode="paged", scheduler="ragged",
                     step_token_budget=16, max_seq_len=160,
                     prefix_cache=4, prefix_block=16)
    first = _staggered(cached, [LONG], n=6)
    second = _staggered(cached, [LONG], n=6)
    assert cached._prefix.hits >= 1
    pool = cached.paged_cache.pool
    live = pool.num_pages - 1 - pool.free_pages
    assert live == cached._prefix.cached_pages  # only the cache holds pages
    cached.stop()
    assert first == want
    assert second == want


def test_ragged_speculation_composes(parts):
    """Spec-as-row (ISSUE 13): under the ragged scheduler, speculation is a
    ROW SHAPE — eligible slots ride the mixed launches as q=k+1 verify
    rows instead of draining the pipeline into the legacy serial scan.
    Greedy streams stay identical to the plain ragged engine (the verify
    guarantee), and the launches actually carry spec_verify rows."""
    bundle, _, params = parts
    prompt = [5, 9, 2, 17, 5, 9, 2]
    plain = _engine(bundle, params, cache_mode="paged", scheduler="ragged",
                    step_token_budget=12)
    want = _staggered(plain, [prompt], n=8)
    plain.stop()
    spec = _engine(bundle, params, cache_mode="paged", scheduler="ragged",
                   step_token_budget=12, speculation="ngram", spec_k=2,
                   spec_ngram=2)
    got = _staggered(spec, [prompt], n=8)
    stats = spec.lifecycle_stats()["ragged"]
    spec.stop()
    assert got == want
    assert stats["step_rows"]["spec_verify"] >= 1
    assert stats["spec_acceptance"]["count"] >= 1


def _overlapped(engine, n_a=24, n_b=8, seed_b=22):
    """A greedy decode stream that is PROVABLY mid-flight when a seeded
    long-prompt admission arrives — the mixed launches carry the decode
    row beside the admission's chunk rows for several steps."""

    async def run():
        a = GenRequest(prompt_ids=list(SHORT), max_new_tokens=n_a)
        a_task = asyncio.create_task(_collect_async(engine, a))
        while a.produced < 2:
            await asyncio.sleep(0.005)
        b = GenRequest(
            prompt_ids=list(LONG), max_new_tokens=n_b,
            temperature=0.7 if seed_b is not None else 0.0, seed=seed_b,
        )
        out_b = [t async for t in engine.generate(b)]
        out_a = await a_task
        await engine.wait_drained()
        return [out_a, out_b]

    return asyncio.run(run())


def test_ragged_multistep_byte_identity(parts, monkeypatch):
    """Multi-step decode rows (ISSUE 13 tentpole): q=decode_steps windows
    chain sampled tokens device-side inside ONE mixed launch. Greedy +
    seeded streams at ragged window ∈ {2, 4} equal the q=1 ragged streams
    AND the legacy two-dispatch streams exactly — dense + paged, armed
    sanitizer."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    bundle, _, params = parts
    for cache_mode, depth in (("dense", 1), ("paged", 2)):
        legacy = _engine(bundle, params, chunked_prefill_size=4,
                         cache_mode=cache_mode, pipeline_depth=depth,
                         decode_steps=4)
        want = _overlapped(legacy)
        legacy.stop()
        for q in (1, 2, 4):
            ragged = _engine(bundle, params, scheduler="ragged",
                             step_token_budget=24, cache_mode=cache_mode,
                             pipeline_depth=depth, decode_steps=4,
                             ragged_decode_steps=q)
            got = _overlapped(ragged)
            stats = ragged.lifecycle_stats()["ragged"]
            ragged.stop()
            assert got == want, (cache_mode, depth, q)
            if q > 1:
                # the window actually engaged: some launch advanced a
                # decode row by more than one token
                snap = stats["tokens_per_launch"]
                assert snap["count"] >= 1, (cache_mode, q)
                assert snap["sum_ms"] > snap["count"], (cache_mode, q)


def test_ragged_multistep_int8_kv(parts, monkeypatch):
    """int8 KV through multi-step windows: the chained steps quantize each
    token's K/V via the same _kv_store math as the q=1 path — streams
    match the fully-chunked two-dispatch arm on both backends."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    _, qbundle, params = parts
    for cache_mode in ("dense", "paged"):
        a, b, _ = _ab(
            qbundle, params, [SHORT, LONG], cache_mode=cache_mode,
            legacy_kw={"decode_steps": 4},
            ragged_kw={"decode_steps": 4, "ragged_decode_steps": 4},
        )
        assert a == b, cache_mode


def test_ragged_multistep_logprobs(parts):
    """Per-step logprob entries through a q=4 window equal the q=1 ones
    (the lp triple is chained step-major through the in-launch scan)."""
    bundle, _, params = parts

    def run(q):
        engine = _engine(bundle, params, cache_mode="paged",
                         scheduler="ragged", step_token_budget=24,
                         decode_steps=4, ragged_decode_steps=q)

        async def go():
            a = GenRequest(prompt_ids=list(SHORT), max_new_tokens=6,
                           logprobs=2)
            b = GenRequest(prompt_ids=list(LONG), max_new_tokens=4)

            async def one(req, delay):
                if delay:
                    await asyncio.sleep(delay)
                return [t async for t in engine.generate(req)]

            outs = await asyncio.gather(one(a, 0), one(b, 0.05))
            await engine.wait_drained()
            return outs, list(a.logprob_entries)

        outs, entries = asyncio.run(go())
        engine.stop()
        return outs, entries

    outs1, entries1 = run(1)
    outs4, entries4 = run(4)
    assert outs1 == outs4
    assert entries1 == entries4
    assert len(entries1) == 6


def test_spec_as_row_matches_legacy_spec(parts):
    """Spec-as-row reproduces the legacy serial spec path's accepted
    streams (greedy): the two-dispatch engine's draft-verify scan and the
    ragged engine's in-launch verify rows emit identical tokens, and the
    ragged engine never touches the serial scan path."""
    bundle, _, params = parts
    prompts = [[5, 9, 2, 17, 5, 9, 2], [3, 3, 7, 3, 3, 7, 3]]
    legacy = _engine(bundle, params, cache_mode="paged",
                     chunked_prefill_size=4, speculation="ngram",
                     spec_k=2, spec_ngram=2)
    want = _staggered(legacy, prompts, n=10)
    legacy.stop()
    ragged = _engine(bundle, params, cache_mode="paged", scheduler="ragged",
                     step_token_budget=12, speculation="ngram", spec_k=2,
                     spec_ngram=2)

    def boom(*a, **k):  # the drain-and-scan path must be dead here
        raise AssertionError(
            "legacy serial spec scan ran under the ragged scheduler"
        )

    ragged._dispatch_spec_paged_chunk = boom
    ragged._dispatch_spec_chunk = boom
    got = _staggered(ragged, prompts, n=10)
    stats = ragged.lifecycle_stats()["ragged"]
    ragged.stop()
    assert got == want
    assert stats["step_rows"]["spec_verify"] >= 1


def test_ragged_decode_steps_validation(parts):
    bundle, _, params = parts
    with pytest.raises(ValueError, match="ragged_decode_steps"):
        _engine(bundle, params, scheduler="ragged", step_token_budget=16,
                decode_steps=2, ragged_decode_steps=8)


def test_ragged_budget_validation(parts):
    bundle, _, params = parts
    with pytest.raises(ValueError, match="step_token_budget"):
        _engine(bundle, params, scheduler="ragged", step_token_budget=2)
    with pytest.raises(ValueError, match="scheduler"):
        _engine(bundle, params, scheduler="nope")


def test_ragged_health_and_stats_blocks(parts):
    bundle, _, params = parts
    engine = _engine(bundle, params, scheduler="ragged", step_token_budget=16)
    try:
        assert engine._prefill_gate is None  # the gate is REPLACED
        h = engine.health()
        assert h["scheduler"] == "ragged"
        assert h["ragged"]["step_token_budget"] == 16
        s = engine.lifecycle_stats()["ragged"]
        assert s["budget_utilization"]["count"] == 0
        assert s["step_rows"] == {
            "prefill": 0, "decode": 0, "spec_verify": 0,
        }
        assert s["decode_steps"] == 2        # inherited from decode_steps
        assert s["decode_tokens"] == 0
        assert s["tokens_per_launch"]["count"] == 0
        assert s["spec_acceptance"]["count"] == 0
    finally:
        engine.stop()
    legacy = _engine(bundle, params)
    try:
        assert legacy.lifecycle_stats()["ragged"] is None
        assert legacy.health()["scheduler"] == "two_dispatch"
    finally:
        legacy.stop()


# -- chaos ------------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_fault_mid_ragged_dispatch_isolates_job(parts, monkeypatch):
    """A poison attributed to the ADMISSION row of a mixed launch (fault at
    the dispatch seam, before device work) fails that request structurally;
    the decode rows keep streaming to completion with the exact tokens an
    undisturbed run produces."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    bundle, _, params = parts
    marker = 251  # only in the admitted prompt
    poisoned = list(LONG)
    poisoned[7] = marker

    clean = _engine(bundle, params, cache_mode="paged", scheduler="ragged",
                    step_token_budget=12)
    want = _staggered(clean, [SHORT], n=8)[0]
    clean.stop()

    engine = _engine(bundle, params, cache_mode="paged", scheduler="ragged",
                     step_token_budget=12)
    faults.configure([
        {"point": "engine.decode", "action": "raise",
         "match_token": marker, "times": 1},
    ])
    try:

        async def run():
            a = GenRequest(prompt_ids=list(SHORT), max_new_tokens=8)
            a_task = asyncio.create_task(
                _collect_async(engine, a)
            )
            # wait for the decode stream to be live, then admit the poison
            while a.produced < 2:
                await asyncio.sleep(0.005)
            b = GenRequest(prompt_ids=poisoned, max_new_tokens=4)
            b_err = None
            try:
                async for _ in engine.generate(b):
                    pass
            except Exception as ex:
                b_err = ex
            out_a = await asyncio.wait_for(a_task, 60)
            await engine.wait_drained()
            return out_a, b_err

        out_a, b_err = asyncio.run(run())
        assert b_err is not None          # job failed structurally
        assert out_a == want              # decode rows survived, exactly
        pool = engine.paged_cache.pool
        assert pool.free_pages == pool.num_pages - 1  # nothing leaked
    finally:
        faults.clear()
        engine.stop()


async def _collect_async(engine, req):
    return [t async for t in engine.generate(req)]


@pytest.mark.chaos
def test_chaos_retire_fault_on_spec_row_stays_per_request(parts, monkeypatch):
    """A per-request ``engine.decode.retire`` fault landing on a SPEC
    verify row fails only that request — including the zero-accepted case
    (window == 1, immediate fail): the failed slot's pages free wholesale
    and the retire's truncate pass must skip it instead of raising out of
    the step and failing the whole batch. The sibling stream completes
    byte-identically and nothing leaks."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    bundle, _, params = parts
    marker = 201
    # draft-hostile prompt (no n-gram repeats): acceptance ~1/vocab, so
    # the faulted verify row's window is (almost surely) a single token
    hostile = [marker, 7, 31, 5, 47, 13]
    sibling = [3, 3, 7, 3, 3, 7, 3]

    clean = _engine(bundle, params, cache_mode="paged", scheduler="ragged",
                    step_token_budget=16, speculation="ngram", spec_k=2,
                    spec_ngram=2)
    want = _staggered(clean, [sibling], n=10)[0]
    clean.stop()

    engine = _engine(bundle, params, cache_mode="paged", scheduler="ragged",
                     step_token_budget=16, speculation="ngram", spec_k=2,
                     spec_ngram=2)
    try:

        async def tolerant(req):
            out = []
            try:
                async for t in engine.generate(req):
                    out.append(t)
            except Exception as ex:
                return out, ex
            return out, None

        async def run():
            a = GenRequest(prompt_ids=list(hostile), max_new_tokens=10)
            b = GenRequest(prompt_ids=list(sibling), max_new_tokens=10)
            a_task = asyncio.create_task(tolerant(a))
            b_task = asyncio.create_task(tolerant(b))
            while a.produced < 1 or b.produced < 1:
                await asyncio.sleep(0.005)
            faults.configure([
                {"point": "engine.decode.retire", "action": "raise",
                 "match_token": marker, "times": 1},
            ])
            out_a, a_err = await asyncio.wait_for(a_task, 60)
            out_b, b_err = await asyncio.wait_for(b_task, 60)
            await engine.wait_drained()
            return out_a, a_err, out_b, b_err

        out_a, a_err, out_b, b_err = asyncio.run(run())
        from clearml_serving_tpu.errors import EngineStepError

        assert isinstance(a_err, EngineStepError)   # only the matched row
        assert b_err is None
        assert out_b == want                        # sibling untouched
        assert engine.counters["step_failures"] == 1
        pool = engine.paged_cache.pool
        assert pool.free_pages == pool.num_pages - 1  # nothing leaked
    finally:
        faults.clear()
        engine.stop()


@pytest.mark.chaos
def test_chaos_retire_fault_mid_multistep_window(parts, monkeypatch):
    """A per-request ``engine.decode.retire`` fault landing on a q>1 decode
    row fails ONLY that request, with its PARTIAL window delivered (all
    but the last token — the tokens were already sampled device-side; the
    failure is a host-emission failure): the delivered stream is a strict
    prefix of the undisturbed run, the concurrent admission completes
    untouched, and no pages leak under the armed sanitizer."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    bundle, _, params = parts
    marker = SHORT[0]  # matches the DECODING request

    clean = _engine(bundle, params, cache_mode="paged", scheduler="ragged",
                    step_token_budget=64, decode_steps=4,
                    ragged_decode_steps=4, max_seq_len=160)
    want = _overlapped(clean, n_a=48, n_b=12, seed_b=None)
    clean.stop()

    engine = _engine(bundle, params, cache_mode="paged", scheduler="ragged",
                     step_token_budget=64, decode_steps=4,
                     ragged_decode_steps=4, max_seq_len=160)
    # deterministic window accounting: record the poisoned row's produced
    # count and window size at the retire the fault fires in
    seen = {}
    real_retire = engine._retire_ragged

    def spy(plan, result):
        if faults.active() and not seen:
            for slot, request in enumerate(engine._slot_req):
                if request is not None and marker in request.prompt_ids:
                    if plan["row_steps"][slot] > 1:
                        seen["produced"] = request.produced
                        seen["steps"] = int(plan["row_steps"][slot])
        return real_retire(plan, result)

    engine._retire_ragged = spy
    try:

        async def tolerant(req):
            out = []
            try:
                async for t in engine.generate(req):
                    out.append(t)
            except Exception as ex:
                return out, ex
            return out, None

        async def run():
            a = GenRequest(prompt_ids=list(SHORT), max_new_tokens=48)
            a_task = asyncio.create_task(tolerant(a))
            while a.produced < 2:
                await asyncio.sleep(0.005)
            # the admission makes the loop take ragged steps; with this
            # much budget the decode row rides them as a q=4 window —
            # arm the poison only now, so it lands on a q>1 retire
            b_task = asyncio.create_task(tolerant(
                GenRequest(prompt_ids=list(LONG), max_new_tokens=12)
            ))
            # a outlives the admission (48 tokens): the poisoned retire is
            # guaranteed to carry its decode row
            while not engine._prefill_jobs:
                await asyncio.sleep(0.002)
            faults.configure([
                {"point": "engine.decode.retire", "action": "raise",
                 "match_token": marker, "times": 1},
            ])
            out_a, a_err = await asyncio.wait_for(a_task, 60)
            out_b, b_err = await asyncio.wait_for(b_task, 60)
            await engine.wait_drained()
            return out_a, a_err, out_b, b_err

        out_a, a_err, out_b, b_err = asyncio.run(run())
        from clearml_serving_tpu.errors import EngineStepError

        assert isinstance(a_err, EngineStepError)
        assert b_err is None
        # partial window: tokens before the poisoned launch plus all but
        # the last token of its window, a strict prefix of the clean run
        assert seen, "fault never landed on a q>1 window"
        assert out_a == want[0][: seen["produced"] + seen["steps"] - 1]
        assert seen["steps"] > 1
        assert out_b == want[1]       # the admission was untouched
        pool = engine.paged_cache.pool
        assert pool.free_pages == pool.num_pages - 1  # nothing leaked
    finally:
        faults.clear()
        engine.stop()


@pytest.mark.chaos
def test_chaos_budget_admission_shed(parts, monkeypatch):
    """``engine.admit.budget`` (faults.KNOWN_POINTS): an injected raise as
    a job's chunk is admitted into a step's budget sheds that admission
    with a structured 429; the shed books under reason="budget"."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    bundle, _, params = parts
    engine = _engine(bundle, params, cache_mode="paged", scheduler="ragged",
                     step_token_budget=12)
    faults.configure([
        {"point": "engine.admit.budget", "action": "raise", "times": 1},
    ])
    try:

        async def run():
            req = GenRequest(prompt_ids=list(LONG), max_new_tokens=4)
            try:
                async for _ in engine.generate(req):
                    pass
            except EngineOverloadedError as ex:
                return ex
            return None

        err = asyncio.run(run())
        assert err is not None and err.retry_after is not None
        assert engine._class_sheds.get("budget", {}).get("interactive") == 1
        pool = engine.paged_cache.pool
        assert pool.free_pages == pool.num_pages - 1
        # the engine keeps serving afterwards
        out = _staggered(engine, [SHORT], n=4)
        assert len(out[0]) == 4
    finally:
        faults.clear()
        engine.stop()


def test_ragged_cancel_mid_admission_reclaims(parts, monkeypatch):
    """Client disconnect while the prompt is mid-chunking: the job aborts
    at the next step boundary and the slot's pages free (sanitizer-armed)."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    bundle, _, params = parts
    engine = _engine(bundle, params, cache_mode="paged", scheduler="ragged",
                     step_token_budget=8, max_seq_len=160)

    async def run():
        req = GenRequest(prompt_ids=list(LONG), max_new_tokens=4)
        agen = engine.generate(req)
        task = asyncio.ensure_future(agen.__anext__())
        await asyncio.sleep(0.05)
        req.cancel()
        try:
            await asyncio.wait_for(task, 30)
        except BaseException:
            pass
        await agen.aclose()
        await engine.wait_drained()

    try:
        asyncio.run(run())
        pool = engine.paged_cache.pool
        assert pool.free_pages == pool.num_pages - 1
    finally:
        engine.stop()


def test_ragged_retire_reads_back_only_finishing_rows(parts, monkeypatch):
    """ISSUE-10 satellite: the retire stage must never read back the full
    [R, vocab] logits — the dispatch worker gathers only the FINISHING
    admission rows device-side (None when no job finishes), and the
    streams stay byte-identical to the two-dispatch arm."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    bundle, _, params = parts
    shapes = []
    orig = LLMEngineCore._dispatch_ragged_device

    def spy(self, plan):
        result = orig(self, plan)
        shapes.append(
            None if result["logits"] is None
            else tuple(result["logits"].shape)
        )
        return result

    monkeypatch.setattr(LLMEngineCore, "_dispatch_ragged_device", spy)
    a, b, stats = _ab(bundle, params, [SHORT, LONG], seeds=[None, 22],
                      cache_mode="paged",
                      legacy_kw={"pipeline_depth": 1},
                      ragged_kw={"pipeline_depth": 1})
    assert a == b, "streams must stay byte-identical under the gather"
    assert stats["ragged"]["steps"] >= 2
    assert shapes, "spy never saw a ragged step"
    vocab = bundle.config["vocab_size"]
    # most steps finish no job: nothing is read back at all
    assert any(s is None for s in shapes)
    finished = [s for s in shapes if s is not None]
    assert finished, "at least one step must complete an admission"
    for shape in finished:
        # padded finishing-row count, never the full R=max_batch rows of
        # a non-finishing step — with 2 jobs in this workload the padded
        # gather is at most 2 rows
        assert shape[1] == vocab
        assert shape[0] <= 2


# -- committed CPU smoke artifact -------------------------------------------

def test_ragged_ab_artifact_schema():
    """benchmarks/RAGGED_AB_cpu.json (committed by ``bench.py --ragged-ab``)
    carries the acceptance headlines: byte-identical streams across
    schedulers and decode-stall-during-admission STRICTLY below the
    two-dispatch arm (ISSUE 9), plus the ISSUE-13 arms — the
    ``--decode-steps`` q=1-vs-q=4 A/B (dispatches-per-decode-token < 0.5
    at q=4, tok/s no worse than q=1, identical streams) and spec-as-row
    vs the legacy serial scan (identical streams, acceptance measured)."""
    path = REPO / "benchmarks" / "RAGGED_AB_cpu.json"
    row = json.loads(path.read_text())
    assert row["metric"] == "llm_ragged_scheduler_ab_cpusmoke"
    assert row["identical_tokens"] is True
    assert (
        row["ragged"]["decode_stall_ms"]
        < row["two_dispatch"]["decode_stall_ms"]
    )
    for arm in ("two_dispatch", "ragged"):
        assert row[arm]["tok_s"] > 0
        assert row[arm]["admit_ttft_ms"] > 0
        assert row[arm]["ttft_p99_ms"] >= row[arm]["ttft_p50_ms"]
        assert 0 < row[arm]["occupancy"] <= row["batch"]
    # ISSUE 13: multi-step decode rows kill the per-launch decode bubble
    ds = row["decode_steps_ab"]
    q = ds["decode_steps"]
    assert ds["identical_tokens"] is True
    assert ds["q{}".format(q)]["dispatches_per_decode_token"] < 0.5
    assert (
        ds["q{}".format(q)]["dispatches_per_decode_token"]
        < ds["q1"]["dispatches_per_decode_token"]
    )
    assert ds["q{}".format(q)]["tok_s"] >= ds["q1"]["tok_s"]
    # ISSUE 13: spec rides mixed launches as verify rows — stream
    # identity with the legacy serial scan is the certified property
    # (the CPU tok/s comparison is reference-path-bound by construction;
    # see run_spec_row_ab's docstring)
    sr = row["spec_row_ab"]
    assert sr["identical_tokens"] is True
    assert sr["spec_as_row"]["spec_verify_rows"] >= 1
    assert 0 <= sr["spec_as_row"]["acceptance_mean"] <= 1
