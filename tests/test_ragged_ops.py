"""Ragged paged attention kernel tests (docs/ragged_attention.md): the
mixed prefill+decode Pallas kernel (interpret mode) against the ragged XLA
reference, the ragged reference against the per-row decode/dense references,
and the layout helper's q-block contract."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from clearml_serving_tpu.ops.paged_attention import (
    paged_attention_xla,
    ragged_layout,
    ragged_paged_attention,
    ragged_paged_attention_xla,
)


def _quantize_pool(pool):
    """Per-(token, head) symmetric int8, mirroring models/llama._kv_store."""
    x = np.asarray(pool, np.float32)
    absmax = np.abs(x).max(axis=-1)
    scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.round(x / scale[..., None]), -127, 127).astype(np.int8)
    return jnp.asarray(q), jnp.asarray(scale)


def _setup(key, *, rows=4, hkv=2, g=2, d=64, page=16, pages_per_seq=6,
           row_lens=(1, 5, 1, 12), kv_extra=(7, 0, 30, 0), q_block=8):
    """Build a mixed batch: row_lens[r] query tokens per row (1 = decode),
    kv_lens = history + chunk. Returns the full operand set plus the
    layout metadata."""
    ks = jax.random.split(key, 3)
    n_pages = rows * pages_per_seq + 1
    k_pool = jax.random.normal(ks[0], (hkv, n_pages, page, d), jnp.float32)
    v_pool = jax.random.normal(ks[1], (hkv, n_pages, page, d), jnp.float32)
    page_table = np.zeros((rows, pages_per_seq), np.int32)
    for r in range(rows):
        page_table[r] = 1 + r * pages_per_seq + np.arange(pages_per_seq)
    row_lens = np.asarray(row_lens, np.int32)
    kv_lens = row_lens + np.asarray(kv_extra, np.int32)
    assert kv_lens.max() <= pages_per_seq * page
    starts, block_rows, block_q0, t_pad = ragged_layout(
        row_lens, q_block=q_block
    )
    q = jax.random.normal(ks[2], (t_pad, hkv, g, d), jnp.float32)
    return (
        q, k_pool, v_pool, jnp.asarray(page_table), jnp.asarray(kv_lens),
        jnp.asarray(starts), jnp.asarray(row_lens),
        jnp.asarray(block_rows), jnp.asarray(block_q0),
    )


def test_ragged_layout_alignment():
    starts, block_rows, block_q0, t_pad = ragged_layout([1, 5, 0, 12], 8)
    assert t_pad % 8 == 0
    # every row starts on a q-block boundary; idle rows own no block
    assert all(int(s) % 8 == 0 for s in starts)
    assert list(block_rows) == [0, 1, 3, 3]
    assert list(block_q0) == [0, 0, 0, 8]
    # fixed `total` pads with unowned blocks (static engine shapes)
    _, br2, _, t2 = ragged_layout([1, 5, 0, 12], 8, total=48)
    assert t2 == 48 and list(br2[4:]) == [-1, -1]
    with pytest.raises(ValueError):
        ragged_layout([64], 8, total=32)


def test_ragged_xla_decode_rows_match_decode_reference():
    """All-decode ragged batch == the decode reference, row for row."""
    args = _setup(jax.random.PRNGKey(0), row_lens=(1, 1, 1, 1),
                  kv_extra=(4, 17, 30, 0))
    (q, k_pool, v_pool, page_table, kv_lens, starts, row_lens,
     _br, _bq) = args
    out = ragged_paged_attention_xla(
        q, k_pool, v_pool, page_table, kv_lens, starts, row_lens
    )
    # the decode reference consumes one query per row
    q_rows = jnp.stack([q[int(s)] for s in starts])        # [R, Hkv, G, D]
    ref = paged_attention_xla(q_rows, k_pool, v_pool, page_table, kv_lens)
    for r, s in enumerate(np.asarray(starts)):
        np.testing.assert_allclose(
            np.asarray(out[int(s)]), np.asarray(ref[r]), rtol=1e-6, atol=1e-6
        )


def test_ragged_xla_prefill_row_matches_dense_causal():
    """A prefill row's chunk must see its history + its own causal
    triangle — checked against an explicit dense softmax."""
    args = _setup(
        jax.random.PRNGKey(1), rows=1, hkv=2, g=2, d=32, page=8,
        pages_per_seq=4, row_lens=(6,), kv_extra=(10,),
    )
    (q, k_pool, v_pool, page_table, kv_lens, starts, row_lens,
     _br, _bq) = args
    out = ragged_paged_attention_xla(
        q, k_pool, v_pool, page_table, kv_lens, starts, row_lens
    )
    kv_len, row_len = int(kv_lens[0]), int(row_lens[0])
    base = kv_len - row_len
    pages = np.asarray(page_table[0])
    k = np.asarray(k_pool[:, pages]).reshape(2, -1, 32)
    v = np.asarray(v_pool[:, pages]).reshape(2, -1, 32)
    for i in range(row_len):
        bound = base + i + 1
        qi = np.asarray(q[i])                               # [Hkv, G, D]
        for h in range(2):
            scores = qi[h] @ k[h, :bound].T * (32 ** -0.5)  # [G, bound]
            p = np.exp(scores - scores.max(axis=-1, keepdims=True))
            p = p / p.sum(axis=-1, keepdims=True)
            want = p @ v[h, :bound]
            np.testing.assert_allclose(
                np.asarray(out[i, h]), want, rtol=1e-5, atol=1e-5
            )


@pytest.mark.parametrize("page", [16, 32])
@pytest.mark.parametrize("pages_per_block", [1, 2, 4])
def test_ragged_kernel_interpret_matches_xla(page, pages_per_block):
    """Mixed row phases x page sizes x DMA block sizes, including a partial
    final chunk (kv not page-aligned) and an idle row."""
    args = _setup(
        jax.random.PRNGKey(2), rows=5, hkv=2, g=2, d=64, page=page,
        pages_per_seq=4, row_lens=(1, 9, 1, 13, 0),
        kv_extra=(page * 2 + 3, 5, 0, 7, 0),
    )
    (q, k_pool, v_pool, page_table, kv_lens, starts, row_lens,
     block_rows, block_q0) = args
    ref = ragged_paged_attention_xla(
        q, k_pool, v_pool, page_table, kv_lens, starts, row_lens
    )
    out = ragged_paged_attention(
        q, k_pool, v_pool, page_table, kv_lens, starts, row_lens,
        block_rows=block_rows, block_q0=block_q0,
        pages_per_block=pages_per_block, interpret=True,
    )
    # compare only owned tokens (unowned blocks hold zeros in both)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


@pytest.mark.parametrize("page", [16, 32])
def test_ragged_kernel_int8_interpret_matches_xla(page):
    """int8 pools + pre-gathered per-row scale operands through the ragged
    kernel (interpret) against the ragged XLA dequant reference."""
    args = _setup(
        jax.random.PRNGKey(3), rows=4, hkv=2, g=2, d=64, page=page,
        pages_per_seq=4, row_lens=(1, 7, 1, 10),
        kv_extra=(page + 1, 3, 2 * page, 0),
    )
    (q, k_pool, v_pool, page_table, kv_lens, starts, row_lens,
     block_rows, block_q0) = args
    k8, ks = _quantize_pool(k_pool)
    v8, vs = _quantize_pool(v_pool)
    ref = ragged_paged_attention_xla(
        q, k8, v8, page_table, kv_lens, starts, row_lens, ks, vs
    )
    out = ragged_paged_attention(
        q, k8, v8, page_table, kv_lens, starts, row_lens,
        block_rows=block_rows, block_q0=block_q0,
        k_scale=ks, v_scale=vs, pages_per_block=2, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )
    # dequant correctness vs a dequantized-pool run (same tolerance class
    # as the decode kernel's int8 test)
    kd = (np.asarray(k8, np.float32) * np.asarray(ks)[..., None])
    vd = (np.asarray(v8, np.float32) * np.asarray(vs)[..., None])
    dense = ragged_paged_attention_xla(
        q, jnp.asarray(kd), jnp.asarray(vd), page_table, kv_lens, starts,
        row_lens,
    )
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(dense), rtol=2e-5, atol=2e-5
    )


def test_ragged_int8_requires_scales():
    args = _setup(jax.random.PRNGKey(4), row_lens=(1, 3, 1, 1))
    (q, k_pool, v_pool, page_table, kv_lens, starts, row_lens,
     _br, _bq) = args
    k8, _ks = _quantize_pool(k_pool)
    v8, _vs = _quantize_pool(v_pool)
    with pytest.raises(ValueError):
        ragged_paged_attention(
            q, k8, v8, page_table, kv_lens, starts, row_lens, interpret=True
        )


def test_ragged_without_block_map_falls_back_to_xla():
    """No block metadata -> the XLA reference (identical output), never a
    kernel crash: jitted callers may omit the host-only layout."""
    args = _setup(jax.random.PRNGKey(5), row_lens=(1, 4, 1, 1))
    (q, k_pool, v_pool, page_table, kv_lens, starts, row_lens,
     _br, _bq) = args
    a = ragged_paged_attention(
        q, k_pool, v_pool, page_table, kv_lens, starts, row_lens,
        interpret=True,
    )
    b = ragged_paged_attention_xla(
        q, k_pool, v_pool, page_table, kv_lens, starts, row_lens
    )
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
