"""Replica fleet: prefix-affine router + engine group (docs/replication.md).

Unit half: routing math (affinity keys, HRW ranking), the ring's
eject/re-warm/readmit lifecycle and the fleet brownout door on stub
replicas. Integration half (chaos marker, real engines on CPU): repeated
conversations stick to one replica's radix cache, a watchdog-tripped
replica drains its streams to the sibling with zero user-visible 503s and
byte-identical tokens, and a fault-forced ``router.eject`` re-admits
through the warmup gate.
"""

import asyncio
import time

import jax
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.errors import (
    EngineOverloadedError,
    EngineUnavailableError,
)
from clearml_serving_tpu.llm import faults
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
from clearml_serving_tpu.llm.replica import EngineReplica, ReplicaGroup
from clearml_serving_tpu.serving.replica_router import (
    ReplicaRouter,
    affinity_key,
    hrw_order,
)

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(autouse=True)
def armed_sanitizer(monkeypatch):
    """Every engine this suite builds runs with the KV sanitizer armed:
    failover resumes and ejection drains must keep page accounting
    balanced, not merely produce the right tokens."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")


# -- routing math --------------------------------------------------------------


def test_affinity_key_stable_as_conversation_grows():
    # once the history is past the anchor depth (max_blocks * block), the
    # block-aligned head — and so the key — never changes: one
    # conversation, one replica, for life
    base = [(7 + i * 13) % 200 + 1 for i in range(80)]
    keys = {
        affinity_key(base, block=16),
        affinity_key(base + [5] * 16, block=16),
        affinity_key(base + [9] * 40, block=16),
    }
    assert len(keys) == 1


def test_affinity_key_caps_at_max_blocks():
    long = list(range(1, 400))
    assert affinity_key(long, block=16, max_blocks=4) == affinity_key(
        long[:64] + [0] * 300, block=16, max_blocks=4
    )


def test_affinity_key_differs_across_conversations():
    a = [(1 + i * 13) % 200 + 1 for i in range(64)]
    b = [(2 + i * 13) % 200 + 1 for i in range(64)]
    assert affinity_key(a, block=16) != affinity_key(b, block=16)


def test_affinity_key_short_prompts_hash_whole():
    # prompts under one block have no storable prefix: hash everything so
    # one-shot work spreads over the ring instead of pinning to one member
    assert affinity_key([1, 2, 3], block=16) != affinity_key(
        [1, 2, 4], block=16
    )


def test_hrw_order_is_deterministic_and_minimally_disruptive():
    names = ["r0", "r1", "r2", "r3"]
    key = affinity_key(list(range(40)), block=16)
    order = hrw_order(key, names)
    assert order == hrw_order(key, names)
    # rendezvous property: dropping one member preserves the relative
    # order of the survivors (only the removed member's keys move)
    survivors = [i for i in order if names[i] != "r1"]
    reduced = hrw_order(key, ["r0", "r2", "r3"])
    mapped = [["r0", "r2", "r3"][i] for i in reduced]
    assert [names[i] for i in survivors] == mapped


# -- router over stub replicas -------------------------------------------------


class StubReplica:
    def __init__(self, index, ready=True, warmed=True, depth=0, stage=0,
                 warm_delay_sweeps=0):
        self.index = index
        self.name = "r{}".format(index)
        self.engine_ready = ready
        self.warmed = warmed
        self.queue_depth = depth
        self.brownout_stage = stage
        self.warm_calls = 0
        self._warm_delay = warm_delay_sweeps
        self.warming = False

    def invalidate_warm(self):
        self.warmed = False

    def begin_warm(self):
        self.warm_calls += 1
        if self._warm_delay > 0:
            self._warm_delay -= 1
            self.warming = True
        else:
            self.warming = False
            self.warmed = True


def _req(ids, priority="interactive"):
    return GenRequest(prompt_ids=list(ids), priority=priority)


def _conv(seed, n=48):
    return [(seed * 29 + i * 7) % 200 + 1 for i in range(n)]


def test_pick_is_affine_and_sticky():
    router = ReplicaRouter([StubReplica(0), StubReplica(1)], block=16)
    ids = _conv(3)
    first, route = router.pick(_req(ids))
    assert route == "affine"
    for _ in range(5):
        replica, route = router.pick(_req(ids + [9] * 7))
        assert replica is first and route == "affine"


def test_pick_rebalances_when_affine_member_is_out():
    a, b = StubReplica(0), StubReplica(1)
    router = ReplicaRouter([a, b], block=16)
    ids = _conv(3)
    affine = router.order_for(ids)[0]
    other = b if affine is a else a
    affine.engine_ready = False
    replica, route = router.pick(_req(ids))
    assert replica is other and route == "rebalance"
    assert router.stats()["ejections"][affine.name] == 1
    # recovery: back into the ring, affinity restored
    affine.engine_ready = True
    router.sweep()
    replica, route = router.pick(_req(ids))
    assert replica is affine and route == "affine"
    assert router.stats()["readmissions"][affine.name] == 1


def test_pick_spills_on_pressure_gap_but_not_on_tie():
    a, b = StubReplica(0), StubReplica(1)
    router = ReplicaRouter([a, b], block=16, spill_brownout_stage=2)
    ids = _conv(5)
    affine = router.order_for(ids)[0]
    other = b if affine is a else a
    affine.brownout_stage = 2
    replica, route = router.pick(_req(ids))
    assert replica is other and route == "spill"
    # a tie is NOT a spill: prefix warmth wins unless the alternative is
    # strictly less pressured
    other.brownout_stage = 2
    replica, route = router.pick(_req(ids))
    assert replica is affine and route == "affine"


def test_pick_spills_on_queue_depth_bound():
    a, b = StubReplica(0), StubReplica(1)
    router = ReplicaRouter([a, b], block=16, spill_queue_depth=4)
    ids = _conv(5)
    affine = router.order_for(ids)[0]
    affine.queue_depth = 4
    replica, route = router.pick(_req(ids))
    assert replica is not affine and route == "spill"


def test_fleet_brownout_sheds_best_effort_at_the_door():
    a, b = StubReplica(0, stage=3), StubReplica(1, stage=3)
    router = ReplicaRouter([a, b], block=16, fleet_shed_stage=3)
    with pytest.raises(EngineOverloadedError) as ei:
        router.pick(_req(_conv(1), priority="best_effort"))
    assert ei.value.shed_class == "best_effort"
    assert router.stats()["fleet_sheds"]["best_effort"] == 1
    # interactive work still routes under fleet brownout
    replica, _ = router.pick(_req(_conv(1)))
    assert replica in (a, b)
    # one member recovering (stage < shed stage) reopens the door:
    # fleet stage = MIN over members — redirect, don't shed
    b.brownout_stage = 0
    replica, _ = router.pick(_req(_conv(1), priority="best_effort"))
    assert replica in (a, b)


def test_empty_ring_raises_unavailable():
    a = StubReplica(0, ready=False)
    router = ReplicaRouter([a], block=16)
    with pytest.raises(EngineUnavailableError):
        router.pick(_req(_conv(2)))


def test_injected_pick_fault_falls_to_next_member():
    a, b = StubReplica(0), StubReplica(1)
    router = ReplicaRouter([a, b], block=16)
    ids = _conv(7)
    affine = router.order_for(ids)[0]
    faults.configure([{"point": "router.pick", "times": 1}])
    replica, route = router.pick(_req(ids))
    assert replica is not affine and route == "rebalance"
    # spec exhausted: the next pick is affine again
    replica, route = router.pick(_req(ids))
    assert replica is affine and route == "affine"


def test_forced_eject_gates_readmission_through_warmup():
    a = StubReplica(0)
    b = StubReplica(1, warm_delay_sweeps=2)
    router = ReplicaRouter([a, b], block=16)
    assert router.ring_size == 2
    faults.configure([
        {"point": "router.eject", "match_token": 1, "times": -1},
    ])
    router.sweep()
    assert router.ring() == ["r0"]
    assert router.stats()["ejections"]["r1"] == 1
    faults.clear()
    # re-admission runs through the warmup gate: b needs 2 sweeps of
    # "warming" before the gate opens, and it stays OUT of the ring until
    # the sweep AFTER it warms — a cold replica never takes serve traffic
    router.sweep()
    assert router.ring() == ["r0"] and b.warm_calls == 1
    router.sweep()
    assert router.ring() == ["r0"]
    router.sweep()  # gate opens during this sweep...
    assert router.ring() == ["r0"]
    router.sweep()  # ...and membership follows on the next
    assert "r1" in router.ring()
    assert router.stats()["readmissions"]["r1"] == 1


# -- real-engine integration ---------------------------------------------------


@pytest.fixture(scope="module")
def parts():
    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, params


def _make_group(bundle, params, n=2, **overrides):
    cfg = dict(
        max_batch=2, max_seq_len=128, prefill_buckets=[16, 32, 64],
        eos_token_id=None, decode_steps=1, cache_mode="paged",
        page_size=16, prefix_cache=64, prefix_block=16, max_pending=8,
    )
    cfg.update(overrides)
    engines = [
        LLMEngineCore(bundle, params, replica="r{}".format(i), **cfg)
        for i in range(n)
    ]
    return ReplicaGroup(engines)


async def _collect(group, ids, n=4, **kw):
    request = GenRequest(prompt_ids=list(ids), max_new_tokens=n, **kw)
    out = []
    async for token in group.generate(request):
        out.append(int(token))
    return out, request


def test_conversation_sticks_to_one_replica_and_hits_its_cache(parts):
    bundle, params = parts
    group = _make_group(bundle, params)
    try:
        async def run():
            conv = _conv(11, 40)
            homes = set()
            for turn in range(3):
                ids = conv + [3 + turn] * (turn + 1)
                _, req = await _collect(group, ids)
                homes.add(req._replica_name)
            await group.wait_drained()
            return homes

        homes = asyncio.run(run())
        assert len(homes) == 1, homes
        home = next(
            r for r in group.replicas if r.name == next(iter(homes))
        )
        other = next(r for r in group.replicas if r is not home)
        # turns 2..3 replayed the stored prefix from the HOME replica's
        # radix tree; the sibling never saw the conversation
        assert home.engine._prefix.hits >= 2
        assert (
            other.engine._prefix is None
            or other.engine._prefix.hits == 0
        )
        routes = group.router.stats()["requests"]
        assert routes[home.name]["affine"] == 3
    finally:
        group.stop()


def test_watchdog_trip_drains_streams_to_sibling_byte_identically(parts):
    """The chaos contract end to end: a stalled replica trips its
    watchdog mid-stream; its streams RESUME on the sibling (no
    user-visible 503), byte-identical for greedy decoding; untouched
    conversations never notice; the tripped replica re-enters the ring
    after recovery."""
    bundle, params = parts
    group = _make_group(bundle, params, watchdog_interval=0.3)
    try:
        async def run():
            prompts = {}
            seed = 0
            while len(prompts) < 2:
                p = _conv(seed, 40)
                prompts.setdefault(
                    group.router.order_for(p)[0].name, p
                )
                seed += 1
            victim_prompt = prompts["r1"][:-1] + [251]
            base_victim, _ = await _collect(group, victim_prompt, 12)
            base_other, _ = await _collect(group, prompts["r0"], 12)
            await group.wait_drained()
            faults.configure([
                {"point": "engine.decode.stall", "action": "delay",
                 "delay": 1.2, "times": 1, "match_token": 251},
            ])
            v_task = asyncio.create_task(
                _collect(group, victim_prompt, 12)
            )
            u_task = asyncio.create_task(
                _collect(group, prompts["r0"], 12)
            )
            (v_out, v_req), (u_out, _) = await asyncio.gather(
                v_task, u_task
            )
            faults.clear()
            t0 = time.monotonic()
            while time.monotonic() - t0 < 30:
                group.router.sweep()
                if group.router.ring_size == 2:
                    break
                await asyncio.sleep(0.02)
            await group.wait_drained()
            return base_victim, base_other, v_out, u_out, v_req

        base_victim, base_other, v_out, u_out, v_req = asyncio.run(run())
        # the victim's stream failed over and CONTINUED byte-identically
        assert v_out == base_victim
        assert v_req._replica_name == "r0"
        assert group.failovers >= 1
        # the untouched conversation never noticed
        assert u_out == base_other
        # the tripped replica recovered, re-warmed, and rejoined
        assert group.router.ring_size == 2
        stats = group.router.stats()
        assert stats["ejections"]["r1"] >= 1
        assert stats["readmissions"]["r1"] >= 1
        assert group.replicas[1].engine.counters["watchdog_trips"] >= 1
    finally:
        group.stop()


def test_forced_eject_reroutes_and_rewarms_through_gate(parts, monkeypatch):
    """Injected ``router.eject`` (the chaos seam): the ejected replica's
    conversations rebalance to the sibling with zero errors; clearing the
    fault re-admits it through the warmup gate (run_warmup called)."""
    bundle, params = parts
    warm_calls = []

    async def fake_warmup(engine, full=True, extra_prompts=None,
                          fence=True):
        warm_calls.append((engine, full, fence))
        return {"requests": 0, "cow_buckets": 0, "fenced": False}

    import clearml_serving_tpu.llm.warmup as warmup_mod

    monkeypatch.setattr(warmup_mod, "run_warmup", fake_warmup)
    engines = [
        LLMEngineCore(
            bundle, params, replica="r{}".format(i), max_batch=2,
            max_seq_len=128,
            prefill_buckets=[16, 32, 64], eos_token_id=None,
            cache_mode="paged", page_size=16, prefix_cache=64,
            prefix_block=16, max_pending=8,
        )
        for i in range(2)
    ]
    group = ReplicaGroup(engines, warmup_mode="startup")
    # gates start closed under warmup_mode=startup: open them directly
    for replica in group.replicas:
        replica.warmed = True
    group.router.sweep()
    try:
        async def run():
            # a conversation homed on r1
            seed = 0
            while True:
                p = _conv(seed, 40)
                if group.router.order_for(p)[0].name == "r1":
                    break
                seed += 1
            base, _ = await _collect(group, p, 6)
            await group.wait_drained()
            faults.configure([
                {"point": "router.eject", "match_token": 1, "times": -1},
            ])
            out, req = await _collect(group, p, 6)
            assert req._replica_name == "r0"
            assert out == base  # greedy: identical tokens on the sibling
            assert group.router.ring() == ["r0"]
            faults.clear()
            t0 = time.monotonic()
            while time.monotonic() - t0 < 10:
                group.router.sweep()
                if group.router.ring_size == 2:
                    break
                await asyncio.sleep(0.01)
            await group.wait_drained()
            return out

        asyncio.run(run())
        assert group.router.ring_size == 2
        # re-admission went THROUGH the warmup gate
        assert any(e is engines[1] for e, _, _ in warm_calls)
        routes = group.router.stats()["requests"]
        assert routes["r0"]["rebalance"] >= 1
    finally:
        group.stop()


def test_group_health_aggregates_ready_iff_ring_nonempty(parts):
    bundle, params = parts
    group = _make_group(bundle, params)
    health = group.health()
    assert health["ready"] and health["ring_size"] == 2
    assert set(health["replicas"]) == {"r0", "r1"}
    assert health["replicas"]["r0"]["replica"] == "r0"
    assert health["router"]["replicas"] == 2
    # one replica down: still ready (>= 1 ring member)
    group.replicas[1].engine.stop()
    health = group.health()
    assert health["ready"] and health["ring_size"] == 1
    assert health["replicas"]["r1"]["ring_state"] == "ejected"
    # all down: not ready
    group.replicas[0].engine.stop()
    health = group.health()
    assert not health["ready"] and health["ring_size"] == 0
    # lifecycle_stats mirrors the fleet view with per-replica blocks
    stats = group.lifecycle_stats()
    assert stats["ready"] == 0
    assert set(stats["replicas"]) == {"r0", "r1"}
    assert stats["replicas"]["r0"]["replica"] == "r0"


def test_check_admission_pins_route_for_generate(parts):
    bundle, params = parts
    group = _make_group(bundle, params)
    try:
        async def run():
            ids = _conv(21, 40)
            request = GenRequest(prompt_ids=ids, max_new_tokens=2)
            group.validate(request)
            group.check_admission(request)
            pinned = request._replica_name
            out = []
            async for token in group.generate(request):
                out.append(token)
            await group.wait_drained()
            return pinned, request._replica_name, out

        pinned, final, out = asyncio.run(run())
        assert pinned == final and len(out) == 2
    finally:
        group.stop()


def test_resume_clone_carries_remaining_deadline_budget():
    """Failover must not reset per-request budgets: the clone's timeouts
    derive from the ORIGINAL request's resolved monotonic deadlines, so a
    request near its total budget cannot run ~2x it across a trip."""
    import time as _time

    request = GenRequest(prompt_ids=[1, 2, 3], max_new_tokens=8,
                         total_timeout=10.0, ttft_timeout=5.0,
                         queue_timeout=2.0)
    now = _time.monotonic()
    request._deadline = now + 1.0       # 9s of a 10s budget already spent
    request._ttft_deadline = now + 0.5
    request._queue_deadline = now + 0.2
    clone = ReplicaGroup._resume_clone(request, [7, 8])
    assert clone.total_timeout is not None and clone.total_timeout <= 1.1
    # tokens already emitted: TTFT/queue phases passed — only the total
    # budget bounds the resume
    assert clone.ttft_timeout is None and clone.queue_timeout is None
    # pre-first-token failover keeps the remaining TTFT/queue budgets
    clone0 = ReplicaGroup._resume_clone(request, [])
    assert clone0.ttft_timeout is not None and clone0.ttft_timeout <= 0.6
    assert clone0.queue_timeout is not None and clone0.queue_timeout <= 0.3
    # an elapsed budget floors at a fail-fast-at-admission value
    request._deadline = now - 5.0
    assert ReplicaGroup._resume_clone(request, [7]).total_timeout == 0.05


def test_failover_does_not_overshoot_max_new_tokens(parts):
    """A replica that fails AFTER delivering every requested token (trip
    between the last token and the finish marker) finishes the stream
    normally — a resume would overshoot max_new_tokens."""
    from clearml_serving_tpu.errors import EngineStuckError

    bundle, params = parts
    group = _make_group(bundle, params)
    try:
        async def run():
            ids = _conv(31, 40)
            home = group.router.order_for(ids)[0]
            orig = home.engine.generate

            async def flaky(req):
                async for token in orig(req):
                    yield token
                raise EngineStuckError("tripped after the last token")

            home.engine.generate = flaky
            try:
                out = []
                request = GenRequest(prompt_ids=ids, max_new_tokens=4)
                async for token in group.generate(request):
                    out.append(token)
            finally:
                home.engine.generate = orig
            await group.wait_drained()
            return out

        out = asyncio.run(run())
        assert len(out) == 4
        assert group.failovers == 0
    finally:
        group.stop()


def test_penalty_requests_do_not_fail_over(parts):
    """Failover eligibility matches the preemption lane: a history-as-
    prompt resume resets the device penalty histogram, so penalty-bearing
    requests propagate their replica's error instead of resuming wrong."""
    from clearml_serving_tpu.errors import EngineStuckError

    bundle, params = parts
    group = _make_group(bundle, params)
    try:
        async def run():
            ids = _conv(33, 40)
            home = group.router.order_for(ids)[0]
            orig = home.engine.generate

            async def dead(req):
                raise EngineStuckError("tripped")
                yield  # pragma: no cover - makes this an async generator

            home.engine.generate = dead
            try:
                request = GenRequest(
                    prompt_ids=ids, max_new_tokens=4, frequency_penalty=0.5
                )
                with pytest.raises(EngineStuckError):
                    async for _ in group.generate(request):
                        pass
                # the SAME failure with plain sampling fails over fine —
                # and a pre-admission failover still reports prompt_len
                plain = GenRequest(prompt_ids=ids, max_new_tokens=4)
                out = []
                async for token in group.generate(plain):
                    out.append(token)
                assert len(out) == 4
                assert plain.prompt_len == len(ids)
            finally:
                home.engine.generate = orig
            await group.wait_drained()

        asyncio.run(run())
        assert group.failovers >= 1
    finally:
        group.stop()
