import asyncio
import json
import time

import numpy as np
import pytest

from clearml_serving_tpu.serving.endpoints import (
    CanaryEP,
    EndpointMetricLogging,
    ModelEndpoint,
    ModelMonitoring,
)
from clearml_serving_tpu.serving.model_request_processor import (
    EndpointNotFoundException,
    FastWriteCounter,
    ModelRequestProcessor,
)
from clearml_serving_tpu.state import ModelRegistry, StateStore

ECHO_CODE = """
class Preprocess:
    def process(self, data, state, collect_fn):
        return {"echo": data}
"""

DOUBLE_CODE = """
class Preprocess:
    def process(self, data, state, collect_fn):
        return {"y": [v * 2 for v in data["x"]]}
"""


@pytest.fixture()
def mrp(state_root, tmp_path):
    proc = ModelRequestProcessor(state_root=str(state_root), force_create=True, name="t")
    code = tmp_path / "echo.py"
    code.write_text(ECHO_CODE)
    proc.add_endpoint(
        ModelEndpoint(engine_type="custom", serving_url="echo"),
        preprocess_code=str(code),
    )
    proc.serialize()
    return proc


def test_fast_write_counter():
    c = FastWriteCounter()
    assert c.value() == 0
    c.inc(); c.inc(); c.dec()
    assert c.value() == 1
    assert c.value() == 1  # reading must not drift


def test_process_request(mrp):
    out = asyncio.run(mrp.process_request("echo", None, {"a": 1}))
    assert out == {"echo": {"a": 1}}


def test_missing_endpoint(mrp):
    with pytest.raises(EndpointNotFoundException):
        asyncio.run(mrp.process_request("nope", None, {}))


def test_serialize_roundtrip(mrp, state_root):
    mrp.serialize()
    other = ModelRequestProcessor(service_id=mrp.get_id(), state_root=str(state_root))
    assert other.deserialize(skip_sync=True)
    assert "echo" in other.list_endpoints()
    # no-op when unchanged (config-hash detection)
    assert not other.deserialize(skip_sync=True)
    out = asyncio.run(other.process_request("echo", None, [1, 2]))
    assert out == {"echo": [1, 2]}


def test_remove_endpoint(mrp):
    assert mrp.remove_endpoint("echo")
    assert not mrp.remove_endpoint("echo")
    with pytest.raises(EndpointNotFoundException):
        asyncio.run(mrp.process_request("echo", None, {}))


def test_canary_routing(mrp, state_root, tmp_path):
    code = tmp_path / "double.py"
    code.write_text(DOUBLE_CODE)
    mrp.add_endpoint(
        ModelEndpoint(engine_type="custom", serving_url="m/1"), preprocess_code=str(code)
    )
    mrp.add_endpoint(
        ModelEndpoint(engine_type="custom", serving_url="m/2"), preprocess_code=str(code)
    )
    mrp.add_canary_endpoint(
        CanaryEP(endpoint="m", weights=[1.0, 0.0], load_endpoints=["m/2", "m/1"])
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    # all traffic -> m/2 (weight 1.0)
    out = asyncio.run(mrp.process_request("m", None, {"x": [3]}))
    assert out == {"y": [6]}

    # prefix mode resolves to highest numeric version first
    mrp.add_canary_endpoint(CanaryEP(endpoint="p", weights=[1.0], load_endpoint_prefix="m/"))
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    assert mrp._canary_route["p"]["endpoints"] == ["m/2"]

    # missing endpoints are skipped + weights renormalized
    mrp.add_canary_endpoint(
        CanaryEP(endpoint="q", weights=[0.5, 0.5], load_endpoints=["m/1", "gone/9"])
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    assert mrp._canary_route["q"]["endpoints"] == ["m/1"]
    assert mrp._canary_route["q"]["weights"] == [1.0]

    # prefix matching respects name boundaries: "m" must not match "m2/1"
    code2 = tmp_path / "double2.py"
    code2.write_text(DOUBLE_CODE)
    mrp.add_endpoint(
        ModelEndpoint(engine_type="custom", serving_url="m2/1"),
        preprocess_code=str(code2),
    )
    mrp.add_canary_endpoint(
        CanaryEP(endpoint="r", weights=[0.5, 0.5], load_endpoint_prefix="m")
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    assert set(mrp._canary_route["r"]["endpoints"]) == {"m/1", "m/2"}


def test_monitoring_auto_deploy(mrp, state_root, tmp_path):
    reg = mrp.registry
    f = tmp_path / "m.txt"
    f.write_text("payload")
    code = tmp_path / "echo2.py"
    code.write_text(ECHO_CODE)
    mrp.add_model_monitoring(
        ModelMonitoring(
            base_serving_url="auto", engine_type="custom",
            monitor_project="prod", max_versions=2,
        ),
        preprocess_code=str(code),
    )
    r1 = reg.register("model-a", project="prod", path=f)
    time.sleep(0.02)
    assert mrp._update_monitored_models()
    assert "auto/1" in mrp._model_monitoring_endpoints

    r2 = reg.register("model-b", project="prod", path=f)
    time.sleep(0.02)
    assert mrp._update_monitored_models()
    # monotone version numbers: newest model gets version 2
    eps = mrp._model_monitoring_endpoints
    assert set(eps) == {"auto/1", "auto/2"}
    assert eps["auto/2"].model_id == r2.id

    # a third model rolls the window (max_versions=2): auto/1 disappears
    r3 = reg.register("model-c", project="prod", path=f)
    time.sleep(0.02)
    assert mrp._update_monitored_models()
    eps = mrp._model_monitoring_endpoints
    assert set(eps) == {"auto/2", "auto/3"}
    assert eps["auto/3"].model_id == r3.id

    # monitored endpoints are servable
    out = asyncio.run(mrp.process_request("auto", "3", {"k": 1}))
    assert out == {"echo": {"k": 1}}


def test_stats_sampling(mrp, state_root, tmp_path):
    broker_dir = tmp_path / "broker"
    mrp.configure(external_stats_broker="file://{}".format(broker_dir))
    mrp.add_metric_logging(
        EndpointMetricLogging(endpoint="echo", log_frequency=1.0, metrics={})
    )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    for _ in range(5):
        asyncio.run(mrp.process_request("echo", None, {"x": 1}))
    batch = mrp._stats_queue.get_all(timeout=0.1)
    assert len(batch) == 5
    assert all(s["_url"] == "echo" and "_latency" in s and s["_count"] == 1 for s in batch)


def test_zero_downtime_swap_under_load(mrp):
    """Concurrent requests + a config swap: nothing drops, nothing errors."""

    async def run():
        async def client(n):
            results = []
            for i in range(n):
                results.append(await mrp.process_request("echo", None, i))
                await asyncio.sleep(0.001)
            return results

        async def swapper():
            await asyncio.sleep(0.01)
            mrp._last_update_hash = None  # force re-apply
            await asyncio.to_thread(mrp.deserialize)

        res, _ = await asyncio.gather(client(30), swapper())
        return res

    results = asyncio.run(run())
    assert len(results) == 30
    assert all(r == {"echo": i} for i, r in enumerate(results))


def test_hot_reload_preprocess_via_sync(mrp, tmp_path):
    """Re-uploading preprocess code under the same artifact name must take
    effect after the next sync (processor cache eviction on hash change)."""
    assert asyncio.run(mrp.process_request("echo", None, [1])) == {"echo": [1]}
    new_code = tmp_path / "echo_v2.py"
    new_code.write_text(ECHO_CODE.replace('{"echo": data}', '{"echo2": data}'))
    mrp.service.upload_artifact("py_code_echo", new_code)
    mrp._last_update_hash = None
    mrp.deserialize()
    assert asyncio.run(mrp.process_request("echo", None, [1])) == {"echo2": [1]}


def test_wildcard_no_cross_family(mrp):
    mrp.add_metric_logging(
        EndpointMetricLogging(endpoint="model/*", log_frequency=0.5, metrics={})
    )
    assert mrp.get_endpoint_metric_logging("model/3") is not None
    assert mrp.get_endpoint_metric_logging("model2/3") is None


def test_metric_wildcard(mrp):
    mrp.add_metric_logging(
        EndpointMetricLogging(endpoint="m/*", log_frequency=0.5, metrics={})
    )
    assert mrp.get_endpoint_metric_logging("m/7").log_frequency == 0.5
    assert mrp.get_endpoint_metric_logging("other") is None
