import asyncio
import gzip
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from clearml_serving_tpu.serving.endpoints import ModelEndpoint
from clearml_serving_tpu.serving.main import build_app
from clearml_serving_tpu.serving.model_request_processor import ModelRequestProcessor

ECHO_CODE = """
class Preprocess:
    def process(self, data, state, collect_fn):
        return {"echo": data}
"""

STREAM_CODE = """
from clearml_serving_tpu.serving.main import StreamingOutput

class Preprocess:
    def process(self, data, state, collect_fn):
        async def gen():
            for i in range(3):
                yield f"data: chunk{i}\\n\\n"
        return StreamingOutput(gen())
"""

OPENAI_CODE = """
class Preprocess:
    def v1_chat_completions(self, data, state, collect_fn):
        return {"choices": [{"message": {"content": "hi from " + data["model"]}}]}
"""


@pytest.fixture()
def served(state_root, tmp_path):
    mrp = ModelRequestProcessor(state_root=str(state_root), force_create=True, name="t")
    for name, code in (("echo", ECHO_CODE), ("stream", STREAM_CODE), ("oai", OPENAI_CODE)):
        f = tmp_path / (name + ".py")
        f.write_text(code)
        mrp.add_endpoint(
            ModelEndpoint(engine_type="custom", serving_url=name),
            preprocess_code=str(f),
        )
    mrp.serialize()
    mrp.deserialize(skip_sync=True)
    return mrp


def _run(served, fn):
    async def runner():
        app = build_app(served)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            return await fn(client)
        finally:
            await client.close()

    return asyncio.run(runner())


def test_serve_endpoint(served):
    async def fn(client):
        r = await client.post("/serve/echo", json={"x": 1})
        assert r.status == 200
        return await r.json()

    assert _run(served, fn) == {"echo": {"x": 1}}


def test_404(served):
    async def fn(client):
        r = await client.post("/serve/ghost", json={})
        assert r.status == 404
        body = await r.json()
        assert "not found" in body["detail"]

    _run(served, fn)


def test_422_on_custom_without_process(served, tmp_path):
    f = tmp_path / "empty.py"
    f.write_text("class Preprocess:\n    pass\n")
    served.add_endpoint(
        ModelEndpoint(engine_type="custom", serving_url="noproc"), preprocess_code=str(f)
    )

    async def fn(client):
        r = await client.post("/serve/noproc", json={})
        assert r.status == 422

    _run(served, fn)


def test_gzip_request(served):
    async def fn(client):
        payload = gzip.compress(json.dumps({"z": 9}).encode())
        r = await client.post(
            "/serve/echo",
            data=payload,
            headers={"Content-Encoding": "gzip", "Content-Type": "application/json"},
        )
        assert r.status == 200
        return await r.json()

    assert _run(served, fn) == {"echo": {"z": 9}}


def test_sse_streaming(served):
    async def fn(client):
        r = await client.post("/serve/stream", json={})
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/event-stream")
        text = await r.text()
        return text

    text = _run(served, fn)
    assert text == "data: chunk0\n\ndata: chunk1\n\ndata: chunk2\n\n"


def test_openai_route(served):
    async def fn(client):
        r = await client.post(
            "/serve/openai/v1/chat/completions",
            json={"model": "oai", "messages": [{"role": "user", "content": "hello"}]},
        )
        assert r.status == 200
        return await r.json()

    out = _run(served, fn)
    assert out["choices"][0]["message"]["content"] == "hi from oai"


def test_openai_route_requires_model(served):
    async def fn(client):
        r = await client.post("/serve/openai/v1/chat/completions", json={"messages": []})
        assert r.status == 422

    _run(served, fn)


def test_openai_unsupported_serve_type(served):
    async def fn(client):
        r = await client.post("/serve/openai/v1/embeddings", json={"model": "oai"})
        assert r.status == 422
        body = await r.json()
        assert "does not support serve type" in body["detail"]

    _run(served, fn)


def test_health(served):
    async def fn(client):
        r = await client.get("/health")
        assert r.status == 200
        return await r.json()

    body = _run(served, fn)
    assert body["status"] == "ok"
    assert "echo" in body["endpoints"]


def test_dashboard(served):
    async def fn(client):
        await client.post("/serve/echo", json={"t": 1})
        await client.post("/serve/ghost", json={})  # 404: not an engine error
        r = await client.get("/dashboard")
        assert r.status == 200
        return await r.json()

    layout = _run(served, fn)
    assert any(e["endpoint"] == "echo" for e in layout["endpoints"])
    assert "routing" in layout and "metrics" in layout
    tele = layout["telemetry"]["echo"]
    assert tele["requests"] >= 1 and tele["mean_latency_ms"] is not None
    assert tele["errors"] == 0
    # a 404 (endpoint-not-found) must not create a telemetry entry
    assert "ghost" not in layout["telemetry"]


def test_versioned_endpoint_path(served, tmp_path):
    f = tmp_path / "v.py"
    f.write_text(ECHO_CODE)
    served.add_endpoint(
        ModelEndpoint(engine_type="custom", serving_url="vmod", version="3"),
        preprocess_code=str(f),
    )

    async def fn(client):
        r = await client.post("/serve/vmod/3", json={"ok": True})
        assert r.status == 200
        return await r.json()

    assert _run(served, fn) == {"echo": {"ok": True}}


def test_binary_body_passthrough(served):
    async def fn(client):
        r = await client.post(
            "/serve/echo", data=b"\x89PNG...", headers={"Content-Type": "application/octet-stream"}
        )
        assert r.status == 500
        return await r.text()

    # The echo preprocess wraps raw bytes in a dict, which is not
    # JSON-serializable — the router must degrade to a clean 500 JSON payload,
    # not an unhandled exception.
    text = _run(served, fn)
    assert "non-JSON-serializable" in text
