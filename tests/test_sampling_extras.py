"""OpenAI sampling-parameter parity tests: presence/frequency/repetition
penalties, logit_bias, per-request seeds (llm/sampling.py extras + engine
threading)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
from clearml_serving_tpu.llm.sampling import (
    SamplingExtras,
    make_sampling_params,
    penalize_logits,
    sample_tokens,
)

CFG = {"preset": "llama-tiny", "dtype": "float32"}


def _extras(b, v, presence=0.0, frequency=0.0, repetition=1.0, bias=None,
            seeds=None, counters=None):
    return SamplingExtras(
        presence=jnp.full((b,), presence, jnp.float32),
        frequency=jnp.full((b,), frequency, jnp.float32),
        repetition=jnp.full((b,), repetition, jnp.float32),
        bias=jnp.zeros((b, v), jnp.float32) if bias is None else jnp.asarray(bias),
        seeds=jnp.full((b,), -1, jnp.int32) if seeds is None else jnp.asarray(seeds),
        counters=jnp.zeros((b,), jnp.int32) if counters is None else jnp.asarray(counters),
    )


# -- unit: penalty math -------------------------------------------------------


def test_frequency_and_presence_math():
    logits = jnp.zeros((1, 4), jnp.float32)
    counts = jnp.asarray([[0, 1, 3, 0]], jnp.int32)
    ex = _extras(1, 4, presence=0.5, frequency=0.25)
    out = np.asarray(penalize_logits(logits, ex, counts, None))
    # token1: -0.25*1 - 0.5 = -0.75 ; token2: -0.25*3 - 0.5 = -1.25
    np.testing.assert_allclose(out[0], [0.0, -0.75, -1.25, 0.0], atol=1e-6)


def test_repetition_penalty_math():
    logits = jnp.asarray([[2.0, -2.0, 2.0, -2.0]], jnp.float32)
    counts = jnp.asarray([[1, 1, 0, 0]], jnp.int32)
    pmask = jnp.asarray([[False, False, True, True]])
    ex = _extras(1, 4, repetition=2.0)
    out = np.asarray(penalize_logits(logits, ex, counts, pmask))
    # seen positive -> /2 ; seen negative -> *2 (both output and prompt hits)
    np.testing.assert_allclose(out[0], [1.0, -4.0, 1.0, -4.0], atol=1e-6)


def test_logit_bias_forces_greedy():
    logits = jnp.zeros((2, 8), jnp.float32)
    bias = np.zeros((2, 8), np.float32)
    bias[0, 5] = 50.0
    bias[1, 2] = 50.0
    ex = _extras(2, 8, bias=bias)
    toks = sample_tokens(
        logits, make_sampling_params(2), jax.random.PRNGKey(0), ex,
        jnp.zeros((2, 8), jnp.int32), jnp.zeros((2, 8), bool),
    )
    assert list(np.asarray(toks)) == [5, 2]


def test_seeded_rows_reproducible_and_batch_independent():
    v = 64
    row = jax.random.normal(jax.random.PRNGKey(1), (1, v)) * 2.0
    logits = jnp.tile(row, (3, 1))  # identical rows: only seeds may differ
    sp = make_sampling_params(3, temperature=1.0)
    ex1 = _extras(3, v, seeds=[7, 7, -1], counters=[4, 4, 0])
    t1 = np.asarray(sample_tokens(logits, sp, jax.random.PRNGKey(0), ex1))
    t2 = np.asarray(sample_tokens(logits, sp, jax.random.PRNGKey(99), ex1))
    # rows 0/1: same seed+counter+logits -> identical regardless of the
    # shared rng; row 2 is unseeded and follows the shared stream
    assert t1[0] == t1[1] == t2[0] == t2[1]
    ex3 = _extras(3, v, seeds=[7, 8, -1], counters=[4, 4, 0])
    t3 = np.asarray(sample_tokens(logits, sp, jax.random.PRNGKey(0), ex3))
    assert t3[0] == t1[0]  # seed 7 unchanged


# -- engine-level -------------------------------------------------------------


def _engine(bundle, params, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("prefill_buckets", [16])
    kw.setdefault("eos_token_id", None)
    kw.setdefault("decode_steps", 2)
    return LLMEngineCore(bundle, params, **kw)


@pytest.fixture(scope="module")
def parts():
    bundle = models.build_model("llama", CFG)
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, params


def _gen(engine, **req_kw):
    async def run():
        req = GenRequest(**req_kw)
        return [t async for t in engine.generate(req)]

    return asyncio.run(run())


def test_presence_penalty_prevents_repeats(parts):
    bundle, params = parts
    prompt = [5, 9, 2, 17]
    engine = _engine(bundle, params)
    toks = _gen(
        engine, prompt_ids=prompt, max_new_tokens=10, presence_penalty=100.0
    )
    engine.stop()
    assert len(toks) == 10
    assert len(set(toks)) == len(toks)  # a 100-point penalty forbids repeats


def test_logit_bias_dominates_generation(parts):
    bundle, params = parts
    engine = _engine(bundle, params)
    toks = _gen(
        engine,
        prompt_ids=[5, 9, 2],
        max_new_tokens=4,
        logit_bias={42: 100.0},
    )
    engine.stop()
    assert toks == [42, 42, 42, 42]


def test_bias_plus_presence_walks_vocab(parts):
    """Bias and penalties compose: +100 bias on two tokens with a forbidding
    presence penalty alternates between exactly those two."""
    bundle, params = parts
    engine = _engine(bundle, params)
    toks = _gen(
        engine,
        prompt_ids=[5, 9, 2],
        max_new_tokens=2,
        logit_bias={42: 200.0, 43: 100.0},
        presence_penalty=150.0,
    )
    engine.stop()
    assert toks == [42, 43]


def test_seed_reproducible_across_batch_composition(parts):
    bundle, params = parts
    prompt = [5, 9, 2, 17, 33]

    engine = _engine(bundle, params)
    solo = _gen(
        engine, prompt_ids=prompt, max_new_tokens=6, temperature=1.0, seed=1234
    )
    engine.stop()

    engine2 = _engine(bundle, params)

    async def pair():
        r1 = GenRequest(
            prompt_ids=list(prompt), max_new_tokens=6, temperature=1.0, seed=1234
        )
        r2 = GenRequest(prompt_ids=[7, 7, 7], max_new_tokens=6, temperature=0.9)

        async def collect(r):
            return [t async for t in engine2.generate(r)]

        return await asyncio.gather(collect(r1), collect(r2))

    with_neighbor, _ = asyncio.run(pair())
    engine2.stop()
    assert with_neighbor == solo  # same seed -> same stream, any batch mix


def test_unseeded_sampling_still_varies(parts):
    bundle, params = parts
    engine = _engine(bundle, params, rng_seed=0)
    a = _gen(engine, prompt_ids=[5, 9, 2], max_new_tokens=8, temperature=1.0)
    engine.stop()
    engine2 = _engine(bundle, params, rng_seed=123)
    b = _gen(engine2, prompt_ids=[5, 9, 2], max_new_tokens=8, temperature=1.0)
    engine2.stop()
    assert a != b


def test_extras_disable_speculation_but_match_plain(parts):
    """Greedy + penalties on a spec-enabled engine must fall back to the
    plain chunk and match a never-speculating engine exactly."""
    bundle, params = parts
    prompt = [5, 9, 2, 17, 5, 9, 2]
    kw = dict(prompt_ids=prompt, max_new_tokens=8, presence_penalty=10.0)

    plain = _engine(bundle, params)
    want = _gen(plain, **kw)
    plain.stop()

    spec = _engine(bundle, params, speculation="ngram", spec_k=2, spec_ngram=2)
    got = _gen(spec, **kw)
    spec.stop()
    assert got == want


def test_invalid_logit_bias_rejected(parts):
    bundle, params = parts
    engine = _engine(bundle, params)

    async def run():
        req = GenRequest(
            prompt_ids=[1, 2], max_new_tokens=2, logit_bias={999999: 1.0}
        )
        with pytest.raises(ValueError):
            async for _ in engine.generate(req):
                pass

    try:
        asyncio.run(run())
    finally:
        engine.stop()


def test_min_tokens_math():
    # eos (col 3) carries the top logit but is suppressed until counters
    # reach min_new; stop sets are [B, K] -1-padded
    logits = jnp.asarray([[0.0, 0.0, 0.0, 5.0]] * 2, jnp.float32)
    ex = _extras(2, 4, counters=jnp.asarray([1, 4], jnp.int32))._replace(
        min_new=jnp.asarray([3, 3], jnp.int32),
        stop=jnp.asarray([[3, -1], [3, -1]], jnp.int32),
    )
    out = np.asarray(penalize_logits(logits, ex, None, None))
    assert out[0, 3] < -1e29          # row 0: 1 < 3 -> suppressed
    assert out[1, 3] == 5.0           # row 1: 4 >= 3 -> allowed


def test_min_tokens_suppresses_custom_stop_ids():
    # both stop tokens (cols 1 and 3) blocked until the floor
    logits = jnp.zeros((1, 4), jnp.float32)
    ex = _extras(1, 4, counters=jnp.asarray([0], jnp.int32))._replace(
        min_new=jnp.asarray([2], jnp.int32),
        stop=jnp.asarray([[1, 3]], jnp.int32),
    )
    out = np.asarray(penalize_logits(logits, ex, None, None))
    assert out[0, 1] < -1e29 and out[0, 3] < -1e29
    assert out[0, 0] == 0.0 and out[0, 2] == 0.0


def test_min_tokens_never_blanks_constrained_row():
    """When an upstream constraint (guided grammar at accept) leaves ONLY
    stop tokens admissible, the floor must yield instead of blanking the
    row (grammar wins — a blank row would sample a violating token)."""
    logits = jnp.full((1, 4), -1e30, jnp.float32).at[0, 3].set(1.0)
    ex = _extras(1, 4, counters=jnp.asarray([0], jnp.int32))._replace(
        min_new=jnp.asarray([5], jnp.int32),
        stop=jnp.asarray([[3, -1]], jnp.int32),
    )
    out = np.asarray(penalize_logits(logits, ex, None, None))
    assert out[0, 3] == 1.0  # eos stays available: nothing else is


def test_min_tokens_engine_defers_eos(parts):
    """A logit_bias that makes EOS the greedy pick must not end generation
    before min_tokens tokens were produced (vLLM min_tokens semantics)."""
    bundle, params = parts
    engine = _engine(bundle, params, eos_token_id=257)
    toks = _gen(
        engine,
        prompt_ids=[5, 9, 2],
        max_new_tokens=8,
        logit_bias={257: 100.0},       # EOS wins whenever it is allowed
        min_tokens=4,
    )
    engine.stop()
    # exactly: 4 forced non-eos tokens, then the biased EOS fires
    assert len(toks) == 5 and toks[-1] == 257
    assert all(t != 257 for t in toks[:4])


def test_min_tokens_suppresses_request_stop_tokens(parts):
    """Custom stop_token_ids must also respect the floor (vLLM semantics:
    min_tokens suppresses eos AND stop ids)."""
    bundle, params = parts
    engine = _engine(bundle, params, eos_token_id=257)
    toks = _gen(
        engine,
        prompt_ids=[5, 9, 2],
        max_new_tokens=8,
        stop_token_ids=[42],
        logit_bias={42: 100.0},
        min_tokens=4,
    )
    engine.stop()
    assert len(toks) == 5 and toks[-1] == 42
    assert all(t != 42 for t in toks[:4])


def test_min_tokens_exceeding_max_tokens_rejected(parts):
    bundle, params = parts
    engine = _engine(bundle, params, eos_token_id=257)
    with pytest.raises(ValueError):
        engine.validate(GenRequest(prompt_ids=[1], max_new_tokens=4, min_tokens=9))
    engine.stop()


def test_min_tokens_with_too_many_stop_ids_rejected(parts):
    """ADVICE r3: suppression rows hold _STOP_SLOTS ids; rather than
    silently under-enforcing the floor on the overflow ids, validate()
    rejects the combination up front."""
    bundle, params = parts
    engine = _engine(bundle, params, eos_token_id=257)
    many = list(range(100, 109))  # 9 > _STOP_SLOTS (8)
    with pytest.raises(ValueError):
        engine.validate(
            GenRequest(
                prompt_ids=[1], max_new_tokens=8, min_tokens=2,
                stop_token_ids=many,
            )
        )
    # without a floor the same stop set remains fine
    engine.validate(
        GenRequest(prompt_ids=[1], max_new_tokens=8, stop_token_ids=many)
    )
    engine.stop()


def test_paged_cache_with_penalties(parts):
    bundle, params = parts
    engine = _engine(bundle, params, cache_mode="paged", page_size=16)
    toks = _gen(
        engine,
        prompt_ids=[5, 9, 2],
        max_new_tokens=4,
        logit_bias={42: 100.0},
    )
    engine.stop()
    assert toks == [42, 42, 42, 42]