"""Deterministic interleaving explorer (llm/schedule_explorer.py): scenario
sweeps stay green under every explored schedule, every seeded defect is
caught (the mutation self-test — acceptance criterion for the race net),
schedules replay deterministically from their seed, and the seam vocabulary
stays in lockstep with the faults registry the engine and the analyzer
share."""

import json
import subprocess
import sys

import pytest

from clearml_serving_tpu.llm import faults
from clearml_serving_tpu.llm.schedule_explorer import (
    MUTATIONS,
    SCENARIOS,
    YIELD_POINTS,
    ScenarioContext,
    ScheduleViolation,
    explore,
    self_test,
)

K = 12          # schedules per scenario: small enough for tier-1, large
SEED = 0        # enough that every seeded defect is caught at this seed


# -- seam vocabulary ----------------------------------------------------------


def test_yield_points_are_registered_fault_points():
    """The explorer's seams ARE the engine's fault points: one registry
    drives chaos specs, analyzer TPU403, and schedule exploration."""
    assert YIELD_POINTS <= faults.KNOWN_POINTS


def test_new_engine_seams_accept_chaos_specs():
    for point in ("engine.dispatch.prepare", "engine.watchdog", "engine.drain",
                  "engine.ledger.leak"):
        faults.configure([{"point": point, "action": "delay", "delay": 0.0}])
    faults.clear()


def test_seam_registries_three_way_consistency():
    """ONE test pins the whole seam vocabulary (the PR-11-era gap: seams
    added to faults.py were not forced through every registry):

    1. analyzer fallback == runtime registry (a detached-fixture analysis
       must validate against the same point set CI validates against);
    2. explorer yield points ⊆ the registry (a scenario can only park on
       seams chaos specs can also target);
    3. every ``faults.fire("<literal>")`` call site in the source tree
       names a registered point (the dynamic twin of analyzer TPU403);
    4. every registered point is documented in faults.py's module
       docstring (an undocumented seam is untargetable in practice).
    """
    import ast
    import os

    from clearml_serving_tpu.analyze import rules_errors
    from clearml_serving_tpu.llm import faults as faults_mod

    # (1) + (2)
    assert rules_errors.FALLBACK_POINTS == faults.KNOWN_POINTS
    assert YIELD_POINTS <= faults.KNOWN_POINTS

    # (3) every fire() literal in the tree is registered
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(faults_mod.__file__)))
    fired = set()
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in filenames:
            if not name.endswith(".py"):
                continue
            with open(os.path.join(dirpath, name), encoding="utf-8") as fh:
                tree = ast.parse(fh.read())
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                func = node.func
                attr = func.attr if isinstance(func, ast.Attribute) else (
                    func.id if isinstance(func, ast.Name) else None
                )
                if attr != "fire":
                    continue
                first = node.args[0]
                if isinstance(first, ast.Constant) and isinstance(
                    first.value, str
                ):
                    fired.add(first.value)
    unregistered = fired - faults.KNOWN_POINTS
    assert not unregistered, (
        "fire() call sites name unregistered points: {}".format(
            sorted(unregistered)
        )
    )

    # (4) the docstring documents every registered point
    doc = faults_mod.__doc__ or ""
    undocumented = {p for p in faults.KNOWN_POINTS if p not in doc}
    assert not undocumented, (
        "registered fault points missing from the faults.py docstring: "
        "{}".format(sorted(undocumented))
    )


def test_unknown_yield_point_is_rejected():
    import random

    ctx = ScenarioContext(random.Random(0))

    def bad():
        ctx.yield_point("engine.not.a.seam")

    ctx.spawn(bad, "t")
    with pytest.raises(ValueError, match="unknown yield point"):
        ctx.run()


# -- clean sweeps -------------------------------------------------------------


@pytest.mark.parametrize("scenario", sorted(SCENARIOS))
def test_scenario_green_under_every_explored_schedule(scenario):
    report = explore(scenario, schedules=K, seed=SEED)
    assert report["violations"] == [], report


# -- determinism --------------------------------------------------------------


def test_schedules_replay_deterministically():
    a = explore("refcount_lock", schedules=6, seed=3, mutate="drop_lock")
    b = explore("refcount_lock", schedules=6, seed=3, mutate="drop_lock")
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)
    # different seeds explore different interleavings
    c = explore("refcount_lock", schedules=6, seed=4, mutate="drop_lock")
    assert json.dumps(a, sort_keys=True) != json.dumps(c, sort_keys=True)


# -- mutation self-test (acceptance) ------------------------------------------


@pytest.mark.parametrize("mutation", sorted(MUTATIONS))
def test_seeded_defect_is_caught(mutation):
    """Each seeded defect — dropped PR-4 buffer copy, dropped quarantine
    barrier, dropped unpin, dropped chain reset, dropped lock — must be
    CAUGHT within K explored schedules, proving the net has no hole for
    that defect class."""
    report = explore(MUTATIONS[mutation], schedules=K, seed=SEED,
                     mutate=mutation)
    assert report["violations"], (
        "mutation {!r} survived {} schedules of {}".format(
            mutation, K, MUTATIONS[mutation]
        )
    )
    # the violation carries an actionable repro: message + schedule trace
    first = report["violations"][0]
    assert first["trace"], first
    assert all(":" in step for step in first["trace"])


def test_self_test_report():
    report = self_test(schedules=K, seed=SEED)
    assert report["ok"], report["detail"]
    assert all(
        v in ("caught", "green") for v in report["detail"].values()
    ), report["detail"]


# -- the PR-4 regression scenario ---------------------------------------------


def test_host_buffer_aliasing_race_class_regression():
    """The exact race class PR 4 fixed by hand (zero-copy jnp.asarray of a
    live-mutated host mirror): with the snapshot copy every interleaving is
    clean; with the copy dropped the explorer finds an interleaving where
    the worker observes the retire stage's writeback."""
    clean = explore("host_buffer_handoff", schedules=K, seed=SEED)
    assert clean["violations"] == []
    raced = explore("host_buffer_handoff", schedules=K, seed=SEED,
                    mutate="drop_buffer_copy")
    assert raced["violations"]
    assert "mutated host buffer" in raced["violations"][0]["message"]


def test_pin_balance_violation_is_the_armed_sanitizer():
    """The pin-balance net is the REAL KV sanitizer: the dropped unpin is
    reported as pins outliving drain, same as in production arming."""
    report = explore("pin_balance", schedules=K, seed=SEED,
                     mutate="drop_unpin")
    assert report["violations"]
    assert "pins outlived drain" in report["violations"][0]["message"]


# -- guards -------------------------------------------------------------------


def test_unknown_scenario_and_mutation_are_rejected():
    with pytest.raises(ValueError, match="unknown scenario"):
        explore("nope", schedules=1)
    with pytest.raises(ValueError, match="unknown mutation"):
        explore("pin_balance", schedules=1, mutate="nope")


def test_violation_inside_thread_surfaces_with_replay_coordinates():
    import random

    ctx = ScenarioContext(random.Random(0), scenario="fixture", seed=9)

    def bad():
        ctx.yield_point("engine.decode")
        raise ScheduleViolation("boom")

    ctx.spawn(bad, "t")
    with pytest.raises(ScheduleViolation, match="boom") as info:
        ctx.run()
    assert ctx.trace == ["t:engine.decode"]
    # the escaping violation is a self-contained repro
    assert info.value.scenario == "fixture"
    assert info.value.seed == 9
    assert info.value.trace == ["t:engine.decode"]


# -- CLI ----------------------------------------------------------------------


def test_cli_smoke_and_mutate_exit_codes(tmp_path):
    import os

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # a mutated run must exit non-zero (a violation was found) and print
    # the schedule trace
    proc = subprocess.run(
        [sys.executable, "-m", "clearml_serving_tpu.llm.schedule_explorer",
         "--scenario", "stale_chain_commit", "--schedules", "4",
         "--mutate", "drop_chain_reset"],
        capture_output=True, text=True, env=env,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "stale token" in proc.stdout and "trace:" in proc.stdout
    # the clean run of the same scenario exits zero
    proc = subprocess.run(
        [sys.executable, "-m", "clearml_serving_tpu.llm.schedule_explorer",
         "--scenario", "stale_chain_commit", "--schedules", "4"],
        capture_output=True, text=True, env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "green" in proc.stdout
