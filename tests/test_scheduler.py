"""SLO-aware scheduling invariants (docs/slo_scheduling.md).

Covers the scheduler contracts the loadtest harness's headline claim rests
on: earliest-deadline-first ordering within a priority class, strict class
order across classes, the starvation floor that keeps batch work moving,
class-aware shedding with a drain-rate-derived Retry-After, brownout
hysteresis (no flapping across a threshold), the brownout stage effects,
and preempt -> resume radix replay correctness under the armed KV
sanitizer.
"""

import asyncio
import time

import jax
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.errors import EngineOverloadedError
from clearml_serving_tpu.llm.engine import (
    GenRequest,
    LLMEngineCore,
    PRIORITY_CLASSES,
    _BrownoutController,
    _ClassedPendingQueue,
)


@pytest.fixture(scope="module")
def parts():
    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, params


@pytest.fixture(autouse=True)
def armed_sanitizer(monkeypatch):
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")


def _req(cls="interactive", deadline=None, ids=(1, 2)):
    r = GenRequest(prompt_ids=list(ids), max_new_tokens=2, priority=cls)
    r._deadline = deadline
    return r


async def _collect(engine, req):
    out = []
    async for token in engine.generate(req):
        out.append(token)
    return out


# -- queue invariants ---------------------------------------------------------


def test_edf_ordering_within_a_class():
    q = _ClassedPendingQueue()
    late = _req(deadline=100.0)
    early = _req(deadline=10.0)
    never = _req(deadline=None)  # no deadline: after every deadlined one
    q.put_nowait(never)
    q.put_nowait(late)
    q.put_nowait(early)
    assert q.get_nowait() is early
    assert q.get_nowait() is late
    assert q.get_nowait() is never


def test_strict_cross_class_dispatch_order():
    q = _ClassedPendingQueue()
    b = _req("batch", deadline=1.0)          # earliest deadline overall...
    e = _req("best_effort", deadline=2.0)
    i = _req("interactive", deadline=999.0)  # ...but interactive still wins
    q.put_nowait(b)
    q.put_nowait(e)
    q.put_nowait(i)
    assert q.get_nowait() is i
    assert q.get_nowait() is b               # then strict class order
    assert q.get_nowait() is e


def test_starvation_floor_admits_batch_within_n_interactive_pops():
    floor = 3
    q = _ClassedPendingQueue(starvation_floor=floor)
    batch = _req("batch")
    q.put_nowait(batch)
    popped = []
    # keep one interactive queued at all times; the batch request must pop
    # within `floor` + 1 pops despite the constant higher-class pressure
    for _ in range(floor + 1):
        q.put_nowait(_req("interactive"))
        popped.append(q.get_nowait())
    assert batch in popped, "batch request starved past the floor"
    assert popped.index(batch) == floor


def test_waiting_skips_cancelled_and_failed_entries():
    """_maybe_preempt sizes preemption off waiting('interactive'): a
    cancelled/expired request still sitting in the heap must not count,
    or batch slots get preempted (and their budget burned) for a corpse
    the admission pop will simply discard."""
    q = _ClassedPendingQueue()
    live, dead, failed = _req(), _req(), _req()
    dead.cancelled = True
    failed.error = RuntimeError("expired")
    for r in (live, dead, failed):
        q.put_nowait(r)
    assert q.waiting("interactive") == 1
    assert q.qsize() == 3  # raw depth still reflects heap residency


def test_pool_pressure_ignores_reclaimable_prefix_cache_pages(parts):
    """A warm-but-idle radix cache retains pages up to its budget; those
    are reclaimable on demand and must not read as pool occupancy, or the
    brownout stage pins high with zero traffic."""
    bundle, params = parts

    async def run():
        engine = LLMEngineCore(
            bundle, params, max_batch=2, max_seq_len=128,
            prefill_buckets=[32, 64], eos_token_id=None, decode_steps=1,
            cache_mode="paged", page_size=16, prefix_cache=64,
            prefix_block=16, prefix_cache_pages=32, max_pending=8,
        )
        # warm the cache well past half the pool, then go idle
        for i in range(4):
            req = GenRequest(
                prompt_ids=[(i * 29 + j) % 250 + 1 for j in range(33)],
                max_new_tokens=2,
            )
            async for _ in engine.generate(req):
                pass
        await engine.wait_drained()
        return engine

    engine = asyncio.run(run())
    assert engine._prefix.cached_pages >= 8  # the cache IS warm
    score, signals = engine._pressure_score()
    assert signals["pool"] < 0.2, signals
    engine.stop()


def test_shed_lowest_never_evicts_midstream_resume():
    """A preempted batch request waiting to resume has already streamed
    tokens to an attached consumer: shedding it turns an in-progress 200
    into a mid-stream 429 and discards its committed KV. Fresh queued work
    sheds first; with only resumes queued, nothing is evicted (the arrival
    sheds at the door instead)."""
    q = _ClassedPendingQueue()
    resume = _req("batch")
    resume.produced = 7  # mid-stream: preempted after 7 emitted tokens
    fresh = _req("batch")
    q.put_nowait(resume)
    q.put_nowait(fresh)
    assert q.shed_lowest("interactive") is fresh
    assert q.shed_lowest("interactive") is None  # resume is immune


def test_retry_after_hint_anchors_drain_rate_at_now(parts):
    """A wedged loop must not advertise the drain rate of a historical
    burst: the hint's rate window is anchored at now, so the longer the
    engine goes without commits, the longer the advertised backoff."""
    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=1, max_seq_len=64, prefill_buckets=[16],
        eos_token_id=None, decode_steps=1, max_pending=8,
    )
    now = time.monotonic()
    # 8 commits in half a second... ten seconds ago
    engine._admit_times.extend(now - 10.0 + 0.0625 * i for i in range(8))
    hint = engine._retry_after_hint(ahead=4)
    # stale-burst rate would be 14/s -> ~0.36s; now-anchored is ~0.7/s
    assert hint >= 5.0, hint
    engine.stop()


def test_brownout_deadline_signal_needs_minimum_volume(parts):
    """One expired request against zero admissions is a deadline ratio of
    1.0 — without a volume floor a single misbehaving client slams an idle
    engine into stage-3 brownout."""
    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=1, max_seq_len=64, prefill_buckets=[16],
        eos_token_id=None, decode_steps=1, max_pending=8,
    )
    engine._pressure_window = (time.monotonic() - 6.0, 0, 0, 0)
    engine.counters["deadline_queue"] = 1
    _, signals = engine._pressure_score()
    assert "deadline" not in signals, signals
    # at volume the ratio counts
    engine._pressure_window = (time.monotonic() - 6.0, 0, 0, 0)
    engine.counters["deadline_queue"] = 3
    engine._admit_count = 1
    _, signals = engine._pressure_score()
    assert signals.get("deadline") == 0.75, signals
    engine.stop()


def test_queue_depths_and_snapshot():
    q = _ClassedPendingQueue()
    q.put_nowait(_req("interactive"))
    q.put_nowait(_req("batch"))
    q.put_nowait(_req("batch"))
    assert q.depths() == {"interactive": 1, "batch": 2, "best_effort": 0}
    assert q.qsize() == 3 and not q.empty()
    assert len(q.requests()) == 3
    assert len(q.pop_all()) == 3 and q.empty()


def test_shed_lowest_takes_strictly_lower_class_latest_deadline():
    q = _ClassedPendingQueue()
    b1 = _req("batch", deadline=5.0)
    b2 = _req("batch", deadline=50.0)
    q.put_nowait(b1)
    q.put_nowait(b2)
    # an interactive arrival evicts the LATEST-deadline batch request
    victim = q.shed_lowest("interactive")
    assert victim is b2
    # batch cannot evict batch (strictly lower only)
    assert q.shed_lowest("batch") is None
    # best_effort has nothing below it
    assert q.shed_lowest("best_effort") is None
    assert q.get_nowait() is b1


# -- brownout controller ------------------------------------------------------


def test_brownout_hysteresis_no_flapping_across_threshold():
    c = _BrownoutController(dwell=10.0)
    t = 1000.0
    assert c.update(0.2, now=t) == 0
    # oscillate tightly around the stage-1 UP threshold (0.70): once up,
    # the stage must hold — dropping needs score < DOWN (0.50) AND dwell
    assert c.update(0.71, now=t + 1) == 1
    transitions_after_up = c.transitions
    for k in range(20):
        score = 0.69 if k % 2 else 0.71
        c.update(score, now=t + 1 + 0.1 * k)
    assert c.stage == 1
    assert c.transitions == transitions_after_up, "stage flapped"
    # below DOWN but inside the dwell window: still held
    assert c.update(0.1, now=t + 5) == 1
    # below DOWN past the dwell: steps down one stage
    assert c.update(0.1, now=t + 12) == 0


def test_brownout_raises_immediately_and_steps_down_one_at_a_time():
    c = _BrownoutController(dwell=1.0)
    t = 0.0
    assert c.update(0.99, now=t) == 3          # straight to the top stage
    assert c.update(0.0, now=t + 0.5) == 3     # dwell holds it
    assert c.update(0.0, now=t + 2.0) == 2     # one stage per dwell
    assert c.update(0.0, now=t + 4.0) == 1
    assert c.update(0.0, now=t + 6.0) == 0


# -- admission: class-aware shedding + Retry-After ----------------------------


def test_priority_validation(parts):
    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64, prefill_buckets=[16],
        eos_token_id=None,
    )
    with pytest.raises(ValueError, match="priority"):
        engine.validate(
            GenRequest(prompt_ids=[1], max_new_tokens=1, priority="vip")
        )
    for cls in PRIORITY_CLASSES:
        engine.validate(
            GenRequest(prompt_ids=[1], max_new_tokens=1, priority=cls)
        )
    engine.stop()


def test_retry_after_hint_grows_with_queue_depth(parts):
    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=1, max_seq_len=64, prefill_buckets=[16],
        eos_token_id=None, max_pending=4,
    )
    # seed an observed drain rate of 2 admissions/s
    t0 = time.monotonic()
    engine._admit_times.extend([t0 - 1.0, t0 - 0.5, t0])
    h0 = engine._retry_after_hint(ahead=0)
    h4 = engine._retry_after_hint(ahead=4)
    h12 = engine._retry_after_hint(ahead=12)
    assert h0 < h4 < h12
    assert h4 == pytest.approx((4 + 1) / 2.0, rel=0.01)
    # no drain observed yet: the fallback still grows with depth
    engine._admit_times.clear()
    assert engine._retry_after_hint(ahead=0) < engine._retry_after_hint(
        ahead=10
    )
    engine.stop()


def test_queue_full_shed_carries_drain_rate_retry_after(parts):
    """Satellite: the PR 2 queue-shed branch now derives Retry-After from
    the observed drain rate — the hint must grow with the queue depth."""
    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=1, max_seq_len=64, prefill_buckets=[16],
        eos_token_id=None, max_pending=2,
    )
    t0 = time.monotonic()
    engine._admit_times.extend([t0 - 2.0, t0 - 1.0, t0])  # 1 admission/s
    # park one interactive request in the queue (no loop running: nothing
    # drains it)
    parked = _req("interactive")
    engine._pending.put_nowait(parked)
    shallow = None
    try:
        engine.check_admission(_req("interactive"))
    except EngineOverloadedError:
        pytest.fail("one queued request is under the bound of 2")
    engine._pending.put_nowait(_req("interactive"))
    with pytest.raises(EngineOverloadedError) as shallow:
        engine.check_admission(_req("interactive"))
    engine._pending.put_nowait(_req("interactive"))
    engine._pending.put_nowait(_req("interactive"))
    with pytest.raises(EngineOverloadedError) as deep:
        engine.check_admission(_req("interactive"))
    assert shallow.value.retry_after is not None
    assert deep.value.retry_after > shallow.value.retry_after
    assert shallow.value.status == 429
    engine.stop()


def test_interactive_arrival_evicts_queued_best_effort(parts):
    """Class-aware shedding: with the queue at its bound, a higher-class
    arrival evicts the lowest-class queued request (429 delivered on ITS
    stream) instead of shedding the arrival."""
    bundle, params = parts

    async def run():
        engine = LLMEngineCore(
            bundle, params, max_batch=1, max_seq_len=64,
            prefill_buckets=[16], eos_token_id=None, max_pending=1,
            decode_steps=1,
        )
        a = GenRequest(prompt_ids=[1, 2], max_new_tokens=10_000)
        agen = engine.generate(a)
        await agen.__anext__()  # A pins the only slot
        be = GenRequest(
            prompt_ids=[1, 3], max_new_tokens=2, priority="best_effort"
        )
        be_task = asyncio.create_task(_collect(engine, be))
        while engine._pending.qsize() < 1:
            await asyncio.sleep(0.005)
        # queue full: an interactive arrival must ADMIT by evicting `be`
        hi = GenRequest(prompt_ids=[1, 4], max_new_tokens=2)
        hi_task = asyncio.create_task(_collect(engine, hi))
        with pytest.raises(EngineOverloadedError) as ei:
            await be_task
        assert ei.value.shed_class == "best_effort"
        assert ei.value.retry_after is not None
        await agen.aclose()  # free the slot; the interactive request runs
        out = await asyncio.wait_for(hi_task, timeout=30)
        assert len(out) >= 1
        return engine

    engine = asyncio.run(run())
    assert engine._class_sheds["queue"]["best_effort"] == 1
    # a best_effort arrival into an all-higher queue sheds ITSELF
    assert engine.counters["sheds_queue"] == 1
    engine.stop()


# -- brownout stage effects ---------------------------------------------------


def test_brownout_stage2_caps_batch_tokens_not_interactive(parts):
    bundle, params = parts

    async def run():
        engine = LLMEngineCore(
            bundle, params, max_batch=2, max_seq_len=128,
            prefill_buckets=[16], eos_token_id=None, decode_steps=1,
            brownout=True, brownout_batch_cap=3, brownout_dwell=120.0,
        )
        engine._brownout.stage = 2
        engine._brownout._changed_at = time.monotonic()  # dwell holds it
        batch = GenRequest(
            prompt_ids=[1, 2], max_new_tokens=50, priority="batch"
        )
        inter = GenRequest(prompt_ids=[1, 3], max_new_tokens=6)
        out_b, out_i = await asyncio.gather(
            _collect(engine, batch), _collect(engine, inter)
        )
        assert len(out_b) == 3, "batch-lane cap must bite at stage 2"
        assert len(out_i) == 6, "interactive is never capped"
        return engine

    engine = asyncio.run(run())
    engine.stop()


def test_brownout_stage3_sheds_best_effort_at_the_door(parts):
    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64, prefill_buckets=[16],
        eos_token_id=None, brownout=True, brownout_dwell=120.0,
    )
    engine._brownout.stage = 3
    engine._brownout._changed_at = time.monotonic()
    with pytest.raises(EngineOverloadedError) as ei:
        engine.check_admission(
            GenRequest(prompt_ids=[1], max_new_tokens=1,
                       priority="best_effort")
        )
    assert ei.value.shed_class == "best_effort"
    # interactive and batch still admit at stage 3
    engine.check_admission(GenRequest(prompt_ids=[1], max_new_tokens=1))
    engine.check_admission(
        GenRequest(prompt_ids=[1], max_new_tokens=1, priority="batch")
    )
    assert engine._class_sheds["brownout"]["best_effort"] == 1
    engine.stop()


# -- preemption: resume replays through the radix cache -----------------------


def test_preempt_resume_radix_replay_byte_identical(parts):
    """A preempted batch request's stream must be byte-identical to an
    uncontended run: its generated-so-far KV is committed into the radix
    prefix cache at preemption, so the resume prefills only the tail and
    greedy decoding continues exactly — audited by the armed sanitizer."""
    bundle, params = parts
    prompt = [(i * 7 + 3) % 250 + 1 for i in range(17)]
    n_new = 24

    def make_engine():
        return LLMEngineCore(
            bundle, params, max_batch=1, max_seq_len=128,
            prefill_buckets=[32, 64], eos_token_id=None, decode_steps=2,
            cache_mode="paged", page_size=16, prefix_cache=64,
            prefix_block=16, preempt_batch=True, preempt_budget=2,
        )

    async def control():
        engine = make_engine()
        req = GenRequest(
            prompt_ids=list(prompt), max_new_tokens=n_new, priority="batch"
        )
        out = await _collect(engine, req)
        await engine.wait_drained()
        engine.stop()
        return out

    async def contended():
        engine = make_engine()
        assert engine._sanitizer is not None, "TPUSERVE_SANITIZE did not arm"
        batch = GenRequest(
            prompt_ids=list(prompt), max_new_tokens=n_new, priority="batch"
        )
        b_task = asyncio.create_task(_collect(engine, batch))
        while batch.produced < 6:
            await asyncio.sleep(0.005)
        # slot pressure + queued interactive work => preemption
        hi = GenRequest(prompt_ids=[1, 9, 9], max_new_tokens=2)
        out_hi = await asyncio.wait_for(_collect(engine, hi), timeout=60)
        assert len(out_hi) >= 1
        out_b = await asyncio.wait_for(b_task, timeout=60)
        await engine.wait_drained()
        return engine, out_b

    expected = asyncio.run(control())
    engine, got = asyncio.run(contended())
    assert engine.counters["preemptions"] >= 1, "no preemption happened"
    assert got == expected, "preempt->resume diverged from the clean run"
    assert engine._prefix.hits >= 1, "resume did not hit the radix cache"
    stats = engine._sanitizer.stats()
    assert stats["checks"] > 0 and stats["failures"] == 0
    pool = engine.paged_cache.pool
    assert pool.free_pages == (
        pool.num_pages - 1 - engine._prefix.cached_pages
    )
    engine.stop()


def test_preempt_pins_history_until_resume(parts):
    """Preemption must PIN the victim's stored history against radix
    eviction while it waits in the queue (prefix_cache.pin_run): the lane's
    near-zero-prefill resume promise would otherwise silently degrade to a
    full re-prefill whenever pool pressure LRU-evicts the stored run. The
    pin is released by the resume's admission lookup — no pinned nodes may
    outlive the run."""
    bundle, params = parts
    prompt = [(i * 11 + 5) % 250 + 1 for i in range(17)]

    async def run():
        engine = LLMEngineCore(
            bundle, params, max_batch=1, max_seq_len=128,
            prefill_buckets=[32, 64], eos_token_id=None, decode_steps=1,
            cache_mode="paged", page_size=16, prefix_cache=64,
            prefix_block=16, prefix_cache_pages=2,  # tight: eviction churns
            preempt_batch=True, preempt_budget=2,
        )
        batch = GenRequest(
            prompt_ids=list(prompt), max_new_tokens=24, priority="batch"
        )
        b_task = asyncio.create_task(_collect(engine, batch))
        while batch.produced < 4:
            await asyncio.sleep(0.005)
        hi = GenRequest(prompt_ids=[1, 9, 9], max_new_tokens=24)
        hi_task = asyncio.create_task(_collect(engine, hi))
        while engine.counters["preemptions"] < 1:
            await asyncio.sleep(0.005)
        # victim waits in the queue (the single slot is busy with `hi`):
        # its history must be pinned and still served by the cache
        assert batch._resume_pin is not None, "preemption took no pin"
        history_len = len(batch.prompt_ids)
        assert engine._prefix.match_len(batch.prompt_ids) >= (
            (history_len - 1) // 16 * 16
        ), "pinned history not cached while queued"
        await asyncio.wait_for(hi_task, timeout=60)
        out_b = await asyncio.wait_for(b_task, timeout=60)
        assert len(out_b) == 24
        await engine.wait_drained()
        return engine, batch

    engine, batch = asyncio.run(run())
    assert batch._resume_pin is None, "resume admission must release the pin"
    # no pinned node outlives the preempt->resume round trip
    with engine._prefix._lock:
        stack = list(engine._prefix._roots.values())
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            assert node.pinned == 0, "leaked pin on a radix node"
    stats = engine._sanitizer.stats() if engine._sanitizer else None
    assert stats is None or stats["failures"] == 0
    engine.stop()


def test_preempt_budget_makes_request_immune(parts):
    """A request that exhausted its preemption budget is no longer a victim
    (the starvation guarantee): with budget 0, interactive arrivals wait
    for the batch slot instead of preempting it."""
    bundle, params = parts

    async def run():
        engine = LLMEngineCore(
            bundle, params, max_batch=1, max_seq_len=128,
            prefill_buckets=[16], eos_token_id=None, decode_steps=1,
            cache_mode="paged", page_size=16, preempt_batch=True,
            preempt_budget=0,
        )
        batch = GenRequest(
            prompt_ids=[1, 2, 3], max_new_tokens=12, priority="batch"
        )
        b_task = asyncio.create_task(_collect(engine, batch))
        while batch.produced < 2:
            await asyncio.sleep(0.005)
        hi = GenRequest(prompt_ids=[1, 5], max_new_tokens=2)
        out_hi = await asyncio.wait_for(_collect(engine, hi), timeout=60)
        out_b = await b_task
        assert len(out_b) == 12, "budget-exhausted batch run must finish"
        assert len(out_hi) >= 1
        return engine

    engine = asyncio.run(run())
    assert engine.counters["preemptions"] == 0
    engine.stop()


# -- ragged scheduler: brownout on the token budget ---------------------------


def test_brownout_stage3_shrinks_ragged_step_token_budget(parts):
    """The legacy stage-3 hook was _prefill_gate.set_budget(1); under the
    ragged scheduler the gate no longer exists — stage 3 must instead
    shrink the effective step token budget, so decode slots drain ahead of
    new admission chunks, and restore it when the stage drops
    (docs/ragged_attention.md)."""
    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64, prefill_buckets=[16],
        eos_token_id=None, brownout=True, brownout_dwell=120.0,
        scheduler="ragged", step_token_budget=128,
    )
    try:
        assert engine._prefill_gate is None  # the gate is gone in ragged mode
        assert engine._effective_token_budget() == 128
        engine._brownout.stage = 3
        engine._brownout._changed_at = time.monotonic()
        shrunk = engine._effective_token_budget()
        assert shrunk < 128
        assert shrunk > engine.max_batch  # decode rows always still fit
        assert engine.lifecycle_stats()["ragged"]["effective_budget"] == shrunk
        # admission work under stage 3 is bounded by the shrunken budget:
        # a planned step may hand prefill jobs at most shrunk - n_decode
        # tokens, exactly the legacy drain-ahead-of-admissions behavior
        engine._brownout.stage = 0
        assert engine._effective_token_budget() == 128
    finally:
        engine.stop()


def test_brownout_stage3_budget_accounts_multi_token_rows(parts):
    """ISSUE 13 satellite: a q=4 decode row is FOUR tokens of the step
    budget. Under the stage-3 shrunken budget the planner collapses the
    multi-step windows until the launch's token demand fits — decode
    keeps draining, admissions keep their minimal chunk, and nothing
    over-commits the brownout ceiling."""
    import numpy as np

    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=6, max_seq_len=64, prefill_buckets=[16],
        eos_token_id=None, decode_steps=4, scheduler="ragged",
        step_token_budget=64, cache_mode="paged",
        brownout=True, brownout_dwell=120.0,
    )
    try:
        for slot in range(6):
            req = GenRequest(prompt_ids=[1, 2, 3], max_new_tokens=50)
            req.prompt_len = 3
            req.produced = 1
            engine._slot_req[slot] = req
            engine.paged_cache.pool.allocate(slot, 3)
        active = np.ones(6, bool)
        plan = engine._prepare_ragged(active, 0)
        assert plan["launch_steps"] == 4
        assert plan["used_tokens"] == 6 * 4
        assert plan["used_tokens"] <= engine._effective_token_budget()
        engine._brownout.stage = 3
        engine._brownout._changed_at = time.monotonic()
        eff = engine._effective_token_budget()
        assert eff < 64
        plan = engine._prepare_ragged(active, 0)
        assert plan["launch_steps"] < 4, "windows must collapse at stage 3"
        assert plan["used_tokens"] <= eff
        engine._brownout.stage = 0
        plan = engine._prepare_ragged(active, 0)
        assert plan["launch_steps"] == 4  # restored with the stage drop
    finally:
        for slot in range(6):
            engine._slot_req[slot] = None
            engine.paged_cache.pool.free(slot)
        engine.stop()


def test_brownout_stage2_cap_clamps_ragged_window_midstream(parts):
    """ISSUE 13 satellite: the stage-2 batch max_new_tokens cap clamps a
    multi-step window MID-WINDOW — a batch row 30 tokens into a capped-
    at-32 stream gets a 2-token window, not a full q=4 one (the window
    never dispatches compute the cap will throw away)."""
    import numpy as np

    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64, prefill_buckets=[16],
        eos_token_id=None, decode_steps=4, scheduler="ragged",
        step_token_budget=64, cache_mode="paged",
        brownout=True, brownout_batch_cap=32, brownout_dwell=120.0,
    )
    try:
        req = GenRequest(
            prompt_ids=[1, 2, 3], max_new_tokens=50, priority="batch"
        )
        req.prompt_len = 3
        req.produced = 30
        engine._slot_req[0] = req
        engine.paged_cache.pool.allocate(0, 32)
        active = np.array([True, False])
        plan = engine._prepare_ragged(active, 0)
        assert plan["row_steps"][0] == 4        # no cap: full window
        engine._brownout.stage = 2
        engine._brownout._changed_at = time.monotonic()
        plan = engine._prepare_ragged(active, 0)
        assert plan["row_steps"][0] == 2        # cap clamps mid-window
    finally:
        engine._slot_req[0] = None
        engine.paged_cache.pool.free(0)
        engine.stop()


def test_brownout_stage2_cap_exact_with_multi_step_chunks(parts):
    """Two-dispatch scheduler: the stage-2 cap landing MID-CHUNK of a
    decode_steps=4 pipelined chunk still delivers exactly the cap (the
    chunk's surplus tokens are dropped at retire) — the multi-token-chunk
    analog of the ragged window clamp."""
    bundle, params = parts

    async def run():
        engine = LLMEngineCore(
            bundle, params, max_batch=2, max_seq_len=128,
            prefill_buckets=[16], eos_token_id=None, decode_steps=4,
            brownout=True, brownout_batch_cap=5, brownout_dwell=120.0,
        )
        engine._brownout.stage = 2
        engine._brownout._changed_at = time.monotonic()
        batch = GenRequest(
            prompt_ids=[1, 2], max_new_tokens=50, priority="batch"
        )
        out_b = await _collect(engine, batch)
        await engine.wait_drained()
        assert len(out_b) == 5, "cap must bite mid-chunk, surplus dropped"
        return engine

    engine = asyncio.run(run())
    engine.stop()


def test_brownout_stage3_still_sets_gate_budget_on_two_dispatch(parts):
    """Legacy two-dispatch engines keep the historical gate hook: the
    stage transition shrinks the per-chunk segment budget to 1 and
    restores the configured value on the way down."""
    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64, prefill_buckets=[16],
        eos_token_id=None, brownout=True, brownout_dwell=0.0,
        prefill_segments_per_decode=3,
    )
    try:
        gate = engine._prefill_gate
        assert gate is not None and gate._spc == 3
        engine._brownout_checked = 0.0
        engine._brownout.update = lambda *a, **k: 3  # force stage
        engine._brownout.stage = 0
        engine._update_brownout()
        assert gate._spc == 1
        engine._brownout.update = lambda *a, **k: 0
        engine._brownout.stage = 3
        engine._brownout_checked = 0.0
        engine._update_brownout()
        assert gate._spc == 3
    finally:
        engine.stop()
