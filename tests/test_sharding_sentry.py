"""Runtime sharding-sentry suite (llm/sharding_sentry.py;
docs/static_analysis.md TPU8xx).

Proves the dynamic half of the sharding discipline end to end:

- spec canonicalization is GSPMD-equivalence-aware: jit outputs drop
  PartitionSpec entries on size-1 mesh axes and strip trailing Nones, so
  the sentry must treat ``P(None, 'dp', None, 'tp', None)`` on a dp=1
  mesh as equal to ``P(None, None, None, 'tp')`` — syntactic equality
  would false-flag every donated rebind on a partly-degenerate mesh;
- the audit baselines paths on first sight, classifies mismatches into
  implicit transfers (host materialization) vs unplanned reshards, tags
  them with the thread-local launch context, and raises in strict mode
  through the engine's loop-boundary check;
- a real engine (dense and meshed) serves traffic under STRICT with zero
  violations — the declared builder layouts survive the serve loop;
- the SEEDED DRIFT DEFECT — ``engine.shard.drift`` swaps a
  host-materialized copy in for the chained decode row — is proven
  caught: strict raises ShardSentryError naming the array path and
  declared-vs-actual spec, and the counter attributes it as an implicit
  transfer (acceptance criterion).
"""

import asyncio
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from clearml_serving_tpu import models
from clearml_serving_tpu.llm import faults, sharding_sentry
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
from clearml_serving_tpu.llm.sharding_sentry import (
    ShardingSentry,
    ShardSentryError,
)
from clearml_serving_tpu.parallel.mesh import make_mesh


@pytest.fixture(scope="module")
def parts():
    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"}
    )
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, params


@pytest.fixture(autouse=True)
def clean_state():
    faults.clear()
    yield
    faults.clear()
    # the singleton is process-wide: never leave strictness (or a stale
    # spec table) behind for unrelated suites
    if sharding_sentry._sentry is not None:
        sharding_sentry._sentry.reset(strict=False)
    sharding_sentry.disarm()


async def _collect(engine, req):
    out = []
    async for token in engine.generate(req):
        out.append(token)
    return out


class _FakeMesh:
    def __init__(self, shape):
        self.shape = shape


# -- spec canonicalization ----------------------------------------------------


def test_canon_spec_drops_size1_axes_and_trailing_nones():
    mesh = _FakeMesh({"dp": 1, "tp": 2, "sp": 4})
    canon = ShardingSentry._canon_spec
    # GSPMD-normalized and builder-declared forms of the same layout agree
    assert canon(("dp", None, "tp", None), mesh) == canon(
        (None, None, "tp"), mesh
    )
    # sharding 1-way IS replication: a dp-only spec on dp=1 is replicated
    assert canon(("dp",), mesh) == canon((), mesh) == "P()"
    # live axes survive, including inside tuple entries
    assert canon((("dp", "sp"), "tp"), mesh) == "P('sp', 'tp')"
    assert canon((("tp", "sp"),), mesh) == "P(('tp', 'sp'))"
    # an unknown mesh (None) keeps every named axis
    assert canon(("dp", None), None) == "P('dp')"


def test_canon_detects_host_and_named_shardings():
    x = jnp.ones((4,))
    assert ShardingSentry._canon(np.ones((4,))) == sharding_sentry._HOST
    assert ShardingSentry._canon(x) == type(x.sharding).__name__
    mesh = make_mesh({"tp": 2, "sp": 4})
    sharded = jax.device_put(
        jnp.ones((8, 8)), NamedSharding(mesh, P("sp", "tp"))
    )
    assert ShardingSentry._canon(sharded) == "P('sp', 'tp')"
    # plain python values are unauditable, not violations
    assert ShardingSentry._canon(3.5) is None
    assert ShardingSentry._canon_declared(NamedSharding(mesh, P("tp"))) == (
        "P('tp')"
    )


# -- audit / baseline / strict ------------------------------------------------


def test_audit_baselines_then_counts_violation_kinds():
    sentry = ShardingSentry(strict=False)
    dev = jnp.ones((4,))
    host = np.ones((4,))
    mesh = make_mesh({"tp": 2, "sp": 4})
    a = jax.device_put(jnp.ones((8, 8)), NamedSharding(mesh, P("sp", "tp")))
    b = jax.device_put(jnp.ones((8, 8)), NamedSharding(mesh, P("tp", None)))

    assert sentry.audit([("e.row", dev, None), ("e.kv", a, None)]) == 0
    assert sentry.stats()["declared_paths"] == 2
    # same specs again: clean
    assert sentry.audit([("e.row", dev, None), ("e.kv", a, None)]) == 0
    # host materialization of a device-baselined path: implicit transfer
    assert sentry.audit([("e.row", host, None)]) == 1
    # spec drift on a device path: unplanned reshard
    assert sentry.audit([("e.kv", b, None)]) == 1
    stats = sentry.stats()
    assert stats["implicit_transfers"] == 1
    assert stats["unplanned_reshards"] == 1
    assert stats["violations"] == 0  # non-strict: counted, never pending
    sentry.check()  # and never raises
    kinds = {e["kind"] for e in stats["events"]}
    assert kinds == {"implicit_transfer", "unplanned_reshard"}


def test_strict_check_raises_with_path_and_specs():
    sentry = ShardingSentry(strict=True)
    dev = jnp.ones((4,))
    sentry.declare("engine[0].row", type(dev.sharding).__name__)
    sentry.audit([("engine[0].row", np.ones((4,)), None)], where="post-step")
    with pytest.raises(ShardSentryError) as exc:
        sentry.check(where="post-step")
    msg = str(exc.value)
    assert "engine[0].row" in msg and "host(ndarray)" in msg
    assert "post-step" in msg and "TPU8xx" in msg
    assert exc.value.kind == "implicit_transfer"
    assert exc.value.actual == "host(ndarray)"
    # reset clears the pending violation and the spec table
    sentry.reset(strict=True)
    assert sentry.stats()["declared_paths"] == 0
    sentry.check()


def test_thread_context_attribution():
    sentry = ShardingSentry(strict=False)
    sentry.declare("e.row", "P('tp')")

    def worker():
        with sentry.context(phase="decode", seq=17):
            sentry.audit([("e.row", np.ones((2,)), None)], where="step")

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    events = sentry.stats()["events"]
    assert events and events[0]["context"] == {"phase": "decode", "seq": 17}
    assert events[0]["where"] == "step"


def test_explicit_declared_entry_wins_over_baseline():
    sentry = ShardingSentry(strict=False)
    host = np.ones((3,))
    # an entry-supplied declared spec pins the table on first audit: the
    # live host value immediately violates it (no silent baseline)
    assert sentry.audit([("e.kv", host, "P('tp')")]) == 1
    assert sentry.stats()["implicit_transfers"] == 1


def test_singleton_arm_disarm_and_env(monkeypatch):
    monkeypatch.delenv(sharding_sentry.ENV, raising=False)
    assert not sharding_sentry.enabled()
    monkeypatch.setenv(sharding_sentry.ENV, "1")
    assert sharding_sentry.enabled() and not sharding_sentry.strict_enabled()
    monkeypatch.setenv(sharding_sentry.ENV, "strict")
    assert sharding_sentry.enabled() and sharding_sentry.strict_enabled()
    sentry = sharding_sentry.arm(strict=False)
    assert sharding_sentry.armed() and sentry is sharding_sentry.get()
    sharding_sentry.disarm()
    assert not sharding_sentry.armed()


# -- engine integration: strict serve stays clean -----------------------------


def test_engine_strict_serve_is_clean(parts, monkeypatch):
    """Tier-1 acceptance path: a dense engine under STRICT audits its
    chained decode state, cache and params tree at every loop boundary
    and finishes traffic with zero implicit transfers / reshards; the
    health() and lifecycle_stats() surfaces expose the counters."""
    monkeypatch.setenv("TPUSERVE_SHARD_SENTRY", "strict")
    sentry = sharding_sentry.get()
    sentry.reset(strict=True)
    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64,
        prefill_buckets=[16, 32], eos_token_id=None, decode_steps=1,
    )
    assert engine._shard_sentry is sentry

    async def run():
        await _collect(engine, GenRequest(
            prompt_ids=[7, 8, 9], max_new_tokens=4
        ))
        await _collect(engine, GenRequest(
            prompt_ids=[5] * 14, max_new_tokens=2
        ))
        await engine.wait_drained()

    try:
        asyncio.run(run())
        stats = sentry.stats()
        assert stats["audits"] > 0 and stats["arrays_checked"] > 0
        assert stats["implicit_transfers"] == 0
        assert stats["unplanned_reshards"] == 0
        assert stats["violations"] == 0
        block = engine.lifecycle_stats()["sharding"]
        assert block["strict"] and block["implicit_transfers"] == 0
        assert engine.health()["sharding"]["audits"] == block["audits"]
    finally:
        engine.stop()
        sentry.reset(strict=False)


def test_meshed_engine_strict_serve_is_clean(parts, monkeypatch):
    """The GSPMD-normalization case that motivated equivalence-aware
    canonicalization: on a dp=1,tp=2,sp=4 mesh, jit outputs rebind the
    donated cache with size-1 axes dropped and trailing Nones stripped —
    the sentry must see those as the declared builder layout, not as a
    reshard per step."""
    monkeypatch.setenv("TPUSERVE_SHARD_SENTRY", "strict")
    sentry = sharding_sentry.get()
    sentry.reset(strict=True)
    bundle, params = parts
    mesh = make_mesh({"dp": 1, "tp": 2, "sp": 4})
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64,
        prefill_buckets=[16, 32], eos_token_id=None, decode_steps=1,
        mesh=mesh,
    )

    async def run():
        await _collect(engine, GenRequest(
            prompt_ids=[3, 5, 7, 9], max_new_tokens=4
        ))
        await engine.wait_drained()

    try:
        asyncio.run(run())
        stats = sentry.stats()
        assert stats["arrays_checked"] > 0
        assert stats["unplanned_reshards"] == 0, stats["events"][:5]
        assert stats["implicit_transfers"] == 0, stats["events"][:5]
    finally:
        engine.stop()
        sentry.reset(strict=False)


def test_engine_unarmed_has_no_sentry_overhead(parts, monkeypatch):
    monkeypatch.delenv("TPUSERVE_SHARD_SENTRY", raising=False)
    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64,
        prefill_buckets=[16], eos_token_id=None, decode_steps=1,
    )
    try:
        assert engine._shard_sentry is None
        assert engine.lifecycle_stats()["sharding"] is None
        assert engine.health()["sharding"] is None
    finally:
        engine.stop()


# -- the seeded drift defect --------------------------------------------------


def test_seeded_shard_drift_is_caught_strict(parts, monkeypatch):
    """Acceptance criterion: `engine.shard.drift` swaps a host-materialized
    numpy copy in for the chained decode row — strict mode fails the
    in-flight request with ShardSentryError naming the path and
    declared-vs-actual, and the counter attributes an implicit transfer."""
    monkeypatch.setenv("TPUSERVE_SHARD_SENTRY", "strict")
    sentry = sharding_sentry.get()
    sentry.reset(strict=True)
    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64,
        prefill_buckets=[16, 32], eos_token_id=None, decode_steps=1,
    )

    async def run():
        # clean request first: baselines every path on real device specs
        await _collect(engine, GenRequest(
            prompt_ids=[7, 8, 9], max_new_tokens=2
        ))
        await engine.wait_drained()
        assert sentry.stats()["implicit_transfers"] == 0
        faults.configure([
            {"point": "engine.shard.drift", "action": "raise",
             "times": 1, "message": "host drift"},
        ])
        with pytest.raises(ShardSentryError) as exc:
            await _collect(engine, GenRequest(
                prompt_ids=[4] * 12, max_new_tokens=12
            ))
        msg = str(exc.value)
        assert "_next_token_dev" in msg
        assert "host(ndarray)" in msg
        assert exc.value.kind == "implicit_transfer"

    try:
        asyncio.run(run())
        stats = sentry.stats()
        assert stats["implicit_transfers"] >= 1
        assert any(
            e["kind"] == "implicit_transfer"
            and e["path"].endswith("._next_token_dev")
            for e in stats["events"]
        )
        assert engine.lifecycle_stats()["sharding"]["violations"] >= 1
    finally:
        engine.stop()
        sentry.reset(strict=False)


def test_seeded_drift_count_mode_counts_without_failing(parts, monkeypatch):
    """Count mode (TPUSERVE_SHARD_SENTRY=1): the same seeded drift is
    counted and attributed but the request completes — the production
    monitoring posture."""
    monkeypatch.setenv("TPUSERVE_SHARD_SENTRY", "1")
    sentry = sharding_sentry.get()
    sentry.reset(strict=False)
    bundle, params = parts
    engine = LLMEngineCore(
        bundle, params, max_batch=2, max_seq_len=64,
        prefill_buckets=[16], eos_token_id=None, decode_steps=1,
    )

    async def run():
        faults.configure([
            {"point": "engine.shard.drift", "action": "raise",
             "times": 1, "message": "host drift"},
        ])
        out = await _collect(engine, GenRequest(
            prompt_ids=[7, 8, 9], max_new_tokens=4
        ))
        assert len(out) == 4  # request completed despite the violation
        await engine.wait_drained()

    try:
        asyncio.run(run())
        assert sentry.stats()["implicit_transfers"] >= 1
        assert sentry.stats()["violations"] == 0
    finally:
        engine.stop()
        sentry.reset(strict=False)
