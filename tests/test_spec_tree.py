"""Draft-tree speculative decoding unit tests (docs/spec_decode_trees.md):
the proposer interface's forest topology contract, the tree-topology
causal mask against the XLA reference and an explicit dense softmax
(chain / binary / forest, int8 KV, partial pages), tree acceptance
walks, and chain-as-degenerate-tree byte-identity for both the greedy
rule and the seeded rejection sampler."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from clearml_serving_tpu import models
from clearml_serving_tpu.llm import faults
from clearml_serving_tpu.llm.engine import GenRequest, LLMEngineCore
from clearml_serving_tpu.llm.sampling import (
    SamplingParams,
    greedy_tree_walk,
    make_sampling_params,
    speculative_sample_chain,
    speculative_sample_tree,
)
from clearml_serving_tpu.llm.spec_proposer import (
    DraftForest,
    NgramChainProposer,
    NgramForestProposer,
    chain_parents,
    make_proposer,
    validate_forest,
)
from clearml_serving_tpu.ops.paged_attention import (
    ragged_layout,
    ragged_paged_attention,
    ragged_paged_attention_xla,
    tree_ancestors,
)


# -- proposer interface -------------------------------------------------------


def _tokbuf(rows, pattern, buf_len=64):
    buf = np.zeros((rows, buf_len), np.int32)
    for r in range(rows):
        seq = pattern(r)
        buf[r, : len(seq)] = seq
    return buf


def test_chain_proposer_matches_legacy_drafts():
    """Single-match history: the chain proposer continues from the LAST
    match, exactly like engine._ngram_draft_rows."""
    k = 4
    seq = [5, 6, 7, 8, 9, 1, 2, 3, 5, 6]           # tail (5,6) matched at 0
    buf = _tokbuf(1, lambda r: seq)
    forest = NgramChainProposer(ngram=2).propose([0], [len(seq)], buf, k)
    validate_forest(forest)
    assert list(forest.parents[0]) == [-1, 0, 1, 2, 3]
    assert list(forest.tokens[0][1:]) == [7, 8, 9, 1]
    assert bool(forest.hits[0])


def test_chain_proposer_fallback_repeats_last():
    buf = _tokbuf(1, lambda r: [1, 2, 3, 4])
    forest = NgramChainProposer(ngram=2).propose([0], [4], buf, 3)
    assert list(forest.tokens[0][1:]) == [4, 4, 4]
    assert not bool(forest.hits[0])


def test_forest_proposer_branches_across_matches():
    """Two matches with distinct continuations: primary chain from the
    most recent match + one depth-1 sibling from the older one."""
    k = 4
    # tail (1, 2): occurs at 0 (-> 7) and at 4 (-> 9); most recent is 4
    seq = [1, 2, 7, 8, 1, 2, 9, 3, 1, 2]
    buf = _tokbuf(1, lambda r: seq)
    prop = NgramForestProposer(ngram=2, branch=2)
    forest = prop.propose([0], [len(seq)], buf, k)
    validate_forest(forest)
    assert int(forest.n_nodes[0]) == k + 1
    # primary chain: 3 deep from the recent match (9, 3, 1), sibling: 7
    assert list(forest.tokens[0][1:4]) == [9, 3, 1]
    assert list(forest.parents[0][1:4]) == [0, 1, 2]
    assert forest.tokens[0][4] == 7 and forest.parents[0][4] == 0
    assert prop.stats()["branched"] == 1


def test_forest_proposer_single_match_degenerates_to_chain():
    seq = [5, 6, 7, 8, 9, 1, 2, 3, 5, 6]
    buf = _tokbuf(1, lambda r: seq)
    chain = NgramChainProposer(ngram=2).propose([0], [len(seq)], buf, 4)
    forest = NgramForestProposer(ngram=2, branch=2).propose(
        [0], [len(seq)], buf, 4)
    np.testing.assert_array_equal(forest.tokens, chain.tokens)
    np.testing.assert_array_equal(forest.parents, chain.parents)


def test_make_proposer_registry():
    assert make_proposer("ngram-forest", branch=3).branch == 3
    with pytest.raises(ValueError, match="unknown spec proposer"):
        make_proposer("medusa")


def test_validate_forest_rejects_bad_topology():
    k = 2
    good = DraftForest(
        tokens=np.zeros((1, k + 1), np.int32),
        parents=chain_parents(k)[None],
        depths=np.arange(k + 1, np.int32)[None]
        if False else np.arange(k + 1, dtype=np.int32)[None],
        n_nodes=np.array([k + 1], np.int32),
        hits=np.zeros(1, bool),
    )
    validate_forest(good)
    bad = DraftForest(
        tokens=np.zeros((1, k + 1), np.int32),
        parents=np.array([[-1, 2, 0]], np.int32),   # parent after child
        depths=np.array([[0, 1, 1]], np.int32),
        n_nodes=np.array([k + 1], np.int32),
        hits=np.zeros(1, bool),
    )
    with pytest.raises(ValueError, match="not before"):
        validate_forest(bad)


# -- tree ancestor builder ----------------------------------------------------


def test_tree_ancestors_chain_and_forest():
    anc = tree_ancestors(chain_parents(3))
    assert list(anc[0]) == [0, -1, -1, -1]
    assert list(anc[3]) == [0, 1, 2, 3]
    # binary-ish forest: 1,2 children of root; 3 child of 1; 4 child of 2
    anc = tree_ancestors([-1, 0, 0, 1, 2])
    assert list(anc[3][:3]) == [0, 1, 3]
    assert list(anc[4][:3]) == [0, 2, 4]
    assert list(anc[2][:2]) == [0, 2] and anc[2][2] == -1
    # dead nodes mask to nothing in-row
    anc = tree_ancestors([-1, 0, 0], n_nodes=2)
    assert list(anc[2]) == [-1, -1, -1]
    with pytest.raises(ValueError, match="depth"):
        tree_ancestors(chain_parents(3), width=2)


# -- tree mask parity ---------------------------------------------------------


def _tree_setup(key, parents_rows, *, hkv=2, g=2, d=64, page=16,
                pages_per_seq=4, hist=(12, 5), q_block=8):
    """Rows: one tree row per parents list (row_len = node count), with
    per-row history. Returns operands + flat tree_anc."""
    rows = len(parents_rows)
    row_lens = np.array([len(p) for p in parents_rows], np.int32)
    kv_lens = row_lens + np.asarray(hist[:rows], np.int32)
    ks = jax.random.split(key, 3)
    n_pages = rows * pages_per_seq + 1
    k_pool = jax.random.normal(ks[0], (hkv, n_pages, page, d), jnp.float32)
    v_pool = jax.random.normal(ks[1], (hkv, n_pages, page, d), jnp.float32)
    page_table = np.zeros((rows, pages_per_seq), np.int32)
    for r in range(rows):
        page_table[r] = 1 + r * pages_per_seq + np.arange(pages_per_seq)
    starts, block_rows, block_q0, t_pad = ragged_layout(row_lens, q_block)
    q = jax.random.normal(ks[2], (t_pad, hkv, g, d), jnp.float32)
    dmax = max(len(p) for p in parents_rows)
    tree_anc = np.full((t_pad, dmax), -1, np.int32)
    tree_anc[:, 0] = -2                                  # default: plain
    for r, parents in enumerate(parents_rows):
        anc = tree_ancestors(parents, width=dmax)
        s = int(starts[r])
        tree_anc[s: s + len(parents)] = anc
    return (q, k_pool, v_pool, jnp.asarray(page_table), jnp.asarray(kv_lens),
            jnp.asarray(starts), jnp.asarray(row_lens),
            jnp.asarray(block_rows), jnp.asarray(block_q0),
            jnp.asarray(tree_anc))


def _dense_tree_reference(q, k_pool, v_pool, page_table, kv_lens, starts,
                          row_lens, tree_anc):
    """Explicit per-query softmax over the allowed set: history plus the
    query's own ancestor path."""
    out = np.zeros_like(np.asarray(q))
    d = q.shape[-1]
    for r in range(page_table.shape[0]):
        kv_len, row_len = int(kv_lens[r]), int(row_lens[r])
        base, s = kv_len - row_len, int(starts[r])
        pages = np.asarray(page_table[r])
        k = np.asarray(k_pool[:, pages]).reshape(k_pool.shape[0], -1, d)
        v = np.asarray(v_pool[:, pages]).reshape(v_pool.shape[0], -1, d)
        for i in range(row_len):
            anc = set(int(a) for a in np.asarray(tree_anc[s + i]) if a >= 0)
            plain = int(tree_anc[s + i, 0]) == -2
            allowed = [
                p for p in range(min(base + i + 1, kv_len))
                if p < base or plain or (p - base) in anc
            ]
            qi = np.asarray(q[s + i])
            for h in range(q.shape[1]):
                sc = qi[h] @ k[h, allowed].T * (d ** -0.5)
                p = np.exp(sc - sc.max(axis=-1, keepdims=True))
                p /= p.sum(axis=-1, keepdims=True)
                out[s + i, h] = p @ v[h, allowed]
    return out


TOPOLOGIES = {
    "chain": [list(chain_parents(4))],
    "binary": [[-1, 0, 0, 1, 1, 2, 2]],
    "forest": [[-1, 0, 0, 1, 2], list(chain_parents(4)), [-1, 0, 0, 0]],
}


@pytest.mark.parametrize("topo", sorted(TOPOLOGIES))
def test_tree_mask_xla_matches_dense_reference(topo):
    args = _tree_setup(jax.random.PRNGKey(0), TOPOLOGIES[topo],
                       hist=(12, 5, 17))
    (q, k_pool, v_pool, page_table, kv_lens, starts, row_lens,
     _br, _bq, tree_anc) = args
    out = ragged_paged_attention_xla(
        q, k_pool, v_pool, page_table, kv_lens, starts, row_lens,
        tree_anc=tree_anc,
    )
    want = _dense_tree_reference(
        q, k_pool, v_pool, page_table, kv_lens, starts, row_lens, tree_anc)
    for r in range(page_table.shape[0]):
        s, n = int(starts[r]), int(row_lens[r])
        np.testing.assert_allclose(
            np.asarray(out[s: s + n]), want[s: s + n], rtol=1e-5, atol=1e-5)


def test_tree_mask_chain_topology_equals_plain_causal():
    """A chain tree's ancestor mask admits exactly the causal triangle:
    outputs must be BIT-identical to the untreed reference."""
    args = _tree_setup(jax.random.PRNGKey(1), TOPOLOGIES["chain"])
    (q, k_pool, v_pool, page_table, kv_lens, starts, row_lens,
     _br, _bq, tree_anc) = args
    a = ragged_paged_attention_xla(
        q, k_pool, v_pool, page_table, kv_lens, starts, row_lens,
        tree_anc=tree_anc)
    b = ragged_paged_attention_xla(
        q, k_pool, v_pool, page_table, kv_lens, starts, row_lens)
    assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


@pytest.mark.parametrize("topo", sorted(TOPOLOGIES))
@pytest.mark.parametrize("page", [16, 32])
def test_tree_mask_kernel_interpret_matches_xla(topo, page):
    """Pallas kernel (interpret) vs XLA reference across topologies,
    including a partial final page (history not page-aligned)."""
    args = _tree_setup(jax.random.PRNGKey(2), TOPOLOGIES[topo],
                       page=page, hist=(page + 3, 5, 2 * page))
    (q, k_pool, v_pool, page_table, kv_lens, starts, row_lens,
     block_rows, block_q0, tree_anc) = args
    ref = ragged_paged_attention_xla(
        q, k_pool, v_pool, page_table, kv_lens, starts, row_lens,
        tree_anc=tree_anc)
    out = ragged_paged_attention(
        q, k_pool, v_pool, page_table, kv_lens, starts, row_lens,
        block_rows=block_rows, block_q0=block_q0, tree_anc=tree_anc,
        pages_per_block=2, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


def test_tree_mask_kernel_int8_interpret_matches_xla():
    def _quantize(pool):
        x = np.asarray(pool, np.float32)
        absmax = np.abs(x).max(axis=-1)
        scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
        qv = np.clip(np.round(x / scale[..., None]), -127, 127)
        return jnp.asarray(qv.astype(np.int8)), jnp.asarray(scale)

    args = _tree_setup(jax.random.PRNGKey(3), TOPOLOGIES["forest"],
                       hist=(9, 5, 17))
    (q, k_pool, v_pool, page_table, kv_lens, starts, row_lens,
     block_rows, block_q0, tree_anc) = args
    k8, ks = _quantize(k_pool)
    v8, vs = _quantize(v_pool)
    ref = ragged_paged_attention_xla(
        q, k8, v8, page_table, kv_lens, starts, row_lens, ks, vs,
        tree_anc=tree_anc)
    out = ragged_paged_attention(
        q, k8, v8, page_table, kv_lens, starts, row_lens,
        block_rows=block_rows, block_q0=block_q0,
        k_scale=ks, v_scale=vs, tree_anc=tree_anc,
        pages_per_block=2, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


# -- acceptance walks ---------------------------------------------------------


def test_greedy_tree_walk_takes_longest_path():
    # topology: 0 -> {1, 4}; 1 -> 2 -> 3 (primary chain), 4 sibling
    parents = jnp.asarray([[-1, 0, 1, 2, 0]], jnp.int32)
    tokens = jnp.asarray([[100, 7, 8, 9, 5]], jnp.int32)
    n_nodes = jnp.asarray([5], jnp.int32)
    # argmax per node: root prefers 7, node1 prefers 8, node2 prefers 0
    greedy = jnp.asarray([[7, 8, 0, 1, 2]], jnp.int32)
    path, acc, nodes = greedy_tree_walk(greedy, tokens, parents, n_nodes)
    assert int(acc[0]) == 2
    assert list(np.asarray(path[0][:3])) == [7, 8, 0]   # drafts + bonus
    # compaction map: accepted nodes 1, 2 land at positions 1, 2
    assert list(np.asarray(nodes[0])) == [0, 1, 2, 3, 4]
    # root prefers the SIBLING: path goes 0 -> 4
    greedy = jnp.asarray([[5, 8, 0, 1, 2]], jnp.int32)
    path, acc, nodes = greedy_tree_walk(greedy, tokens, parents, n_nodes)
    assert int(acc[0]) == 1
    assert list(np.asarray(path[0][:2])) == [5, 2]
    # compaction map: sibling node 4's K/V moves to row position 1;
    # everything past acc stays identity
    assert list(np.asarray(nodes[0])) == [0, 4, 2, 3, 4]
    # nothing matches: bonus only
    greedy = jnp.asarray([[3, 8, 0, 1, 2]], jnp.int32)
    path, acc, nodes = greedy_tree_walk(greedy, tokens, parents, n_nodes)
    assert int(acc[0]) == 0 and int(path[0, 0]) == 3
    assert list(np.asarray(nodes[0])) == [0, 1, 2, 3, 4]


def test_greedy_tree_walk_chain_matches_cumprod_rule():
    b, k, v = 3, 4, 11
    rng = np.random.default_rng(0)
    drafts = rng.integers(0, v, (b, k)).astype(np.int32)
    argmax = rng.integers(0, v, (b, k + 1)).astype(np.int32)
    argmax[0, :2] = drafts[0, :2]                       # partial accept
    argmax[1] = np.concatenate([drafts[1], [3]])        # full accept
    tokens = np.concatenate(
        [np.full((b, 1), 9, np.int32), drafts], axis=1)
    parents = np.broadcast_to(chain_parents(k), (b, k + 1))
    path, acc, nodes = greedy_tree_walk(
        jnp.asarray(argmax), jnp.asarray(tokens),
        jnp.asarray(parents), jnp.full((b,), k + 1, jnp.int32))
    # a chain accepts in node order: the compaction map is identity
    np.testing.assert_array_equal(
        np.asarray(nodes), np.broadcast_to(np.arange(k + 1), (b, k + 1)))
    want_acc = np.sum(np.cumprod(drafts == argmax[:, :k], axis=1), axis=1)
    np.testing.assert_array_equal(np.asarray(acc), want_acc)
    for r in range(b):
        a = int(want_acc[r])
        np.testing.assert_array_equal(
            np.asarray(path[r][:a]), drafts[r][:a])
        assert int(path[r][a]) == int(argmax[r, a])


def test_sample_tree_chain_byte_identical_to_chain_sampler():
    """The tentpole identity: on the degenerate chain topology, the tree
    sampler's emitted tokens and acceptance counts are byte-identical to
    speculative_sample_chain under the same rng (greedy rows are covered
    by the cumprod test above; this is the seeded sampled path)."""
    b, k, v = 4, 4, 37
    key = jax.random.PRNGKey(42)
    logits = jax.random.normal(key, (b, k + 1, v)) * 3.0
    kd, kr = jax.random.split(jax.random.PRNGKey(7))
    drafts = jax.random.randint(kd, (b, k), 0, v, jnp.int32)
    # make some drafts likely-accepted so both branches exercise
    drafts = drafts.at[0].set(jnp.argmax(logits[0, :k], axis=-1))
    params = make_sampling_params(b, temperature=0.9, top_k=0, top_p=1.0)
    ct, ca = speculative_sample_chain(logits, drafts, params, kr)
    tokens = jnp.concatenate(
        [jnp.full((b, 1), 5, jnp.int32), drafts], axis=1)
    parents = jnp.broadcast_to(
        jnp.asarray(chain_parents(k)), (b, k + 1))
    tt, ta, tn = speculative_sample_tree(
        logits, tokens, parents, jnp.full((b,), k + 1, jnp.int32),
        params, kr)
    np.testing.assert_array_equal(np.asarray(ca), np.asarray(ta))
    for r in range(b):
        a = int(ca[r])
        assert (np.asarray(ct[r][: a + 1]).tobytes()
                == np.asarray(tt[r][: a + 1]).tobytes())


def test_sample_tree_law_on_binary_tree():
    """Distributional sanity: the emitted FIRST token's law must equal
    the root's warped softmax regardless of topology (the rejection
    scheme is unbiased)."""
    v = 8
    key = jax.random.PRNGKey(0)
    logits_row = jax.random.normal(key, (v,)) * 2.0
    n = 5
    logits = jnp.broadcast_to(logits_row, (1, n, v))
    # binary tree with drafts on the two most likely tokens
    top2 = np.argsort(np.asarray(logits_row))[::-1][:2]
    tokens = jnp.asarray(
        [[0, int(top2[0]), int(top2[1]), 3, 4]], jnp.int32)
    parents = jnp.asarray([[-1, 0, 0, 1, 2]], jnp.int32)
    n_nodes = jnp.asarray([n], jnp.int32)
    params = make_sampling_params(1, temperature=1.0)

    @jax.jit
    def draw(key):
        path, acc, _ = speculative_sample_tree(
            logits, tokens, parents, n_nodes, params, key)
        return path[0, 0]

    trials = 4000
    keys = jax.random.split(jax.random.PRNGKey(123), trials)
    first = np.asarray(jax.vmap(draw)(keys))
    counts = np.bincount(first, minlength=v) / trials
    want = np.asarray(jax.nn.softmax(logits_row))
    np.testing.assert_allclose(counts, want, atol=0.03)


# -- engine integration -------------------------------------------------------


@pytest.fixture(scope="module")
def eparts():
    bundle = models.build_model(
        "llama", {"preset": "llama-tiny", "dtype": "float32"})
    params = bundle.init(jax.random.PRNGKey(0))
    return bundle, params


def _engine(bundle, params, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 96)
    kw.setdefault("prefill_buckets", [16, 64])
    kw.setdefault("eos_token_id", None)
    kw.setdefault("decode_steps", 2)
    kw.setdefault("cache_mode", "paged")
    kw.setdefault("scheduler", "ragged")
    kw.setdefault("step_token_budget", 12)
    return LLMEngineCore(bundle, params, **kw)


def _staggered(engine, prompts, n=8, seeds=None):
    async def one(i, ids):
        if i:
            await asyncio.sleep(0.05 * i)
        seed = seeds[i] if seeds else None
        req = GenRequest(
            prompt_ids=list(ids), max_new_tokens=n,
            temperature=0.7 if seed is not None else 0.0, seed=seed,
        )
        return [t async for t in engine.generate(req)]

    async def run():
        outs = await asyncio.gather(*(one(i, p) for i, p in enumerate(prompts)))
        await engine.wait_drained()
        return outs

    return asyncio.run(run())


SPEC_A = [5, 9, 2, 17, 5, 9, 2]
SPEC_B = [3, 3, 7, 3, 3, 7, 3]


def test_spec_tree_engine_requires_ngram_and_paged(eparts):
    """spec_tree is a mode OF n-gram speculation on the PAGED ragged path
    (dense chunk layers cannot express a tree mask) — anything else is a
    construction-time error, not a silent downgrade."""
    bundle, params = eparts
    with pytest.raises(ValueError, match="spec_tree"):
        _engine(bundle, params, spec_tree=True)
    with pytest.raises(ValueError, match="spec_tree"):
        _engine(bundle, params, cache_mode="dense", speculation="ngram",
                spec_k=2, spec_ngram=2, spec_tree=True)


def test_spec_tree_engine_greedy_three_arm_identity(eparts, monkeypatch):
    """The headline verify guarantee across all three arms: plain ragged
    decode, chain spec (k drafts, PR 13), and draft-TREE spec (same k+1
    verify budget, forest proposer) emit byte-identical GREEDY streams.
    The tree arm must actually verify tree rows (depth histogram
    populated, forest proposer live) — not silently fall back."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    bundle, params = eparts
    spec_kw = dict(speculation="ngram", spec_k=4, spec_ngram=2)
    arms = {}
    stats = {}
    for name, kw in (
        ("plain", {}),
        ("chain", spec_kw),
        ("tree", dict(spec_kw, spec_tree=True, spec_branch=2)),
    ):
        engine = _engine(bundle, params, **kw)
        # row 0 greedy, row 1 seeded: the sampled tree walk rides the
        # same launches (seeded streams are distribution-exact, not
        # byte-stable across arms, so only the greedy row is compared)
        arms[name] = _staggered(engine, [SPEC_A, SPEC_B], n=10,
                                seeds=[None, 22])
        stats[name] = engine.lifecycle_stats()["ragged"]
        engine.stop()
    assert arms["chain"][0] == arms["plain"][0]
    assert arms["tree"][0] == arms["plain"][0]
    for arm in ("plain", "chain", "tree"):
        assert len(arms[arm][1]) == 10          # seeded row completed
    assert stats["tree"]["step_rows"]["spec_verify"] >= 1
    assert stats["tree"]["spec_tree_depth"]["count"] >= 1
    assert stats["tree"]["spec_proposer"]["name"] == "ngram-forest"
    assert stats["tree"]["spec_proposer"]["proposed"] >= 1
    assert stats["chain"]["spec_tree_depth"] is None
    assert stats["chain"]["spec_proposer"]["name"] == "ngram-chain"
    assert stats["plain"]["spec_proposer"] is None


@pytest.mark.chaos
def test_spec_tree_chaos_fault_demotes_row_to_plain_decode(eparts,
                                                          monkeypatch):
    """An ``engine.spec.tree`` fault mid-planning demotes ONLY the matched
    request's verify row to plain decode in the same launch: both greedy
    streams stay byte-identical to an undisturbed run (the demoted row
    simply decodes draft-free that step), the fallback is counted, and
    nothing leaks — the seam sits before any allocation."""
    monkeypatch.setenv("TPUSERVE_SANITIZE", "1")
    bundle, params = eparts
    marker = 211
    marked = [marker] + SPEC_A
    kw = dict(speculation="ngram", spec_k=2, spec_ngram=2,
              spec_tree=True, spec_branch=2)

    clean = _engine(bundle, params, **kw)
    want = _staggered(clean, [marked, SPEC_B], n=10)
    clean.stop()

    engine = _engine(bundle, params, **kw)
    faults.configure([
        {"point": "engine.spec.tree", "action": "raise",
         "match_token": marker, "times": 2},
    ])
    try:
        got = _staggered(engine, [marked, SPEC_B], n=10)
        assert got == want
        assert engine.counters["spec_tree_fallbacks"] >= 1
        stats = engine.lifecycle_stats()["ragged"]
        assert stats["spec_tree_fallbacks"] >= 1
        # the sibling kept speculating: verify rows still ran somewhere
        assert stats["step_rows"]["spec_verify"] >= 1
        pool = engine.paged_cache.pool
        assert pool.free_pages == pool.num_pages - 1  # nothing leaked
    finally:
        faults.clear()
        engine.stop()


# -- committed CPU smoke artifact -------------------------------------------

def test_spec_tree_ab_artifact_schema():
    """benchmarks/SPEC_TREE_AB_cpu.json (committed by ``bench.py
    --spec-tree-ab``) carries the ISSUE-20 acceptance headlines:
    byte-identical greedy streams across the no-spec / chain / tree arms,
    and the tree arm committing STRICTLY more decode tokens per ragged
    launch than the chain arm at the same k+1 verify budget."""
    import json
    import pathlib

    path = (
        pathlib.Path(__file__).resolve().parents[1]
        / "benchmarks" / "SPEC_TREE_AB_cpu.json"
    )
    row = json.loads(path.read_text())
    assert row["metric"] == "llm_spec_tree_ab_cpusmoke"
    assert row["identical_tokens"] is True
    # the headline: the tree closes the acceptance gap from the SAME
    # verify budget — strictly more committed tokens per launch
    assert (
        row["tree"]["accepted_tokens_per_launch"]
        > row["chain"]["accepted_tokens_per_launch"]
    )
    assert row["value"] > 0
    for arm in ("chain", "tree"):
        assert row[arm]["tok_s"] > 0
        assert row[arm]["spec_verify_rows"] >= 1
        assert 0 <= row[arm]["acceptance_mean"] <= 1
        assert row[arm]["proposer"]["proposed"] >= row[arm]["proposer"]["hit"]
        # the inverse view the roofline reasons in: launches (each one a
        # would-be tunnel dispatch on chip) per committed decode token
        assert 0 < row[arm]["dispatches_per_decode_token"] <= 1
    assert row["no_spec"]["tok_s"] > 0
    assert row["chain"]["proposer"]["name"] == "ngram-chain"
    assert row["tree"]["proposer"]["name"] == "ngram-forest"
    # the forest actually branched (the ambiguity regime was exercised —
    # a zero here means the arms degenerated to identical chains and the
    # per-launch gap is noise)
    assert row["tree"]["proposer"]["branched"] >= 1
    assert row["tree"]["accept_depth_mean"] > 0
    assert row["tree"]["tree_fallbacks"] == 0
    # strict-sentry certification (the slo_loadtest pattern): the smoke
    # arms all four sentries strict, fences the compile sentry after each
    # arm's warmup, and strict mode fails the run outright on a violation
    # — so these zeros are proven by the artifact existing at all
    certs = row["certs"]
    assert certs["sanitizer_checks"] >= 1
    assert certs["sanitizer_violations"] == 0
    assert certs["post_warmup_compiles"] == 0
    assert certs["leaks"] == 0
    assert certs["ledger_mode"] == "strict"
    assert certs["implicit_transfers"] == 0
    assert certs["unplanned_reshards"] == 0
    assert certs["shard_sentry_mode"] == "strict"
    for arm in ("no_spec", "chain", "tree"):
        assert row[arm]["certs"]["sanitizer_violations"] == 0
        assert row[arm]["certs"]["post_warmup_compiles"] == 0
