from pathlib import Path

from clearml_serving_tpu.state import ModelRegistry, StateStore


def test_service_lifecycle(state_root):
    store = StateStore(state_root)
    svc = store.create_service("my-serving", project="DevOps")
    assert svc.exists
    assert store.get_service(svc.id).name == "my-serving"

    svc.update_parameters({"serving_base_url": "http://127.0.0.1:8080/serve"})
    assert svc.get_parameters()["serving_base_url"].endswith("/serve")

    svc.set_configuration_objects({"endpoints": {"a": {"x": 1}}})
    assert svc.get_configuration_object("endpoints") == {"a": {"x": 1}}
    assert svc.get_configuration_object("missing") is None

    c0 = svc.update_counter
    svc.set_runtime_properties({"version": "9.9"})
    assert svc.update_counter == c0 + 1

    svc.ping(instance_id="inst-1")
    listed = store.list_services()
    assert len(listed) == 1 and listed[0]["id"] == svc.id
    assert store.find_service("my-serving").id == svc.id
    assert store.find_service("unknown") is None


def test_artifacts(state_root, tmp_path):
    store = StateStore(state_root)
    svc = store.create_service("svc")
    code = tmp_path / "preprocess.py"
    code.write_text("def preprocess(x):\n    return x\n")
    svc.upload_artifact("py_code_ep1", code)
    stored = svc.get_artifact("py_code_ep1")
    assert stored and stored.is_file()
    assert "def preprocess" in stored.read_text()
    assert svc.artifact_hash("py_code_ep1")
    assert svc.list_artifacts() == ["py_code_ep1"]

    # package dir becomes a zip
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "mod.py").write_text("x = 1\n")
    svc.upload_artifact("py_code_pkg", pkg)
    assert svc.get_artifact("py_code_pkg").suffix == ".zip"


def test_model_registry(state_root, tmp_path):
    reg = ModelRegistry(state_root)
    f = tmp_path / "model.pkl"
    f.write_bytes(b"weights")
    m1 = reg.register("iris-clf", project="examples", path=f, framework="sklearn")
    m2 = reg.register("iris-clf", project="examples", path=f, publish=True)
    reg.register("other", project="elsewhere", path=f)

    got = reg.get(m1.id)
    assert got and got.name == "iris-clf"
    assert Path(got.get_local_copy()).read_bytes() == b"weights"

    res = reg.query(project="examples", name="iris-clf")
    assert [m.id for m in res] == [m2.id, m1.id]  # newest first
    assert [m.id for m in reg.query(project="examples", only_published=True)] == [m2.id]
    assert len(reg.query(max_results=2)) == 2

    m1.publish()
    assert reg.get(m1.id).published

    # tag query
    m2.set_metadata(tags=["prod"])
    assert [m.id for m in reg.query(tags=["prod"])] == [m2.id]
